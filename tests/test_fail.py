"""Failure-path tests: errors in init/work/deinit must fail the whole run cleanly.

Reference: `tests/fail.rs:66-104`, `tests/bad_block.rs:16-60`.
"""

import numpy as np
import pytest

from futuresdr_tpu import Flowgraph, Runtime, Kernel, FlowgraphError
from futuresdr_tpu.blocks import NullSource, NullSink, VectorSource, VectorSink, Copy


class FailInit(Kernel):
    def __init__(self, dtype):
        super().__init__()
        self.input = self.add_stream_input("in", dtype)
        self.output = self.add_stream_output("out", dtype)

    async def init(self, mio, meta):
        raise RuntimeError("boom in init")


class FailWork(Kernel):
    def __init__(self, dtype, after: int = 1000):
        super().__init__()
        self.input = self.add_stream_input("in", dtype)
        self.output = self.add_stream_output("out", dtype)
        self.after = after
        self.n = 0

    async def work(self, io, mio, meta):
        inp = self.input.slice()
        out = self.output.slice()
        n = min(len(inp), len(out))
        self.n += n
        if self.n >= self.after:
            raise RuntimeError("boom in work")
        if n:
            out[:n] = inp[:n]
            self.input.consume(n)
            self.output.produce(n)
        if self.input.finished() and n == len(inp):
            io.finished = True


class FailDeinit(Kernel):
    def __init__(self, dtype):
        super().__init__()
        self.input = self.add_stream_input("in", dtype)

    async def work(self, io, mio, meta):
        self.input.consume(self.input.available())
        if self.input.finished():
            io.finished = True

    async def deinit(self, mio, meta):
        raise RuntimeError("boom in deinit")


def test_fail_in_init_terminates_run():
    fg = Flowgraph()
    src = NullSource(np.float32)
    bad = FailInit(np.float32)
    snk = NullSink(np.float32)
    fg.connect(src, bad, snk)
    with pytest.raises(FlowgraphError):
        Runtime().run(fg)


def test_fail_in_work_terminates_run():
    fg = Flowgraph()
    src = NullSource(np.float32)
    bad = FailWork(np.float32)
    snk = NullSink(np.float32)
    fg.connect(src, bad, snk)
    with pytest.raises(FlowgraphError):
        Runtime().run(fg)


def test_fail_in_deinit_terminates_run():
    fg = Flowgraph()
    src = VectorSource(np.zeros(1000, np.float32))
    bad = FailDeinit(np.float32)
    fg.connect(src, bad)
    with pytest.raises(FlowgraphError):
        Runtime().run(fg)


def test_healthy_blocks_survive_peer_failure():
    """The non-failing sink still gets terminated and restored."""
    fg = Flowgraph()
    src = NullSource(np.float32)
    bad = FailWork(np.float32, after=10_000)
    snk = VectorSink(np.float32)
    fg.connect(src, bad, snk)
    with pytest.raises(FlowgraphError):
        Runtime().run(fg)
    # flowgraph was restored: a second launch attempt is possible structurally
    assert len(fg) == 3
