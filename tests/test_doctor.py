"""Flowgraph doctor (telemetry/doctor.py + telemetry/hist.py): histogram
bucket/percentile math, watchdog trip/classification/re-arm, the
no-false-positive contract on slow-but-progressing graphs, flight-recorder
dump shape, bottleneck attribution, the doctor REST endpoint, the devchain
pick of a cached ``autotune_streamed`` megabatch K, and the perf-regression
gate's compare logic."""

import json
import math
import time
import types
import urllib.error
import urllib.request

import numpy as np
import pytest

from futuresdr_tpu.telemetry import doctor as doc
from futuresdr_tpu.telemetry import prom, spans
from futuresdr_tpu.telemetry.hist import Log2Hist, log2_bounds
from futuresdr_tpu.telemetry.spans import SpanEvent


@pytest.fixture
def watchdog():
    """Arm the process doctor's watchdog for a test; always disarm + clear."""
    d = doc.doctor()
    d.last_trip = None

    def arm(interval, window):
        d.enable(interval=interval, window=window)
        return d

    yield arm
    d.disable()
    d.last_trip = None


@pytest.fixture
def fake_link():
    from futuresdr_tpu.ops import xfer
    installed = []

    def install(h2d_bps, d2h_bps):
        installed.append(xfer.set_fake_link(h2d_bps, d2h_bps))

    yield install
    from futuresdr_tpu.ops import xfer as _x
    _x.set_fake_link()


# ---------------------------------------------------------------------------
# histogram bucket / percentile math
# ---------------------------------------------------------------------------

def test_log2_bucket_indexing():
    h = Log2Hist(lo_exp=-4, hi_exp=2)          # bounds 1/16 … 4
    assert h.bounds == (0.0625, 0.125, 0.25, 0.5, 1.0, 2.0, 4.0)
    # (lo, hi] membership, exact powers land in their OWN bucket (le is
    # inclusive), overflow past the top bound, underflow clamps to bucket 0
    for v, idx in ((0.001, 0), (0.0625, 0), (0.1, 1), (0.125, 1),
                   (0.2, 2), (1.0, 4), (1.5, 5), (4.0, 6), (100.0, 7)):
        assert h._index(v) == idx, (v, idx)


def test_log2_hist_observe_and_quantile():
    h = Log2Hist()
    for v in (0.001, 0.001, 0.001, 0.001, 0.010, 0.010, 0.010, 0.100, 0.100,
              1.000):
        h.observe(v)
    assert h.count == 10
    assert h.sum == pytest.approx(1.234)
    b = log2_bounds()
    # p50 falls in the 0.010 bucket, p99 in the 1.0 bucket — each estimate
    # must stay inside its bucket's (lo, hi] envelope (log2 precision bound)
    def bucket_of(v):
        i = h._index(v)
        return (b[i - 1] if i else 0.0), b[i]
    for q, v_true in ((0.5, 0.010), (0.99, 1.000)):
        lo, hi = bucket_of(v_true)
        est = h.quantile(q)
        assert lo <= est <= hi, (q, est, lo, hi)
    # degenerate / invalid inputs
    assert Log2Hist().quantile(0.5) is None
    h.observe(-1.0)                  # negative (clock skew): dropped
    h.observe(float("nan"))
    assert h.count == 10
    with pytest.raises(ValueError):
        h.quantile(1.5)


def test_prom_histogram_exposition_and_merge():
    reg = prom.Registry()
    H = reg.histogram("t_lat_seconds", "latency", ("src",))
    a = H.labels(src="a")
    for v in (0.001, 0.004, 0.004):
        a.observe(v)
    H.observe(2.0, src="b")
    text = reg.render()
    assert "# TYPE t_lat_seconds histogram" in text
    # cumulative buckets per child + _sum/_count, +Inf carries the total
    assert 't_lat_seconds_bucket{le="+Inf",src="a"} 3' in text
    assert 't_lat_seconds_count{src="a"} 3' in text
    assert 't_lat_seconds_count{src="b"} 1' in text
    assert f't_lat_seconds_sum{{src="a"}} {0.009}' in text
    # the le="0.001953125" cumulative count covers 0.001 + both 0.004 values?
    # no: 0.004 > 0.001953125 → cumulative there is exactly 1
    assert 't_lat_seconds_bucket{le="0.001953125",src="a"} 1' in text
    # child quantile vs merged-family quantile
    qa = H.quantile(0.5, src="a")
    assert 0.001953125 <= qa <= 0.0078125
    qall = H.quantile(1.0)            # merged across children: max bucket 2.0
    assert qall >= 1.0
    # registry re-registration guard covers histograms too
    with pytest.raises(ValueError, match="re-registered"):
        reg.counter("t_lat_seconds", "", ("src",))


def test_observe_sampled_stride():
    """The work-duration site samples 1-in-8 systematically: counts reflect
    the sampled observations (exact totals live on the work_calls/work_time_s
    counters), and every sampled value lands in the right bucket."""
    h = Log2Hist()
    for _ in range(64):
        h.observe_sampled(0.002)
    assert h.count == 64 // Log2Hist.SAMPLE_STRIDE
    assert h.quantile(0.5) == pytest.approx(0.002, rel=1.0)  # right bucket
    h2 = Log2Hist()
    for _ in range(Log2Hist.SAMPLE_STRIDE - 1):
        h2.observe_sampled(1.0)
    assert h2.count == 0              # below one stride: nothing recorded yet


def test_histogram_observe_is_cheap():
    """The per-work-call observe must stay O(100ns)-class: the ≤3% telemetry
    gate multiplies this by the chain's call rate (coarse 5µs bound so CI
    noise cannot flake it)."""
    h = Log2Hist()
    n = 50_000
    t0 = time.perf_counter_ns()
    for _ in range(n):
        h.observe(1.5e-4)
    per_call = (time.perf_counter_ns() - t0) / n
    assert per_call < 5000, f"observe costs {per_call:.0f} ns"


# ---------------------------------------------------------------------------
# watchdog strike machinery + classification (unit, no threads)
# ---------------------------------------------------------------------------

def _fake_wk(name="fake_0"):
    wk = types.SimpleNamespace()
    wk.instance_name = name
    wk.kernel = types.SimpleNamespace(stream_inputs=(), stream_outputs=())
    wk.counters = {"work_calls": 0}
    wk.metrics = lambda: dict(wk.counters)
    return wk


def test_watchdog_strikes_trip_and_rearm():
    d = doc.Doctor()
    d.interval, d.window = 0.01, 3
    wk = _fake_wk()
    token = d.attach([wk], [])
    d.tick()                          # baseline sample, no strike
    for _ in range(2):
        d.tick()
    assert d.last_trip is None        # window not reached yet
    d.tick()
    assert d.last_trip is not None
    # no stream ports anywhere + drained inboxes = a message-plane flowgraph
    # waiting for events: reported `idle`, NOT `deadlocked` (ROADMAP
    # follow-up), and no flight record fires for it
    assert d.last_trip["state"] == "idle"
    assert d.last_trip["suspect_block"] is None
    assert d.last_report is None
    # progress resumes → re-armed, diagnosis flips to progressing
    wk.counters["work_calls"] = 7
    d.tick()
    att = d._fgs[token]
    assert not att.tripped and att.diagnosis["state"] == "progressing"
    d.detach(token)
    assert d.attached() == []


def test_watchdog_message_plane_classification():
    """Satellite (ROADMAP follow-up): message-plane-only flowgraphs are no
    longer blanket-`deadlocked` — drained inboxes report `idle`; queued
    messages that are not draining report `deadlocked` naming the stuck
    block."""
    d = doc.Doctor()
    d.interval, d.window = 0.01, 2
    wk = _fake_wk("msg_sink_0")
    wk.inbox = []                     # duck-typed: len() is the queue depth
    token = d.attach([wk], [])
    d.tick()
    for _ in range(2):
        d.tick()
    assert d.last_trip["state"] == "idle"
    assert "waiting for events" in d.last_trip["detail"]
    # idle does NOT latch the trip: if messages later queue up and the
    # handler wedges (progress still flat), the re-armed window escalates to
    # a real deadlocked diagnosis (with flight record)
    wk.inbox = ["m1", "m2"]
    for _ in range(2):
        d.tick()
    assert d.last_trip["state"] == "deadlocked"
    assert d.last_trip["suspect_block"] == "msg_sink_0"
    assert d.last_report is not None  # the escalation dumped a flight record
    # same graph, but now messages are queued and the handler isn't draining
    d2 = doc.Doctor()
    d2.interval, d2.window = 0.01, 2
    wk2 = _fake_wk("msg_sink_1")
    wk2.inbox = ["m1", "m2", "m3"]
    d2.attach([wk2], [])
    d2.tick()
    for _ in range(2):
        d2.tick()
    diag = d2.last_trip
    assert diag["state"] == "deadlocked"
    assert diag["suspect_block"] == "msg_sink_1"
    assert "3 queued" in diag["detail"]
    d.detach(token)


def test_watchdog_idle_on_live_message_flowgraph(watchdog):
    """Integration regression: a real message-plane-only flowgraph (periodic
    source → sink) between events samples as `idle`, never `deadlocked`."""
    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import MessageSink, MessageSource
    d = doc.doctor()
    fg = Flowgraph()
    src = MessageSource("tick", interval=60.0, count=3)   # one event, then quiet
    snk = MessageSink()
    fg.connect_message(src, "out", snk, "in")
    running = Runtime().start(fg)
    try:
        # deterministic stepping: sample well past the window while the
        # source sleeps out its 60 s interval (no watchdog thread needed —
        # the fixture arms one at a long interval to keep enable/disable
        # lifecycle covered, but ticks are driven here)
        watchdog(interval=30.0, window=3)
        for _ in range(5):
            d.tick()
        # assert on THIS flowgraph's attachment only: other tests may leave
        # legitimately-live graphs attached to the process doctor
        ours = [a for a in d._fgs.values()
                if {b.instance_name for b in a.blocks} ==
                {src.meta.instance_name, snk.meta.instance_name}]
        assert ours, "flowgraph not attached"
        states = {a.diagnosis["state"] for a in ours if a.diagnosis}
        assert states == {"idle"}, states
    finally:
        running.stop_sync()


# ---------------------------------------------------------------------------
# watchdog integration: wedged sink, starved sink, slow-but-progressing
# ---------------------------------------------------------------------------

def _make_kernel_cls(consume):
    from futuresdr_tpu.runtime.kernel import Kernel

    class _Sink(Kernel):
        def __init__(self, dtype):
            super().__init__()
            self.input = self.add_stream_input("in", dtype)

        async def work(self, io, mio, meta):
            if consume:
                n = len(self.input.slice())
                if n:
                    self.input.consume(n)
            if self.input.finished() and not len(self.input.slice()):
                io.finished = True

    return _Sink


def test_watchdog_trips_on_wedged_sink(watchdog, monkeypatch):
    """A blocked sink backpressures the whole chain: the trip names the
    blocked edge and the sink as the suspect, and the flight record carries
    the diagnosis (acceptance: wedged flowgraph trips within its window)."""
    monkeypatch.setenv("FSDR_NO_FASTCHAIN", "1")
    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import Copy, NullSource
    d = watchdog(interval=0.03, window=3)
    Wedge = _make_kernel_cls(consume=False)
    fg = Flowgraph()
    src, cp, snk = NullSource(np.float32), Copy(np.float32), Wedge(np.float32)
    fg.connect(src, cp, snk)
    running = Runtime().start(fg)
    try:
        deadline = time.perf_counter() + 15.0
        while d.last_trip is None and time.perf_counter() < deadline:
            time.sleep(0.02)
        diag = d.last_trip
        assert diag is not None, "watchdog never tripped on a wedged sink"
        assert diag["state"] == "backpressured"
        assert diag["suspect_block"] == snk.meta.instance_name
        # the suspect edge is the blocked one: Copy.out → Wedge.in
        assert diag["suspect_edge"] == [cp.meta.instance_name, "out",
                                        snk.meta.instance_name, "in"]
        assert diag["no_progress_for_s"] >= 3 * 0.03 * 0.99
        # the flight recorder fired on the trip and names the blocked edge
        rep = d.last_report
        assert rep is not None and rep["reason"] == "watchdog:backpressured"
        fg_dump = list(rep["flowgraphs"].values())
        assert any(f["diagnosis"] == diag for f in fg_dump)
    finally:
        running.stop_sync()


def test_watchdog_classifies_starvation(watchdog, monkeypatch):
    """A source that stops producing (without EOS) starves the sink: state is
    ``starved`` and the silent SOURCE is the suspect — distinguished from the
    backpressure case above."""
    monkeypatch.setenv("FSDR_NO_FASTCHAIN", "1")
    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.runtime.kernel import Kernel

    class SilentSource(Kernel):
        def __init__(self, dtype):
            super().__init__()
            self.output = self.add_stream_output("out", dtype)

        async def work(self, io, mio, meta):
            pass                      # never produces, never finishes

    d = watchdog(interval=0.03, window=3)
    Sink = _make_kernel_cls(consume=True)
    fg = Flowgraph()
    src, snk = SilentSource(np.float32), Sink(np.float32)
    fg.connect(src, snk)
    running = Runtime().start(fg)
    try:
        deadline = time.perf_counter() + 15.0
        while d.last_trip is None and time.perf_counter() < deadline:
            time.sleep(0.02)
        diag = d.last_trip
        assert diag is not None
        assert diag["state"] == "starved"
        assert diag["suspect_block"] == src.meta.instance_name
    finally:
        running.stop_sync()


def test_watchdog_no_false_positive_on_slow_link(watchdog, fake_link):
    """Acceptance + satellite: a rate-throttled fake link makes every frame
    slow (~70 ms of modeled wire time) but the chain keeps progressing — the
    watchdog must NOT trip; afterwards the doctor's attribution must name the
    throttled H2D lane as the bottleneck and carry e2e percentiles."""
    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import VectorSink, VectorSource
    from futuresdr_tpu.ops import mag2_stage
    from futuresdr_tpu.tpu import TpuKernel

    n, frame = 1 << 18, 1 << 14
    # f32 pair wire: 128 KiB per frame up at 2 MB/s ≈ 65 ms/frame H2D;
    # D2H fast — H2D is the known dominant lane
    fake_link(h2d_bps=2e6, d2h_bps=400e6)
    d = watchdog(interval=0.05, window=8)     # trip needs 0.4 s of silence;
    #                                           progress lands every ~70 ms
    tone = np.exp(2j * np.pi * 0.1 * np.arange(n)).astype(np.complex64)
    fg = Flowgraph()
    src = VectorSource(tone)
    tk = TpuKernel([mag2_stage()], np.complex64, frame_size=frame,
                   frames_in_flight=2, wire="f32")
    snk = VectorSink(np.float32)
    fg.connect(src, tk, snk)
    was = spans.enabled()
    spans.enable(True)
    spans.drain()
    try:
        Runtime().run(fg)
        evs = spans.drain()
    finally:
        spans.enable(was)
    assert d.last_trip is None, \
        f"false positive on a slow-but-progressing chain: {d.last_trip}"
    assert len(snk.items()) == n
    rep = doc.report(events=evs)
    assert rep["bottleneck_lane"] == "H2D", rep["lanes"]
    assert rep["lanes"]["H2D"]["busy_frac"] > \
        2 * rep["lanes"]["compute"]["busy_frac"]
    e2e = rep["e2e_latency"]
    assert e2e is not None and e2e["p50_s"] > 0
    assert e2e["p99_s"] >= e2e["p50_s"]


# ---------------------------------------------------------------------------
# bottleneck attribution over synthetic spans
# ---------------------------------------------------------------------------

def _span(name, s_ms, e_ms, cat="tpu"):
    return SpanEvent(1, "t", int(s_ms * 1e6), int((e_ms - s_ms) * 1e6),
                     cat, name, None)


def test_attribution_lane_unions():
    # H2D busy 80 of 100 ms (overlapping spans union, not sum), compute 20,
    # D2H 10; one actor block's work lane exists but must not outrank the
    # device lanes (a BLOCKING work span contains its own waits)
    evs = [_span("H2D", 0, 50), _span("H2D", 40, 80),
           _span("compute", 10, 30), _span("D2H", 50, 60),
           _span("blk_1", 0, 100, cat="block")]
    rep = doc.doctor().report(events=evs)
    assert rep["bottleneck_lane"] == "H2D"
    assert rep["lanes"]["H2D"]["busy_frac"] == pytest.approx(0.8, abs=0.01)
    assert rep["lanes"]["H2D"]["busy_s"] == pytest.approx(0.08, rel=0.01)
    assert rep["lanes"]["compute"]["busy_frac"] == pytest.approx(0.2,
                                                                abs=0.01)
    assert rep["blocks"]["work:blk_1"]["busy_frac"] == pytest.approx(1.0)
    assert rep["wall_s"] == pytest.approx(0.1, rel=0.01)


def test_attribution_falls_back_to_work_lanes():
    evs = [_span("blk_a", 0, 90, cat="block"),
           _span("blk_b", 0, 30, cat="block")]
    rep = doc.doctor().report(events=evs)
    assert rep["bottleneck_lane"] == "work:blk_a"
    assert doc.doctor().report(events=[])["bottleneck_lane"] is None


# ---------------------------------------------------------------------------
# flight recorder shape + markdown + REST endpoint
# ---------------------------------------------------------------------------

def _start_live_fg():
    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import NullSink, NullSource
    fg = Flowgraph()
    fg.connect(NullSource(np.float32), NullSink(np.float32))
    rt = Runtime()
    return rt, rt.start(fg)


def test_flight_record_shape_and_markdown(tmp_path, monkeypatch):
    rt, running = _start_live_fg()
    try:
        d = doc.doctor()
        rep = d.flight_record("shape-test")
        # golden shape: every black-box section present
        assert set(rep) == {"reason", "unix_time", "threads", "flowgraphs",
                            "spans", "span_drops", "e2e_latency", "profile",
                            "serve", "metrics", "journal", "tail", "fleet"}
        # lifecycle journal section: the last-N structured events (or None
        # when this process journaled nothing yet); each carries the
        # monotonic seq + category the /api/events/ cursor pages by
        if rep["journal"] is not None:
            assert all({"seq", "cat", "event", "t_wall"} <= set(e)
                       for e in rep["journal"])
        # profile-plane section: compile counters + storm classification
        # ride every flight record (telemetry/profile.py)
        assert set(rep["profile"]) == {"active_compiles", "compiles_total",
                                       "storms"}
        assert rep["reason"] == "shape-test"
        # the calling thread's stack is recorded down to this test
        main = next(t for t in rep["threads"] if t["name"] == "MainThread")
        assert any("test_doctor" in ln for ln in main["stack"])
        # the live flowgraph's blocks carry port occupancy + counters
        fgd = list(rep["flowgraphs"].values())
        assert fgd, "running flowgraph not attached"
        blocks = [b for f in fgd for b in f["blocks"].values()]
        assert any("inputs" in b and "outputs" in b for b in blocks)
        src_out = [b["outputs"] for f in fgd for n, b in f["blocks"].items()
                   if "NullSource" in n]
        assert src_out and "space" in list(src_out[0].values())[0]
        assert any(f["edges"] for f in fgd)
        # JSON-serializable end to end, and the prom snapshot is exposition
        assert json.loads(json.dumps(rep, default=str))
        assert "fsdr_xfer_bytes_total" in rep["metrics"]
        assert "fsdr_block_work_duration_seconds" in rep["metrics"]
        md = doc.render_markdown(rep)
        for section in ("# Flight record — shape-test", "## Flowgraph",
                        "## Threads", "| block |"):
            assert section in md, section
        # dump honors doctor_dir (written as .json + .md)
        from futuresdr_tpu.config import config
        monkeypatch.setattr(config(), "doctor_dir", str(tmp_path))
        paths = d.dump(rep)
        assert paths is not None
        assert json.load(open(paths[0]))["reason"] == "shape-test"
        assert open(paths[1]).read().startswith("# Flight record")
    finally:
        running.stop_sync()


def test_doctor_endpoint_round_trip():
    from futuresdr_tpu.runtime.ctrl_port import ControlPort
    rt, running = _start_live_fg()
    cp = ControlPort(rt.handle, bind="127.0.0.1:29473")
    cp.start()
    base = "http://127.0.0.1:29473"
    try:
        body = json.load(urllib.request.urlopen(base + "/api/fg/0/doctor/"))
        assert set(body) == {"report", "flight_record"}
        assert body["flight_record"]["reason"] == "endpoint"
        assert body["flight_record"]["flowgraphs"]
        assert "bottleneck_lane" in body["report"]
        assert "lanes" in body["report"]
        md = urllib.request.urlopen(
            base + "/api/fg/0/doctor/?md=1").read().decode()
        assert md.startswith("# Flight record")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + "/api/fg/99/doctor/")
        assert ei.value.code == 404
    finally:
        running.stop_sync()
        cp.stop()


# ---------------------------------------------------------------------------
# latency probes feed the e2e histogram; latency_stats percentiles
# ---------------------------------------------------------------------------

def test_latency_probes_feed_e2e_histogram():
    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import Copy, VectorSource
    from futuresdr_tpu.utils import (LatencyProbeSink, LatencyProbeSource,
                                     latency_stats)
    before = doc.E2E_LATENCY.labels(source="latency_probe").count
    fg = Flowgraph()
    src = VectorSource(np.zeros(200_000, np.float32))
    probe = LatencyProbeSource(np.float32, granularity=16_384)
    sink = LatencyProbeSink(np.float32)
    fg.connect(src, probe, Copy(np.float32), sink)
    Runtime().run(fg)
    stats = latency_stats(sink.records)
    # p95 satellite: full percentile ladder, ordered
    assert stats["count"] == len(sink.records) > 0
    assert stats["max_us"] >= stats["p99_us"] >= stats["p95_us"] \
        >= stats["p50_us"] >= 0
    child = doc.E2E_LATENCY.labels(source="latency_probe")
    assert child.count == before + stats["count"]
    assert child.quantile(0.5) > 0


# ---------------------------------------------------------------------------
# devchain picks frames_per_dispatch from a cached autotune_streamed result
# ---------------------------------------------------------------------------

def test_devchain_uses_cached_autotune_k():
    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import VectorSink, VectorSource
    from futuresdr_tpu.ops import mag2_stage
    from futuresdr_tpu.tpu import TpuD2H, TpuH2D, TpuStage, instance
    from futuresdr_tpu.tpu.autotune import (_streamed_cache,
                                            cached_frames_per_dispatch,
                                            record_streamed_pick)
    frame, k = 4096, 2
    n = 4 * frame
    tone = np.exp(2j * np.pi * 0.05 * np.arange(n)).astype(np.complex64)
    fg = Flowgraph()
    src = VectorSource(tone)
    h2d = TpuH2D(np.complex64, frame_size=frame)
    st = TpuStage([mag2_stage()], np.complex64)
    d2h = TpuD2H(np.float32)
    snk = VectorSink(np.float32)
    fg.connect(src, h2d, st, d2h, snk)
    # the "cached autotune_streamed result" for this chain (the member's
    # post-optimize stage list is what the fused composition will carry)
    record_streamed_pick(st.pipeline.stages, np.complex64,
                         instance().platform, k)
    assert cached_frames_per_dispatch(st.pipeline.stages, np.complex64,
                                      instance().platform) == k
    try:
        done = Runtime().run(fg)
        m = done.wrapped(st).metrics()
        assert m.get("fused_devchain") is True, m
        assert m.get("frames_per_dispatch") == k, m
        # 4 frames at K=2 → 2 dispatches
        assert m.get("devchain_frames") == 4 and \
            m.get("devchain_dispatches") == 2, m
        assert len(snk.items()) == n
        np.testing.assert_allclose(
            np.asarray(snk.items()),
            (tone.real ** 2 + tone.imag ** 2).astype(np.float32), rtol=1e-5)
    finally:
        _streamed_cache.clear()


# ---------------------------------------------------------------------------
# streamed-pick cache persists across processes (ISSUE 6 satellite)
# ---------------------------------------------------------------------------

def test_streamed_pick_cache_persists_across_processes(tmp_path, monkeypatch):
    import json as _json
    import os as _os

    from futuresdr_tpu.config import config
    from futuresdr_tpu.ops import mag2_stage
    from futuresdr_tpu.tpu.autotune import (_streamed_cache,
                                            cached_frames_per_dispatch,
                                            record_streamed_pick)
    monkeypatch.setattr(config(), "autotune_cache_dir", str(tmp_path))
    stages = [mag2_stage()]
    try:
        record_streamed_pick(stages, np.complex64, "cpu", 4)
        path = _os.path.join(str(tmp_path), "streamed_picks.json")
        assert _os.path.exists(path)
        disk = _json.load(open(path))
        assert list(disk.values()) == [4]
        # simulate a NEW process: the in-memory layer is empty, the lookup
        # falls through to the persisted store and promotes the hit
        _streamed_cache.clear()
        assert cached_frames_per_dispatch(stages, np.complex64, "cpu") == 4
        assert _streamed_cache, "disk hit not promoted to the memory layer"
        # in-memory stays authoritative within a process: a newer in-process
        # record wins over what the file said
        record_streamed_pick(stages, np.complex64, "cpu", 2)
        assert cached_frames_per_dispatch(stages, np.complex64, "cpu") == 2
        # persistence disabled → no disk fallback
        _streamed_cache.clear()
        monkeypatch.setattr(config(), "autotune_cache_dir", "off")
        assert cached_frames_per_dispatch(stages, np.complex64, "cpu") is None
    finally:
        _streamed_cache.clear()


# ---------------------------------------------------------------------------
# perf-regression gate compare logic
# ---------------------------------------------------------------------------

def test_regress_compare_logic():
    import importlib.util
    import os
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "perf", "regress.py")
    spec = importlib.util.spec_from_file_location("perf_regress", path)
    regress = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(regress)
    traj = [
        (3, {"backend": "cpu", "value": 40.0, "cpu_baseline_msps": 24.0,
             "streamed_msps": 20.0}),
        (5, {"backend": "tpu", "value": 2000.0, "cpu_baseline_msps": 23.0,
             "streamed_msps": 5.0}),
    ]
    # cpu stamp: backend fields graded against r03, cpu baseline against the
    # LATEST stamp that carries it (r05) — never cpu `value` vs tpu `value`
    cur = {"backend": "cpu", "value": 25.0, "cpu_baseline_msps": 22.0,
           "streamed_msps": 19.0}
    rows, ref_round = regress.compare(cur, traj, tolerance=0.25)
    by = {r[0]: r for r in rows}
    assert ref_round == 3
    assert by["value"][2] == 40.0 and by["value"][5] is True      # 0.62 < 0.75
    assert by["cpu_baseline_msps"][2] == 23.0 and \
        by["cpu_baseline_msps"][5] is False
    assert by["streamed_msps"][5] is False                        # 0.95
    # fields absent from either side are skipped, unknown backend → only the
    # backend-agnostic cpu baseline is graded
    rows2, ref2 = regress.compare({"backend": "rocm",
                                   "cpu_baseline_msps": 23.0}, traj, 0.25)
    assert ref2 is None and [r[0] for r in rows2] == ["cpu_baseline_msps"]

    traj_loaded = regress.load_trajectory()
    assert traj_loaded and all(isinstance(s, dict) for _, s in traj_loaded)
