"""Every example script actually runs — end-to-end smoke in subprocesses.

The examples are the user's first contact with the framework; a bit-rotted
example is a worse advertisement than a missing one. Each runs with its
smallest useful workload in its own process (its own jax init, forced to the
CPU platform via FSDR_FORCE_CPU so the wedged axon tunnel can't hang CI) and
must exit 0 within the timeout.
"""

import os
import subprocess
import sys
from pathlib import Path

import pytest

_ROOT = Path(__file__).resolve().parent.parent
_EXAMPLES = [
    ("cw_beacon.py", ["HI", "--wav", "{tmp}/cw.wav"]),
    ("lora_loopback.py", ["--frames", "2"]),
    ("m17_loopback.py", ["--frames", "1"]),
    ("rattlegram_loopback.py", ["--messages", "1", "--payload-size", "32"]),
    ("wlan_loopback.py", ["--frames", "2"]),
    ("zigbee_loopback.py", ["--frames", "2"]),
    ("modem_ota.py", ["hello"]),
    ("modem_ota.py", ["metadata in band", "--callsign", "N0CALL"]),
    ("adsb_rx.py", []),                      # synthesizes its own stream
    ("custom_routes.py", []),                # self-curls its extra REST routes
    ("file_trx.py", ["rx", "--out", "{tmp}/cap.cs8", "--samples", "50000"]),
    ("ssb_rx.py", ["--wav", "{tmp}/ssb.wav"]),   # self-validating loopback
    ("keyfob_rx.py", []),                        # tx → rx loopback, code checked
    ("keyfob_rx.py", ["tx", "--out", "{tmp}/burst.cf32"]),
    ("sharded_spectrum.py", ["--devices", "2", "--frames", "2",
                             "--frame-size", "16384"]),
]


@pytest.mark.parametrize("script,args", _EXAMPLES,
                         ids=[e[0].removesuffix(".py") for e in _EXAMPLES])
def test_example_runs(script, args, tmp_path):
    args = [a.format(tmp=tmp_path) for a in args]
    env = dict(os.environ, FSDR_FORCE_CPU="1",
               PYTHONPATH=str(_ROOT) + os.pathsep + os.environ.get("PYTHONPATH", ""))
    env.pop("JAX_PLATFORMS", None)          # examples force CPU themselves
    r = subprocess.run([sys.executable, str(_ROOT / "examples" / script), *args],
                       capture_output=True, text=True, timeout=240, env=env,
                       cwd=_ROOT)
    assert r.returncode == 0, f"{script} failed:\n{r.stdout[-1500:]}\n{r.stderr[-1500:]}"
