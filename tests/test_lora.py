"""LoRa PHY tests: coding round-trips and chirp loopbacks (reference: lora example's
decoding chain tests)."""

import numpy as np
import pytest

from futuresdr_tpu.models.lora import (LoraParams, modulate_frame, demodulate_frame,
                                       detect_frames, LoraTransmitter, LoraReceiver,
                                       coding)


def test_whitening_roundtrip():
    data = bytes(range(100))
    assert coding.dewhiten(coding.whiten(data)) == data
    assert coding.whiten(data) != data


@pytest.mark.parametrize("cr", [1, 2, 3, 4])
def test_hamming_roundtrip(cr):
    nibbles = np.arange(16, dtype=np.uint8)
    cw = coding.hamming_encode(nibbles, cr)
    np.testing.assert_array_equal(coding.hamming_decode(cw, cr), nibbles)


@pytest.mark.parametrize("cr", [3, 4])
def test_hamming_corrects_single_error(cr):
    nibbles = np.arange(16, dtype=np.uint8)
    cw = coding.hamming_encode(nibbles, cr)
    for bit in range(4):          # flip each data bit
        corrupted = cw ^ (1 << bit)
        np.testing.assert_array_equal(coding.hamming_decode(corrupted, cr), nibbles)


@pytest.mark.parametrize("sf_app,cr", [(5, 4), (7, 1), (7, 4), (10, 2)])
def test_interleaver_roundtrip(sf_app, cr):
    rng = np.random.default_rng(0)
    cw = rng.integers(0, 1 << (4 + cr), sf_app).astype(np.uint8)
    sym = coding.interleave_block(cw, sf_app, cr)
    assert (sym < (1 << sf_app)).all()
    np.testing.assert_array_equal(coding.deinterleave_block(sym, sf_app, cr), cw)


def test_gray_roundtrip():
    x = np.arange(4096)
    np.testing.assert_array_equal(coding.degray(coding.gray(x)), x)


def test_header_roundtrip():
    h = coding.build_header(123, 2, True)
    assert coding.parse_header(h) == (123, 2, True)
    bad = h.copy()
    bad[0] ^= 0x3
    assert coding.parse_header(bad) is None


@pytest.mark.parametrize("sf,cr", [(7, 1), (7, 4), (8, 2), (9, 1), (10, 3)])
def test_lora_loopback_clean(sf, cr):
    p = LoraParams(sf=sf, cr=cr)
    payload = f"lora sf{sf} cr{cr} hello".encode()
    sig = modulate_frame(payload, p)
    starts = detect_frames(np.concatenate([np.zeros(137, np.complex64), sig,
                                           np.zeros(1000, np.complex64)]), p)
    assert len(starts) >= 1
    sig2 = np.concatenate([np.zeros(137, np.complex64), sig, np.zeros(1000, np.complex64)])
    r = demodulate_frame(sig2, starts[0], p)
    assert r is not None
    got, crc_ok, hdr = r
    assert got == payload
    assert crc_ok


def test_lora_loopback_noise():
    p = LoraParams(sf=8, cr=4)
    rng = np.random.default_rng(1)
    payload = b"noisy chirps carry data anyway"
    sig = modulate_frame(payload, p)
    sig = np.concatenate([np.zeros(500, np.complex64), sig, np.zeros(500, np.complex64)])
    sig = (sig + 0.35 * (rng.standard_normal(len(sig))
                         + 1j * rng.standard_normal(len(sig)))).astype(np.complex64)
    starts = detect_frames(sig, p)
    assert len(starts) >= 1
    r = demodulate_frame(sig, starts[0], p)
    assert r is not None
    got, crc_ok, _ = r
    assert got == payload
    assert crc_ok


@pytest.mark.parametrize("f_bin", [2.0, -3.0, 4.3])
def test_lora_cfo_recovery(f_bin):
    """Carrier offsets (integer and fractional bins) are separated from timing by the
    up/down-chirp bin measurements and compensated."""
    p = LoraParams(sf=7, cr=2)
    rng = np.random.default_rng(5)
    payload = b"cfo robust lora!"
    sig = np.concatenate([np.zeros(333, np.complex64), modulate_frame(payload, p),
                          np.zeros(400, np.complex64)])
    k = np.arange(len(sig))
    sig = (sig * np.exp(2j * np.pi * f_bin * k / p.n)).astype(np.complex64)
    sig = (sig + 0.05 * (rng.standard_normal(len(sig))
                         + 1j * rng.standard_normal(len(sig)))).astype(np.complex64)
    got = None
    for s in detect_frames(sig, p):
        r = demodulate_frame(sig, s, p)
        if r is not None and r[1]:
            got = r[0]
            break
    assert got == payload


def test_lora_ldro_mode():
    p = LoraParams(sf=9, cr=2, ldro=True)
    payload = b"low data rate optimization"
    sig = modulate_frame(payload, p)
    r = demodulate_frame(sig, 0, p)
    assert r is not None and r[0] == payload and r[1]


def test_crc_detects_corruption():
    from futuresdr_tpu.models.lora.phy import encode_payload_symbols, decode_symbols

    p = LoraParams(sf=7, cr=1)
    payload = b"check me"
    symbols = encode_payload_symbols(payload, p)
    bad = symbols.copy()
    # corrupt a data-plane symbol (the last symbol of a block carries only parity
    # bits, which detect-only rates ignore — so hit an earlier one)
    bad[-3] = (bad[-3] + 7) % p.n
    r = decode_symbols(bad, p)
    assert r is None or r[1] is False or r[0] != payload


def test_flowgraph_loopback():
    from futuresdr_tpu import Flowgraph, Runtime, Pmt
    from futuresdr_tpu.blocks import Apply

    p = LoraParams(sf=7, cr=2)
    rng = np.random.default_rng(2)
    fg = Flowgraph()
    tx = LoraTransmitter(p)
    chan = Apply(lambda x: (x + 0.1 * (rng.standard_normal(len(x))
                                       + 1j * rng.standard_normal(len(x)))
                            ).astype(np.complex64), np.complex64)
    rx = LoraReceiver(p)
    fg.connect(tx, chan, rx)
    payloads = [f"packet {i}".encode() * 3 for i in range(4)]
    rt = Runtime()
    running = rt.start(fg)
    for pl in payloads:
        rt.scheduler.run_coro_sync(running.handle.call(tx, "tx", Pmt.blob(pl)))
    rt.scheduler.run_coro_sync(running.handle.call(tx, "tx", Pmt.finished()))
    running.wait_sync()
    assert rx.frames == payloads
    assert all(rx.crc_flags)


def _resample_ppm(x, ppm):
    import numpy as np
    t_new = np.arange(int(len(x) / (1 + ppm * 1e-6))) * (1 + ppm * 1e-6)
    i = np.clip(t_new.astype(int), 0, len(x) - 2)
    fr = t_new - i
    return ((1 - fr) * x[i] + fr * x[i + 1]).astype(np.complex64)


@pytest.mark.parametrize("sf,ldro,ppm", [(7, False, 30), (7, False, -30),
                                         (12, True, 30), (12, True, -30)])
def test_clock_offset_long_frame_decode(sf, ldro, ppm):
    """SFO tracking (VERDICT r1 item 5): >=64-byte frame at +/-30 ppm clock offset.

    The drift walks the dechirped bins by one every ~1/(ppm*2^sf) symbols; the
    parity-arbitrated offset-profile tracker in decode_symbols must follow it."""
    import numpy as np
    from futuresdr_tpu.models.lora.phy import (LoraParams, modulate_frame,
                                               detect_frames, demodulate_frame)
    p = LoraParams(sf=sf, ldro=ldro)
    payload = bytes(range(64))
    frame = modulate_frame(payload, p)
    sig = np.concatenate([np.zeros(p.n * 2, np.complex64), frame,
                          np.zeros(p.n * 2, np.complex64)])
    x = _resample_ppm(sig, ppm)
    rng = np.random.default_rng(1)
    x = x + 0.01 * (rng.standard_normal(len(x))
                    + 1j * rng.standard_normal(len(x))).astype(np.complex64)
    ok = any((r := demodulate_frame(x, s, p)) is not None and r[0] == payload and r[1]
             for s in detect_frames(x, p))
    assert ok, f"sf={sf} ldro={ldro} ppm={ppm} failed to decode"


def test_noisy_burst_train_exact_once():
    """Same interrogation standard as the WLAN/ZigBee trains: 12 noisy bursts
    with CFO and random phase decode exactly once each, in order, CRC-valid."""
    p = LoraParams(sf=7, cr=2)
    rng = np.random.default_rng(3)
    parts, sent = [], []
    for i in range(12):
        payload = f"lora train {i}".encode()
        sent.append(payload)
        b = modulate_frame(payload, p)
        parts += [np.zeros(400 + 67 * i, np.complex64), b.astype(np.complex64)]
    parts.append(np.zeros(500, np.complex64))
    sig = np.concatenate(parts)
    sig = sig * np.exp(1j * (0.4 + 1e-4 * np.arange(len(sig))))
    rms = np.sqrt(np.mean(np.abs(sig[np.abs(sig) > 0]) ** 2))
    sigma = rms * 10 ** (-15 / 20) / np.sqrt(2)
    sig = (sig + sigma * (rng.standard_normal(len(sig))
                          + 1j * rng.standard_normal(len(sig)))
           ).astype(np.complex64)
    starts = detect_frames(sig, p)
    assert len(starts) == 12
    got = [demodulate_frame(sig, s, p) for s in starts]
    assert all(g is not None and g[1] for g in got), "CRC failures"
    assert [g[0] for g in got] == sent


def test_implicit_header_loopback():
    """Implicit-header mode (`decoder.rs:36`): no in-band header — the receiver
    is told length/cr/crc a priori; loops back across sf/cr/ldro with CFO+noise,
    and a wrong a-priori length fails CRC instead of decoding garbage as ok."""
    rng = np.random.default_rng(5)
    for sf, cr, ldro in ((7, 1, False), (7, 4, False), (9, 2, False), (8, 2, True)):
        p = LoraParams(sf=sf, cr=cr, ldro=ldro, implicit_header=True)
        payload = f"implicit sf{sf} cr{cr}".encode()
        sig = np.concatenate([np.zeros(300, np.complex64),
                              modulate_frame(payload, p),
                              np.zeros(300, np.complex64)])
        sig = sig * np.exp(1j * (0.3 + 5e-5 * np.arange(len(sig))))
        sig = (sig + 0.05 * (rng.standard_normal(len(sig))
                             + 1j * rng.standard_normal(len(sig)))
               ).astype(np.complex64)
        start = detect_frames(sig, p)[0]
        r = demodulate_frame(sig, start, p, n_payload=len(payload))
        assert r is not None and r[0] == payload and r[1], (sf, cr, ldro)
        # wrong a-priori length: must not pass CRC
        rbad = demodulate_frame(sig, start, p, n_payload=len(payload) - 3)
        assert rbad is None or not rbad[1]

    with pytest.raises(ValueError, match="n_payload"):
        demodulate_frame(sig, start, p)
    with pytest.raises(ValueError, match="n_payload"):
        demodulate_frame(sig, start, p, n_payload=-2)


def test_receiver_overlap_covers_worst_case_frame():
    """OVERLAP must retain a full max-length frame across work() windows — incl.
    ldro (payload columns carry sf-2 nibbles) and implicit_payload_len > max_payload."""
    for p, kw in ((LoraParams(sf=8, ldro=True, cr=2), {}),
                  (LoraParams(sf=7, ldro=True, cr=4), {"max_payload": 200}),
                  (LoraParams(sf=7, cr=2, implicit_header=True),
                   {"max_payload": 16, "implicit_payload_len": 200})):
        rx = LoraReceiver(params=p, **kw)
        longest = kw.get("implicit_payload_len") or kw.get("max_payload", 256)
        frame = modulate_frame(bytes(longest), p)
        assert rx.OVERLAP >= len(frame), (p, kw, rx.OVERLAP, len(frame))

    with pytest.raises(ValueError, match="implicit_payload_len"):
        LoraReceiver(params=LoraParams(implicit_header=True), implicit_payload_len=-1)


def test_implicit_header_receiver_block():
    """LoraReceiver(implicit_payload_len=...) decodes implicit frames; building
    it without the length raises."""
    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import VectorSource

    p = LoraParams(sf=7, cr=2, implicit_header=True)
    payload = b"implicit block"
    sig = np.concatenate([np.zeros(400, np.complex64), modulate_frame(payload, p),
                          np.zeros(400, np.complex64)]).astype(np.complex64)
    with pytest.raises(ValueError, match="implicit_payload_len"):
        LoraReceiver(params=p)
    rx = LoraReceiver(params=p, implicit_payload_len=len(payload))
    fg = Flowgraph()
    fg.connect_stream(VectorSource(sig), "out", rx, "in")
    Runtime().run(fg)
    assert rx.frames == [payload], rx.frames


def test_sync_word_gate():
    """Sync-word validation (`frame_sync.rs:1098-1101`): a frame from another
    network (different sync word) is rejected; a tuple of accepted ids admits
    any of them; the gate survives CFO + noise."""
    rng = np.random.default_rng(11)

    def impaired(payload, p):
        sig = np.concatenate([np.zeros(300, np.complex64), modulate_frame(payload, p),
                              np.zeros(300, np.complex64)])
        sig = sig * np.exp(1j * (0.4 + 4e-5 * np.arange(len(sig))))
        return (sig + 0.05 * (rng.standard_normal(len(sig))
                              + 1j * rng.standard_normal(len(sig)))).astype(np.complex64)

    tx_pub = LoraParams(sf=7, cr=2, sync_word=0x34)     # public-network id
    tx_prv = LoraParams(sf=7, cr=2, sync_word=0x12)
    sig_pub = impaired(b"public net", tx_pub)
    sig_prv = impaired(b"private net", tx_prv)

    # private receiver: decodes its own, rejects the foreign id
    rx_prv = LoraParams(sf=7, cr=2, sync_word=0x12)
    s = detect_frames(sig_prv, rx_prv)[0]
    r = demodulate_frame(sig_prv, s, rx_prv)
    assert r is not None and r[0] == b"private net" and r[1]
    s = detect_frames(sig_pub, rx_prv)[0]
    assert demodulate_frame(sig_pub, s, rx_prv) is None, "foreign sync word accepted"

    # multi-id receiver accepts both networks
    rx_multi = LoraParams(sf=7, cr=2, sync_word=(0x12, 0x34))
    for sig, want in ((sig_prv, b"private net"), (sig_pub, b"public net")):
        s = detect_frames(sig, rx_multi)[0]
        r = demodulate_frame(sig, s, rx_multi)
        assert r is not None and r[0] == want and r[1]


def test_sync_gate_survives_preamble_undershoot():
    """A TX with a longer preamble than the RX expects leaves the walk short of
    the sync chirps; the gate must slide to the true sync position instead of
    misreading the boundary (preamble, nib_hi) pair as a foreign id. A params
    object with a tuple sync_word must also transmit (first id)."""
    rng = np.random.default_rng(21)
    tx = LoraParams(sf=7, cr=2, n_preamble=12, sync_word=(0x12, 0x34))
    rx = LoraParams(sf=7, cr=2, n_preamble=8, sync_word=0x12)
    payload = b"long preamble"
    sig = np.concatenate([np.zeros(300, np.complex64), modulate_frame(payload, tx),
                          np.zeros(300, np.complex64)])
    sig = sig * np.exp(1j * (0.5 + 3e-5 * np.arange(len(sig))))
    sig = (sig + 0.05 * (rng.standard_normal(len(sig))
                         + 1j * rng.standard_normal(len(sig)))).astype(np.complex64)
    ok = any((r := demodulate_frame(sig, s, rx)) is not None
             and r[0] == payload and r[1] for s in detect_frames(sig, rx))
    assert ok, "undershoot recovery failed"


def test_soft_decoding_loopback_all_modes():
    """soft_decoding=True (`fft_demod.rs` soft buffers + `hamming_dec.rs` soft
    path) decodes everything the hard path does, across sf/cr/ldro/implicit."""
    rng = np.random.default_rng(7)
    for sf, cr, ldro, imp in ((7, 1, False, False), (7, 4, False, False),
                              (8, 2, True, False), (7, 2, False, True)):
        p = LoraParams(sf=sf, cr=cr, ldro=ldro, implicit_header=imp,
                       soft_decoding=True)
        payload = f"soft sf{sf}cr{cr}".encode()
        sig = np.concatenate([np.zeros(300, np.complex64), modulate_frame(payload, p),
                              np.zeros(300, np.complex64)])
        sig = sig * np.exp(1j * (0.3 + 4e-5 * np.arange(len(sig))))
        sig = (sig + 0.1 * (rng.standard_normal(len(sig))
                            + 1j * rng.standard_normal(len(sig)))).astype(np.complex64)
        s = detect_frames(sig, p)[0]
        r = demodulate_frame(sig, s, p, n_payload=len(payload) if imp else None)
        assert r is not None and r[0] == payload and r[1], (sf, cr, ldro, imp)


def test_soft_decoding_rescues_hard_failures():
    """At the decode cliff, LLR soft decision corrects blocks the hard
    Hamming decoder cannot (2-bit codeword errors at cr4): pinned noise seeds
    where the soft path decodes and the hard path fails CRC."""
    from dataclasses import replace
    from futuresdr_tpu.models.lora.phy import (encode_payload_symbols, _upchirp,
                                               _dechirp_bins, decode_symbols)
    p = LoraParams(sf=7, cr=4)
    ps = replace(p, soft_decoding=True)
    payload = b"decoder-only gain"
    syms = encode_payload_symbols(payload, p)
    clean = np.concatenate([_upchirp(p.n, int(s)) for s in syms])
    hard_fails = 0
    for t in (14, 20, 40, 46):
        rng = np.random.default_rng(t * 7 + 1)
        x = (clean + 2.2 * (rng.standard_normal(len(clean))
                            + 1j * rng.standard_normal(len(clean)))).astype(np.complex64)
        amags = np.abs(_dechirp_bins(x, p))
        bins = np.argmax(amags, axis=1) % p.n
        rs = decode_symbols(bins, ps, mags=amags)
        assert rs is not None and rs[0] == payload and rs[1], f"seed {t}"
        rh = decode_symbols(bins, p)
        hard_fails += not (rh is not None and rh[0] == payload and rh[1])
    assert hard_fails >= 2, "seeds no longer exercise the soft-decision gain"


def test_soft_decoding_no_crc_clean_exact():
    """No-CRC frames return the FIRST arbitration combo — the preferred-offset
    soft candidate must lead (a speculative wrong-offset soft in front corrupts
    clean payloads; regression for exactly that)."""
    for cr in (1, 2, 3, 4):
        p = LoraParams(sf=7, cr=cr, has_crc=False, soft_decoding=True)
        payload = b"clean check"
        sig = modulate_frame(payload, p)
        r = demodulate_frame(sig, 0, p)
        assert r is not None and r[0] == payload, (cr, r)
        # and with mild noise
        rng = np.random.default_rng(cr)
        x = (sig + 0.15 * (rng.standard_normal(len(sig))
                           + 1j * rng.standard_normal(len(sig)))).astype(np.complex64)
        r = demodulate_frame(x, 0, p)
        assert r is not None and r[0] == payload, (cr, "noisy", r)


def test_ldro_auto_rule():
    """ldro=None auto-enables low-data-rate optimize when the symbol exceeds
    16 ms at the configured bandwidth (`default_values.rs` LDRO_MAX_DURATION_MS):
    SF11+ at 125 kHz on, SF12 at 500 kHz off; a loopback under auto works."""
    assert not LoraParams(sf=10, ldro=None).ldro_on          # 8.2 ms
    assert LoraParams(sf=11, ldro=None).ldro_on              # 16.4 ms
    assert LoraParams(sf=12, ldro=None).ldro_on
    assert not LoraParams(sf=12, ldro=None, bw_hz=500_000).ldro_on
    assert LoraParams(sf=12, ldro=True, bw_hz=500_000).ldro_on   # manual wins

    p = LoraParams(sf=11, cr=2, ldro=None)
    payload = b"auto ldro frame"
    sig = modulate_frame(payload, p)
    r = demodulate_frame(sig, 0, p)
    assert r is not None and r[0] == payload and r[1]


def test_random_config_roundtrip_fuzz():
    """Seeded sweep over random (sf, cr, ldro, implicit, soft, sync) configs:
    every combination must loop back through the full demodulator under mild
    noise + CFO — breadth regression across the feature matrix."""
    rng = np.random.default_rng(2026)
    for trial in range(20):
        sf = int(rng.integers(5, 11))   # SX126x range incl. SF5/6 (r4)
        cr = int(rng.integers(1, 5))
        p = LoraParams(
            sf=sf, cr=cr,
            ldro=bool(rng.integers(0, 2)) if rng.integers(0, 2) else None,
            implicit_header=bool(rng.integers(0, 2)),
            soft_decoding=bool(rng.integers(0, 2)),
            # only nibbles with 8*nib < 2^sf are encodable (bites at SF5/6);
            # hi nibble may be 0 (keeps the overshoot-alias class in coverage),
            # the all-zero word is excluded
            sync_word=int(max(1, (rng.integers(0, min(16, (1 << sf) // 8)) << 4)
                              | rng.integers(0, min(16, (1 << sf) // 8)))),
        )
        n_pay = int(rng.integers(1, 40))
        payload = rng.integers(0, 256, n_pay).astype(np.uint8).tobytes()
        sig = np.concatenate([np.zeros(300, np.complex64), modulate_frame(payload, p),
                              np.zeros(300, np.complex64)])
        sig = sig * np.exp(1j * (float(rng.uniform(0, 6)) +
                                 float(rng.uniform(-5e-5, 5e-5)) * np.arange(len(sig))))
        sig = (sig + 0.05 * (rng.standard_normal(len(sig))
                             + 1j * rng.standard_normal(len(sig)))).astype(np.complex64)
        npay = n_pay if p.implicit_header else None
        ok = False
        for s in detect_frames(sig, p):
            r = demodulate_frame(sig, s, p, n_payload=npay)
            if r is not None and r[0] == payload and r[1]:
                ok = True
                break
        assert ok, (trial, sf, cr, p.ldro, p.implicit_header, p.soft_decoding,
                    hex(p.sync_word))


def test_multi_id_with_zero_hi_nibble_does_not_alias():
    """A multi-id RX accepting a 0x0X word must not let the overshoot scan slot
    alias the (preamble, sync_hi) boundary of a 0x12 frame onto 0x01 — the
    legitimate frame still decodes, and a real 0x04 frame is still accepted."""
    rng = np.random.default_rng(31)
    rx = LoraParams(sf=7, cr=2, sync_word=(0x01, 0x12))
    for tx_word, payload in ((0x12, b"normal id frame"), ):
        tx = LoraParams(sf=7, cr=2, sync_word=tx_word)
        sig = np.concatenate([np.zeros(300, np.complex64),
                              modulate_frame(payload, tx),
                              np.zeros(300, np.complex64)])
        sig = (sig + 0.03 * (rng.standard_normal(len(sig))
                             + 1j * rng.standard_normal(len(sig)))).astype(np.complex64)
        ok = any((r := demodulate_frame(sig, s, rx)) is not None
                 and r[0] == payload and r[1] for s in detect_frames(sig, rx))
        assert ok, hex(tx_word)
    # zero-high-nibble word still decodes via the overshoot slot
    p4 = LoraParams(sf=9, cr=4, sync_word=0x04)
    payload = b"zero hi nibble"
    sig = np.concatenate([np.zeros(300, np.complex64), modulate_frame(payload, p4),
                          np.zeros(300, np.complex64)])
    sig = (sig + 0.03 * (rng.standard_normal(len(sig))
                         + 1j * rng.standard_normal(len(sig)))).astype(np.complex64)
    ok = any((r := demodulate_frame(sig, s, p4)) is not None
             and r[0] == payload and r[1] for s in detect_frames(sig, p4))
    assert ok


# ---- SF5/SF6 (SX126x additions — the reference's DEFAULT SF, `utils.rs:515-525`) ----

def test_sf5_sf6_loopback_matrix():
    """SF5/6 end-to-end across cr/implicit/ldro: the header block runs FULL rate
    (sf rows, no x4 bins — `deinterleaver.rs:202-208`, `fft_demod.rs:72-75`) and
    the frame carries two null symbols after the downchirps (`modulator.rs:118-130`)."""
    rng = np.random.default_rng(54)
    for sf in (5, 6):
        for cr in (1, 2, 3, 4):
            for imp in (False, True):
                for ldro in (False, True):
                    p = LoraParams(sf=sf, cr=cr, implicit_header=imp, ldro=ldro)
                    payload = bytes(rng.integers(0, 256, 13, dtype=np.uint8))
                    sig = np.concatenate([np.zeros(200, np.complex64),
                                          modulate_frame(payload, p),
                                          np.zeros(200, np.complex64)])
                    sig = sig * np.exp(1j * (0.3 + 5e-5 * np.arange(len(sig))))
                    sig = (sig + 0.05 * (rng.standard_normal(len(sig))
                                         + 1j * rng.standard_normal(len(sig)))
                           ).astype(np.complex64)
                    starts = detect_frames(sig, p)
                    assert starts, (sf, cr, imp, ldro)
                    r = demodulate_frame(sig, starts[0], p,
                                         n_payload=len(payload) if imp else None)
                    assert r is not None and r[0] == payload and r[1], \
                        (sf, cr, imp, ldro)


def test_sf5_header_spill_layout():
    """At SF5 the full-rate header block carries exactly the 5 header nibbles
    (zero payload spill); at SF6, one payload nibble rides the first block; at
    SF7, sf-2-5 = 0 spill again — symbol counts must match the reference's
    m_symb_numb formula (`frame_sync.rs:1309-1320`)."""
    from futuresdr_tpu.models.lora.phy import encode_payload_symbols
    for sf, pay_len, cr, has_crc in ((5, 11, 1, True), (6, 11, 1, True),
                                     (5, 4, 4, False), (6, 4, 4, False),
                                     (7, 11, 1, True)):
        p = LoraParams(sf=sf, cr=cr, has_crc=has_crc, ldro=False)
        syms = encode_payload_symbols(bytes(range(pay_len)), p)
        nibbles = 2 * pay_len + 5 + (4 if has_crc else 0)
        first_rows = sf if sf < 7 else sf - 2
        import math
        expect = 8 + math.ceil(max(0, nibbles - first_rows) / sf) * (4 + cr)
        assert len(syms) == expect, (sf, len(syms), expect)


def test_sf5_noisy_burst_train_exact_once():
    """The exact-once interrogation standard at the reference's default SF."""
    p = LoraParams(sf=5, cr=2)
    rng = np.random.default_rng(9)
    parts, sent = [], []
    for i in range(10):
        payload = f"sf5 train {i}".encode()
        sent.append(payload)
        parts += [np.zeros(150 + 31 * i, np.complex64),
                  modulate_frame(payload, p).astype(np.complex64)]
    parts.append(np.zeros(300, np.complex64))
    sig = np.concatenate(parts)
    sig = sig * np.exp(1j * (0.4 + 1e-4 * np.arange(len(sig))))
    rms = np.sqrt(np.mean(np.abs(sig[np.abs(sig) > 0]) ** 2))
    sigma = rms * 10 ** (-15 / 20) / np.sqrt(2)
    sig = (sig + sigma * (rng.standard_normal(len(sig))
                          + 1j * rng.standard_normal(len(sig)))).astype(np.complex64)
    starts = detect_frames(sig, p)
    # at n=32 a run of equal payload symbols IS locally a preamble, so the scan
    # may surface a few extra candidates — the sync-word gate must kill them
    # (reference behavior: frame_sync triggers on any constant run, the net-id
    # check rejects); the decode-level standard stays exact-once in order
    assert 10 <= len(starts) <= 14
    got = [r for r in (demodulate_frame(sig, s, p) for s in starts)
           if r is not None]
    assert all(g[1] for g in got), "CRC failures"
    assert [g[0] for g in got] == sent


def test_sf5_sync_word_gate():
    """The network-id gate holds at SF5: a foreign id is rejected, the
    configured id decodes. Only nibbles 0..3 are encodable at n=32
    (`utils.rs:465-489`) — ids above that must be rejected at construction."""
    rng = np.random.default_rng(77)
    p_tx = LoraParams(sf=5, cr=1, sync_word=0x23)
    payload = b"sf5 gate"
    sig = np.concatenate([np.zeros(100, np.complex64),
                          modulate_frame(payload, p_tx),
                          np.zeros(100, np.complex64)])
    sig = (sig + 0.03 * (rng.standard_normal(len(sig))
                         + 1j * rng.standard_normal(len(sig)))).astype(np.complex64)
    p_ok = LoraParams(sf=5, cr=1, sync_word=0x23)
    p_foreign = LoraParams(sf=5, cr=1, sync_word=0x12)
    s = detect_frames(sig, p_ok)[0]
    r = demodulate_frame(sig, s, p_ok)
    assert r is not None and r[0] == payload and r[1]
    assert demodulate_frame(sig, s, p_foreign) is None
    with pytest.raises(ValueError, match="symbol space"):
        LoraParams(sf=5, sync_word=0x34)     # nibble 4 -> bin 32 >= n
    LoraParams(sf=6, sync_word=0x34)         # fits at n=64


def test_sf_out_of_range_rejected():
    with pytest.raises(ValueError, match="sf"):
        LoraParams(sf=4)
    with pytest.raises(ValueError, match="sf"):
        LoraParams(sf=13)
