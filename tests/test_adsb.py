"""ADS-B tests with published Mode S test vectors (the 1090MHz-riddle examples) plus a
full PPM loopback through the detector/demodulator/tracker."""

import numpy as np
import pytest

from futuresdr_tpu.models.adsb import (modulate_frame, detect_and_demodulate, crc24,
                                       decode_frame, Tracker, cpr_global_decode,
                                       build_df17_frame)


def hex_to_bits(h: str) -> np.ndarray:
    v = bytes.fromhex(h)
    return np.unpackbits(np.frombuffer(v, np.uint8)).astype(np.uint8)


# well-known public test frames
CALLSIGN_FRAME = "8D4840D6202CC371C32CE0576098"     # KLM1023
POS_EVEN = "8D40621D58C382D690C8AC2863A7"           # lat 52.2572, lon 3.9194
POS_ODD = "8D40621D58C386435CC412692AD6"
VELOCITY_FRAME = "8D485020994409940838175B284F"     # 159 kt, trk 182.88, -832 fpm


def test_crc_validates_real_frames():
    for h in (CALLSIGN_FRAME, POS_EVEN, POS_ODD, VELOCITY_FRAME):
        assert crc24(hex_to_bits(h)) == 0
    bad = hex_to_bits(CALLSIGN_FRAME)
    bad[40] ^= 1
    assert crc24(bad) != 0


def test_decode_callsign():
    m = decode_frame(hex_to_bits(CALLSIGN_FRAME))
    assert m.crc_ok
    assert m.icao == 0x4840D6
    assert m.callsign == "KLM1023"


def test_decode_position_pair():
    me = decode_frame(hex_to_bits(POS_EVEN))
    mo = decode_frame(hex_to_bits(POS_ODD))
    assert me.crc_ok and mo.crc_ok
    assert me.cpr is not None and me.cpr[0] == 0
    assert mo.cpr is not None and mo.cpr[0] == 1
    assert me.altitude_ft == 38000
    pos = cpr_global_decode(me.cpr, mo.cpr, most_recent_odd=False)
    assert pos is not None
    lat, lon = pos
    assert abs(lat - 52.2572) < 0.001
    assert abs(lon - 3.9194) < 0.001


def test_decode_velocity():
    m = decode_frame(hex_to_bits(VELOCITY_FRAME))
    assert m.crc_ok
    assert abs(m.ground_speed_kt - 159.20) < 0.5
    assert abs(m.track_deg - 182.88) < 0.5
    assert m.vertical_rate_fpm == -832


def test_ppm_loopback_with_noise():
    rng = np.random.default_rng(0)
    frame_bits = hex_to_bits(CALLSIGN_FRAME)
    sig = modulate_frame(frame_bits, amplitude=1.0)
    stream = np.concatenate([
        0.05 * rng.random(500).astype(np.float32), sig + 0.05 * rng.random(len(sig)).astype(np.float32),
        0.05 * rng.random(300).astype(np.float32)])
    frames = detect_and_demodulate(stream)
    assert len(frames) == 1
    start, bits = frames[0]
    assert 495 <= start <= 505
    np.testing.assert_array_equal(bits, frame_bits)


def test_tracker_integration():
    tr = Tracker()
    for h in (CALLSIGN_FRAME,):
        tr.update(decode_frame(hex_to_bits(h)), now=0.0)
    ac = tr.aircraft[0x4840D6]
    assert ac.callsign == "KLM1023"
    tr.update(decode_frame(hex_to_bits(POS_EVEN)), now=1.0)
    tr.update(decode_frame(hex_to_bits(POS_ODD)), now=2.0)
    ac2 = tr.aircraft[0x40621D]
    assert ac2.lat is not None and abs(ac2.lat - 52.2572) < 0.01
    assert ac2.altitude_ft == 38000
    # expiry
    tr.update(decode_frame(hex_to_bits(VELOCITY_FRAME)), now=100.0)
    assert 0x4840D6 not in tr.aircraft


def test_build_frame_roundtrip():
    me = np.zeros(56, np.uint8)
    me[:5] = [0, 0, 1, 0, 0]     # TC 4: identification
    frame = build_df17_frame(0xABCDEF, me)
    assert crc24(frame) == 0
    m = decode_frame(frame)
    assert m.crc_ok and m.icao == 0xABCDEF and m.type_code == 4


def test_cpr_nl_table_edges():
    from futuresdr_tpu.models.adsb.decoder import _cpr_nl
    assert _cpr_nl(0.0) == 59
    assert _cpr_nl(87.0) == 2
    assert _cpr_nl(-87.0) == 2
    assert _cpr_nl(88.5) == 1
    assert _cpr_nl(10.0) == 59           # interior of the NL=59 zone
    assert _cpr_nl(86.0) == 3            # near-polar interior still formula-driven
    assert _cpr_nl(45.0) == 42


def test_noisy_burst_train_exact_once():
    """Interrogation standard: 10 DF17 bursts in a noisy magnitude stream
    decode exactly once each, all CRC-valid, in order."""
    rng = np.random.default_rng(6)
    sent = [0xABC000 + i for i in range(10)]
    parts = []
    for i, icao in enumerate(sent):
        me = rng.integers(0, 2, 56).astype(np.uint8)
        parts += [np.zeros(300 + 41 * i, np.float32),
                  modulate_frame(build_df17_frame(icao, me))]
    parts.append(np.zeros(400, np.float32))
    mag = np.concatenate(parts)
    mag = (mag + 0.12 * np.abs(rng.standard_normal(len(mag)))).astype(np.float32)
    decoded = detect_and_demodulate(mag)
    msgs = [m for _, b in decoded
            if (m := decode_frame(b)) is not None and m.crc_ok]
    assert [m.icao for m in msgs] == sent


def _hexbits(h):
    v = int(h, 16)
    n = len(h) * 4
    return np.array([(v >> (n - 1 - i)) & 1 for i in range(n)], dtype=np.uint8)


def _df11_frame(icao):
    """Parity-consistent DF11 acquisition squitter for the given address."""
    from futuresdr_tpu.models.adsb.decoder import crc24
    head = np.zeros(32, dtype=np.uint8)
    head[0:5] = [0, 1, 0, 1, 1]                     # DF=11
    head[8:32] = [(icao >> (23 - i)) & 1 for i in range(24)]
    rem = crc24(np.concatenate([head, np.zeros(24, np.uint8)]))
    return np.concatenate([head, np.array([(rem >> (23 - i)) & 1
                                           for i in range(24)], np.uint8)])


def test_surveillance_replies_published_vectors():
    """DF4/DF5 surveillance replies (published pyModeS vectors): altitude and
    squawk decode, with the ICAO recovered from the AP parity overlay."""
    m = decode_frame(_hexbits("2000171806A983"))
    assert m.df == 4 and m.altitude_ft == 36000 and m.icao_derived
    assert m.icao == 0x4CA7E8
    m = decode_frame(_hexbits("2A00516D492B80"))
    assert m.df == 5 and m.squawk == "0356" and m.icao_derived


def test_df11_all_call_roundtrip():
    """A parity-consistent DF11 acquisition squitter validates and yields the
    announced ICAO; a corrupted one fails the CRC gate."""
    icao = 0x4840D6
    frame = _df11_frame(icao)
    m = decode_frame(frame)
    assert m.df == 11 and m.crc_ok and m.icao == icao and not m.icao_derived
    bad = frame.copy(); bad[40] ^= 1
    assert not decode_frame(bad).crc_ok


def test_tracker_gates_derived_icao():
    """AP-overlay (unverified) addresses must never create aircraft — only
    update ones already acquired through a CRC-checked frame."""
    from futuresdr_tpu.models.adsb.decoder import Tracker
    t = Tracker()
    alt = decode_frame(_hexbits("2000171806A983"))          # DF4, derived icao
    assert t.update(alt, now=0.0) is None and not t.aircraft
    # acquire via a valid DF11, then the DF4 altitude applies
    assert t.update(decode_frame(_df11_frame(alt.icao)), now=1.0) is not None
    ac = t.update(alt, now=2.0)
    assert ac is not None and ac.altitude_ft == 36000
    # identity reply fills the squawk on the same aircraft-acquisition rule
    sq = decode_frame(_hexbits("2A00516D492B80"))
    assert t.update(sq, now=3.0) is None                    # unknown icao: gated


def test_receiver_block_mode_s_surveillance():
    """Streamed DF11 acquisition then DF4 altitude updates the tracker; an
    AP-overlay reply for an unknown aircraft is gated (not posted, not counted)."""
    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import VectorSource
    from futuresdr_tpu.models.adsb import AdsbReceiver
    from futuresdr_tpu.models.adsb.phy import modulate_frame

    icao = 0x4CA7E8
    df11 = _df11_frame(icao)
    parts = [np.zeros(400, np.float32)]
    for bits in (_hexbits("2A00516D492B80"),    # DF5, unknown icao: gated
                 df11, _hexbits("2000171806A983")):
        parts += [modulate_frame(bits, amplitude=2.0), np.zeros(300, np.float32)]
    rx = AdsbReceiver()
    fg = Flowgraph()
    fg.connect_stream(VectorSource(np.concatenate(parts).astype(np.float32)),
                      "out", rx, "in")
    Runtime().run(fg)
    assert rx.n_frames == 2
    assert rx.tracker.aircraft[icao].altitude_ft == 36000
    assert 0x510AF9 not in rx.tracker.aircraft


def test_cpr_local_decode_with_reference():
    """Receiver-site-aided single-message position (canonical 1090-riddle
    vectors): the even frame with a nearby reference reproduces the global-pair
    solution; a ref_pos-equipped tracker gets a position from ONE message."""
    from futuresdr_tpu.models.adsb.decoder import Tracker, cpr_local_decode
    me = decode_frame(_hexbits(POS_EVEN))
    lat, lon = cpr_local_decode(me.cpr, 52.25, 3.92)
    assert abs(lat - 52.2572021) < 1e-6 and abs(lon - 3.9193725) < 1e-6
    mo = decode_frame(_hexbits(POS_ODD))
    lat, lon = cpr_local_decode(mo.cpr, 52.25, 3.92)
    assert abs(lat - 52.2657801) < 1e-6 and abs(lon - 3.9389125) < 1e-6

    t = Tracker(ref_pos=(52.25, 3.92))
    ac = t.update(me, now=0.0)
    assert ac.lat is not None and abs(ac.lat - 52.2572021) < 1e-6
    t2 = Tracker()                       # without a reference: needs the pair
    assert t2.update(me, now=0.0).lat is None


def test_cpr_local_decode_guards():
    """Local decode wraps longitude to [-180, 180) and the tracker rejects
    local solutions landing beyond the 180 NM unambiguity range of the site
    (zone-corner decodes; aliasing by a whole zone is undetectable from one
    message — that is inherent to receiver-aided CPR)."""
    from futuresdr_tpu.models.adsb.decoder import (Tracker, cpr_local_decode,
                                                   _dist_nm)
    lat, lon = cpr_local_decode((0, 60000, 1500), 45.0, 179.98)
    assert -180.0 <= lon < 180.0
    # a site whose zone estimate throws the solution >180 NM out: rejected
    me = decode_frame(_hexbits(POS_EVEN))
    ref = (48.6, -2.0)
    cand = cpr_local_decode(me.cpr, *ref)
    assert _dist_nm(*cand, *ref) > 180.0          # the guard's trigger condition
    t = Tracker(ref_pos=ref)
    assert t.update(me, now=0.0).lat is None, "out-of-range local CPR accepted"


def test_random_frame_train_fuzz():
    """Seeded sweep: random DF17 trains with interleaved surveillance replies
    decode exactly once each through the magnitude-stream receiver."""
    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import VectorSource
    from futuresdr_tpu.models.adsb import AdsbReceiver, build_df17_frame
    from futuresdr_tpu.models.adsb.phy import modulate_frame

    rng = np.random.default_rng(1090)
    icaos = [int(rng.integers(1, 1 << 24)) for _ in range(4)]
    parts = [np.zeros(300, np.float32)]
    n_expected = 0
    for i in range(10):
        icao = icaos[int(rng.integers(0, len(icaos)))]
        if rng.integers(0, 4) == 0:
            bits = _df11_frame(icao)
        else:
            me = rng.integers(0, 2, 56).astype(np.uint8)
            bits = build_df17_frame(icao, me)
        parts += [modulate_frame(bits, amplitude=2.0),
                  np.zeros(int(rng.integers(250, 800)), np.float32)]
        n_expected += 1
    sig = np.concatenate(parts)
    sig = (sig + 0.08 * np.abs(rng.standard_normal(len(sig)))).astype(np.float32)
    rx = AdsbReceiver()
    fg = Flowgraph()
    fg.connect_stream(VectorSource(sig), "out", rx, "in")
    Runtime().run(fg)
    assert rx.n_frames == n_expected, (rx.n_frames, n_expected)
