"""ADS-B tests with published Mode S test vectors (the 1090MHz-riddle examples) plus a
full PPM loopback through the detector/demodulator/tracker."""

import numpy as np
import pytest

from futuresdr_tpu.models.adsb import (modulate_frame, detect_and_demodulate, crc24,
                                       decode_frame, Tracker, cpr_global_decode,
                                       build_df17_frame)


def hex_to_bits(h: str) -> np.ndarray:
    v = bytes.fromhex(h)
    return np.unpackbits(np.frombuffer(v, np.uint8)).astype(np.uint8)


# well-known public test frames
CALLSIGN_FRAME = "8D4840D6202CC371C32CE0576098"     # KLM1023
POS_EVEN = "8D40621D58C382D690C8AC2863A7"           # lat 52.2572, lon 3.9194
POS_ODD = "8D40621D58C386435CC412692AD6"
VELOCITY_FRAME = "8D485020994409940838175B284F"     # 159 kt, trk 182.88, -832 fpm


def test_crc_validates_real_frames():
    for h in (CALLSIGN_FRAME, POS_EVEN, POS_ODD, VELOCITY_FRAME):
        assert crc24(hex_to_bits(h)) == 0
    bad = hex_to_bits(CALLSIGN_FRAME)
    bad[40] ^= 1
    assert crc24(bad) != 0


def test_decode_callsign():
    m = decode_frame(hex_to_bits(CALLSIGN_FRAME))
    assert m.crc_ok
    assert m.icao == 0x4840D6
    assert m.callsign == "KLM1023"


def test_decode_position_pair():
    me = decode_frame(hex_to_bits(POS_EVEN))
    mo = decode_frame(hex_to_bits(POS_ODD))
    assert me.crc_ok and mo.crc_ok
    assert me.cpr is not None and me.cpr[0] == 0
    assert mo.cpr is not None and mo.cpr[0] == 1
    assert me.altitude_ft == 38000
    pos = cpr_global_decode(me.cpr, mo.cpr, most_recent_odd=False)
    assert pos is not None
    lat, lon = pos
    assert abs(lat - 52.2572) < 0.001
    assert abs(lon - 3.9194) < 0.001


def test_decode_velocity():
    m = decode_frame(hex_to_bits(VELOCITY_FRAME))
    assert m.crc_ok
    assert abs(m.ground_speed_kt - 159.20) < 0.5
    assert abs(m.track_deg - 182.88) < 0.5
    assert m.vertical_rate_fpm == -832


def test_ppm_loopback_with_noise():
    rng = np.random.default_rng(0)
    frame_bits = hex_to_bits(CALLSIGN_FRAME)
    sig = modulate_frame(frame_bits, amplitude=1.0)
    stream = np.concatenate([
        0.05 * rng.random(500).astype(np.float32), sig + 0.05 * rng.random(len(sig)).astype(np.float32),
        0.05 * rng.random(300).astype(np.float32)])
    frames = detect_and_demodulate(stream)
    assert len(frames) == 1
    start, bits = frames[0]
    assert 495 <= start <= 505
    np.testing.assert_array_equal(bits, frame_bits)


def test_tracker_integration():
    tr = Tracker()
    for h in (CALLSIGN_FRAME,):
        tr.update(decode_frame(hex_to_bits(h)), now=0.0)
    ac = tr.aircraft[0x4840D6]
    assert ac.callsign == "KLM1023"
    tr.update(decode_frame(hex_to_bits(POS_EVEN)), now=1.0)
    tr.update(decode_frame(hex_to_bits(POS_ODD)), now=2.0)
    ac2 = tr.aircraft[0x40621D]
    assert ac2.lat is not None and abs(ac2.lat - 52.2572) < 0.01
    assert ac2.altitude_ft == 38000
    # expiry
    tr.update(decode_frame(hex_to_bits(VELOCITY_FRAME)), now=100.0)
    assert 0x4840D6 not in tr.aircraft


def test_build_frame_roundtrip():
    me = np.zeros(56, np.uint8)
    me[:5] = [0, 0, 1, 0, 0]     # TC 4: identification
    frame = build_df17_frame(0xABCDEF, me)
    assert crc24(frame) == 0
    m = decode_frame(frame)
    assert m.crc_ok and m.icao == 0xABCDEF and m.type_code == 4


def test_cpr_nl_table_edges():
    from futuresdr_tpu.models.adsb.decoder import _cpr_nl
    assert _cpr_nl(0.0) == 59
    assert _cpr_nl(87.0) == 2
    assert _cpr_nl(-87.0) == 2
    assert _cpr_nl(88.5) == 1
    assert _cpr_nl(10.0) == 59           # interior of the NL=59 zone
    assert _cpr_nl(86.0) == 3            # near-polar interior still formula-driven
    assert _cpr_nl(45.0) == 42


def test_noisy_burst_train_exact_once():
    """Interrogation standard: 10 DF17 bursts in a noisy magnitude stream
    decode exactly once each, all CRC-valid, in order."""
    rng = np.random.default_rng(6)
    sent = [0xABC000 + i for i in range(10)]
    parts = []
    for i, icao in enumerate(sent):
        me = rng.integers(0, 2, 56).astype(np.uint8)
        parts += [np.zeros(300 + 41 * i, np.float32),
                  modulate_frame(build_df17_frame(icao, me))]
    parts.append(np.zeros(400, np.float32))
    mag = np.concatenate(parts)
    mag = (mag + 0.12 * np.abs(rng.standard_normal(len(mag)))).astype(np.float32)
    decoded = detect_and_demodulate(mag)
    msgs = [m for _, b in decoded
            if (m := decode_frame(b)) is not None and m.crc_ok]
    assert [m.icao for m in msgs] == sent
