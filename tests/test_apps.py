"""End-to-end application tests (reference: example binaries as integration tests)."""

import numpy as np
import pytest

from futuresdr_tpu import Flowgraph, Runtime
from futuresdr_tpu.blocks import VectorSource, SignalSource, Head, WavSource, WavSink


def test_spectrum_app_finds_tone(tmp_path):
    from futuresdr_tpu.apps.spectrum import build_flowgraph

    fft = 512
    tone = np.exp(1j * 2 * np.pi * 0.125 * np.arange(64 * fft)).astype(np.complex64)
    src = VectorSource(tone)
    fg, sink = build_flowgraph(src, use_tpu=True, fft_size=fft, collect=True)
    Runtime().run(fg)
    spec = sink.items()
    assert len(spec) >= fft
    last = spec[-fft:]
    assert np.argmax(last) == round(0.125 * fft)


def test_spectrum_app_cpu_path():
    from futuresdr_tpu.apps.spectrum import build_flowgraph

    fft = 256
    tone = np.exp(1j * 2 * np.pi * 0.25 * np.arange(64 * fft)).astype(np.complex64)
    src = VectorSource(tone)
    fg, sink = build_flowgraph(src, use_tpu=False, fft_size=fft, collect=True)
    Runtime().run(fg)
    spec = sink.items()
    assert len(spec) >= fft
    assert np.argmax(spec[-fft:]) == round(0.25 * fft)


def test_fm_receiver_recovers_audio_tone(tmp_path):
    from futuresdr_tpu.apps.fm_receiver import build_flowgraph, SAMPLE_RATE, AUDIO_RATE

    # synthesize FM: 1 kHz tone, 75 kHz deviation, at 1 MHz input rate
    fs_in = 1e6
    n = 400_000
    t = np.arange(n) / fs_in
    msg = np.sin(2 * np.pi * 1000.0 * t)
    phase = 2 * np.pi * 75e3 * np.cumsum(msg) / fs_in
    iq = np.exp(1j * phase).astype(np.complex64)
    src = VectorSource(iq)
    wav = str(tmp_path / "audio.wav")
    fg, xlate, sink = build_flowgraph(src, input_rate=fs_in, audio_path=wav)
    Runtime().run(fg)
    assert sink.n_written > AUDIO_RATE // 10
    # read the wav back and check the 1 kHz tone dominates
    import wave
    w = wave.open(wav, "rb")
    pcm = np.frombuffer(w.readframes(w.getnframes()), np.int16).astype(np.float64)
    w.close()
    pcm = pcm[len(pcm) // 4:]           # skip transients
    spec = np.abs(np.fft.rfft(pcm * np.hanning(len(pcm))))
    freq = np.fft.rfftfreq(len(pcm), 1.0 / AUDIO_RATE)
    peak = freq[np.argmax(spec[5:]) + 5]
    assert abs(peak - 1000.0) < 20.0


def test_fm_receiver_tpu_fused_path(tmp_path):
    """The whole FM front end as one fused stage chain recovers the audio tone."""
    from futuresdr_tpu.apps.fm_receiver import build_flowgraph, AUDIO_RATE

    fs = 1e6
    n = 1_500_000
    t = np.arange(n) / fs
    msg = np.sin(2 * np.pi * 1000.0 * t)
    iq = np.exp(1j * 2 * np.pi * 75e3 * np.cumsum(msg) / fs).astype(np.complex64)
    wav = str(tmp_path / "fm_tpu.wav")
    fg, _, sink = build_flowgraph(VectorSource(iq), input_rate=fs, audio_path=wav,
                                  use_tpu=True)
    Runtime().run(fg)
    assert sink.n_written > AUDIO_RATE // 10
    import wave
    w = wave.open(wav, "rb")
    pcm = np.frombuffer(w.readframes(w.getnframes()), np.int16).astype(np.float64)
    w.close()
    pcm = pcm[len(pcm) // 4:]
    spec = np.abs(np.fft.rfft(pcm * np.hanning(len(pcm))))
    peak = np.fft.rfftfreq(len(pcm), 1.0 / AUDIO_RATE)[np.argmax(spec[5:]) + 5]
    assert abs(peak - 1000.0) < 20.0


def test_wav_roundtrip(tmp_path):
    path = str(tmp_path / "t.wav")
    data = (0.5 * np.sin(2 * np.pi * 440 / 8000 * np.arange(8000))).astype(np.float32)
    fg = Flowgraph()
    fg.connect(VectorSource(data), WavSink(path, 8000))
    Runtime().run(fg)
    fg2 = Flowgraph()
    src = WavSource(path)
    from futuresdr_tpu.blocks import VectorSink
    snk = VectorSink(np.float32)
    fg2.connect(src, snk)
    Runtime().run(fg2)
    got = snk.items()
    assert len(got) == 8000
    np.testing.assert_allclose(got, data, atol=1e-3)
