"""Native C++ MM clock-recovery loop vs the Python fallback: bit-matched drop-in.

The MM control loop is sequential per symbol (reference runs it compiled,
``examples/zigbee/src/clock_recovery_mm.rs``); ours is C++ behind ctypes
(``native/mm.cpp``) with float32 arithmetic mirroring numpy NEP-50 promotion, so
both paths walk identical timing trajectories.
"""
import os

import numpy as np
import pytest

from futuresdr_tpu import Flowgraph, Runtime
from futuresdr_tpu.blocks import VectorSink, VectorSource
from futuresdr_tpu.blocks.dsp import ClockRecoveryMm


def _run(x, force_py, omega=4.0, **kw):
    old = os.environ.pop("FSDR_NO_NATIVE", None)
    if force_py:
        os.environ["FSDR_NO_NATIVE"] = "1"
    try:
        ClockRecoveryMm._native = None
        fg = Flowgraph()
        src = VectorSource(x)
        mm = ClockRecoveryMm(omega, omega_limit=0.1, **kw)
        snk = VectorSink(np.float32)
        fg.connect(src, mm, snk)
        Runtime().run(fg)
        used_native = bool(ClockRecoveryMm._native)
        return snk.items(), used_native
    finally:
        ClockRecoveryMm._native = None
        if old is not None:
            os.environ["FSDR_NO_NATIVE"] = old
        else:
            os.environ.pop("FSDR_NO_NATIVE", None)


def test_native_matches_python_bitexact():
    rng = np.random.default_rng(7)
    sym = rng.choice([-1.0, 1.0], 30_000)
    x = np.repeat(sym, 4).astype(np.float32)
    x += 0.05 * rng.standard_normal(len(x)).astype(np.float32)
    y_py, _ = _run(x, force_py=True)
    y_nat, used_native = _run(x, force_py=False)
    if not used_native:
        pytest.skip("native library unavailable")
    assert len(y_py) == len(y_nat)
    np.testing.assert_array_equal(y_py, y_nat)


def test_native_recovers_symbols_with_clock_offset():
    rng = np.random.default_rng(1)
    sym = rng.choice([-1.0, 1.0], 5_000)
    # 2% clock offset: resample 4 sps to 4.08 sps
    up = np.repeat(sym, 4).astype(np.float32)
    t = np.arange(int(len(up) / 1.02)) * 1.02
    i = t.astype(int)
    x = (up[i] * (1 - (t - i)) + up[np.minimum(i + 1, len(up) - 1)] * (t - i)
         ).astype(np.float32)
    # loop gains sized for a 2% rate offset (the defaults assume ppm-scale drift)
    y, _ = _run(x, force_py=False, gain_omega=5e-3, gain_mu=0.1)
    # decisions after settling must track the symbol stream; acquisition may slip a
    # few symbols, so align at the best small lag
    settled = np.sign(y[500:4000])
    best = 0.0
    for lag in range(-8, 9):
        ref = sym[500 + lag:500 + lag + len(settled)]
        n = min(len(ref), len(settled))
        best = max(best, float(np.mean(settled[:n] == ref[:n])))
    assert best > 0.97, best
