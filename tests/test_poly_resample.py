"""Polyphase resample_stage vs the zero-stuffed overlap-save reference form.

The poly implementation groups outputs by residue mod I (one phase per group, windows
on stride-D offsets built from static slices) and contracts all phases in one einsum —
it must stream identically to the stuffed form for any rational I/D.
"""
import numpy as np
import pytest

from futuresdr_tpu.ops.stages import resample_stage


def _run(st, x, frame):
    carry = st.init_carry(x.dtype)
    outs = []
    for i in range(0, len(x), frame):
        carry, y = st.fn(carry, x[i:i + frame])
        outs.append(np.asarray(y))
    return np.concatenate(outs)


@pytest.mark.parametrize("iq", [(2, 3), (7, 4), (4, 1), (1, 5), (48, 125)])
@pytest.mark.parametrize("dtype", [np.float32, np.complex64])
def test_poly_matches_stuffed(iq, dtype):
    I, D = iq
    rng = np.random.default_rng(I * 100 + D)
    taps = rng.standard_normal(int(rng.integers(I * 3, I * 9))).astype(np.float32)
    x = rng.standard_normal(100_000).astype(np.float32)
    if dtype == np.complex64:
        x = (x + 1j * rng.standard_normal(len(x))).astype(np.complex64)
    sp = resample_stage(I, D, taps, impl="poly")
    ss = resample_stage(I, D, taps, impl="stuff")
    mult = int(np.lcm(sp.frame_multiple, ss.frame_multiple))
    n = (len(x) // (2 * mult)) * mult
    assert n > 0
    x = x[:2 * n]
    yp, ys = _run(sp, x, n), _run(ss, x, n)
    L = min(len(yp), len(ys))
    assert L > 0
    assert np.abs(yp[:L] - ys[:L]).max() < 2e-3


def test_complex_taps_fall_back_to_stuffed():
    taps = (np.random.default_rng(1).standard_normal(24)
            + 1j * np.random.default_rng(2).standard_normal(24)).astype(np.complex64)
    st = resample_stage(2, 3, taps, impl="poly")   # silently needs the stuff path
    x = (np.random.default_rng(3).standard_normal(st.frame_multiple * 4)).astype(np.complex64)
    _, y = st.fn(st.init_carry(np.complex64), x)
    assert np.asarray(y).shape[0] == x.shape[0] * 2 // 3


def test_chunked_processing_is_chunk_invariant():
    """Regression (r5, found by the fast-chain A/B): the m_hi decrement-loop
    undershot the producible-output boundary for some interp>decim alignments
    (e.g. I=12, D=5, total=37), deferring an output past the K-1 kept history;
    the next chunk then zero-filled part of its window, making results depend
    on work-call chunking. The closed form (I*total-1)//D + 1 fixes it —
    chunked processing must equal one-shot, bit for bit, at every split."""
    from futuresdr_tpu.dsp.kernels import PolyphaseResamplingFir
    rng = np.random.default_rng(55)
    x = rng.standard_normal(300).astype(np.float32)
    for interp, decim in ((12, 5), (5, 12), (3, 2), (7, 3), (1, 4)):
        taps = rng.standard_normal(4 * interp).astype(np.float32)
        ref = PolyphaseResamplingFir(interp, decim, taps).process(x)
        for split in (1, 7, 37, 123, 299):
            ch = PolyphaseResamplingFir(interp, decim, taps)
            got = np.concatenate([ch.process(x[:split]),
                                  ch.process(x[split:])])
            np.testing.assert_array_equal(got, ref, err_msg=f"{interp}/{decim}@{split}")
