"""LoRa ecosystem tests: semtech UDP packet forwarder (GWMP v2), Meshtastic
channel crypto/presets, multi-channel RX (reference:
``examples/lora/src/packet_forwarder_client.rs``, ``meshtastic.rs``,
``bin/rx_all_channels_eu.rs``)."""

import base64
import json
import socket
import threading

import numpy as np
import pytest

from futuresdr_tpu import Flowgraph, Runtime, Pmt
from futuresdr_tpu.blocks import MessageSink
from futuresdr_tpu.models.lora import (LoraParams, LoraTransmitter,
                                       PacketForwarderClient, build_rxpk,
                                       build_multichannel_rx, meshtastic)
from futuresdr_tpu.models.lora.forwarder import (PROTOCOL_VERSION, PUSH_DATA,
                                                 PUSH_ACK, PULL_DATA, PULL_RESP,
                                                 TX_ACK)


class FakeGwmpServer:
    """Minimal Semtech GWMP v2 server: records PUSH_DATA, acks everything, and can
    inject a PULL_RESP downlink."""

    def __init__(self):
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.bind(("127.0.0.1", 0))
        self.sock.settimeout(0.2)
        self.addr = self.sock.getsockname()
        self.push_data = []
        self.pull_addrs = []
        self.tx_acks = []           # (token, body) pairs
        self._stop = False
        self.thread = threading.Thread(target=self._run, daemon=True)
        self.thread.start()

    def _run(self):
        while not self._stop:
            try:
                data, addr = self.sock.recvfrom(65536)
            except socket.timeout:
                continue
            if len(data) < 4 or data[0] != PROTOCOL_VERSION:
                continue
            token, ident = data[1:3], data[3]
            if ident == PUSH_DATA:
                self.push_data.append(json.loads(data[12:].decode()))
                self.sock.sendto(bytes([PROTOCOL_VERSION]) + token
                                 + bytes([PUSH_ACK]), addr)
            elif ident == PULL_DATA:
                self.pull_addrs.append(addr)
                self.sock.sendto(bytes([PROTOCOL_VERSION]) + token + bytes([4]), addr)
            elif ident == TX_ACK:
                self.tx_acks.append((bytes(token), data[12:]))

    def send_downlink(self, txpk: dict, token: bytes = b"\x5a\xa5"):
        body = json.dumps({"txpk": txpk}).encode()
        for addr in self.pull_addrs[-1:]:
            self.sock.sendto(bytes([PROTOCOL_VERSION]) + token
                             + bytes([PULL_RESP]) + body, addr)

    def close(self):
        self._stop = True
        self.thread.join()
        self.sock.close()


def test_forwarder_push_data_and_downlink():
    server = FakeGwmpServer()
    try:
        fwd = PacketForwarderClient(gateway_eui="aa-bb-cc-dd-ee-ff-00-11",
                                    server=f"127.0.0.1:{server.addr[1]}",
                                    sf=7, bandwidth=125_000, cr=1,
                                    freq_hz=868.1e6, keepalive_s=0.05)
        snk = MessageSink()
        fg = Flowgraph()
        fg.add(fwd)
        fg.connect_message(fwd, "downlink", snk, "in")

        import asyncio

        async def scenario():
            rt = Runtime()
            running = await rt.start_async(fg)
            await running.handle.post(fwd, "in", Pmt.map({
                "payload": Pmt.blob(b"hello-lora"),
                "sf": Pmt.usize(9), "snr": Pmt.f64(7.5)}))
            for _ in range(40):                      # wait for push + keepalive
                await asyncio.sleep(0.05)
                if server.push_data and server.pull_addrs:
                    break
            server.send_downlink({"freq": 869.525, "data":
                                  base64.b64encode(b"dl-payload").decode()})
            for _ in range(40):
                await asyncio.sleep(0.05)
                if snk.received:
                    break
            await running.handle.post(fwd, "in", Pmt.finished())
            await running.wait()

        asyncio.run(scenario())

        assert server.push_data, "no PUSH_DATA reached the server"
        rxpk = server.push_data[0]["rxpk"][0]
        assert rxpk["modu"] == "LORA"
        assert rxpk["datr"] == "SF9BW125"
        assert rxpk["codr"] == "4/5"
        assert base64.b64decode(rxpk["data"]) == b"hello-lora"
        assert rxpk["size"] == len(b"hello-lora")
        assert abs(rxpk["freq"] - 868.1) < 1e-6
        assert rxpk["lsnr"] == 7.5
        assert fwd.acked >= 1                        # PUSH_ACK/PULL_ACK processed
        assert snk.received, "downlink not surfaced"
        dl = snk.received[0].to_map()
        assert dl["data"].to_blob() == b"dl-payload"
        # TX_ACK must echo the PULL_RESP token (servers correlate acks by token)
        assert server.tx_acks and server.tx_acks[0][0] == b"\x5a\xa5"
    finally:
        server.close()


def test_rxpk_fields():
    r = build_rxpk(b"\x01\x02", sf=12, bw_hz=62_500, cr=4, freq_hz=869.4925e6,
                   snr=-19.75, crc_ok=False, timestamp_ns=1_700_000_000_000_000_000)
    assert r["datr"] == "SF12BW62"
    assert r["codr"] == "4/8"
    assert r["stat"] == -1
    assert r["size"] == 2
    assert r["time"].endswith("Z") and "T" in r["time"]


def test_meshtastic_presets_and_channel_roundtrip():
    cfg = meshtastic.preset("longfasteu")
    assert (cfg.sf, cfg.cr, cfg.bandwidth_hz, cfg.ldro) == (11, 1, 250_000, False)
    assert cfg.frequency_hz == 869_525_000
    p = cfg.lora_params()
    assert isinstance(p, LoraParams) and p.sf == 11 and p.sync_word == 0x2B
    assert meshtastic.preset("VeryLongSlowUs").frequency_hz == 916_218_750
    with pytest.raises(KeyError):
        meshtastic.preset("NoSuchPreset")

    # channel crypto roundtrip with the default key
    ch = meshtastic.MeshtasticChannel("LongFast", "AQ==")
    pkt = ch.encode("hello mesh", sender=0x12345678, packet_id=99)
    wire = pkt.to_bytes()
    back = meshtastic.decode_any([ch], wire)
    assert back is not None
    ch2, portnum, payload = back
    assert ch2 is ch and portnum == 1 and payload == b"hello mesh"
    # wrong channel name → hash mismatch → no decode
    other = meshtastic.MeshtasticChannel("Different", "AQ==")
    assert other.decode(meshtastic.MeshPacket.parse(wire)) is None


def test_multichannel_rx_two_channels():
    """Two frames on two EU868 channels inside one wideband stream, both decoded
    with the right channel frequency tag."""
    from futuresdr_tpu.blocks import VectorSource
    from futuresdr_tpu.models.lora.phy import modulate_frame

    p = LoraParams(sf=7)
    rate = 1e6
    center = 867.9e6
    channels = [867.7e6, 868.1e6]
    decim = int(rate // 125e3)

    payloads = [b"chan-A-frame", b"chan-B-frame"]
    n = p.n
    base = np.zeros(int(rate * 0.06), np.complex64)
    t = np.arange(len(base)) / rate
    for f, payload in zip(channels, payloads):
        chips = modulate_frame(payload, p)
        up = np.zeros(len(chips) * decim, np.complex64)   # chip rate → wideband rate
        up[::decim] = chips
        from scipy import signal as sps
        lp = sps.firwin(8 * decim + 1, 0.9 / decim)
        up = sps.lfilter(lp, 1.0, up).astype(np.complex64) * decim
        k = 2000
        seg = min(len(up), len(base) - k)
        base[k:k + seg] += (up[:seg]
                            * np.exp(2j * np.pi * (f - center) * t[:seg])
                            ).astype(np.complex64)

    fg = Flowgraph()
    src = VectorSource(base)
    fg, receivers, tags = build_multichannel_rx(src, rate, center, p,
                                                channels_hz=channels, fg=fg)
    sinks = []
    for tag in tags:
        snk = MessageSink()
        fg.connect_message(tag, "out", snk, "in")
        sinks.append(snk)
    Runtime().run(fg)

    got = {}
    for snk in sinks:
        for m in snk.received:
            d = m.to_map()
            got[d["payload"].to_blob()] = d["freq"].to_float()
    assert got.get(b"chan-A-frame") == 867.7e6
    assert got.get(b"chan-B-frame") == 868.1e6


def test_multichannel_rx_channelizer_front_end():
    """use_channelizer=True: ONE PFB channelizer + per-channel arb resampler
    (the reference `rx_all_channels_eu.rs:109-144` chain) decodes frames on two
    grid channels with the right frequency tags."""
    from futuresdr_tpu.blocks import VectorSource
    from futuresdr_tpu.models.lora.phy import modulate_frame

    p = LoraParams(sf=7)
    rate = 1e6
    center = 867.9e6
    channels = [867.65e6, 868.15e6]            # ±250 kHz: on the 4-slot grid
    decim = int(rate // 125e3)

    payloads = [b"grid-chan-lo", b"grid-chan-hi"]
    base = np.zeros(int(rate * 0.06), np.complex64)
    t = np.arange(len(base)) / rate
    from scipy import signal as sps
    for f, payload in zip(channels, payloads):
        chips = modulate_frame(payload, p)
        up = np.zeros(len(chips) * decim, np.complex64)
        up[::decim] = chips
        lp = sps.firwin(8 * decim + 1, 0.9 / decim)
        up = sps.lfilter(lp, 1.0, up).astype(np.complex64) * decim
        k = 3000
        seg = min(len(up), len(base) - k)
        base[k:k + seg] += (up[:seg]
                            * np.exp(2j * np.pi * (f - center) * t[:seg])
                            ).astype(np.complex64)

    fg = Flowgraph()
    src = VectorSource(base)
    fg, receivers, tags = build_multichannel_rx(src, rate, center, p,
                                                channels_hz=channels, fg=fg,
                                                use_channelizer=True,
                                                spacing_hz=250e3)
    sinks = []
    for tag in tags:
        snk = MessageSink()
        fg.connect_message(tag, "out", snk, "in")
        sinks.append(snk)
    Runtime().run(fg)

    got = {}
    for snk in sinks:
        for m in snk.received:
            d = m.to_map()
            got[d["payload"].to_blob()] = d["freq"].to_float()
    assert got.get(b"grid-chan-lo") == 867.65e6
    assert got.get(b"grid-chan-hi") == 868.15e6


def test_meshtastic_random_roundtrip_fuzz():
    """Seeded sweep: random Meshtastic payloads/senders/packet-ids across
    random channel keys encode→decode exactly; wrong channels never decode."""
    rng = np.random.default_rng(20101)
    for trial in range(10):
        key = base64.b64encode(rng.integers(0, 256, 16).astype(np.uint8)
                               .tobytes()).decode()
        ch = meshtastic.MeshtasticChannel(f"Chan{trial}", key)
        text = bytes(rng.integers(32, 127, int(rng.integers(1, 60)))
                     .astype(np.uint8)).decode()
        sender = int(rng.integers(1, 1 << 32))
        pid = int(rng.integers(1, 1 << 32))
        wire = ch.encode(text, sender=sender, packet_id=pid).to_bytes()
        back = meshtastic.decode_any([ch], wire)
        assert back is not None and back[2].decode() == text, trial
        other = meshtastic.MeshtasticChannel("Other", "AQ==")
        assert other.decode(meshtastic.MeshPacket.parse(wire)) is None, trial


def test_hash_collision_wrong_key_garbage_rejected():
    """Regression (r5 fuzz campaign, offset 23253 trial 5): when a random
    channel's 1-byte xor hash COLLIDES with another channel's, the wrong-key
    decrypt reaches the Data parser — garbage must not parse as a packet.
    The exact colliding configuration is pinned here."""
    rng = np.random.default_rng(20101 + 23253)
    key = sender = pid = text = None
    for trial in range(6):
        key = base64.b64encode(rng.integers(0, 256, 16).astype(np.uint8)
                               .tobytes()).decode()
        ch = meshtastic.MeshtasticChannel(f"Chan{trial}", key)
        text = bytes(rng.integers(32, 127, int(rng.integers(1, 60)))
                     .astype(np.uint8)).decode()
        sender = int(rng.integers(1, 1 << 32))
        pid = int(rng.integers(1, 1 << 32))
    other = meshtastic.MeshtasticChannel("Other", "AQ==")
    assert ch.hash == other.hash          # the collision that let garbage in
    wire = ch.encode(text, sender=sender, packet_id=pid).to_bytes()
    assert other.decode(meshtastic.MeshPacket.parse(wire)) is None
    # the right channel still decodes (portnum-presence gate is not too strict)
    got = meshtastic.decode_any([ch], wire)
    assert got is not None and got[2].decode() == text
