"""Integration tests: real mini-flowgraphs on the real runtime.

Reference: `tests/flowgraph.rs` (1M zeros through a copy chain :50-71; 10M random f32
bit-exact :147-172; fan-out broadcast :174-207; handle start/stop :97-113).
"""

import numpy as np
import pytest

from futuresdr_tpu import Flowgraph, Runtime, Pmt, FlowgraphError, ConnectError
from futuresdr_tpu.blocks import (Apply, Copy, Head, VectorSource, VectorSink,
                                  NullSource, NullSink, CopyRand, Combine)


def test_copy_chain_zeros():
    fg = Flowgraph()
    src = VectorSource(np.zeros(100_000, np.float32))
    c1, c2 = Copy(np.float32), Copy(np.float32)
    snk = VectorSink(np.float32)
    fg.connect(src, c1, c2, snk)
    Runtime().run(fg)
    out = snk.items()
    assert len(out) == 100_000
    assert not out.any()


def test_random_bit_exact():
    # 10M random f32, bit-exact through the runtime (`tests/flowgraph.rs:147-172`)
    data = np.random.default_rng(42).random(10_000_000).astype(np.float32)
    fg = Flowgraph()
    src = VectorSource(data)
    mid = CopyRand(np.float32, max_copy=4096)
    snk = VectorSink(np.float32)
    fg.connect(src >> mid >> snk)
    Runtime().run(fg)
    np.testing.assert_array_equal(snk.items(), data)


def test_fanout_broadcast():
    data = np.arange(10_000, dtype=np.float32)
    fg = Flowgraph()
    src = VectorSource(data)
    sinks = [VectorSink(np.float32) for _ in range(10)]
    for s in sinks:
        fg.connect_stream(src, "out", s, "in")
    Runtime().run(fg)
    for s in sinks:
        np.testing.assert_array_equal(s.items(), data)


def test_apply_chain_math():
    data = np.arange(1000, dtype=np.float32)
    fg = Flowgraph()
    src = VectorSource(data)
    a = Apply(lambda x: x * 2.0, np.float32)
    b = Apply(lambda x: x + 1.0, np.float32)
    snk = VectorSink(np.float32)
    fg.connect(src, a, b, snk)
    Runtime().run(fg)
    np.testing.assert_allclose(snk.items(), data * 2.0 + 1.0)


def test_combine_two_streams():
    a = np.arange(5000, dtype=np.float32)
    b = np.arange(5000, dtype=np.float32) * 10
    fg = Flowgraph()
    sa, sb = VectorSource(a), VectorSource(b)
    add = Combine(lambda x, y: x + y, np.float32)
    snk = VectorSink(np.float32)
    fg.connect_stream(sa, "out", add, "in0")
    fg.connect_stream(sb, "out", add, "in1")
    fg.connect_stream(add, "out", snk, "in")
    Runtime().run(fg)
    np.testing.assert_allclose(snk.items(), a + b)


def test_null_source_head_sink():
    fg = Flowgraph()
    src = NullSource(np.complex64)
    head = Head(np.complex64, 500_000)
    snk = NullSink(np.complex64)
    fg.connect(src, head, snk)
    Runtime().run(fg)
    assert snk.n_received == 500_000


def test_start_stop_handle():
    fg = Flowgraph()
    src = NullSource(np.float32)
    snk = NullSink(np.float32)
    fg.connect(src, snk)
    rt = Runtime()
    running = rt.start(fg)
    desc = running.handle.describe_sync()
    assert len(desc.blocks) == 2
    fg2 = running.stop_sync()
    assert fg2 is fg
    assert snk.n_received > 0


def test_dtype_mismatch_rejected():
    fg = Flowgraph()
    src = NullSource(np.float32)
    snk = NullSink(np.complex64)
    with pytest.raises(ConnectError):
        fg.connect(src, snk)


def test_bad_port_name_rejected():
    fg = Flowgraph()
    src = NullSource(np.float32)
    snk = NullSink(np.float32)
    with pytest.raises(KeyError):
        fg.connect_stream(src, "bogus", snk, "in")


def test_double_connect_rejected():
    fg = Flowgraph()
    a, b = NullSource(np.float32), NullSource(np.float32)
    snk = NullSink(np.float32)
    fg.connect(a, snk)
    with pytest.raises(ConnectError):
        fg.connect(b, snk)


def test_unconnected_input_fails():
    fg = Flowgraph()
    snk = NullSink(np.float32)
    fg.add(snk)
    with pytest.raises(FlowgraphError):
        Runtime().run(fg)
