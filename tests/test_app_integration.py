"""Full-stack integration: spectrum flowgraph + control port + GUI page + websocket
spectrum frames + runtime retuning — one user session end to end."""

import asyncio
import json
import urllib.request

import numpy as np

from futuresdr_tpu import Flowgraph, Runtime, Pmt
from futuresdr_tpu.blocks import (Apply, Fft, MovingAvg, SignalSource, WebsocketSink,
                                  Head)
from futuresdr_tpu.runtime.ctrl_port import ControlPort


def test_spectrum_session_end_to_end():
    fs = 1e6
    fft_size = 512
    fg = Flowgraph()
    src = SignalSource("complex", 100e3, fs)
    head = Head(np.complex64, 200_000_000)
    fft = Fft(fft_size)
    mag = Apply(lambda x: (x.real ** 2 + x.imag ** 2), np.complex64, np.float32)
    avg = MovingAvg(fft_size, width=2, decay=0.3)
    ws = WebsocketSink(29619, np.float32, chunk_items=fft_size)
    fg.connect(src, head, fft, mag, avg, ws)

    rt = Runtime()
    cp = ControlPort(rt.handle, bind="127.0.0.1:29620")
    cp.start()
    running = rt.start(fg)
    try:
        base = "http://127.0.0.1:29620"
        # GUI page + flowgraph structure over REST
        html = urllib.request.urlopen(f"{base}/").read().decode()
        assert "waterfall" in html
        desc = json.load(urllib.request.urlopen(f"{base}/api/fg/0/"))
        names = [b["type_name"] for b in desc["blocks"]]
        assert "SignalSource" in names and "WebsocketSink" in names

        async def grab_spectrum():
            import websockets
            for _ in range(50):
                try:
                    async with websockets.connect("ws://127.0.0.1:29619") as c:
                        return np.frombuffer(
                            await asyncio.wait_for(c.recv(), timeout=5), np.float32)
                except (ConnectionRefusedError, OSError):
                    await asyncio.sleep(0.1)
            raise RuntimeError("ws connect failed")

        spec = rt.scheduler.run_coro_sync(grab_spectrum())
        assert len(spec) == fft_size
        assert np.argmax(spec) == round(100e3 / fs * fft_size)

        # retune over REST, confirm the peak moves
        req = urllib.request.Request(
            f"{base}/api/fg/0/block/0/call/freq/",
            data=json.dumps({"F64": 250e3}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        assert json.load(urllib.request.urlopen(req)) == "Ok"
        import time
        deadline = time.time() + 10
        moved = False
        while time.time() < deadline and not moved:
            spec = rt.scheduler.run_coro_sync(grab_spectrum())
            moved = np.argmax(spec) == round(250e3 / fs * fft_size)
        assert moved
        # live metrics over REST
        m = json.load(urllib.request.urlopen(f"{base}/api/fg/0/metrics/"))
        assert any(v["work_calls"] > 0 for v in m.values())
    finally:
        running.stop_sync()
        cp.stop()
