"""Wire-format codec layer + overlapped transfer machinery (ops/wire, ops/xfer).

Tier-1 coverage for the streamed-path wire codec PR:

- codec round trips per format (host↔host is direction-symmetric, so it is
  exactly one link crossing's quantization), measured-SNR floors, byte widths,
  non-float passthrough, empty frames;
- ``to_device``/``to_host`` round trips: complex64/complex128, strided and
  non-contiguous inputs, empty frames, and BIT-exactness of the f32-pair path
  (regression-locks the ``ascontiguousarray`` view trick);
- the D2H fallback path (no ``copy_to_host_async``) must start every fetch
  eagerly — a stub array type proves two slow fetches overlap;
- streamed smoke over a rate-throttled fake link: a TpuKernel chain through
  every wire format is tolerance-correct, and the pipelined drain loop
  beats the serialized one on wall-clock (transfer/compute overlap).
"""

import time

import numpy as np
import pytest

from futuresdr_tpu.ops import xfer
from futuresdr_tpu.ops.wire import (WIRE_FORMATS, get_wire, measure_snr_db,
                                    resolve_wire, streamed_ceiling_msps,
                                    wire_names)

ALL_WIRES = sorted(wire_names())


@pytest.fixture
def fake_link():
    """Install a throttled fake link for the test; always restore after."""
    installed = []

    def install(h2d_bps, d2h_bps):
        installed.append(xfer.set_fake_link(h2d_bps, d2h_bps))

    yield install
    xfer.set_fake_link()


def _gaussian_c64(n, seed=0):
    rng = np.random.default_rng(seed)
    return ((rng.standard_normal(n) + 1j * rng.standard_normal(n))
            / np.sqrt(2)).astype(np.complex64)


# ---------------------------------------------------------------------------
# codec unit tests
# ---------------------------------------------------------------------------

# measured-SNR floor per format for a unit-power Gaussian c64 frame; nominal
# figures are NOT trusted (the table in ops/wire.py is derived, these are
# asserted)
SNR_FLOORS = {"f32": float("inf"), "bf16": 35.0, "sc16": 80.0, "sc8": 38.0}


@pytest.mark.parametrize("name", ALL_WIRES)
def test_measured_snr_floor(name):
    snr = measure_snr_db(name)
    assert snr >= SNR_FLOORS[name]


@pytest.mark.parametrize("name", ALL_WIRES)
def test_host_round_trip_complex(name):
    w = get_wire(name)
    x = _gaussian_c64(4096, seed=1)
    y = w.decode_host(w.encode_host(x), np.complex64)
    assert y.dtype == np.complex64 and y.shape == x.shape
    tol = 10 ** (-SNR_FLOORS[name] / 20) if name != "f32" else 0.0
    np.testing.assert_allclose(y, x, atol=2 * tol + 1e-12, rtol=0)


@pytest.mark.parametrize("name", ALL_WIRES)
def test_host_round_trip_real(name):
    w = get_wire(name)
    x = np.random.default_rng(2).standard_normal(1024).astype(np.float32)
    y = w.decode_host(w.encode_host(x), np.float32)
    assert y.dtype == np.float32 and y.shape == x.shape
    tol = 10 ** (-SNR_FLOORS[name] / 20) if name != "f32" else 0.0
    np.testing.assert_allclose(y, x, atol=2 * tol + 1e-12, rtol=0)


@pytest.mark.parametrize("name", ALL_WIRES)
def test_jax_decode_matches_host_decode(name):
    """The jitted device prolog and the host decode agree on the same parts —
    the two ends of the link speak the same layout."""
    import jax
    w = get_wire(name)
    x = _gaussian_c64(512, seed=3)
    parts = w.encode_host(x)
    dec = jax.jit(lambda *p: w.decode_jax(p, np.complex64))
    y_dev = np.asarray(dec(*parts))
    y_host = w.decode_host(parts, np.complex64)
    np.testing.assert_allclose(y_dev, y_host, rtol=1e-6, atol=1e-7)


@pytest.mark.parametrize("name", ALL_WIRES)
def test_jax_encode_round_trip(name):
    """Device epilog encode → host decode: the D2H direction's codec."""
    import jax
    import jax.numpy as jnp
    w = get_wire(name)
    x = _gaussian_c64(512, seed=4)
    enc = jax.jit(lambda v: w.encode_jax(v))
    parts = tuple(np.asarray(p) for p in enc(jnp.asarray(x)))
    y = w.decode_host(parts, np.complex64)
    tol = 10 ** (-SNR_FLOORS[name] / 20) if name != "f32" else 1e-7
    np.testing.assert_allclose(y, x, atol=2 * tol + 1e-12, rtol=0)


def test_bytes_per_sample():
    c, f = np.complex64, np.float32
    assert get_wire("f32").bytes_per_sample(c) == 8
    assert get_wire("bf16").bytes_per_sample(c) == 4
    assert get_wire("sc16").bytes_per_sample(c) == 4
    assert get_wire("sc8").bytes_per_sample(c) == 2
    assert get_wire("f32").bytes_per_sample(f) == 4
    assert get_wire("sc8").bytes_per_sample(f) == 1
    # non-float payloads pass through at their own width
    assert get_wire("sc8").bytes_per_sample(np.int32) == 4


@pytest.mark.parametrize("name", ALL_WIRES)
def test_non_float_passthrough(name):
    """Integer payloads (demod symbol indices) must cross every format
    bit-exact — quantizing indices would corrupt them."""
    w = get_wire(name)
    x = np.arange(-5, 250, dtype=np.int32)
    y = w.decode_host(w.encode_host(x), np.int32)
    np.testing.assert_array_equal(y, x)


@pytest.mark.parametrize("name", ALL_WIRES)
def test_empty_frame(name):
    w = get_wire(name)
    x = np.empty(0, dtype=np.complex64)
    y = w.decode_host(w.encode_host(x), np.complex64)
    assert y.shape == (0,) and y.dtype == np.complex64


def test_quant_constant_and_zero_frames():
    """Block-floating-point: a constant frame uses the full int range (exact
    up to rounding), and an all-zero frame survives (scale guard, no 0/0)."""
    w = get_wire("sc16")
    x = np.full(256, 0.125 + 0.0625j, dtype=np.complex64)
    y = w.decode_host(w.encode_host(x), np.complex64)
    np.testing.assert_allclose(y, x, rtol=1e-4)
    z = np.zeros(256, dtype=np.complex64)
    y = w.decode_host(w.encode_host(z), np.complex64)
    np.testing.assert_array_equal(y, z)


@pytest.mark.parametrize("name", ["sc16", "sc8"])
def test_quant_nonfinite_samples_zeroed_frame_survives(name):
    """One inf/NaN sample must not poison the frame: the quantizer zeroes
    non-finite samples (an int wire cannot carry them) and every finite
    neighbour round-trips at full scale — regression for the scale-fallback
    overflow (scale=1.0 would wrap amplitude-1000 samples to garbage)."""
    import jax.numpy as jnp
    w = get_wire(name)
    x = np.full(256, 1000.0 + 500.0j, dtype=np.complex64)
    x[7] = np.inf + 0j
    x[11] = np.nan * 1j
    tol = 1000.0 / (2 * w.qmax)
    # host-side encode
    y = w.decode_host(w.encode_host(x), np.complex64)
    assert np.isfinite(y).all()
    assert y[7] == 0 and y[11] == 0
    keep = np.ones(256, bool); keep[[7, 11]] = False
    np.testing.assert_allclose(y[keep], x[keep], atol=2 * tol, rtol=0)
    # device-side encode epilog behaves identically
    y = w.decode_host(
        tuple(np.asarray(p) for p in w.jit_encode()(jnp.asarray(x))),
        np.complex64)
    assert np.isfinite(y).all()
    assert y[7] == 0 and y[11] == 0
    np.testing.assert_allclose(y[keep], x[keep], atol=2 * tol, rtol=0)


def test_get_wire_and_resolve():
    with pytest.raises(KeyError, match="unknown wire format"):
        get_wire("sc4")
    assert get_wire(WIRE_FORMATS["sc16"]) is WIRE_FORMATS["sc16"]
    # auto: exact on the CPU backend (the "link" is a memcpy), sc16 elsewhere
    assert resolve_wire("auto", "cpu").name == "f32"
    assert resolve_wire("auto", "tpu").name == "sc16"
    assert resolve_wire("sc8", "cpu").name == "sc8"


def test_streamed_ceiling_msps():
    # 96 MB/s up, 62 MB/s down; c64 in (8 B f32 / 4 B sc16), f32 out (4/2 B)
    f32 = streamed_ceiling_msps("f32", 96e6, 62e6)
    sc16 = streamed_ceiling_msps("sc16", 96e6, 62e6)
    assert f32 == pytest.approx(12.0)        # min(96/8, 62/4)
    assert sc16 == pytest.approx(24.0)       # min(96/4, 62/2) — 2× the bytes win
    assert streamed_ceiling_msps("sc8", 96e6, 62e6) == pytest.approx(48.0)


def test_pick_wire_snr_floor_and_tie_break():
    from futuresdr_tpu.tpu.autotune import pick_wire
    # link-bound: sc16 halves the bytes and clears the 60 dB floor → picked;
    # sc8/bf16 are excluded by the floor despite their higher ceilings
    assert pick_wire(96e6, 62e6, np.complex64, np.float32) == "sc16"
    # compute-bound far below every ceiling: ties go to the exact format
    assert pick_wire(96e6, 62e6, np.complex64, np.float32,
                     compute_msps=1.0) == "f32"
    # floor disabled and link-bound: sc8's 4× byte win takes it
    assert pick_wire(96e6, 62e6, np.complex64, np.float32,
                     min_snr_db=None) == "sc8"


# ---------------------------------------------------------------------------
# xfer round trips (satellite: regression-lock the pair-shim view trick)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("dtype", [np.complex64, np.complex128])
def test_to_device_to_host_round_trip(dtype):
    x = (_gaussian_c64(2048, seed=5)).astype(dtype)
    y = xfer.to_host(xfer.to_device(x))
    np.testing.assert_allclose(y, x.astype(np.complex64), rtol=1e-6, atol=1e-7)


def test_round_trip_strided_and_noncontiguous():
    base = _gaussian_c64(4096, seed=6)
    strided = base[::3]                          # non-unit stride
    np.testing.assert_allclose(xfer.to_host(xfer.to_device(strided)), strided,
                               rtol=1e-6, atol=0)
    mat = base.reshape(64, 64).T                 # non-contiguous 2-D view
    np.testing.assert_allclose(xfer.to_host(xfer.to_device(mat)), mat,
                               rtol=1e-6, atol=0)


def test_round_trip_empty():
    y = xfer.to_host(xfer.to_device(np.empty(0, np.complex64)))
    assert y.shape == (0,)


def test_pair_path_bit_exact(monkeypatch):
    """The f32-pair shim (forced on, as on every accelerator platform) must be
    BIT-exact: the wire is a reinterpreting view, not an arithmetic cast."""
    monkeypatch.setattr(xfer, "split_complex_platform", lambda p: True)
    x = _gaussian_c64(4096, seed=7)
    x[7] = np.float32(1e-38) + 1j * np.float32(-1e38)    # extreme exponents
    y = xfer.to_host(xfer.to_device(x))
    assert y.dtype == np.complex64
    np.testing.assert_array_equal(y.view(np.uint64), x.view(np.uint64))


def test_host_array_passthrough():
    """start_host_transfer of a plain numpy array must not round-trip it
    through the device."""
    x = _gaussian_c64(64, seed=8)
    np.testing.assert_array_equal(xfer.start_host_transfer(x)(), x)


# ---------------------------------------------------------------------------
# D2H fallback: fetches must start eagerly (satellite fix)
# ---------------------------------------------------------------------------

class _SlowStubArray:
    """Array type WITHOUT copy_to_host_async: conversion costs ``delay``."""

    def __init__(self, value, delay=0.05):
        self._v = np.asarray(value)
        self.delay = delay

    def __array__(self, dtype=None, copy=None):
        time.sleep(self.delay)
        return self._v if dtype is None else self._v.astype(dtype)


class _AsyncStubArray(_SlowStubArray):
    """Array type WITH copy_to_host_async: records when the copy started."""

    def __init__(self, value):
        super().__init__(value, delay=0.0)
        self.async_started = False

    def copy_to_host_async(self):
        self.async_started = True


def test_start_fetch_fallback_overlaps():
    """Two fallback fetches (no copy_to_host_async) must ride concurrently:
    the old code fetched synchronously inside finish(), oldest-first, so two
    50 ms fetches cost 100 ms; the eager pool brings it to ~50 ms."""
    a = _SlowStubArray(np.arange(4, dtype=np.float32))
    b = _SlowStubArray(np.arange(4, 8, dtype=np.float32))
    t0 = time.perf_counter()
    fa, fb = xfer._start_fetch(a), xfer._start_fetch(b)
    ra, rb = fa(), fb()
    elapsed = time.perf_counter() - t0
    np.testing.assert_array_equal(ra, a._v)
    np.testing.assert_array_equal(rb, b._v)
    assert elapsed < 0.085, f"fetches serialized: {elapsed * 1e3:.0f} ms"


def test_start_fetch_uses_copy_to_host_async():
    a = _AsyncStubArray(np.ones(4, np.float32))
    fin = xfer._start_fetch(a)
    assert a.async_started            # started at call time, not inside finish
    np.testing.assert_array_equal(fin(), a._v)


# ---------------------------------------------------------------------------
# fake link + streamed smoke (satellite: CI overlap evidence)
# ---------------------------------------------------------------------------

def test_fake_link_throttles_and_restores(fake_link):
    payload = np.zeros(1 << 18, np.float32)      # 1 MiB
    fake_link(h2d_bps=64e6, d2h_bps=64e6)        # → ≥ ~16 ms per crossing
    t0 = time.perf_counter()
    y = xfer.to_device(payload)
    up = time.perf_counter() - t0
    t0 = time.perf_counter()
    xfer.to_host(y)
    down = time.perf_counter() - t0
    assert up >= 0.014 and down >= 0.014
    xfer.set_fake_link()                         # removed → no throttle
    t0 = time.perf_counter()
    xfer.to_host(xfer.to_device(payload))
    assert time.perf_counter() - t0 < 0.014


# per-format output tolerance for the fft+mag2 chain, relative to the spectrum
# peak (quantization noise spreads over the fft; block-fp scales to the peak)
CHAIN_TOL = {"f32": 1e-5, "bf16": 3e-2, "sc16": 1e-3, "sc8": 8e-2}


def _run_wired_kernel(wire, tone, frame, depth):
    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import VectorSink, VectorSource
    from futuresdr_tpu.ops import fft_stage, mag2_stage
    from futuresdr_tpu.tpu import TpuKernel
    fg = Flowgraph()
    src = VectorSource(tone)
    tk = TpuKernel([fft_stage(256), mag2_stage()], np.complex64,
                   frame_size=frame, frames_in_flight=depth, wire=wire)
    snk = VectorSink(np.float32)
    fg.connect(src, tk, snk)
    t0 = time.perf_counter()
    Runtime().run(fg)
    return np.asarray(snk.items()), time.perf_counter() - t0


@pytest.mark.parametrize("name", ALL_WIRES)
def test_streamed_kernel_every_wire_format(name, fake_link):
    """TpuKernel chain through each wire format over a throttled fake link:
    output is tolerance-correct for the format's SNR class."""
    fake_link(h2d_bps=400e6, d2h_bps=400e6)
    n, frame = 1 << 16, 1 << 14
    x = (0.8 * np.exp(2j * np.pi * 0.125 * np.arange(n))
         + _gaussian_c64(n, seed=9) * 0.01).astype(np.complex64)
    got, _ = _run_wired_kernel(name, x, frame, depth=4)
    assert len(got) == n
    ref = (np.abs(np.fft.fft(x.reshape(-1, 256), axis=1)) ** 2).reshape(-1)
    peak = float(ref.max())
    np.testing.assert_allclose(got, ref, atol=CHAIN_TOL[name] * peak,
                               rtol=CHAIN_TOL[name] * 10)


def test_streamed_pipelining_overlaps_link(fake_link):
    """Trace-measured evidence of H2D ∥ compute ∥ D2H: the span recorder's
    per-frame lane intervals prove the overlap directly — union(all lanes) <
    Σ(durations) — instead of the old wall-clock `pipelined ≤ 0.75×serialized`
    heuristic (which conflated scheduler noise with overlap and could not say
    WHICH lane hid under which). Serialized (depth=1) must read ≈ 1.0 and the
    pipelined loop ≤ 0.75: with the fake link's per-direction wire occupancy
    deterministically modeled, the ideal pipelined ratio here is ~0.5 (D2H
    fully hidden under H2D, compute ≈ 0) and the serialized one exactly 1.0
    (lanes strictly alternate on one frame in flight)."""
    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import VectorSink, VectorSource
    from futuresdr_tpu.ops import mag2_stage
    from futuresdr_tpu.telemetry import spans
    from futuresdr_tpu.tpu import TpuKernel

    n, frame = 1 << 19, 1 << 15
    tone = np.exp(2j * np.pi * 0.2 * np.arange(n)).astype(np.complex64)

    def run(depth):
        fg = Flowgraph()
        src = VectorSource(tone)
        tk = TpuKernel([mag2_stage()], np.complex64, frame_size=frame,
                       frames_in_flight=depth, wire="f32")
        snk = VectorSink(np.float32)
        fg.connect(src, tk, snk)
        spans.drain()                            # fresh ring for this run
        Runtime().run(fg)
        return spans.overlap_report(spans.drain())

    was = spans.enabled()
    spans.enable(True)
    try:
        # f32 wire: 256 KiB/frame up (16 ms at 16 MB/s), 128 KiB down (16 ms
        # at 8 MB/s); 16 frames → ≈512 ms of modeled wire time per run
        fake_link(h2d_bps=16e6, d2h_bps=8e6)
        serial = run(1)
        fake_link(h2d_bps=16e6, d2h_bps=8e6)     # fresh timeline
        pipe = run(4)
    finally:
        spans.enable(was)
    # every lane actually recorded every frame
    for rep in (serial, pipe):
        for lane in ("H2D", "compute", "D2H"):
            assert rep["lanes"][lane]["spans"] == n // frame, (lane, rep)
    # the wire time is real (≈0.13 s per direction at these rates), so the
    # ratio is measuring modeled link occupancy, not noise-scale intervals
    assert pipe["sum_s"] >= 0.2, pipe
    assert serial["ratio"] >= 0.9, \
        f"serialized lanes overlapped: {serial}"
    assert pipe["ratio"] <= 0.75, \
        f"no overlap: pipelined union/sum {pipe['ratio']:.2f} ({pipe})"


def test_frame_plane_wire_round_trip(fake_link):
    """TpuH2D(wire) → TpuStage → TpuD2H(wire): the frame plane speaks the
    codec on both crossings too."""
    from scipy import signal as sps
    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import VectorSink, VectorSource
    from futuresdr_tpu.dsp import firdes
    from futuresdr_tpu.ops import fir_stage
    from futuresdr_tpu.tpu import TpuD2H, TpuH2D, TpuStage
    fake_link(h2d_bps=400e6, d2h_bps=400e6)
    taps = firdes.lowpass(0.2, 64).astype(np.float32)
    data = np.random.default_rng(10).standard_normal(100_000).astype(np.float32)
    frame = 16384
    fg = Flowgraph()
    src, snk = VectorSource(data), VectorSink(np.float32)
    h2d = TpuH2D(np.float32, frame_size=frame, wire="sc16")
    st = TpuStage([fir_stage(taps, fft_len=1024)], np.float32)
    d2h = TpuD2H(np.float32, wire="sc16")
    fg.connect(src, h2d, st, d2h, snk)
    Runtime().run(fg)
    got = snk.items()
    ref = sps.lfilter(taps, 1.0, data)
    n = (len(data) // frame) * frame
    assert len(got) >= n
    np.testing.assert_allclose(got[:n], ref[:n], rtol=1e-2, atol=2e-3)


def test_wire_config_env_override(monkeypatch):
    """FUTURESDR_TPU_WIRE_FORMAT pins the codec through resolve_wire(None)."""
    monkeypatch.setenv("FUTURESDR_TPU_WIRE_FORMAT", "sc8")
    from futuresdr_tpu.config import reload_config
    reload_config()
    try:
        assert resolve_wire(None, "cpu").name == "sc8"
    finally:
        monkeypatch.delenv("FUTURESDR_TPU_WIRE_FORMAT")
        reload_config()
