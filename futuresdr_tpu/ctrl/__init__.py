"""Remote control client (the ``futuresdr-remote`` crate equivalent)."""

from .remote import Remote, RemoteFlowgraph, RemoteBlock

__all__ = ["Remote", "RemoteFlowgraph", "RemoteBlock"]
