"""Typed async HTTP client for the REST control plane.

Re-design of the ``futuresdr-remote`` crate (``crates/remote/src/remote.rs:17-291``):
``Remote → RemoteFlowgraph → RemoteBlock.call/(callback)`` mirroring the server routes.
"""

from __future__ import annotations

from typing import List, Optional

from ..types import Pmt

__all__ = ["Remote", "RemoteFlowgraph", "RemoteBlock"]


class Remote:
    def __init__(self, url: str):
        self.url = url.rstrip("/")

    async def _get(self, path: str):
        import aiohttp
        async with aiohttp.ClientSession() as s:
            async with s.get(self.url + path) as r:
                r.raise_for_status()
                return await r.json()

    async def _post(self, path: str, body):
        import aiohttp
        async with aiohttp.ClientSession() as s:
            async with s.post(self.url + path, json=body) as r:
                r.raise_for_status()
                return await r.json()

    async def flowgraphs(self) -> List["RemoteFlowgraph"]:
        ids = await self._get("/api/fg/")
        return [RemoteFlowgraph(self, i) for i in ids]

    async def flowgraph(self, fg_id: int = 0) -> "RemoteFlowgraph":
        return RemoteFlowgraph(self, fg_id)


class Connection:
    """A typed edge of the remote flowgraph (`remote.rs:246-291`)."""

    def __init__(self, kind: str, src: "RemoteBlock", src_port, dst: "RemoteBlock",
                 dst_port):
        self.kind = kind                      # "stream" | "message"
        self.src, self.src_port = src, src_port
        self.dst, self.dst_port = dst, dst_port

    def __repr__(self):
        return (f"Connection({self.kind}: {self.src.instance_name}.{self.src_port} → "
                f"{self.dst.instance_name}.{self.dst_port})")


class RemoteFlowgraph:
    def __init__(self, remote: Remote, fg_id: int):
        self.remote = remote
        self.id = fg_id

    async def description(self) -> dict:
        return await self.remote._get(f"/api/fg/{self.id}/")

    async def blocks(self) -> List["RemoteBlock"]:
        desc = await self.description()
        return [RemoteBlock(self, b["id"], b) for b in desc["blocks"]]

    async def block(self, block_id: int) -> "RemoteBlock":
        desc = await self.remote._get(f"/api/fg/{self.id}/block/{block_id}/")
        return RemoteBlock(self, block_id, desc)

    async def connections(self) -> List[Connection]:
        """Typed stream + message edges (`remote.rs` Connection/ConnectionType)."""
        desc = await self.description()
        by_id = {b["id"]: RemoteBlock(self, b["id"], b) for b in desc["blocks"]}
        out: List[Connection] = []
        for kind, key in (("stream", "stream_edges"), ("message", "message_edges")):
            for s, sp, d, dp in desc.get(key, []):
                out.append(Connection(kind, by_id[s], sp, by_id[d], dp))
        return out


class RemoteBlock:
    def __init__(self, fg: RemoteFlowgraph, block_id: int, description: Optional[dict] = None):
        self.fg = fg
        self.id = block_id
        self.description = description or {}

    @property
    def instance_name(self) -> str:
        return self.description.get("instance_name", f"block{self.id}")

    @property
    def type_name(self) -> str:
        return self.description.get("type_name", "")

    def handlers(self) -> List[str]:
        """Names of the block's message handlers — addressable by name or index
        (`remote.rs` Handler::Name/Handler::Id)."""
        return list(self.description.get("message_inputs", []))

    async def call(self, handler) -> Pmt:
        """Call with ``Pmt::Null`` — the get-style form (`remote.rs:211-214`:
        `call` delegates to `callback` with Null)."""
        return await self.callback(handler, Pmt.null())

    async def callback(self, handler, pmt: Pmt = None) -> Pmt:
        """Call a handler (by name or index) with ``pmt``; returns the reply."""
        if pmt is None:
            pmt = Pmt.null()
        pmt = Pmt.from_py(pmt) if not isinstance(pmt, Pmt) else pmt
        r = await self.fg.remote._post(
            f"/api/fg/{self.fg.id}/block/{self.id}/call/{handler}/", pmt.to_json())
        return Pmt.from_json(r)

    def __repr__(self):
        return f"{self.instance_name} ({self.type_name}, {self.id})"
