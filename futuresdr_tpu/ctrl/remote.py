"""Typed async HTTP client for the REST control plane.

Re-design of the ``futuresdr-remote`` crate (``crates/remote/src/remote.rs:17-291``):
``Remote → RemoteFlowgraph → RemoteBlock.call/(callback)`` mirroring the server routes.
"""

from __future__ import annotations

from typing import List, Optional

from ..types import Pmt

__all__ = ["Remote", "RemoteFlowgraph", "RemoteBlock"]


class Remote:
    def __init__(self, url: str):
        self.url = url.rstrip("/")

    async def _get(self, path: str):
        import aiohttp
        async with aiohttp.ClientSession() as s:
            async with s.get(self.url + path) as r:
                r.raise_for_status()
                return await r.json()

    async def _post(self, path: str, body):
        import aiohttp
        async with aiohttp.ClientSession() as s:
            async with s.post(self.url + path, json=body) as r:
                r.raise_for_status()
                return await r.json()

    async def flowgraphs(self) -> List["RemoteFlowgraph"]:
        ids = await self._get("/api/fg/")
        return [RemoteFlowgraph(self, i) for i in ids]

    async def flowgraph(self, fg_id: int = 0) -> "RemoteFlowgraph":
        return RemoteFlowgraph(self, fg_id)


class RemoteFlowgraph:
    def __init__(self, remote: Remote, fg_id: int):
        self.remote = remote
        self.id = fg_id

    async def description(self) -> dict:
        return await self.remote._get(f"/api/fg/{self.id}/")

    async def blocks(self) -> List["RemoteBlock"]:
        desc = await self.description()
        return [RemoteBlock(self, b["id"], b) for b in desc["blocks"]]

    async def block(self, block_id: int) -> "RemoteBlock":
        desc = await self.remote._get(f"/api/fg/{self.id}/block/{block_id}/")
        return RemoteBlock(self, block_id, desc)


class RemoteBlock:
    def __init__(self, fg: RemoteFlowgraph, block_id: int, description: Optional[dict] = None):
        self.fg = fg
        self.id = block_id
        self.description = description or {}

    async def call(self, handler, pmt: Pmt = None) -> Pmt:
        pmt = Pmt.from_py(pmt) if not isinstance(pmt, Pmt) else pmt
        r = await self.fg.remote._post(
            f"/api/fg/{self.fg.id}/block/{self.id}/call/{handler}/", pmt.to_json())
        return Pmt.from_json(r)
