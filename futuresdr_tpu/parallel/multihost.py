"""Multi-host (DCN) initialization and mesh construction.

The reference crosses hosts with explicit socket blocks and has no intra-runtime
distribution (SURVEY §2.7). Here, multi-host scale is jax's distributed runtime: every
host runs the same SPMD program; the global mesh spans all hosts' devices; XLA routes
intra-host collectives over ICI and inter-host legs over DCN.

Single-host CI cannot exercise real DCN; this module is the thin, documented entry:

    from futuresdr_tpu.parallel import multihost
    multihost.initialize(coordinator="10.0.0.1:9999", num_processes=4, process_id=rank)
    mesh = multihost.global_mesh(("dp", "sp"))

The stream ops in :mod:`.stream_sp` then work unchanged on the global mesh — ``ppermute``
halo exchanges between shards on different hosts ride DCN automatically.
"""

from __future__ import annotations

from typing import Optional, Sequence

__all__ = ["initialize", "global_mesh", "is_distributed", "local_device_count",
           "global_device_count"]

_initialized = False


def initialize(coordinator: Optional[str] = None, num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Bring up jax's distributed runtime (no-op when already up or single-host).

    With no arguments, jax auto-detects the cluster environment (TPU pods set the
    coordination env vars); pass explicit values for manual bring-up.
    """
    global _initialized
    if _initialized:
        return
    import jax
    if coordinator is None and num_processes is None:
        try:
            jax.distributed.initialize()
        except Exception:
            pass          # single-host / no cluster env: stay local
    else:
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=num_processes,
                                   process_id=process_id)
    _initialized = True


def is_distributed() -> bool:
    import jax
    return jax.process_count() > 1


def local_device_count() -> int:
    import jax
    return jax.local_device_count()


def global_device_count() -> int:
    import jax
    return jax.device_count()


def global_mesh(axis_names: Sequence[str], shape: Optional[Sequence[int]] = None):
    """Mesh over ALL hosts' devices (call after :func:`initialize` on every host)."""
    from .mesh import make_mesh
    import jax
    return make_mesh(axis_names, shape=shape, devices=jax.devices())
