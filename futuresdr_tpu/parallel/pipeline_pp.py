"""Pipeline parallelism over a device mesh axis — GPipe-style microbatching.

The frame plane (``tpu/frames.py``) pipelines *whole flowgraph stages* across
time on one chip; this module pipelines a *single model* across CHIPS: each
device on the ``pp`` axis owns one stage's weights, activations hop stage→stage
over ICI with ``ppermute``, and microbatches stream through so all stages work
concurrently after the fill phase (the standard bubble of (S-1)/(S-1+M)).

Everything is a single jitted ``shard_map``: the schedule is a ``lax.scan`` over
``n_micro + n_stages - 1`` static steps, so XLA sees one compiled program with
collective permutes — no host round-trips between pipeline ticks.

Reference role: SURVEY §2.7 "pipeline parallel". The reference pipelines blocks
over CPU threads; the TPU-native form pipelines over the mesh.
"""

from __future__ import annotations

from typing import Callable

__all__ = ["make_pp_pipeline"]


def make_pp_pipeline(apply_stage: Callable, n_stages: int, n_micro: int,
                     mesh, axis: str = "pp"):
    """Build ``fn(stage_params, micro_x) -> micro_y`` running a ``n_stages``-deep
    pipeline over ``mesh[axis]``.

    - ``apply_stage(params_one_stage, x) -> y``: one stage's computation; input
      and output must share shape/dtype (activations ride one ppermute channel).
    - ``stage_params``: any pytree whose leaves have a leading ``n_stages`` axis
      — sharded one-stage-per-device along ``axis``.
    - ``micro_x``: ``[n_micro, ...]`` microbatches (replicated); returns
      ``[n_micro, ...]`` outputs of the final stage (replicated).
    """
    import inspect

    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    try:
        from jax import shard_map          # jax ≥ 0.7 stable API
    except ImportError:                    # pragma: no cover
        from jax.experimental.shard_map import shard_map

    assert mesh.shape[axis] == n_stages, \
        f"mesh axis {axis} has {mesh.shape[axis]} devices, need {n_stages}"
    n_steps = n_micro + n_stages - 1
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def body(my_params, micro_x):
        # my_params leaves arrive as [1, ...] — this device's stage
        my_params = jax.tree_util.tree_map(lambda a: a[0], my_params)
        s = jax.lax.axis_index(axis)
        zero = jnp.zeros_like(micro_x[0])

        def step(carry, t):
            recv, outs = carry
            m_in = jnp.clip(t, 0, n_micro - 1)
            feed = jnp.where(t < n_micro, micro_x[m_in], zero)
            xin = jnp.where(s == 0, feed, recv)
            y = apply_stage(my_params, xin)
            recv_next = jax.lax.ppermute(y, axis, perm)
            # the LAST stage's step-t output is microbatch t-(n_stages-1); a
            # single dynamic-index add (fill/drain steps and non-final stages
            # contribute zeros at the clamped row)
            m_out = t - (n_stages - 1)
            outs = outs.at[jnp.clip(m_out, 0, n_micro - 1)].add(
                jnp.where((m_out >= 0) & (s == n_stages - 1), y, zero))
            return (recv_next, outs), None

        outs0 = jnp.zeros((n_micro,) + micro_x.shape[1:], micro_x.dtype)
        (_, outs), _ = jax.lax.scan(step, (zero, outs0),
                                    jnp.arange(n_steps))
        # only the last stage holds real outputs; psum replicates them to all
        return jax.lax.psum(outs, axis)

    kwargs = {}
    if "check_vma" in inspect.signature(shard_map).parameters:
        kwargs["check_vma"] = False
    elif "check_rep" in inspect.signature(shard_map).parameters:  # pragma: no cover
        kwargs["check_rep"] = False       # pre-0.7 name for the same check
    return shard_map(body, mesh=mesh, in_specs=(P(axis), P()),
                     out_specs=P(), **kwargs)
