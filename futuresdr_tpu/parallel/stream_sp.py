"""Sequence parallelism for sample streams: shard the time axis over the mesh with
halo exchange.

This is the SDR analog of ring attention / context parallelism (SURVEY §2.7 row
"Sequence parallelism"): a long frame is split into contiguous time shards, one per
device; streaming operators that need history (FIR overlap, `fir.rs:49` ``min_items``)
get their left halo from the previous device via a single ``ppermute`` over ICI, then
compute purely locally. One collective per frame, O(taps) bytes — the collective rides
ICI, not HBM.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map          # jax ≥ 0.7 stable API
except ImportError:                    # pragma: no cover
    from jax.experimental.shard_map import shard_map

__all__ = ["sp_fir", "sp_fir_fft_mag2", "sp_fir_stream", "sp_fir_fft_mag2_stream",
           "sp_channelizer", "sp_channelizer_a2a", "sp_dechirp_scan"]


def _axis_size(axis_name: str) -> int:
    """Static mapped-axis size. ``jax.lax.axis_size`` where it exists (jax ≥
    0.4.38-ish); older jax exposes the same trace-time axis env through
    ``jax.core.axis_frame``."""
    try:
        return jax.lax.axis_size(axis_name)
    except AttributeError:             # pragma: no cover - version-dependent
        return jax.core.axis_frame(axis_name)


def _halo_from_left(local: jnp.ndarray, halo: int, axis_name: str,
                    carry: jnp.ndarray = None) -> jnp.ndarray:
    """Prepend the previous shard's tail — the halo exchange.

    Shard 0's left context is ``carry`` (the previous FRAME's global tail) when given,
    zeros otherwise; so the stateful variants make sharded streaming bit-match a
    single-device streaming stage across frame boundaries (the cross-frame carry the
    reference keeps implicitly in its ring buffers, `fir.rs:49` min_items)."""
    if halo <= 0:
        return local                    # 1-tap FIR: no history needed
    n = _axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    tail = local[-halo:]
    perm = [(i, (i + 1) % n) for i in range(n)]
    left_tail = jax.lax.ppermute(tail, axis_name, perm)  # shard i gets shard i-1's tail
    fill = jnp.zeros_like(left_tail) if carry is None else carry.astype(local.dtype)
    left_tail = jnp.where(idx == 0, fill, left_tail)
    return jnp.concatenate([left_tail, local])


def _conv_valid(ext: jnp.ndarray, tj: jnp.ndarray) -> jnp.ndarray:
    """Valid-mode FIR of the halo-extended shard (complex as two real passes)."""
    if jnp.iscomplexobj(ext):
        re = jnp.convolve(ext.real, tj, mode="valid", precision="highest")
        im = jnp.convolve(ext.imag, tj, mode="valid", precision="highest")
        return re + 1j * im
    return jnp.convolve(ext, tj, mode="valid", precision="highest")


def sp_fir(taps: np.ndarray, mesh: Mesh, axis: str = "sp") -> Callable:
    """Time-sharded FIR: input [n] sharded over ``axis``; output identically sharded.

    y = conv_valid(halo ++ local) per shard == the global FIR, exactly.
    Requires local shard length ≥ len(taps)-1 (the halo must fit in one neighbour).
    """
    nt = len(taps)
    tj = jnp.asarray(np.asarray(taps))

    def local_fir(x_local):
        ext = _halo_from_left(x_local, nt - 1, axis)
        return _conv_valid(ext, tj).astype(x_local.dtype)

    return shard_map(local_fir, mesh=mesh, in_specs=P(axis), out_specs=P(axis))


def sp_fir_fft_mag2(taps: np.ndarray, fft_size: int, mesh: Mesh,
                    axis: str = "sp") -> Callable:
    """The fused north-star chain, time-sharded: FIR (halo exchange) → per-shard batched
    FFT → |x|². Local shard length must be a multiple of ``fft_size``."""
    nt = len(taps)
    tj = jnp.asarray(np.asarray(taps, dtype=np.float32))

    def local(x_local):
        ext = _halo_from_left(x_local, nt - 1, axis)
        y = _conv_valid(ext, tj)
        spec = jnp.fft.fft(y.reshape(-1, fft_size), axis=1)
        return (spec.real**2 + spec.imag**2).astype(jnp.float32).reshape(-1)

    return shard_map(local, mesh=mesh, in_specs=P(axis), out_specs=P(axis))


def _make_stream(local: Callable, nt: int, mesh: Mesh, axis: str):
    """Wrap a carry-taking local kernel into ``fn(carry, x) -> (carry, y)`` +
    ``init_carry``: the carry is the previous frame's global tail (``nt-1`` samples,
    replicated), consumed by shard 0 as left context. jit ``fn`` with
    ``donate_argnums=(0,)`` to chain carries on-device."""
    inner = shard_map(local, mesh=mesh, in_specs=(P(axis), P()), out_specs=P(axis))
    n_dev = mesh.shape[axis]

    def fn(carry, x):
        if x.shape[0] // n_dev < nt - 1:     # trace-time: clear error, not a deep
            raise ValueError(                # shard_map broadcast failure
                f"per-shard length {x.shape[0] // n_dev} < halo {nt - 1}: "
                f"grow the frame or reduce taps/devices")
        # fn is jitted by its consumers (SpKernel), so this body only runs at
        # TRACE time — mark each (re)trace in the span stream: silent retraces
        # (shape drift, carry dtype churn) are the classic sharded-pipeline
        # stall and otherwise invisible from the host
        from ..telemetry.spans import recorder
        rec = recorder()
        if rec.enabled and isinstance(x, jax.core.Tracer):
            rec.instant("jit", "sp_trace",
                        args={"frame": int(x.shape[0]),
                              "devices": int(n_dev), "halo": int(nt - 1)})
        y = inner(x, carry)
        # new carry: global frame tail (x[-0:] would be the WHOLE frame at nt=1)
        return x[x.shape[0] - (nt - 1):], y

    def init_carry(dtype):
        from jax.sharding import NamedSharding

        from ..ops.xfer import to_device
        return to_device(np.zeros(nt - 1, dtype=np.dtype(dtype)),
                         NamedSharding(mesh, P()))

    return fn, init_carry


def sp_fir_stream(taps: np.ndarray, mesh: Mesh, axis: str = "sp"):
    """Cross-frame-stateful time-sharded FIR: ``fn(carry, x) -> (carry, y)``.

    Streaming N frames through the sharded fn bit-matches the single-device streaming
    ``fir_stage`` (see :func:`_make_stream` for the carry contract)."""
    nt = len(taps)
    tj = jnp.asarray(np.asarray(taps))

    def local_fir(x_local, carry):
        ext = _halo_from_left(x_local, nt - 1, axis, carry)
        return _conv_valid(ext, tj).astype(x_local.dtype)

    return _make_stream(local_fir, nt, mesh, axis)


def sp_fir_fft_mag2_stream(taps: np.ndarray, fft_size: int, mesh: Mesh,
                           axis: str = "sp"):
    """Cross-frame-stateful fused north-star chain (see :func:`sp_fir_stream`):
    FIR with frame-carry halo → per-shard batched FFT → |x|²."""
    nt = len(taps)
    tj = jnp.asarray(np.asarray(taps, dtype=np.float32))

    def local(x_local, carry):
        ext = _halo_from_left(x_local, nt - 1, axis, carry)
        y = _conv_valid(ext, tj)
        spec = jnp.fft.fft(y.reshape(-1, fft_size), axis=1)
        return (spec.real**2 + spec.imag**2).astype(jnp.float32).reshape(-1)

    return _make_stream(local, nt, mesh, axis)


def sp_channelizer(n_channels: int, taps: np.ndarray, mesh: Mesh,
                   axis: str = "sp") -> Callable:
    """Critically-sampled PFB channelizer, time-sharded: input [n] complex sharded over
    ``axis`` (n/shards must be a multiple of n_channels); output [n_channels, n/N] with
    the channel axis replicated and time sharded.

    Each branch filter needs K-1 blocks of history → halo = (K-1)·N input samples from
    the left neighbour; the IFFT across channels is purely local. This is the reference's
    ``PfbChannelizer`` (`pfb/channelizer.rs`) scaled across chips.
    """
    N = n_channels
    taps = np.asarray(taps, dtype=np.float32)
    K = -(-len(taps) // N)
    padded = np.zeros(K * N, dtype=np.float32)
    padded[:len(taps)] = taps
    branch = jnp.asarray(padded.reshape(K, N).T)          # [N, K]

    def local(x_local):
        halo = (K - 1) * N
        ext = _halo_from_left(x_local, halo, axis)        # [(S + K-1)·N]
        blocks = ext.reshape(-1, N)[:, ::-1].T            # [N, S + K-1] commutated
        # batched branch FIR via valid correlation against each branch's taps
        def one_branch(u, h):
            return jnp.convolve(u, h[::-1], mode="valid", precision="highest")
        v = jax.vmap(one_branch)(blocks, branch)          # [N, S]
        return (jnp.fft.ifft(v, axis=0) * N).astype(jnp.complex64)

    return shard_map(local, mesh=mesh, in_specs=P(axis),
                     out_specs=P(None, axis))


def _halo_from_right(local: jnp.ndarray, halo: int, axis_name: str) -> jnp.ndarray:
    """Append the NEXT shard's head — the mirror of :func:`_halo_from_left`, for
    operators whose windows extend rightward past the shard boundary. The last
    shard pads with zeros (stream edge)."""
    if halo <= 0:
        return local
    n = _axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    head = local[:halo]
    perm = [(i, (i - 1) % n) for i in range(n)]
    right_head = jax.lax.ppermute(head, axis_name, perm)  # shard i gets i+1's head
    right_head = jnp.where(idx == n - 1, jnp.zeros_like(right_head), right_head)
    return jnp.concatenate([local, right_head])


def sp_dechirp_scan(sf: int, mesh: Mesh, hop: int = None, axis: str = "sp"):
    """LoRa preamble-scan primitive, time-sharded: dechirp every ``hop``-spaced
    window of a long capture and return each window's peak FFT bin and energy
    concentration — the hot loop of `frame_sync.rs` (and this framework's
    ``detect_frames``) scaled across chips.

    Input [n] complex64 sharded over ``axis`` (per-shard length must be a
    multiple of ``hop``); windows anchored near a shard's end extend into the
    next shard, so each device fetches a window-length right halo with one
    ``ppermute`` — O(2^sf) bytes over ICI per frame — then computes purely
    locally. Output: (bins [n/hop], conc [n/hop]), identically time-sharded.
    Windows whose span crosses the stream end are reported from zero-padding
    (conc ≈ 0), matching how the host scan bounds its probe count.
    """
    n = 1 << sf
    hop = hop or n // 4
    if n % hop != 0:
        raise ValueError(f"window length {n} must be a multiple of hop {hop}")
    from ..models.lora.phy import _downchirp     # the host scan's exact chirp
    down = jnp.asarray(_downchirp(n).astype(np.complex64))

    def local(x_local):
        if x_local.shape[0] < n:                 # trace-time: a truncated halo
            raise ValueError(                    # would silently garble windows
                f"per-shard length {x_local.shape[0]} < window {n}: "
                f"grow the capture or reduce sf/devices")
        if x_local.shape[0] % hop:               # trace-time: a non-multiple would
            raise ValueError(                    # drop scan windows at shard seams
                f"per-shard length {x_local.shape[0]} must be a multiple of "
                f"hop {hop}")
        ext = _halo_from_right(x_local, n, axis)
        idx = jnp.arange(x_local.shape[0] // hop)[:, None] * hop + jnp.arange(n)
        spec = jnp.fft.fft(ext[idx] * down[None, :], axis=1)
        pw = spec.real ** 2 + spec.imag ** 2     # |X|^2: argmax and conc need no sqrt
        peak = jnp.argmax(pw, axis=1)
        p2 = jnp.take_along_axis(pw, peak[:, None], axis=1)[:, 0]
        conc = p2 / jnp.maximum(jnp.sum(pw, axis=1), 1e-12)
        return peak.astype(jnp.int32), conc.astype(jnp.float32)

    return shard_map(local, mesh=mesh, in_specs=P(axis),
                     out_specs=(P(axis), P(axis)))


def sp_channelizer_a2a(n_channels: int, taps: np.ndarray, mesh: Mesh,
                       axis: str = "sp") -> Callable:
    """All-to-all (Ulysses-style) sequence parallelism for the channelizer: input is
    time-sharded; each device channelizes its own time shard locally (halo from the left
    neighbour), then one ``all_to_all`` over ICI re-shards from time-split to
    CHANNEL-split — output [n_channels/n_dev local channels, full time] per device,
    i.e. [n_channels, n/N] sharded over the channel axis.

    Complements :func:`sp_channelizer` (which keeps time sharding): choose a2a when the
    downstream consumer is per-channel (demodulators, per-channel decoders), so each
    device owns whole channels and no further collectives are needed.
    """
    N = n_channels
    n_dev = mesh.shape[axis]
    assert N % n_dev == 0, "n_channels must divide the mesh axis"
    taps = np.asarray(taps, dtype=np.float32)
    K = -(-len(taps) // N)
    padded = np.zeros(K * N, dtype=np.float32)
    padded[:len(taps)] = taps
    branch = jnp.asarray(padded.reshape(K, N).T)          # [N, K]

    def local(x_local):
        halo = (K - 1) * N
        ext = _halo_from_left(x_local, halo, axis)
        blocks = ext.reshape(-1, N)[:, ::-1].T            # [N, S + K-1]

        def one_branch(u, h):
            return jnp.convolve(u, h[::-1], mode="valid", precision="highest")

        v = jax.vmap(one_branch)(blocks, branch)          # [N, S_local]
        y = (jnp.fft.ifft(v, axis=0) * N).astype(jnp.complex64)
        # re-shard: split channel axis into n_dev groups, swap with the time axis
        y = y.reshape(n_dev, N // n_dev, -1)              # [n_dev, N/n_dev, S_local]
        g = jax.lax.all_to_all(y, axis, split_axis=0, concat_axis=1, tiled=False)
        # g: [N/n_dev, n_dev, S_local] — device-major time; flatten to full time
        return g.reshape(N // n_dev, -1)

    return shard_map(local, mesh=mesh, in_specs=P(axis),
                     out_specs=P(axis, None))
