"""Sequence parallelism for sample streams: shard the time axis over the mesh with
halo exchange.

This is the SDR analog of ring attention / context parallelism (SURVEY §2.7 row
"Sequence parallelism"): a long frame is split into contiguous time shards, one per
device; streaming operators that need history (FIR overlap, `fir.rs:49` ``min_items``)
get their left halo from the previous device via a single ``ppermute`` over ICI, then
compute purely locally. One collective per frame, O(taps) bytes — the collective rides
ICI, not HBM.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map          # jax ≥ 0.7 stable API
except ImportError:                    # pragma: no cover
    from jax.experimental.shard_map import shard_map

__all__ = ["sp_fir", "sp_fir_fft_mag2", "sp_channelizer", "sp_channelizer_a2a"]


def _halo_from_left(local: jnp.ndarray, halo: int, axis_name: str) -> jnp.ndarray:
    """Prepend the previous shard's tail (zeros on shard 0) — the halo exchange."""
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    tail = local[-halo:]
    perm = [(i, (i + 1) % n) for i in range(n)]
    left_tail = jax.lax.ppermute(tail, axis_name, perm)  # shard i gets shard i-1's tail
    left_tail = jnp.where(idx == 0, jnp.zeros_like(left_tail), left_tail)
    return jnp.concatenate([left_tail, local])


def sp_fir(taps: np.ndarray, mesh: Mesh, axis: str = "sp") -> Callable:
    """Time-sharded FIR: input [n] sharded over ``axis``; output identically sharded.

    y = conv_valid(halo ++ local) per shard == the global FIR, exactly.
    """
    nt = len(taps)
    H = jnp.asarray(taps[::-1])  # correlation kernel

    def local_fir(x_local):
        ext = _halo_from_left(x_local, nt - 1, axis)
        if jnp.iscomplexobj(ext):
            re = jnp.convolve(ext.real, jnp.asarray(taps), mode="valid", precision="highest")
            im = jnp.convolve(ext.imag, jnp.asarray(taps), mode="valid", precision="highest")
            return (re + 1j * im).astype(x_local.dtype)
        return jnp.convolve(ext, jnp.asarray(taps), mode="valid",
                            precision="highest").astype(x_local.dtype)

    return shard_map(local_fir, mesh=mesh, in_specs=P(axis), out_specs=P(axis))


def sp_fir_fft_mag2(taps: np.ndarray, fft_size: int, mesh: Mesh,
                    axis: str = "sp") -> Callable:
    """The fused north-star chain, time-sharded: FIR (halo exchange) → per-shard batched
    FFT → |x|². Local shard length must be a multiple of ``fft_size``."""
    nt = len(taps)
    tj = jnp.asarray(np.asarray(taps, dtype=np.float32))

    def local(x_local):
        ext = _halo_from_left(x_local, nt - 1, axis)
        if jnp.iscomplexobj(ext):
            y = (jnp.convolve(ext.real, tj, mode="valid", precision="highest")
                 + 1j * jnp.convolve(ext.imag, tj, mode="valid", precision="highest"))
        else:
            y = jnp.convolve(ext, tj, mode="valid", precision="highest")
        spec = jnp.fft.fft(y.reshape(-1, fft_size), axis=1)
        return (spec.real**2 + spec.imag**2).astype(jnp.float32).reshape(-1)

    return shard_map(local, mesh=mesh, in_specs=P(axis), out_specs=P(axis))


def sp_channelizer(n_channels: int, taps: np.ndarray, mesh: Mesh,
                   axis: str = "sp") -> Callable:
    """Critically-sampled PFB channelizer, time-sharded: input [n] complex sharded over
    ``axis`` (n/shards must be a multiple of n_channels); output [n_channels, n/N] with
    the channel axis replicated and time sharded.

    Each branch filter needs K-1 blocks of history → halo = (K-1)·N input samples from
    the left neighbour; the IFFT across channels is purely local. This is the reference's
    ``PfbChannelizer`` (`pfb/channelizer.rs`) scaled across chips.
    """
    N = n_channels
    taps = np.asarray(taps, dtype=np.float32)
    K = -(-len(taps) // N)
    padded = np.zeros(K * N, dtype=np.float32)
    padded[:len(taps)] = taps
    branch = jnp.asarray(padded.reshape(K, N).T)          # [N, K]

    def local(x_local):
        halo = (K - 1) * N
        ext = _halo_from_left(x_local, halo, axis)        # [(S + K-1)·N]
        blocks = ext.reshape(-1, N)[:, ::-1].T            # [N, S + K-1] commutated
        # batched branch FIR via valid correlation against each branch's taps
        def one_branch(u, h):
            return jnp.convolve(u, h[::-1], mode="valid", precision="highest")
        v = jax.vmap(one_branch)(blocks, branch)          # [N, S]
        return (jnp.fft.ifft(v, axis=0) * N).astype(jnp.complex64)

    return shard_map(local, mesh=mesh, in_specs=P(axis),
                     out_specs=P(None, axis))


def sp_channelizer_a2a(n_channels: int, taps: np.ndarray, mesh: Mesh,
                       axis: str = "sp") -> Callable:
    """All-to-all (Ulysses-style) sequence parallelism for the channelizer: input is
    time-sharded; each device channelizes its own time shard locally (halo from the left
    neighbour), then one ``all_to_all`` over ICI re-shards from time-split to
    CHANNEL-split — output [n_channels/n_dev local channels, full time] per device,
    i.e. [n_channels, n/N] sharded over the channel axis.

    Complements :func:`sp_channelizer` (which keeps time sharding): choose a2a when the
    downstream consumer is per-channel (demodulators, per-channel decoders), so each
    device owns whole channels and no further collectives are needed.
    """
    N = n_channels
    n_dev = mesh.shape[axis]
    assert N % n_dev == 0, "n_channels must divide the mesh axis"
    taps = np.asarray(taps, dtype=np.float32)
    K = -(-len(taps) // N)
    padded = np.zeros(K * N, dtype=np.float32)
    padded[:len(taps)] = taps
    branch = jnp.asarray(padded.reshape(K, N).T)          # [N, K]

    def local(x_local):
        halo = (K - 1) * N
        ext = _halo_from_left(x_local, halo, axis)
        blocks = ext.reshape(-1, N)[:, ::-1].T            # [N, S + K-1]

        def one_branch(u, h):
            return jnp.convolve(u, h[::-1], mode="valid", precision="highest")

        v = jax.vmap(one_branch)(blocks, branch)          # [N, S_local]
        y = (jnp.fft.ifft(v, axis=0) * N).astype(jnp.complex64)
        # re-shard: split channel axis into n_dev groups, swap with the time axis
        y = y.reshape(n_dev, N // n_dev, -1)              # [n_dev, N/n_dev, S_local]
        g = jax.lax.all_to_all(y, axis, split_axis=0, concat_axis=1, tiled=False)
        # g: [N/n_dev, n_dev, S_local] — device-major time; flatten to full time
        return g.reshape(N // n_dev, -1)

    return shard_map(local, mesh=mesh, in_specs=P(axis),
                     out_specs=P(axis, None))
