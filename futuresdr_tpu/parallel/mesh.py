"""Device mesh helpers for multi-chip scaling.

The reference is single-process shared-memory (SURVEY §2.7); its scale-out story is
transport blocks between hosts. The TPU-native scale-out is SPMD over an ICI mesh:
``jax.sharding.Mesh`` + shardings, XLA inserting the collectives.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["make_mesh", "factor_devices", "shard_params", "P", "NamedSharding"]


def factor_devices(n: int, n_axes: int = 2) -> Tuple[int, ...]:
    """Factor n devices into a near-balanced axis tuple (largest factors first)."""
    dims = [1] * n_axes
    rem = n
    # peel off prime factors, assigning each to the currently-smallest axis
    f = 2
    factors = []
    while rem > 1 and f * f <= rem:
        while rem % f == 0:
            factors.append(f)
            rem //= f
        f += 1
    if rem > 1:
        factors.append(rem)
    for f in sorted(factors, reverse=True):
        i = int(np.argmin(dims))
        dims[i] *= f
    return tuple(sorted(dims, reverse=True))


def make_mesh(axis_names: Sequence[str], shape: Optional[Sequence[int]] = None,
              devices=None) -> Mesh:
    """Mesh over all (or given) devices; shape auto-factored when omitted."""
    devices = list(devices if devices is not None else jax.devices())
    if shape is None:
        shape = factor_devices(len(devices), len(axis_names))
    arr = np.array(devices[:int(np.prod(shape))]).reshape(tuple(shape))
    return Mesh(arr, tuple(axis_names))


def shard_params(params, mesh: Mesh, axis: str = "mp"):
    """FSDP-style weight sharding: for each parameter leaf, shard its largest
    evenly-divisible axis over ``axis``; replicate the rest.

    Returns (sharded_params, shardings_pytree) — pass the shardings as jit
    in_shardings/out_shardings so the train step runs fully SPMD.
    """
    n = mesh.shape[axis]

    def spec_for(leaf):
        if not hasattr(leaf, "shape") or leaf.ndim == 0:
            return P()
        sizes = list(leaf.shape)
        order = np.argsort(sizes)[::-1]
        for ax in order:
            if sizes[ax] % n == 0 and sizes[ax] >= n:
                spec = [None] * leaf.ndim
                spec[ax] = axis
                return P(*spec)
        return P()

    specs = jax.tree_util.tree_map(spec_for, params)
    shardings = jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s), specs)
    sharded = jax.device_put(params, shardings)
    return sharded, shardings
