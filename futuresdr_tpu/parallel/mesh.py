"""Device mesh helpers for multi-chip scaling.

The reference is single-process shared-memory (SURVEY §2.7); its scale-out story is
transport blocks between hosts. The TPU-native scale-out is SPMD over an ICI mesh:
``jax.sharding.Mesh`` + shardings, XLA inserting the collectives.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["make_mesh", "factor_devices", "shard_params", "P", "NamedSharding"]


def factor_devices(n: int, n_axes: int = 2) -> Tuple[int, ...]:
    """Factor n devices into a near-balanced axis tuple (largest axes first).

    The product ALWAYS equals ``n`` and the tuple always has ``n_axes``
    entries — prime counts on deep meshes land the whole prime on one axis
    with 1s elsewhere (``factor_devices(7, 3) == (7, 1, 1)``), never a
    truncated or padded factorization. Degenerate inputs are refused
    loudly instead of returning a shape whose product is wrong."""
    n, n_axes = int(n), int(n_axes)
    if n < 1:
        raise ValueError(f"cannot factor {n} devices (need >= 1)")
    if n_axes < 1:
        raise ValueError(f"need >= 1 mesh axis, got {n_axes}")
    dims = [1] * n_axes
    rem = n
    # peel off prime factors, assigning each to the currently-smallest axis
    f = 2
    factors = []
    while rem > 1 and f * f <= rem:
        while rem % f == 0:
            factors.append(f)
            rem //= f
        f += 1
    if rem > 1:
        factors.append(rem)
    for f in sorted(factors, reverse=True):
        i = int(np.argmin(dims))
        dims[i] *= f
    assert int(np.prod(dims)) == n, (n, n_axes, dims)
    return tuple(sorted(dims, reverse=True))


def make_mesh(axis_names: Sequence[str], shape: Optional[Sequence[int]] = None,
              devices=None) -> Mesh:
    """Mesh over all (or given) devices; shape auto-factored when omitted.

    A ``shape`` needing MORE devices than exist is refused with a clear
    error (previously a cryptic numpy reshape failure): a silently
    truncated or short mesh would change the program's sharding semantics.
    A shape covering FEWER devices than exist stays valid — an explicit
    sub-mesh (e.g. a 1-device reference mesh next to the full one) is a
    deliberate, documented pattern (``__graft_entry__.dryrun_multichip``).
    """
    devices = list(devices if devices is not None else jax.devices())
    if shape is None:
        shape = factor_devices(len(devices), len(axis_names))
    shape = tuple(int(s) for s in shape)
    if len(shape) != len(axis_names):
        raise ValueError(
            f"mesh shape {shape} has {len(shape)} axes but "
            f"{len(axis_names)} axis names {tuple(axis_names)}")
    need = int(np.prod(shape))
    if need > len(devices):
        raise ValueError(
            f"mesh shape {shape} needs {need} devices but only "
            f"{len(devices)} exist — refusing to build a short mesh "
            f"(shrink the shape or grow the slice)")
    arr = np.array(devices[:need]).reshape(shape)
    return Mesh(arr, tuple(axis_names))


def shard_params(params, mesh: Mesh, axis: str = "mp"):
    """FSDP-style weight sharding: for each parameter leaf, shard its largest
    evenly-divisible axis over ``axis``; replicate the rest.

    Returns (sharded_params, shardings_pytree) — pass the shardings as jit
    in_shardings/out_shardings so the train step runs fully SPMD.
    """
    n = mesh.shape[axis]

    def spec_for(leaf):
        if not hasattr(leaf, "shape") or leaf.ndim == 0:
            return P()
        sizes = list(leaf.shape)
        order = np.argsort(sizes)[::-1]
        for ax in order:
            if sizes[ax] % n == 0 and sizes[ax] >= n:
                spec = [None] * leaf.ndim
                spec[ax] = axis
                return P(*spec)
        return P()

    specs = jax.tree_util.tree_map(spec_for, params)
    shardings = jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s), specs)
    sharded = jax.device_put(params, shardings)
    return sharded, shardings
