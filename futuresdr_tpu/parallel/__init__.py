"""Multi-chip parallelism: meshes, sharded stream ops, sharded training.

TPU-native replacement for the reference's distribution story (SURVEY §2.7): where the
reference spreads block tasks over cores and crosses hosts with ZMQ/TCP blocks, this layer
scales single logical operators over the ICI mesh — time-sharded streams with halo
exchange (sequence parallelism), channel-sharded filterbanks, and dp/fsdp-sharded model
training for the in-flowgraph ML path.
"""

from .mesh import make_mesh, factor_devices, shard_params, P, NamedSharding
from .stream_sp import (sp_fir, sp_fir_fft_mag2, sp_fir_stream,
                        sp_fir_fft_mag2_stream, sp_channelizer, sp_channelizer_a2a,
                        sp_dechirp_scan)
from .pipeline_pp import make_pp_pipeline
from . import multihost

__all__ = ["make_mesh", "factor_devices", "shard_params", "P", "NamedSharding",
           "sp_fir", "sp_fir_fft_mag2", "sp_fir_stream", "sp_fir_fft_mag2_stream",
           "sp_channelizer", "sp_channelizer_a2a", "sp_dechirp_scan",
           "make_pp_pipeline", "multihost"]
