"""Honest device-throughput measurement for streaming stages.

Async-dispatch timing loops lie on this dev environment's tunneled TPU (and can mislead
on any async backend): `block_until_ready` has been observed returning before queued
work drains, and the ~100 ms dispatch/readback latency swamps sub-second kernels. See
docs/tpu_notes.md "Measuring through the tunnel".

:func:`run_marginal` implements the corrected methodology used by ``bench.py`` and
``perf/fir.py``:

- the frame loop rides INSIDE the jitted program via ``lax.scan`` — one dispatch runs
  K frames with the stage carry chained;
- a checksum accumulates in the scan carry and is fed back into each iteration's input,
  creating a sequential data dependence so XLA cannot hoist the (otherwise
  loop-invariant) body out of the loop;
- the checksum readback happens inside the timed region and is validated finite;
- the reported rate is the **marginal** rate between the two K values, cancelling the
  constant dispatch latency.
"""
from __future__ import annotations

import time
from typing import Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.xfer import to_host

__all__ = ["run_marginal", "run_marginal_retry", "default_k_pair",
           "scaled_k_pair"]


def run_marginal(step: Callable, carry0, x, k_pair: Tuple[int, int] = (512, 1024),
                 reps: int = 4) -> float:
    """Measure sustained samples/s of ``step(carry, x) -> (carry, y)`` on x's device.

    ``x`` may be any shape; the rate is ``x.size`` samples per step invocation.
    Returns samples/second (marginal between the two scan lengths). Raises
    RuntimeError if timing noise makes the marginal ill-conditioned (k_hi run not
    measurably longer than k_lo run) — callers should retry rather than report it.
    (Real raises, not asserts: under ``python -O`` an assert-based rail would
    silently report garbage — the exact failure mode this module exists to prevent.)
    """
    k_lo, k_hi = k_pair
    if k_hi <= k_lo:
        raise ValueError(f"k_pair must be increasing, got {k_pair}")

    def make(k):
        @jax.jit
        def run_k(carry, xin):
            def body(c, _):
                stage_c, acc = c
                xi = xin * (1 + 1e-20 * acc.astype(xin.dtype))
                stage_c, y = step(stage_c, xi)
                return (stage_c, acc + jnp.sum(y).real.astype(jnp.float32)), None
            (carry, acc), _ = jax.lax.scan(body, (carry, jnp.float32(0)), None,
                                           length=k)
            return carry, acc
        return run_k

    times = {}
    for k in (k_lo, k_hi):
        run_k = make(k)
        _, acc = run_k(carry0, x)
        warm = float(to_host(acc))                    # compile + warm + validate
        if not np.isfinite(warm):
            raise RuntimeError(f"non-finite warmup checksum {warm} at K={k}")
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            _, acc = run_k(carry0, x)
            checksum = float(to_host(acc))            # sync inside the timed region
            best = min(best, time.perf_counter() - t0)
        if not np.isfinite(checksum):
            raise RuntimeError(f"non-finite checksum {checksum} at K={k}")
        times[k] = best
    if times[k_hi] <= times[k_lo]:
        raise RuntimeError(
            f"marginal ill-conditioned: K={k_hi} ran in {times[k_hi]:.3f}s vs "
            f"K={k_lo} in {times[k_lo]:.3f}s — timing noise exceeds the workload; "
            f"increase k_pair or frame size")
    return (k_hi - k_lo) * int(np.prod(np.shape(x))) / (times[k_hi] - times[k_lo])


def default_k_pair(platform: str) -> Tuple[int, int]:
    """Scan-length pair for the marginal methodology: hundreds of frames per scan
    amortize the tunnel's ~100 ms dispatch latency on TPU; the CPU backend
    dispatches in µs, so short scans keep fallback runs fast. THE single source of
    these constants — bench.py and every perf/ harness route through here."""
    return (512, 1024) if platform == "tpu" else (8, 16)


def scaled_k_pair(k_pair: Tuple[int, int], frame_items: int, platform: str,
                  min_lo_items: int = None) -> Tuple[int, int]:
    """Grow a scan pair so ONE ``k_lo`` scan covers a worthwhile timed window.

    Small frames make sub-ms scans where scheduler noise dominates the
    marginal (r4: lora_msps 58–182 across rounds on the CPU backend); behind
    an accelerator dispatch path, per-RPC jitter (tens of ms through the
    tunnel) swamps a tens-of-ms scan delta the same way (r5:
    ``lora_msps_runs`` spread ±80%, ``wlan`` run 1 a cold outlier). Scale the
    pair so the k_lo scan covers ≥2M samples on the CPU backend and ≥512M on
    accelerators (≈0.2 s of compute at the measured ~2.9 Gsps chain rate —
    the k_hi−k_lo delta then dwarfs per-dispatch jitter). THE shared window
    discipline of bench.py / perf/lora.py / perf/wlan.py."""
    if min_lo_items is None:
        min_lo_items = 2_000_000 if platform == "cpu" else 512_000_000
    scale = max(1, -(-min_lo_items // (k_pair[0] * max(1, frame_items))))
    return (k_pair[0] * scale, k_pair[1] * scale)


def run_marginal_retry(step: Callable, carry0, x,
                       k_pair: Tuple[int, int] = (512, 1024),
                       attempts: int = 3, grow: int = 2) -> float:
    """:func:`run_marginal` with the retry its error contract asks callers for:
    on an ill-conditioned marginal, double the scan lengths (more work per timing
    window conditions the difference) and try again, up to ``attempts`` total."""
    last = None
    for _ in range(attempts):
        try:
            return run_marginal(step, carry0, x, k_pair)
        except RuntimeError as e:
            last = e
            k_pair = (k_pair[0] * grow, k_pair[1] * grow)
    raise last
