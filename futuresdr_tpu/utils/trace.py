"""Latency tracepoints: timestamped probes through the stream plane.

Re-design of the reference perf harness's LTTng tracepoint blocks
(``perf/perf/src/lttng_sink.rs:1-60``, used by ``perf/null_rand_latency``): a
``LatencyProbeSource`` stamps wall-clock tags every ``granularity`` items; a matching
``LatencyProbeSink`` records (index, send_ts, recv_ts) so per-sample pipeline latency can
be analyzed without external tracers.
"""

from __future__ import annotations

import time
from typing import List, Tuple

import numpy as np

from ..runtime.kernel import Kernel
from ..runtime.tag import Tag, filter_tags
from ..telemetry.doctor import E2E_LATENCY as _E2E_LATENCY

__all__ = ["LatencyProbeSource", "LatencyProbeSink", "latency_stats"]

_TAG_NAME = "latency_probe_ts"


class LatencyProbeSource(Kernel):
    """Pass-through that attaches a timestamp tag every ``granularity`` items."""

    def __init__(self, dtype, granularity: int = 32768):
        super().__init__()
        self.input = self.add_stream_input("in", dtype)
        self.output = self.add_stream_output("out", dtype)
        self.granularity = granularity
        self._next = 0          # absolute index of the next probe
        self._abs = 0

    async def work(self, io, mio, meta):
        inp = self.input.slice()
        out = self.output.slice()
        n = min(len(inp), len(out))
        if n > 0:
            out[:n] = inp[:n]
            while self._next < self._abs + n:
                self.output.add_tag(self._next - self._abs,
                                    Tag.named_f32(_TAG_NAME, time.perf_counter()))
                self._next += self.granularity
            self._abs += n
            self.input.consume(n)
            self.output.produce(n)
        if self.input.finished() and n == len(inp):
            io.finished = True
        elif n > 0 and n < len(inp):
            io.call_again = True


class LatencyProbeSink(Kernel):
    """Terminal consumer recording probe-tag arrival latencies."""

    def __init__(self, dtype):
        super().__init__()
        self.input = self.add_stream_input("in", dtype)
        self.records: List[Tuple[int, float, float]] = []   # (abs_index, sent, seen)
        self._abs = 0
        # every probe latency also feeds the doctor's e2e histogram
        # (telemetry/doctor.py), so `GET /metrics` and flight records carry
        # stream-plane percentiles without the raw records leaving the sink
        self._hist = _E2E_LATENCY.labels(source="latency_probe")

    async def work(self, io, mio, meta):
        inp = self.input.slice()
        n = len(inp)
        if n:
            now = time.perf_counter()
            for t in filter_tags(self.input.tags(), n):
                if t.tag.name == _TAG_NAME:
                    self.records.append((self._abs + t.index, t.tag.value, now))
                    self._hist.observe(max(0.0, now - t.tag.value))
            self._abs += n
            self.input.consume(n)
        if self.input.finished():
            io.finished = True


def latency_stats(records) -> dict:
    """Exact percentiles over raw probe records (p50/p95/p99 — the
    ``perf/latency.py`` CSV columns); the log2-bucket estimates of the same
    latencies live in the always-on ``fsdr_e2e_latency_seconds`` histogram."""
    if not records:
        return {"count": 0}
    lat = np.array([seen - sent for _, sent, seen in records])
    return {
        "count": len(lat),
        "mean_us": float(lat.mean() * 1e6),
        "p50_us": float(np.percentile(lat, 50) * 1e6),
        "p95_us": float(np.percentile(lat, 95) * 1e6),
        "p99_us": float(np.percentile(lat, 99) * 1e6),
        "max_us": float(lat.max() * 1e6),
    }
