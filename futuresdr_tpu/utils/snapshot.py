"""Shared disk-snapshot helpers (docs/robustness.md "Persisting checkpoints").

One implementation of the durable-state disk contract used by BOTH the
kernel checkpoint persistence (``tpu/kernel_block.py``, config
``checkpoint_dir``) and the serving plane's per-session carry store
(``serve/persist.py``, config ``serve_persist_dir``):

* **atomic rename** — a reader sees the old or the new snapshot, never a
  torn one (``os.replace`` of a pid-suffixed temp file);
* **CRC integrity** — a crc32 over every leaf's bytes is stored alongside
  and re-checked on load; a corrupted file reads as "absent", it never
  restores garbage;
* **signature-keyed filenames** — :func:`snapshot_signature` hashes the
  owning name together with the pipeline signature (stage names + input
  dtype), so a REUSED name over a DIFFERENT pipeline maps to a different
  file and can never restore a mismatched carry (the key-collision rule
  pinned by ``tests/test_arena.py::test_checkpoint_dir_key_collisions``);
* **optional metadata** — a small JSON dict (session id, tenant, frame
  cursors) rides next to the leaves for stores that need more than a
  sequence number;
* **one serialized writer** — :func:`persist_executor` is the process-wide
  single-worker pool every snapshot write/purge rides, so writes land
  newest-last and a purge queued after pending writes wins.

Writes are best-effort by contract: a failed write only narrows the
restore window, it must never fail the caller's hot path.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..log import logger

__all__ = [
    "snapshot_signature", "sanitize_name", "snapshot_crc",
    "write_snapshot", "read_snapshot", "persist_executor",
]

log = logger("utils.snapshot")

_persist_pool = None
_persist_pool_lock = threading.Lock()


def persist_executor():
    """The ONE-worker persistence executor (strictly serialized FIFO): every
    disk snapshot write and purge in the process rides it, off the caller's
    dispatch/drain/step thread."""
    global _persist_pool
    if _persist_pool is None:
        with _persist_pool_lock:
            if _persist_pool is None:
                from concurrent.futures import ThreadPoolExecutor
                _persist_pool = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="fsdr-codec-persist")
    return _persist_pool


def sanitize_name(name: str) -> str:
    """A filesystem-safe rendering of an instance/session name."""
    return "".join(c if c.isalnum() or c in "-_." else "_" for c in str(name))


def snapshot_signature(pipeline, name: str) -> str:
    """Ten hex chars keying ``name`` + the pipeline signature (stage names +
    input dtype): a restarted process with the same flowgraph maps to the
    same file, and a DIFFERENT pipeline under a reused name can never read
    the other's snapshot — the integrity check would reject it anyway, the
    signature keeps unrelated snapshots from colliding at all."""
    stages = getattr(pipeline, "stages", ())
    sig = "|".join(str(getattr(s, "name", "?")) for s in stages) \
        or type(pipeline).__name__
    return hashlib.sha1(
        f"{name}|{sig}|{np.dtype(pipeline.in_dtype)}".encode()
    ).hexdigest()[:10]


def snapshot_crc(leaves) -> int:
    crc = 0
    for l in leaves:
        a = np.ascontiguousarray(np.asarray(l))
        crc = zlib.crc32(a.tobytes(), crc)
    return crc & 0xFFFFFFFF


def write_snapshot(path: str, seq: int, leaves,
                   meta: Optional[Dict[str, Any]] = None) -> bool:
    """Serialize one snapshot at ``path``: atomic rename, CRC-stamped,
    optional JSON ``meta``. Returns False (logged) on any failure — a lost
    write narrows the restore window, it never raises into the caller."""
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.{os.getpid()}.tmp"
        lv = [np.asarray(l) for l in leaves]
        arrs = {f"leaf{i}": a for i, a in enumerate(lv)}
        crc_over = list(lv)
        if meta:
            arrs["_meta"] = np.frombuffer(
                json.dumps(meta).encode(), dtype=np.uint8).copy()
            # the metadata (session id, frame cursors) is restore-critical
            # state too: it rides the SAME integrity check as the leaves —
            # a digit flip in a persisted frame cursor must read as
            # "corrupted file, skipped", never as a silently shifted resume
            crc_over.append(arrs["_meta"])
        with open(tmp, "wb") as f:
            np.savez(f, _seq=np.int64(seq), _n=np.int64(len(lv)),
                     _crc=np.uint32(snapshot_crc(crc_over)), **arrs)
        os.replace(tmp, path)
        return True
    except Exception as e:                             # noqa: BLE001
        log.warning("snapshot persist %s @%d failed (%r)", path, seq, e)
        return False


def read_snapshot(path: str
                  ) -> Optional[Tuple[int, List[np.ndarray],
                                      Optional[Dict[str, Any]]]]:
    """``(seq, leaves, meta)`` of a persisted snapshot, or None when absent,
    unreadable, or failing the CRC — a corrupted file is logged and ignored
    (the caller falls through to its fresh-init path)."""
    if not path or not os.path.exists(path):
        return None
    try:
        with np.load(path) as z:
            n = int(z["_n"])
            seq = int(z["_seq"])
            crc = int(z["_crc"])
            leaves = [z[f"leaf{i}"] for i in range(n)]
            meta = None
            crc_over = list(leaves)
            if "_meta" in z.files:
                meta_arr = z["_meta"]
                crc_over.append(meta_arr)      # meta rides the CRC (write side)
                meta = json.loads(bytes(meta_arr.tobytes()).decode())
        if crc != snapshot_crc(crc_over):
            log.warning("persisted snapshot %s failed its integrity "
                        "check — ignored", path)
            return None
        return seq, leaves, meta
    except Exception as e:                             # noqa: BLE001
        log.warning("persisted snapshot %s unreadable (%r) — ignored",
                    path, e)
        return None
