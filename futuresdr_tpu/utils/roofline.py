"""Roofline accounting for fused stage pipelines — XLA's own cost model, not hand
math.

VERDICT r3 item 7: a bare "2,944 Msps" is not auditable; ops/sample and
bytes/sample turn it into an efficiency claim. The numbers come from the
compiled program's ``cost_analysis()`` (XLA's flop/byte counts for exactly the
HLO that runs), so they track fusion decisions instead of a paper formula.
Caveat: the analysis is per-backend — a CPU-compiled pipeline fuses differently
than the TPU one, so artifacts must carry the backend they were derived on.

Peaks: :func:`detect_peaks` resolves the denominator for MFU/HBM-utilization
claims in three layers — explicit config overrides (``peak_flops`` in FLOP/s,
``peak_hbm_gbps`` in GB/s), then the LIVE chip kind from
``jax.devices()[0].device_kind`` against the public per-chip spec table
(:data:`CHIP_PEAKS`, bf16 matmul peaks — the standard MFU convention; there is
no official f32 peak, f32 matmuls lower to multiple bf16 passes so f32 chains
simply show proportionally lower MFU), and finally the historical
backend-label mapping (:data:`PEAKS` — "tpu"/"axon" are the tunnel's v5 lite
chip) for callers naming a backend without a live device to interrogate. An
UNKNOWN live accelerator returns None: flops/bytes-only output, never an MFU
against the wrong denominator.

Cost records are cached **by program signature** (:data:`_cost_cache`):
``cost_of`` pays its AOT ``jax.jit(fn).lower().compile()`` once per signature
per process, so bench roofline accounting and the profile plane's program
registration (``telemetry/profile.py``) stop double-compiling programs the
pipeline's own jit cache already holds.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

__all__ = ["cost_of", "pipeline_roofline", "graph_roofline", "program_cost",
           "detect_peaks", "dtype_peak_flops", "dominant_dtype",
           "PEAKS", "CHIP_PEAKS"]

# public per-chip specs (per chip, bf16 matmul peak FLOP/s + HBM B/s;
# ``int8_flops`` where the generation publishes a distinct int8 OPS figure —
# v5e/v5p/v6e run int8 matmuls at 2x the bf16 rate, v2–v4 have no int8
# acceleration so the key is absent and int8 grades against the bf16 peak)
CHIP_PEAKS = {
    "v2": {"flops": 45e12, "hbm_bytes": 700e9},
    "v3": {"flops": 123e12, "hbm_bytes": 900e9},
    "v4": {"flops": 275e12, "hbm_bytes": 1228e9},
    "v5e": {"flops": 197e12, "hbm_bytes": 819e9, "int8_flops": 394e12},
    "v5p": {"flops": 459e12, "hbm_bytes": 2765e9, "int8_flops": 918e12},
    "v6e": {"flops": 918e12, "hbm_bytes": 1640e9, "int8_flops": 1836e12},
}

# historical backend-label mapping: "tpu" maps the tunneled TPU v5 lite to
# v5e; "axon" is the tunnel plugin's own platform name for the same chip.
PEAKS = {"tpu": dict(CHIP_PEAKS["v5e"])}
PEAKS["axon"] = PEAKS["tpu"]


def _kind_to_chip(kind: str) -> Optional[str]:
    """Map a ``device_kind`` string to a :data:`CHIP_PEAKS` key (None =
    unknown). Kind strings vary by runtime version ("TPU v5 lite",
    "TPU v5e", "tpu_v5_lite", …) — match on the version token."""
    k = str(kind).lower().replace("_", " ")
    if "v5p" in k:
        return "v5p"
    if "v5" in k and ("lite" in k or "v5e" in k):
        return "v5e"
    if "v6" in k:
        return "v6e"
    if "v4" in k:
        return "v4"
    if "v3" in k:
        return "v3"
    if "v2" in k:
        return "v2"
    return None


def dtype_peak_flops(peaks: dict, dtype: Optional[str] = None) -> float:
    """The MFU flops denominator for a program whose dominant compute dtype
    is ``dtype``. The tabled peaks (and the config ``peak_flops`` override —
    config.py documents it as the bf16 matmul peak) are BF16 figures; f32
    matmuls lower to multiple bf16 passes on every tabled chip, so the f32
    peak is half. Keying the denominator on the program's dtype stops
    f32-dominant chains from grading themselves against a peak they cannot
    reach (5.6% of bf16-peak is 11.2% of the f32 peak the chain actually
    runs against — the headroom claim changes materially). ``"int8"`` uses
    the chip's published int8 OPS figure (``int8_flops`` in
    :data:`CHIP_PEAKS`) where one exists — the HONEST denominator for an
    int8-accumulating program, typically 2x the bf16 peak — falling back to
    the bf16 figure on generations without int8 acceleration (and on pure
    config-override peaks, which carry no int8 axis)."""
    f = float(peaks["flops"])
    d = str(dtype or "bf16")
    if d == "bf16":
        return f
    if d == "int8":
        return float(peaks.get("int8_flops", f))
    return f / 2.0


def dominant_dtype(stages) -> str:
    """The per-program key for :func:`dtype_peak_flops`: ``"int8"`` when any
    stage of the (possibly lowered) chain accumulates through an int8 MXU
    pass (the deepest ladder rung dominates — its peak is the one the
    program's hot matmuls run against), else ``"bf16"`` when any stage
    accumulates in bf16 or the process-wide MXU FFT precision policy is
    bf16, else ``"f32"``."""
    bf16 = False
    try:
        from ..ops import mxu_fft
        if mxu_fft._precision == "bf16":
            bf16 = True
    except Exception:                                   # noqa: BLE001
        pass
    for s in stages:
        cd = getattr(s, "compute_dtype", "f32")
        if cd == "int8":
            return "int8"
        if cd == "bf16":
            bf16 = True
    return "bf16" if bf16 else "f32"


def detect_peaks(backend: Optional[str] = None,
                 dtype: Optional[str] = None) -> Optional[dict]:
    """Resolve ``{"flops", "hbm_bytes", "chip"}`` for MFU accounting.

    Layering (module docstring): both config overrides set → pure-config
    peaks; a live TPU device → its ``device_kind`` against the public table
    (single-axis overrides still apply; an unknown kind returns None —
    degrade, don't guess); else the ``backend`` LABEL against the historical
    :data:`PEAKS` mapping. None disables MFU/HBM-util output entirely.

    ``dtype`` keys the flops figure on the program's dominant compute dtype
    (:func:`dtype_peak_flops`): ``"f32"`` halves the tabled bf16 peak and
    stamps ``"dtype"`` on the result; ``None``/``"bf16"`` keeps the tabled
    figure (back-compatible)."""
    from ..config import config
    c = config()
    try:
        pf = float(c.get("peak_flops", 0) or 0)
    except (TypeError, ValueError):
        pf = 0.0
    try:
        pb = float(c.get("peak_hbm_gbps", 0) or 0)
    except (TypeError, ValueError):
        pb = 0.0
    def _keyed(out: dict) -> dict:
        # per-dtype denominator: applied LAST so it scales whatever source
        # won (table, label, or the config override — all bf16 figures)
        if dtype is not None:
            out = dict(out)
            out["flops"] = dtype_peak_flops(out, dtype)
            out["dtype"] = str(dtype)
        return out

    if pf > 0 and pb > 0:
        return _keyed({"flops": pf, "hbm_bytes": pb * 1e9, "chip": "config"})

    def _overridden(p: dict, chip: str) -> dict:
        out = dict(p)
        out["chip"] = chip
        if pf > 0:
            out["flops"] = pf
        if pb > 0:
            out["hbm_bytes"] = pb * 1e9
        return out

    try:
        import jax
        dev = jax.devices()[0]
        if dev.platform != "cpu":
            chip = _kind_to_chip(getattr(dev, "device_kind", "") or "")
            if chip is None:
                # unknown LIVE accelerator: flops/bytes only, even when the
                # backend LABEL would map — the live device IS the chip
                # being measured, and the label mapping is an offline-
                # analysis convention for CPU hosts. Pin the denominator on
                # an unknown chip with peak_flops/peak_hbm_gbps instead.
                return None
            return _keyed(_overridden(CHIP_PEAKS[chip], chip))
    except Exception:                                   # noqa: BLE001 — peak
        pass                                            # lookup is best-effort
    p = PEAKS.get(str(backend or ""))
    if p is not None:
        return _keyed(_overridden(p, "v5e"))
    return None


# ---------------------------------------------------------------------------
# cost analysis (signature-cached)
# ---------------------------------------------------------------------------

#: ``signature -> {"flops", "bytes"}`` — one AOT cost-analysis compile per
#: signature per process (bench prefix sweeps, kernel registrations and the
#: profile plane's ensure_costs all share it)
_cost_cache: Dict[tuple, dict] = {}


def cost_of(fn, *args, signature: Optional[tuple] = None,
            compiled=None) -> dict:
    """flops + bytes accessed of ``jit(fn)(*args)`` from XLA's cost analysis.

    ``signature`` (hashable) memoizes the record — the second ask for the
    same program is free. ``compiled`` reuses an ALREADY-compiled executable
    (anything with ``cost_analysis()``) instead of paying the AOT
    ``jax.jit(fn).lower().compile()`` second copy. An actual AOT compile is
    billed to the profile plane as ``reason="cost"`` (visible to the
    doctor's "compiling" verdict; excluded from storm detection — each
    signature compiles at most once per process by construction)."""
    if signature is not None:
        hit = _cost_cache.get(signature)
        if hit is not None:
            return dict(hit)
    if compiled is None:
        import jax

        from ..telemetry import profile as _profile
        with _profile.compiling("cost_analysis", "cost",
                                str(signature or "?")):
            compiled = jax.jit(fn).lower(*args).compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0]
    out = {"flops": float(ca.get("flops", 0.0)),
           "bytes": float(ca.get("bytes accessed", 0.0))}
    if signature is not None:
        _cost_cache[signature] = dict(out)
    return dict(out)


def _stage_marker(s) -> tuple:
    """A structural fingerprint of one stage for cost-cache keys. Names
    alone are NOT enough — two ``fir_stage``s with different tap counts or
    decimation share ``name="fir"`` but compile to different-cost programs.
    Ratio, out dtype, frame multiple and the LTI config (tap count, decim,
    fft length, impl) disambiguate every structural cost determinant;
    carry-resident parameters (retunable without recompile) by construction
    cannot change the program's cost."""
    lti = getattr(s, "lti", None)
    lti_m = None
    if lti is not None:
        taps, decim, fft_len, impl = lti
        lti_m = (int(np.asarray(taps).size), int(decim), int(fft_len),
                 str(impl))
    return (str(getattr(s, "name", "?")), str(getattr(s, "ratio", "")),
            str(getattr(s, "out_dtype", None)),
            int(getattr(s, "frame_multiple", 1) or 1), lti_m,
            # per-call-site route pins (impl, fft_impl, precision): two
            # same-shape stages on different routes compile different-cost
            # programs and must not share a cost-cache line
            getattr(s, "route", None),
            # MergeStage extras (None for plain stages): input count + mode
            getattr(s, "k", None), getattr(s, "mode", None))


def _host_frame(in_dtype, frame: int) -> np.ndarray:
    rng = np.random.default_rng(0)
    if np.issubdtype(np.dtype(in_dtype), np.complexfloating):
        return (rng.standard_normal(frame)
                + 1j * rng.standard_normal(frame)).astype(in_dtype)
    return rng.standard_normal(frame).astype(in_dtype)


def program_cost(pipeline, frame: int, wire=None, k: int = 1) -> dict:
    """Per-DISPATCH flops/bytes of a pipeline's compiled program FORM.

    ``wire=None`` analyzes the bare ``(carry, frame) -> (carry, out)``
    program; a wire name analyzes the WIRED form (decode prolog + encode
    epilog fused in) and ``k > 1`` the megabatch ``lax.scan`` form — exactly
    the program ``TpuKernel`` dispatches, so the profile plane's live MFU is
    charged for the HLO that actually runs. Cached by signature (pipeline
    shape + topology + dtype + frame + wire + k + backend)."""
    import jax

    from ..ops.stages import DagPipeline, FanoutPipeline
    markers = tuple(_stage_marker(s) for s in pipeline.stages)
    # flat markers alone cannot distinguish two graphs with the same stage
    # multiset (a diamond vs a chain of the same nodes, or a fan-out split
    # at a different producer boundary) — the edge structure changes the
    # compiled program's cost, so it must be part of the cache key. The
    # node lengths partition the MERGED flat ``stages`` list the markers
    # were taken from, so (markers, topo) fully determines the program.
    topo: Optional[tuple] = None
    if isinstance(pipeline, DagPipeline):
        topo = ("dag", tuple((len(sl), tuple(inputs))
                             for sl, inputs, _off in pipeline._nodes))
    elif isinstance(pipeline, FanoutPipeline):
        topo = ("fanout", len(pipeline.producer.stages),
                tuple(len(b.stages) for b in pipeline.branches))
    in_dt = np.dtype(pipeline.in_dtype)
    wire_name = None
    if wire is not None:
        from ..ops.wire import get_wire
        wire = get_wire(wire)
        wire_name = wire.name
    sig = ("program", jax.default_backend(), type(pipeline).__name__,
           str(in_dt), int(frame), wire_name, int(k), markers, topo)
    hit = _cost_cache.get(sig)
    if hit is not None:
        return dict(hit)
    carry = pipeline.init_carry()
    host = np.zeros(frame, dtype=in_dt)
    if wire is None:
        return cost_of(pipeline.fn(), carry, host, signature=sig)
    parts = wire.encode_host(host)
    if k > 1:
        parts = tuple(np.stack([np.asarray(p)] * int(k)) for p in parts)
    return cost_of(pipeline.wired_fn(wire, int(k)), carry,
                   *[np.asarray(p) for p in parts], signature=sig)


# ---------------------------------------------------------------------------
# per-stage / per-node attribution
# ---------------------------------------------------------------------------

def pipeline_roofline(stages: Sequence, in_dtype, frame: int,
                      rate_sps: Optional[float] = None,
                      backend: str = "cpu") -> dict:
    """Ops/sample + bytes/sample for the FUSED pipeline and per-stage prefixes.

    Per-stage numbers are DIFFERENCES of compiled prefixes (stage k's cost =
    cost(stages[:k+1]) − cost(stages[:k])), so each stage is charged exactly
    what adding it to the fused program costs — fusion across the boundary
    lands on the stage that triggered it. With ``rate_sps`` the achieved
    FLOP/s, bandwidth, and (when :func:`detect_peaks` knows the chip) MFU
    are filled in. Prefix costs are signature-cached, so a repeated bench
    run (or a profile-plane registration of the full chain) compiles each
    prefix once per process."""
    from ..ops.stages import Pipeline

    out = {"frame": frame, "backend": backend, "stages": []}
    prev = {"flops": 0.0, "bytes": 0.0}
    host = _host_frame(in_dtype, frame)
    dt = str(np.dtype(in_dtype))
    markers = tuple(_stage_marker(s) for s in stages)

    for k in range(1, len(stages) + 1):
        pipe = Pipeline(list(stages[:k]), in_dtype)
        carry = pipe.init_carry()
        sig = ("prefix", backend, dt, int(frame), markers[:k])
        cost = cost_of(pipe.fn(), carry, host, signature=sig)
        out["stages"].append({
            "name": stages[k - 1].name,
            "flops_per_sample": (cost["flops"] - prev["flops"]) / frame,
            "bytes_per_sample": (cost["bytes"] - prev["bytes"]) / frame,
        })
        prev = cost
    out["flops_per_sample"] = prev["flops"] / frame
    out["bytes_per_sample"] = prev["bytes"] / frame
    _finish_roofline(out, out["stages"], rate_sps, backend,
                     dominant_dtype(stages))
    return out


def graph_roofline(pipeline, frame: Optional[int] = None,
                   rate_sps: Optional[float] = None,
                   backend: str = "cpu") -> dict:
    """Per-NODE roofline attribution for fan-out / general-DAG pipelines.

    The prefix-difference math of :func:`pipeline_roofline` generalized to
    DAGs: node i's cost = cost(DAG truncated to nodes[:i+1]) − cost(nodes[:i])
    (node lists are topological, so every prefix is a valid sub-DAG; a
    truncated prefix's extra sink materializations mirror the linear prefix
    caveat). Accepts a :class:`~futuresdr_tpu.ops.stages.DagPipeline`, a
    :class:`~futuresdr_tpu.ops.stages.FanoutPipeline` (viewed as producer
    node + one node per branch), or a plain
    :class:`~futuresdr_tpu.ops.stages.Pipeline` (delegates to the per-stage
    form, re-keyed under ``nodes``). Per-sample numbers are per REGION-INPUT
    sample."""
    from ..ops.stages import DagPipeline, FanoutPipeline, Pipeline

    if isinstance(pipeline, Pipeline):
        out = pipeline_roofline(pipeline.stages, pipeline.in_dtype,
                                frame or pipeline.frame_multiple,
                                rate_sps, backend)
        out["nodes"] = [dict(s, inputs=([] if i == 0 else [i - 1]))
                        for i, s in enumerate(out["stages"])]
        return out
    if isinstance(pipeline, FanoutPipeline):
        nodes = [(list(pipeline.producer.stages), [])]
        nodes += [(list(b.stages), [0]) for b in pipeline.branches]
        in_dtype = pipeline.in_dtype
    elif isinstance(pipeline, DagPipeline):
        nodes = [(list(sl), list(inputs))
                 for sl, inputs in pipeline.raw_nodes]
        in_dtype = pipeline.in_dtype
    else:
        raise TypeError(f"graph_roofline: unsupported pipeline type "
                        f"{type(pipeline).__name__}")
    fm = pipeline.frame_multiple
    frame = frame or fm
    frame = max(fm, (int(frame) // fm) * fm)
    host = _host_frame(in_dtype, frame)
    dt = str(np.dtype(in_dtype))
    node_names = tuple(
        ("+".join(str(getattr(s, "name", "?")) for s in sl) or "passthrough",
         tuple(inputs)) for sl, inputs in nodes)
    node_markers = tuple(
        (tuple(_stage_marker(s) for s in sl), tuple(inputs))
        for sl, inputs in nodes)

    out = {"frame": frame, "backend": backend, "nodes": []}
    prev = {"flops": 0.0, "bytes": 0.0}
    for i in range(1, len(nodes) + 1):
        sub = DagPipeline(nodes[:i], in_dtype)
        sig = ("dag-prefix", backend, dt, frame, node_markers[:i])
        cost = cost_of(sub.fn(), sub.init_carry(), host, signature=sig)
        name, inputs = node_names[i - 1]
        out["nodes"].append({
            "name": name,
            "inputs": list(inputs),
            "flops_per_sample": (cost["flops"] - prev["flops"]) / frame,
            "bytes_per_sample": (cost["bytes"] - prev["bytes"]) / frame,
        })
        prev = cost
    out["flops_per_sample"] = prev["flops"] / frame
    out["bytes_per_sample"] = prev["bytes"] / frame
    _finish_roofline(out, out["nodes"], rate_sps, backend,
                     dominant_dtype(pipeline.stages))
    return out


def _finish_roofline(out: dict, entries, rate_sps, backend: str,
                     dtype: Optional[str] = None) -> None:
    """Shared tail of the per-stage/per-node walks: bound classification
    against the detected chip ridge + achieved-rate fields, with the MFU
    denominator keyed on the chain's dominant compute dtype."""
    peak = detect_peaks(backend, dtype=dtype)
    if dtype is not None:
        out["compute_dtype"] = str(dtype)
    if peak:
        ridge = peak["flops"] / peak["hbm_bytes"]     # flop/byte ridge point
        for s in entries:
            ai = s["flops_per_sample"] / max(s["bytes_per_sample"], 1e-12)
            s["arith_intensity"] = ai
            s["bound"] = "hbm" if ai < ridge else "compute"
    if rate_sps:
        out["achieved_flops"] = rate_sps * out["flops_per_sample"]
        out["achieved_bw_bytes"] = rate_sps * out["bytes_per_sample"]
        if peak:
            out["mfu"] = out["achieved_flops"] / peak["flops"]
            out["hbm_util"] = out["achieved_bw_bytes"] / peak["hbm_bytes"]
