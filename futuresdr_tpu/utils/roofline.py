"""Roofline accounting for fused stage pipelines — XLA's own cost model, not hand
math.

VERDICT r3 item 7: a bare "2,944 Msps" is not auditable; ops/sample and
bytes/sample turn it into an efficiency claim. The numbers come from the
compiled program's ``cost_analysis()`` (XLA's flop/byte counts for exactly the
HLO that runs), so they track fusion decisions instead of a paper formula.
Caveat: the analysis is per-backend — a CPU-compiled pipeline fuses differently
than the TPU one, so artifacts must carry the backend they were derived on.

Peak table: the only figures used are the PUBLIC v5e chip specs (197e12 bf16
FLOP/s, 819e9 B/s HBM) — MFU is reported against the bf16 matmul peak, the
standard MFU convention. There is no official f32 peak; f32 matmuls lower to
multiple bf16 passes, so the same denominator is used and f32 chains simply
show proportionally lower MFU.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

__all__ = ["cost_of", "pipeline_roofline", "PEAKS"]

# public chip specs (per chip). "tpu" maps the tunneled TPU v5 lite to v5e;
# "axon" is the tunnel plugin's own platform name for the same chip.
PEAKS = {
    "tpu": {"flops": 197e12, "hbm_bytes": 819e9},     # v5e, bf16 matmul peak
}
PEAKS["axon"] = PEAKS["tpu"]


def cost_of(fn, *args) -> dict:
    """flops + bytes accessed of ``jit(fn)(*args)`` from XLA's cost analysis."""
    import jax

    comp = jax.jit(fn).lower(*args).compile()
    ca = comp.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0]
    return {"flops": float(ca.get("flops", 0.0)),
            "bytes": float(ca.get("bytes accessed", 0.0))}


def pipeline_roofline(stages: Sequence, in_dtype, frame: int,
                      rate_sps: Optional[float] = None,
                      backend: str = "cpu") -> dict:
    """Ops/sample + bytes/sample for the FUSED pipeline and per-stage prefixes.

    Per-stage numbers are DIFFERENCES of compiled prefixes (stage k's cost =
    cost(stages[:k+1]) − cost(stages[:k])), so each stage is charged exactly
    what adding it to the fused program costs — fusion across the boundary
    lands on the stage that triggered it. With ``rate_sps`` the achieved
    FLOP/s, bandwidth, and (for TPU) MFU vs the public bf16 peak are filled in.
    """
    import jax

    from ..ops.stages import Pipeline

    out = {"frame": frame, "backend": backend, "stages": []}
    prev = {"flops": 0.0, "bytes": 0.0}
    rng = np.random.default_rng(0)
    if np.issubdtype(np.dtype(in_dtype), np.complexfloating):
        host = (rng.standard_normal(frame)
                + 1j * rng.standard_normal(frame)).astype(in_dtype)
    else:
        host = rng.standard_normal(frame).astype(in_dtype)

    for k in range(1, len(stages) + 1):
        pipe = Pipeline(list(stages[:k]), in_dtype)
        carry = pipe.init_carry()
        cost = cost_of(pipe.fn(), carry, host)
        out["stages"].append({
            "name": stages[k - 1].name,
            "flops_per_sample": (cost["flops"] - prev["flops"]) / frame,
            "bytes_per_sample": (cost["bytes"] - prev["bytes"]) / frame,
        })
        prev = cost
    out["flops_per_sample"] = prev["flops"] / frame
    out["bytes_per_sample"] = prev["bytes"] / frame
    ridge = None
    peak = PEAKS.get(backend)
    if peak:
        ridge = peak["flops"] / peak["hbm_bytes"]      # flop/byte ridge point
        for s in out["stages"]:
            ai = s["flops_per_sample"] / max(s["bytes_per_sample"], 1e-12)
            s["arith_intensity"] = ai
            s["bound"] = "hbm" if ai < ridge else "compute"
    if rate_sps:
        out["achieved_flops"] = rate_sps * out["flops_per_sample"]
        out["achieved_bw_bytes"] = rate_sps * out["bytes_per_sample"]
        if peak:
            out["mfu"] = out["achieved_flops"] / peak["flops"]
            out["hbm_util"] = out["achieved_bw_bytes"] / peak["hbm_bytes"]
    return out
