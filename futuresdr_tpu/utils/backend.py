"""Backend liveness helper: probe device init in a subprocess before touching jax.

The axon TPU tunnel can wedge so that the first ``jax.devices()`` blocks indefinitely —
and the plugin hooks backend init such that only ``jax.config.update('jax_platforms',
'cpu')`` (not the env var) avoids it. Tools that want "TPU if alive, else CPU" call
:func:`ensure_backend` before their first jax use.
"""

from __future__ import annotations

import subprocess
import sys

__all__ = ["ensure_backend"]


def ensure_backend(probe_timeout: int = 120) -> str:
    """Returns the platform that will be used ("tpu-like" native platform or "cpu")."""
    import os
    if os.environ.get("FSDR_FORCE_CPU"):
        # the init-guarded route (no-op once a backend is live; switching then
        # would re-trigger plugin discovery and hang)
        from ..tpu.instance import force_cpu_platform
        force_cpu_platform()
        return "cpu"
    code = "import jax; jax.devices(); print('ok')"
    try:
        r = subprocess.run([sys.executable, "-c", code], timeout=probe_timeout,
                           capture_output=True, text=True)
        alive = r.returncode == 0 and "ok" in r.stdout
    except subprocess.TimeoutExpired:
        alive = False
    if not alive:
        import jax
        jax.config.update("jax_platforms", "cpu")
        return "cpu"
    import jax
    return jax.devices()[0].platform
