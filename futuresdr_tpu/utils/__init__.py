"""Utilities: latency tracepoints, misc helpers."""

from .trace import LatencyProbeSource, LatencyProbeSink, latency_stats

__all__ = ["LatencyProbeSource", "LatencyProbeSink", "latency_stats"]
