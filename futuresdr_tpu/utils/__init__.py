"""Utilities: latency tracepoints, checkpoint/resume, misc helpers."""

from .trace import LatencyProbeSource, LatencyProbeSink, latency_stats
from .checkpoint import (save_pytree, load_pytree, save_flowgraph_state,
                         load_flowgraph_state)
from .backend import ensure_backend

__all__ = ["LatencyProbeSource", "LatencyProbeSink", "latency_stats",
           "save_pytree", "load_pytree", "save_flowgraph_state",
           "load_flowgraph_state", "ensure_backend"]
