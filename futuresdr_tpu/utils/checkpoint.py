"""Checkpoint / resume.

The reference has NO checkpointing (SURVEY §5: closest analog is that finished blocks are
restored into the Flowgraph). This framework goes further: block state and jax pytrees
(model params / optimizer state) can be saved and restored — training jobs in the
flowgraph (modrec) resume across process restarts via orbax.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Dict, Optional

from ..log import logger

__all__ = ["save_pytree", "load_pytree", "save_flowgraph_state", "load_flowgraph_state"]

log = logger("checkpoint")


def save_pytree(path: str, tree: Any) -> None:
    """Persist a jax pytree (params/opt state) with orbax."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, tree, force=True)
    ckptr.wait_until_finished()


def load_pytree(path: str, like: Optional[Any] = None) -> Any:
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    if like is not None:
        import jax
        target = jax.tree_util.tree_map(ocp.utils.to_shape_dtype_struct, like) \
            if hasattr(ocp.utils, "to_shape_dtype_struct") else like
        try:
            return ckptr.restore(path, target)
        except Exception:
            pass
    return ckptr.restore(path)


def save_flowgraph_state(fg, path: str) -> None:
    """Snapshot every block exposing ``state_dict()`` (plus Vector-style sinks)."""
    states: Dict[str, Any] = {}
    for bid in range(len(fg)):
        try:
            blk = fg.wrapped(bid)
        except Exception:
            continue
        k = blk.kernel
        if hasattr(k, "state_dict"):
            states[blk.instance_name] = k.state_dict()
    with open(path, "wb") as f:
        pickle.dump(states, f)
    log.info("saved %d block states to %s", len(states), path)


def load_flowgraph_state(fg, path: str) -> int:
    with open(path, "rb") as f:
        states = pickle.load(f)
    n = 0
    for bid in range(len(fg)):
        try:
            blk = fg.wrapped(bid)
        except Exception:
            continue
        k = blk.kernel
        if blk.instance_name in states and hasattr(k, "load_state_dict"):
            k.load_state_dict(states[blk.instance_name])
            n += 1
    return n
