"""Checkpoint / resume.

The reference has NO checkpointing (SURVEY §5: closest analog is that finished blocks are
restored into the Flowgraph). This framework goes further: block state and jax pytrees
(model params / optimizer state) can be saved and restored — training jobs in the
flowgraph (modrec) resume across process restarts via orbax.
"""

from __future__ import annotations

import base64
import json
import os
from typing import Any, Dict, Optional

import numpy as np

from ..log import logger

__all__ = ["save_pytree", "load_pytree", "save_flowgraph_state", "load_flowgraph_state"]

log = logger("checkpoint")


# ---------------------------------------------------------------------------
# data-only block-state serialization (no pickle: a checkpoint file must never
# be able to execute code on restore)
# ---------------------------------------------------------------------------

def _flatten(obj: Any, path: str, arrays: Dict[str, np.ndarray]) -> Any:
    """Encode ``obj`` as a JSON-able spec; ndarrays go to ``arrays`` by key."""
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, bytes):
        return {"__t__": "bytes", "v": base64.b64encode(obj).decode()}
    if isinstance(obj, complex):
        return {"__t__": "complex", "re": obj.real, "im": obj.imag}
    if isinstance(obj, np.generic):                       # numpy scalar
        return _flatten(obj.item(), path, arrays)
    if hasattr(obj, "__array__"):                         # ndarray / jax array
        a = np.asarray(obj)
        if a.dtype == object:
            # would save fine but np.load(allow_pickle=False) can never restore it
            raise TypeError(f"state_dict entry {path!r} is an object-dtype array; "
                            f"only numeric/bool dtypes are checkpointable")
        key = f"a{len(arrays)}"
        arrays[key] = a
        return {"__t__": "nd", "k": key}
    if isinstance(obj, (list, tuple)):
        items = [_flatten(v, f"{path}[{i}]", arrays) for i, v in enumerate(obj)]
        return {"__t__": "tuple" if isinstance(obj, tuple) else "list", "v": items}
    if isinstance(obj, dict):
        return {"__t__": "dict",
                "v": [[_flatten(k, path, arrays), _flatten(v, f"{path}.{k}", arrays)]
                      for k, v in obj.items()]}
    raise TypeError(f"state_dict entry {path!r} has unserializable type "
                    f"{type(obj).__name__}; use scalars/ndarrays/containers")


def _unflatten(spec: Any, arrays) -> Any:
    if not isinstance(spec, dict):
        return spec
    t = spec["__t__"]
    if t == "bytes":
        return base64.b64decode(spec["v"])
    if t == "complex":
        return complex(spec["re"], spec["im"])
    if t == "nd":
        return arrays[spec["k"]]
    if t == "list":
        return [_unflatten(v, arrays) for v in spec["v"]]
    if t == "tuple":
        return tuple(_unflatten(v, arrays) for v in spec["v"])
    if t == "dict":
        return {_unflatten(k, arrays): _unflatten(v, arrays) for k, v in spec["v"]}
    raise ValueError(f"unknown spec tag {t!r}")


def save_pytree(path: str, tree: Any) -> None:
    """Persist a jax pytree (params/opt state) with orbax."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, tree, force=True)
    ckptr.wait_until_finished()


def load_pytree(path: str, like: Optional[Any] = None) -> Any:
    """Restore a pytree. With ``like``, leaves are restored HOST-side (numpy)
    and re-placed onto ``like``'s devices through the transfer pair shim
    (``ops/xfer.to_device``) — orbax's own restore device_puts raw complex
    buffers, the exact H2D path that is broken on the axon TPU backend, which
    would poison a restored device-pipeline carry (e.g. a FIR stage's
    frequency-domain taps). No-op on backends with working complex transfers."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    if like is not None:
        import jax
        import numpy as np

        from ..ops.xfer import to_device

        def host_struct(a):
            if hasattr(a, "shape") and hasattr(a, "dtype"):
                return np.zeros(a.shape, a.dtype)
            return a

        def place(restored, ref):
            if isinstance(ref, jax.Array):
                devs = list(ref.devices())
                if len(devs) > 1:
                    # multi-device leaf: restore the reference's SHARDING (a
                    # single-device put would concentrate the carry on one chip
                    # and break the next sharded dispatch). Sharded complex on a
                    # split-complex backend cannot transfer either way — let
                    # device_put raise loudly rather than mis-place silently.
                    return jax.device_put(np.asarray(restored), ref.sharding)
                return to_device(np.asarray(restored),
                                 devs[0] if devs else None)
            return restored

        try:
            host = ckptr.restore(
                path, jax.tree_util.tree_map(host_struct, like))
            return jax.tree_util.tree_map(place, host, like)
        except Exception as e:
            # falling back means RAW device_puts — the complex-broken path on
            # axon; the swallowed reason must not vanish with it
            log.warning("host-side checkpoint restore failed (%r); falling "
                        "back to direct orbax restore", e)
    return ckptr.restore(path)


def save_flowgraph_state(fg, path: str) -> None:
    """Snapshot every block exposing ``state_dict()`` (plus Vector-style sinks)."""
    states: Dict[str, Any] = {}
    for bid in range(len(fg)):
        try:
            blk = fg.wrapped(bid)
        except Exception:
            continue
        k = blk.kernel
        if hasattr(k, "state_dict"):
            states[blk.instance_name] = k.state_dict()
    arrays: Dict[str, np.ndarray] = {}
    spec = _flatten(states, "$", arrays)
    with open(path, "wb") as f:           # file object: no .npz suffix munging
        np.savez(f, __spec__=np.frombuffer(
            json.dumps(spec).encode(), dtype=np.uint8), **arrays)
    log.info("saved %d block states to %s", len(states), path)


def load_flowgraph_state(fg, path: str) -> int:
    with open(path, "rb") as f:
        magic = f.read(1)
    if magic == b"\x80":                                  # pickle protocol header
        raise ValueError(
            f"{path} is a legacy pickle-format checkpoint; the format changed to "
            f"data-only npz (arbitrary-code-execution hardening). Re-create it with "
            f"save_flowgraph_state from this version.")
    with np.load(path, allow_pickle=False) as z:
        spec = json.loads(bytes(z["__spec__"]).decode())
        states = _unflatten(spec, z)
    n = 0
    for bid in range(len(fg)):
        try:
            blk = fg.wrapped(bid)
        except Exception:
            continue
        k = blk.kernel
        if blk.instance_name in states and hasattr(k, "load_state_dict"):
            k.load_state_dict(states[blk.instance_name])
            n += 1
    return n
