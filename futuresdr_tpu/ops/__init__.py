"""Jittable TPU ops: streaming stages and their composition into fused XLA programs.

These are the jax/XLA counterparts of the numpy cores in :mod:`futuresdr_tpu.dsp` — same
streaming contracts, explicit carry, static shapes. Used by :class:`futuresdr_tpu.tpu.TpuKernel`.
"""

from .stages import (Stage, Pipeline, FanoutPipeline, MergeStage, DagPipeline,
                     apply_merge_stage, add_merge_stage, interleave_merge_stage,
                     concat_merge_stage,
                     fir_stage, fft_stage, mag2_stage, log10_stage,
                     xlating_fir_stage,
                     rotator_stage, quad_demod_stage, apply_stage, fftshift_stage,
                     decimate_stage, moving_avg_stage, resample_stage, agc_stage,
                     channelizer_stage, lora_demod_stage)
from .wire import (Wire, WIRE_FORMATS, get_wire, resolve_wire, wire_names,
                   measure_snr_db, streamed_ceiling_msps)
from .precision import (PrecisionPlan, plan_interior_precision,
                        lower_pipeline)

__all__ = ["Stage", "Pipeline", "FanoutPipeline", "MergeStage", "DagPipeline",
           "apply_merge_stage", "add_merge_stage", "interleave_merge_stage",
           "concat_merge_stage",
           "fir_stage", "fft_stage", "mag2_stage", "log10_stage",
           "xlating_fir_stage",
           "rotator_stage", "quad_demod_stage", "apply_stage", "fftshift_stage",
           "decimate_stage", "moving_avg_stage", "resample_stage", "agc_stage",
           "channelizer_stage", "lora_demod_stage",
           "Wire", "WIRE_FORMATS", "get_wire", "resolve_wire", "wire_names",
           "measure_snr_db", "streamed_ceiling_msps",
           "PrecisionPlan", "plan_interior_precision", "lower_pipeline"]
