"""Pallas TPU kernels for the streaming hot ops.

Hand-written kernels for cases XLA's fusion doesn't cover well: the short-tap streaming
FIR (direct form beats FFT overlap-save below ~32 taps) as an unrolled shifted
multiply-accumulate on the VPU, with the inter-block overlap handled by passing each grid
step both its own input block and its left neighbour (no overlapping BlockSpecs needed).

Falls back to interpret mode off-TPU — numerics are identical, so CI validates the kernel
on CPU and the same code runs compiled on the chip.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

__all__ = ["pallas_fir", "pallas_fir_continue", "pallas_fir_stage"]


def _fir_kernel(prev_ref, cur_ref, taps_ref, o_ref, *, n_taps: int, block: int):
    """One grid step: y[i] = Σ_k taps[k] · x[i − k] over this block, using the previous
    block's tail for the first n_taps−1 outputs."""
    full = jnp.concatenate([prev_ref[...], cur_ref[...]])       # [2·block]
    acc = jnp.zeros((block,), jnp.float32)
    base = block - (n_taps - 1)
    for k in range(n_taps):                                     # static unroll
        # static slice offsets (k is a Python int) — dynamic_slice has no Mosaic
        # TC lowering; static lax.slice does
        acc = acc + taps_ref[n_taps - 1 - k] * full[base + k:base + k + block]
    o_ref[...] = acc


def pallas_fir(x: jnp.ndarray, taps, block: int = 4096,
               interpret: Optional[bool] = None) -> jnp.ndarray:
    """Causal FIR of a float32 frame (zero initial state): len(x) must divide ``block``.

    Complex frames are filtered as two real passes at the wrapper level
    (:func:`pallas_fir_stage`).
    """
    taps = jnp.asarray(taps, jnp.float32)
    n_taps = taps.shape[0]
    assert block >= n_taps, "block must exceed the tap count"
    n = x.shape[0]
    assert n % block == 0, f"frame ({n}) must be a multiple of block ({block})"
    grid = n // block
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    # block i sees: prev = x[(i-1)·block : i·block] (block 0 → block of zeros via the
    # leading pad), cur = x[i·block : (i+1)·block]
    xp = jnp.concatenate([jnp.zeros(block, x.dtype), x])
    kernel = partial(_fir_kernel, n_taps=n_taps, block=block)
    return pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((block,), lambda i: (i,)),        # prev (offset by the pad)
            pl.BlockSpec((block,), lambda i: (i + 1,)),    # cur
            pl.BlockSpec((n_taps,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((n,), jnp.float32),
        interpret=interpret,
    )(xp, xp, taps)


def pallas_fir_continue(hist: jnp.ndarray, x: jnp.ndarray, taps: np.ndarray,
                        block: int = 4096) -> jnp.ndarray:
    """Streaming continuation: filter frame ``x`` given the previous ``n_taps-1``
    input samples in ``hist``. Pads to the kernel's block granularity, runs complex
    frames as two real passes, and returns exactly ``len(x)`` aligned outputs.
    Shared by :func:`pallas_fir_stage` and ``stages.fir_stage(impl="pallas")``.
    ``taps`` may be a traced device array (carry-resident, for runtime tap swap) —
    only its static shape is read here."""
    taps = jnp.asarray(taps, dtype=jnp.float32)
    nt = taps.shape[0]
    ext = jnp.concatenate([hist, x])               # [(nt-1) + n]
    pad = (-ext.shape[0]) % block
    if pad:
        ext = jnp.concatenate([ext, jnp.zeros(pad, ext.dtype)])
    if jnp.iscomplexobj(x):
        yr = pallas_fir(ext.real, taps, block)
        yi = pallas_fir(ext.imag, taps, block)
        y = (yr + 1j * yi).astype(x.dtype)
    else:
        y = pallas_fir(ext, taps, block).astype(x.dtype)
    return y[nt - 1:nt - 1 + x.shape[0]]


def pallas_fir_stage(taps, block: int = 4096):
    """Streaming Stage (carry = tail samples) running the pallas kernel per frame; the
    drop-in alternative to :func:`futuresdr_tpu.ops.stages.fir_stage` for short taps."""
    from fractions import Fraction

    from .stages import Stage

    taps = np.asarray(taps, dtype=np.float32)
    nt = len(taps)

    def fn(carry, x):
        y = pallas_fir_continue(carry, x, taps, block)
        ext = jnp.concatenate([carry, x])
        return ext[ext.shape[0] - (nt - 1):], y

    def init_carry(dtype):
        return jnp.zeros(nt - 1, dtype=dtype)

    return Stage(fn, init_carry, Fraction(1, 1), None, 1, "pallas_fir")
