"""Pallas TPU kernels for the streaming hot ops.

Hand-written kernels for cases XLA's fusion doesn't cover well (the dataflow-shaped
kernel argument of Flex-TPU, arXiv:2407.08700):

* the short-tap streaming FIR (direct form beats FFT overlap-save below ~32 taps) as an
  unrolled shifted multiply-accumulate on the VPU, with the inter-block overlap handled
  by passing each grid step both its own input block and its left neighbour (no
  overlapping BlockSpecs needed);
* the fused PFB channelizer (:func:`pallas_pfb`): polyphase partition MAC + the
  twiddle-feed IDFT across branches as one kernel — the intermediate ``v[t, c]`` bank
  never round-trips HBM between the branch filters and the branch transform, which is
  exactly the HBM-bound half of the ``blocks/pfb.py`` / ``ops/stages.channelizer_stage``
  matmul path;
* the fused FIR→decimate kernel (:func:`pallas_poly_fir`): the shifted-row polyphase
  factorization of ``ops/stages._poly_decim_fir_stage`` computed at the DECIMATED rate
  inside one kernel (ntaps/D MACs per input sample, no full-rate intermediate) — a 3-D
  weight tensor runs the same kernel per interpolation phase, which is the resampler's
  polyphase inner loop;
* the fused FIR→FFT kernel (:func:`pallas_fir_fft`): filter + windowed DFT in one
  kernel — the filtered frame never round-trips HBM between the FIR and the transform,
  which is the resident fir64+fft2048 chain's whole interior edge;
* the rotator / quadrature-demod inner loops (:func:`pallas_rotator`,
  :func:`pallas_quad_demod`): phase-ramp multiply and ``angle(x·conj(x₋₁))`` over 2-D
  lane tiles, the remaining elementwise hot loops of the FM chain.

Every kernel takes ``precision="bf16"`` for the interior-precision policy
(``ops/precision.py``): operands are cast to bfloat16 and accumulated in float32 —
on the MXU this is the native-speed pass; on CPU/interpret it applies exactly the same
quantization, so SNR calibration measures the real thing. (The int8 rung does NOT run
through these kernels — it lowers to quantized XLA matmuls in ``ops/stages``.)

Block shapes: every kernel's ``block`` parameter defaults to ``None`` = "resolve
through the autotuned table" (:func:`set_tuned_blocks`, installed at kernel init from
the ``pallas_blocks`` autotune-cache axis swept by ``tpu/pallas_tune.py``), falling
back to the hand-picked :data:`DEFAULT_BLOCKS`. Stage-level callers pass no block, so
a measured sweep reaches every ``impl="pallas"`` stage without re-plumbing.

Falls back to interpret mode off-TPU — numerics are identical, so CI validates the kernel
on CPU and the same code runs compiled on the chip.
"""

from __future__ import annotations

import threading
from functools import partial
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

__all__ = ["pallas_fir", "pallas_fir_continue", "pallas_fir_stage",
           "pallas_pfb", "pallas_poly_fir", "pallas_fir_fft",
           "pallas_rotator", "pallas_quad_demod",
           "DEFAULT_BLOCKS", "set_tuned_blocks", "tuned_blocks"]

# ---------------------------------------------------------------------------
# tuned block shapes (the Pallas autotune plane, tpu/pallas_tune.py)
# ---------------------------------------------------------------------------

#: hand-picked fallback block shapes per kernel — the pre-autotune defaults
#: (``fir``/``poly_fir`` in samples / decimated rows, ``pfb`` in commutated
#: time rows, ``fir_fft`` in transform rows, ``rotator``/``quad_demod`` in
#: 128-lane rows). Always part of the sweep's candidate set, so a recorded
#: winner is never a regression against them.
DEFAULT_BLOCKS: Dict[str, int] = {
    "fir": 4096, "pfb": 256, "poly_fir": 1024, "fir_fft": 8,
    "rotator": 256, "quad_demod": 256,
}

_tuned_lock = threading.Lock()
_tuned: Dict[str, int] = {}


def set_tuned_blocks(blocks: Optional[Dict[str, int]]) -> None:
    """Install measured block shapes process-wide (``None``/``{}`` clears).
    Unknown kernel keys and non-positive values are IGNORED, not raised —
    a stale cache entry from an older repo revision must never wedge kernel
    init (mirrors the autotune cache's per-axis guarded-parse contract)."""
    with _tuned_lock:
        _tuned.clear()
        for k, v in (blocks or {}).items():
            try:
                v = int(v)
            except (TypeError, ValueError):
                continue
            if k in DEFAULT_BLOCKS and v > 0:
                _tuned[k] = v


def tuned_blocks() -> Dict[str, int]:
    """The active block table: measured winners over the defaults."""
    with _tuned_lock:
        return {**DEFAULT_BLOCKS, **_tuned}


def _resolve_block(kernel: str, block: Optional[int]) -> int:
    """``block=None`` (the stage-level calling convention) → the tuned table;
    an explicit block always wins (tests pin odd shapes through it)."""
    if block is not None:
        return int(block)
    with _tuned_lock:
        return int(_tuned.get(kernel, DEFAULT_BLOCKS[kernel]))


def _maybe_bf16(*arrays, bf16: bool):
    if not bf16:
        return arrays if len(arrays) > 1 else arrays[0]
    out = tuple(a.astype(jnp.bfloat16) for a in arrays)
    return out if len(out) > 1 else out[0]


def _fir_kernel(prev_ref, cur_ref, taps_ref, o_ref, *, n_taps: int, block: int,
                bf16: bool = False):
    """One grid step: y[i] = Σ_k taps[k] · x[i − k] over this block, using the previous
    block's tail for the first n_taps−1 outputs."""
    full = jnp.concatenate([prev_ref[...], cur_ref[...]])       # [2·block]
    taps = taps_ref[...]
    full, taps = _maybe_bf16(full, taps, bf16=bf16)
    acc = jnp.zeros((block,), jnp.float32)
    base = block - (n_taps - 1)
    for k in range(n_taps):                                     # static unroll
        # static slice offsets (k is a Python int) — dynamic_slice has no Mosaic
        # TC lowering; static lax.slice does
        acc = acc + (taps[n_taps - 1 - k]
                     * full[base + k:base + k + block]).astype(jnp.float32)
    o_ref[...] = acc


def pallas_fir(x: jnp.ndarray, taps, block: Optional[int] = None,
               interpret: Optional[bool] = None,
               precision: Optional[str] = None) -> jnp.ndarray:
    """Causal FIR of a float32 frame (zero initial state): len(x) must divide ``block``
    (default: the tuned table's ``"fir"`` shape).

    Complex frames are filtered as two real passes at the wrapper level
    (:func:`pallas_fir_stage`). ``precision="bf16"`` runs the MAC with bfloat16
    operands and float32 accumulation (module docstring).
    """
    block = _resolve_block("fir", block)
    taps = jnp.asarray(taps)
    if not jnp.issubdtype(taps.dtype, jnp.bfloat16):
        taps = taps.astype(jnp.float32)
    n_taps = taps.shape[0]
    assert block >= n_taps, "block must exceed the tap count"
    n = x.shape[0]
    assert n % block == 0, f"frame ({n}) must be a multiple of block ({block})"
    grid = n // block
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    # block i sees: prev = x[(i-1)·block : i·block] (block 0 → block of zeros via the
    # leading pad), cur = x[i·block : (i+1)·block]
    xp = jnp.concatenate([jnp.zeros(block, x.dtype), x])
    kernel = partial(_fir_kernel, n_taps=n_taps, block=block,
                     bf16=(precision == "bf16"))
    return pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((block,), lambda i: (i,)),        # prev (offset by the pad)
            pl.BlockSpec((block,), lambda i: (i + 1,)),    # cur
            pl.BlockSpec((n_taps,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((n,), jnp.float32),
        interpret=interpret,
    )(xp, xp, taps)


def pallas_fir_continue(hist: jnp.ndarray, x: jnp.ndarray, taps: np.ndarray,
                        block: Optional[int] = None,
                        precision: Optional[str] = None) -> jnp.ndarray:
    """Streaming continuation: filter frame ``x`` given the previous ``n_taps-1``
    input samples in ``hist``. Pads to the kernel's block granularity, runs complex
    frames as two real passes, and returns exactly ``len(x)`` aligned outputs.
    Shared by :func:`pallas_fir_stage` and ``stages.fir_stage(impl="pallas")``.
    ``taps`` may be a traced device array (carry-resident, for runtime tap swap) —
    only its static shape is read here."""
    block = _resolve_block("fir", block)
    taps = jnp.asarray(taps)
    if not jnp.issubdtype(taps.dtype, jnp.bfloat16):
        taps = taps.astype(jnp.float32)
    nt = taps.shape[0]
    ext = jnp.concatenate([hist, x])               # [(nt-1) + n]
    pad = (-ext.shape[0]) % block
    if pad:
        ext = jnp.concatenate([ext, jnp.zeros(pad, ext.dtype)])
    if jnp.iscomplexobj(x):
        yr = pallas_fir(ext.real, taps, block, precision=precision)
        yi = pallas_fir(ext.imag, taps, block, precision=precision)
        y = (yr + 1j * yi).astype(x.dtype)
    else:
        y = pallas_fir(ext, taps, block, precision=precision).astype(x.dtype)
    return y[nt - 1:nt - 1 + x.shape[0]]


def pallas_fir_stage(taps, block: Optional[int] = None):
    """Streaming Stage (carry = tail samples) running the pallas kernel per frame; the
    drop-in alternative to :func:`futuresdr_tpu.ops.stages.fir_stage` for short taps."""
    from fractions import Fraction

    from .stages import Stage

    taps = np.asarray(taps, dtype=np.float32)
    nt = len(taps)

    def fn(carry, x):
        y = pallas_fir_continue(carry, x, taps, block)
        ext = jnp.concatenate([carry, x])
        return ext[ext.shape[0] - (nt - 1):], y

    def init_carry(dtype):
        return jnp.zeros(nt - 1, dtype=dtype)

    return Stage(fn, init_carry, Fraction(1, 1), None, 1, "pallas_fir")


# ---------------------------------------------------------------------------
# fused PFB channelizer: polyphase MAC + twiddle-feed IDFT in one kernel
# ---------------------------------------------------------------------------

def _pfb_kernel(prev_r, prev_i, cur_r, cur_i, taps_ref, er_ref, ei_ref,
                out_r, out_i, *, n_taps: int, block: int, bf16: bool):
    """One grid step over ``block`` commutated time rows: the branch-filter MAC
    ``v[s, c] = Σ_k taps[k, c] · rows[s + K−1 − k, c]`` (history rows ride in
    from the previous block, exactly the FIR kernel's neighbour trick), then
    the IDFT across branches as two real matmuls per output plane — the
    intermediate ``v`` bank lives only in VMEM."""
    fr = jnp.concatenate([prev_r[...], cur_r[...]])          # [2·block, N]
    fi = jnp.concatenate([prev_i[...], cur_i[...]])
    taps = taps_ref[...]                                     # [K, N]
    fr, fi, taps = _maybe_bf16(fr, fi, taps, bf16=bf16)
    acc_r = jnp.zeros(cur_r.shape, jnp.float32)
    acc_i = jnp.zeros(cur_i.shape, jnp.float32)
    for k in range(n_taps):                                  # static unroll
        t = taps[k]
        acc_r = acc_r + (t * fr[block - k:2 * block - k]).astype(jnp.float32)
        acc_i = acc_i + (t * fi[block - k:2 * block - k]).astype(jnp.float32)
    er, ei = er_ref[...], ei_ref[...]
    prec = (jax.lax.Precision.DEFAULT if bf16
            else jax.lax.Precision.HIGHEST)
    if bf16:
        acc_r, acc_i, er, ei = _maybe_bf16(acc_r, acc_i, er, ei, bf16=True)
    dot = partial(jnp.dot, preferred_element_type=jnp.float32,
                  precision=prec)
    # y = v @ E with E = exp(+2πi·cc'/N): 4 real matmuls (er=cos, ei=sin)
    out_r[...] = dot(acc_r, er) - dot(acc_i, ei)
    out_i[...] = dot(acc_r, ei) + dot(acc_i, er)


def pallas_pfb(rows: jnp.ndarray, taps_kn, block: Optional[int] = None,
               interpret: Optional[bool] = None,
               precision: Optional[str] = None) -> jnp.ndarray:
    """Fused critically-sampled PFB analysis bank over commutated rows.

    ``rows``: ``[t + K−1, N]`` complex64 — the channelizer's commutated block
    matrix WITH its K−1 history rows in front (``ops/stages.channelizer_stage``
    builds exactly this from its carry). ``taps_kn``: ``[K, N]`` branch taps at
    depth k (``branchᵀ`` — may be a carry-resident traced array, f32 or bf16).
    Returns ``[t, N]`` complex64 — bit-comparable to the matmul path's
    ``ifft(v) * N`` (same math, fused op order; tolerance-pinned in
    tests/test_pallas.py). ``precision="bf16"`` casts MAC/matmul operands to
    bfloat16 with float32 accumulation.
    """
    block = _resolve_block("pfb", block)
    K, N = taps_kn.shape
    R = rows.shape[0]
    t = R - (K - 1)
    bt = max(int(block), K)             # alignment needs bt ≥ K−1; K is safe
    assert t >= 1, "need at least one output row"
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    bf16 = precision == "bf16"
    rr = jnp.real(rows).astype(jnp.float32)
    ri = jnp.imag(rows).astype(jnp.float32)
    # pad t up to a block multiple with zero rows (their outputs are trimmed)
    t_pad = -(-t // bt) * bt
    tail = t_pad - t
    if tail:
        z = jnp.zeros((tail, N), jnp.float32)
        rr = jnp.concatenate([rr, z])
        ri = jnp.concatenate([ri, z])
    # causal alignment: front-pad so output row s reads full[bt + s − k]
    z0 = jnp.zeros((bt - (K - 1), N), jnp.float32)
    xr = jnp.concatenate([z0, rr])
    xi = jnp.concatenate([z0, ri])
    # twiddle-feed IDFT matrix built IN TRACE (device constant — the axon
    # tunnel cannot ship host complex constants, ops/xfer.py). The phase
    # index reduces mod N BEFORE the float multiply: cc' grows to ~N² and
    # f32 rounding of 2π·cc'/N at large N costs ~10 dB per octave of N
    # (88 dB @ N=512 without the reduction vs near-exact with it)
    c = jnp.arange(N)
    ang = 2 * jnp.pi * (jnp.outer(c, c) % N) / N
    er = jnp.cos(ang).astype(jnp.float32)
    ei = jnp.sin(ang).astype(jnp.float32)
    grid = t_pad // bt
    kern = partial(_pfb_kernel, n_taps=K, block=bt, bf16=bf16)
    out_r, out_i = pl.pallas_call(
        kern,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((bt, N), lambda i: (i, 0)),       # prev rows (re)
            pl.BlockSpec((bt, N), lambda i: (i, 0)),       # prev rows (im)
            pl.BlockSpec((bt, N), lambda i: (i + 1, 0)),   # cur rows (re)
            pl.BlockSpec((bt, N), lambda i: (i + 1, 0)),   # cur rows (im)
            pl.BlockSpec((K, N), lambda i: (0, 0)),
            pl.BlockSpec((N, N), lambda i: (0, 0)),
            pl.BlockSpec((N, N), lambda i: (0, 0)),
        ],
        out_specs=[pl.BlockSpec((bt, N), lambda i: (i, 0)),
                   pl.BlockSpec((bt, N), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((t_pad, N), jnp.float32),
                   jax.ShapeDtypeStruct((t_pad, N), jnp.float32)],
        interpret=interpret,
    )(xr, xi, xr, xi, taps_kn, er, ei)
    return jax.lax.complex(out_r[:t], out_i[:t])


# ---------------------------------------------------------------------------
# fused FIR→decimate: shifted-row polyphase MACs at the decimated rate
# ---------------------------------------------------------------------------

def _poly_fir_kernel(prev, cur, w_ref, o_ref, *, m: int, block: int,
                     bf16: bool):
    """One grid step of ``block`` decimated outputs: ``y[q] = Σ_a
    rows[q + m − a] · W[a]`` over the stride-D row matrix — m+1 [block, D]·[D]
    matvecs, the in-kernel form of ``ops/stages._shifted_matvec``. A 3-D
    weight tensor (``W[a]``: [D, I] — the resampler's phase-tap matrix) runs
    the same accumulation as m+1 [block, D]·[D, I] matmuls."""
    full = jnp.concatenate([prev[...], cur[...]])            # [2·block, D]
    W = w_ref[...]                                           # [m+1, D]
    full, W = _maybe_bf16(full, W, bf16=bf16)
    prec = (jax.lax.Precision.DEFAULT if bf16
            else jax.lax.Precision.HIGHEST)
    dot = partial(jnp.dot, preferred_element_type=jnp.float32,
                  precision=prec)
    acc = dot(full[block:2 * block], W[0])
    for a in range(1, m + 1):                                # static unroll
        acc = acc + dot(full[block - a:2 * block - a], W[a])
    o_ref[...] = acc


def pallas_poly_fir(rows: jnp.ndarray, W, block: Optional[int] = None,
                    interpret: Optional[bool] = None,
                    precision: Optional[str] = None) -> jnp.ndarray:
    """Fused decimating FIR over the stride-D row matrix.

    ``rows``: ``[m + nq, D]`` float32 — the reshape of the history-extended
    input (``ext.reshape(-1, D)``, no copy); ``W``: ``[m+1, D]`` the shifted-row
    weight matrix (``ops/stages._poly_decim_weights`` — may be carry-resident,
    f32 or bf16, REAL taps only). Returns ``[nq]`` float32 decimated outputs —
    ntaps/D MACs per input sample with no full-rate intermediate (the fused
    FIR→decimate kernel). A 3-D ``W`` (``[m+1, D, I]`` — the resampler's
    phase-tap tensor, :func:`ops.stages.resample_stage`) returns ``[nq, I]``
    interpolated rows instead, same kernel. Complex frames run as two real
    passes at the stage level. ``precision="bf16"`` casts operands to
    bfloat16, accumulates f32.
    """
    block = _resolve_block("poly_fir", block)
    m1, D = W.shape[0], W.shape[1]
    m = m1 - 1
    nq = rows.shape[0] - m
    assert nq >= 1, "need at least one output row"
    bq = max(int(block), m)             # slice starts need bq ≥ m
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    rows = rows.astype(jnp.float32)
    nq_pad = -(-nq // bq) * bq
    tail = nq_pad - nq
    if tail:
        rows = jnp.concatenate([rows, jnp.zeros((tail, D), jnp.float32)])
    # causal alignment: front-pad so output q reads full[bq + q − a]
    xp = jnp.concatenate([jnp.zeros((bq - m, D), jnp.float32), rows])
    grid = nq_pad // bq
    kern = partial(_poly_fir_kernel, m=m, block=bq,
                   bf16=(precision == "bf16"))
    if W.ndim == 3:
        I = W.shape[2]
        w_spec = pl.BlockSpec((m + 1, D, I), lambda i: (0, 0, 0))
        out_specs = pl.BlockSpec((bq, I), lambda i: (i, 0))
        out_shape = jax.ShapeDtypeStruct((nq_pad, I), jnp.float32)
    else:
        w_spec = pl.BlockSpec((m + 1, D), lambda i: (0, 0))
        out_specs = pl.BlockSpec((bq,), lambda i: (i,))
        out_shape = jax.ShapeDtypeStruct((nq_pad,), jnp.float32)
    y = pl.pallas_call(
        kern,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((bq, D), lambda i: (i, 0)),       # prev rows
            pl.BlockSpec((bq, D), lambda i: (i + 1, 0)),   # cur rows
            w_spec,
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(xp, xp, W)
    return y[:nq]


# ---------------------------------------------------------------------------
# fused FIR→FFT: filter + windowed DFT with no HBM round-trip between them
# ---------------------------------------------------------------------------

def _fir_fft_kernel(prev_r, prev_i, cur_r, cur_i, taps_ref, er_ref, ei_ref,
                    out_r, out_i, *, n_taps: int, block: int, n_fft: int,
                    bf16: bool):
    """One grid step over ``block`` transform rows of ``n_fft`` samples: the
    FIR MAC over the row-major stream (sample shifts that cross a row
    boundary read the tail of the row above — the 1-D neighbour trick lifted
    to 2-D row tiles), then the forward DFT along rows as four real matmuls.
    The filtered rows live only in VMEM between the two halves — that
    intermediate is exactly the resident chain's fir→fft HBM edge."""
    ar = jnp.concatenate([prev_r[...], cur_r[...]])          # [2·block, n_fft]
    ai = jnp.concatenate([prev_i[...], cur_i[...]])
    taps = taps_ref[...]
    ar, ai, taps = _maybe_bf16(ar, ai, taps, bf16=bf16)

    def _shift(a, k):
        # S_k[r, c] = stream[r·n_fft + c − k] for the rows of the CUR tile:
        # the first k columns come from the row above (static slices only)
        if k == 0:
            return a[block:2 * block]
        left = a[block - 1:2 * block - 1, n_fft - k:]
        right = a[block:2 * block, :n_fft - k]
        return jnp.concatenate([left, right], axis=1)

    acc_r = jnp.zeros(cur_r.shape, jnp.float32)
    acc_i = jnp.zeros(cur_i.shape, jnp.float32)
    for k in range(n_taps):                                  # static unroll
        t = taps[k]
        acc_r = acc_r + (t * _shift(ar, k)).astype(jnp.float32)
        acc_i = acc_i + (t * _shift(ai, k)).astype(jnp.float32)
    er, ei = er_ref[...], ei_ref[...]
    prec = (jax.lax.Precision.DEFAULT if bf16
            else jax.lax.Precision.HIGHEST)
    if bf16:
        acc_r, acc_i, er, ei = _maybe_bf16(acc_r, acc_i, er, ei, bf16=True)
    dot = partial(jnp.dot, preferred_element_type=jnp.float32,
                  precision=prec)
    # Y = v @ E with E = exp(−2πi·cj/N) = er − i·ei (forward DFT sign)
    out_r[...] = dot(acc_r, er) + dot(acc_i, ei)
    out_i[...] = dot(acc_i, er) - dot(acc_r, ei)


def pallas_fir_fft(hist: jnp.ndarray, x: jnp.ndarray, taps, n_fft: int,
                   block: Optional[int] = None,
                   interpret: Optional[bool] = None,
                   precision: Optional[str] = None) -> jnp.ndarray:
    """Fused FIR → windowed forward FFT: ``fft(filtered.reshape(-1, n_fft))``
    flattened, without materializing the filtered stream in HBM.

    ``hist``: the previous ``n_taps−1`` input samples (carry-resident);
    ``x``: the frame, ``len(x) % n_fft == 0``; ``taps``: REAL taps (may be a
    traced carry array), ``n_taps ≤ n_fft`` (a shift never reaches past the
    row directly above). ``block`` counts transform ROWS per grid step
    (default: the tuned table's ``"fir_fft"`` shape — ragged row counts are
    zero-padded and trimmed). Complex frames filter both planes with the real
    taps and transform once. ``precision="bf16"`` casts the MAC and DFT
    matmul operands to bfloat16 with float32 accumulation.
    """
    block = _resolve_block("fir_fft", block)
    taps = jnp.asarray(taps)
    if not jnp.issubdtype(taps.dtype, jnp.bfloat16):
        taps = taps.astype(jnp.float32)
    nt = taps.shape[0]
    n = x.shape[0]
    assert n % n_fft == 0, f"frame ({n}) must be a multiple of n_fft ({n_fft})"
    assert nt <= n_fft, "fused FIR→FFT requires n_taps <= n_fft"
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    B = max(1, int(block))
    R = n // n_fft
    R_pad = -(-R // B) * B

    def _plane(p):
        # history row: the nt−1 carry samples land at the END of the row
        # directly above the frame's first row, zeros elsewhere
        pad_row = jnp.concatenate(
            [jnp.zeros(n_fft - (nt - 1), jnp.float32), p[:nt - 1]])
        rows = jnp.concatenate([pad_row[None, :],
                                p[nt - 1:].reshape(R, n_fft)])
        z0 = jnp.zeros((B - 1, n_fft), jnp.float32)
        ztail = jnp.zeros((R_pad - R, n_fft), jnp.float32)
        return jnp.concatenate([z0, rows, ztail])        # [B + R_pad, n_fft]

    if jnp.iscomplexobj(x):
        full = jnp.concatenate([hist, x])
        pr = _plane(full.real.astype(jnp.float32))
        pi = _plane(full.imag.astype(jnp.float32))
    else:
        full = jnp.concatenate([hist, x]).astype(jnp.float32)
        pr = _plane(full)
        pi = jnp.zeros_like(pr)
    # forward-DFT twiddles built IN TRACE, phase index reduced mod N before
    # the float multiply (same reasoning as pallas_pfb's IDFT matrix)
    c = jnp.arange(n_fft)
    ang = 2 * jnp.pi * (jnp.outer(c, c) % n_fft) / n_fft
    er = jnp.cos(ang).astype(jnp.float32)
    ei = jnp.sin(ang).astype(jnp.float32)
    grid = R_pad // B
    kern = partial(_fir_fft_kernel, n_taps=nt, block=B, n_fft=n_fft,
                   bf16=(precision == "bf16"))
    out_r, out_i = pl.pallas_call(
        kern,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((B, n_fft), lambda i: (i, 0)),      # prev rows (re)
            pl.BlockSpec((B, n_fft), lambda i: (i, 0)),      # prev rows (im)
            pl.BlockSpec((B, n_fft), lambda i: (i + 1, 0)),  # cur rows (re)
            pl.BlockSpec((B, n_fft), lambda i: (i + 1, 0)),  # cur rows (im)
            pl.BlockSpec((nt,), lambda i: (0,)),
            pl.BlockSpec((n_fft, n_fft), lambda i: (0, 0)),
            pl.BlockSpec((n_fft, n_fft), lambda i: (0, 0)),
        ],
        out_specs=[pl.BlockSpec((B, n_fft), lambda i: (i, 0)),
                   pl.BlockSpec((B, n_fft), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((R_pad, n_fft), jnp.float32),
                   jax.ShapeDtypeStruct((R_pad, n_fft), jnp.float32)],
        interpret=interpret,
    )(pr, pi, pr, pi, taps, er, ei)
    return jax.lax.complex(out_r[:R], out_i[:R]).reshape(-1)


# ---------------------------------------------------------------------------
# rotator / quadrature-demod inner loops over 2-D lane tiles
# ---------------------------------------------------------------------------

_LANES = 128      # TPU vector lane width — the tile minor dimension


def _rotator_kernel(xr, xi, p_ref, or_, oi_, *, block: int):
    """One grid step of ``block`` 128-lane rows: y = x · exp(i·(ph0 + inc·t))
    with the absolute sample index rebuilt from the grid position (2-D iota —
    1-D iota has no TPU lowering)."""
    ph0 = p_ref[0, 0]
    inc = p_ref[1, 0]
    g = pl.program_id(0)
    r = jax.lax.broadcasted_iota(jnp.float32, (block, _LANES), 0)
    c = jax.lax.broadcasted_iota(jnp.float32, (block, _LANES), 1)
    t = (g * block + r) * _LANES + c
    ph = ph0 + inc * t
    cr = jnp.cos(ph)
    si = jnp.sin(ph)
    or_[...] = xr[...] * cr - xi[...] * si
    oi_[...] = xr[...] * si + xi[...] * cr


def pallas_rotator(x: jnp.ndarray, ph0, inc,
                   block: Optional[int] = None,
                   interpret: Optional[bool] = None) -> jnp.ndarray:
    """Phase-ramp rotator ``y[t] = x[t] · exp(i·(ph0 + inc·t))`` over 2-D
    lane tiles — the in-kernel form of ``ops/stages.rotator_stage``'s inner
    loop. ``ph0``/``inc`` may be traced carry scalars; ragged frames are
    zero-padded to the tile grid and trimmed."""
    block = max(1, _resolve_block("rotator", block))
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n = x.shape[0]
    tile = block * _LANES
    n_pad = -(-n // tile) * tile
    xr = jnp.real(x).astype(jnp.float32)
    xi = jnp.imag(x).astype(jnp.float32)
    if n_pad != n:
        z = jnp.zeros(n_pad - n, jnp.float32)
        xr = jnp.concatenate([xr, z])
        xi = jnp.concatenate([xi, z])
    rows = n_pad // _LANES
    xr = xr.reshape(rows, _LANES)
    xi = xi.reshape(rows, _LANES)
    # carry scalars ride a broadcast VMEM row (no SMEM plumbing needed):
    # row 0 = ph0, row 1 = inc
    params = jnp.stack([jnp.broadcast_to(jnp.float32(ph0), (_LANES,)),
                        jnp.broadcast_to(jnp.float32(inc), (_LANES,))])
    kern = partial(_rotator_kernel, block=block)
    out_r, out_i = pl.pallas_call(
        kern,
        grid=(rows // block,),
        in_specs=[
            pl.BlockSpec((block, _LANES), lambda i: (i, 0)),
            pl.BlockSpec((block, _LANES), lambda i: (i, 0)),
            pl.BlockSpec((2, _LANES), lambda i: (0, 0)),
        ],
        out_specs=[pl.BlockSpec((block, _LANES), lambda i: (i, 0)),
                   pl.BlockSpec((block, _LANES), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((rows, _LANES), jnp.float32),
                   jax.ShapeDtypeStruct((rows, _LANES), jnp.float32)],
        interpret=interpret,
    )(xr, xi, params)
    y = jax.lax.complex(out_r.reshape(-1), out_i.reshape(-1))
    return y[:n].astype(jnp.complex64)


def _quad_demod_kernel(prev_r, prev_i, cur_r, cur_i, g_ref, o_ref, *,
                       block: int):
    """One grid step: y[t] = gain · atan2(im, re) of x[t]·conj(x[t−1]) — the
    one-sample shift reads the previous tile's last lane row (the FIR
    neighbour trick at shift 1, lifted to 2-D tiles)."""
    gain = g_ref[0, 0]
    ar = jnp.concatenate([prev_r[...], cur_r[...]])      # [2·block, 128]
    ai = jnp.concatenate([prev_i[...], cur_i[...]])

    def _shift1(a):
        left = a[block - 1:2 * block - 1, _LANES - 1:]
        right = a[block:2 * block, :_LANES - 1]
        return jnp.concatenate([left, right], axis=1)

    xr, xi = ar[block:2 * block], ai[block:2 * block]
    pr, pi = _shift1(ar), _shift1(ai)
    zr = xr * pr + xi * pi
    zi = xi * pr - xr * pi
    o_ref[...] = gain * jnp.arctan2(zi, zr)


def pallas_quad_demod(prev, x: jnp.ndarray, gain,
                      block: Optional[int] = None,
                      interpret: Optional[bool] = None) -> jnp.ndarray:
    """Quadrature (FM) demod ``y[t] = gain · angle(x[t] · conj(x[t−1]))``
    over 2-D lane tiles — the in-kernel form of
    ``ops/stages.quad_demod_stage``'s inner loop. ``prev`` is the carry's
    last sample of the previous frame (a traced scalar); ragged frames are
    zero-padded and trimmed."""
    block = max(1, _resolve_block("quad_demod", block))
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n = x.shape[0]
    tile = block * _LANES
    n_pad = -(-n // tile) * tile
    # the stream with its one-sample history in front; the pad keeps tile
    # rows aligned so sample t sits at flat index t + tile
    ext = jnp.concatenate([jnp.zeros(tile - 1, x.dtype),
                           jnp.reshape(prev, (1,)).astype(x.dtype), x])
    if n_pad != n:
        ext = jnp.concatenate([ext, jnp.zeros(n_pad - n, x.dtype)])
    xr = jnp.real(ext).astype(jnp.float32).reshape(-1, _LANES)
    xi = jnp.imag(ext).astype(jnp.float32).reshape(-1, _LANES)
    g = jnp.broadcast_to(jnp.float32(gain), (1, _LANES))
    kern = partial(_quad_demod_kernel, block=block)
    y = pl.pallas_call(
        kern,
        grid=(n_pad // tile,),
        in_specs=[
            pl.BlockSpec((block, _LANES), lambda i: (i, 0)),      # prev tile
            pl.BlockSpec((block, _LANES), lambda i: (i, 0)),
            pl.BlockSpec((block, _LANES), lambda i: (i + 1, 0)),  # cur tile
            pl.BlockSpec((block, _LANES), lambda i: (i + 1, 0)),
            pl.BlockSpec((1, _LANES), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((block, _LANES), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n_pad // _LANES, _LANES),
                                       jnp.float32),
        interpret=interpret,
    )(xr, xi, xr, xi, g)
    return y.reshape(-1)[:n]
