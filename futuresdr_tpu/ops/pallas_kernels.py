"""Pallas TPU kernels for the streaming hot ops.

Hand-written kernels for cases XLA's fusion doesn't cover well (the dataflow-shaped
kernel argument of Flex-TPU, arXiv:2407.08700):

* the short-tap streaming FIR (direct form beats FFT overlap-save below ~32 taps) as an
  unrolled shifted multiply-accumulate on the VPU, with the inter-block overlap handled
  by passing each grid step both its own input block and its left neighbour (no
  overlapping BlockSpecs needed);
* the fused PFB channelizer (:func:`pallas_pfb`): polyphase partition MAC + the
  twiddle-feed IDFT across branches as one kernel — the intermediate ``v[t, c]`` bank
  never round-trips HBM between the branch filters and the branch transform, which is
  exactly the HBM-bound half of the ``blocks/pfb.py`` / ``ops/stages.channelizer_stage``
  matmul path;
* the fused FIR→decimate kernel (:func:`pallas_poly_fir`): the shifted-row polyphase
  factorization of ``ops/stages._poly_decim_fir_stage`` computed at the DECIMATED rate
  inside one kernel (ntaps/D MACs per input sample, no full-rate intermediate).

Every kernel takes ``precision="bf16"`` for the interior-precision policy
(``ops/precision.py``): operands are cast to bfloat16 and accumulated in float32 —
on the MXU this is the native-speed pass; on CPU/interpret it applies exactly the same
quantization, so SNR calibration measures the real thing.

Falls back to interpret mode off-TPU — numerics are identical, so CI validates the kernel
on CPU and the same code runs compiled on the chip.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

__all__ = ["pallas_fir", "pallas_fir_continue", "pallas_fir_stage",
           "pallas_pfb", "pallas_poly_fir"]


def _maybe_bf16(*arrays, bf16: bool):
    if not bf16:
        return arrays if len(arrays) > 1 else arrays[0]
    out = tuple(a.astype(jnp.bfloat16) for a in arrays)
    return out if len(out) > 1 else out[0]


def _fir_kernel(prev_ref, cur_ref, taps_ref, o_ref, *, n_taps: int, block: int,
                bf16: bool = False):
    """One grid step: y[i] = Σ_k taps[k] · x[i − k] over this block, using the previous
    block's tail for the first n_taps−1 outputs."""
    full = jnp.concatenate([prev_ref[...], cur_ref[...]])       # [2·block]
    taps = taps_ref[...]
    full, taps = _maybe_bf16(full, taps, bf16=bf16)
    acc = jnp.zeros((block,), jnp.float32)
    base = block - (n_taps - 1)
    for k in range(n_taps):                                     # static unroll
        # static slice offsets (k is a Python int) — dynamic_slice has no Mosaic
        # TC lowering; static lax.slice does
        acc = acc + (taps[n_taps - 1 - k]
                     * full[base + k:base + k + block]).astype(jnp.float32)
    o_ref[...] = acc


def pallas_fir(x: jnp.ndarray, taps, block: int = 4096,
               interpret: Optional[bool] = None,
               precision: Optional[str] = None) -> jnp.ndarray:
    """Causal FIR of a float32 frame (zero initial state): len(x) must divide ``block``.

    Complex frames are filtered as two real passes at the wrapper level
    (:func:`pallas_fir_stage`). ``precision="bf16"`` runs the MAC with bfloat16
    operands and float32 accumulation (module docstring).
    """
    taps = jnp.asarray(taps)
    if not jnp.issubdtype(taps.dtype, jnp.bfloat16):
        taps = taps.astype(jnp.float32)
    n_taps = taps.shape[0]
    assert block >= n_taps, "block must exceed the tap count"
    n = x.shape[0]
    assert n % block == 0, f"frame ({n}) must be a multiple of block ({block})"
    grid = n // block
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    # block i sees: prev = x[(i-1)·block : i·block] (block 0 → block of zeros via the
    # leading pad), cur = x[i·block : (i+1)·block]
    xp = jnp.concatenate([jnp.zeros(block, x.dtype), x])
    kernel = partial(_fir_kernel, n_taps=n_taps, block=block,
                     bf16=(precision == "bf16"))
    return pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((block,), lambda i: (i,)),        # prev (offset by the pad)
            pl.BlockSpec((block,), lambda i: (i + 1,)),    # cur
            pl.BlockSpec((n_taps,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((n,), jnp.float32),
        interpret=interpret,
    )(xp, xp, taps)


def pallas_fir_continue(hist: jnp.ndarray, x: jnp.ndarray, taps: np.ndarray,
                        block: int = 4096,
                        precision: Optional[str] = None) -> jnp.ndarray:
    """Streaming continuation: filter frame ``x`` given the previous ``n_taps-1``
    input samples in ``hist``. Pads to the kernel's block granularity, runs complex
    frames as two real passes, and returns exactly ``len(x)`` aligned outputs.
    Shared by :func:`pallas_fir_stage` and ``stages.fir_stage(impl="pallas")``.
    ``taps`` may be a traced device array (carry-resident, for runtime tap swap) —
    only its static shape is read here."""
    taps = jnp.asarray(taps)
    if not jnp.issubdtype(taps.dtype, jnp.bfloat16):
        taps = taps.astype(jnp.float32)
    nt = taps.shape[0]
    ext = jnp.concatenate([hist, x])               # [(nt-1) + n]
    pad = (-ext.shape[0]) % block
    if pad:
        ext = jnp.concatenate([ext, jnp.zeros(pad, ext.dtype)])
    if jnp.iscomplexobj(x):
        yr = pallas_fir(ext.real, taps, block, precision=precision)
        yi = pallas_fir(ext.imag, taps, block, precision=precision)
        y = (yr + 1j * yi).astype(x.dtype)
    else:
        y = pallas_fir(ext, taps, block, precision=precision).astype(x.dtype)
    return y[nt - 1:nt - 1 + x.shape[0]]


def pallas_fir_stage(taps, block: int = 4096):
    """Streaming Stage (carry = tail samples) running the pallas kernel per frame; the
    drop-in alternative to :func:`futuresdr_tpu.ops.stages.fir_stage` for short taps."""
    from fractions import Fraction

    from .stages import Stage

    taps = np.asarray(taps, dtype=np.float32)
    nt = len(taps)

    def fn(carry, x):
        y = pallas_fir_continue(carry, x, taps, block)
        ext = jnp.concatenate([carry, x])
        return ext[ext.shape[0] - (nt - 1):], y

    def init_carry(dtype):
        return jnp.zeros(nt - 1, dtype=dtype)

    return Stage(fn, init_carry, Fraction(1, 1), None, 1, "pallas_fir")


# ---------------------------------------------------------------------------
# fused PFB channelizer: polyphase MAC + twiddle-feed IDFT in one kernel
# ---------------------------------------------------------------------------

def _pfb_kernel(prev_r, prev_i, cur_r, cur_i, taps_ref, er_ref, ei_ref,
                out_r, out_i, *, n_taps: int, block: int, bf16: bool):
    """One grid step over ``block`` commutated time rows: the branch-filter MAC
    ``v[s, c] = Σ_k taps[k, c] · rows[s + K−1 − k, c]`` (history rows ride in
    from the previous block, exactly the FIR kernel's neighbour trick), then
    the IDFT across branches as two real matmuls per output plane — the
    intermediate ``v`` bank lives only in VMEM."""
    fr = jnp.concatenate([prev_r[...], cur_r[...]])          # [2·block, N]
    fi = jnp.concatenate([prev_i[...], cur_i[...]])
    taps = taps_ref[...]                                     # [K, N]
    fr, fi, taps = _maybe_bf16(fr, fi, taps, bf16=bf16)
    acc_r = jnp.zeros(cur_r.shape, jnp.float32)
    acc_i = jnp.zeros(cur_i.shape, jnp.float32)
    for k in range(n_taps):                                  # static unroll
        t = taps[k]
        acc_r = acc_r + (t * fr[block - k:2 * block - k]).astype(jnp.float32)
        acc_i = acc_i + (t * fi[block - k:2 * block - k]).astype(jnp.float32)
    er, ei = er_ref[...], ei_ref[...]
    prec = (jax.lax.Precision.DEFAULT if bf16
            else jax.lax.Precision.HIGHEST)
    if bf16:
        acc_r, acc_i, er, ei = _maybe_bf16(acc_r, acc_i, er, ei, bf16=True)
    dot = partial(jnp.dot, preferred_element_type=jnp.float32,
                  precision=prec)
    # y = v @ E with E = exp(+2πi·cc'/N): 4 real matmuls (er=cos, ei=sin)
    out_r[...] = dot(acc_r, er) - dot(acc_i, ei)
    out_i[...] = dot(acc_r, ei) + dot(acc_i, er)


def pallas_pfb(rows: jnp.ndarray, taps_kn, block: int = 256,
               interpret: Optional[bool] = None,
               precision: Optional[str] = None) -> jnp.ndarray:
    """Fused critically-sampled PFB analysis bank over commutated rows.

    ``rows``: ``[t + K−1, N]`` complex64 — the channelizer's commutated block
    matrix WITH its K−1 history rows in front (``ops/stages.channelizer_stage``
    builds exactly this from its carry). ``taps_kn``: ``[K, N]`` branch taps at
    depth k (``branchᵀ`` — may be a carry-resident traced array, f32 or bf16).
    Returns ``[t, N]`` complex64 — bit-comparable to the matmul path's
    ``ifft(v) * N`` (same math, fused op order; tolerance-pinned in
    tests/test_pallas.py). ``precision="bf16"`` casts MAC/matmul operands to
    bfloat16 with float32 accumulation.
    """
    K, N = taps_kn.shape
    R = rows.shape[0]
    t = R - (K - 1)
    bt = max(int(block), K)             # alignment needs bt ≥ K−1; K is safe
    assert t >= 1, "need at least one output row"
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    bf16 = precision == "bf16"
    rr = jnp.real(rows).astype(jnp.float32)
    ri = jnp.imag(rows).astype(jnp.float32)
    # pad t up to a block multiple with zero rows (their outputs are trimmed)
    t_pad = -(-t // bt) * bt
    tail = t_pad - t
    if tail:
        z = jnp.zeros((tail, N), jnp.float32)
        rr = jnp.concatenate([rr, z])
        ri = jnp.concatenate([ri, z])
    # causal alignment: front-pad so output row s reads full[bt + s − k]
    z0 = jnp.zeros((bt - (K - 1), N), jnp.float32)
    xr = jnp.concatenate([z0, rr])
    xi = jnp.concatenate([z0, ri])
    # twiddle-feed IDFT matrix built IN TRACE (device constant — the axon
    # tunnel cannot ship host complex constants, ops/xfer.py). The phase
    # index reduces mod N BEFORE the float multiply: cc' grows to ~N² and
    # f32 rounding of 2π·cc'/N at large N costs ~10 dB per octave of N
    # (88 dB @ N=512 without the reduction vs near-exact with it)
    c = jnp.arange(N)
    ang = 2 * jnp.pi * (jnp.outer(c, c) % N) / N
    er = jnp.cos(ang).astype(jnp.float32)
    ei = jnp.sin(ang).astype(jnp.float32)
    grid = t_pad // bt
    kern = partial(_pfb_kernel, n_taps=K, block=bt, bf16=bf16)
    out_r, out_i = pl.pallas_call(
        kern,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((bt, N), lambda i: (i, 0)),       # prev rows (re)
            pl.BlockSpec((bt, N), lambda i: (i, 0)),       # prev rows (im)
            pl.BlockSpec((bt, N), lambda i: (i + 1, 0)),   # cur rows (re)
            pl.BlockSpec((bt, N), lambda i: (i + 1, 0)),   # cur rows (im)
            pl.BlockSpec((K, N), lambda i: (0, 0)),
            pl.BlockSpec((N, N), lambda i: (0, 0)),
            pl.BlockSpec((N, N), lambda i: (0, 0)),
        ],
        out_specs=[pl.BlockSpec((bt, N), lambda i: (i, 0)),
                   pl.BlockSpec((bt, N), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((t_pad, N), jnp.float32),
                   jax.ShapeDtypeStruct((t_pad, N), jnp.float32)],
        interpret=interpret,
    )(xr, xi, xr, xi, taps_kn, er, ei)
    return jax.lax.complex(out_r[:t], out_i[:t])


# ---------------------------------------------------------------------------
# fused FIR→decimate: shifted-row polyphase MACs at the decimated rate
# ---------------------------------------------------------------------------

def _poly_fir_kernel(prev, cur, w_ref, o_ref, *, m: int, block: int,
                     bf16: bool):
    """One grid step of ``block`` decimated outputs: ``y[q] = Σ_a
    rows[q + m − a] · W[a]`` over the stride-D row matrix — m+1 [block, D]·[D]
    matvecs, the in-kernel form of ``ops/stages._shifted_matvec``."""
    full = jnp.concatenate([prev[...], cur[...]])            # [2·block, D]
    W = w_ref[...]                                           # [m+1, D]
    full, W = _maybe_bf16(full, W, bf16=bf16)
    prec = (jax.lax.Precision.DEFAULT if bf16
            else jax.lax.Precision.HIGHEST)
    dot = partial(jnp.dot, preferred_element_type=jnp.float32,
                  precision=prec)
    acc = dot(full[block:2 * block], W[0])
    for a in range(1, m + 1):                                # static unroll
        acc = acc + dot(full[block - a:2 * block - a], W[a])
    o_ref[...] = acc


def pallas_poly_fir(rows: jnp.ndarray, W, block: int = 1024,
                    interpret: Optional[bool] = None,
                    precision: Optional[str] = None) -> jnp.ndarray:
    """Fused decimating FIR over the stride-D row matrix.

    ``rows``: ``[m + nq, D]`` float32 — the reshape of the history-extended
    input (``ext.reshape(-1, D)``, no copy); ``W``: ``[m+1, D]`` the shifted-row
    weight matrix (``ops/stages._poly_decim_weights`` — may be carry-resident,
    f32 or bf16, REAL taps only). Returns ``[nq]`` float32 decimated outputs —
    ntaps/D MACs per input sample with no full-rate intermediate (the fused
    FIR→decimate kernel). Complex frames run as two real passes at the stage
    level. ``precision="bf16"`` casts operands to bfloat16, accumulates f32.
    """
    m1, D = W.shape
    m = m1 - 1
    nq = rows.shape[0] - m
    assert nq >= 1, "need at least one output row"
    bq = max(int(block), m)             # slice starts need bq ≥ m
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    rows = rows.astype(jnp.float32)
    nq_pad = -(-nq // bq) * bq
    tail = nq_pad - nq
    if tail:
        rows = jnp.concatenate([rows, jnp.zeros((tail, D), jnp.float32)])
    # causal alignment: front-pad so output q reads full[bq + q − a]
    xp = jnp.concatenate([jnp.zeros((bq - m, D), jnp.float32), rows])
    grid = nq_pad // bq
    kern = partial(_poly_fir_kernel, m=m, block=bq,
                   bf16=(precision == "bf16"))
    y = pl.pallas_call(
        kern,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((bq, D), lambda i: (i, 0)),       # prev rows
            pl.BlockSpec((bq, D), lambda i: (i + 1, 0)),   # cur rows
            pl.BlockSpec((m + 1, D), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((bq,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((nq_pad,), jnp.float32),
        interpret=interpret,
    )(xp, xp, W)
    return y[:nq]
