"""Host↔device transfer shim: complex streams ride as float32 pairs.

The axon TPU tunnel cannot materialise ``device_put`` complex64 buffers: the put itself
reports success (it is async), on-device compute over the buffer runs, but ANY
device-to-host readback whose ancestry includes such a buffer fails with
``UNIMPLEMENTED: TPU backend error`` (measured round 2; see ``docs/tpu_notes.md``).
Complex arrays *created on device* (by an XLA program, including in-trace constants)
are fine in both directions.

So every host→device crossing of a complex array ships the interleaved re/im float32
pairs (a zero-copy ``view`` on the host) and forms the complex array with one jitted
``lax.complex`` on device; device→host splits ``.real``/``.imag`` on device and joins on
the host. Cost on a healthy backend: one trivially fused kernel per transfer — so the
shim is on for every non-CPU platform rather than probing (a probe would poison the
process on the broken one).

This mirrors how the reference treats its interleaved-IQ DMA formats (seify streams are
f32-pair interleaved on the wire, ``src/blocks/seify/source.rs``): pairs are the
portable wire layout; the "complex" view is formed device-side.
"""

from __future__ import annotations

import numpy as np

__all__ = ["to_device", "to_host", "start_host_transfer", "split_complex_platform"]

_join_jit = None
_split_jit = None


def _jits():
    global _join_jit, _split_jit
    if _join_jit is None:
        import jax

        _join_jit = jax.jit(lambda p: jax.lax.complex(p[..., 0], p[..., 1]))
        _split_jit = jax.jit(lambda x: (x.real, x.imag))
    return _join_jit, _split_jit


def split_complex_platform(platform: str) -> bool:
    """Pair-shipping applies on every accelerator platform (cpu transfers are sane)."""
    return platform != "cpu"


def h2d_needs_staging(platform: str) -> bool:
    """Must a ring-buffer view be copied out before being handed to
    ``device_put`` (and the ring position consumed)? ALWAYS — on every
    platform. Single source of truth for TpuKernel/PpKernel.

    On accelerators the H2D is async and reads the source buffer later. The
    CPU backend is the trap: ``device_put`` of a numpy view usually copies
    eagerly, but a 64-BYTE-ALIGNED view is zero-copy BORROWED
    (``unsafe_buffer_pointer() == view.ctypes.data``) — and ring buffers are
    page-aligned memfd mappings, so frame-sized slices are almost always
    aligned. A borrowed frame aliases ring memory the upstream writer then
    overwrites → flaky corruption of in-flight frames (round-5 regression:
    ``test_tpu_kernel_block_in_flowgraph`` failed ~50% after the copy was
    elided on "cpu"; probes with ``np.zeros`` buffers missed it because the
    allocator happened to return misaligned bases). Forcing misalignment
    would just move the same copy inside jax, so the explicit staging copy
    stays."""
    return True


def _device_platform(device=None) -> str:
    import jax

    if device is None:
        return jax.default_backend()
    if hasattr(device, "platform"):          # a Device
        return device.platform
    try:                                      # a Sharding
        devs = list(device.device_set)
        if devs:
            return devs[0].platform
    except AttributeError:
        pass
    return jax.default_backend()


def to_device(arr, device=None):
    """``jax.device_put`` that is safe for complex dtypes on broken-transfer backends."""
    import jax

    if isinstance(arr, jax.Array):
        # already device-resident: device_put is a same-device no-op (or a safe D2D
        # move); forcing it through np.asarray would be a blocking D2H round-trip
        return jax.device_put(arr, device) if device is not None else arr
    a = np.asarray(arr)
    if np.issubdtype(a.dtype, np.complexfloating) and \
            split_complex_platform(_device_platform(device)):
        f = np.float64 if a.dtype == np.complex128 else np.float32
        pairs = np.ascontiguousarray(a).view(f).reshape(a.shape + (2,))
        join, _ = _jits()
        return join(jax.device_put(pairs, device))
    return jax.device_put(a, device)


def to_host(arr) -> np.ndarray:
    """``np.asarray`` that reads complex device arrays back as two float transfers."""
    return start_host_transfer(arr)()


def start_host_transfer(arr):
    """Begin a NON-blocking D2H of ``arr``; returns a zero-arg ``finish()`` that
    blocks until the copy lands and yields the numpy array.

    This is how a drain loop overlaps transfers: start transfers for every
    completed frame first, then finish them oldest-first — frame t+1's D2H rides
    the wire while the caller is still consuming frame t (the role of the
    reference's circulating empty/full staging buffers, ``buffer/vulkan/d2h.rs``).
    :func:`to_host` is this with an immediate finish; all complex-pair-shim and
    platform logic lives here, once."""
    import jax

    if not isinstance(arr, jax.Array):
        # host data: the jitted split() would device_put the raw complex array —
        # the exact broken path this shim avoids
        return lambda: np.asarray(arr)
    dt = np.dtype(getattr(arr, "dtype", np.float32))
    if np.issubdtype(dt, np.complexfloating):
        try:
            devs = list(arr.devices())
            platform = devs[0].platform if devs else _device_platform()
        except Exception:
            platform = _device_platform()
        if split_complex_platform(platform):
            _, split = _jits()
            r, i = split(arr)                    # async device-side split
            for part in (r, i):
                if hasattr(part, "copy_to_host_async"):
                    part.copy_to_host_async()

            def finish(r=r, i=i):
                out = np.empty(r.shape, dtype=dt)
                out.real = np.asarray(r)
                out.imag = np.asarray(i)
                return out

            return finish
    if hasattr(arr, "copy_to_host_async"):
        arr.copy_to_host_async()
    return lambda: np.asarray(arr)
