"""Host↔device transfer shim: complex streams ride as float32 pairs.

The axon TPU tunnel cannot materialise ``device_put`` complex64 buffers: the put itself
reports success (it is async), on-device compute over the buffer runs, but ANY
device-to-host readback whose ancestry includes such a buffer fails with
``UNIMPLEMENTED: TPU backend error`` (measured round 2; see ``docs/tpu_notes.md``).
Complex arrays *created on device* (by an XLA program, including in-trace constants)
are fine in both directions.

So every host→device crossing of a complex array ships the interleaved re/im float32
pairs (a zero-copy ``view`` on the host) and forms the complex array with one jitted
``lax.complex`` on device; device→host splits ``.real``/``.imag`` on device and joins on
the host. Cost on a healthy backend: one trivially fused kernel per transfer — so the
shim is on for every non-CPU platform rather than probing (a probe would poison the
process on the broken one).

This mirrors how the reference treats its interleaved-IQ DMA formats (seify streams are
f32-pair interleaved on the wire, ``src/blocks/seify/source.rs``): pairs are the
portable wire layout; the "complex" view is formed device-side.
"""

from __future__ import annotations

import random as _random
import threading
import time
from typing import Optional

import numpy as np

from ..log import logger
from ..telemetry import prom as _prom
from ..telemetry.spans import recorder as _trace_recorder

__all__ = ["to_device", "to_host", "start_host_transfer", "start_device_transfer",
           "start_device_transfer_parts", "start_host_transfer_parts",
           "split_complex_platform", "set_fake_link", "fake_link",
           "TransferError", "FakeLinkFault", "classify_transfer_error",
           "PackedLayout"]

log = logger("ops.xfer")
_trace = _trace_recorder()
# link-plane metrics (always on; updates are per-frame, not per-sample)
_XFER_BYTES = _prom.counter(
    "fsdr_xfer_bytes_total", "bytes started on the host-device link",
    ("direction",))
_XFER_TRANSFERS = _prom.counter(
    "fsdr_xfer_transfers_total", "transfers started on the host-device link",
    ("direction",))
# physical per-buffer starts: how many device_put/fetch calls actually hit
# the link. A coalesced (packed) frame counts ONE h2d start; the per-part
# path counts len(parts). The transfers counter above stays frame-granular —
# starts/transfers is the coalescing ratio the uplink gate reads.
_XFER_STARTS = _prom.counter(
    "fsdr_xfer_starts_total",
    "physical per-buffer put/fetch starts on the host-device link",
    ("direction",))
# per-transfer duration histogram (telemetry/hist.py log2 buckets) — always
# on like the counters. Under the fake link the observed duration clamps to
# the modeled wire window (true occupancy); on real backends it is the
# stage→finish() DWELL as the drain loop experiences it, which includes any
# read-ahead queue wait — a latency signal, not a pure wire-time measurement
# (same semantics as the H2D/D2H trace spans, docs/observability.md)
_XFER_HIST = _prom.histogram(
    "fsdr_xfer_seconds",
    "host-device transfer duration, start to landing (fake link: modeled "
    "wire window)", ("direction",))
_H2D_HIST = _XFER_HIST.labels(direction="h2d")
_D2H_HIST = _XFER_HIST.labels(direction="d2h")
# transient-retry billing (docs/robustness.md): one tick per retried attempt,
# so a seeded fault campaign's retry count is auditable from /metrics
_RETRIES = _prom.counter(
    "fsdr_retries_total", "transient host-device transfer retries",
    ("direction",))
_RETRY_H2D = _RETRIES.labels(direction="h2d")
_RETRY_D2H = _RETRIES.labels(direction="d2h")


# ---------------------------------------------------------------------------
# transfer retry: transient-vs-fatal classification + backoff under deadline
# ---------------------------------------------------------------------------

class TransferError(RuntimeError):
    """Fatal transfer failure: non-transient cause, retry budget exhausted,
    or the per-transfer deadline (``xfer_deadline``) blown."""


class FakeLinkFault(RuntimeError):
    """Transient fault injected by the seeded fake link (CI retry testing)."""


#: lowercase substrings marking a backend/driver error as WORTH retrying —
#: gRPC retryable codes the tunnel surfaces plus classic socket transients
_TRANSIENT_MARKERS = ("unavailable", "resource_exhausted", "deadline_exceeded",
                      "aborted", "connection reset", "temporarily",
                      "try again", "timed out")


def classify_transfer_error(e: BaseException) -> bool:
    """True when ``e`` is transient (worth a retry): injected link faults
    (``FakeLinkFault``, transient ``runtime/faults.py`` injections) and
    backend errors matching :data:`_TRANSIENT_MARKERS`. A ``TransferError``
    is always fatal (it already wraps an exhausted retry loop)."""
    if isinstance(e, FakeLinkFault):
        return True
    if isinstance(e, TransferError):
        return False
    transient = getattr(e, "transient", None)     # InjectedFault carries it
    if transient is not None:
        return bool(transient)
    msg = str(e).lower()
    return any(m in msg for m in _TRANSIENT_MARKERS)


#: jitter source for retry backoff — deliberately NOT the fault-injection rng:
#: jitter shifts retry *timing*, never the retry *count*, so seeded campaigns
#: stay deterministic in their observable outcome
_jitter_rng = _random.Random(0x5FDB7)


def _with_retry(direction: str, attempt_fn):
    """Run one transfer attempt with transient-classified retries: jittered
    exponential backoff (``xfer_backoff`` base) under the retry budget
    (``xfer_retries``) and the per-transfer deadline (``xfer_deadline``).
    ``attempt_fn`` must be idempotent — H2D re-puts the host STAGING copies
    (the non-aliasing encode path makes the frames immutable by contract) and
    D2H re-reads the still-resident device array, so a retried frame is
    bit-identical to an unfaulted one."""
    from ..config import config
    c = config()
    retries = int(c.get("xfer_retries", 3))
    backoff = float(c.get("xfer_backoff", 0.005))
    deadline_s = float(c.get("xfer_deadline", 30.0))
    t0 = time.perf_counter()
    attempt = 0
    ctr = _RETRY_H2D if direction == "h2d" else _RETRY_D2H
    while True:
        try:
            return attempt_fn()
        except Exception as e:
            attempt += 1
            if not classify_transfer_error(e):
                raise
            pause = min(backoff * (1 << (attempt - 1)), 1.0)
            pause *= 0.5 + _jitter_rng.random()
            out_of_budget = attempt > retries
            past_deadline = deadline_s > 0 and \
                time.perf_counter() - t0 + pause > deadline_s
            if out_of_budget or past_deadline:
                raise TransferError(
                    f"{direction} transfer failed after {attempt} attempt(s) "
                    f"({'retry budget' if out_of_budget else 'deadline'} "
                    f"exhausted): {e!r}") from e
            ctr.inc()
            log.warning("%s transfer attempt %d failed transiently (%r): "
                        "retrying in %.1f ms", direction, attempt, e,
                        pause * 1e3)
            time.sleep(pause)


_faults_mod = None


def _check_injected(direction: str) -> None:
    """Raise any armed injected fault for this crossing: the fake link's own
    seeded fault model plus the ``h2d``/``d2h``/``link`` sites of
    ``runtime/faults.py`` (imported lazily — ops must not import runtime at
    module level)."""
    link = _fake_link
    if link is not None:
        link.maybe_fault(direction)
    global _faults_mod
    if _faults_mod is None:
        from ..runtime import faults as _fm
        _faults_mod = _fm
    p = _faults_mod.plan()
    if p.armed():
        p.maybe(direction)
        p.maybe("link")


def _span_bounds_ns(t0_ns: int, service: float, deadline: float) -> tuple:
    """``(start_ns, end_ns)`` of a transfer span, clamped to the fake link's
    modeled wire occupancy when one exists: the span STARTS when the wire
    begins servicing these bytes (not when they were queued behind an earlier
    frame — same-lane queue wait double-counted into span sums would inflate
    the overlap ratio) and ENDS at the landing deadline (a finish() called
    late must not inflate the lane's busy interval either)."""
    end = time.perf_counter_ns()
    if deadline:
        dl = int(deadline * 1e9)       # perf_counter and perf_counter_ns share
        if t0_ns < dl < end:           # one epoch (time module contract)
            end = dl
    start = t0_ns
    if service:
        sv = int(service * 1e9)
        if t0_ns < sv:
            start = min(sv, end)
    return start, end

_join_jit = None
_split_jit = None


def _jits():
    global _join_jit, _split_jit
    if _join_jit is None:
        import jax

        _join_jit = jax.jit(lambda p: jax.lax.complex(p[..., 0], p[..., 1]))
        _split_jit = jax.jit(lambda x: (x.real, x.imag))
    return _join_jit, _split_jit


class _FakeLink:
    """Rate-throttled fake link for deterministic CI pipelining tests.

    Models each direction as a serial wire: a transfer of ``nbytes`` occupies
    the direction for ``nbytes/rate`` seconds starting when the wire frees up.
    ``reserve`` is called at transfer START and returns the wall-clock deadline
    the bytes land at; ``finish()`` sleeps out the remainder. No threads — the
    timeline alone decides whether a drain loop overlapped its transfers:
    serialized loops pay Σ(h2d+compute+d2h), pipelined ones pay ≈ the max.

    ``fault_rate``/``fault_seed`` add a seeded fault model: each transfer
    START draws from a per-direction ``random.Random(f"{seed}:{dir}")``
    stream and raises a transient :class:`FakeLinkFault` on a hit — so the
    retry path is CI-testable deterministically (same seed + same transfer
    sequence → same faults → same retry count, billed on
    ``fsdr_retries_total{direction}``). Per-direction streams keep the draw
    order independent of h2d/d2h thread interleaving."""

    def __init__(self, h2d_bps: Optional[float], d2h_bps: Optional[float],
                 fault_rate: float = 0.0, fault_seed: int = 0):
        self.h2d_bps = h2d_bps
        self.d2h_bps = d2h_bps
        self._lock = threading.Lock()
        self._busy = {"h2d": 0.0, "d2h": 0.0}
        self.fault_rate = float(fault_rate or 0.0)
        self.fault_seed = int(fault_seed)
        # the draw machinery IS runtime/faults.py's SiteInjector (one seeded
        # Bernoulli implementation in the codebase, billed on
        # fsdr_faults_injected_total{site="link:<dir>"}); this class only
        # wraps the fire into its own FakeLinkFault surface
        from ..runtime.faults import SiteInjector
        self._injectors = {
            d: SiteInjector(f"link:{d}", self.fault_rate, self.fault_seed,
                            max_faults=None, transient=True)
            for d in ("h2d", "d2h")}

    @property
    def faults(self):
        """``{direction: fired}`` — campaign introspection."""
        return {d: inj.fired for d, inj in self._injectors.items()}

    def maybe_fault(self, direction: str) -> None:
        """One seeded per-direction draw at transfer start; raises on a hit."""
        if not self.fault_rate:
            return
        from ..runtime.faults import InjectedFault
        try:
            self._injectors[direction].check()
        except InjectedFault as e:
            raise FakeLinkFault(
                f"injected fake-link fault on {direction} (#{e.seq}, "
                f"seed {self.fault_seed})") from e

    def reserve(self, direction: str, nbytes: int) -> tuple:
        """Returns ``(service_start, deadline)``: the wire begins moving these
        bytes at ``service_start`` (after any queued predecessor) and lands
        them at ``deadline`` — both wall-clock ``perf_counter`` values."""
        rate = self.h2d_bps if direction == "h2d" else self.d2h_bps
        if not rate:
            return (0.0, 0.0)
        with self._lock:
            start = max(time.perf_counter(), self._busy[direction])
            self._busy[direction] = start + nbytes / rate
            return (start, self._busy[direction])


_fake_link: Optional[_FakeLink] = None


def set_fake_link(h2d_bps: Optional[float] = None,
                  d2h_bps: Optional[float] = None,
                  fault_rate: float = 0.0, fault_seed: int = 0):
    """Install (or with no args remove) a throttled fake link on every transfer
    started through this module; returns the previous link for restoration.
    CI/testing only — lets the CPU backend reproduce the tunnel's link-bound
    streamed regime deterministically. ``fault_rate``/``fault_seed`` arm the
    link's seeded fault model (see :class:`_FakeLink`) so the transfer-retry
    path is exercised deterministically too."""
    global _fake_link
    prev = _fake_link
    _fake_link = _FakeLink(h2d_bps, d2h_bps, fault_rate, fault_seed) \
        if (h2d_bps or d2h_bps or fault_rate) else None
    return prev


def fake_link() -> Optional[_FakeLink]:
    return _fake_link


def _reserve(direction: str, nbytes: int) -> tuple:
    """``(service_start, deadline)`` of the modeled wire; zeros without a link."""
    return _fake_link.reserve(direction, nbytes) if _fake_link else (0.0, 0.0)


def _wait_deadline(deadline: float) -> None:
    """Wait out a fake-link deadline PRECISELY: plain ``time.sleep`` overshoots
    by 1-4 ms on Linux, a proportionally larger tax on short (small-frame /
    compact-wire) transfers — enough to skew A/B wire-format ratios. Sleep to
    ~1.5 ms short of the deadline, then yield-spin the remainder."""
    if not deadline:
        return
    while True:
        d = deadline - time.perf_counter()
        if d <= 0:
            return
        time.sleep(d - 0.0015 if d > 0.0015 else 0.0)


_fetch_pool = None
_fetch_pool_lock = threading.Lock()


def _start_fetch(part):
    """Begin the D2H of one device array NOW; returns ``thunk() -> np.ndarray``.

    ``copy_to_host_async`` when the array type has it; otherwise the fetch is
    submitted to a small thread pool immediately — the fallback used to fetch
    synchronously inside ``finish()``, serializing oldest-first and losing the
    overlap the caller staged for (round-6 fix).

    The thunk RETRIES transient materialization failures: on a real flaky
    link the error surfaces when the bytes land (inside ``finish()``), not at
    start — the device array stays resident, so re-reading it is idempotent
    and the retried frame is bit-identical."""
    if hasattr(part, "copy_to_host_async"):
        part.copy_to_host_async()
        # the FIRST _with_retry attempt is the original materialization, so
        # the budget/billing contract matches the transfer-start paths
        # exactly: xfer_retries retries, each billed once
        return lambda p=part: _with_retry("d2h", lambda: np.asarray(p))
    global _fetch_pool
    if _fetch_pool is None:
        with _fetch_pool_lock:   # BLOCKING kernel threads race the first fetch
            if _fetch_pool is None:
                from concurrent.futures import ThreadPoolExecutor
                _fetch_pool = ThreadPoolExecutor(max_workers=2,
                                                 thread_name_prefix="fsdr-d2h")
    fut = _fetch_pool.submit(np.asarray, part)

    def pool_thunk(p=part, f=fut):
        # first attempt consumes the already-started pool fetch; retries
        # re-read the still-resident device array inline — same standard
        # budget/billing as every other retry path
        pending = [f]

        def attempt():
            if pending:
                return pending.pop().result()
            return np.asarray(p)

        return _with_retry("d2h", attempt)
    return pool_thunk


def split_complex_platform(platform: str) -> bool:
    """Pair-shipping applies on every accelerator platform (cpu transfers are sane)."""
    return platform != "cpu"


def h2d_needs_staging(platform: str) -> bool:
    """Must a ring-buffer view be copied out before being handed to
    ``device_put`` (and the ring position consumed)? ALWAYS — on every
    platform. Single source of truth for TpuKernel/PpKernel.

    On accelerators the H2D is async and reads the source buffer later. The
    CPU backend is the trap: ``device_put`` of a numpy view usually copies
    eagerly, but a 64-BYTE-ALIGNED view is zero-copy BORROWED
    (``unsafe_buffer_pointer() == view.ctypes.data``) — and ring buffers are
    page-aligned memfd mappings, so frame-sized slices are almost always
    aligned. A borrowed frame aliases ring memory the upstream writer then
    overwrites → flaky corruption of in-flight frames (round-5 regression:
    ``test_tpu_kernel_block_in_flowgraph`` failed ~50% after the copy was
    elided on "cpu"; probes with ``np.zeros`` buffers missed it because the
    allocator happened to return misaligned bases). Forcing misalignment
    would just move the same copy inside jax, so the explicit staging copy
    stays."""
    return True


def _device_platform(device=None) -> str:
    import jax

    if device is None:
        return jax.default_backend()
    if hasattr(device, "platform"):          # a Device
        return device.platform
    try:                                      # a Sharding
        devs = list(device.device_set)
        if devs:
            return devs[0].platform
    except AttributeError:
        pass
    return jax.default_backend()


def start_device_transfer_parts(parts, device=None):
    """Begin a NON-blocking H2D of pre-encoded wire parts (``ops/wire.py``
    layouts — plain real/int numpy arrays, never complex); returns a zero-arg
    ``finish()`` that blocks until the payload is device-resident and yields
    the tuple of device arrays.

    This is the H2D symmetric of :func:`start_host_transfer` — the primitive
    that lets a drain loop keep H2D(t+1) on the wire while frame t computes
    (``device_put`` is async on accelerator backends; the fake link models the
    wire time for deterministic CPU-backend tests). ``device`` may be a Device
    or a Sharding."""
    import jax

    host = [np.asarray(p) for p in parts]
    nbytes = sum(p.nbytes for p in host)
    _XFER_BYTES.inc(nbytes, direction="h2d")
    _XFER_TRANSFERS.inc(direction="h2d")
    _XFER_STARTS.inc(len(host), direction="h2d")

    def attempt():
        # idempotent: re-puts the immutable host STAGING copies — a retried
        # frame lands bit-identical to an unfaulted one
        _check_injected("h2d")
        return tuple(jax.device_put(p, device) for p in host)

    devs = _with_retry("h2d", attempt)
    # the wire is reserved AFTER the attempt succeeds: faulted attempts spend
    # backoff wall-clock, not modeled wire occupancy
    service, deadline = _reserve("h2d", nbytes)
    t0 = time.perf_counter_ns()

    def finish():
        _wait_deadline(deadline)
        s, e = _span_bounds_ns(t0, service, deadline)
        _H2D_HIST.observe((e - s) * 1e-9)
        if _trace.enabled:
            _trace.complete("tpu", "H2D", s, end_ns=e, args={"bytes": nbytes})
        return devs

    # modeled wire window (service start, landing deadline) — zeros without a
    # fake link. The streamed credit controller (tpu/kernel_block.py) reads
    # consecutive windows to detect up-link idle gaps; symmetric with the
    # D2H finishes' _wire attribute below.
    finish._wire = (service, deadline)
    return finish


class PackedLayout:
    """Offset table of ONE dispatch group's coalesced H2D transfer buffer.

    The uplink coalescing plane: a quantizing wire ships several parts per
    frame (int payload + scale; a megabatch K-stack per part), and each part
    is a separate ``device_put`` — a separate link start. ``PackedLayout``
    fixes the byte layout that packs every part of a dispatch group into one
    contiguous uint8 buffer: slot ``i`` holds part ``i``'s bytes at a
    64-byte-aligned offset (TPU/infeed-friendly, and it keeps every int16
    payload view naturally aligned). The host side writes payloads in place
    via ``ops/arena.PackedAlloc``; the device side recovers the parts with
    :meth:`unpack_jax` — a slice→bitcast prolog fused into the wired program
    by ``Pipeline.compile_wired(packed=...)``, so the unpack costs one fused
    reshape pass, not a dispatch.

    The layout is a pure function of the wire codec + frame shape (probed
    from an encode of zeros), so host packer and device unpacker can never
    disagree, and a replayed frame re-ships the EXACT packed bytes the first
    attempt shipped (the replay log retains the packed buffer, not the
    parts).
    """

    ALIGN = 64
    __slots__ = ("slots", "nbytes")

    def __init__(self, slots, nbytes):
        self.slots = tuple(slots)     # (shape, dtype, offset, nbytes) each
        self.nbytes = int(nbytes)

    @classmethod
    def from_parts(cls, parts) -> "PackedLayout":
        """Layout for a concrete part tuple (shapes/dtypes as shipped)."""
        slots, off = [], 0
        for p in parts:
            p = np.asarray(p)
            slots.append((tuple(p.shape), np.dtype(p.dtype), off,
                          int(p.nbytes)))
            off += -(-max(p.nbytes, 1) // cls.ALIGN) * cls.ALIGN
        return cls(slots, off)

    @classmethod
    def probe(cls, wire, frame_size: int, in_dtype, k: int = 1):
        """Layout for ``wire``'s encode of a ``frame_size`` frame (``k > 1``:
        the megabatch stack — every part gains a leading ``[k]`` axis), or
        ``None`` when the wire ships a single part (nothing to coalesce —
        packing a lone payload would only add a copy)."""
        parts = wire.encode_host(np.zeros(frame_size, dtype=in_dtype))
        parts = [np.asarray(p) for p in parts]
        if len(parts) < 2:
            return None
        if k > 1:
            parts = [np.broadcast_to(p, (int(k),) + p.shape) for p in parts]
        return cls.from_parts(parts)

    @property
    def key(self):
        """Hashable identity (the wired-program cache key extension)."""
        return self.slots

    def matches(self, parts) -> bool:
        """Do ``parts`` fit this layout slot-for-slot (shape and dtype)?"""
        if len(parts) != len(self.slots):
            return False
        return all(tuple(np.shape(p)) == sh and np.dtype(
            getattr(p, "dtype", type(p))) == dt
            for p, (sh, dt, _o, _n) in zip(parts, self.slots))

    def pack(self, parts, out: np.ndarray) -> np.ndarray:
        """Copy any part not already resident in its slot into ``out`` (a
        ``(nbytes,)`` uint8 buffer) and zero the alignment gaps, so the
        shipped bytes are a deterministic function of the parts. Parts the
        encoder already wrote through a slot view (``PackedAlloc``) are left
        untouched."""
        assert out.nbytes >= self.nbytes, (out.nbytes, self.nbytes)
        end = 0
        for p, (sh, dt, off, nb) in zip(parts, self.slots):
            p = np.asarray(p)
            if end < off:                       # alignment gap before slot
                out[end:off] = 0
            view = out[off:off + nb].view(dt).reshape(sh)
            if not np.shares_memory(view, p):
                view[...] = p
            end = off + nb
        if end < self.nbytes:
            out[end:self.nbytes] = 0
        return out

    def unpack_jax(self, buf):
        """The device-side slicing prolog: recover the part tuple from the
        packed uint8 buffer with slice→bitcast→reshape (pure XLA ops — they
        fuse into the wired program's decode prolog, no extra dispatch)."""
        import jax

        parts = []
        for sh, dt, off, nb in self.slots:
            seg = jax.lax.slice(buf, (off,), (off + nb,))
            if dt.itemsize > 1:
                seg = jax.lax.bitcast_convert_type(
                    seg.reshape(-1, dt.itemsize), dt)
            elif dt != np.uint8:
                seg = jax.lax.bitcast_convert_type(seg, dt)
            parts.append(seg.reshape(sh))
        return tuple(parts)


def start_device_transfer(arr, device=None):
    """Begin a NON-blocking H2D of one host array (complex rides the pair shim);
    returns ``finish() -> device array``. :func:`to_device` is this with an
    immediate finish."""
    import jax

    if isinstance(arr, jax.Array):
        # already device-resident: device_put is a same-device no-op (or a safe D2D
        # move); forcing it through np.asarray would be a blocking D2H round-trip
        x = jax.device_put(arr, device) if device is not None else arr
        return lambda: x
    a = np.asarray(arr)
    if np.issubdtype(a.dtype, np.complexfloating) and \
            split_complex_platform(_device_platform(device)):
        from .wire import _pairs_view
        pairs = _pairs_view(a)   # the ONE copy of the regression-locked trick
        put = start_device_transfer_parts((pairs,), device)
        join, _ = _jits()

        def finish():
            (p,) = put()
            return join(p)

        finish._wire = getattr(put, "_wire", None)
        return finish
    put = start_device_transfer_parts((a,), device)

    def finish():
        (x,) = put()
        return x

    finish._wire = getattr(put, "_wire", None)
    return finish


def to_device(arr, device=None):
    """``jax.device_put`` that is safe for complex dtypes on broken-transfer backends."""
    return start_device_transfer(arr, device)()


def to_host(arr) -> np.ndarray:
    """``np.asarray`` that reads complex device arrays back as two float transfers."""
    return start_host_transfer(arr)()


def start_host_transfer(arr, _instrument: bool = True):
    """Begin a NON-blocking D2H of ``arr``; returns a zero-arg ``finish()`` that
    blocks until the copy lands and yields the numpy array.
    ``_instrument=False`` (module-private) suppresses the per-call telemetry so
    :func:`start_host_transfer_parts` can bill one frame's parts as ONE
    transfer — symmetric with the H2D side, which reserves per frame.

    This is how a drain loop overlaps transfers: start transfers for every
    completed frame first, then finish them oldest-first — frame t+1's D2H rides
    the wire while the caller is still consuming frame t (the role of the
    reference's circulating empty/full staging buffers, ``buffer/vulkan/d2h.rs``).
    :func:`to_host` is this with an immediate finish; all complex-pair-shim and
    platform logic lives here, once."""
    import jax

    if not isinstance(arr, jax.Array):
        # host data: the jitted split() would device_put the raw complex array —
        # the exact broken path this shim avoids
        return lambda: np.asarray(arr)
    dt = np.dtype(getattr(arr, "dtype", np.float32))
    if np.issubdtype(dt, np.complexfloating):
        try:
            devs = list(arr.devices())
            platform = devs[0].platform if devs else _device_platform()
        except Exception:
            platform = _device_platform()
        if split_complex_platform(platform):
            _, split = _jits()
            r, i = split(arr)                    # async device-side split
            nbytes = r.nbytes + i.nbytes
            # physical starts bill regardless of _instrument (parts-path
            # callers suppress the per-frame counters, not the start count)
            _XFER_STARTS.inc(2, direction="d2h")
            if _instrument:
                _XFER_BYTES.inc(nbytes, direction="d2h")
                _XFER_TRANSFERS.inc(direction="d2h")

            def attempt():
                # idempotent: the split halves stay device-resident, so a
                # retried fetch re-reads the same bits
                _check_injected("d2h")
                # both halves start NOW (async copy, or eager pool fetch when
                # the array type has no copy_to_host_async) — never serially
                # in finish
                return _start_fetch(r), _start_fetch(i)

            fr, fi = _with_retry("d2h", attempt)
            service, deadline = _reserve("d2h", nbytes)
            t0 = time.perf_counter_ns() if _instrument else 0

            def finish():
                out = np.empty(r.shape, dtype=dt)
                out.real = fr()
                out.imag = fi()
                _wait_deadline(deadline)
                if t0:
                    s, e = _span_bounds_ns(t0, service, deadline)
                    _D2H_HIST.observe((e - s) * 1e-9)
                    if _trace.enabled:
                        _trace.complete("tpu", "D2H", s, end_ns=e,
                                        args={"bytes": nbytes})
                return out

            finish._wire = (service, deadline)
            return finish
    nbytes = int(getattr(arr, "nbytes", 0))
    _XFER_STARTS.inc(direction="d2h")
    if _instrument:
        _XFER_BYTES.inc(nbytes, direction="d2h")
        _XFER_TRANSFERS.inc(direction="d2h")

    def attempt():
        _check_injected("d2h")
        return _start_fetch(arr)

    fetch = _with_retry("d2h", attempt)
    service, deadline = _reserve("d2h", nbytes)
    t0 = time.perf_counter_ns() if _instrument else 0

    def finish():
        out = fetch()
        _wait_deadline(deadline)
        if t0:
            s, e = _span_bounds_ns(t0, service, deadline)
            _D2H_HIST.observe((e - s) * 1e-9)
            if _trace.enabled:
                _trace.complete("tpu", "D2H", s, end_ns=e,
                                args={"bytes": nbytes})
        return out

    finish._wire = (service, deadline)
    return finish


def start_host_transfer_parts(parts):
    """Begin a NON-blocking D2H of a tuple of wire parts (a jitted epilog's
    output, ``ops/wire.py``); returns ``finish() -> tuple of np arrays``.
    Every part's transfer starts immediately, so in-flight frames' payloads
    ride the wire together (per-direction fake-link accounting included).

    Telemetry bills the WHOLE frame as one D2H transfer/span (symmetric with
    :func:`start_device_transfer_parts`): per-part billing would make the
    d2h counters and lane span counts scale with the wire's part count
    instead of the frame count."""
    fins = [start_host_transfer(p, _instrument=False) for p in parts]
    nbytes = sum(int(getattr(p, "nbytes", 0)) for p in parts)
    _XFER_BYTES.inc(nbytes, direction="d2h")
    _XFER_TRANSFERS.inc(direction="d2h")
    t0 = time.perf_counter_ns()

    def finish():
        out = tuple(f() for f in fins)
        wires = [getattr(f, "_wire", (0.0, 0.0)) for f in fins]
        service = min((s for s, _ in wires if s), default=0.0)
        deadline = max((d for _, d in wires), default=0.0)
        s, e = _span_bounds_ns(t0, service, deadline)
        _D2H_HIST.observe((e - s) * 1e-9)
        if _trace.enabled:
            _trace.complete("tpu", "D2H", s, end_ns=e, args={"bytes": nbytes})
        return out

    return finish
