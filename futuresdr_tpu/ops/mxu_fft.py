"""MXU-mapped FFT: the DFT as batched matrix products (four-step algorithm).

XLA's TPU FFT lowers to a vector-unit kernel that measures ~3 Gsamples/s for batched
2048-point complex64 FFTs on a v5e chip, leaving the MXU (where the chip's FLOPs live)
idle. This module runs the same transform as two matmul passes — the classic four-step
decomposition N = N1·N2:

    X[k1 + N1·k2] = Σ_b W_N^{b·k1} · ( Σ_a x[a·N2 + b] · W_N1^{a·k1} ) · W_N2^{b·k2}

i.e. ``DFT_N1 @ A`` (columns), a twiddle multiply, and ``C @ DFT_N2ᵀ`` (rows) — both
matmuls batched over frames and mapped onto the systolic array. Measured on-chip
(docs/tpu_notes.md): ~5.5 Gsps at float32 matmul precision (rel err ~1e-5, same order
as the FFT itself) and ~19 Gsps at bfloat16 precision (rel err ~4e-3 ≈ -47 dB — fine
for spectrum display, not for decoding chains).

The DFT/twiddle matrices are built *in trace* (``jnp.exp`` of ``jnp.outer``), never as
embedded host constants — the axon tunnel mis-compiles large embedded constants and
cannot transfer host complex arrays at all (see ``ops/xfer.py``).

Reference role: the reference delegates FFTs to rustfft (``src/blocks/fft.rs``); this
module is the TPU-first equivalent of "use the fastest FFT the hardware has".
"""
from __future__ import annotations

import math
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

# Module policy: implementation ("auto" | "mxu" | "xla") and matmul precision
# ("f32" | "bf16"). Env overrides let a deployment flip the policy without code.
#
# TRACE-TIME BINDING: the policy is read when a function is *traced*, and jit
# caches keep whichever path was bound at first trace. Flipping set_impl /
# set_precision after a stage or Pipeline has compiled has no effect on the
# cached executable — rebuild the stage, or pass impl=/precision= explicitly
# to bind per call site: fft/ifft(..., impl=..., precision=...) here,
# fft_stage(impl=..., precision=...) and fir_stage(fft_impl=...,
# precision=...) at the stage layer (regression-pinned in
# tests/test_precision.py) — two chains in one process can hold different
# routes without fighting over the module policy.
_impl = os.environ.get("FUTURESDR_TPU_FFT_IMPL", "auto")
_precision = os.environ.get("FUTURESDR_TPU_FFT_PRECISION", "f32")

_MIN_MXU_N = 256          # below this the four-step matmuls are too skinny...
_MAX_DIRECT_N = 512       # ...but a DIRECT [n,n] DFT matmul wins for small n (any
                          # factorization, huge batch): one dense MXU pass
_MAX_FORCED_DIRECT_N = 4096   # forced-mxu safety cap: above this a dense [n,n]
                              # DFT is O(n^2) HBM (4096^2 c64 = 134 MB); fall
                              # back to jnp.fft rather than OOM/crawl


def set_impl(impl: str) -> None:
    """Set the FFT implementation policy: "auto" (MXU on TPU), "mxu", or "xla".

    Trace-time binding: affects only functions traced *after* this call; already
    jit-compiled stages keep their old path (see module docstring)."""
    global _impl
    assert impl in ("auto", "mxu", "xla"), impl
    _impl = impl


def set_precision(precision: str) -> None:
    """Set MXU matmul precision: "f32" (accurate) or "bf16" (~2-4x faster, -47 dB).

    Trace-time binding: affects only functions traced *after* this call; already
    jit-compiled stages keep their old path (see module docstring)."""
    global _precision
    assert precision in ("f32", "bf16"), precision
    _precision = precision


def _use_mxu(n: int, impl: Optional[str] = None) -> bool:
    """Trace-time dispatch decision (backend is static under jit)."""
    eff = impl or _impl
    if eff == "xla":
        return False
    if eff == "mxu":
        if n > _MAX_FORCED_DIRECT_N and (n & (n - 1)) != 0:
            # forced policy would route this through a dense [n,n] DFT matmul —
            # O(n^2) HBM with no upside at this size; refuse and use jnp.fft
            import logging
            logging.getLogger("futuresdr_tpu").warning(
                "fft: impl='mxu' forced but n=%d is a non-power-of-two above the "
                "direct-DFT cap (%d); falling back to jnp.fft for this size",
                n, _MAX_FORCED_DIRECT_N)
            return False
        return True
    if jax.default_backend() != "tpu":
        return False
    return (8 <= n <= _MAX_DIRECT_N) or (n >= _MIN_MXU_N and (n & (n - 1)) == 0)


def _factor(n: int) -> tuple:
    """Split n = N1 * N2 with N1 >= N2, both powers of two, near sqrt(n)."""
    assert n >= 4 and (n & (n - 1)) == 0, f"four-step FFT needs power-of-two n, got {n}"
    log = n.bit_length() - 1
    n1 = 1 << ((log + 1) // 2)
    return n1, n // n1


def _lax_precision(precision: Optional[str]):
    p = precision or _precision
    return jax.lax.Precision.HIGHEST if p == "f32" else jax.lax.Precision.DEFAULT


def _mxu_fft(x: jnp.ndarray, n: int, precision: Optional[str]) -> jnp.ndarray:
    if n <= _MAX_DIRECT_N or (n & (n - 1)) != 0:
        # direct DFT matmul: one dense [n, n] MXU pass, any n
        k = jnp.arange(n)
        F = jnp.exp(-2j * jnp.pi * jnp.outer(k, k) / n).astype(jnp.complex64)
        return jnp.einsum("kn,...n->...k", F, x, precision=_lax_precision(precision))
    n1, n2 = _factor(n)
    prec = _lax_precision(precision)
    # DFT + twiddle factors computed in trace (device constants, not host transfers)
    a = jnp.arange(n1)
    b = jnp.arange(n2)
    f1 = jnp.exp(-2j * jnp.pi * jnp.outer(a, a) / n1).astype(jnp.complex64)  # [k1, a]
    f2 = jnp.exp(-2j * jnp.pi * jnp.outer(b, b) / n2).astype(jnp.complex64)  # [k2, b]
    tw = jnp.exp(-2j * jnp.pi * jnp.outer(a, b) / n).astype(jnp.complex64)   # [k1, b]
    shape = x.shape
    A = x.reshape(shape[:-1] + (n1, n2))
    B = jnp.einsum("ka,...ab->...kb", f1, A, precision=prec)
    D = jnp.einsum("...kb,cb->...kc", B * tw, f2, precision=prec)            # (k1, k2)
    return jnp.swapaxes(D, -1, -2).reshape(shape)


def fft(x: jnp.ndarray, precision: Optional[str] = None,
        impl: Optional[str] = None) -> jnp.ndarray:
    """Forward DFT along the last axis. Dispatches MXU four-step vs jnp.fft per the
    module policy; always safe to call on any backend.

    ``impl``/``precision`` override the module policy for this call site, binding
    the choice at trace time regardless of later set_impl/set_precision calls."""
    n = x.shape[-1]
    x = x.astype(jnp.complex64)
    if _use_mxu(n, impl):
        return _mxu_fft(x, n, precision)
    return jnp.fft.fft(x, axis=-1)


def ifft(x: jnp.ndarray, precision: Optional[str] = None,
         impl: Optional[str] = None) -> jnp.ndarray:
    """Inverse DFT along the last axis (conjugation trick over the forward path)."""
    n = x.shape[-1]
    x = x.astype(jnp.complex64)
    if _use_mxu(n, impl):
        return jnp.conj(_mxu_fft(jnp.conj(x), n, precision)) / n
    return jnp.fft.ifft(x, axis=-1)
