"""Wire-format codecs for every host↔device crossing of the streamed path.

The streamed flowgraph path is bounded by min(compute, link), and the link has
been the framework's worst number: complex64 ships as 8 B/sample float32 pairs
both ways (`ops/xfer.py`), so a ~12 Msps tunnel ceiling caps the streamed rate
at 5 Msps (BENCH_r05.json). Real SDR links quantize IQ on the wire — sc16/sc8
interleaved formats are what the reference's seify streams and every
USRP/SoapySDR transport speak — because RF data carries 50-80 dB of SNR at
best, far below 16-bit quantization noise. The same trick (cheap host-side
cast, dequantize on the accelerator) is how TPU input pipelines feed
(arXiv:1810.09868 §4).

A :class:`Wire` turns a logical frame (complex64/float32 stream samples) into
**wire parts** — a tuple of small-dtype numpy/jax arrays that cross the link —
and back, on both ends:

    host:   encode_host(frame) -> parts          (cheap views/casts, one pass)
    device: decode_jax(parts)  -> frame          (jitted PROLOG, fused into the
    device: encode_jax(frame)  -> parts           kernel program — dequantized
    host:   decode_host(parts) -> frame           frames never round-trip)

Part layouts are SYMMETRIC in both directions, so a host-side
``encode_host → decode_host`` round trip measures exactly the quantization the
link applies (see :func:`measure_snr_db` — bench.py stamps the measured, not
nominal, SNR).

Formats:

========  ==============  ==========================  =====================
name      c64 B/sample    layout                      SNR (measured, c64)
========  ==============  ==========================  =====================
``f32``   8               float32 IQ pairs            exact
``bf16``  4               bfloat16 IQ pairs           ~40 dB (8-bit mantissa)
``sc16``  4               int16 IQ + per-frame scale  ~85-90 dB
``sc8``   2               int8 IQ + per-frame scale   ~45-50 dB
========  ==============  ==========================  =====================

``sc16``/``sc8`` use per-frame block-floating-point: one float32 scale =
max(|I|,|Q|) over the frame rides with the int payload, so the full int range
is always used regardless of the stream's absolute level (the AGC-free
convention of UHD's sc16 mode). Complex arrays are never materialised on the
wire — every format ships reals and forms the complex frame device-side in the
jitted prolog, which also keeps the broken-tunnel rule (docs/tpu_notes.md
"complex arrays must be formed on device") satisfied for free.

Non-float payloads (e.g. a lora demod's int32 symbols) pass through every
format unchanged: quantizing indices would corrupt them, and they are already
compact.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

__all__ = ["Wire", "WIRE_FORMATS", "get_wire", "resolve_wire", "wire_names",
           "measure_snr_db", "streamed_ceiling_msps"]


def _is_float(dt) -> bool:
    dt = np.dtype(dt)
    return (np.issubdtype(dt, np.floating)
            or np.issubdtype(dt, np.complexfloating))


def _pairs_view(a: np.ndarray) -> np.ndarray:
    """complex (…) → float re/im pairs (…, 2) — a zero-copy view after the
    contiguity normalization (the regression-locked trick of ops/xfer.py)."""
    f = np.float64 if a.dtype == np.complex128 else np.float32
    return np.ascontiguousarray(a).view(f).reshape(a.shape + (2,))


def _join_pairs_np(p: np.ndarray, dt: np.dtype) -> np.ndarray:
    """float32 pairs (…, 2) → complex (…) host-side (zero-copy when contiguous)."""
    p = np.ascontiguousarray(np.asarray(p, dtype=np.float32))
    return p.view(np.complex64).reshape(p.shape[:-1]).astype(dt, copy=False)


class Wire:
    """One wire format. Stateless; instances are shared via :data:`WIRE_FORMATS`."""

    name = "?"
    #: nominal quantization SNR in dB for a full-scale c64 stream (None = exact)
    nominal_snr_db: Optional[float] = None

    def __init__(self):
        self._jit_dec: dict = {}          # np.dtype -> jitted decode prolog
        self._jit_enc = None              # jitted encode epilog
        self._part_counts: dict = {}      # np.dtype -> parts per frame

    def bytes_per_sample(self, dtype) -> int:
        """Bytes ONE logical sample of ``dtype`` occupies on the wire (the
        per-frame scale scalar is amortized away)."""
        raise NotImplementedError

    def part_count(self, dtype) -> int:
        """How many wire parts one frame of ``dtype`` ships as (quantizing
        formats ride a scale scalar beside the int payload; f32/bf16 ship one
        part). Probed once per dtype with a 1-item host encode and cached —
        the re-nesting key for multi-output (fan-out) programs whose flat
        part tuple concatenates per-branch parts
        (:meth:`futuresdr_tpu.ops.stages.FanoutPipeline.part_counts`)."""
        dt = np.dtype(dtype)
        n = self._part_counts.get(dt)
        if n is None:
            n = self._part_counts[dt] = len(self.encode_host(np.zeros(1, dt)))
        return n

    def encode_may_alias(self, dtype) -> bool:
        """Can :meth:`encode_host` return views ALIASING its input's memory?
        Decides whether a caller handing in a live ring-buffer slice must copy
        it out first (the async H2D would read the ring after the writer
        reclaims it — ``ops/xfer.h2d_needs_staging``). Quantizing/casting
        formats materialize fresh arrays for float payloads, so the staging
        copy is pure waste there — one fewer frame-sized memcpy per crossing
        on the hot path."""
        return True

    def encode_host(self, a: np.ndarray) -> Tuple[np.ndarray, ...]:
        raise NotImplementedError

    def encode_into(self, a: np.ndarray, alloc) -> Tuple[np.ndarray, ...]:
        """:meth:`encode_host` with output buffers drawn from ``alloc``
        (an ``ops/arena.GroupAlloc``): quantizing formats land their int
        payload in recycled arena pages instead of fresh allocations —
        bit-identical parts, no per-frame allocator tax. The base
        implementation falls back to :meth:`encode_host` (exact formats'
        parts are views of the caller's staging buffer, which the caller
        already pins; formats without an arena path stay allocation-fresh,
        which is always recycle-safe)."""
        return self.encode_host(a)

    def decode_jax(self, parts: Sequence, dtype):
        raise NotImplementedError

    def encode_jax(self, y) -> tuple:
        raise NotImplementedError

    def decode_host(self, parts: Sequence[np.ndarray], dtype) -> np.ndarray:
        raise NotImplementedError

    def jit_decode(self, dtype):
        """Cached ``jax.jit`` of :meth:`decode_jax` for one logical dtype —
        the standalone wire PROLOG for blocks that decode onto the frame
        plane without a fused pipeline (``tpu/frames.py``). One cache per
        shared Wire instance keeps the jit function identity stable."""
        import jax
        dt = np.dtype(dtype)
        fn = self._jit_dec.get(dt)
        if fn is None:
            w = self
            fn = self._jit_dec[dt] = jax.jit(lambda *p: w.decode_jax(p, dt))
        return fn

    def jit_encode(self):
        """Cached ``jax.jit`` of :meth:`encode_jax` — the standalone wire
        EPILOG (symmetric of :meth:`jit_decode`)."""
        import jax
        if self._jit_enc is None:
            w = self
            self._jit_enc = jax.jit(lambda y: w.encode_jax(y))
        return self._jit_enc

    def __repr__(self):
        return f"Wire({self.name})"


class F32Wire(Wire):
    """Today's pair shim as a codec: float32 IQ pairs, bit-exact."""

    name = "f32"
    nominal_snr_db = None

    def bytes_per_sample(self, dtype) -> int:
        return np.dtype(dtype).itemsize

    def encode_host(self, a):
        a = np.asarray(a)
        if np.issubdtype(a.dtype, np.complexfloating):
            return (_pairs_view(a),)
        return (np.ascontiguousarray(a),)

    def decode_jax(self, parts, dtype):
        import jax
        (p,) = parts
        if np.issubdtype(np.dtype(dtype), np.complexfloating):
            return jax.lax.complex(p[..., 0], p[..., 1])
        return p

    def encode_jax(self, y):
        import jax.numpy as jnp
        if jnp.iscomplexobj(y):
            return (jnp.stack([y.real, y.imag], axis=-1),)
        return (y,)

    def decode_host(self, parts, dtype):
        dt = np.dtype(dtype)
        (p,) = parts
        if np.issubdtype(dt, np.complexfloating):
            return _join_pairs_np(np.asarray(p), dt)
        return np.asarray(p).astype(dt, copy=False)


class Bf16Wire(Wire):
    """bfloat16 IQ pairs: truncated-mantissa float32 — 2× fewer bytes, no scale
    bookkeeping, graceful over any dynamic range (~40 dB SNR: display-grade)."""

    name = "bf16"
    nominal_snr_db = 54.0    # 8-bit mantissa: ~2^-9 relative error per sample

    def encode_may_alias(self, dtype) -> bool:
        return not _is_float(dtype)      # astype(bf16) materializes floats

    def _bf16(self):
        import ml_dtypes
        return ml_dtypes.bfloat16

    def bytes_per_sample(self, dtype) -> int:
        dt = np.dtype(dtype)
        if not _is_float(dt):
            return dt.itemsize
        return 4 if np.issubdtype(dt, np.complexfloating) else 2

    def encode_host(self, a):
        a = np.asarray(a)
        if np.issubdtype(a.dtype, np.complexfloating):
            return (_pairs_view(a.astype(np.complex64, copy=False))
                    .astype(self._bf16()),)
        if np.issubdtype(a.dtype, np.floating):
            return (a.astype(self._bf16()),)
        return (np.ascontiguousarray(a),)

    def decode_jax(self, parts, dtype):
        import jax
        import jax.numpy as jnp
        dt = np.dtype(dtype)
        (p,) = parts
        if np.issubdtype(dt, np.complexfloating):
            f = p.astype(jnp.float32)
            return jax.lax.complex(f[..., 0], f[..., 1])
        if np.issubdtype(dt, np.floating):
            return p.astype(jnp.float32)
        return p

    def encode_jax(self, y):
        import jax.numpy as jnp
        if jnp.iscomplexobj(y):
            return (jnp.stack([y.real, y.imag], axis=-1).astype(jnp.bfloat16),)
        if np.issubdtype(y.dtype, np.floating):
            return (y.astype(jnp.bfloat16),)
        return (y,)

    def decode_host(self, parts, dtype):
        dt = np.dtype(dtype)
        (p,) = parts
        p = np.asarray(p)
        if np.issubdtype(dt, np.complexfloating):
            return _join_pairs_np(p.astype(np.float32), dt)
        if np.issubdtype(dt, np.floating):
            return p.astype(np.float32).astype(dt, copy=False)
        return p


class _QuantWire(Wire):
    """Block-floating-point int IQ: ``q = round(x * qmax / scale)`` with
    ``scale = max(|I|,|Q|)`` over the frame (one float32 riding beside the
    payload). Quantization error is uniform in ±scale/(2·qmax) →
    SNR ≈ 6.02·bits + 1.76 − PAPR dB relative to the frame peak.

    Non-finite samples are ZEROED on encode, both host- and device-side: an
    int wire cannot carry inf/NaN, and letting one bad sample poison the
    frame scale would overflow/wrap every finite neighbour — zeroing loses
    only the already-meaningless sample."""

    itype: np.dtype
    qmax: float

    def encode_may_alias(self, dtype) -> bool:
        return not _is_float(dtype)      # quantization materializes floats

    def bytes_per_sample(self, dtype) -> int:
        dt = np.dtype(dtype)
        if not _is_float(dt):
            return dt.itemsize
        unit = np.dtype(self.itype).itemsize
        return 2 * unit if np.issubdtype(dt, np.complexfloating) else unit

    def _flat_host(self, a: np.ndarray):
        if np.issubdtype(a.dtype, np.complexfloating):
            return _pairs_view(a.astype(np.complex64, copy=False))
        return a.astype(np.float32, copy=False)

    def encode_host(self, a):
        a = np.asarray(a)
        if not _is_float(a.dtype):
            return (np.ascontiguousarray(a),)
        flat = self._flat_host(a)
        peak = float(np.max(np.abs(flat))) if flat.size else 0.0
        if not np.isfinite(peak):
            # non-finite samples (upstream divide-by-zero, AGC transients)
            # cannot ride an int wire; ZERO them so the rest of the frame
            # survives — without this the scale fallback would let every
            # finite sample overflow/wrap the int payload
            flat = np.where(np.isfinite(flat), flat, np.float32(0.0))
            peak = float(np.max(np.abs(flat))) if flat.size else 0.0
        if peak <= 0.0:
            peak = 1.0
        q = np.round(flat * (self.qmax / peak)).astype(self.itype)
        return (q, np.float32(peak))

    def encode_into(self, a, alloc):
        """Arena path: the int payload lands in a recycled buffer; the
        float scratch is a pool temp released before returning. The math is
        exactly :meth:`encode_host`'s (multiply → round → cast), so the
        parts are bit-identical to the allocating path."""
        a = np.asarray(a)
        if not _is_float(a.dtype):
            return (np.ascontiguousarray(a),)
        flat = self._flat_host(a)
        peak = float(np.max(np.abs(flat))) if flat.size else 0.0
        if not np.isfinite(peak):
            flat = np.where(np.isfinite(flat), flat, np.float32(0.0))
            peak = float(np.max(np.abs(flat))) if flat.size else 0.0
        if peak <= 0.0:
            peak = 1.0
        scratch = alloc.temp(flat.shape, np.float32)
        np.multiply(flat, np.float32(self.qmax / peak), out=scratch)
        np.round(scratch, out=scratch)
        q = alloc(flat.shape, self.itype)
        np.copyto(q, scratch, casting="unsafe")
        alloc.drop_temps()
        return (q, np.float32(peak))

    def decode_jax(self, parts, dtype):
        import jax
        import jax.numpy as jnp
        dt = np.dtype(dtype)
        if not _is_float(dt):
            return parts[0]
        q, scale = parts
        x = q.astype(jnp.float32) * (scale.astype(jnp.float32) / self.qmax)
        if np.issubdtype(dt, np.complexfloating):
            return jax.lax.complex(x[..., 0], x[..., 1])
        return x

    def encode_jax(self, y):
        import jax.numpy as jnp
        if jnp.iscomplexobj(y):
            flat = jnp.stack([y.real, y.imag], axis=-1)
        elif np.issubdtype(y.dtype, np.floating):
            flat = y.astype(jnp.float32)
        else:
            return (y,)
        flat = flat.astype(jnp.float32)
        # zero non-finite samples (host-side encode contract): the scale must
        # stay finite and finite neighbours must not overflow the int payload
        flat = jnp.where(jnp.isfinite(flat), flat, jnp.float32(0.0))
        if flat.size:
            peak = jnp.max(jnp.abs(flat)).astype(jnp.float32)
            scale = jnp.where(peak > 0, peak, jnp.float32(1.0))
        else:
            scale = jnp.float32(1.0)
        q = jnp.round(flat * (self.qmax / scale)).astype(self.itype)
        return (q, scale)

    def decode_host(self, parts, dtype):
        dt = np.dtype(dtype)
        if not _is_float(dt):
            return np.asarray(parts[0])
        q, scale = parts
        x = np.asarray(q).astype(np.float32) * \
            (np.float32(np.asarray(scale)) / np.float32(self.qmax))
        if np.issubdtype(dt, np.complexfloating):
            return _join_pairs_np(x, dt)
        return x.astype(dt, copy=False)


class Sc16Wire(_QuantWire):
    name = "sc16"
    itype = np.int16
    qmax = 32767.0
    nominal_snr_db = 90.0


class Sc8Wire(_QuantWire):
    name = "sc8"
    itype = np.int8
    qmax = 127.0
    nominal_snr_db = 41.0    # 6.02·7 + 1.76 − Gaussian PAPR


WIRE_FORMATS = {w.name: w for w in (F32Wire(), Bf16Wire(), Sc16Wire(), Sc8Wire())}


def wire_names() -> tuple:
    return tuple(WIRE_FORMATS)


def get_wire(w) -> Wire:
    """``"sc16"`` / Wire instance → Wire instance; raises on unknown names."""
    if isinstance(w, Wire):
        return w
    try:
        return WIRE_FORMATS[str(w)]
    except KeyError:
        raise KeyError(f"unknown wire format {w!r}; "
                       f"known: {sorted(WIRE_FORMATS)}") from None


def resolve_wire(w, platform: str) -> Wire:
    """Resolve a user/config wire choice for a backend platform.

    ``None`` reads ``config().tpu_wire_format`` (env override:
    ``FUTURESDR_TPU_WIRE_FORMAT``). ``"auto"`` picks ``f32`` on the CPU backend
    (the "link" is a memcpy — quantization would only add an encode pass and
    noise) and ``sc16`` elsewhere (half the bytes at ~-90 dB, far below any RF
    noise floor; :func:`futuresdr_tpu.tpu.autotune.autotune_streamed` refines
    the choice against the measured link envelope)."""
    if w is None:
        from ..config import config
        w = config().tpu_wire_format
    if isinstance(w, str) and w == "auto":
        w = "f32" if platform == "cpu" else "sc16"
    wire = get_wire(w)
    _note_wire_gauges(wire)
    return wire


_noted_wires: set = set()


def _note_wire_gauges(wire: Wire) -> None:
    """Stamp the telemetry gauges for a wire format the first time a block
    resolves it: measured codec SNR (one host round trip, ~ms) and the
    per-sample byte widths — so ``GET /metrics`` carries the rate/fidelity
    tradeoff of every codec actually in use."""
    if wire.name in _noted_wires:
        return
    _noted_wires.add(wire.name)
    try:
        from ..telemetry import prom
        prom.gauge("fsdr_wire_snr_db",
                   "measured codec SNR of one link crossing (c64 payload)",
                   ("wire",)).set(measure_snr_db(wire), wire=wire.name)
        prom.gauge("fsdr_wire_bytes_per_sample",
                   "wire bytes per complex64 sample",
                   ("wire",)).set(wire.bytes_per_sample(np.complex64),
                                  wire=wire.name)
    except Exception:                    # pragma: no cover — never block a
        _noted_wires.discard(wire.name)  # kernel build on telemetry



def measure_snr_db(wire, dtype=np.complex64, n: int = 8192,
                   seed: int = 0) -> float:
    """MEASURED codec SNR in dB: a host encode→decode round trip over a
    unit-power Gaussian frame (part layouts are direction-symmetric, so this is
    exactly the quantization one link crossing applies). ``inf`` for exact
    formats — bench.py stamps this next to the throughput so the artifact
    carries the actual rate/fidelity tradeoff, not the nominal one."""
    wire = get_wire(wire)
    rng = np.random.default_rng(seed)
    dt = np.dtype(dtype)
    if not _is_float(dt):
        return float("inf")       # int payloads pass through every wire losslessly
    if np.issubdtype(dt, np.complexfloating):
        x = ((rng.standard_normal(n) + 1j * rng.standard_normal(n))
             / np.sqrt(2)).astype(np.complex64)
    else:
        x = rng.standard_normal(n).astype(np.float32)
    y = wire.decode_host(wire.encode_host(x), dt)
    err = float(np.mean(np.abs(y - x) ** 2))
    if err == 0.0:
        return float("inf")
    sig = float(np.mean(np.abs(x) ** 2))
    return 10.0 * np.log10(sig / err)


def streamed_ceiling_msps(wire, h2d_Bps: float, d2h_Bps: float,
                          in_dtype=np.complex64, out_dtype=np.float32,
                          out_per_in: float = 1.0) -> float:
    """Link-bounded streamed ceiling for one wire format, in Msamples/s:
    ``min(h2d / up_bytes, d2h / (down_bytes · out_per_in))``. The duplex
    directions overlap when frames are in flight, so the binding one is the
    slower, not the sum (bench.py's ``streamed_link_ceiling_msps`` rule)."""
    w = get_wire(wire)
    up = w.bytes_per_sample(in_dtype)
    down = w.bytes_per_sample(out_dtype) * max(out_per_in, 1e-12)
    return min(h2d_Bps / up, d2h_Bps / down) / 1e6
