"""Codec worker pool: host-side wire encode/decode off the drain thread.

The streamed drain loop used to run the wire codec inline: encode on the way
into ``start_device_transfer_parts``, decode after the D2H lands — both on
the one BLOCKING kernel thread, serializing host codec time against dispatch
and against each other. numpy releases the GIL on large-array ops, so a small
thread pool turns the three-lane overlap (H2D ∥ compute ∥ D2H) into five:

    encode(t+1) ∥ H2D(t) ∥ compute(t) ∥ D2H(t−1) ∥ decode(t−2)

Two separate lanes, deliberately: DECODE tasks block on the D2H landing
(under a fake link that is a modeled wire-time sleep), so sharing one
executor would let parked decodes starve encodes and idle the up-link.
Workers are process-global (like the ``fsdr-d2h`` fetch pool) and live for
the process; threads are named ``fsdr-codec-enc*`` / ``fsdr-codec-dec*``.

ORDER is the caller's contract, not the pool's: the kernel drains its staged
and in-flight deques oldest-first and joins each future in sequence, so
emission order is preserved no matter how workers interleave. The telemetry
spans a task emits (encode/decode, ``telemetry/spans.py``) land in the
worker thread's own ring — the doctor's interval-union lanes therefore stay
honest, and ``doctor.report()["host_codec_overlap_frac"]`` measures how much
of the wall the codec lanes actually covered.

Config: ``host_codec_workers`` (default 2 per lane; 0 disables the pool —
every caller falls back to the inline synchronous path, the A/B baseline).
"""

from __future__ import annotations

import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Optional

from ..log import logger

__all__ = ["CodecPool", "pool", "reset_pool"]

log = logger("ops.codec_pool")


class CodecPool:
    """One encode executor + one decode executor of ``workers`` threads each."""

    def __init__(self, workers: int):
        self.workers = int(workers)
        self._enc = ThreadPoolExecutor(
            max_workers=self.workers, thread_name_prefix="fsdr-codec-enc")
        self._dec = ThreadPoolExecutor(
            max_workers=self.workers, thread_name_prefix="fsdr-codec-dec")

    def submit_encode(self, fn, *args) -> Future:
        return self._enc.submit(fn, *args)

    def submit_decode(self, fn, *args) -> Future:
        return self._dec.submit(fn, *args)

    def shutdown(self) -> None:
        self._enc.shutdown(wait=True)
        self._dec.shutdown(wait=True)


_pool: Optional[CodecPool] = None
_pool_disabled = False
_pool_lock = threading.Lock()


def pool() -> Optional[CodecPool]:
    """The process-global pool, or None when ``host_codec_workers`` is 0
    (callers run the codec inline — today's synchronous path)."""
    global _pool, _pool_disabled
    if _pool is None and not _pool_disabled:
        with _pool_lock:
            if _pool is None and not _pool_disabled:
                from ..config import config
                n = int(config().get("host_codec_workers", 2))
                if n <= 0:
                    _pool_disabled = True
                    return None
                _pool = CodecPool(n)
                log.info("codec pool: %d encode + %d decode worker(s)", n, n)
    return _pool


def reset_pool() -> None:
    """Shut down and drop the process pool (tests / config re-reads)."""
    global _pool, _pool_disabled
    with _pool_lock:
        if _pool is not None:
            _pool.shutdown()
        _pool = None
        _pool_disabled = False
