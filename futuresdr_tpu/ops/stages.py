"""Jittable streaming stages: the TPU compute plane's unit of composition.

This is where the reference's per-block accelerator dispatch (Vulkan/WGPU compute shaders,
``blocks/vulkan.rs:96+``) is re-designed TPU-first: instead of one device dispatch per block,
adjacent DSP blocks compose into ONE jitted XLA program (`SURVEY §7.5`). A :class:`Stage` is a
pure function ``(carry, frame) -> (carry, out)`` with static frame shape — streaming state
(filter history, oscillator phase) is explicit carry, which keeps the program jit-compatible
and lets frame t+1's dispatch chain on frame t's carry entirely on-device (no host sync
between frames).

Rate changes are rational and static (``in_per_out``/``out_per_in``), mirroring the
``ComputationStatus`` frame contract of ``futuredsp/lib.rs:33-45``.
"""

from __future__ import annotations

from dataclasses import dataclass
from fractions import Fraction
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import mxu_fft

__all__ = ["Stage", "Pipeline", "FanoutPipeline", "MergeStage", "DagPipeline",
           "apply_merge_stage", "add_merge_stage", "interleave_merge_stage",
           "concat_merge_stage", "fir_stage", "fft_stage",
           "mag2_stage", "log10_stage",
           "rotator_stage", "quad_demod_stage", "apply_stage", "fftshift_stage",
           "decimate_stage", "moving_avg_stage"]


def _donate_argnums(donate) -> tuple:
    """Normalize a donation spec into jit ``donate_argnums``.

    ``True`` donates the carries (argnum 0, the historical default), ``False``
    donates nothing, and a sequence is an explicit per-argnum mask — the knob
    multi-output fan-out programs need: the carries and the input wire parts
    are donation-safe (each dispatch consumes them), but a value that is
    multiply-consumed ACROSS outputs (the fan-out producer boundary) must
    never be threaded through as a donated argument — it rides the carry as a
    program output root instead (see :class:`FanoutPipeline`)."""
    if donate is True:
        return (0,)
    if not donate:
        return ()
    return tuple(int(i) for i in donate)


@dataclass
class Stage:
    """One streaming stage.

    ``fn(carry, x) -> (carry, y)`` must be jax-traceable with static shapes: for an input
    frame of n items it returns ``n * ratio`` items (ratio = out/in, a Fraction).
    """

    fn: Callable[[Any, jnp.ndarray], Tuple[Any, jnp.ndarray]]
    init_carry: Callable[[np.dtype], Any]
    ratio: Fraction = Fraction(1, 1)
    out_dtype: Optional[np.dtype] = None          # None = same as input
    frame_multiple: int = 1                       # input frame must divide this
    name: str = "stage"
    lti: Optional[Tuple[np.ndarray, int, int, str]] = None  # (taps, decim, fft_len, impl)
    #   when the stage is a linear time-invariant FIR — lets Pipeline merge adjacent
    #   FIRs into one (impl: the builder used for the merged stage, see _merge_lti)
    update: Optional[Callable[..., Any]] = None   # host-side ``(carry, **params) -> carry``
    #   runtime control hook: parameters (taps, phase_inc, …) live in the carry, so a
    #   retune is carry surgery between dispatches — NO recompile, frames stay in flight
    lower: Optional[Callable[[str], Optional["Stage"]]] = None
    #   interior-precision hook (ops/precision.py): return this stage rebuilt with its
    #   accumulation/taps lowered to the given precision ("bf16"; "int8" where the
    #   stage declares support), or None when unsupported — the SNR-budgeted lowering
    #   pass only considers stages that offer the hook; everything else gets at most
    #   an interior-EDGE cast
    compute_dtype: str = "f32"                    # dominant accumulation dtype of the
    #   traced program ("f32" | "bf16" | "int8") — keys the MFU denominator on the
    #   right per-dtype chip peak (utils/roofline.detect_peaks)
    route: Optional[Tuple[Optional[str], Optional[str], Optional[str]]] = None
    #   (impl, fft_impl, precision) — the builder's per-call-site selection for
    #   kernel-backed stages (fir/fft/channelizer). LTI merging preserves pins
    #   only when both sides agree (a pin must never be silently dropped), the
    #   cost-cache marker includes it (two same-shape stages on different
    #   routes compile different-cost programs), and
    #   ops/precision.pallas_stage_count resolves pallas routing from it

    def __repr__(self):
        return f"Stage({self.name}, ratio={self.ratio})"


@dataclass
class MergeStage:
    """A fan-IN stage: K ordered inputs joined into one output stream.

    ``fn(carry, xs) -> (carry, y)`` with ``xs`` a K-tuple of arrays, jax-
    traceable with static shapes — the merge node of a device-plane DAG
    (:class:`DagPipeline`): the WLAN ``{demod, chan-est} → decode`` join and
    the FM ``{audio, RDS} → mux`` both land here. The rate contract is per
    MODE:

    * ``mode="equal"`` — every input arrives at the SAME path rate (the
      :class:`DagPipeline` constructor enforces it; a violating region is a
      rate-contract error the devchain finder declines on). For n items per
      input the output is ``n * ratio`` items (``apply_merge_stage``: ratio 1;
      ``interleave_merge_stage(k)``: ratio k).
    * ``mode="concat"`` — inputs may arrive at DIFFERENT rates; the output is
      ``sum(n_i) * ratio`` items (``concat_merge_stage``: the mux join).

    Stream tags crossing a merge ride the PRIMARY input (index 0): on the
    actor path (``tpu/frames.TpuMergeStage``) only input 0's tags propagate
    (rebased by ``ratio`` — concat places input 0 at offset 0, so the same
    index math holds), and the fused path rebases region-input tags through
    each sink's primary-chain ``tag_ratio`` — the two stay bit-identical.
    """

    fn: Callable[[Any, Tuple[jnp.ndarray, ...]], Tuple[Any, jnp.ndarray]]
    init_carry: Callable[[np.dtype], Any]
    k: int
    mode: str = "equal"                           # "equal" | "concat"
    ratio: Fraction = Fraction(1, 1)
    out_dtype: Optional[np.dtype] = None          # None = same as input
    frame_multiple: int = 1                       # per-INPUT requirement
    name: str = "merge"
    update: Optional[Callable[..., Any]] = None

    def __post_init__(self):
        assert self.mode in ("equal", "concat"), self.mode
        assert self.k >= 2, "a merge needs >= 2 inputs"

    def __repr__(self):
        return f"MergeStage({self.name}, k={self.k}, mode={self.mode})"


def apply_merge_stage(f: Callable[..., jnp.ndarray], k: int,
                      out_dtype=None, name: str = "merge") -> MergeStage:
    """Elementwise K-way join: ``y = f(x_0, …, x_{K-1})`` over equal-length
    inputs (``mode="equal"``, ratio 1) — the device-plane ``Combine``
    (``blocks/functional.py``) generalized to K inputs."""

    def fn(carry, xs):
        return carry, f(*xs)

    return MergeStage(fn, lambda d: jnp.zeros(()), k, "equal",
                      Fraction(1, 1), out_dtype, 1, name)


def add_merge_stage(k: int, name: str = "add_merge") -> MergeStage:
    """Elementwise sum of K equal-rate inputs (diversity/branch combining)."""

    def fn(carry, xs):
        y = xs[0]
        for x in xs[1:]:
            y = y + x
        return carry, y

    return MergeStage(fn, lambda d: jnp.zeros(()), k, "equal",
                      Fraction(1, 1), None, 1, name)


def interleave_merge_stage(k: int, name: str = "interleave") -> MergeStage:
    """Item-interleave K equal-rate inputs: ``y[i·K + j] = x_j[i]`` (K· the
    per-input rate) — the symbol-mux join."""

    def fn(carry, xs):
        return carry, jnp.stack(xs, axis=1).reshape(-1)

    return MergeStage(fn, lambda d: jnp.zeros(()), k, "equal",
                      Fraction(k, 1), None, 1, name)


def concat_merge_stage(k: int, name: str = "concat_merge") -> MergeStage:
    """Frame-concatenate K inputs (rates may differ): ``y = x_0 ++ … ++
    x_{K-1}`` per frame — the FM ``{audio, RDS} → mux`` style join where each
    branch contributes its own item count."""

    def fn(carry, xs):
        return carry, jnp.concatenate(xs)

    return MergeStage(fn, lambda d: jnp.zeros(()), k, "concat",
                      Fraction(1, 1), None, 1, name)


class Pipeline:
    """A fused chain of stages compiled as a single XLA program.

    The composition is where TPU wins over per-block GPU dispatch: XLA fuses the
    elementwise stages into the FIR/FFT hot ops, so a NullSource→FIR→FFT→|x|² chain is one
    kernel launch per frame instead of four buffer hops.
    """

    def __init__(self, stages: Sequence[Stage], in_dtype, optimize: bool = True):
        self.in_dtype = np.dtype(in_dtype)
        self.stages = (_merge_lti(list(stages), self.in_dtype)
                       if optimize else list(stages))
        dtype = self.in_dtype
        fm = 1                      # required input-frame multiple
        r = Fraction(1, 1)          # cumulative rate in front of each stage
        for s in self.stages:
            # stage input = frame_in * r must be integral and a multiple of s.frame_multiple:
            # frame_in must be a multiple of reduce(m_i / r).numerator (see Fraction math)
            need = Fraction(s.frame_multiple, 1) / r
            fm = int(np.lcm(fm, need.numerator))
            r *= s.ratio
            fm = int(np.lcm(fm, r.denominator))   # integral intermediate frame sizes
            if s.out_dtype is not None:
                dtype = np.dtype(s.out_dtype)
        self.frame_multiple = fm
        self.ratio = r
        self.out_dtype = dtype
        self._fn = None
        self._wired_fns = {}        # (wire name, k) -> wrapped fn (stable for jit cache)

    def init_carry(self):
        dtype = self.in_dtype
        carries = []
        for s in self.stages:
            carries.append(s.init_carry(dtype))
            if s.out_dtype is not None:
                dtype = np.dtype(s.out_dtype)
        return tuple(carries)

    def fn(self):
        if self._fn is None:
            stages = self.stages

            def run(carries, x):
                new_c = []
                for s, c in zip(stages, carries):
                    c, x = s.fn(c, x)
                    new_c.append(c)
                return tuple(new_c), x

            self._fn = run
        return self._fn

    def compile(self, frame_size: int, device=None, donate=True):
        """Jit for a fixed frame size; returns (compiled_fn, initial device carry).

        Placement follows the data: put the carry (and inputs) on ``device``; jit then
        dispatches there without a deprecated device= argument.

        ``donate``: ``True`` donates the carries (argnum 0), ``False`` nothing,
        or an explicit argnum sequence (per-argnum donation mask — see
        :func:`_donate_argnums`).
        """
        assert frame_size % self.frame_multiple == 0, \
            f"frame_size {frame_size} not a multiple of {self.frame_multiple}"
        fn = jax.jit(self.fn(), donate_argnums=_donate_argnums(donate))
        carry = self.init_carry()
        if device is not None:
            carry = jax.device_put(carry, device)
        return fn, carry

    def wired_fn(self, wire, k: int = 1):
        """The stage chain with the wire codec's decode PROLOG and encode EPILOG
        fused in: ``(carries, *in_parts) -> (carries, out_parts)``. Dequantized
        frames exist only inside the XLA program — they never round-trip
        through HBM as a separate dispatch (``ops/wire.py``).

        ``k > 1`` returns the MEGABATCH form: each wire part gains a leading
        ``[k]`` axis and a ``lax.scan`` runs the k frames through the chain in
        ONE program call with the carry chained frame-to-frame — per-call host
        dispatch overhead is amortized k× (the ``frames_per_dispatch`` knob of
        ``TpuKernel``/``tpu/autotune.py``). Output parts carry the same leading
        axis. Functions are cached per ``(wire, k)`` so the jit identity stays
        stable across compiles."""
        from .wire import get_wire
        wire = get_wire(wire)
        key = (wire.name, int(k))
        if key not in self._wired_fns:
            inner = self.fn()
            in_dt, w = self.in_dtype, wire

            def run(carries, *parts):
                carries, y = inner(carries, w.decode_jax(parts, in_dt))
                return carries, w.encode_jax(y)

            if k == 1:
                self._wired_fns[key] = run
            else:
                def run_scan(carries, *parts):
                    def body(c, p):
                        return run(c, *p)
                    return jax.lax.scan(body, carries, tuple(parts))

                self._wired_fns[key] = run_scan
        return self._wired_fns[key]

    def packed_wired_fn(self, wire, k: int = 1, packed=None):
        """:meth:`wired_fn` with the COALESCED-uplink slicing prolog fused in
        front: ``(carries, packed_u8) -> (carries, out_parts)``. ``packed`` is
        an ``ops/xfer.PackedLayout`` — the offset table both the host packer
        and this unpacker derive from the wire codec, so they cannot
        disagree. The unpack is pure slice→bitcast→reshape, which XLA fuses
        into the decode prolog; the host pays ONE ``device_put`` per dispatch
        group instead of ``len(parts)``. Cached per
        ``(wire, k, layout)`` so the jit identity stays stable across
        compiles, exactly like :meth:`wired_fn`."""
        from .wire import get_wire
        wire = get_wire(wire)
        key = (wire.name, int(k), "packed", packed.key)
        if key not in self._wired_fns:
            inner = self.wired_fn(wire, k)
            lay = packed

            def run_packed(carries, buf):
                return inner(carries, *lay.unpack_jax(buf))

            self._wired_fns[key] = run_packed
        return self._wired_fns[key]

    def compile_wired(self, frame_size: int, wire, device=None,
                      donate=True, k: int = 1, packed=None):
        """:meth:`compile` for the wired form: the compiled fn consumes/produces
        wire parts (see :meth:`wired_fn`); returns (compiled_fn, initial carry).
        ``k > 1`` compiles the megabatch scan form (parts carry a leading
        ``[k]`` frame axis). ``donate`` accepts the same bool-or-argnums
        per-argnum mask as :meth:`compile`. ``packed`` (an
        ``ops/xfer.PackedLayout``) compiles the single-buffer coalesced form
        instead — the fn consumes ONE packed uint8 array
        (:meth:`packed_wired_fn`); only the carries (argnum 0) can donate
        there, so an explicit parts-argnum mask is clamped."""
        assert frame_size % self.frame_multiple == 0, \
            f"frame_size {frame_size} not a multiple of {self.frame_multiple}"
        if packed is not None:
            donate = bool(donate) if not isinstance(donate, (tuple, list)) \
                else (0 in tuple(donate))
            fn = jax.jit(self.packed_wired_fn(wire, k, packed),
                         donate_argnums=_donate_argnums(donate))
        else:
            fn = jax.jit(self.wired_fn(wire, k),
                         donate_argnums=_donate_argnums(donate))
        carry = self.init_carry()
        if device is not None:
            carry = jax.device_put(carry, device)
        return fn, carry

    def out_items(self, in_items: int) -> int:
        q = Fraction(in_items) * self.ratio
        assert q.denominator == 1
        return int(q)

    # -- carry checkpointing (the device-plane recovery contract) -------------
    # A pipeline's streaming state is EXPLICIT carry (module docstring), which
    # makes the whole program a pure function of (carry, frame): snapshotting
    # the carry at frame N and replaying frames N+1… from their host staging
    # copies reproduces an unfailed run bit-for-bit. These helpers give the
    # kernel blocks (tpu/kernel_block.py) a pipeline-owned flatten/validate/
    # restore surface so checkpoint integrity is checked against the carry
    # CONTRACT (tree structure + per-leaf shape/dtype), not ad hoc.

    def snapshot_carry(self, carry):
        """Flatten a live carry into ``(host_fetches, treedef)``: one zero-arg
        thunk per leaf that yields the host value. Device leaves begin their
        D2H NOW (``ops/xfer.start_host_transfer`` — the snapshot rides the
        existing D2H lane, off the dispatch critical path); host leaves pass
        through. The caller must materialize the thunks before the next
        dispatch donates the carry buffers (donation fence — a donated buffer
        read after reuse raises, never silently corrupts)."""
        import jax

        from .xfer import start_host_transfer
        leaves, treedef = jax.tree_util.tree_flatten(carry)
        fins = [start_host_transfer(leaf, _instrument=False)
                if isinstance(leaf, jax.Array) else (lambda v=leaf: v)
                for leaf in leaves]
        return fins, treedef

    def carry_matches(self, leaves, treedef, template) -> bool:
        """Integrity check of a materialized snapshot against a live carry
        ``template`` (same pipeline, same compile): tree structure and every
        leaf's shape/dtype must agree — the restore-path validation that lets
        a corrupted checkpoint candidate (the ``carry`` fault site) be
        rejected in favor of the previous one."""
        import jax
        t_leaves, t_def = jax.tree_util.tree_flatten(template)
        if treedef != t_def or len(leaves) != len(t_leaves):
            return False
        for leaf, t in zip(leaves, t_leaves):
            a = np.asarray(leaf)
            if a.shape != tuple(np.shape(t)) or \
                    a.dtype != np.dtype(getattr(t, "dtype", a.dtype)):
                return False
        return True

    def restore_carry(self, leaves, treedef, device=None):
        """Rebuild a device carry from a materialized host snapshot (complex
        leaves ride the pair shim — ``ops/xfer.to_device``)."""
        import jax

        from .xfer import to_device
        return jax.tree_util.tree_unflatten(
            treedef, [to_device(np.asarray(l), device) for l in leaves])

    def update_stage(self, carries, stage, _validate_only: bool = False, **params):
        """Runtime control: apply a stage's ``update`` hook to its slot in ``carries``.

        ``stage``: post-merge index or stage ``name`` (LTI merging may have renamed a
        FIR to ``"a*b"`` — address the pipeline you built, check ``.stages``). Returns
        the new carries tuple; the in-flight frames that captured the old carry are
        untouched, every later dispatch sees the new parameters — the device-path
        retune-while-running of ``examples/fm-receiver/src/main.rs:83-155``.

        ``_validate_only``: resolve the stage and check it has an update hook
        WITHOUT touching carries (which may be None) — for callers that must
        queue an update before any carry exists (TpuStage's lazy compile) but
        still want to reject a bad stage name immediately.
        """
        if isinstance(stage, str):
            hits = [i for i, s in enumerate(self.stages) if s.name == stage]
            if not hits:
                raise KeyError(
                    f"no stage named {stage!r} in {[s.name for s in self.stages]}")
            if len(hits) > 1:
                raise KeyError(f"stage name {stage!r} is ambiguous (indices {hits})")
            idx = hits[0]
        else:
            idx = int(stage)
            if not 0 <= idx < len(self.stages):
                raise KeyError(f"stage index {idx} out of range "
                               f"({len(self.stages)} stages)")
        s = self.stages[idx]
        if s.update is None:
            raise ValueError(f"stage {s.name!r} has no runtime-update hook")
        if _validate_only:
            return carries
        carries = list(carries)
        carries[idx] = s.update(carries[idx], **params)
        return tuple(carries)


class FanoutPipeline:
    """A fan-out stage DAG compiled as ONE multi-output XLA program.

    Shape: ``producer stages → boundary → N branch stage chains``. The
    producer computes once per frame; its boundary value feeds every branch
    INSIDE the program (no host round trip, no duplicate H2D — the
    whole-program fusion argument of arXiv:1810.09868 applied across a
    broadcast), and the program returns one output frame per branch. This is
    the compute plane of the device-graph fan-out fusion pass
    (``runtime/devchain.py``): a ``sync → {demod, channel-est}`` or
    ``FM → {audio, RDS}`` flowgraph region becomes one dispatch per frame.

    Donation contract (the reason this is its own class and not N stacked
    Pipelines): the flat carries tuple and the input wire parts stay
    donation-safe — each dispatch consumes them (``donate=True`` donates the
    carries; :meth:`donation_mask` is the widest sound per-argnum mask). The
    producer BOUNDARY value is multiply-consumed (every branch reads it), so
    it is never threaded through as a donated argument: it rides the carry of
    a ``devchain_boundary`` fence stage, which makes it a program OUTPUT
    root — XLA materializes exactly the value the standalone producer would
    have produced (the fused-vs-actor bit-equality contract) and the donation
    analysis never sees it as an aliasable input.

    Duck-types the :class:`Pipeline` surface the TPU kernel blocks consume
    (``in_dtype``/``stages``/``frame_multiple``/``init_carry``/``fn``/
    ``wired_fn``/``compile``/``compile_wired``/``update_stage``), with the
    single-output fields generalized per branch: ``out_dtypes[j]``,
    ``path_ratios[j]`` (producer·branch rate), ``branch_out_items(j, n)``.
    ``stages`` is the FLAT concatenation (producer then branches in order),
    which is also the carry layout — ``update_stage`` addresses it exactly
    like a linear pipeline's (the devchain ctrl-retune contract).
    """

    def __init__(self, producer_stages: Sequence[Stage],
                 branch_stage_lists: Sequence[Sequence[Stage]], in_dtype,
                 optimize: bool = True):
        if not branch_stage_lists or len(branch_stage_lists) < 2:
            raise ValueError("FanoutPipeline needs >= 2 branches "
                             "(use Pipeline for linear chains)")
        self.in_dtype = np.dtype(in_dtype)
        # the AS-GIVEN stage lists, before any LTI merging: the streamed-pick
        # cache records a signature from these too, so a devchain-composed
        # region (per-member optimized names) still finds the pick when the
        # caller's optimize=True merged stages across member boundaries
        self.raw_stage_lists = (list(producer_stages),
                                [list(bs) for bs in branch_stage_lists])
        self.producer = Pipeline(list(producer_stages), in_dtype,
                                 optimize=optimize)
        self.branches = [Pipeline(list(bs), self.producer.out_dtype,
                                  optimize=optimize)
                         for bs in branch_stage_lists]
        self.stages = list(self.producer.stages)
        for b in self.branches:
            self.stages.extend(b.stages)
        # input-frame contract: the lcm of every producer→branch path's
        # requirement (each path is a linear pipeline; reuse its math)
        fm = self.producer.frame_multiple
        for b in self.branches:
            path = Pipeline(self.producer.stages + b.stages, in_dtype,
                            optimize=False)
            fm = int(np.lcm(fm, path.frame_multiple))
        self.frame_multiple = fm
        self.path_ratios = [self.producer.ratio * b.ratio
                            for b in self.branches]
        self.out_dtypes = [b.out_dtype for b in self.branches]
        self.n_branches = len(self.branches)
        # single-output compatibility surface (wire picking / link budgeting):
        # total output items per input item, and the first branch's dtype
        self.ratio = sum(self.path_ratios, Fraction(0, 1))
        self.out_dtype = self.out_dtypes[0]
        self._fn = None
        self._wired_fns = {}

    def branch_out_items(self, branch: int, in_items: int) -> int:
        q = Fraction(in_items) * self.path_ratios[branch]
        assert q.denominator == 1, (in_items, self.path_ratios[branch])
        return int(q)

    def out_items(self, in_items: int) -> int:
        """TOTAL items across branches per ``in_items`` inputs (the linear
        surface; per-branch counts come from :meth:`branch_out_items`)."""
        q = Fraction(in_items) * self.ratio
        assert q.denominator == 1
        return int(q)

    def init_carry(self):
        """Flat carries: producer slots then each branch's, matching
        ``self.stages`` (the ``update_stage`` addressing contract)."""
        out = list(self.producer.init_carry())
        for b in self.branches:
            out.extend(b.init_carry())
        return tuple(out)

    def fn(self):
        """``run(carries, x) -> (carries, (y_0, …, y_{N-1}))``: the producer
        output is computed once and consumed by every branch in-program."""
        if self._fn is None:
            n_p = len(self.producer.stages)
            pfn = self.producer.fn()
            bfns = [b.fn() for b in self.branches]
            sizes = [len(b.stages) for b in self.branches]

            def run(carries, x):
                pc, mid = pfn(tuple(carries[:n_p]), x)
                new_c, outs, off = list(pc), [], n_p
                for bf, sz in zip(bfns, sizes):
                    bc, y = bf(tuple(carries[off:off + sz]), mid)
                    new_c.extend(bc)
                    outs.append(y)
                    off += sz
                return tuple(new_c), tuple(outs)

            self._fn = run
        return self._fn

    def part_counts(self, wire) -> tuple:
        """Wire parts PER BRANCH of the wired form's flat output (a quantizing
        wire ships payload + scale; f32/bf16 ship one part) — the re-nesting
        key for drain loops consuming the flat part tuple."""
        from .wire import get_wire
        wire = get_wire(wire)
        return tuple(wire.part_count(dt) for dt in self.out_dtypes)

    def in_part_count(self, wire) -> int:
        from .wire import get_wire
        return get_wire(wire).part_count(self.in_dtype)

    def wired_fn(self, wire, k: int = 1):
        """The fan-out DAG with the wire codec's decode PROLOG fused in and
        one encode EPILOG per branch: ``(carries, *in_parts) -> (carries,
        flat_out_parts)`` where the flat tuple concatenates each branch's
        parts in branch order (:meth:`part_counts` gives the split). ``k > 1``
        is the megabatch scan form, exactly as :meth:`Pipeline.wired_fn`."""
        from .wire import get_wire
        wire = get_wire(wire)
        key = (wire.name, int(k))
        if key not in self._wired_fns:
            inner = self.fn()
            in_dt, w = self.in_dtype, wire

            def run(carries, *parts):
                carries, ys = inner(carries, w.decode_jax(parts, in_dt))
                flat = []
                for y in ys:
                    flat.extend(w.encode_jax(y))
                return carries, tuple(flat)

            if k == 1:
                self._wired_fns[key] = run
            else:
                def run_scan(carries, *parts):
                    def body(c, p):
                        return run(c, *p)
                    return jax.lax.scan(body, carries, tuple(parts))

                self._wired_fns[key] = run_scan
        return self._wired_fns[key]

    def donation_mask(self, wire) -> tuple:
        """The WIDEST sound wired donation mask: the carries AND the input
        wire parts (every argument is single-consumer per dispatch). The
        producer boundary value is NOT in this set by construction — it is a
        program output root (class docstring), so the mask can never alias a
        multiply-consumed value. Opt-in (``compile_wired(donate=mask)``)
        rather than the default: XLA only profits when an input part's
        shape/dtype matches an output's, and warns otherwise."""
        return (0,) + tuple(range(1, 1 + self.in_part_count(wire)))

    # compile/compile_wired/update_stage are the linear pipeline's own
    # methods, borrowed: they touch only the duck-typed surface this class
    # implements (frame_multiple / fn / wired_fn / init_carry / stages, with
    # the flat carry layout matching self.stages by construction), so one
    # implementation serves both and can never diverge. The fan-out-specific
    # donation story lives in :meth:`donation_mask` — pass it as
    # ``compile_wired(donate=...)`` for the widest sound mask (carries +
    # input frame parts; the multiply-consumed boundary value can never
    # appear in any mask because it is not an argument).
    compile = Pipeline.compile
    compile_wired = Pipeline.compile_wired
    packed_wired_fn = Pipeline.packed_wired_fn
    update_stage = Pipeline.update_stage
    # carry checkpointing borrows too: the FLAT carries tuple (producer then
    # branches) is an ordinary pytree, so snapshot/validate/restore of the
    # composed fan-out carry is exactly the linear pipeline's contract — one
    # checkpoint covers every branch's state at once (per-branch replay
    # cursors live in the kernel's drain bookkeeping, not the carry)
    snapshot_carry = Pipeline.snapshot_carry
    carry_matches = Pipeline.carry_matches
    restore_carry = Pipeline.restore_carry


class DagPipeline:
    """A general device-plane stage DAG compiled as ONE multi-output program.

    The explicit node/edge generalization of :class:`FanoutPipeline`: nested
    fan-out (a node's value consumed by several nodes, at ANY depth), fan-IN
    (a node whose first stage is a :class:`MergeStage` over K ordered input
    nodes), and their closure — the diamond ``producer → broadcast →
    branches → merge`` — all collapse into one XLA program whose outputs are
    the DAG's SINK set. This is the compute plane of the whole-receiver
    fusion pass (``runtime/devchain.py``): a ``sync → {demod, chan-est} →
    decode`` region becomes one dispatch per frame with zero interior
    host↔device traffic (the whole-program handoff of arXiv:1810.09868).

    ``nodes`` is a sequence of ``(stage_list, input_ids)`` in TOPOLOGICAL
    order: node 0 is the root (``input_ids == []``, reads the program input);
    every other node lists the node indices feeding it (all ``< i``). A node
    with several inputs must START with a ``MergeStage(k == len(inputs))``;
    plain stages compose linearly after it. Sinks (nodes no other node
    consumes, in index order) are the program outputs.

    Donation contract — exactly :class:`FanoutPipeline`'s, generalized: the
    flat carries and the input wire parts are donation-safe
    (:meth:`donation_mask`); any MULTIPLY-consumed interior value is a node
    output read by several nodes, which is never a program argument, so no
    donation mask can alias it. The devchain builder additionally pins every
    such value (and every member boundary) to standalone numerics with a
    carry-stash ``devchain_boundary`` fence — a program output root.

    Rate contracts: each sink ``j`` carries ``path_ratios[j]`` (output items
    per region-input item — through a merge this SUMS the joined branches in
    ``concat`` mode) and ``tag_ratios[j]`` (the tag-index remap along the
    PRIMARY chain: merges contribute only their own ``ratio``, because tags
    ride input 0 — see :class:`MergeStage`). ``mode="equal"`` merges whose
    input paths arrive at different rates raise ``ValueError`` at
    construction (the devchain finder declines such regions honestly).

    Duck-types the fan-out surface the TPU kernel blocks consume
    (``n_branches``/``path_ratios``/``out_dtypes``/``branch_out_items``/
    ``part_counts``/``in_part_count``/``wired_fn``/``donation_mask`` plus the
    linear compile/checkpoint surface), with ``stages`` the FLAT node-order
    concatenation — also the carry layout, so ``update_stage`` addressing and
    carry checkpointing work exactly as on a linear pipeline.
    """

    def __init__(self, nodes, in_dtype, optimize: bool = False):
        if not nodes:
            raise ValueError("DagPipeline needs at least one node")
        self.in_dtype = np.dtype(in_dtype)
        self.raw_nodes = [(list(sl), tuple(int(j) for j in inputs))
                          for sl, inputs in nodes]
        consumed: dict = {}
        for i, (_sl, inputs) in enumerate(self.raw_nodes):
            if i == 0:
                if inputs:
                    raise ValueError("node 0 is the root and takes the "
                                     "program input (input_ids must be [])")
            elif not inputs:
                raise ValueError(f"node {i} has no inputs (one root only)")
            for j in inputs:
                if not 0 <= j < i:
                    raise ValueError(
                        f"node {i} input {j} violates topological order")
                consumed[j] = consumed.get(j, 0) + 1
        self.sinks = [i for i in range(len(self.raw_nodes))
                      if i not in consumed]
        # -- per-node stage lists (optionally LTI-merged per linear segment) --
        self._nodes: list = []           # (stages, inputs, carry_offset)
        self.stages: list = []
        # -- rate/dtype walk: r = items per region-input item in front of the
        # value; fm accumulates the region-input frame multiple exactly like
        # Pipeline's scan, but per DAG path --
        fm = 1
        node_r: list = []                # per node: output rate
        node_dt: list = []               # per node: output dtype
        node_tag_r: list = []            # per node: primary-chain tag remap
        for i, (sl, inputs) in enumerate(self.raw_nodes):
            stages = list(sl)
            if len(inputs) > 1:
                if not stages or not isinstance(stages[0], MergeStage):
                    raise ValueError(
                        f"node {i} joins {len(inputs)} inputs but does not "
                        f"start with a MergeStage")
                m = stages[0]
                if m.k != len(inputs):
                    raise ValueError(
                        f"node {i}: MergeStage k={m.k} != {len(inputs)} "
                        f"inputs")
                in_rs = [node_r[j] for j in inputs]
                in_dts = {np.dtype(node_dt[j]) for j in inputs}
                if len(in_dts) != 1:
                    raise ValueError(
                        f"node {i}: merge inputs disagree on dtype "
                        f"({sorted(str(d) for d in in_dts)})")
                for r_i in in_rs:
                    need = Fraction(m.frame_multiple, 1) / r_i
                    fm = int(np.lcm(fm, need.numerator))
                if m.mode == "equal":
                    if len(set(in_rs)) != 1:
                        raise ValueError(
                            f"node {i}: equal-mode merge rate contract "
                            f"violated (input path rates {in_rs})")
                    r = in_rs[0] * m.ratio
                else:                    # concat: output counts every input
                    r = sum(in_rs, Fraction(0, 1)) * m.ratio
                fm = int(np.lcm(fm, r.denominator))
                dt = np.dtype(m.out_dtype) if m.out_dtype is not None \
                    else in_dts.pop()
                tag_r = node_tag_r[inputs[0]] * m.ratio
                rest = stages[1:]
            else:
                r = node_r[inputs[0]] if inputs else Fraction(1, 1)
                dt = np.dtype(node_dt[inputs[0]]) if inputs \
                    else self.in_dtype
                tag_r = node_tag_r[inputs[0]] if inputs else Fraction(1, 1)
                m = None
                rest = stages
            if any(isinstance(s, MergeStage) for s in rest):
                raise ValueError(
                    f"node {i}: a MergeStage may only be a multi-input "
                    f"node's FIRST stage")
            if optimize and rest:
                rest = _merge_lti(rest, dt)
            for s in rest:
                need = Fraction(s.frame_multiple, 1) / r
                fm = int(np.lcm(fm, need.numerator))
                r *= s.ratio
                tag_r *= s.ratio
                fm = int(np.lcm(fm, r.denominator))
                if s.out_dtype is not None:
                    dt = np.dtype(s.out_dtype)
            node_r.append(r)
            node_dt.append(dt)
            node_tag_r.append(tag_r)
            final = ([m] if m is not None else []) + list(rest)
            self._nodes.append((final, tuple(inputs), len(self.stages)))
            self.stages.extend(final)
        self.frame_multiple = fm
        self.node_ratios = list(node_r)
        self.node_dtypes = list(node_dt)
        # -- fan-out-compatible sink surface ---------------------------------
        self.n_branches = len(self.sinks)
        self.path_ratios = [node_r[s] for s in self.sinks]
        self.tag_ratios = [node_tag_r[s] for s in self.sinks]
        self.out_dtypes = [node_dt[s] for s in self.sinks]
        # per sink: does its path cross a concat-mode merge? A concat output
        # interleaves its inputs' FULL frames back to back, so a partial
        # (EOS-tail) input frame cannot be represented by a valid-prefix
        # count — such sinks emit only full frames (the kernels' drain clamps
        # a partial group's valid to 0; same rule as TpuMergeStage's actor
        # path), which stays inside the devchain EOS-tail divergence contract
        crossed = []
        for i, (_sl, inputs) in enumerate(self.raw_nodes):
            c = any(crossed[j] for j in inputs)
            first = self._nodes[i][0][0] if self._nodes[i][0] else None
            if isinstance(first, MergeStage) and first.mode == "concat":
                c = True
            crossed.append(c)
        self.concat_sinks = [crossed[s] for s in self.sinks]
        self.ratio = sum(self.path_ratios, Fraction(0, 1))
        self.out_dtype = self.out_dtypes[0]
        self._fn = None
        self._wired_fns = {}

    def init_carry(self):
        """Flat carries in node order, matching ``self.stages`` (the
        ``update_stage`` / checkpoint addressing contract)."""
        carries = []
        for i, (stages, inputs, _off) in enumerate(self._nodes):
            dt = self.in_dtype if not inputs \
                else np.dtype(self.node_dtypes[inputs[0]])
            for s in stages:
                carries.append(s.init_carry(dt))
                if s.out_dtype is not None:
                    dt = np.dtype(s.out_dtype)
        return tuple(carries)

    def fn(self):
        """``run(carries, x) -> (carries, (y_sink0, …))``: every interior
        edge stays in-program — a multiply-consumed node output feeds each
        consumer without rematerialization, a merge node reads its K input
        values as one tuple."""
        if self._fn is None:
            nodes = self._nodes
            sinks = self.sinks

            def run(carries, x):
                new_c = list(carries)
                vals: list = [None] * len(nodes)
                for i, (stages, inputs, off) in enumerate(nodes):
                    if not inputs:
                        v = x
                    elif len(inputs) == 1:
                        v = vals[inputs[0]]
                    else:
                        v = tuple(vals[j] for j in inputs)
                    for si, s in enumerate(stages):
                        c, v = s.fn(carries[off + si], v)
                        new_c[off + si] = c
                    vals[i] = v
                return tuple(new_c), tuple(vals[s] for s in sinks)

            self._fn = run
        return self._fn

    # the per-sink item math, flat multi-output wired form, donation mask and
    # the linear compile/checkpoint surface are exactly the fan-out
    # pipeline's — the sink tuple quacks like the branch tuple (part_counts
    # gives the split)
    branch_out_items = FanoutPipeline.branch_out_items
    out_items = FanoutPipeline.out_items
    part_counts = FanoutPipeline.part_counts
    in_part_count = FanoutPipeline.in_part_count
    wired_fn = FanoutPipeline.wired_fn
    donation_mask = FanoutPipeline.donation_mask
    compile = Pipeline.compile
    compile_wired = Pipeline.compile_wired
    packed_wired_fn = Pipeline.packed_wired_fn
    update_stage = Pipeline.update_stage
    snapshot_carry = Pipeline.snapshot_carry
    carry_matches = Pipeline.carry_matches
    restore_carry = Pipeline.restore_carry


def _merge_lti(stages: Sequence[Stage], in_dtype) -> list:
    """Peephole pass: collapse runs of adjacent LTI FIR stages into ONE overlap-save.

    A cascade of FIRs is itself an FIR with the convolved taps; filtering after a
    decimator by ``d`` equals filtering with the taps zero-stuffed by ``d`` before it
    (noble identity), so ``(t1, d1) · (t2, d2) → (t1 * stuff(t2, d1), d1·d2)``. On the
    device this is the big fusion win: N stage cascades cost ONE FFT pass instead of N
    (the reference pays per-block dispatch here, ``perf/fir/fir.rs:49-95``).

    The stream dtype is tracked through the chain: on a REAL stream each FIR stage
    takes ``.real`` at its boundary, so complex-tap runs only merge where the stream
    is complex at that position.
    """
    out: list = []
    dtype = np.dtype(in_dtype)
    out_dtypes: list = []               # stream dtype ENTERING each stage in `out`
    for s in stages:
        if s.lti is not None and out and out[-1].lti is not None:
            t1, d1, fl1, im1 = out[-1].lti
            t2, d2, fl2, im2 = s.lti
            # per-call-site route pins (fft_impl, precision): merge only when
            # both sides agree — a merged stage can honor ONE pin set, and
            # silently dropping a pin would revert the stage to the module
            # policy / f32, defeating exactly what the pin bought
            p1 = (out[-1].route or (None, None, None))[1:]
            p2 = (s.route or (None, None, None))[1:]
            complex_stream = bool(np.issubdtype(out_dtypes[-1], np.complexfloating))
            if p1 != p2 or (not complex_stream
                            and not (np.isrealobj(t1) and np.isrealobj(t2))):
                # (real streams take .real at EACH stage boundary; merging
                # complex-tap cascades would change that — only safe on
                # complex streams)
                out.append(s)
                out_dtypes.append(dtype)
                if s.out_dtype is not None:
                    dtype = np.dtype(s.out_dtype)
                continue
            if d1 == 1:
                taps = np.convolve(t1, t2)
            else:
                up = np.zeros((len(t2) - 1) * d1 + 1, dtype=np.result_type(t1, t2))
                up[::d1] = t2
                taps = np.convolve(t1, up)
            # an explicit "os" on either side pins the merged numerics; "pallas"/
            # "poly" survive only if both sides forced them (and the merged taps
            # allow it) — a force must not silently downgrade to "auto"
            impl = "os" if "os" in (im1, im2) else \
                ("pallas" if im1 == im2 == "pallas" else
                 ("poly" if im1 == im2 == "poly" else "auto"))
            out[-1] = fir_stage(taps, decim=d1 * d2, fft_len=max(fl1, fl2),
                                name=f"{out[-1].name}*{s.name}", impl=impl,
                                fft_impl=p1[0], precision=p1[1])
            # stream dtype entering the merged stage is unchanged; FIR stages keep the
            # stream dtype so `dtype` needs no update here
        else:
            out.append(s)
            out_dtypes.append(dtype)
            if s.out_dtype is not None:
                dtype = np.dtype(s.out_dtype)
    return out


# ---------------------------------------------------------------------------
# stage factories
# ---------------------------------------------------------------------------

def _pallas_fir_wins(nt: int, is_complex: bool) -> bool:
    """Trace-time choice of the direct pallas FIR over FFT overlap-save.

    Round-5 on-chip sweep (v5e through the tunnel, `perf/probes/ab_r5.py`,
    frame 512k, marginal methodology): real 16 taps the pallas kernel is a
    decisive 3.3x over overlap-save (9.5 vs 2.9 Gsps, far outside the tunnel's
    ~±2x per-draw dispersion); the advantage decays with tap count and the
    median-of-3 crossover sits between 48 (pallas +12%) and 64 (OS +17%).
    Complex frames pay two real passes: a tie at 16 taps, OS-favored by 32 —
    at a tie OS wins (one pass, no split). Hence real <= 48, complex never.
    """
    if jax.default_backend() != "tpu":
        return False
    return (not is_complex) and nt <= 48


def fir_stage(taps, decim: int = 1, fft_len: int = 8192, name: str = "fir",
              impl: str = "auto", fft_impl: Optional[str] = None,
              precision: Optional[str] = None) -> Stage:
    """FFT overlap-save FIR (+ optional decimation) as a jitted stage.

    History carry = last ``ntaps-1`` inputs (the `min_items` overlap of `fir.rs:49`
    reframed for frames, SURVEY §5 long-context note). The frame is blocked into
    ``fft_len`` segments with hop ``L = fft_len - (ntaps-1)`` and filtered in the
    frequency domain — batched 2D FFTs are the TPU-idiomatic FIR (direct time-domain
    convolution compiles poorly at SDR frame sizes on the TPU backend). The
    frequency-domain taps ride in the carry (identity pass-through under XLA
    input-output aliasing), which also makes them donation-safe and hot-swappable.

    ``impl``: "auto" additionally routes short real-tap filters to the direct pallas
    kernel on TPU (see :func:`_pallas_fir_wins`), and decimating filters with modest
    per-output work to the polyphase-decimation einsum (see below); "os" forces
    overlap-save; "pallas" forces the direct kernel (CI exercises it in interpret
    mode); "poly" forces the decimating einsum.

    Polyphase decimation (``decim > 1``): computing the full-rate convolution and
    slicing ``y[::D]`` wastes (D-1)/D of the FLOPs. The decimated output is
    ``y[q] = Σ_t taps[t] · x[q·D − t]`` — windows of ``ntaps`` samples at stride D,
    which (like :func:`resample_stage`'s poly path) are STATIC slices of a row-concat
    matrix, contracted against the reversed taps in one MXU einsum: ntaps/D MACs per
    input sample, and the stage's frame multiple drops from lcm(hop, D) to D.
    Matches ``decimate == true`` FIR cores (``futuredsp/fir.rs:31``) re-designed for
    the MXU rather than translated.

    ``fft_impl`` pins the overlap-save core's FFT implementation PER CALL SITE
    (``mxu_fft.fft(impl=…)``): the module ``set_impl`` policy binds at trace time
    and jit caches keep whichever path was bound first, so a per-stage pin is
    the only way two chains in one process can hold different FFT routes
    (the plumbing promised in the ``ops/mxu_fft.py`` header).

    ``precision="bf16"`` builds the interior-precision-lowered variant
    (``ops/precision.py``): bf16 MXU passes in the overlap-save FFTs, bf16
    tap/accumulation in the pallas and polyphase kernels (carried weights land
    in bf16). ``precision="int8"`` (real taps only) abandons the FFT form
    entirely — no useful int8 FFT exists — and runs the convolution as a
    banded windowed matmul: the frame blocks into ``Bq``-sample tiles
    (each with its left neighbour, the overlap-save trick in the time
    domain), both operands absmax-quantized to int8 in-trace, one
    ``[2Bq]·[2Bq, Bq]`` int8 matmul with int32 accumulation per tile. The
    band matrix is built from the CARRIED taps so runtime swaps reach it, and
    the carry tree (spectrum, taps, tail) is bit-compatible with the f32
    stage — the serve brownout's leaf conversion and the checkpoint leaf
    contract both depend on that. The f32-built stage exposes both lowerings
    through its ``Stage.lower`` hook — the SNR-budgeted pass uses that.
    """
    assert impl in ("auto", "os", "pallas", "poly"), impl
    taps = np.asarray(taps)
    nt = len(taps)
    built_real = np.isrealobj(taps)     # baked into the traced branches; the update
    #                                     hook refuses swaps that would change it
    # auto cap nt/D ≤ 32: the poly window matrix materializes ~nt/D × the frame in
    # HBM, so the route stays where both the MACs/input and the intermediate are
    # modest; longer filters keep the OS path's fixed fft_len working set.
    # An explicit pallas force on a DECIMATING filter routes through the poly
    # factorization too — its fused FIR→decimate kernel (pallas_poly_fir)
    # computes at the decimated rate instead of full-rate-then-slice.
    if impl == "poly" or (impl == "pallas" and decim > 1) \
            or (impl == "auto" and decim > 1 and nt <= 32 * decim):
        return _poly_decim_fir_stage(taps, decim, fft_len, name, impl,
                                     precision=precision)
    if impl == "pallas":
        # an explicit force must not silently no-op: the kernel is real-taps-only
        assert np.isrealobj(taps) and nt >= 2, \
            "impl='pallas' requires >= 2 real taps (complex taps: use the OS path)"
    # 50% overlap-save with power-of-two hop L and fft_len = 2L: radix-friendly FFTs and
    # power-of-two frame multiples (at the cost of carrying L instead of ntaps-1 samples).
    L = fft_len // 2
    while L < 2 * nt:                   # hop must comfortably exceed the tap overlap
        L *= 2
    fft_len = 2 * L
    if precision == "int8":
        assert built_real, "precision='int8' requires real taps"
    # int8 banded-matmul tile: a power of two dividing the hop L (frames are
    # L-multiples, so they block evenly) that covers the tap overlap in one
    # left-neighbour tile (Bq >= nt-1; pow2ceil(nt-1) <= L since L >= 2*nt)
    Bq = min(L, 128)
    while Bq < nt - 1:
        Bq *= 2

    def _spectra(t):
        # full spectrum, and the real-input half spectrum (real inputs discard the
        # imaginary response, so conv(x, t).real == conv(x, t.real) — same semantics)
        full = np.fft.fft(np.concatenate([t, np.zeros(fft_len - nt)])
                          ).astype(np.complex64)
        half = np.fft.rfft(np.concatenate([np.real(t), np.zeros(fft_len - nt)])
                           ).astype(np.complex64)
        return full, half

    H, Hr = _spectra(taps)

    fft_prec = "bf16" if precision == "bf16" else None

    def fn(carry, x):
        Hc, tt, tail = carry
        if precision == "int8":
            # int8 ladder rung: banded windowed matmul over Bq-sample tiles.
            # T[j, i] = taps[Bq + i − j], so tile s's output
            # y[s·Bq + i] = Σ_j ext8[s·Bq + j] · T[j, i] = Σ_k taps[k]·x[s·Bq+i−k]
            # with ext8 carrying Bq history samples in front (Bq >= nt−1).
            jj = jnp.arange(2 * Bq)[:, None]
            ii = jnp.arange(Bq)[None, :]
            kk = Bq + ii - jj
            T = jnp.where((kk >= 0) & (kk < nt),
                          tt[jnp.clip(kk, 0, nt - 1)], 0.0)
            sw = jnp.maximum(jnp.max(jnp.abs(tt)), 1e-30) / 127.0
            Tq = jnp.round(T / sw).astype(jnp.int8)

            def _conv(plane):
                sx = jnp.maximum(jnp.max(jnp.abs(plane)), 1e-30) / 127.0
                q = jnp.round(plane / sx).astype(jnp.int8)
                rq = q.reshape(-1, Bq)                      # [S+1, Bq]
                blk = jnp.concatenate([rq[:-1], rq[1:]], axis=1)   # [S, 2Bq]
                acc = jnp.matmul(blk, Tq,
                                 preferred_element_type=jnp.int32)
                return acc.reshape(-1).astype(jnp.float32) * (sx * sw)

            ext8 = jnp.concatenate([tail[L - Bq:], x])
            if jnp.iscomplexobj(x):
                y = jax.lax.complex(_conv(ext8.real), _conv(ext8.imag))
            else:
                y = _conv(ext8)
            y = y.astype(x.dtype)
            if decim > 1:
                y = y[::decim]
            # frames are >= L samples (frame_multiple), so the new tail is
            # the frame's own last L samples
            return (Hc, tt, x[x.shape[0] - L:]), y
        ext = jnp.concatenate([tail, x])             # [(S+1)·L], S = n // L
        is_c = jnp.iscomplexobj(x)
        if impl != "os" and np.isrealobj(taps) and nt >= 2 and (
                impl == "pallas" or _pallas_fir_wins(nt, is_c)):
            from .pallas_kernels import pallas_fir_continue
            # time-domain taps come from the CARRY (not the closure) so a runtime
            # tap swap reaches the pallas path too — same shape, no recompile
            y = pallas_fir_continue(ext[L - (nt - 1):L], x, tt,
                                    precision=precision)
            if decim > 1:
                y = y[::decim]
            return (Hc, tt, ext[ext.shape[0] - L:]), y
        # block s = ext[sL : sL+2L] = rows[s] ++ rows[s+1]: built from two strided
        # slices + concat, NOT a gather — TPU gathers run ~9× slower than this form
        rows = ext.reshape(-1, L)
        blocks = jnp.concatenate([rows[:-1], rows[1:]], axis=1)   # [S, 2L]
        if jnp.iscomplexobj(x):
            spec = mxu_fft.fft(blocks, precision=fft_prec,
                               impl=fft_impl) * Hc[None, :]
            seg = mxu_fft.ifft(spec, precision=fft_prec,
                               impl=fft_impl)[:, L:]   # linear-conv region
        elif Hc.shape[0] == fft_len:
            # real input with a full-spectrum carry (chosen at init_carry time when the
            # MXU policy was active — the four-step has no half-spectrum variant; it
            # still beats the XLA rfft). Branching on the carry shape keeps fn and
            # carry coherent even if the policy flips between init and trace.
            spec = mxu_fft.fft(blocks.astype(jnp.complex64), precision=fft_prec,
                               impl=fft_impl) * Hc[None, :]
            seg = mxu_fft.ifft(spec, precision=fft_prec, impl=fft_impl)[:, L:].real
        else:
            spec = jnp.fft.rfft(blocks, axis=1) * Hc[None, :]
            seg = jnp.fft.irfft(spec, n=fft_len, axis=1)[:, L:]
        y = seg.reshape(-1).astype(x.dtype)
        if decim > 1:
            y = y[::decim]
        return (Hc, tt, ext[ext.shape[0] - L:]), y

    def init_carry(dtype):
        dt = np.dtype(dtype)
        use_full = (np.issubdtype(dt, np.complexfloating)
                    or mxu_fft._use_mxu(fft_len, fft_impl))
        Hsel = H if use_full else Hr
        # complex H2D (incl. eager jnp.zeros, which is a host device_put!) must ride
        # the pair shim — broken complex transfers on axon, see ops/xfer.py
        from .xfer import to_device
        return (to_device(Hsel), to_device(np.real(taps).astype(np.float32)),
                to_device(np.zeros(L, dtype=dt)))

    def update(carry, taps=None):
        """Swap the filter while frames are in flight: same tap COUNT (shapes are
        static under jit), new response. Rebuilds the spectrum matching the carry's
        layout (full vs half, inferred from the carried H's length) and the
        time-domain taps the pallas branch reads; history is preserved, so the
        transition is seamless after nt-1 samples. New arrays land on the device
        the carry lives on."""
        if taps is None:
            return carry
        new = np.asarray(taps)
        if len(new) != nt:
            raise ValueError(
                f"tap swap must keep the tap count ({nt}); got {len(new)} — "
                f"rebuild the stage for a different filter length")
        if np.iscomplexobj(new) and built_real:
            # realness is baked at trace time (pallas branch, half-spectrum path);
            # a complex swap on a real-built stage would silently drop .imag there
            raise ValueError(
                "stage was built with real taps; swapping to complex taps "
                "requires rebuilding the stage")
        Hc_old, _tt, tail = carry
        full, half = _spectra(new)
        from .xfer import to_device
        dev = next(iter(tail.devices())) if isinstance(tail, jax.Array) else None
        Hn = full if Hc_old.shape[0] == fft_len else half
        return (to_device(Hn, dev),
                to_device(np.real(new).astype(np.float32), dev), tail)

    # frame must be a multiple of the hop (and of decim at the output side)
    multiple = int(np.lcm(L, decim))

    def _lower(p: str) -> Optional[Stage]:
        if p == "bf16" or (p == "int8" and built_real):
            return fir_stage(taps, decim=decim, fft_len=fft_len, name=name,
                             impl=impl, fft_impl=fft_impl, precision=p)
        return None

    return Stage(fn, init_carry, Fraction(1, decim), None, multiple, name,
                 lti=(taps, decim, fft_len, impl), update=update,
                 lower=_lower,
                 compute_dtype=(precision if precision in ("bf16", "int8")
                                else "f32"),
                 route=(impl, fft_impl, precision))


def _int8_shifted_matvec(rows, W, m: int, nq: int):
    """The int8 ladder rung of :func:`_shifted_matvec` (real planes only):
    dynamic absmax quantization of BOTH operands (scale = absmax/127 — the
    standard symmetric int8 scheme), every shifted MAC on the int8 matmul
    path with int32 accumulation, one dequantize at the sink. The scales are
    data-derived in-trace, so the carried weight matrix stays float32 and the
    carry tree is bit-compatible with the f32 stage (the serve brownout's
    leaf-wise ``astype`` conversion and the checkpoint leaf contract both
    rely on that — see ops/precision.py)."""
    from functools import partial as _partial
    sw = jnp.maximum(jnp.max(jnp.abs(W)), 1e-30) / 127.0
    Wq = jnp.round(W / sw).astype(jnp.int8)
    sx = jnp.maximum(jnp.max(jnp.abs(rows)), 1e-30) / 127.0
    rq = jnp.round(rows / sx).astype(jnp.int8)
    mm = _partial(jnp.matmul, preferred_element_type=jnp.int32)
    acc = mm(rq[m:m + nq], Wq[0])
    for r in range(1, m + 1):
        acc = acc + mm(rq[m - r:m - r + nq], Wq[r])
    return acc.astype(jnp.float32) * (sx * sw)


def _shifted_matvec(ext: jnp.ndarray, W, m: int, nq: int,
                    precision: Optional[str] = None):
    """``y = Σ_{r=0..m} rows[m−r : m−r+nq] @ W[r]`` with ``rows = ext.reshape(-1, D)``
    (a view — nothing materialized). The shared accumulation of the shifted-row
    polyphase factorization (_poly_decim_fir_stage / resample_stage /
    xlating_fir_stage); HIGHEST precision by default so no TPU bf16 passes sneak
    in. ``precision="bf16"`` (the interior-precision policy, ops/precision.py)
    casts REAL operands to bfloat16 with float32 accumulation — the native MXU
    pass on TPU, the identical quantization on CPU; complex operands (no bf16
    complex exists) fall back to DEFAULT matmul precision, which is the bf16-pass
    path on TPU and a no-op on CPU. ``precision="int8"`` (real weights only —
    the lower hooks guard that) quantizes through :func:`_int8_shifted_matvec`,
    complex streams per re/im plane."""
    from functools import partial as _partial
    D = W.shape[-2] if W.ndim == 3 else W.shape[-1]
    rows = ext.reshape(-1, D)
    if precision == "int8" and not jnp.iscomplexobj(W):
        if jnp.iscomplexobj(rows):
            return jax.lax.complex(
                _int8_shifted_matvec(rows.real, W, m, nq),
                _int8_shifted_matvec(rows.imag, W, m, nq))
        return _int8_shifted_matvec(rows, W, m, nq)
    if precision == "bf16" and not (jnp.iscomplexobj(rows)
                                    or jnp.iscomplexobj(W)):
        rows = rows.astype(jnp.bfloat16)
        W = W.astype(jnp.bfloat16)
        mm = _partial(jnp.matmul, precision=jax.lax.Precision.DEFAULT,
                      preferred_element_type=jnp.float32)
    elif precision == "bf16":
        mm = _partial(jnp.matmul, precision=jax.lax.Precision.DEFAULT)
    else:
        mm = _partial(jnp.matmul, precision=jax.lax.Precision.HIGHEST)
    y = mm(rows[m:m + nq], W[0])
    for r in range(1, m + 1):
        y = y + mm(rows[m - r:m - r + nq], W[r])
    return y


def _poly_decim_weights(taps: np.ndarray, D: int, m: int) -> np.ndarray:
    """Arrange ``taps`` as the shifted-row weight matrix ``W[r, s] = taps[r·D − s]``
    (zero where out of range), so ``y[q] = Σ_r rows[q+m−r] · W[r]`` with
    ``rows[j, s] = ext[j·D + s]`` — see :func:`_poly_decim_fir_stage`."""
    nt = len(taps)
    W = np.zeros((m + 1, D), taps.dtype)
    for r in range(m + 1):
        for s in range(D):
            t = r * D - s
            if 0 <= t < nt:
                W[r, s] = taps[t]
    return W


def _poly_decim_fir_stage(taps: np.ndarray, decim: int, fft_len: int,
                          name: str, impl: str,
                          precision: Optional[str] = None) -> Stage:
    """Decimating FIR as m+1 shifted matvecs over the stride-D row matrix.

    ``y[q] = Σ_t taps[t] · x[q·D − t]``. Decompose ``t = r·D − s``: with
    ``rows[j, s] = ext[j·D + s]`` (a RESHAPE of the input — no copy),
    ``y[q] = Σ_{r=0..m} rows[q+m−r] · W[r]`` where ``W[r, s] = taps[r·D − s]``.
    Each term is a [n/D, D]·[D] matvec on a static slice of ``rows`` — ntaps/D
    MACs per input with NO materialized window matrix. The previous einsum form
    concatenated an (m+1)·D-wide window matrix first ((m+1)× the input in HBM
    writes); dropping it is ~10× on the CPU backend for the FM channel filter
    (128 taps, D=16) and strictly less HBM traffic on TPU (VERDICT r3 weak 2).
    The weight matrix rides the carry, so it is donation-safe and hot-swappable
    exactly like the OS path's frequency-domain ``Hc``.

    ``impl="pallas"`` routes REAL weight matrices through the fused
    FIR→decimate kernel (``pallas_kernels.pallas_poly_fir``): the same
    shifted-row MACs computed inside one kernel at the decimated rate (complex
    frames run two real passes; complex taps keep the matvec path — the kernel
    is real-only). ``precision="bf16"`` carries the weight matrix in bfloat16
    and runs the MACs with bf16 operands / f32 accumulation on either path.
    ``precision="int8"`` (real taps only) runs the shifted MACs as int8×int8
    matmuls with int32 accumulation (:func:`_int8_shifted_matvec`); the Pallas
    kernel is f32/bf16-only, so an int8 build routes the matvec path and the
    carried weights STAY float32 (quantized in-trace) — the carry tree is
    bit-compatible with the f32 stage for brownout/checkpoint conversion.
    """
    D = int(decim)
    nt = len(taps)
    built_real = np.isrealobj(taps)
    m = max(1, -(-(nt - 1) // D))       # history rows so windows never underflow
    H = m * D

    def fn(carry, x):
        W, hist = carry
        ext = jnp.concatenate([hist, x])                 # [H + n]
        if impl == "pallas" and not jnp.iscomplexobj(W) \
                and precision != "int8":
            from .pallas_kernels import pallas_poly_fir
            if jnp.iscomplexobj(x):
                yr = pallas_poly_fir(ext.real.reshape(-1, D), W,
                                     precision=precision)
                yi = pallas_poly_fir(ext.imag.reshape(-1, D), W,
                                     precision=precision)
                y = jax.lax.complex(yr, yi)
            else:
                y = pallas_poly_fir(ext.reshape(-1, D), W,
                                    precision=precision)
        else:
            y = _shifted_matvec(ext, W, m, x.shape[0] // D,
                                precision=precision)
        return (W, ext[ext.shape[0] - H:]), y.astype(x.dtype)

    def _weights(t, complex_stream: bool):
        # a real stream takes .real at the stage boundary (same semantics as the OS
        # path's half-spectrum Hr) — bake that into the carried weights
        teff = t if complex_stream else np.real(t)
        teff = teff.astype(np.complex64 if np.iscomplexobj(teff) else np.float32)
        W = _poly_decim_weights(teff, D, m)
        if precision == "bf16" and not np.iscomplexobj(W):
            import ml_dtypes
            W = W.astype(ml_dtypes.bfloat16)   # carried weights: half the HBM
        return W

    def init_carry(dtype):
        dt = np.dtype(dtype)
        from .xfer import to_device
        return (to_device(_weights(taps, np.issubdtype(dt, np.complexfloating))),
                to_device(np.zeros(H, dtype=dt)))

    def update(carry, taps=None):
        """Runtime tap swap (same count — shapes are static under jit); the carried
        weight matrix is rebuilt with the SAME complex/real treatment init_carry
        applied, keyed on the stream dtype (the carried history's dtype)."""
        if taps is None:
            return carry
        new = np.asarray(taps)
        if len(new) != nt:
            raise ValueError(
                f"tap swap must keep the tap count ({nt}); got {len(new)} — "
                f"rebuild the stage for a different filter length")
        if np.iscomplexobj(new) and built_real:
            raise ValueError(
                "stage was built with real taps; swapping to complex taps "
                "requires rebuilding the stage")
        _w_old, hist = carry
        from .xfer import to_device
        dev = next(iter(hist.devices())) if isinstance(hist, jax.Array) else None
        complex_stream = np.issubdtype(hist.dtype, np.complexfloating)
        return (to_device(_weights(new, complex_stream), dev), hist)

    def _lower(p: str) -> Optional[Stage]:
        if p not in ("bf16", "int8") or not built_real:
            return None
        return _poly_decim_fir_stage(taps, D, fft_len, name, impl,
                                     precision=p)

    return Stage(fn, init_carry, Fraction(1, D), None, D, name,
                 lti=(taps, D, fft_len, impl), update=update,
                 lower=_lower,
                 compute_dtype=(precision if precision in ("bf16", "int8")
                                else "f32"),
                 route=(impl, None, precision))


def resample_stage(interp: int, decim: int, taps=None, fft_len: int = 8192,
                   name: str = "resample", impl: str = "poly") -> Stage:
    """Rational I/D resampler as a fused stage — the TPU counterpart of
    ``PolyphaseResamplingFir`` (``futuredsp/polyphase_resampling_fir.rs:41``).

    ``impl="poly"`` (default): true polyphase — phase-grouped stride-D windows built
    from static slices, contracted against the phase-tap matrix in one MXU einsum.
    ``impl="pallas"``: the same factorization computed inside the fused
    polyphase kernel (``pallas_kernels.pallas_poly_fir`` with the 3-D
    phase-tap tensor) — the resampler's inner loop on the autotuned Pallas
    plane; complex frames run two real passes.
    ``impl="stuff"``: the earlier zero-stuff ×I → overlap-save lowpass → ↓D form
    (kept for cross-validation and for complex taps)."""
    from math import gcd

    g = gcd(int(interp), int(decim))
    I, D = int(interp) // g, int(decim) // g
    if taps is None:
        from ..dsp import firdes
        r = max(I, D)
        taps = firdes.kaiser_lowpass(0.5 / r * 0.8, 0.1 / r) * I
    taps = np.asarray(taps)
    assert impl in ("poly", "stuff", "pallas"), impl
    if np.iscomplexobj(taps):
        impl = "stuff"                  # poly path computes a plain taps·x dot; the
                                        # stuffed OS path owns complex-tap semantics

    if impl == "stuff":
        inner = fir_stage(taps, decim=1, fft_len=fft_len, name=f"{name}_fir")
        L = inner.frame_multiple                   # hop of the overlap-save core

        def fn(carry, x):
            n = x.shape[0]
            up = jnp.zeros(n * I, dtype=x.dtype).at[::I].set(x)
            carry, y = inner.fn(carry, up)
            if D > 1:
                y = y[::D]
            return carry, y

        def init_carry(dtype):
            return inner.init_carry(dtype)

        # frame n must satisfy: n·I divisible by the OS hop L and by D
        mult = int(np.lcm(L // np.gcd(I, L), D // np.gcd(I, D)))
        return Stage(fn, init_carry, Fraction(I, D), None, mult, name)

    # Polyphase form (default): output j = Σ_t taps[p_j + I·t] · x[s_j − t] with
    # p_j = (j·D) mod I and s_j = ⌊j·D/I⌋. Outputs grouped by residue r = j mod I
    # share one phase p_r = (r·D) mod I and land on stride-D input offsets
    # s = q·D + c_r. Same shifted-matvec factorization as the poly-decimation FIR
    # (see _poly_decim_fir_stage): per group, y_r[q] = Σ_k phase_r[k]·ext[H + q·D
    # + c_r − k]; decomposing the flat index over the stride-D row matrix gives
    # W[r, a, s] = phase_r[a·D + c_r − s] and
    #   y[:, r] = Σ_{a=0..m} rows[m−a : m−a+nq] @ W[r, a]
    # — m+1 true [n/D, D]·[D, I] MXU matmuls, NO materialized window stack (the
    # previous einsum stacked I per-group window matrices — I·Kmax/D× the input
    # in HBM writes; 48 groups for the audio resampler). Cost stays T/D MACs per
    # input vs the zero-stuffed form's I× inflated FFT frames, with no scatter.
    T = len(taps)
    Kmax = -(-T // I)                   # taps per phase
    ftaps = taps.astype(np.float32)
    c_off = [(r_ * D) // I for r_ in range(I)]
    m = max(1, -(-(Kmax - 1) // D))     # history rows so windows never underflow
    #   (also covers the W row range: a ≤ floor((Kmax+D−2)/D) = this m)
    H = m * D
    W = np.zeros((m + 1, D, I), np.float32)       # [row shift, col, group]
    for r_ in range(I):
        phase = ftaps[(r_ * D) % I::I]            # phase_r, length <= Kmax
        for a in range(m + 1):
            for s in range(D):
                k = a * D + c_off[r_] - s
                if 0 <= k < len(phase):
                    W[a, s, r_] = phase[k]

    def fn(carry, x):
        hist = carry
        ext = jnp.concatenate([hist, x])                 # [H + n]
        if impl == "pallas":
            from .pallas_kernels import pallas_poly_fir
            Wj = jnp.asarray(W)
            if jnp.iscomplexobj(x):
                yr = pallas_poly_fir(ext.real.reshape(-1, D), Wj)
                yi = pallas_poly_fir(ext.imag.reshape(-1, D), Wj)
                y = jax.lax.complex(yr, yi)              # [nq, I]
            else:
                y = pallas_poly_fir(ext.reshape(-1, D), Wj)
        else:
            y = _shifted_matvec(ext, jnp.asarray(W), m,
                                x.shape[0] // D)         # [nq, I]
        return ext[ext.shape[0] - H:], y.reshape(-1).astype(x.dtype)

    def init_carry(dtype):
        from .xfer import to_device
        return to_device(np.zeros(H, dtype=np.dtype(dtype)))

    return Stage(fn, init_carry, Fraction(I, D), None, D, name,
                 route=(("pallas", None, None) if impl == "pallas" else None))


def decimate_stage(decim: int) -> Stage:
    def fn(carry, x):
        return carry, x[::decim]

    return Stage(fn, lambda d: jnp.zeros(()), Fraction(1, decim), None, decim, f"decim{decim}")


def fft_stage(n: int, direction: str = "forward", shift: bool = False,
              normalize: bool = False, window=None,
              impl: Optional[str] = None,
              precision: Optional[str] = None) -> Stage:
    """Batched frame FFT: input frame reshaped [-1, n], transformed on axis 1.
    ``window``: optional name/array applied per frame before a forward FFT.

    ``impl``/``precision`` pin the FFT route and MXU matmul precision PER CALL
    SITE (``mxu_fft.fft(impl=…, precision=…)``): the module ``set_impl`` /
    ``set_precision`` policy binds at trace time and jit caches keep the
    first-bound path, so per-stage pins are how two chains in one process hold
    different routes (the ``ops/mxu_fft.py`` header's promised plumbing).
    ``precision="bf16"`` is also what the interior-precision policy
    (``ops/precision.py``) selects through this stage's ``lower`` hook."""
    if window is not None:
        from ..dsp.windows import get_window
        window = np.asarray(window, dtype=np.float32) if not isinstance(window, str) \
            else get_window(window, n).astype(np.float32)
    fft_prec = "bf16" if precision == "bf16" else None

    def fn(carry, x):
        f = x.reshape(-1, n)
        if direction == "forward":
            if window is not None:
                f = f * jnp.asarray(window)[None, :]
            y = mxu_fft.fft(f, precision=fft_prec, impl=impl)
        else:
            y = mxu_fft.ifft(f, precision=fft_prec, impl=impl) * n
        if normalize:
            y = y / jnp.sqrt(n)
        if shift:
            y = jnp.fft.fftshift(y, axes=1)
        return carry, y.reshape(-1).astype(jnp.complex64)

    def _lower(p: str) -> Optional[Stage]:
        if p != "bf16":
            return None
        return fft_stage(n, direction, shift, normalize, window,
                         impl=impl, precision="bf16")

    return Stage(fn, lambda d: jnp.zeros(()), Fraction(1, 1), np.complex64, n,
                 f"fft{n}", lower=_lower,
                 compute_dtype="bf16" if precision == "bf16" else "f32",
                 route=(impl, None, precision))


def fir_fft_stage(taps, n_fft: int, name: Optional[str] = None,
                  precision: Optional[str] = None) -> Stage:
    """Fused FIR → windowed-FFT stage (``pallas_kernels.pallas_fir_fft``):
    the filtered stream never round-trips HBM between the filter MAC and the
    transform — the resident ``fir_stage + fft_stage`` chain's whole interior
    edge, collapsed into one kernel.

    Semantically identical (allclose-pinned, tests/test_pallas.py) to
    ``Pipeline([fir_stage(taps), fft_stage(n_fft)])``: frames of ``n_fft``
    samples are filtered causally (history rides the carry) and transformed
    per ``n_fft`` window. REAL taps only, ``2 <= n_taps <= n_fft`` (a tap
    shift must not reach past the transform row directly above — the
    kernel's neighbour-tile precondition). The taps ride the carry, so
    runtime swaps (``update(taps=…)``) reach the kernel with no recompile.
    NOT LTI-mergeable (``lti=None`` — the FFT half is not a filter); the
    ``route`` pin marks the pallas dispatch for the cost-cache marker and
    ``pallas_stage_count``. ``precision="bf16"`` runs MAC + DFT matmuls with
    bf16 operands / f32 accumulation; the ``lower`` hook exposes that to the
    SNR-budgeted interior-precision pass.
    """
    taps = np.asarray(taps)
    nt = len(taps)
    assert np.isrealobj(taps) and 2 <= nt <= int(n_fft), \
        "fir_fft_stage requires real taps with 2 <= n_taps <= n_fft"
    n_fft = int(n_fft)
    name = name or f"fir_fft{n_fft}"

    def fn(carry, x):
        tt, tail = carry
        from .pallas_kernels import pallas_fir_fft
        y = pallas_fir_fft(tail, x, tt, n_fft, precision=precision)
        # frames are >= n_fft >= nt samples, so the new history is the
        # frame's own last nt-1 samples
        return (tt, x[x.shape[0] - (nt - 1):]), y

    def init_carry(dtype):
        from .xfer import to_device
        return (to_device(np.real(taps).astype(np.float32)),
                to_device(np.zeros(nt - 1, dtype=np.dtype(dtype))))

    def update(carry, taps=None):
        """Runtime tap swap (same count; real — the kernel is real-taps-only)."""
        if taps is None:
            return carry
        new = np.asarray(taps)
        if len(new) != nt:
            raise ValueError(
                f"tap swap must keep the tap count ({nt}); got {len(new)} — "
                f"rebuild the stage for a different filter length")
        if np.iscomplexobj(new):
            raise ValueError("fir_fft_stage taps must stay real")
        _tt, tail = carry
        from .xfer import to_device
        dev = next(iter(tail.devices())) if isinstance(tail, jax.Array) else None
        return (to_device(new.astype(np.float32), dev), tail)

    def _lower(p: str) -> Optional[Stage]:
        if p != "bf16":
            return None
        return fir_fft_stage(taps, n_fft, name=name, precision="bf16")

    return Stage(fn, init_carry, Fraction(1, 1), np.complex64, n_fft, name,
                 update=update, lower=_lower,
                 compute_dtype="bf16" if precision == "bf16" else "f32",
                 route=("pallas", None, precision))


def fftshift_stage(n: int) -> Stage:
    def fn(carry, x):
        return carry, jnp.fft.fftshift(x.reshape(-1, n), axes=1).reshape(-1)

    return Stage(fn, lambda d: jnp.zeros(()), Fraction(1, 1), None, n, "fftshift")


def mag2_stage() -> Stage:
    def fn(carry, x):
        return carry, (x.real * x.real + x.imag * x.imag).astype(jnp.float32)

    return Stage(fn, lambda d: jnp.zeros(()), Fraction(1, 1), np.float32, 1, "mag2")


def log10_stage(scale: float = 10.0, floor: float = 1e-20) -> Stage:
    def fn(carry, x):
        return carry, (scale * jnp.log10(jnp.maximum(x, floor))).astype(jnp.float32)

    return Stage(fn, lambda d: jnp.zeros(()), Fraction(1, 1), np.float32, 1, "log10")


def xlating_fir_stage(taps, phase_inc: float, decim: int,
                      name: str = "xlating") -> Stage:
    """Frequency-translating decimating FIR as ONE fused stage — the TPU form of
    the reference's freq-shift → decimating-FIR front half
    (``examples/fm-receiver/src/main.rs:83-130``; blocks `XlatingFir` role).

    The full-rate rotator is FOLDED into the filter (LTI modulation shift):

        y[q] = Σ_t h[t]·e^{jθ(qD−t)}·x[qD−t]
             = e^{jθDq} · Σ_t (h[t]e^{-jθt}) · x[qD−t]

    so the filter runs with complex taps ``h[t]e^{-jθt}`` via the shifted-matvec
    polyphase form (:func:`_poly_decim_fir_stage`), and only a residual rotator
    at the DECIMATED rate remains — D× fewer rotations than rotating the input
    (VERDICT r3 weak-item 2: the FM front end's full-rate tuner pass).

    Retune keeps the exact rotator grammar: ``update(phase_inc=θ')`` rebuilds
    the carried weight matrix AND the residual increment in one carry swap (no
    recompile, phase stays continuous); ``update(taps=…)`` swaps the base
    lowpass while preserving the current translation frequency.
    """
    D = int(decim)
    base0 = np.real(np.asarray(taps)).astype(np.float32)
    nt = len(base0)
    m = max(1, -(-(nt - 1) // D))
    H = m * D

    def _weights(base: np.ndarray, theta: float) -> np.ndarray:
        ct = (base * np.exp(-1j * theta * np.arange(nt))).astype(np.complex64)
        return _poly_decim_weights(ct, D, m)

    # The exact translation theta rides the CARRY as a float32 hi/lo pair
    # (double-double split, ~48 significant bits): the carry only holds the
    # float32 decimated increment otherwise, and re-deriving theta from it on
    # a taps-only update() would rebuild the weights with a rounded theta
    # (round-4 advisory). Closure state would alias across carries built from
    # the same Stage (round-5 review) — every other piece of stage state rides
    # the carry, so this does too.
    def _theta_split(theta: float):
        hi = np.float32(theta)
        return hi, np.float32(theta - float(hi))

    def _theta_join(hi, lo) -> float:
        return float(hi) + float(lo)

    def fn(carry, x):
        W, base, ph0, inc_d, th_hi, th_lo, hist = carry
        ext = jnp.concatenate([hist, x])
        nq = x.shape[0] // D
        y = _shifted_matvec(ext, W, m, nq)
        ph = ph0 + inc_d * jnp.arange(nq, dtype=jnp.float32)
        y = y * jnp.exp(1j * ph).astype(y.dtype)
        ph_new = jnp.mod(ph0 + inc_d * nq, 2 * np.pi)
        return (W, base, ph_new, inc_d, th_hi, th_lo,
                ext[ext.shape[0] - H:]), y.astype(x.dtype)

    def init_carry(dtype):
        from .xfer import to_device
        hi, lo = _theta_split(float(phase_inc))
        return (to_device(_weights(base0, float(phase_inc))),
                to_device(base0),
                jnp.zeros((), jnp.float32),
                jnp.asarray(float(phase_inc) * D, jnp.float32),
                jnp.asarray(hi), jnp.asarray(lo),
                to_device(np.zeros(H, dtype=np.dtype(dtype))))

    def update(carry, phase_inc=None, taps=None):
        W, base, ph0, inc_d, th_hi, th_lo, hist = carry
        from .xfer import to_device
        dev = next(iter(hist.devices())) if isinstance(hist, jax.Array) else None
        nbase = np.asarray(jax.device_get(base), np.float32)
        if taps is not None:
            new = np.asarray(taps)
            if len(new) != nt:
                raise ValueError(f"tap swap must keep the tap count ({nt}); "
                                 f"got {len(new)}")
            if np.iscomplexobj(new):
                raise ValueError("xlating stage taps are the REAL base lowpass; "
                                 "the translation rides phase_inc")
            nbase = new.astype(np.float32)
            base = to_device(nbase, dev)
        if phase_inc is not None:
            theta = float(phase_inc)
            hi, lo = _theta_split(theta)
            def _dev(v):
                return jax.device_put(v, dev) if dev is not None else jnp.asarray(v)
            inc_d = _dev(jnp.asarray(theta * D, jnp.float32))
            th_hi, th_lo = _dev(jnp.asarray(hi)), _dev(jnp.asarray(lo))
        else:
            theta = _theta_join(jax.device_get(th_hi), jax.device_get(th_lo))
        W = to_device(_weights(nbase, theta), dev)
        return (W, base, ph0, inc_d, th_hi, th_lo, hist)

    return Stage(fn, init_carry, Fraction(1, D), None, D, name, update=update)


def rotator_stage(phase_inc: float, name: str = "rotator",
                  impl: str = "xla") -> Stage:
    """Complex rotator with phase carry (futuredsp `Rotator` as a stage).

    The increment rides the CARRY (not the trace), so a runtime retune —
    ``pipeline.update_stage(carries, "rotator", phase_inc=…)`` or the TpuKernel
    ``ctrl`` port — takes effect on the next dispatched frame with phase
    continuity, no recompile: the device-path analog of the fm-receiver's
    ``freq`` handler (``examples/fm-receiver/src/main.rs:83-155``).

    ``impl="pallas"`` routes the phase-ramp multiply through the 2-D lane-tile
    kernel (``pallas_kernels.pallas_rotator`` — the autotuned Pallas plane);
    ``"xla"`` (default) keeps the fused XLA form. Same carry, same retune
    grammar on both routes."""
    assert impl in ("xla", "pallas"), impl

    def fn(carry, x):
        ph0, inc = carry
        n = x.shape[0]
        if impl == "pallas":
            from .pallas_kernels import pallas_rotator
            y = pallas_rotator(x, ph0, inc).astype(x.dtype)
        else:
            ph = ph0 + inc * jnp.arange(n, dtype=jnp.float32)
            y = x * jnp.exp(1j * ph).astype(x.dtype)
        new = jnp.mod(ph0 + inc * n, 2 * np.pi)
        return (new, inc), y

    def init_carry(dtype):
        return (jnp.zeros((), dtype=jnp.float32),
                jnp.asarray(float(phase_inc), dtype=jnp.float32))

    def update(carry, phase_inc=None):
        if phase_inc is None:
            return carry
        ph0, _inc = carry
        new_inc = jnp.asarray(float(phase_inc), dtype=jnp.float32)
        if isinstance(ph0, jax.Array):          # land beside the carry's phase
            new_inc = jax.device_put(new_inc, next(iter(ph0.devices())))
        return (ph0, new_inc)

    return Stage(fn, init_carry, Fraction(1, 1), None, 1, name, update=update,
                 route=(("pallas", None, None) if impl == "pallas" else None))


def quad_demod_stage(gain: float = 1.0, impl: str = "xla") -> Stage:
    """FM discriminator with one-sample carry. ``impl="pallas"`` routes the
    ``angle(x·conj(x₋₁))`` inner loop through the 2-D lane-tile kernel
    (``pallas_kernels.pallas_quad_demod``); the one-sample history carry is
    identical on both routes."""
    assert impl in ("xla", "pallas"), impl

    def fn(carry, x):
        if impl == "pallas":
            from .pallas_kernels import pallas_quad_demod
            y = pallas_quad_demod(carry, x, gain)
            return x[-1], y.astype(jnp.float32)
        prev = jnp.concatenate([carry[None], x[:-1]])
        y = gain * jnp.angle(x * jnp.conj(prev))
        return x[-1], y.astype(jnp.float32)

    def init_carry(dtype):
        # complex host scalars (incl. eager jnp.ones) are device_puts the axon tunnel
        # cannot materialise — ship via the pair shim (ops/xfer.py)
        from .xfer import to_device
        return to_device(np.ones((), dtype=np.dtype(dtype)))

    return Stage(fn, init_carry, Fraction(1, 1), np.float32, 1, "quad_demod",
                 route=(("pallas", None, None) if impl == "pallas" else None))


def apply_stage(f: Callable[[jnp.ndarray], jnp.ndarray], out_dtype=None,
                name: str = "apply") -> Stage:
    """Arbitrary elementwise jax function (1:1)."""

    def fn(carry, x):
        return carry, f(x)

    return Stage(fn, lambda d: jnp.zeros(()), Fraction(1, 1), out_dtype, 1, name)


def channelizer_stage(n_channels: int, taps=None, name: str = "channelizer",
                      impl: str = "auto",
                      precision: Optional[str] = None) -> Stage:
    """Critically-sampled PFB analysis bank as a stage: frames of k·N complex samples →
    k·N outputs, CHANNEL-INTERLEAVED ([t, N] flattened — feed a StreamDeinterleaver(N)
    to split, or consume interleaved). Carry = the branch-filter history block.

    ``impl="matmul"``: the polyphase branch FIRs as one [N, K] × windows dot per
    output step batched over the frame (MXU work), followed by a batched IFFT
    across branches — the fused-TPU form of `blocks/pfb.PfbChannelizer`.
    ``impl="pallas"``: the fused PFB kernel (``pallas_kernels.pallas_pfb``) —
    polyphase MAC + twiddle-feed IDFT in ONE kernel, so the [t, N] branch bank
    never round-trips HBM between the two passes (the windows stack is ~K× the
    frame in HBM writes on the matmul path). ``"auto"`` picks pallas on the TPU
    backend (trace-time, same convention as ``_pallas_fir_wins``) and the matmul
    path elsewhere. ``precision="bf16"`` carries the branch taps in bfloat16 and
    runs MAC/IDFT with bf16 operands, f32 accumulation (the interior-precision
    policy selects it via this stage's ``lower`` hook).
    """
    assert impl in ("auto", "matmul", "pallas"), impl
    N = n_channels
    if taps is None:
        from ..blocks.pfb import pfb_default_taps
        taps = pfb_default_taps(N)
    taps = np.asarray(taps, dtype=np.float32)
    K = -(-len(taps) // N)
    padded = np.zeros(K * N, dtype=np.float32)
    padded[:len(taps)] = taps
    branch_np = padded.reshape(K, N).T                    # [N, K]
    if precision == "bf16":
        import ml_dtypes
        branch_np = branch_np.astype(ml_dtypes.bfloat16)  # carried taps: half HBM
    branch = jnp.asarray(branch_np)
    fft_prec = "bf16" if precision == "bf16" else None

    def fn(carry, x):
        Hc, hist = carry                                   # hist: [(K-1)·N]
        ext = jnp.concatenate([hist, x])                   # [(t + K-1)·N]
        blocks = ext.reshape(-1, N)[:, ::-1]               # [t+K-1, N] commutated
        t = x.shape[0] // N
        use_pallas = impl == "pallas" or (
            impl == "auto" and jax.default_backend() == "tpu")
        if use_pallas:
            from .pallas_kernels import pallas_pfb
            y = pallas_pfb(blocks, Hc.T, precision=precision)      # [t, N]
        else:
            # windows[s, k, c] = blocks[s + (K-1) - k, c] (branch c, depth k):
            # K static slices + stack instead of a gather (slow on TPU)
            windows = jnp.stack(
                [blocks[(K - 1) - k:(K - 1) - k + t] for k in range(K)],
                axis=1)                                            # [t, K, N]
            prec = (jax.lax.Precision.DEFAULT if precision == "bf16"
                    else jax.lax.Precision.HIGHEST)
            v = jnp.einsum("tkc,ck->tc", windows, Hc, precision=prec)  # [t, N]
            y = mxu_fft.ifft(v, precision=fft_prec) * N    # ifft across branches
        new_hist = ext[ext.shape[0] - (K - 1) * N:]
        return (Hc, new_hist), y.reshape(-1).astype(jnp.complex64)

    def init_carry(dtype):
        from .xfer import to_device
        return (branch, to_device(np.zeros((K - 1) * N, dtype=np.dtype(dtype))))

    def _lower(p: str) -> Optional[Stage]:
        if p != "bf16":
            return None
        return channelizer_stage(N, taps, name, impl=impl, precision="bf16")

    return Stage(fn, init_carry, Fraction(1, 1), np.complex64, N, name,
                 lower=_lower,
                 compute_dtype="bf16" if precision == "bf16" else "f32",
                 route=(impl, None, precision))


def lora_demod_stage(sf: int, name: str = "lora_demod") -> Stage:
    """LoRa dechirp + batched FFT + argmax as a stage: frames of k·2^sf complex chips →
    k int32 symbol values (the `FftDemod` hot loop of the LoRa example, fused).
    The downchirp is generated in-trace (no HBM table)."""
    n = 1 << sf
    k_idx = np.arange(n)
    ph = 2 * np.pi * ((k_idx * k_idx) / (2 * n) + k_idx * (-0.5))
    down = np.exp(-1j * ph).astype(np.complex64)    # conj(upchirp)

    def fn(carry, x):
        blocks = x.reshape(-1, n) * jnp.asarray(down)[None, :]
        spec = jnp.abs(jnp.fft.fft(blocks, axis=1))
        return carry, jnp.argmax(spec, axis=1).astype(jnp.int32)

    return Stage(fn, lambda d: jnp.zeros(()), Fraction(1, n), np.int32, n, name)


def agc_stage(reference: float = 1.0, rate: float = 0.1, block: int = 256,
              max_gain: float = 65536.0) -> Stage:
    """Block-floating AGC: per-sample gain feedback is inherently sequential, so the
    TPU form tracks gain at ``block`` granularity — mean magnitude per block, gain
    evolved by a short ``lax.scan`` over blocks (frame_len/block steps), then applied
    vectorized. Converges like the reference's per-sample loop (`blocks/agc.rs`) with a
    ``block``-sample control delay. Carry = the running gain."""

    def fn(carry, x):
        mags = jnp.abs(x.reshape(-1, block)).mean(axis=1)

        def step(g, m):
            err = reference - m * g
            g = jnp.clip(g + rate * err, 0.0, max_gain)
            return g, g

        g_final, gains = jax.lax.scan(step, carry, mags)
        y = (x.reshape(-1, block) * gains[:, None]).reshape(-1).astype(x.dtype)
        return g_final, y

    def init_carry(dtype):
        return jnp.asarray(1.0, dtype=jnp.float32)

    return Stage(fn, init_carry, Fraction(1, 1), None, block, "agc")


def moving_avg_stage(frame_len: int, decay: float = 0.1) -> Stage:
    """EMA across frames of length ``frame_len`` (spectrum smoothing), carry = the EMA."""

    def fn(carry, x):
        rows = x.reshape(-1, frame_len)

        def step(c, row):
            c = c * (1.0 - decay) + row * decay
            return c, c

        carry, out = jax.lax.scan(step, carry, rows)
        return carry, out.reshape(-1)

    def init_carry(dtype):
        return jnp.zeros(frame_len, dtype=jnp.float32)

    return Stage(fn, init_carry, Fraction(1, 1), np.float32, frame_len, "moving_avg")
