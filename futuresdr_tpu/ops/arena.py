"""Host staging arena: a size-classed pool of recycled host buffers.

The streamed path's steady state used to allocate fresh numpy buffers every
frame — the ring-exit staging copy, the quantizing wire-encode outputs, the
megabatch pad frames. At MB-scale frames every one of those allocations is an
mmap'd region whose pages fault in on first write, so the allocator taxes the
drain loop with work the wire could have been riding under (the host-transfer
bottleneck of arXiv:1810.09868 §4 — once device compute is fused, the input
pipeline's residual cost IS the host plane). The arena replaces them with
recycled buffers: after the first lap of the in-flight window every ``take``
is a pop from a free list of warm, already-faulted pages.

Ownership is explicit, because recycling under fault tolerance is the
dangerous part: a buffer whose frame may be RE-SHIPPED — by the transfer
plane's idempotent re-put (``ops/xfer.py``) or by the checkpoint replay log
(``tpu/kernel_block.py``) — must not be recycled into a newer frame, or the
retry would upload aliased garbage bit-for-bit confidently. So every consumer
holds its own reference: :meth:`ArenaBuffer.retain` / :meth:`release`, and a
buffer returns to its size-class free list only at refcount zero. The kernel
releases a dispatch group's buffers when its outputs drain; the replay log
holds an additional retain until a committed checkpoint covers the group.

Size classes are powers of two (min 4 KiB), so a frame-size change mid-run
cannot fragment the pool; the pool is bounded (``host_arena_mb`` config) —
past the cap a released buffer is dropped to the allocator instead of pooled.

Telemetry (always on, docs/observability.md): ``fsdr_arena_hits_total`` /
``fsdr_arena_misses_total`` (takes served from the pool vs fresh
allocations), ``fsdr_arena_pinned_bytes`` / ``fsdr_arena_pooled_bytes``
gauges, and a ``doctor.report()["arena"]`` snapshot.

Config: ``host_arena`` (default on; ``FUTURESDR_TPU_HOST_ARENA=0`` disables —
every caller falls back to plain allocation), ``host_arena_mb`` byte cap.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..log import logger
from ..telemetry import prom as _prom

__all__ = ["ArenaBuffer", "StagingArena", "arena", "reset_arena",
           "arena_stats"]

log = logger("ops.arena")

_HITS = _prom.counter(
    "fsdr_arena_hits_total", "arena takes served from a recycled buffer")
_MISSES = _prom.counter(
    "fsdr_arena_misses_total", "arena takes that allocated a fresh buffer")
_PINNED = _prom.gauge(
    "fsdr_arena_pinned_bytes", "bytes of arena buffers currently checked out")
_POOLED = _prom.gauge(
    "fsdr_arena_pooled_bytes", "bytes of arena buffers idle in the pool")

_MIN_CLASS = 12                       # 4 KiB floor: below it pooling is noise


def _class_of(nbytes: int) -> int:
    """Size-class exponent: smallest power of two ≥ nbytes (≥ 4 KiB)."""
    return max(_MIN_CLASS, int(nbytes - 1).bit_length()) if nbytes > 1 \
        else _MIN_CLASS


class ArenaBuffer:
    """One pooled buffer: a flat byte array plus an explicit refcount.

    Created at refcount 1 (the taker owns that reference). Additional
    consumers — the replay log, a retry-window holder — call
    :meth:`retain` and balance it with :meth:`release`; the buffer returns
    to its arena's free list only when the count reaches zero. ``release``
    past zero is a no-op (a defensive contract: a double release must never
    recycle a buffer some other holder still pins)."""

    __slots__ = ("base", "_arena", "_cls", "_rc", "_lock")

    def __init__(self, arena: "StagingArena", cls: int):
        self.base = np.empty(1 << cls, dtype=np.uint8)
        self._arena = arena
        self._cls = cls
        self._rc = 1
        self._lock = threading.Lock()

    @property
    def nbytes(self) -> int:
        return self.base.nbytes

    def array(self, shape, dtype) -> np.ndarray:
        """A leading view of the buffer as ``shape``/``dtype`` (must fit)."""
        dt = np.dtype(dtype)
        n = int(np.prod(shape)) * dt.itemsize
        assert n <= self.base.nbytes, (shape, dt, self.base.nbytes)
        return self.base[:n].view(dt).reshape(shape)

    def retain(self) -> "ArenaBuffer":
        with self._lock:
            assert self._rc > 0, "retain() of an already-recycled buffer"
            self._rc += 1
        return self

    def release(self) -> None:
        with self._lock:
            if self._rc <= 0:
                return
            self._rc -= 1
            if self._rc:
                return
        self._arena._recycle(self)


class StagingArena:
    """The pool: per-size-class free lists, bounded by ``max_bytes``."""

    def __init__(self, max_bytes: int = 256 << 20):
        self.max_bytes = int(max_bytes)
        self._free: Dict[int, List[ArenaBuffer]] = {}
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.pinned_bytes = 0
        self.pooled_bytes = 0

    # -- take -----------------------------------------------------------------
    def take(self, nbytes: int) -> ArenaBuffer:
        """Check out a buffer of capacity ≥ nbytes (refcount 1)."""
        cls = _class_of(int(nbytes))
        with self._lock:
            lst = self._free.get(cls)
            if lst:
                buf = lst.pop()
                self.pooled_bytes -= buf.nbytes
                self.pinned_bytes += buf.nbytes
                self.hits += 1
                hit = True
            else:
                self.misses += 1
                hit = False
                buf = None
        if buf is None:
            buf = ArenaBuffer(self, cls)
            with self._lock:
                self.pinned_bytes += buf.nbytes
        else:
            buf._rc = 1
        (_HITS if hit else _MISSES).inc()
        _PINNED.set(self.pinned_bytes)
        _POOLED.set(self.pooled_bytes)
        return buf

    def take_array(self, shape, dtype) -> Tuple[np.ndarray, ArenaBuffer]:
        """``(array view, owning buffer)`` for a fresh-content buffer."""
        dt = np.dtype(dtype)
        buf = self.take(int(np.prod(shape)) * dt.itemsize)
        return buf.array(shape, dt), buf

    def copy_in(self, a: np.ndarray) -> Tuple[np.ndarray, ArenaBuffer]:
        """Copy ``a`` into an arena buffer — the ring-exit staging copy of
        the drain loops (``TpuKernel._stage_available_input``): the frame
        leaves the live ring before ``consume()``, into recycled pages
        instead of a fresh allocation."""
        v, buf = self.take_array(a.shape, a.dtype)
        np.copyto(v, a)
        return v, buf

    # -- recycle --------------------------------------------------------------
    def _recycle(self, buf: ArenaBuffer) -> None:
        with self._lock:
            self.pinned_bytes -= buf.nbytes
            if self.pooled_bytes + buf.nbytes <= self.max_bytes:
                self._free.setdefault(buf._cls, []).append(buf)
                self.pooled_bytes += buf.nbytes
            # else: past the cap — drop to the allocator
        _PINNED.set(self.pinned_bytes)
        _POOLED.set(self.pooled_bytes)

    # -- introspection --------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "pinned_bytes": self.pinned_bytes,
                "pooled_bytes": self.pooled_bytes,
                "classes": {1 << c: len(l)
                            for c, l in sorted(self._free.items()) if l},
            }


_arena: Optional[StagingArena] = None
_arena_lock = threading.Lock()
_arena_disabled = False


def arena() -> Optional[StagingArena]:
    """The process-global arena, or None when ``host_arena`` is off (every
    caller must fall back to plain allocation — the A/B baseline mode)."""
    global _arena, _arena_disabled
    if _arena is None and not _arena_disabled:
        with _arena_lock:
            if _arena is None and not _arena_disabled:
                from ..config import config
                c = config()
                if not bool(c.get("host_arena", True)):
                    _arena_disabled = True
                    return None
                _arena = StagingArena(
                    int(c.get("host_arena_mb", 256)) << 20)
    return _arena


def reset_arena() -> None:
    """Drop the process arena (tests / config re-reads); the next
    :func:`arena` call re-resolves config."""
    global _arena, _arena_disabled
    with _arena_lock:
        _arena = None
        _arena_disabled = False


def arena_stats() -> Optional[dict]:
    """Snapshot for ``doctor.report()`` (None when the arena is off or was
    never used)."""
    a = _arena
    return a.stats() if a is not None else None


class GroupAlloc:
    """Per-dispatch-group allocator handed to ``Wire.encode_into``: records
    every buffer it hands out so the caller can pin the whole group's
    staging set in one list (the replay-log / drain release contract of
    ``tpu/kernel_block.py``). ``temp()`` buffers are scratch the encode
    itself drops via :meth:`drop_temps` — they never outlive the encode."""

    __slots__ = ("arena", "handles", "_temps")

    def __init__(self, arena: StagingArena):
        self.arena = arena
        self.handles: List[ArenaBuffer] = []
        self._temps: List[ArenaBuffer] = []

    def __call__(self, shape, dtype) -> np.ndarray:
        v, buf = self.arena.take_array(shape, dtype)
        self.handles.append(buf)
        return v

    def temp(self, shape, dtype) -> np.ndarray:
        v, buf = self.arena.take_array(shape, dtype)
        self._temps.append(buf)
        return v

    def drop_temps(self) -> None:
        for b in self._temps:
            b.release()
        self._temps.clear()

    def temps_only(self) -> "_TempsOnly":
        """An alloc view whose ``__call__`` also lands in the temp set — for
        intermediates (per-frame encodes before a megabatch stack) that must
        not pin past the encode."""
        return _TempsOnly(self)


class _TempsOnly:
    """See :meth:`GroupAlloc.temps_only` — everything is scratch, owned (and
    dropped) by the parent alloc."""

    __slots__ = ("_parent",)

    def __init__(self, parent: GroupAlloc):
        self._parent = parent

    def __call__(self, shape, dtype) -> np.ndarray:
        return self._parent.temp(shape, dtype)

    def temp(self, shape, dtype) -> np.ndarray:
        return self._parent.temp(shape, dtype)

    def drop_temps(self) -> None:
        pass                                # the parent owns the temp set


class PackedAlloc(GroupAlloc):
    """A :class:`GroupAlloc` whose payload allocations are VIEWS into ONE
    contiguous packed transfer buffer (the H2D coalescing plane,
    ``ops/xfer.PackedLayout``): ``__call__`` hands out the next unfilled
    layout slot matching the requested shape/dtype, so a quantizing encode's
    int payload is written directly at its packed offset — the coalesce
    costs zero extra payload copies. A request no slot matches falls back to
    a plain arena take (``PackedLayout.pack`` copies those, plus bare parts
    like the quantizer's scale scalar, into their slots afterwards).
    ``handles[0]`` pins the packed buffer itself; the whole-group pinning /
    replay-retention contract is the parent's, unchanged."""

    __slots__ = ("layout", "packed", "_filled")

    def __init__(self, arena: StagingArena, layout):
        super().__init__(arena)
        self.layout = layout
        self.packed, buf = arena.take_array((layout.nbytes,), np.uint8)
        self.handles.append(buf)
        self._filled = [False] * len(layout.slots)

    def __call__(self, shape, dtype) -> np.ndarray:
        sh = ((int(shape),) if isinstance(shape, (int, np.integer))
              else tuple(shape))
        dt = np.dtype(dtype)
        for i, (ssh, sdt, off, nb) in enumerate(self.layout.slots):
            if not self._filled[i] and ssh == sh and sdt == dt:
                self._filled[i] = True
                return self.packed[off:off + nb].view(dt).reshape(sh)
        return super().__call__(shape, dtype)

    def finish(self, parts) -> np.ndarray:
        """Settle the packed buffer for shipping: copy in every part the
        encode did not write through a slot view, zero alignment gaps, and
        return the packed uint8 array (backed by ``handles[0]``)."""
        return self.layout.pack(parts, self.packed)
