"""Zero-copy ingest: externally-owned buffers ride the uplink without the
ring-exit copy.

The streamed drain loop pays one host copy per frame at the ring exit
(``TpuKernel._stage_copy``): ``device_put`` is async, so a live ring view
handed to it would race with the upstream writer reclaiming consumed space.
That copy is a safety tax, not a law of physics — when the frame's backing
buffer is EXTERNALLY OWNED (a dlpack import, a shared-memory mapping, a
recorded capture an offline source replays), nobody overwrites it behind the
transfer, and the copy buys nothing.

This module is the ownership registry that makes skipping the copy sound. A
source that controls its buffer's lifetime registers it (:func:`register`);
the kernel's staging path looks frames up (:func:`lookup`) by walking the
numpy base chain to the registered root. On a hit the frame is staged AS the
ring-exit "copy" and the registered buffer's refcounted pin handle rides the
arena pinning rules (``ops/arena.ArenaBuffer`` protocol: ``retain`` /
``release``) through the dispatch group's handle set AND the checkpoint
replay log — the buffer stays pinned until the frame's outputs drain and a
committed checkpoint covers the group, exactly the retention the arena
staging copy would have had. The owner learns the buffer is reclaimable from
:attr:`IngestBuffer.pinned` (or an ``on_idle`` callback).

The fast path only engages when it is actually free AND safe:

* the buffer must be registered and READ-ONLY (``register`` clears the
  writeable flag as a tripwire; a writable frame never matches — the
  "falls back whenever the buffer is writable" contract);
* the wire's host encode must ALIAS its input (the f32 pairs view). A
  quantizing wire materializes fresh int payloads anyway — the copy it
  would skip does not exist (the deferred-consume staging plane covers
  that case instead).

Everything else falls back to the copying path, bit-identically.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

import numpy as np

from ..log import logger
from ..telemetry import prom as _prom

__all__ = ["IngestBuffer", "register", "unregister", "lookup", "reset",
           "stats", "from_dlpack"]

log = logger("ops.ingest")

_INGEST_FRAMES = _prom.counter(
    "fsdr_ingest_zero_copy_frames_total",
    "frames staged zero-copy from a registered externally-owned buffer")

_lock = threading.Lock()
_registry: Dict[int, "IngestBuffer"] = {}


class IngestBuffer:
    """Refcounted pin handle of one registered externally-owned buffer.

    Speaks the ``ops/arena.ArenaBuffer`` retention protocol (``retain`` /
    ``release``), so the kernel's group-handle set and replay log can pin it
    exactly like an arena staging buffer. The registry's own reference is
    one count; every staged frame adds one (released when the frame's
    dispatch group drains / its replay-log entry is pruned). ``release``
    past zero is a no-op, like the arena's. When the count returns to the
    registry-only baseline the owner may reclaim the memory (``pinned``
    goes False; ``on_idle`` fires if given)."""

    __slots__ = ("root", "name", "on_idle", "_rc", "_lock")

    def __init__(self, root: np.ndarray, name: str = "",
                 on_idle: Optional[Callable[["IngestBuffer"], None]] = None):
        self.root = root
        self.name = name
        self.on_idle = on_idle
        self._rc = 1                      # the registry's reference
        self._lock = threading.Lock()

    def retain(self) -> "IngestBuffer":
        with self._lock:
            self._rc += 1
        return self

    def release(self) -> None:
        cb = None
        with self._lock:
            if self._rc > 0:
                self._rc -= 1
                if self._rc == 1 and self.on_idle is not None:
                    cb = self.on_idle      # back to registry-only: idle
        if cb is not None:
            try:
                cb(self)
            except Exception as e:         # noqa: BLE001 — observer only
                log.warning("ingest on_idle callback failed: %r", e)

    @property
    def pinned(self) -> bool:
        """True while any staged frame / replay-log entry still pins the
        buffer (the owner must not reclaim or rewrite it)."""
        with self._lock:
            return self._rc > 1

    @property
    def refcount(self) -> int:
        with self._lock:
            return self._rc


def _root_of(a: np.ndarray) -> np.ndarray:
    """Walk the numpy base chain to the owning array (views of views of a
    registered buffer still resolve to the same root)."""
    while isinstance(getattr(a, "base", None), np.ndarray):
        a = a.base
    return a


def register(arr: np.ndarray, name: str = "",
             on_idle: Optional[Callable[[IngestBuffer], None]] = None
             ) -> IngestBuffer:
    """Register an externally-owned buffer for zero-copy ingest.

    ``arr`` (or any view of it) handed to a TPU kernel as a frame will skip
    the ring-exit staging copy on aliasing wires; the returned handle's
    :attr:`IngestBuffer.pinned` tells the owner when the buffer may be
    reclaimed. Registration clears the writeable flag on the ROOT buffer
    (the ownership contract says nobody writes it while registered; the
    flag makes an accidental write raise instead of corrupting in-flight
    frames). Registering the same root twice returns the existing handle."""
    root = _root_of(np.asarray(arr))
    with _lock:
        got = _registry.get(id(root))
        if got is not None:
            return got
        try:
            root.setflags(write=False)
        except ValueError:
            # a foreign-owned view (dlpack import) may refuse; its producer
            # already owns writability — the lookup-side check still holds
            pass
        h = IngestBuffer(root, name=name, on_idle=on_idle)
        _registry[id(root)] = h
        return h


def unregister(handle: IngestBuffer) -> None:
    """Drop the registry's reference. Frames already staged keep their own
    pins; the buffer must stay valid until :attr:`IngestBuffer.pinned` goes
    False."""
    with _lock:
        _registry.pop(id(handle.root), None)
    handle.release()


def lookup(frame: np.ndarray) -> Optional[IngestBuffer]:
    """The staging-path probe: the registered handle backing ``frame``, or
    None when the frame is unregistered OR writable (a writable view means
    the zero-copy ownership contract cannot hold — fall back to copying)."""
    if not _registry or frame.flags.writeable:
        return None
    root = _root_of(frame)
    with _lock:
        return _registry.get(id(root))


def note_zero_copy(n: int = 1) -> None:
    """Bill ``n`` frames staged through the zero-copy fast path."""
    _INGEST_FRAMES.inc(n)


def from_dlpack(capsule_owner) -> np.ndarray:
    """Import an external producer's buffer via the dlpack protocol and
    register the result: the shared-memory ingest entry point for sources
    whose payload already lives in another framework's host buffer. Returns
    the registered (read-only) numpy view."""
    arr = np.from_dlpack(capsule_owner)
    register(arr)
    return arr


def reset() -> None:
    """Drop every registration (tests)."""
    with _lock:
        _registry.clear()


def stats() -> dict:
    with _lock:
        return {
            "registered": len(_registry),
            "pinned": sum(1 for h in _registry.values() if h.pinned),
        }
