"""Viterbi add-compare-select as a jitted lax.scan — the TPU/XLA decode path.

The reference decodes Viterbi in a scalar Rust loop (``examples/wlan/src/
viterbi_decoder.rs``); here the per-step ACS is vectorized over all trellis states and the
time recursion is a ``lax.scan``, jit-compiled once per (n_states, bucket-length) and
reused — frame lengths are padded up to power-of-two buckets. Traceback stays on host
(cheap, sequential).
"""

from __future__ import annotations

from functools import lru_cache

import numpy as np

__all__ = ["scan_viterbi", "backend_ready"]


def backend_ready() -> bool:
    """True iff a jax backend is ALREADY initialized in this process. Callers that have
    a numpy fallback use this to avoid triggering device discovery (which can block for
    minutes when the axon TPU tunnel is wedged) from a pure-CPU code path."""
    try:
        from jax._src import xla_bridge
        return bool(xla_bridge._backends)
    except Exception:
        return False


@lru_cache(maxsize=None)
def _compiled(n_states: int, bucket: int, tables_key):
    import jax
    import jax.numpy as jnp

    prev_s, prev_b, bm0, bm1 = [np.asarray(t) for t in tables_key_store[tables_key]]
    ps = jnp.asarray(prev_s)
    b0 = jnp.asarray(bm0)
    b1 = jnp.asarray(bm1)

    def step(metrics, lam):
        cand = metrics[ps] + b0 * lam[0] + b1 * lam[1]       # [S, 2]
        pick = jnp.argmax(cand, axis=1)
        new = jnp.take_along_axis(cand, pick[:, None], axis=1)[:, 0]
        return new, pick.astype(jnp.uint8)

    @jax.jit
    def run(lams):                                            # [bucket, 2]
        init = jnp.full((n_states,), -1e18).at[0].set(0.0)
        _, picks = jax.lax.scan(step, init, lams)
        return picks                                          # [bucket, S]

    return run


tables_key_store: dict = {}


@lru_cache(maxsize=None)
def _compiled_batch(n_states: int, bucket: int, batch: int, tables_key):
    import jax
    import jax.numpy as jnp

    prev_s, prev_b, bm0, bm1 = [np.asarray(t) for t in tables_key_store[tables_key]]
    ps = jnp.asarray(prev_s)
    b0 = jnp.asarray(bm0)
    b1 = jnp.asarray(bm1)

    def step(metrics, lam):                                   # metrics [B, S]
        cand = metrics[:, ps] + b0[None] * lam[:, None, None, 0] \
            + b1[None] * lam[:, None, None, 1]                # [B, S, 2]
        pick = jnp.argmax(cand, axis=2)
        new = jnp.take_along_axis(cand, pick[..., None], axis=2)[..., 0]
        return new, pick.astype(jnp.uint8)

    @jax.jit
    def run(lams):                                            # [B, bucket, 2]
        init = jnp.full((batch, n_states), -1e18).at[:, 0].set(0.0)
        _, picks = jax.lax.scan(step, init, jnp.swapaxes(lams, 0, 1))
        return picks                                          # [bucket, B, S]

    return run


def scan_viterbi_batch(llrs_list, n_bits_list, prev_s, prev_b, bm0, bm1):
    """Decode a batch of frames in one scan: the TPU-idiomatic burst decoder.

    ``llrs_list``: per-frame soft arrays (2 per step); returns list of bit arrays.
    Frames are padded to a common power-of-two step bucket and the batch to a power of
    two, so distinct shapes stay few and jit-cached.
    """
    n_states = prev_s.shape[0]
    steps = [min(len(l) // 2, n) for l, n in zip(llrs_list, n_bits_list)]
    max_steps = max(steps)
    bucket = max(8, 1 << int(np.ceil(np.log2(max_steps))))
    b_real = len(llrs_list)
    batch = max(1, 1 << int(np.ceil(np.log2(b_real))))
    lams = np.zeros((batch, bucket, 2), dtype=np.float32)
    for i, (l, t) in enumerate(zip(llrs_list, steps)):
        lams[i, :t] = np.asarray(l[:2 * t], np.float32).reshape(t, 2)
    key = (n_states, prev_s.tobytes(), prev_b.tobytes(), bm0.tobytes(), bm1.tobytes())
    hkey = hash(key)
    tables_key_store.setdefault(hkey, (prev_s, prev_b, bm0, bm1))
    run = _compiled_batch(n_states, bucket, batch, hkey)
    picks = np.asarray(run(lams))                             # [bucket, B, S]
    # vectorized traceback over the whole batch: one loop over time, [B] states;
    # frames shorter than the bucket stay parked at state 0 until their own end
    steps_arr = np.asarray(steps + [0] * (batch - b_real))
    states = np.zeros(batch, dtype=np.int64)
    bits_all = np.zeros((bucket, batch), dtype=np.uint8)
    rows = np.arange(batch)
    for tt in range(bucket - 1, -1, -1):
        active = tt < steps_arr
        b = picks[tt, rows, states]
        bits_all[tt, active] = prev_b[states, b][active]
        states = np.where(active, prev_s[states, b], states)
    return [bits_all[:steps[i], i][:n_bits_list[i]] for i in range(b_real)]


def scan_viterbi(llrs: np.ndarray, n_bits: int, prev_s: np.ndarray, prev_b: np.ndarray,
                 bm0: np.ndarray, bm1: np.ndarray) -> np.ndarray:
    """Decode ``n_bits`` from soft ``llrs`` (2 per step) given trellis tables.

    ``prev_s/prev_b``: [S, 2] predecessor state/input per next-state; ``bm0/bm1``: the
    corresponding branch output bits in ±1. Terminated trellis (traceback from state 0).
    """
    n_states = prev_s.shape[0]
    n_steps = min(len(llrs) // 2, n_bits)
    lam = np.zeros((max(8, 1 << int(np.ceil(np.log2(max(n_steps, 1))))), 2),
                   dtype=np.float32)
    lam[:n_steps] = llrs[:2 * n_steps].reshape(n_steps, 2)
    key = (n_states, prev_s.tobytes(), prev_b.tobytes(), bm0.tobytes(), bm1.tobytes())
    hkey = hash(key)
    tables_key_store.setdefault(hkey, (prev_s, prev_b, bm0, bm1))
    run = _compiled(n_states, lam.shape[0], hkey)
    picks = np.asarray(run(lam))                              # [bucket, S]
    # traceback over the real steps only (padding never enters)
    state = 0
    out = np.empty(n_steps, dtype=np.uint8)
    for t in range(n_steps - 1, -1, -1):
        b = picks[t, state]
        out[t] = prev_b[state, b]
        state = prev_s[state, b]
    return out[:n_bits]
