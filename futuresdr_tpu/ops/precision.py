"""Interior precision policy: SNR-budgeted auto-lowering of the fused device plane.

The resident chains are HBM-bound (docs/tpu_notes.md roofline table: the
fir64+fft2048 chain runs at ~5.6% MFU with every hot stage under the ridge
point), and bf16 alone nearly doubles on-chip throughput (BENCH_TPU_r5: 3967
vs 2087 Msps). The boundary wire already has a quantified-loss story —
``ops/wire.py`` measures each codec's SNR and ``pick_wire`` refuses formats
under a floor. This module extends that machinery INWARD: interior DAG edges
and stage accumulation lower to bf16 (int8 where a stage declares support)
only when a configured SNR budget allows, with the loss MEASURED against the
f32 reference program, never assumed.

Two lowering mechanisms, per stage:

* **Accumulation lowering** — a stage that offers the ``Stage.lower`` hook
  (``fir_stage``, ``fft_stage``, ``channelizer_stage`` and the polyphase
  decimator behind them) is rebuilt with bf16 operands / f32 accumulation:
  native-speed MXU passes on TPU, carried weight/tap matrices landing in
  bf16 (half the carry's HBM round trip per dispatch). On CPU the same cast
  applies the same quantization, so calibration is honest on every backend.
* **Interior-edge lowering** — any float-valued edge BETWEEN stages (never
  the boundary wire — that belongs to ``ops/wire.py``) is quantized through
  bfloat16 (complex edges per re/im plane). Inside the fused XLA program
  this frees the compiler to keep the edge's materialization (scan
  intermediates, multiply-consumed fence stashes) in half-width form.

Calibration (``mode="auto"``): a seeded Gaussian calibration dispatch runs
the f32 reference program stage by stage, then each candidate lowering is
replayed on the reference inputs at its own edge and its output SNR vs the
reference output is measured — a lowering that blows
``interior_snr_budget_db`` is REFUSED, per edge, with the reason recorded.
An end-to-end check guards the composition: the fully-lowered program's sink
SNR must clear the budget minus the incoherent-sum allowance
(``budget − 10·log10(n_lowered)``), else the whole plan declines.
``mode="bf16"`` force-lowers every supporting stage/edge to bf16 (budget
ignored, SNR still measured and reported); ``mode="int8"`` force-lowers each
supporting stage as deep as its hook goes — int8 where accepted (the FIR
family's quantized matmul rungs), bf16 otherwise — the deepest serve
brownout lever. ``mode="off"`` returns the pipeline object UNCHANGED —
bit-identical by construction.

Declined edges and achieved per-edge SNR are visible in ``doctor.report()``
(key ``"precision"``) and the REST profile view
(``GET /api/fg/{fg}/profile/``) via :func:`plans_report`; the applied mode
also rides the autotune streamed-pick cache
(``tpu/autotune.record_interior_precision``) next to (k, inflight,
serve_buckets).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field, replace
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

__all__ = ["EdgeDecision", "PrecisionPlan", "plan_interior_precision",
           "lower_pipeline", "snr_db", "parse_overrides", "note_plan",
           "plans_report", "clear_plans", "pallas_stage_count",
           "dominant_compute_dtype"]

#: precisions tried per stage, most-compressed first (int8 only where the
#: stage's ``lower`` hook accepts it — the FIR family does: ``fir_stage``'s
#: banded int8 matmul and the polyphase decimator's int8 shifted matvec,
#: both real-taps-only; FFT/channelizer stages decline the rung)
LOWER_LADDER = ("int8", "bf16")

#: ``"bf16"`` force-lowers every supporting stage/edge to bf16 exactly;
#: ``"int8"`` force-lowers each supporting stage as DEEP as it goes (int8
#: where the hook accepts it, bf16 fallback, edges bf16) — the serve
#: brownout's deepest precision lever. Forced modes ignore the budget but
#: still measure and report every SNR.
MODES = ("off", "auto", "bf16", "int8")


def snr_db(ref, got) -> float:
    """SNR of ``got`` against reference ``ref`` in dB (inf when exact) — the
    interior-edge counterpart of ``ops/wire.measure_snr_db``."""
    ref = np.asarray(ref).astype(np.complex128)
    got = np.asarray(got).astype(np.complex128)
    err = float(np.mean(np.abs(got - ref) ** 2))
    sig = float(np.mean(np.abs(ref) ** 2))
    if err == 0.0:
        return float("inf")
    if sig == 0.0:
        return float("-inf")
    return 10.0 * float(np.log10(sig / err))


def _edge_cast(y):
    """Quantize one interior edge value through bfloat16 (complex: per
    re/im plane), preserving the stream dtype contract."""
    import jax
    import jax.numpy as jnp
    if jnp.iscomplexobj(y):
        return jax.lax.complex(
            y.real.astype(jnp.bfloat16).astype(jnp.float32),
            y.imag.astype(jnp.bfloat16).astype(jnp.float32)).astype(y.dtype)
    if jnp.issubdtype(y.dtype, jnp.floating):
        return y.astype(jnp.bfloat16).astype(y.dtype)
    return y                      # int payloads (symbols) pass through


@dataclass
class EdgeDecision:
    """One stage's lowering verdict: the accumulation precision applied, the
    output-edge precision applied, the MEASURED SNRs backing both, and —
    only when NO lowering was applied at all — the refusal reason (a
    partially-lowered stage reads its accum refusal from ``accum="f32"`` +
    the measured ``accum_snr_db``, never from ``declined``)."""
    stage: str
    node: int
    index: int                    # flat stage index (update_stage addressing)
    accum: str = "f32"            # "f32" | "bf16" | "int8"
    edge: str = "f32"             # "f32" | "bf16"
    accum_snr_db: Optional[float] = None
    edge_snr_db: Optional[float] = None
    declined: Optional[str] = None

    def as_dict(self) -> dict:
        def _r(v):
            if v is None:
                return None
            return round(v, 1) if np.isfinite(v) else None
        return {"stage": self.stage, "node": self.node, "index": self.index,
                "accum": self.accum, "edge": self.edge,
                "accum_snr_db": _r(self.accum_snr_db),
                "edge_snr_db": _r(self.edge_snr_db),
                "declined": self.declined}


@dataclass
class PrecisionPlan:
    mode: str
    budget_db: float
    edges: List[EdgeDecision] = field(default_factory=list)
    e2e_snr_db: Optional[float] = None     # min across sinks, lowered vs f32
    declined_e2e: bool = False             # auto plan rolled back entirely
    frame: int = 0                         # calibration frame size

    @property
    def lowered(self) -> int:
        """How many stages carry ANY lowering (accum or edge)."""
        return sum(1 for e in self.edges
                   if e.accum != "f32" or e.edge != "f32")

    @property
    def min_snr_db(self) -> Optional[float]:
        """The worst MEASURED SNR among accepted lowerings — the pinned floor
        the bench stamps as ``interior_snr_db_min``. None when nothing
        lowered or every measurement was exact (inf)."""
        vals = []
        for e in self.edges:
            if e.accum != "f32" and e.accum_snr_db is not None \
                    and np.isfinite(e.accum_snr_db):
                vals.append(e.accum_snr_db)
            if e.edge != "f32" and e.edge_snr_db is not None \
                    and np.isfinite(e.edge_snr_db):
                vals.append(e.edge_snr_db)
        if self.e2e_snr_db is not None and np.isfinite(self.e2e_snr_db) \
                and self.lowered:
            vals.append(self.e2e_snr_db)
        return min(vals) if vals else None

    def as_dict(self) -> dict:
        mn = self.min_snr_db
        e2e = self.e2e_snr_db
        return {"mode": self.mode, "budget_db": self.budget_db,
                "lowered": self.lowered,
                "declined": sum(1 for e in self.edges if e.declined),
                "min_snr_db": round(mn, 1) if mn is not None else None,
                "e2e_snr_db": (round(e2e, 1)
                               if e2e is not None and np.isfinite(e2e)
                               else None),
                "declined_e2e": self.declined_e2e,
                "frame": self.frame,
                "edges": [e.as_dict() for e in self.edges]}


def parse_overrides(spec) -> Dict[str, str]:
    """``"fir=off;fft2048=bf16"`` (the config string form) or a dict →
    ``{stage_name: "off"|"auto"|"bf16"|"int8"}``. Unknown values raise — a
    typo'd override must not silently lower or pin anything."""
    if not spec:
        return {}
    if isinstance(spec, dict):
        items = spec.items()
    else:
        items = (part.split("=", 1) for part in str(spec).split(";") if part)
    out = {}
    for k, v in items:
        v = str(v).strip()
        if v not in ("off", "auto", "bf16", "int8"):
            raise ValueError(f"interior_precision override {k!r}={v!r}: "
                             f"expected off|auto|bf16|int8")
        out[str(k).strip()] = v
    return out


# ---------------------------------------------------------------------------
# graph normalization: one node/edge view over all three pipeline classes
# ---------------------------------------------------------------------------

def _as_nodes(pipeline) -> Tuple[list, str]:
    """``([(stages, input_node_ids)], kind)`` in topological order — the
    post-LTI-merge stage lists, so the plan addresses exactly the stages
    ``update_stage`` sees."""
    from .stages import DagPipeline, FanoutPipeline
    if isinstance(pipeline, DagPipeline):
        return [(list(sl), list(inputs))
                for sl, inputs, _off in pipeline._nodes], "dag"
    if isinstance(pipeline, FanoutPipeline):
        nodes = [(list(pipeline.producer.stages), [])]
        nodes += [(list(b.stages), [0]) for b in pipeline.branches]
        return nodes, "fanout"
    return [(list(pipeline.stages), [])], "linear"


def _rebuild(pipeline, kind: str, new_nodes: list):
    from .stages import DagPipeline, FanoutPipeline, Pipeline
    if kind == "dag":
        return DagPipeline([(sl, inputs) for sl, inputs in new_nodes],
                           pipeline.in_dtype, optimize=False)
    if kind == "fanout":
        return FanoutPipeline(new_nodes[0][0],
                              [sl for sl, _in in new_nodes[1:]],
                              pipeline.in_dtype, optimize=False)
    return Pipeline(new_nodes[0][0], pipeline.in_dtype, optimize=False)


def _sink_nodes(nodes: list) -> set:
    consumed = set()
    for _sl, inputs in nodes:
        consumed.update(inputs)
    return {i for i in range(len(nodes)) if i not in consumed}


def _calib_frames(in_dtype, frame: int, n: int, seed: int) -> list:
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        if np.issubdtype(np.dtype(in_dtype), np.complexfloating):
            f = ((rng.standard_normal(frame) + 1j * rng.standard_normal(frame))
                 / np.sqrt(2)).astype(in_dtype)
        elif np.issubdtype(np.dtype(in_dtype), np.floating):
            f = rng.standard_normal(frame).astype(in_dtype)
        else:
            f = rng.integers(0, 127, frame).astype(in_dtype)
        out.append(f)
    return out


def _dtype_of(v):
    if isinstance(v, tuple):
        v = v[0]
    return np.dtype(getattr(v, "dtype", np.float32))


def _run_graph(nodes: list, frames: list, io_ins: Optional[dict] = None,
               io_out: Optional[dict] = None) -> list:
    """Run the node graph eagerly over the calibration frames, carry chained
    frame to frame; returns per-sink output of the LAST frame. ``io_ins``
    collects per-(node, stage) input values of EVERY frame (the candidate
    replay feed); ``io_out`` the last frame's output per stage (the per-edge
    reference)."""
    import jax.numpy as jnp
    carries: Dict[tuple, Any] = {}
    sinks = sorted(_sink_nodes(nodes))
    last_out = None
    for fi, x in enumerate(frames):
        vals: list = [None] * len(nodes)
        for ni, (stages, inputs) in enumerate(nodes):
            if not inputs:
                v = jnp.asarray(x)
            elif len(inputs) == 1:
                v = vals[inputs[0]]
            else:
                v = tuple(vals[j] for j in inputs)
            for si, s in enumerate(stages):
                key = (ni, si)
                if key not in carries:
                    carries[key] = s.init_carry(_dtype_of(v))
                if io_ins is not None:
                    io_ins.setdefault(key, []).append(v)
                c, v = s.fn(carries[key], v)
                carries[key] = c
                if io_out is not None and fi == len(frames) - 1:
                    io_out[key] = v
            vals[ni] = v
        last_out = [vals[s] for s in sinks]
    return last_out


def _replay_stage(stage, ref_in_frames: list) -> Any:
    """Run a candidate stage over the reference inputs at its edge (fresh
    carry, carry chained across the calibration frames); returns the last
    frame's output."""
    c = stage.init_carry(_dtype_of(ref_in_frames[0]))
    y = None
    for v in ref_in_frames:
        c, y = stage.fn(c, v)
    return y


def _wrap_edge(s):
    """The (possibly accum-lowered) stage with its OUTPUT edge quantized
    through bf16. ``lti`` is dropped — lowering runs post-merge and a
    re-merge would discard the wrapper."""
    inner = s.fn

    def fn(carry, x):
        carry, y = inner(carry, x)
        return carry, _edge_cast(y)

    return replace(s, fn=fn, lti=None)


def plan_interior_precision(pipeline, mode: Optional[str] = None,
                            budget_db: Optional[float] = None,
                            overrides=None, frame: Optional[int] = None,
                            seed: int = 0):
    """Plan + build the interior-precision-lowered form of ``pipeline``.

    Returns ``(lowered_pipeline, plan)``. ``mode``/``budget_db`` default to
    config ``interior_precision`` / ``interior_snr_budget_db``;
    ``overrides`` (dict or ``"stage=off;…"`` string, default config
    ``interior_precision_overrides``) pins per-stage verdicts. ``mode="off"``
    returns the SAME pipeline object — bit-identical by construction.
    See the module docstring for the calibration contract.
    """
    from ..config import config
    c = config()
    if mode is None:
        mode = str(c.get("interior_precision", "off") or "off")
    if mode in ("", "off", "0", "false", "none"):
        return pipeline, PrecisionPlan("off", 0.0)
    if mode not in MODES:
        raise ValueError(f"interior_precision mode {mode!r}: "
                         f"expected one of {MODES}")
    if budget_db is None:
        budget_db = float(c.get("interior_snr_budget_db", 40.0))
    if overrides is None:
        overrides = c.get("interior_precision_overrides", "")
    overrides = parse_overrides(overrides)

    nodes, kind = _as_nodes(pipeline)
    fm = int(pipeline.frame_multiple)
    if frame is None:
        frame = fm * max(1, -(-8192 // fm))
    else:
        frame = max(fm, (int(frame) // fm) * fm)
    frames = _calib_frames(pipeline.in_dtype, frame, 2, seed)

    # f32 reference trace: per-stage input feed (every frame — the candidate
    # replay input) and last-frame output (the per-edge reference), with warm
    # carries so streaming state is realistic; plus per-sink outputs
    io_all: Dict[tuple, list] = {}
    io_out: Dict[tuple, Any] = {}
    ref_sinks = _run_graph(nodes, frames, io_ins=io_all, io_out=io_out)

    sinks = _sink_nodes(nodes)
    plan = PrecisionPlan(str(mode), float(budget_db), frame=frame)
    new_nodes: list = []
    flat = 0
    from .stages import MergeStage
    for ni, (stages, inputs) in enumerate(nodes):
        new_stages: list = []
        for si, s in enumerate(stages):
            d = EdgeDecision(stage=str(getattr(s, "name", "?")), node=ni,
                             index=flat)
            flat += 1
            cur = s
            ref_out = io_out[(ni, si)]
            ref_ins = io_all[(ni, si)]
            ov = overrides.get(d.stage)
            is_boundary = si == len(stages) - 1 and ni in sinks
            float_out = _is_float_val(ref_out)
            if isinstance(s, MergeStage):
                d.declined = "merge"
            elif ov == "off":
                d.declined = "override"
            elif not float_out:
                d.declined = "non-float"
            else:
                # -- accumulation ladder (stage-declared support only) ------
                if s.lower is not None:
                    if ov in ("bf16", "int8"):
                        ladder = (ov,)
                    elif mode == "bf16":
                        # forced-bf16 must not force-accept a DEEPER rung
                        ladder = ("bf16",)
                    else:
                        ladder = LOWER_LADDER
                    forced = mode in ("bf16", "int8")
                    for prec in ladder:
                        cand = s.lower(prec)
                        if cand is None:
                            if ov == prec:
                                d.declined = f"unsupported:{prec}"
                            continue
                        got = _replay_stage(cand, ref_ins)
                        s_db = snr_db(ref_out, got)
                        if forced or s_db >= budget_db or ov == prec:
                            d.accum = prec
                            d.accum_snr_db = s_db
                            cur = cand
                            # an earlier rung's refusal (int8 SNR, forced-
                            # unsupported) no longer describes this stage —
                            # ``declined`` means NO lowering was applied
                            d.declined = None
                            break
                        d.accum_snr_db = s_db
                        d.declined = f"accum-snr<{budget_db:g}dB"
                elif ov in ("bf16", "int8"):
                    d.declined = "no-lower-hook"
                # -- interior edge (never the boundary wire) ----------------
                if not is_boundary:
                    e_db = snr_db(ref_out, _edge_cast_host(ref_out))
                    d.edge_snr_db = e_db
                    if mode in ("bf16", "int8") or e_db >= budget_db:
                        d.edge = "bf16"
                        cur = _wrap_edge(cur)
                        # a partially-lowered stage is LOWERED: the accum
                        # refusal stays readable as accum="f32" + its
                        # measured accum_snr_db, not as a decline
                        d.declined = None
                    elif d.accum == "f32" and d.declined is None:
                        d.declined = f"edge-snr<{budget_db:g}dB"
            plan.edges.append(d)
            new_stages.append(cur)
        new_nodes.append((new_stages, list(inputs)))

    if plan.lowered == 0:
        return pipeline, plan

    lowered = _rebuild(pipeline, kind, new_nodes)
    # end-to-end guard: the composition must clear the budget minus the
    # incoherent-sum allowance for the accepted lowerings
    low_sinks = _run_graph(_as_nodes(lowered)[0], frames)
    e2e = min(snr_db(r, g) for r, g in zip(ref_sinks, low_sinks))
    plan.e2e_snr_db = e2e
    if mode == "auto":
        floor = budget_db - 10.0 * np.log10(max(1, plan.lowered))
        if e2e < floor:
            plan.declined_e2e = True
            for d in plan.edges:
                if d.accum != "f32" or d.edge != "f32":
                    d.accum = d.edge = "f32"
                    d.declined = f"e2e-snr<{floor:.1f}dB"
            return pipeline, plan
    return lowered, plan


def _is_float_val(v) -> bool:
    dt = _dtype_of(v)
    return (np.issubdtype(dt, np.floating)
            or np.issubdtype(dt, np.complexfloating))


def _edge_cast_host(y):
    """Host-side mirror of :func:`_edge_cast` for SNR measurement (numpy in,
    numpy out — no trace)."""
    import ml_dtypes
    a = np.asarray(y)
    if np.issubdtype(a.dtype, np.complexfloating):
        re = a.real.astype(np.float32).astype(ml_dtypes.bfloat16)
        im = a.imag.astype(np.float32).astype(ml_dtypes.bfloat16)
        return (re.astype(np.float32)
                + 1j * im.astype(np.float32)).astype(a.dtype)
    if np.issubdtype(a.dtype, np.floating):
        return a.astype(ml_dtypes.bfloat16).astype(a.dtype)
    return a


#: back-compat convenience name: most callers want the (pipeline, plan) pair
lower_pipeline = plan_interior_precision


# ---------------------------------------------------------------------------
# plan registry: doctor.report()["precision"] / REST profile view
# ---------------------------------------------------------------------------

_plans_lock = threading.Lock()
_plans: Dict[str, dict] = {}


def note_plan(program: str, plan: PrecisionPlan) -> None:
    """Publish a kernel's applied plan under its program name (the same name
    the profile plane bills compiles/MFU to)."""
    with _plans_lock:
        _plans[str(program)] = plan.as_dict()


def plans_report() -> Dict[str, dict]:
    """Every published plan — the ``doctor.report()["precision"]`` body and
    the REST profile view's ``"precision"`` key."""
    with _plans_lock:
        return {k: dict(v) for k, v in _plans.items()}


def clear_plans() -> None:
    with _plans_lock:
        _plans.clear()


# ---------------------------------------------------------------------------
# attribution helpers
# ---------------------------------------------------------------------------

def dominant_compute_dtype(pipeline) -> str:
    """"bf16" when any stage accumulates in bf16 (a lowered pipeline) or the
    process-wide MXU precision policy is bf16, else "f32" — the per-dtype
    MFU-denominator key (delegates to ``utils/roofline.dominant_dtype``)."""
    from ..utils.roofline import dominant_dtype
    return dominant_dtype(getattr(pipeline, "stages", []))


def pallas_stage_count(pipeline) -> int:
    """How many stages of ``pipeline`` route through a hand-written Pallas
    kernel (the ``pallas_kernels_active`` bench stamp), mirroring each
    stage's actual trace-time dispatch from its ``Stage.route`` — a forced
    ``impl="pallas"`` counts on every backend (the kernel genuinely runs,
    interpret mode off-TPU); ``"auto"`` counts only where the policy picks
    the kernel on THIS backend (``_pallas_fir_wins`` for FIRs, TPU for the
    channelizer); explicit matmul/os/poly pins never count. The stream
    dtype is walked through the flat stage list (exact for linear chains;
    topological approximation on fan-out/DAG shapes)."""
    import jax
    on_tpu = jax.default_backend() == "tpu"
    n = 0
    dt = np.dtype(getattr(pipeline, "in_dtype", np.complex64))
    for s in getattr(pipeline, "stages", []):
        name = str(getattr(s, "name", ""))
        route = getattr(s, "route", None)
        lti = getattr(s, "lti", None)
        is_c = np.issubdtype(dt, np.complexfloating)
        if route is not None and len(route) > 2 and route[2] == "int8":
            # the int8 rung computes through quantized XLA matmuls, not the
            # (f32/bf16-only) Pallas kernels — never counts
            pass
        elif name == "pallas_fir":
            n += 1
        elif lti is not None:
            taps, decim, _fl, lti_impl = lti
            eff = (route[0] if route else None) or lti_impl
            taps = np.asarray(taps)
            nt = int(taps.size)
            if eff == "pallas" and np.isrealobj(taps) and nt >= 2:
                n += 1          # forced: direct FIR (decim=1) or fused
                #                 FIR→decimate kernel, any backend
            elif eff == "auto" and decim == 1 and on_tpu and not is_c \
                    and np.isrealobj(taps) and 2 <= nt <= 48:
                n += 1          # the fn's _pallas_fir_wins branch
        elif route is not None and "channelizer" in name:
            if route[0] == "pallas" or (route[0] == "auto" and on_tpu):
                n += 1
        elif route is not None and route[0] == "pallas":
            # an edge-wrapped lowered FIR (_wrap_edge drops lti so a
            # re-merge can't discard the wrapper) keeps its route: a forced
            # pallas build asserted real taps at construction, so it counts
            # without re-checking them here
            n += 1
        if getattr(s, "out_dtype", None) is not None:
            dt = np.dtype(s.out_dtype)
    return n
