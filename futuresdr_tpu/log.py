"""Structured logging setup.

Reference: ``src/runtime/logging.rs:7-26`` (tracing-subscriber with ``FUTURESDR_LOG`` env filter).
Here: stdlib logging with ``FUTURESDR_TPU_LOG`` overriding the config level.
"""

from __future__ import annotations

import logging
import os

from .config import config

__all__ = ["init", "logger"]

_initialized = False

_LEVELS = {
    "trace": logging.DEBUG,
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "off": logging.CRITICAL,
}


def init() -> None:
    global _initialized
    if _initialized:
        return
    level_name = os.environ.get("FUTURESDR_TPU_LOG", config().log_level).lower()
    level = _LEVELS.get(level_name, logging.INFO)
    root = logging.getLogger("futuresdr_tpu")
    if not root.handlers:
        h = logging.StreamHandler()
        h.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)-5s %(name)s: %(message)s", datefmt="%H:%M:%S"))
        root.addHandler(h)
    root.setLevel(level)
    _initialized = True


def logger(name: str = "") -> logging.Logger:
    init()
    return logging.getLogger(f"futuresdr_tpu.{name}" if name else "futuresdr_tpu")
