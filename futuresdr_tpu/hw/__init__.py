"""Hardware abstraction layer — the `seify` crate equivalent.

The reference's hardware blocks are generic over the external seify HAL (RTL-SDR, HackRF,
SoapySDR, Aaronia, dummy — ``src/blocks/seify/``). Here the HAL is a small driver registry;
the :class:`DummyDriver` plays the role of seify's ``driver=dummy`` (`tests/seify.rs:16-60`,
feature ``seify_dummy``): hardware-shaped tests with no hardware, producing a rate-limited
noise+tone IQ stream.
"""

from __future__ import annotations

import time
from abc import ABC, abstractmethod
from typing import Dict, Optional, Type

import numpy as np

__all__ = ["Driver", "DummyDriver", "FileDriver", "Device", "register_driver",
           "parse_args"]


def parse_args(args: str) -> Dict[str, str]:
    """Parse 'driver=dummy,rate=1e6'-style device args (seify Args format)."""
    d: Dict[str, str] = {}
    for part in args.split(","):
        part = part.strip()
        if part:
            k, _, v = part.partition("=")
            d[k.strip()] = v.strip()
    return d


class Driver(ABC):
    """One hardware device: RX/TX streaming + tuning knobs."""

    def __init__(self, args: Dict[str, str]):
        self.args = args
        self.sample_rate = float(args.get("rate", 1e6))
        self.frequency = float(args.get("freq", 100e6))
        # None = "not set" (drivers fall back to AGC); 0.0 is a valid manual gain
        self.gain = float(args["gain"]) if "gain" in args else None

    # -- tuning ---------------------------------------------------------------
    def set_sample_rate(self, rate: float, channel: int = 0):
        self.sample_rate = float(rate)

    def set_frequency(self, freq: float, channel: int = 0):
        self.frequency = float(freq)

    def set_gain(self, gain: float, channel: int = 0):
        self.gain = float(gain)

    # -- streaming --------------------------------------------------------------
    @abstractmethod
    def activate_rx(self, channels=(0,)):
        ...

    @abstractmethod
    def read(self, n: int):
        """Blocking read of up to n complex64 samples (per activated channel).

        Returns an ndarray (possibly empty = no data yet) or ``None`` for
        end-of-stream (device gone) — the source block finishes on None."""

    def activate_tx(self, channels=(0,)):
        pass

    def write(self, samples: np.ndarray) -> int:
        return len(samples)

    def deactivate(self):
        pass


class DummyDriver(Driver):
    """Fake SDR: noise + a tone at 10% of the sample rate, wall-clock rate-limited."""

    def __init__(self, args: Dict[str, str]):
        super().__init__(args)
        self._t0: Optional[float] = None
        self._produced = 0
        self._phase = 0.0
        self._rng = np.random.default_rng(int(args.get("seed", 1)))
        self.tx_written = 0
        self.throttle = args.get("throttle", "true").lower() != "false"

    def activate_rx(self, channels=(0,)):
        self._t0 = None
        self._produced = 0

    def read(self, n: int) -> np.ndarray:
        now = time.monotonic()
        if self._t0 is None:
            self._t0 = now
        if self.throttle:
            budget = int((now - self._t0) * self.sample_rate) - self._produced
            while budget <= 0:
                time.sleep(min(0.005, n / self.sample_rate))
                budget = int((time.monotonic() - self._t0) * self.sample_rate) - self._produced
            n = min(n, budget)
        inc = 2 * np.pi * 0.1
        ph = self._phase + inc * np.arange(n)
        self._phase = float((self._phase + inc * n) % (2 * np.pi))
        x = (np.exp(1j * ph) +
             0.1 * (self._rng.standard_normal(n) + 1j * self._rng.standard_normal(n)))
        self._produced += n
        return x.astype(np.complex64)

    def activate_tx(self, channels=(0,)):
        self.tx_written = 0

    def write(self, samples: np.ndarray) -> int:
        self.tx_written += len(samples)
        return len(samples)


class FileDriver(Driver):
    """Replay a complex64 IQ recording as a device (`driver=file,path=...,repeat=true`),
    wall-clock throttled to the sample rate — the HAL-level file-trx analog."""

    def __init__(self, args: Dict[str, str]):
        super().__init__(args)
        self.path = args.get("path")
        if not self.path:
            raise ValueError("FileDriver needs path=<file>")
        self.repeat = args.get("repeat", "true").lower() != "false"
        self.throttle = args.get("throttle", "true").lower() != "false"
        self._f = None
        self._t0: Optional[float] = None
        self._produced = 0
        self.tx_written = 0

    def activate_rx(self, channels=(0,)):
        self._f = open(self.path, "rb")
        self._t0 = None
        self._produced = 0

    def read(self, n: int) -> np.ndarray:
        if self.throttle:
            now = time.monotonic()
            if self._t0 is None:
                self._t0 = now
            budget = int((now - self._t0) * self.sample_rate) - self._produced
            while budget <= 0:
                time.sleep(min(0.005, n / self.sample_rate))
                budget = int((time.monotonic() - self._t0) * self.sample_rate) \
                    - self._produced
            n = min(n, budget)
        data = self._f.read(n * 8)
        if len(data) < 8:
            if not self.repeat:
                # end-of-recording IS end-of-stream for a non-repeating
                # replay: the read contract reserves None for EOS — an empty
                # array means "no data yet" and would spin the source forever
                return None
            self._f.seek(0)
            data = self._f.read(n * 8)
        out = np.frombuffer(data[:(len(data) // 8) * 8], dtype=np.complex64)
        self._produced += len(out)
        return out

    def write(self, samples: np.ndarray) -> int:
        self.tx_written += len(samples)
        return len(samples)

    def deactivate(self):
        if self._f:
            self._f.close()
            self._f = None


_DRIVERS: Dict[str, Type[Driver]] = {"dummy": DummyDriver, "file": FileDriver}


def register_driver(name: str, cls: Type[Driver]) -> None:
    _DRIVERS[name] = cls


class Device:
    """Device factory from an args string (seify ``Device::from_args``)."""

    def __init__(self, args: str = "driver=dummy"):
        parsed = parse_args(args)
        name = parsed.get("driver", "dummy")
        if name not in _DRIVERS:
            # optional drivers live in sibling modules that self-register on import
            # (hw/rtl_tcp.py pattern) — try the generic lazy import first
            import importlib
            try:
                importlib.import_module(f".{name}", __package__)
            except ModuleNotFoundError as e:
                # only "no such driver module" falls through to the unknown-driver
                # error; a driver module that exists but fails to import should
                # surface its real failure
                if e.name != f"{__package__}.{name}":
                    raise
        try:
            cls = _DRIVERS[name]
        except KeyError:
            raise ValueError(f"unknown driver {name!r}; registered: {list(_DRIVERS)}") from None
        self.driver = cls(parsed)
        self.driver_name = name
