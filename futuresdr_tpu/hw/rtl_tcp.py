"""rtl_tcp driver: real RTL-SDR hardware over the rtl_tcp network protocol.

The reference reaches RTL-SDR/HackRF/Soapy hardware through the external seify HAL
(``src/blocks/seify/builder.rs``); this driver gives the same capability with zero
native dependencies by speaking the ``rtl_tcp`` wire protocol (shipped with librtlsdr,
speaks to any RTL dongle on the network):

- on connect the server sends a 12-byte greeting: ``"RTL0"`` magic, tuner type (u32 BE),
  tuner gain count (u32 BE);
- the client tunes with 5-byte commands ``[id, u32 param BE]`` — 0x01 frequency Hz,
  0x02 sample rate Hz, 0x03 gain mode (1 = manual), 0x04 gain in tenths of dB,
  0x08 AGC mode;
- the server then streams interleaved unsigned-8-bit I/Q; samples map to complex64 as
  ``(u8 − 127.5)/127.5``.

Usage: ``SeifySource(args="driver=rtl_tcp,host=192.168.1.5,port=1234,rate=2.4e6,
freq=100e6,gain=28")``.
"""

from __future__ import annotations

import socket
import struct
from typing import Dict, Optional

import numpy as np

from . import Driver, register_driver
from ..log import logger

__all__ = ["RtlTcpDriver"]

log = logger("hw.rtl_tcp")

CMD_FREQUENCY = 0x01
CMD_SAMPLE_RATE = 0x02
CMD_GAIN_MODE = 0x03
CMD_GAIN = 0x04
CMD_FREQ_CORRECTION = 0x05
CMD_AGC_MODE = 0x08


class RtlTcpDriver(Driver):
    """``driver=rtl_tcp,host=...,port=...[,rate=][,freq=][,gain=]``."""

    def __init__(self, args: Dict[str, str]):
        super().__init__(args)
        self.host = args.get("host", "127.0.0.1")
        self.port = int(float(args.get("port", 1234)))
        self._sock: Optional[socket.socket] = None
        self._leftover = b""        # odd trailing byte of a half-received I/Q pair
        self.tuner_type = 0
        self.tuner_gain_count = 0

    # -- connection -----------------------------------------------------------
    def _connect(self) -> None:
        s = socket.create_connection((self.host, self.port), timeout=10.0)
        s.settimeout(10.0)
        magic = self._recv_exact(s, 12)
        if magic[:4] != b"RTL0":
            s.close()
            raise ConnectionError(
                f"{self.host}:{self.port} is not an rtl_tcp server "
                f"(magic {magic[:4]!r})")
        self.tuner_type, self.tuner_gain_count = struct.unpack(">II", magic[4:])
        self._sock = s
        log.info("rtl_tcp %s:%d connected (tuner type %d, %d gains)",
                 self.host, self.port, self.tuner_type, self.tuner_gain_count)

    @staticmethod
    def _recv_exact(s: socket.socket, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = s.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("rtl_tcp server closed the connection")
            buf += chunk
        return buf

    def _cmd(self, cmd: int, param: int) -> None:
        if self._sock is not None:
            self._sock.sendall(struct.pack(">BI", cmd, int(param) & 0xFFFFFFFF))

    # -- tuning (live when connected, latched otherwise) ------------------------
    def set_sample_rate(self, rate: float, channel: int = 0):
        super().set_sample_rate(rate, channel)
        self._cmd(CMD_SAMPLE_RATE, int(rate))

    def set_frequency(self, freq: float, channel: int = 0):
        super().set_frequency(freq, channel)
        self._cmd(CMD_FREQUENCY, int(freq))

    def set_gain(self, gain: float, channel: int = 0):
        super().set_gain(gain, channel)
        self._cmd(CMD_GAIN_MODE, 1)                 # manual
        self._cmd(CMD_GAIN, int(round(gain * 10)))  # tenths of dB

    # -- streaming --------------------------------------------------------------
    def activate_rx(self, channels=(0,)):
        if self._sock is None:
            self._connect()
        self._cmd(CMD_SAMPLE_RATE, int(self.sample_rate))
        self._cmd(CMD_FREQUENCY, int(self.frequency))
        if self.gain is not None:                   # 0.0 dB is a valid manual gain
            self._cmd(CMD_GAIN_MODE, 1)
            self._cmd(CMD_GAIN, int(round(self.gain * 10)))
        else:
            self._cmd(CMD_AGC_MODE, 1)

    def read(self, n: int):
        if self._sock is None:
            raise RuntimeError("rtl_tcp: read before activate_rx")
        # collect up to 2n bytes; on server close deliver the partial tail first
        # and signal EOS (None) on the NEXT read
        buf = self._leftover
        self._leftover = b""
        want = 2 * n
        eos = False
        while len(buf) < want:
            try:
                chunk = self._sock.recv(want - len(buf))
            except socket.timeout:
                # a lull on a live connection is NOT end-of-stream: hand back what we
                # have (possibly nothing) and let the caller poll again
                break
            except OSError:
                chunk = b""
            if not chunk:
                eos = True
                break
            buf += chunk
        if eos and len(buf) < 2:
            return None                             # EOS: server gone → finish
        raw = buf[:(len(buf) // 2) * 2]
        # a half pair at a timeout boundary belongs to the NEXT read — dropping it
        # would shift the stream one byte and swap I/Q for the rest of the session
        self._leftover = buf[len(raw):]
        u = np.frombuffer(raw, dtype=np.uint8).astype(np.float32)
        u = (u - 127.5) / 127.5
        return (u[0::2] + 1j * u[1::2]).astype(np.complex64)

    def deactivate(self):
        if self._sock is not None:
            self._sock.close()
            self._sock = None


register_driver("rtl_tcp", RtlTcpDriver)
