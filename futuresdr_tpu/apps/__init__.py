"""Runnable applications (the reference's `examples/` binaries re-imagined)."""
