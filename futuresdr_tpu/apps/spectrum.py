"""Spectrum analyzer: the TPU FFT showcase app.

Reference: ``examples/spectrum`` (``spectrum/src/bin/cpu.rs:14-31``: seify src → Fft(2048)
→ |x|² → MovingAvg → WebsocketSink, plus a Vulkan variant). Here the compute chain runs
either on CPU blocks or fused on the TPU (one jitted FFT+|x|²+EMA program), feeding a
websocket for a GUI and/or a vector sink.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..blocks import Fft, Apply, MovingAvg, SeifyBuilder, WebsocketSink, VectorSink, Head
from ..runtime import Flowgraph, Runtime
from ..ops import fft_stage, mag2_stage, moving_avg_stage, log10_stage

FFT_SIZE = 2048


def build_flowgraph(source=None, *, use_tpu: bool = True, fft_size: int = FFT_SIZE,
                    ws_port: Optional[int] = None, n_samples: Optional[int] = None,
                    collect: bool = False):
    """Assemble the spectrum flowgraph; returns (fg, sink_or_None)."""
    fg = Flowgraph()
    if source is None:
        source = SeifyBuilder().args("driver=dummy,throttle=false").build_source()
    last = source
    if n_samples:
        head = Head(np.complex64, n_samples)
        fg.connect(last, head)
        last = head
    if use_tpu:
        from ..tpu import TpuKernel
        chain = TpuKernel(
            [fft_stage(fft_size), mag2_stage(),
             moving_avg_stage(fft_size, decay=0.1), log10_stage()],
            np.complex64, frame_size=max(16 * fft_size, 1 << 15))
        fg.connect(last, chain)
        last = chain
    else:
        fft = Fft(fft_size)
        mag = Apply(lambda x: (x.real ** 2 + x.imag ** 2), np.complex64, np.float32)
        avg = MovingAvg(fft_size, width=3, decay=0.1)
        log = Apply(lambda x: 10.0 * np.log10(np.maximum(x, 1e-20)), np.float32)
        fg.connect(last, fft, mag, avg, log)
        last = log
    sink = None
    if ws_port:
        ws = WebsocketSink(ws_port, np.float32, chunk_items=fft_size)
        fg.connect(last, ws)
    elif collect:
        sink = VectorSink(np.float32)
        fg.connect(last, sink)
    else:
        from ..blocks import NullSink
        sink = NullSink(np.float32)
        fg.connect(last, sink)
    return fg, sink


def main(argv=None):
    import argparse
    p = argparse.ArgumentParser(description="TPU spectrum analyzer")
    p.add_argument("--args", default="driver=dummy,throttle=false")
    p.add_argument("--fft", type=int, default=FFT_SIZE)
    p.add_argument("--cpu", action="store_true", help="use CPU blocks instead of TPU")
    p.add_argument("--ws-port", type=int, default=9001)
    p.add_argument("--samples", type=int, default=None)
    p.add_argument("--autotune", action="store_true",
                   help="sweep device frame sizes before starting")
    p.add_argument("--bf16", action="store_true",
                   help="display-grade bf16 FFT precision on the MXU (~6x the XLA "
                        "FFT, -47 dB error — fine for a waterfall, not for decoding)")
    a = p.parse_args(argv)
    if a.bf16:
        import sys as _sys

        import jax

        from ..ops import mxu_fft
        mxu_fft.set_precision("bf16")
        if a.cpu or jax.default_backend() != "tpu":
            print("note: --bf16 affects only the TPU MXU FFT path; "
                  "this run uses the XLA FFT at full precision", file=_sys.stderr)
    if a.autotune and not a.cpu:
        from ..tpu import autotune, instance
        frame, depth, grid = autotune(
            [fft_stage(a.fft), mag2_stage(), moving_avg_stage(a.fft, 0.1),
             log10_stage()], np.complex64)
        inst = instance()
        inst.frame_size, inst.frames_in_flight = frame, depth
        print(f"autotuned: frame={frame} depth={depth} ({grid})")
    src = SeifyBuilder().args(a.args).build_source()
    fg, _ = build_flowgraph(src, use_tpu=not a.cpu, fft_size=a.fft,
                            ws_port=a.ws_port, n_samples=a.samples)
    Runtime().run(fg)


if __name__ == "__main__":
    main()
