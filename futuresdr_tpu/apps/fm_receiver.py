"""Broadcast FM receiver with interactive retuning.

Reference: ``examples/fm-receiver/src/main.rs:83-155``: seify → freq-shift → resampling
FIR → quadrature demod → audio resampler → AudioSink, retuned at runtime via
``handle.post(src, "freq", Pmt::F64)``. Same chain here; the front half can run fused on
the TPU.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..blocks import (SeifyBuilder, XlatingFir, QuadratureDemod, Fir, WavSink,
                      Head, NullSink)
from ..dsp import firdes
from ..runtime import Flowgraph, Runtime

SAMPLE_RATE = 250_000       # after front-end decimation
AUDIO_RATE = 48_000


def front_end_stages(input_rate: float = 1_000_000.0, offset: float = 0.0):
    """The fused-device FM front end (rotate → decimating FIR → FM discriminator →
    polyphase audio resampler) as a stage list — shared by :func:`build_flowgraph`
    and ``perf/fm.py`` so the benchmark measures exactly the pipeline the app ships."""
    from math import gcd
    from ..ops import quad_demod_stage, resample_stage, xlating_fir_stage
    decim = int(input_rate // SAMPLE_RATE)
    g = gcd(AUDIO_RATE, SAMPLE_RATE)
    return [
        # tuner+channel filter folded into ONE xlating FIR: complex taps carry
        # the shift, the residual rotator runs at the decimated rate; retune
        # grammar unchanged ({"stage": "tuner", "phase_inc": θ})
        xlating_fir_stage(firdes.lowpass(0.5 / decim * 0.8, 128),
                          -2 * np.pi * offset / input_rate, decim, name="tuner"),
        quad_demod_stage(SAMPLE_RATE / (2 * np.pi * 75e3)),
        resample_stage(AUDIO_RATE // g, SAMPLE_RATE // g),
    ]


def build_flowgraph(source=None, *, input_rate: float = 1_000_000.0,
                    offset: float = 0.0, audio_path: Optional[str] = None,
                    n_samples: Optional[int] = None, use_tpu: bool = False):
    fg = Flowgraph()
    if source is None:
        source = (SeifyBuilder().args("driver=dummy,throttle=false")
                  .sample_rate(input_rate).build_source())
    last = source
    if n_samples:
        head = Head(np.complex64, n_samples)
        fg.connect(last, head)
        last = head
    decim = int(input_rate // SAMPLE_RATE)
    from math import gcd
    g = gcd(AUDIO_RATE, SAMPLE_RATE)
    if use_tpu:
        # whole front end as ONE fused XLA program; runtime retune reaches the
        # device path through the TpuKernel ctrl port ("tuner" stage carry swap —
        # frames in flight finish at the old frequency, no recompile)
        from ..tpu import TpuKernel
        chain = TpuKernel(front_end_stages(input_rate, offset), np.complex64)
        fg.connect(last, chain)
        retune = chain
        out_block = chain
    else:
        xlate = XlatingFir(firdes.lowpass(0.5 / decim * 0.8, 128), decim, offset,
                           input_rate)
        demod = QuadratureDemod(gain=SAMPLE_RATE / (2 * np.pi * 75e3))
        audio_resamp = Fir(firdes.kaiser_lowpass(0.4 * g / SAMPLE_RATE,
                                                 0.1 * g / SAMPLE_RATE)
                           * (AUDIO_RATE // g),
                           np.float32, decim=SAMPLE_RATE // g, interp=AUDIO_RATE // g)
        fg.connect(last, xlate, demod, audio_resamp)
        retune = xlate
        out_block = audio_resamp
    if audio_path:
        sink = WavSink(audio_path, AUDIO_RATE)
    else:
        sink = NullSink(np.float32)
    fg.connect(out_block, sink)
    return fg, retune, sink


def main(argv=None):
    import argparse
    p = argparse.ArgumentParser(description="FM receiver")
    p.add_argument("--args", default="driver=dummy,throttle=false")
    p.add_argument("--freq", type=float, default=100.0e6)
    p.add_argument("--rate", type=float, default=1e6)
    p.add_argument("--wav", default=None, help="write audio to WAV instead of soundcard")
    p.add_argument("--tpu", action="store_true", help="fused TPU front end")
    a = p.parse_args(argv)
    src = (SeifyBuilder().args(a.args).frequency(a.freq).sample_rate(a.rate)
           .build_source())
    fg, xlate, _ = build_flowgraph(src, input_rate=a.rate, audio_path=a.wav,
                                   use_tpu=a.tpu)
    rt = Runtime()
    running = rt.start(fg)
    print("FM receiver running; type a frequency offset in Hz (or 'q'):")
    try:
        while True:
            line = input("> ").strip()
            if line in ("q", "quit", "exit"):
                break
            try:
                off = float(line)
                if a.tpu:
                    from ..types import Pmt
                    running.handle.post_sync(xlate, "ctrl", Pmt.map(
                        {"stage": "tuner", "phase_inc": -2 * np.pi * off / a.rate}))
                else:
                    running.handle.post_sync(xlate, "freq", off)
            except ValueError:
                print("not a number")
    except (EOFError, KeyboardInterrupt):
        pass
    running.stop_sync()


if __name__ == "__main__":
    main()
