"""Fixed-point phase arithmetic — drift-free NCO state.

Re-design of the reference's ``FixedPointPhase``
(``src/blocks/signal_source/fxpt_phase.rs:11-19``): phase lives in a wrapping
i32 where ``-2^31 ↔ -π`` and ``2^31-1 ↔ π-ε``. Because the per-sample increment
is an exact integer, the accumulated phase never collects floating-point error —
after a billion samples the oscillator is still bit-exactly on its (quantized)
frequency, which a float accumulator is not. Frequency resolution is
``fs / 2^32`` (sub-millihertz at any practical rate).

Deviation from the reference, by design: the reference pairs the i32 phase with
a 10-bit sine LUT because scalar CPU ``sin`` was the bottleneck; here synthesis
is vectorized (numpy/XLA transcendentals over the whole chunk), so the LUT's
speed role is moot and its ~1e-3 amplitude quantization is simply not inherited.
The phase-domain semantics (wrap, increment, retune) are identical.
"""

from __future__ import annotations

import numpy as np

__all__ = ["FixedPointPhase", "advance_u32", "phase_ramp_i32", "i32_to_radians"]

_TWO31 = float(2 ** 31)
_MASK = np.uint64(0xFFFF_FFFF)


def advance_u32(phase: int, inc: int, n: int = 1) -> int:
    """The single wrap-advance rule, unsigned domain: ``(phase + inc·n) mod 2^32``.
    Every fxpt consumer (FixedPointPhase.advance, streaming block state) must go
    through this so a width change happens in exactly one place."""
    return (int(phase) + int(inc) * int(n)) & 0xFFFF_FFFF


def _wrap_to_i32(x_rad: float) -> int:
    """Fold radians into [-π, π) and quantize to the i32 phase domain."""
    tau = 2.0 * np.pi
    d = np.floor(x_rad / tau + 0.5)
    x = x_rad - d * tau
    return int(np.int32(np.clip(round(x * _TWO31 / np.pi), -(2 ** 31), 2 ** 31 - 1)))


class FixedPointPhase:
    """Wrapping-i32 phase accumulator (`fxpt_phase.rs:11-19` semantics)."""

    __slots__ = ("value",)

    def __init__(self, radians: float = 0.0, *, raw: int | None = None):
        self.value = int(np.int32(raw)) if raw is not None else _wrap_to_i32(radians)

    @staticmethod
    def increment_for(frequency: float, sample_rate: float) -> int:
        """Exact i32 per-sample increment for a tone at ``frequency``."""
        cycles = frequency / sample_rate
        v = round((cycles % 1.0) * 2 ** 32) & 0xFFFF_FFFF
        return v - 2 ** 32 if v >= 2 ** 31 else v

    def advance(self, inc: int, n: int = 1) -> "FixedPointPhase":
        """Phase after ``n`` wrapping additions of ``inc`` — O(1), exact."""
        v = advance_u32(self.value, inc, n)
        return FixedPointPhase(raw=v - 2 ** 32 if v >= 2 ** 31 else v)

    def to_radians(self) -> float:
        return self.value * (np.pi / _TWO31)

    def __eq__(self, other) -> bool:
        return isinstance(other, FixedPointPhase) and self.value == other.value

    def __hash__(self) -> int:
        return hash(self.value)

    def __repr__(self) -> str:
        return f"FixedPointPhase({self.to_radians():.6f} rad, {self.value:#x})"


def phase_ramp_i32(start: int, inc: int, n: int) -> np.ndarray:
    """``n`` successive wrapped phases as int32: ``start + inc·[0..n)`` mod 2^32.

    Vectorized in the unsigned domain (int64 intermediate, masked) — the whole
    chunk's phase schedule is exact regardless of chunk boundaries."""
    ramp = (np.uint64(int(start) & 0xFFFF_FFFF) +
            np.uint64(int(inc) & 0xFFFF_FFFF) * np.arange(n, dtype=np.uint64)) & _MASK
    return ramp.astype(np.uint32).view(np.int32)


def i32_to_radians(ph: np.ndarray) -> np.ndarray:
    """Map i32 phases to radians in [-π, π) as float64."""
    return ph.astype(np.float64) * (np.pi / _TWO31)
