"""FIR filter design.

Re-design of ``crates/futuredsp/src/firdes/`` (reference): windowed lowpass/highpass/
bandpass/bandstop, root-raised-cosine and Hilbert designs (``firdes/basic.rs:310-440``),
Kaiser window+order estimation from spec (``firdes::kaiser``), and Parks-McClellan/Remez
equiripple design (the reference ports Janovetz's C remez, ``firdes/remez_impl.rs``; here the
numerical backend is scipy.signal.remez — same exchange algorithm).

All cutoffs are normalized to the sample rate (cycles/sample, i.e. 0.5 = Nyquist).
"""

from __future__ import annotations

import numpy as np

from . import windows as _win

__all__ = ["lowpass", "highpass", "bandpass", "bandstop", "root_raised_cosine",
           "hilbert", "kaiser_order", "kaiser_lowpass", "remez"]


def _sinc_lp(cutoff: float, n: int) -> np.ndarray:
    """Ideal lowpass impulse response, length n, centered."""
    k = np.arange(n) - (n - 1) / 2.0
    return 2.0 * cutoff * np.sinc(2.0 * cutoff * k)


def _apply_window(h: np.ndarray, window) -> np.ndarray:
    w = _win.get_window(window, len(h)) if not isinstance(window, np.ndarray) else window
    return h * w


def lowpass(cutoff: float, n_taps: int, window="hamming") -> np.ndarray:
    """Windowed-sinc lowpass (`firdes/basic.rs` lowpass)."""
    h = _apply_window(_sinc_lp(cutoff, n_taps), window)
    return h / h.sum()


def highpass(cutoff: float, n_taps: int, window="hamming") -> np.ndarray:
    """Spectral inversion of the windowed lowpass (`firdes/basic.rs` highpass)."""
    if n_taps % 2 == 0:
        raise ValueError("highpass needs odd tap count")
    h = -lowpass(cutoff, n_taps, window)
    h[(n_taps - 1) // 2] += 1.0
    return h


def bandpass(f_lo: float, f_hi: float, n_taps: int, window="hamming") -> np.ndarray:
    """Windowed bandpass via lowpass difference (`firdes/basic.rs` bandpass)."""
    k = np.arange(n_taps) - (n_taps - 1) / 2.0
    h = 2.0 * f_hi * np.sinc(2.0 * f_hi * k) - 2.0 * f_lo * np.sinc(2.0 * f_lo * k)
    h = _apply_window(h, window)
    # normalize to unit gain at band center
    fc = (f_lo + f_hi) / 2.0
    gain = np.abs(np.sum(h * np.exp(-2j * np.pi * fc * np.arange(n_taps))))
    return h / gain


def bandstop(f_lo: float, f_hi: float, n_taps: int, window="hamming") -> np.ndarray:
    if n_taps % 2 == 0:
        raise ValueError("bandstop needs odd tap count")
    bp = bandpass(f_lo, f_hi, n_taps, window)
    h = -bp
    h[(n_taps - 1) // 2] += 1.0
    return h


def root_raised_cosine(span_symbols: int, sps: int, rolloff: float) -> np.ndarray:
    """RRC pulse (`firdes/basic.rs` root_raised_cosine); unit energy."""
    n = span_symbols * sps + 1
    t = (np.arange(n) - (n - 1) / 2.0) / sps
    b = rolloff
    h = np.empty(n)
    for i, ti in enumerate(t):
        if abs(ti) < 1e-9:
            h[i] = 1.0 + b * (4.0 / np.pi - 1.0)
        elif b > 0 and abs(abs(ti) - 1.0 / (4.0 * b)) < 1e-9:
            h[i] = (b / np.sqrt(2.0)) * ((1 + 2 / np.pi) * np.sin(np.pi / (4 * b))
                                         + (1 - 2 / np.pi) * np.cos(np.pi / (4 * b)))
        else:
            num = np.sin(np.pi * ti * (1 - b)) + 4 * b * ti * np.cos(np.pi * ti * (1 + b))
            den = np.pi * ti * (1 - (4 * b * ti) ** 2)
            h[i] = num / den
    return h / np.sqrt(np.sum(h ** 2))


def hilbert(n_taps: int, window="hamming") -> np.ndarray:
    """Hilbert transformer (`firdes/basic.rs` hilbert); odd length."""
    if n_taps % 2 == 0:
        raise ValueError("hilbert needs odd tap count")
    k = np.arange(n_taps) - (n_taps - 1) // 2
    with np.errstate(divide="ignore", invalid="ignore"):
        h = np.where(k % 2 != 0, 2.0 / (np.pi * k), 0.0)
    return _apply_window(h, window)


def kaiser_order(atten_db: float, transition_width: float) -> tuple:
    """Kaiser order/beta estimation from stopband attenuation + normalized transition
    width (`firdes/basic.rs:310-440` kaiser auto-order)."""
    a = float(atten_db)
    if a > 50.0:
        beta = 0.1102 * (a - 8.7)
    elif a >= 21.0:
        beta = 0.5842 * (a - 21.0) ** 0.4 + 0.07886 * (a - 21.0)
    else:
        beta = 0.0
    n = int(np.ceil((a - 7.95) / (2.285 * 2 * np.pi * transition_width))) + 1
    return n, beta


def kaiser_lowpass(cutoff: float, transition_width: float, atten_db: float = 60.0) -> np.ndarray:
    """Lowpass from spec via Kaiser window (`firdes::kaiser::lowpass`)."""
    n, beta = kaiser_order(atten_db, transition_width)
    if n % 2 == 0:
        n += 1
    return lowpass(cutoff, n, _win.kaiser(n, beta))


def remez(n_taps: int, bands, desired, weight=None, kind: str = "bandpass") -> np.ndarray:
    """Parks-McClellan equiripple design (`firdes/remez_impl.rs:713` role).

    ``bands`` are normalized edge pairs in cycles/sample (0..0.5); ``desired`` one gain
    per band. Native Remez exchange implementation (:mod:`.remez`), matching scipy's to
    ~1e-4 in |H| (cross-checked in tests).
    """
    from .remez import remez_exchange
    return remez_exchange(n_taps, np.asarray(bands).ravel(), desired, weight)
