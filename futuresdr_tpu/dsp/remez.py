"""Parks-McClellan equiripple FIR design — native Remez exchange.

Re-design of the reference's Remez port (``crates/futuredsp/src/firdes/remez_impl.rs:713``,
itself from Janovetz's C): Chebyshev approximation over a dense frequency grid with
barycentric-Lagrange interpolation and extremal exchange. Type-I/II linear-phase designs
(symmetric impulse response).

Bands/gains as in the reference API: band edges normalized to cycles/sample (0..0.5),
one desired gain and weight per band.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

__all__ = ["remez_exchange"]


def _build_grid(n_taps: int, bands: np.ndarray, desired: Sequence[float],
                weight: Sequence[float], grid_density: int = 16):
    r = n_taps // 2 + 2                       # number of extremals (alternations)
    n_grid = grid_density * n_taps
    freqs, D, W = [], [], []
    total = sum(b[1] - b[0] for b in bands)
    for (f0, f1), d, w in zip(bands, desired, weight):
        m = max(int(round(n_grid * (f1 - f0) / total)), 8)
        f = np.linspace(f0, f1, m)
        freqs.append(f)
        D.append(np.full(m, d))
        W.append(np.full(m, w))
    return np.concatenate(freqs), np.concatenate(D), np.concatenate(W), r


def remez_exchange(n_taps: int, bands, desired, weight: Optional[Sequence[float]] = None,
                   grid_density: int = 16, max_iters: int = 40,
                   tol: float = 1e-7) -> np.ndarray:
    """Design a linear-phase FIR; returns ``n_taps`` coefficients.

    ``bands``: flat ``[f0, f1, f2, f3, ...]`` edge list or list of (lo, hi) pairs;
    ``desired``: one gain per band; ``weight``: one per band (default 1).
    """
    bands = np.asarray(bands, dtype=np.float64).reshape(-1, 2)
    n_bands = len(bands)
    desired = list(desired)
    weight = list(weight) if weight is not None else [1.0] * n_bands
    assert len(desired) == n_bands and len(weight) == n_bands

    odd = n_taps % 2 == 1
    grid, D, W, r = _build_grid(n_taps, bands, desired, weight, grid_density)
    x = np.cos(2 * np.pi * grid)              # Chebyshev variable on the grid
    if not odd:
        # type II: factor out cos(πf); approximate D/cos(πf) with weight W·cos(πf)
        c = np.cos(np.pi * grid)
        keep = np.abs(c) > 1e-9
        grid, D, W, x, c = grid[keep], D[keep], W[keep], x[keep], np.cos(np.pi * grid[keep])
        D = D / c
        W = W * np.abs(c)
        r = (n_taps + 1) // 2 + 1

    # initial extremals: uniform over the grid
    ext = np.round(np.linspace(0, len(grid) - 1, r)).astype(np.int64)

    last_delta = 0.0
    for _ in range(max_iters):
        xe = x[ext]
        de = D[ext]
        we = W[ext]
        # barycentric weights over the extremal set
        diff = xe[:, None] - xe[None, :]
        np.fill_diagonal(diff, 1.0)
        # guard duplicate abscissae
        b = 1.0 / np.prod(np.where(np.abs(diff) < 1e-14, 1e-14, diff), axis=1)
        sgn = (-1.0) ** np.arange(r)
        delta = np.dot(b, de) / np.dot(b, sgn / we)
        # Lagrange interpolation through r-1 points of A(x): A(xe_i) = de_i − sgn_i·δ/we_i
        ae = de - sgn * delta / we
        xs, as_, bs = xe[:-1], ae[:-1], b[:-1] * (xe[:-1] - xe[-1])
        # evaluate A on the whole grid (barycentric form)
        dx = x[:, None] - xs[None, :]
        small = np.abs(dx) < 1e-12
        dx = np.where(small, 1.0, dx)
        num = (bs * as_ / dx).sum(axis=1)
        den = (bs / dx).sum(axis=1)
        A = num / den
        hit = small.any(axis=1)
        if hit.any():
            A[hit] = as_[np.argmax(small[hit], axis=1)]
        E = W * (D - A)

        # find new extremals: local maxima of |E| + band edges, alternating, top r
        cand = [0]
        for i in range(1, len(E) - 1):
            if (E[i] - E[i - 1]) * (E[i + 1] - E[i]) <= 0:
                cand.append(i)
        cand.append(len(E) - 1)
        cand = np.array(sorted(set(cand)))
        # enforce sign alternation keeping the largest |E| of consecutive same-sign runs
        keep = []
        for i in cand:
            if keep and np.sign(E[i]) == np.sign(E[keep[-1]]):
                if np.abs(E[i]) > np.abs(E[keep[-1]]):
                    keep[-1] = i
            else:
                keep.append(i)
        if len(keep) < r:
            break                              # converged / degenerate; keep last ext
        keep = np.array(keep)
        # drop the smallest-|E| endpoints until exactly r remain
        while len(keep) > r:
            if np.abs(E[keep[0]]) <= np.abs(E[keep[-1]]):
                keep = keep[1:]
            else:
                keep = keep[:-1]
        new_ext = keep
        if np.array_equal(new_ext, ext) or abs(abs(delta) - abs(last_delta)) < tol * max(1e-12, abs(delta)):
            ext = new_ext
            break
        ext = new_ext
        last_delta = delta

    # final response on the extremal polynomial → impulse response by frequency sampling
    m = n_taps // 2
    fs = np.arange(n_taps) / n_taps            # sample A(f) at n_taps points (0..1)
    fs = np.where(fs > 0.5, 1.0 - fs, fs)      # symmetric
    xs_all = np.cos(2 * np.pi * fs)
    xe = x[ext]
    de = D[ext]
    we = W[ext]
    diff = xe[:, None] - xe[None, :]
    np.fill_diagonal(diff, 1.0)
    b = 1.0 / np.prod(np.where(np.abs(diff) < 1e-14, 1e-14, diff), axis=1)
    sgn = (-1.0) ** np.arange(len(ext))
    delta = np.dot(b, de) / np.dot(b, sgn / we)
    ae = de - sgn * delta / we
    xs, as_, bs = xe[:-1], ae[:-1], b[:-1] * (xe[:-1] - xe[-1])
    dx = xs_all[:, None] - xs[None, :]
    small = np.abs(dx) < 1e-12
    dx = np.where(small, 1.0, dx)
    A_s = ((bs * as_ / dx).sum(axis=1)) / ((bs / dx).sum(axis=1))
    if small.any():
        rows = small.any(axis=1)
        A_s[rows] = as_[np.argmax(small[rows], axis=1)]
    if not odd:
        A_s = A_s * np.cos(np.pi * np.arange(n_taps) / n_taps *
                           np.where(np.arange(n_taps) <= n_taps / 2, 1, -1))
        # type II frequency sampling handled below via linear-phase reconstruction
    # linear-phase impulse response from the real amplitude samples
    k = np.arange(n_taps)
    if odd:
        # h[n] = (1/N) Σ_k A(f_k)·cos(2π k (n − M)/N)
        n_idx = np.arange(n_taps)[:, None]
        A_full = A_s
        h = (A_full[None, :] * np.cos(2 * np.pi * k[None, :] * (n_idx - m) / n_taps)
             ).sum(axis=1) / n_taps
    else:
        n_idx = np.arange(n_taps)[:, None]
        h = (A_s[None, :] * np.cos(2 * np.pi * k[None, :] * (n_idx - (n_taps - 1) / 2)
                                   / n_taps)).sum(axis=1) / n_taps
    return h
