"""Parks-McClellan equiripple FIR design — native Remez exchange, all four types.

Re-design of the reference's Remez port (``crates/futuredsp/src/firdes/remez_impl.rs:713``,
itself from Janovetz's C): Chebyshev approximation over a dense per-band frequency grid
with barycentric-Lagrange interpolation and extremal exchange. Supports all four
linear-phase types — I/II (symmetric: ``filter_type="bandpass"``) and III/IV
(antisymmetric: ``"hilbert"`` and ``"differentiator"``) — via the standard
amplitude factorization A(f) = Q(f)·P(cos 2πf):

    type I:  Q = 1          type II: Q = cos(πf)
    type III: Q = sin(2πf)  type IV: Q = sin(πf)

The exchange approximates D/Q with weight W·Q; the impulse response is synthesized
exactly from N amplitude samples of the converged polynomial (per-type cosine/sine
series), so the only approximation is the grid discretization itself.

Bands/gains follow the reference API: band edges in cycles/sample (0..0.5), one
desired gain and weight per band.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

__all__ = ["remez_exchange"]


def _band_grids(r: int, bands: np.ndarray, density: int, n_taps: int,
                antisym: bool):
    """Per-band dense grids, classic discretization: points spaced
    ``delf = 0.5/(density·r)`` from each band's lower edge, the last point clamped
    to the upper edge. Edges where the structural factor Q vanishes are clamped
    inward by delf (not dropped), keeping the grid aligned with the canonical
    algorithm. Returned per band so extremal candidates never straddle the
    discontinuity between adjacent bands."""
    odd = n_taps % 2 == 1
    delf = 0.5 / (density * r)
    grids = []
    for bi, (f0, f1) in enumerate(bands):
        if bi == 0 and antisym and f0 < delf:
            f0 = delf                       # Q(0) = 0 for types III/IV
        k = max(int((f1 - f0) / delf + 0.5), 8)
        pts = f0 + delf * np.arange(k)
        pts[-1] = f1
        grids.append(pts)
    # Q(0.5) = 0 for type II (sym even) and type III (antisym odd)
    if (not antisym and not odd) or (antisym and odd):
        last = grids[-1]
        if last[-1] > 0.5 - delf:
            last[-1] = 0.5 - delf
    return grids


def _q_factor(f: np.ndarray, n_taps: int, antisym: bool) -> np.ndarray:
    odd = n_taps % 2 == 1
    if not antisym:
        return np.ones_like(f) if odd else np.cos(np.pi * f)
    return np.sin(2 * np.pi * f) if odd else np.sin(np.pi * f)


def _poly_eval(x, xe, ye, b):
    """Barycentric evaluation of the polynomial through (xe, ye) with weights b."""
    dx = x[:, None] - xe[None, :]
    small = np.abs(dx) < 1e-13
    dx = np.where(small, 1.0, dx)
    num = (b * ye / dx).sum(axis=1)
    den = (b / dx).sum(axis=1)
    out = num / den
    hit = small.any(axis=1)
    if hit.any():
        out[hit] = ye[np.argmax(small[hit], axis=1)]
    return out


def remez_exchange(n_taps: int, bands, desired,
                   weight: Optional[Sequence[float]] = None,
                   grid_density: int = 16, max_iters: int = 64,
                   filter_type: str = "bandpass") -> np.ndarray:
    """Design a linear-phase FIR; returns ``n_taps`` coefficients.

    ``bands``: flat ``[f0, f1, f2, f3, ...]`` edge list or list of (lo, hi) pairs;
    ``desired``: one gain per band; ``weight``: one per band (default 1).
    ``filter_type``: "bandpass" (types I/II), "hilbert" (III/IV, antisymmetric),
    or "differentiator" (III/IV with D ∝ f·gain and 1/f weighting within bands,
    as in the reference/scipy conventions).
    """
    assert filter_type in ("bandpass", "hilbert", "differentiator"), filter_type
    bands = np.asarray(bands, dtype=np.float64).reshape(-1, 2)
    n_bands = len(bands)
    desired = [float(d) for d in desired]
    weight = [float(w) for w in (weight if weight is not None else [1.0] * n_bands)]
    assert len(desired) == n_bands and len(weight) == n_bands

    odd = n_taps % 2 == 1
    antisym = filter_type != "bandpass"
    if antisym:
        L = (n_taps - 3) // 2 if odd else n_taps // 2 - 1
    else:
        L = (n_taps - 1) // 2 if odd else n_taps // 2 - 1
    r = L + 2                                  # extremal count (alternations)

    grids = _band_grids(r, bands, grid_density, n_taps, antisym)
    gf, gD, gW = [], [], []
    for g, (f0, f1), d, w in zip(grids, bands, desired, weight):
        D = np.full(len(g), d)
        W = np.full(len(g), w)
        if filter_type == "differentiator":
            D = d * g
            # relative-error weighting where the response is large (Janovetz rule)
            nz = np.abs(D) > 1e-4
            W = np.where(nz, w / np.maximum(np.abs(D), 1e-12), w)
        gf.append(g)
        gD.append(D)
        gW.append(W)

    grid = np.concatenate(gf)
    D = np.concatenate(gD)
    W = np.concatenate(gW)
    Q = _q_factor(grid, n_taps, antisym)
    D = D / Q
    W = W * np.abs(Q)
    x = np.cos(2 * np.pi * grid)
    seg_edges = np.cumsum([0] + [len(g) for g in gf])

    n_grid = len(grid)
    assert n_grid > r, "grid too small for the requested order"
    ext = np.round(np.linspace(0, n_grid - 1, r)).astype(np.int64)

    delta = 0.0
    for _ in range(max_iters):
        xe, de, we = x[ext], D[ext], W[ext]
        diff = xe[:, None] - xe[None, :]
        np.fill_diagonal(diff, 1.0)
        b = 1.0 / np.prod(np.where(np.abs(diff) < 1e-14, 1e-14, diff), axis=1)
        sgn = (-1.0) ** np.arange(r)
        delta = np.dot(b, de) / np.dot(b, sgn / we)
        ae = de - sgn * delta / we
        A = _poly_eval(x, xe[:-1], ae[:-1], b[:-1] * (xe[:-1] - xe[-1]))
        E = W * (D - A)

        # candidates: per-band local maxima of |E| plus BOTH band edges — never
        # across the inter-band discontinuity (the seam is not a real extremum)
        cand = []
        for s0, s1 in zip(seg_edges[:-1], seg_edges[1:]):
            seg = E[s0:s1]
            if len(seg) == 0:
                continue
            cand.append(s0)
            for i in range(1, len(seg) - 1):
                if (seg[i] - seg[i - 1]) * (seg[i + 1] - seg[i]) <= 0:
                    cand.append(s0 + i)
            if s1 - 1 != s0:
                cand.append(s1 - 1)
        cand = np.array(sorted(set(cand)))
        # enforce alternation: of consecutive same-sign candidates keep largest |E|
        kept: list = []
        for i in cand:
            if kept and np.sign(E[i]) == np.sign(E[kept[-1]]):
                if np.abs(E[i]) > np.abs(E[kept[-1]]):
                    kept[-1] = i
            else:
                kept.append(i)
        if len(kept) < r:
            break                              # degenerate; keep previous extremals
        keep_arr = np.array(kept)
        while len(keep_arr) > r:
            # drop the weaker endpoint (classic rule retains the alternation)
            if np.abs(E[keep_arr[0]]) <= np.abs(E[keep_arr[-1]]):
                keep_arr = keep_arr[1:]
            else:
                keep_arr = keep_arr[:-1]
        new_ext = keep_arr
        if np.array_equal(new_ext, ext):
            break
        ext = new_ext
        # classic done test: the error profile is flat over the extremal set
        aE = np.abs(E[ext])
        if (aE.max() - aE.min()) <= 1e-12 * max(aE.max(), 1e-12):
            break

    # exact synthesis: sample the converged amplitude at k/N and apply the
    # per-type cosine/sine series
    xe, de, we = x[ext], D[ext], W[ext]
    diff = xe[:, None] - xe[None, :]
    np.fill_diagonal(diff, 1.0)
    b = 1.0 / np.prod(np.where(np.abs(diff) < 1e-14, 1e-14, diff), axis=1)
    sgn = (-1.0) ** np.arange(len(ext))
    delta = np.dot(b, de) / np.dot(b, sgn / we)
    ae = de - sgn * delta / we

    N = n_taps
    M = (N - 1) / 2.0
    ks = np.arange(N // 2 + 1)
    fk = ks / N                                # 0 .. 0.5
    Qk = _q_factor(fk, n_taps, antisym)
    Pk = _poly_eval(np.cos(2 * np.pi * fk), xe[:-1], ae[:-1],
                    b[:-1] * (xe[:-1] - xe[-1]))
    Ak = Qk * Pk                               # true amplitude at the sample points

    n_idx = np.arange(N)
    h = np.zeros(N)
    if not antisym:
        h += Ak[0]
        hi = N // 2 if N % 2 == 0 else N // 2 + 1
        for k in range(1, hi):
            h += 2 * Ak[k] * np.cos(2 * np.pi * k * (n_idx - M) / N)
        if N % 2 == 0:
            h += Ak[N // 2] * np.cos(np.pi * (n_idx - M))   # structurally 0 (type II)
    else:
        hi = N // 2 if N % 2 == 0 else N // 2 + 1
        for k in range(1, hi):
            h += 2 * Ak[k] * np.sin(2 * np.pi * k * (M - n_idx) / N)
        if N % 2 == 0:
            h += Ak[N // 2] * np.sin(np.pi * (M - n_idx))
    return h / N
