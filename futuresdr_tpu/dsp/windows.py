"""Window functions for filter design and spectral analysis.

Re-design of ``crates/futuredsp/src/windows.rs`` (reference): rect, bartlett, blackman,
hamming, hann, kaiser, gaussian. Computed vectorized in float64 and cast by the caller.
"""

from __future__ import annotations

import numpy as np

__all__ = ["rect", "bartlett", "blackman", "hamming", "hann", "kaiser", "gaussian",
           "get_window"]


def rect(n: int) -> np.ndarray:
    return np.ones(n)


def bartlett(n: int) -> np.ndarray:
    return np.bartlett(n)


def blackman(n: int, exact: bool = False) -> np.ndarray:
    if not exact:
        return np.blackman(n)
    # "exact Blackman" coefficients (reference windows.rs)
    a0, a1, a2 = 7938 / 18608, 9240 / 18608, 1430 / 18608
    k = np.arange(n)
    return a0 - a1 * np.cos(2 * np.pi * k / (n - 1)) + a2 * np.cos(4 * np.pi * k / (n - 1))


def hamming(n: int) -> np.ndarray:
    return np.hamming(n)


def hann(n: int) -> np.ndarray:
    return np.hanning(n)


def kaiser(n: int, beta: float) -> np.ndarray:
    return np.kaiser(n, beta)


def gaussian(n: int, alpha: float = 2.5) -> np.ndarray:
    k = np.arange(n) - (n - 1) / 2.0
    sigma = (n - 1) / (2.0 * alpha)
    return np.exp(-0.5 * (k / sigma) ** 2)


_WINDOWS = {
    "rect": rect,
    "rectangular": rect,
    "bartlett": bartlett,
    "blackman": blackman,
    "hamming": hamming,
    "hann": hann,
    "hanning": hann,
}


def get_window(name, n: int, **kw) -> np.ndarray:
    """Window by name; ``kaiser`` needs ``beta``, ``gaussian`` takes ``alpha``."""
    if callable(name):
        return name(n, **kw)
    name = name.lower()
    if name == "kaiser":
        return kaiser(n, kw.get("beta", 8.6))
    if name == "gaussian":
        return gaussian(n, kw.get("alpha", 2.5))
    try:
        return _WINDOWS[name](n)
    except KeyError:
        raise ValueError(f"unknown window {name!r}") from None
