"""Streaming filter cores: stateful, vectorized, frame-in/frame-out.

Re-design of ``crates/futuredsp/src/`` (reference ``Filter``/``StatefulFilter`` traits,
``fir.rs:31``, ``iir.rs``, ``polyphase_resampling_fir.rs:41``, ``rotator.rs``): each core
carries its history/phase state internally and exposes ``process(x) -> y``, so a block's
``work`` is "read window → process → write". The same cores back the CPU block path (scipy/
numpy, C-speed) while the TPU path re-expresses them as jitted overlap-save stages
(``futuresdr_tpu/ops``) with explicit carry — the streaming contract is identical.
"""

from __future__ import annotations

from typing import Optional

import numpy as np
from scipy.signal import lfilter

__all__ = ["FirFilter", "DecimatingFirFilter", "PolyphaseResamplingFir", "IirFilter",
           "Rotator", "poly_resample_m_hi"]


def poly_resample_m_hi(total: int, interp: int, decim: int) -> int:
    """Outputs producible once ``total`` absolute inputs are visible: the
    largest m with ``(m·D)//I ≤ total−1`` is ``(I·total−1)//D``, plus one.

    THE single Python source of the resampler's producible-output contract
    (used by :class:`PolyphaseResamplingFir` and the native fast-chain's sink
    bound; mirrored once in C, ``native/fastchain.cpp resample_m_hi``). The
    closed form also guarantees ``n_{m_hi} ≥ total``, so K−1 kept history
    always covers the next chunk's windows — the former decrement-loop could
    undershoot the boundary (e.g. I=12, D=5, total=37), deferring a producible
    output past the kept history and making results CHUNK-DEPENDENT (round-5
    fast-chain A/B finding)."""
    if total <= 0:
        return 0
    return (interp * total - 1) // decim + 1


class FirFilter:
    """Plain FIR with per-call state carry (`futuredsp/fir.rs:31`).

    Implementation: explicit input history + direct ``np.convolve`` (SIMD-vectorized C),
    ~2× scipy's ``lfilter`` state machine for typical SDR tap counts.
    """

    def __init__(self, taps, dtype=None):
        self.taps = np.asarray(taps)
        self._hist: Optional[np.ndarray] = None

    @property
    def n_taps(self) -> int:
        return len(self.taps)

    def process(self, x: np.ndarray) -> np.ndarray:
        if len(x) == 0:
            return x
        nt = len(self.taps)
        # preserve the stream's item dtype (float32/complex64 streams stay narrow)
        out_dtype = x.dtype if x.dtype.kind in "fc" else \
            np.result_type(self.taps.dtype, x.dtype)
        if self._hist is None:
            self._hist = np.zeros(nt - 1, dtype=out_dtype)
        ext = np.concatenate([self._hist, x])
        if nt > 1:
            y = np.convolve(ext, self.taps)[nt - 1:nt - 1 + len(x)]
            self._hist = ext[len(ext) - (nt - 1):]
        else:
            y = ext * self.taps[0]
        return y.astype(out_dtype, copy=False)

    def reset(self):
        self._hist = None


class DecimatingFirFilter:
    """FIR + keep-every-Nth with phase carried across calls (`DecimatingFirFilter`)."""

    def __init__(self, taps, decim: int):
        self.fir = FirFilter(taps)
        self.decim = int(decim)
        self._phase = 0  # offset of next kept sample within the incoming filtered stream

    @property
    def n_taps(self) -> int:
        return self.fir.n_taps

    def process(self, x: np.ndarray) -> np.ndarray:
        y = self.fir.process(x)
        if len(y) == 0:
            return y[:0]
        out = y[self._phase::self.decim]
        taken = len(out)
        if taken:
            last = self._phase + (taken - 1) * self.decim
            self._phase = last + self.decim - len(y)
        else:
            self._phase -= len(y)
        return out

    def reset(self):
        self.fir.reset()
        self._phase = 0


class PolyphaseResamplingFir:
    """Rational interp/decim polyphase resampler (`polyphase_resampling_fir.rs:41`).

    Output ``y[m] = Σ_k h[k·I + p_m] · x[n_m − k]`` with ``p_m = (m·D) mod I``,
    ``n_m = (m·D) div I``. History and the absolute output counter are carried so frame
    boundaries are seamless.
    """

    def __init__(self, interp: int, decim: int, taps):
        from math import gcd
        g = gcd(int(interp), int(decim))
        self.interp = int(interp) // g
        self.decim = int(decim) // g
        self.taps = np.asarray(taps)
        # polyphase sub-filters, padded to equal length K
        L = len(self.taps)
        self.K = -(-L // self.interp)
        padded = np.zeros(self.K * self.interp, dtype=self.taps.dtype)
        padded[:L] = self.taps
        self.poly = padded.reshape(self.K, self.interp).T   # [interp, K]
        self._hist = None          # last K-1 input samples
        self._m = 0                # absolute output index
        self._consumed = 0         # absolute count of inputs fully behind history

    @property
    def n_taps(self) -> int:
        return len(self.taps)

    def process(self, x: np.ndarray) -> np.ndarray:
        if self._hist is None:
            self._hist = np.zeros(self.K - 1, dtype=np.result_type(self.taps.dtype, x.dtype))
            self._consumed = -(self.K - 1)   # history is virtual zero-padding
        buf = np.concatenate([self._hist, x])
        total = self._consumed + len(buf)     # inputs available: absolute indices < total
        # produce ALL m with n_m <= total - 1 (see poly_resample_m_hi for why
        # the closed form, and why the former decrement-loop was a
        # chunk-dependence bug)
        m_hi = poly_resample_m_hi(total, self.interp, self.decim)
        ms = np.arange(self._m, m_hi)
        if len(ms) == 0:
            out = np.zeros(0, dtype=buf.dtype)
        else:
            pos = (ms * self.decim) // self.interp - self._consumed   # index into buf
            phase = (ms * self.decim) % self.interp
            # gather K-sample windows ending at pos (reversed for dot with poly rows)
            idx = pos[:, None] - np.arange(self.K)[None, :]
            windows = np.where(idx >= 0, buf[np.clip(idx, 0, None)], 0)
            out = np.einsum("mk,mk->m", windows, self.poly[phase])
            self._m = m_hi
        # retain K-1 samples of history
        keep = min(self.K - 1, len(buf))
        self._hist = buf[len(buf) - keep:]
        self._consumed = total - keep
        return out.astype(buf.dtype, copy=False)

    def reset(self):
        self._hist = None
        self._m = 0
        self._consumed = 0


class IirFilter:
    """Direct-form IIR with carried state (`futuredsp` IirFilter)."""

    def __init__(self, b, a=(1.0,)):
        self.b = np.asarray(b, dtype=np.float64)
        self.a = np.asarray(a, dtype=np.float64)
        self._zi: Optional[np.ndarray] = None

    def process(self, x: np.ndarray) -> np.ndarray:
        if len(x) == 0:
            return x
        if self._zi is None:
            n = max(len(self.b), len(self.a)) - 1
            self._zi = np.zeros(n, dtype=np.result_type(x.dtype, np.float64))
        y, self._zi = lfilter(self.b, self.a, x, zi=self._zi)
        return y.astype(x.dtype, copy=False) if np.iscomplexobj(x) else y

    def reset(self):
        self._zi = None


class Rotator:
    """Oscillator-corrected complex rotator (`futuredsp` Rotator): multiplies by
    ``exp(j·(φ₀ + k·Δφ))``, renormalizing periodically to stop drift."""

    def __init__(self, phase_inc: float, phase: float = 0.0):
        self.phase_inc = float(phase_inc)
        self._phase = float(phase)

    def set_phase_inc(self, inc: float):
        self.phase_inc = float(inc)

    def process(self, x: np.ndarray) -> np.ndarray:
        n = len(x)
        if n == 0:
            return x
        ph = self._phase + self.phase_inc * np.arange(n)
        y = x * np.exp(1j * ph).astype(np.complex64 if x.dtype == np.complex64 else complex)
        self._phase = float((self._phase + self.phase_inc * n) % (2 * np.pi))
        return y.astype(x.dtype, copy=False)
