"""DSP math library — the ``futuredsp`` crate equivalent (`crates/futuredsp/src/`).

Pure, dependency-light numerics: window functions, FIR design (windowed/Kaiser/Remez),
streaming filter cores (FIR/decimating/polyphase-resampling/IIR/rotator). The TPU-jitted
counterparts live in :mod:`futuresdr_tpu.ops`.
"""

from . import windows, firdes
from .kernels import (FirFilter, DecimatingFirFilter, PolyphaseResamplingFir,
                      IirFilter, Rotator)

__all__ = ["windows", "firdes", "FirFilter", "DecimatingFirFilter",
           "PolyphaseResamplingFir", "IirFilter", "Rotator"]
