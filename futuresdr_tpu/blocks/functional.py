"""Functional blocks: closures over sample streams.

Re-design of the reference's functional family (``src/blocks/apply.rs``, ``combine.rs``,
``filter.rs``, ``split.rs``, ``source.rs``, ``sink.rs``, ``finite_source.rs``,
``apply_nm.rs``, ``apply_into_iter.rs``). Idiomatic difference: closures here are
**vectorized** — they receive/return numpy arrays over the whole work window rather than a
per-sample scalar, which is what makes the CPU path fast in Python and maps 1:1 onto jitted
TPU stage functions.
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from ..runtime.kernel import Kernel
from ..runtime.tag import filter_tags

__all__ = ["Apply", "Combine", "Filter", "Split", "Source", "FiniteSource", "Sink",
           "ApplyNM", "ApplyIntoIter"]


class Apply(Kernel):
    """1:1 map over a stream (`apply.rs:99-128`): ``out[i] = f(in[i])``, vectorized.

    ``f(x: ndarray) -> ndarray`` must return the same length.
    """

    def __init__(self, f: Callable[[np.ndarray], np.ndarray], in_dtype, out_dtype=None):
        super().__init__()
        self.f = f
        self.input = self.add_stream_input("in", in_dtype)
        self.output = self.add_stream_output("out", out_dtype if out_dtype is not None else in_dtype)

    async def work(self, io, mio, meta):
        inp = self.input.slice()
        out = self.output.slice()
        n = min(len(inp), len(out))
        if n > 0:
            out[:n] = self.f(inp[:n])
            for t in filter_tags(self.input.tags(), n):
                self.output.add_tag(t.index, t.tag)
            self.input.consume(n)
            self.output.produce(n)
        if self.input.finished() and n == len(inp):
            io.finished = True
        elif n > 0 and n < len(inp):
            io.call_again = True


class Combine(Kernel):
    """2→1 zip (`combine.rs`): ``out[i] = f(a[i], b[i])``, vectorized."""

    def __init__(self, f: Callable[[np.ndarray, np.ndarray], np.ndarray],
                 a_dtype, b_dtype=None, out_dtype=None):
        super().__init__()
        self.f = f
        self.in0 = self.add_stream_input("in0", a_dtype)
        self.in1 = self.add_stream_input("in1", b_dtype if b_dtype is not None else a_dtype)
        self.output = self.add_stream_output(
            "out", out_dtype if out_dtype is not None else a_dtype)

    async def work(self, io, mio, meta):
        a = self.in0.slice()
        b = self.in1.slice()
        out = self.output.slice()
        n = min(len(a), len(b), len(out))
        if n > 0:
            out[:n] = self.f(a[:n], b[:n])
            self.in0.consume(n)
            self.in1.consume(n)
            self.output.produce(n)
        if (self.in0.finished() and n == len(a)) or (self.in1.finished() and n == len(b)):
            io.finished = True
        elif n > 0:
            io.call_again = True


class Filter(Kernel):
    """Keep items where the predicate holds (`filter.rs`): ``f(x) -> bool mask``."""

    def __init__(self, f: Callable[[np.ndarray], np.ndarray], dtype):
        super().__init__()
        self.f = f
        self.input = self.add_stream_input("in", dtype)
        self.output = self.add_stream_output("out", dtype)

    async def work(self, io, mio, meta):
        inp = self.input.slice()
        out = self.output.slice()
        n = min(len(inp), len(out))  # worst case: everything passes
        if n > 0:
            kept = inp[:n][np.asarray(self.f(inp[:n]), dtype=bool)]
            out[:len(kept)] = kept
            self.input.consume(n)
            self.output.produce(len(kept))
        if self.input.finished() and n == len(inp):
            io.finished = True
        elif n > 0 and n < len(inp):
            io.call_again = True


class Split(Kernel):
    """1→2 unzip (`split.rs`): ``f(x) -> (a, b)`` of equal length."""

    def __init__(self, f: Callable, in_dtype, out0_dtype=None, out1_dtype=None):
        super().__init__()
        self.f = f
        self.input = self.add_stream_input("in", in_dtype)
        self.out0 = self.add_stream_output(
            "out0", out0_dtype if out0_dtype is not None else in_dtype)
        self.out1 = self.add_stream_output(
            "out1", out1_dtype if out1_dtype is not None else in_dtype)

    async def work(self, io, mio, meta):
        inp = self.input.slice()
        o0 = self.out0.slice()
        o1 = self.out1.slice()
        n = min(len(inp), len(o0), len(o1))
        if n > 0:
            a, b = self.f(inp[:n])
            o0[:n] = a
            o1[:n] = b
            self.input.consume(n)
            self.out0.produce(n)
            self.out1.produce(n)
        if self.input.finished() and n == len(inp):
            io.finished = True
        elif n > 0 and n < len(inp):
            io.call_again = True


class Source(Kernel):
    """Infinite source (`source.rs`): ``f(n) -> ndarray`` fills up to n items per call."""

    def __init__(self, f: Callable[[int], np.ndarray], dtype):
        super().__init__()
        self.f = f
        self.output = self.add_stream_output("out", dtype)

    async def work(self, io, mio, meta):
        out = self.output.slice()
        n = len(out)
        if n > 0:
            data = np.asarray(self.f(n))
            k = min(len(data), n)
            out[:k] = data[:k]
            self.output.produce(k)
            if k > 0:
                io.call_again = True


class FiniteSource(Kernel):
    """Source that ends (`finite_source.rs`): ``f(n) -> ndarray | None`` (None = EOS)."""

    def __init__(self, f: Callable[[int], Optional[np.ndarray]], dtype):
        super().__init__()
        self.f = f
        self.output = self.add_stream_output("out", dtype)

    async def work(self, io, mio, meta):
        out = self.output.slice()
        n = len(out)
        if n == 0:
            return
        data = self.f(n)
        if data is None:
            io.finished = True
            return
        data = np.asarray(data)
        k = min(len(data), n)
        out[:k] = data[:k]
        self.output.produce(k)
        if k > 0:
            io.call_again = True


class Sink(Kernel):
    """Terminal consumer (`sink.rs`): ``f(chunk)`` per work window."""

    def __init__(self, f: Callable[[np.ndarray], None], dtype):
        super().__init__()
        self.f = f
        self.input = self.add_stream_input("in", dtype)

    async def work(self, io, mio, meta):
        inp = self.input.slice()
        if len(inp):
            self.f(inp)
            self.input.consume(len(inp))
        if self.input.finished():
            io.finished = True


class ApplyNM(Kernel):
    """Fixed N:M rate map (`apply_nm.rs`): ``f`` maps k·N input items to k·M output items.

    ``f(x: ndarray[k*N]) -> ndarray[k*M]`` — called with a whole number of N-blocks.
    """

    def __init__(self, f: Callable[[np.ndarray], np.ndarray], n: int, m: int,
                 in_dtype, out_dtype=None):
        super().__init__()
        self.f = f
        self.n = n
        self.m = m
        self.input = self.add_stream_input("in", in_dtype, min_items=n)
        self.output = self.add_stream_output(
            "out", out_dtype if out_dtype is not None else in_dtype, min_items=m)

    async def work(self, io, mio, meta):
        inp = self.input.slice()
        out = self.output.slice()
        k = min(len(inp) // self.n, len(out) // self.m)
        if k > 0:
            out[:k * self.m] = self.f(inp[:k * self.n])
            for t in filter_tags(self.input.tags(), k * self.n):
                self.output.add_tag(t.index * self.m // self.n, t.tag)
            self.input.consume(k * self.n)
            self.output.produce(k * self.m)
        if self.input.finished() and len(inp) - k * self.n < self.n:
            io.finished = True
        elif k > 0:
            io.call_again = True


class ApplyIntoIter(Kernel):
    """1→many expansion (`apply_into_iter.rs`): ``f(x: ndarray) -> ndarray`` of any length.

    Consumes the whole window, buffering overflow output internally.
    """

    def __init__(self, f: Callable[[np.ndarray], np.ndarray], in_dtype, out_dtype=None):
        super().__init__()
        self.f = f
        self.input = self.add_stream_input("in", in_dtype)
        self.output = self.add_stream_output(
            "out", out_dtype if out_dtype is not None else in_dtype)
        self._carry: Optional[np.ndarray] = None

    async def work(self, io, mio, meta):
        progressed = 0
        out = self.output.slice()
        if self._carry is not None and len(out):
            k = min(len(self._carry), len(out))
            out[:k] = self._carry[:k]
            self.output.produce(k)
            self._carry = self._carry[k:] if k < len(self._carry) else None
            progressed += k
            out = self.output.slice()
        if self._carry is None:
            inp = self.input.slice()
            if len(inp):
                data = np.asarray(self.f(inp))
                self.input.consume(len(inp))
                progressed += len(inp)
                k = min(len(data), len(out))
                out[:k] = data[:k]
                self.output.produce(k)
                if k < len(data):
                    self._carry = data[k:].copy()
        if self._carry is not None:
            if progressed:
                io.call_again = True
            # else: park; downstream consume() notifies this block
        elif self.input.finished() and len(self.input.slice()) == 0:
            io.finished = True
