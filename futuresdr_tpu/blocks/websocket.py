"""WebSocket sinks feeding GUIs (waterfall/constellation/time-sink).

Reference: ``src/blocks/{websocket_sink,websocket_pmt_sink}.rs`` — a WS server that pushes
the latest stream chunk (or Pmt) to every connected client; the prophecy GUI widgets
subscribe to these.
"""

from __future__ import annotations

import json
from typing import Set

from ..log import logger
from ..runtime.kernel import Kernel, message_handler
from ..types import Pmt

__all__ = ["WebsocketSink", "WebsocketPmtSink"]

log = logger("blocks.websocket")


class _WsServerMixin:
    async def _start_ws(self, port: int):
        import websockets
        self._clients: Set = set()

        async def handler(ws):
            self._clients.add(ws)
            try:
                await ws.wait_closed()
            finally:
                self._clients.discard(ws)

        self._server = await websockets.serve(handler, "0.0.0.0", port)
        log.info("websocket sink listening on :%d", port)

    async def _stop_ws(self):
        if getattr(self, "_server", None):
            self._server.close()

    async def _broadcast(self, payload):
        dead = []
        for ws in list(self._clients):
            try:
                await ws.send(payload)
            except Exception:
                dead.append(ws)
        for ws in dead:
            self._clients.discard(ws)


class WebsocketSink(Kernel, _WsServerMixin):
    """Push fixed-size binary chunks of the stream to WS clients (`websocket_sink.rs`).

    ``mode``: "drop" sends only the latest chunk per send opportunity (GUI rate),
    "block" applies backpressure.
    """

    def __init__(self, port: int, dtype, chunk_items: int = 2048, mode: str = "drop"):
        super().__init__()
        self.port = port
        self.chunk = chunk_items
        assert mode in ("drop", "block")
        self.mode = mode
        self.input = self.add_stream_input("in", dtype, min_items=chunk_items)

    async def init(self, mio, meta):
        await self._start_ws(self.port)

    async def deinit(self, mio, meta):
        await self._stop_ws()

    async def work(self, io, mio, meta):
        inp = self.input.slice()
        n = (len(inp) // self.chunk) * self.chunk
        if n:
            if self._clients:
                if self.mode == "drop":
                    chunk = inp[n - self.chunk:n]
                    await self._broadcast(chunk.tobytes())
                else:
                    for i in range(0, n, self.chunk):
                        await self._broadcast(inp[i:i + self.chunk].tobytes())
            self.input.consume(n)
        if self.input.finished() and len(inp) - n < self.chunk:
            io.finished = True


class WebsocketPmtSink(Kernel, _WsServerMixin):
    """Push received Pmts to WS clients as JSON (`websocket_pmt_sink.rs`)."""

    def __init__(self, port: int):
        super().__init__()
        self.port = port

    async def init(self, mio, meta):
        await self._start_ws(self.port)

    async def deinit(self, mio, meta):
        await self._stop_ws()

    @message_handler(name="in")
    async def in_handler(self, io, mio, meta, p: Pmt) -> Pmt:
        if p.is_finished():
            io.finished = True
            return Pmt.ok()
        await self._broadcast(json.dumps(p.to_json()))
        return Pmt.ok()
