"""Block library.

Re-design of the reference's ~60-block catalog (``src/blocks/mod.rs:1-110``). Grouped modules:
functional, vector, stream, dsp, message, io, audio, hardware (seify-style), tpu acceleration.
"""

from .functional import (Apply, Combine, Filter, Split, Source, FiniteSource, Sink,
                         ApplyNM, ApplyIntoIter)
from .vector import VectorSource, VectorSink, NullSource, NullSink, CopyRand
from .stream import (Copy, Head, Throttle, MovingAvg, TagDebug, Delay,
                     StreamDuplicator, StreamDeinterleaver, Selector)
from .dsp import (Fir, FirBuilder, Iir, Fft, XlatingFir, SignalSource,
                  QuadratureDemod, Agc, ClockRecoveryMm)
from .pfb import PfbChannelizer, PfbSynthesizer, PfbArbResampler
from .message import (MessageAnnotator, MessageApply, MessageBurst, MessageCopy,
                      MessagePipe, MessageSink, MessageSource)
from .io import (FileSource, FileSink, TcpSource, TcpSink, UdpSource, BlobToUdp,
                 ChannelSource, ChannelSink)
from .websocket import WebsocketSink, WebsocketPmtSink
from .zeromq import PubSink, SubSource
from .seify import SeifySource, SeifySink, SeifyBuilder
from .audio import WavSource, WavSink, AudioSink, AudioSource

__all__ = [
    "Apply", "Combine", "Filter", "Split", "Source", "FiniteSource", "Sink",
    "ApplyNM", "ApplyIntoIter",
    "VectorSource", "VectorSink", "NullSource", "NullSink", "CopyRand",
    "Copy", "Head", "Throttle", "MovingAvg", "TagDebug", "Delay",
    "StreamDuplicator", "StreamDeinterleaver", "Selector",
    "Fir", "FirBuilder", "Iir", "Fft", "XlatingFir", "SignalSource",
    "QuadratureDemod", "Agc", "ClockRecoveryMm",
    "PfbChannelizer", "PfbSynthesizer", "PfbArbResampler",
    "MessageAnnotator", "MessageApply", "MessageBurst", "MessageCopy",
    "MessagePipe", "MessageSink", "MessageSource",
    "FileSource", "FileSink", "TcpSource", "TcpSink", "UdpSource", "BlobToUdp",
    "ChannelSource", "ChannelSink",
    "WebsocketSink", "WebsocketPmtSink",
    "PubSink", "SubSource",
    "SeifySource", "SeifySink", "SeifyBuilder",
    "WavSource", "WavSink", "AudioSink", "AudioSource",
]
