"""ZeroMQ transport blocks: host-to-host flowgraph distribution.

Reference: ``src/blocks/zeromq/{pub_sink,sub_source}.rs`` — the reference's inter-process
distribution story (SURVEY §2.7): PUB/SUB sample streams between runtimes.
"""

from __future__ import annotations

import numpy as np

from ..log import logger
from ..runtime.kernel import Kernel

__all__ = ["PubSink", "SubSource"]

log = logger("blocks.zeromq")


class PubSink(Kernel):
    """Publish stream chunks on a ZMQ PUB socket (`zeromq/pub_sink.rs`)."""

    def __init__(self, address: str, dtype):
        super().__init__()
        self.address = address
        self._sock = None
        self._ctx = None
        self.input = self.add_stream_input("in", dtype)

    async def init(self, mio, meta):
        import zmq
        self._ctx = zmq.Context.instance()
        self._sock = self._ctx.socket(zmq.PUB)
        self._sock.bind(self.address)

    async def deinit(self, mio, meta):
        if self._sock is not None:
            self._sock.close(linger=0)

    async def work(self, io, mio, meta):
        inp = self.input.slice()
        if len(inp):
            self._sock.send(inp.tobytes(), copy=True)
            self.input.consume(len(inp))
        if self.input.finished():
            io.finished = True


class SubSource(Kernel):
    """Subscribe to a ZMQ stream (`zeromq/sub_source.rs`)."""

    BLOCKING = True  # zmq recv blocks its own thread, like #[blocking] hardware blocks

    def __init__(self, address: str, dtype, timeout_ms: int = 100):
        super().__init__()
        self.address = address
        self.timeout_ms = timeout_ms
        self._sock = None
        self._tail = b""
        self.output = self.add_stream_output("out", dtype)

    async def init(self, mio, meta):
        import zmq
        ctx = zmq.Context.instance()
        self._sock = ctx.socket(zmq.SUB)
        self._sock.connect(self.address)
        self._sock.setsockopt(zmq.SUBSCRIBE, b"")
        self._sock.setsockopt(zmq.RCVTIMEO, self.timeout_ms)

    async def deinit(self, mio, meta):
        if self._sock is not None:
            self._sock.close(linger=0)

    async def work(self, io, mio, meta):
        import zmq
        out = self.output.slice()
        if len(out) == 0:
            return
        try:
            data = self._sock.recv()
        except zmq.Again:
            io.call_again = True   # poll again (dedicated thread; cheap)
            return
        buf = self._tail + data
        itemsize = self.output.dtype.itemsize
        k = min(len(buf) // itemsize, len(out))
        if k:
            out[:k] = np.frombuffer(buf[:k * itemsize], dtype=self.output.dtype)
            self.output.produce(k)
        self._tail = buf[k * itemsize:]
        io.call_again = True
