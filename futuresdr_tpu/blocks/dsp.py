"""DSP blocks: filters, FFT, NCO signal source, frequency translation.

Reference: ``src/blocks/{fft.rs,fir.rs,iir.rs,xlating_fir.rs,signal_source/}``. The CPU path
runs the stateful cores from :mod:`futuresdr_tpu.dsp`; fused TPU execution of the same chains
lives in :mod:`futuresdr_tpu.tpu`.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..dsp import firdes, fxpt
from ..dsp.kernels import (DecimatingFirFilter, FirFilter, IirFilter,
                           PolyphaseResamplingFir, Rotator)
from ..runtime.kernel import Kernel, message_handler
from ..runtime.tag import filter_tags
from ..types import Pmt

__all__ = ["Fir", "FirBuilder", "Iir", "Fft", "XlatingFir", "SignalSource",
           "QuadratureDemod", "Agc", "ClockRecoveryMm"]


def _load_mm_native():
    """Bind the native MM work loop (``native/mm.cpp``) once per process; returns the
    (lib, state_type) pair or None when the native library is unavailable. The MM
    control loop is sequential per symbol — the reference runs it compiled
    (``clock_recovery_mm.rs``); here the same loop is C++ behind ctypes, with the
    Python loop kept as a portable fallback (``FSDR_NO_NATIVE=1`` forces it)."""
    import ctypes

    class MmState(ctypes.Structure):
        _fields_ = [("omega", ctypes.c_double), ("omega0", ctypes.c_double),
                    ("mu", ctypes.c_double), ("last", ctypes.c_double),
                    ("last_d", ctypes.c_double), ("gain_omega", ctypes.c_double),
                    ("gain_mu", ctypes.c_double), ("limit", ctypes.c_double)]

    from ..runtime.buffer.circular import probe_native
    f32p = ctypes.POINTER(ctypes.c_float)
    lib = probe_native("fsdr_mm_work", ctypes.c_int64,
                       [f32p, ctypes.c_int64, f32p, ctypes.c_int64,
                        ctypes.POINTER(MmState), ctypes.POINTER(ctypes.c_int64)])
    if lib is None:
        return None
    return lib, MmState


class ClockRecoveryMm(Kernel):
    """Mueller-Müller symbol timing recovery on a real-valued waveform.

    Library-block form of the ZigBee example's ``ClockRecoveryMm``
    (``examples/zigbee/src/clock_recovery_mm.rs``): emits one sample per recovered
    symbol; ``omega`` is the nominal samples/symbol, adapted within ``±limit``.

    The per-symbol adaptation is sequential by construction (each symbol's timing
    error steers the next sample position), so the hot loop runs as native C++
    (``native/mm.cpp``, matched to the Python fallback kept below) — the same
    answer the reference gives by being compiled Rust.
    """

    _native = None      # class-level cache: (lib, MmState) | False

    def __init__(self, omega: float, gain_omega: float = 0.25e-3,
                 mu: float = 0.5, gain_mu: float = 0.03, omega_limit: float = 0.05):
        super().__init__()
        self.omega0 = float(omega)
        self.omega = float(omega)
        self.gain_omega = gain_omega
        self.mu = mu
        self.gain_mu = gain_mu
        self.limit = omega_limit
        self._last = 0.0
        self._last_d = 0.0
        if ClockRecoveryMm._native is None:
            ClockRecoveryMm._native = _load_mm_native() or False
        self.input = self.add_stream_input("in", np.float32,
                                           min_items=int(np.ceil(omega)) + 2)
        self.output = self.add_stream_output("out", np.float32)

    def _work_native(self, inp: np.ndarray, out: np.ndarray) -> tuple:
        import ctypes
        lib, MmState = ClockRecoveryMm._native
        st = MmState(self.omega, self.omega0, self.mu, self._last, self._last_d,
                     self.gain_omega, self.gain_mu, self.limit)
        consumed = ctypes.c_int64(0)
        f32p = ctypes.POINTER(ctypes.c_float)
        inp = np.ascontiguousarray(inp)
        n_out = int(lib.fsdr_mm_work(
            inp.ctypes.data_as(f32p), len(inp), out.ctypes.data_as(f32p),
            len(out), ctypes.byref(st), ctypes.byref(consumed)))
        self.omega, self.mu = st.omega, st.mu
        self._last, self._last_d = st.last, st.last_d
        return consumed.value, n_out

    async def work(self, io, mio, meta):
        inp = self.input.slice()
        out = self.output.slice()
        # entry-omega window requirement — the SAME value the native loop derives
        # internally (mm.cpp computes it from st->omega before iterating), so the
        # finished check below agrees with where either loop actually stopped
        need = int(np.ceil(self.omega * (1 + self.limit))) + 2
        if ClockRecoveryMm._native:
            i, n_out = self._work_native(inp, out)
        else:
            n_out = 0
            i = 0
            while i + need < len(inp) and n_out < len(out):
                s = inp[i] * (1 - self.mu) + inp[i + 1] * self.mu
                d = 1.0 if s > 0 else -1.0
                err = self._last_d * s - d * self._last
                self._last, self._last_d = s, d
                out[n_out] = s
                n_out += 1
                self.omega += self.gain_omega * err
                self.omega = min(max(self.omega, self.omega0 * (1 - self.limit)),
                                 self.omega0 * (1 + self.limit))
                step = self.omega + self.gain_mu * err
                pos = i + self.mu + step
                i = int(pos)
                self.mu = pos - i
        if i > 0:
            self.input.consume(i)
        if n_out:
            self.output.produce(n_out)
        if self.input.finished() and i + need >= len(inp):
            io.finished = True
        elif n_out and n_out == len(out):
            io.call_again = True


class Fir(Kernel):
    """FIR filter block (`fir.rs`), generic over the filter core: plain, decimating, or
    polyphase-resampling (pass ``decim``/``interp``). ``min_items`` is set from the tap
    count as in `fir.rs:49`."""

    def __init__(self, taps, dtype=np.float32, decim: int = 1, interp: int = 1,
                 tap_dtype=None):
        super().__init__()
        taps = np.asarray(taps, dtype=tap_dtype)
        if interp != 1:
            self.core = PolyphaseResamplingFir(interp, decim, taps)
        elif decim != 1:
            self.core = DecimatingFirFilter(taps, decim)
        else:
            self.core = FirFilter(taps)
        self.decim, self.interp = decim, interp
        self.input = self.add_stream_input("in", dtype, min_items=min(len(taps), 1 << 14))
        self.output = self.add_stream_output("out", dtype)

    async def work(self, io, mio, meta):
        inp = self.input.slice()
        out = self.output.slice()
        if self.interp > 1:
            # the resampler emits up to (I·n−1)//D + 1 outputs for n inputs
            # (closed-form m_hi marginal); bound n so that never exceeds the
            # out window
            n_in = min(len(inp),
                       max(0, ((len(out) - 1) * self.decim + 1) // self.interp))
        else:
            # decimating/plain: ceil(n/decim) outputs for n inputs
            n_in = min(len(inp), len(out) * self.decim)
        if n_in > 0:
            y = self.core.process(inp[:n_in])
            assert len(y) <= len(out), "resampler produced more than negotiated"
            out[:len(y)] = y
            # tag transport with rate-change index remapping (SURVEY §7 hard part:
            # item metadata must survive decimation — `circular.rs:37-64` rebasing
            # plus the sample-rate scale)
            for t in filter_tags(self.input.tags(), n_in):
                self.output.add_tag(min(t.index * self.interp // self.decim,
                                        max(len(y) - 1, 0)), t.tag)
            self.input.consume(n_in)
            self.output.produce(len(y))
        if self.input.finished() and n_in == len(inp):
            io.finished = True
        elif n_in > 0 and n_in < len(inp):
            io.call_again = True


class FirBuilder:
    """Convenience constructors (`fir.rs` FirBuilder)."""

    @staticmethod
    def lowpass(cutoff: float, n_taps: int = 64, dtype=np.float32, **kw) -> Fir:
        return Fir(firdes.lowpass(cutoff, n_taps), dtype=dtype, **kw)

    @staticmethod
    def resampling(interp: int, decim: int, dtype=np.complex64,
                   atten_db: float = 60.0) -> Fir:
        """Rational resampler with auto-designed Kaiser lowpass (`FirBuilder::resampling`)."""
        from math import gcd
        g = gcd(interp, decim)
        interp, decim = interp // g, decim // g
        r = max(interp, decim)
        taps = firdes.kaiser_lowpass(0.5 / r * 0.8, 0.1 / r, atten_db) * interp
        return Fir(taps, dtype=dtype, decim=decim, interp=interp)

    @staticmethod
    def decimating(decim: int, cutoff: Optional[float] = None, n_taps: int = 64,
                   dtype=np.complex64) -> Fir:
        cutoff = cutoff if cutoff is not None else 0.4 / decim
        return Fir(firdes.lowpass(cutoff, n_taps), dtype=dtype, decim=decim)


class Iir(Kernel):
    """IIR filter block (`iir.rs`)."""

    def __init__(self, b, a=(1.0,), dtype=np.float32):
        super().__init__()
        self.core = IirFilter(b, a)
        self.input = self.add_stream_input("in", dtype)
        self.output = self.add_stream_output("out", dtype)

    async def work(self, io, mio, meta):
        inp = self.input.slice()
        out = self.output.slice()
        n = min(len(inp), len(out))
        if n > 0:
            out[:n] = self.core.process(inp[:n])
            self.input.consume(n)
            self.output.produce(n)
        if self.input.finished() and n == len(inp):
            io.finished = True
        elif n > 0 and n < len(inp):
            io.call_again = True


class Fft(Kernel):
    """Frame-wise FFT (`fft.rs`): forward/inverse, optional fftshift and 1/√N
    normalization, runtime-switchable ``fft_size`` message port."""

    def __init__(self, fft_size: int = 2048, direction: str = "forward",
                 shift: bool = False, normalize: bool = False, dtype=np.complex64,
                 window=None):
        """``window``: optional name ("hann", "blackman", …) or array applied per
        frame before a forward FFT (spectral-leakage control for spectrum display)."""
        super().__init__()
        assert direction in ("forward", "inverse")
        self.fft_size = int(fft_size)
        self.direction = direction
        self.shift = shift
        self.normalize = normalize
        if window is not None:
            from ..dsp.windows import get_window
            window = np.asarray(window) if not isinstance(window, str) \
                else get_window(window, self.fft_size)
            assert len(window) == self.fft_size
        self.window = window
        self.input = self.add_stream_input("in", dtype, min_items=self.fft_size)
        self.output = self.add_stream_output("out", dtype, min_items=self.fft_size)

    @message_handler(name="fft_size")
    async def fft_size_handler(self, io, mio, meta, p: Pmt) -> Pmt:
        try:
            new = p.to_int()
        except Exception:
            return Pmt.invalid_value()
        if new <= 0:
            return Pmt.invalid_value()
        cap = self.input.reader.capacity_items() if self.input.reader else None
        if cap is not None and new > cap // 2:
            return Pmt.invalid_value()    # would exceed the negotiated buffer window
        self.fft_size = new
        if self.window is not None and len(self.window) != new:
            self.window = None            # window length no longer matches; drop it
        return Pmt.ok()

    async def work(self, io, mio, meta):
        n = self.fft_size
        inp = self.input.slice()
        out = self.output.slice()
        k = min(len(inp) // n, len(out) // n)
        if k > 0:
            frames = inp[:k * n].reshape(k, n)
            if self.direction == "forward":
                if self.window is not None:
                    frames = frames * self.window[None, :]
                y = np.fft.fft(frames, axis=1)
            else:
                y = np.fft.ifft(frames, axis=1) * n   # match reference's unscaled inverse
            if self.normalize:
                y = y / np.sqrt(n)
            if self.shift:
                y = np.fft.fftshift(y, axes=1)
            out[:k * n] = y.reshape(-1).astype(out.dtype, copy=False)
            self.input.consume(k * n)
            self.output.produce(k * n)
        if self.input.finished() and len(inp) - k * n < n:
            io.finished = True
        elif k > 0:
            io.call_again = True


class XlatingFir(Kernel):
    """Frequency-translating decimating FIR (`xlating_fir.rs`): rotate to baseband,
    lowpass, decimate — the front half of every receiver."""

    def __init__(self, taps, decim: int, offset_freq: float, sample_rate: float,
                 dtype=np.complex64):
        super().__init__()
        self.rotator = Rotator(-2.0 * np.pi * offset_freq / sample_rate)
        self.fir = DecimatingFirFilter(np.asarray(taps), decim)
        self.sample_rate = sample_rate
        self.input = self.add_stream_input("in", dtype, min_items=len(taps))
        self.output = self.add_stream_output("out", dtype)
        self.decim = decim

    @message_handler(name="freq")
    async def freq_handler(self, io, mio, meta, p: Pmt) -> Pmt:
        try:
            self.rotator.set_phase_inc(-2.0 * np.pi * p.to_float() / self.sample_rate)
        except Exception:
            return Pmt.invalid_value()
        return Pmt.ok()

    async def work(self, io, mio, meta):
        inp = self.input.slice()
        out = self.output.slice()
        n_in = min(len(inp), len(out) * self.decim)
        if n_in > 0:
            y = self.fir.process(self.rotator.process(inp[:n_in]))
            out[:len(y)] = y
            self.input.consume(n_in)
            self.output.produce(len(y))
        if self.input.finished() and n_in == len(inp):
            io.finished = True
        elif n_in > 0 and n_in < len(inp):
            io.call_again = True


class SignalSource(Kernel):
    """NCO signal source (`signal_source/`): sin/cos/complex-exponential/square at a
    given frequency, with ``freq``/``amplitude`` message ports.

    ``nco="fxpt"`` (the reference's `fxpt_phase.rs:11-19` semantics) keeps phase in
    a wrapping i32 — the increment is an exact integer, so the oscillator never
    accumulates floating-point phase drift over arbitrarily long runs (frequency
    quantized to fs/2^32). ``nco="float"`` is the plain float accumulator, kept for
    comparison; see ``dsp/fxpt.py`` for why the reference's sine LUT is not
    reproduced."""

    def __init__(self, waveform: str, frequency: float, sample_rate: float,
                 amplitude: float = 1.0, offset: float = 0.0, dtype=None,
                 nco: str = "fxpt"):
        super().__init__()
        assert waveform in ("sin", "cos", "complex", "square")
        assert nco in ("fxpt", "float"), nco
        self.waveform = waveform
        self.sample_rate = float(sample_rate)
        self.amplitude = float(amplitude)
        self.offset = float(offset)
        self.nco = nco
        self._phase = 0.0
        self._inc = 2.0 * np.pi * frequency / sample_rate
        self._phase_i = 0                 # wrapping-i32 domain (nco="fxpt")
        self._inc_i = fxpt.FixedPointPhase.increment_for(frequency, sample_rate)
        if dtype is None:
            dtype = np.complex64 if waveform == "complex" else np.float32
        self.output = self.add_stream_output("out", dtype)

    @message_handler(name="freq")
    async def freq_handler(self, io, mio, meta, p: Pmt) -> Pmt:
        try:
            f = p.to_float()
            self._inc = 2.0 * np.pi * f / self.sample_rate
            self._inc_i = fxpt.FixedPointPhase.increment_for(f, self.sample_rate)
        except Exception:
            return Pmt.invalid_value()
        return Pmt.ok()

    @message_handler(name="amplitude")
    async def amplitude_handler(self, io, mio, meta, p: Pmt) -> Pmt:
        try:
            self.amplitude = p.to_float()
        except Exception:
            return Pmt.invalid_value()
        return Pmt.ok()

    async def work(self, io, mio, meta):
        out = self.output.slice()
        n = len(out)
        if n == 0:
            return
        if self.nco == "fxpt":
            ph = fxpt.i32_to_radians(fxpt.phase_ramp_i32(self._phase_i, self._inc_i, n))
            self._phase_i = fxpt.advance_u32(self._phase_i, self._inc_i, n)
        else:
            ph = self._phase + self._inc * np.arange(n)
            self._phase = float((self._phase + self._inc * n) % (2.0 * np.pi))
        if self.waveform == "sin":
            y = np.sin(ph)
        elif self.waveform == "cos":
            y = np.cos(ph)
        elif self.waveform == "square":
            y = np.sign(np.sin(ph))
        else:
            y = np.exp(1j * ph)
        out[:n] = (self.amplitude * y + self.offset).astype(out.dtype, copy=False)
        self.output.produce(n)
        io.call_again = True


class QuadratureDemod(Kernel):
    """FM quadrature demodulator: ``gain · arg(x[n] · conj(x[n-1]))`` (the reference
    builds this as an `Apply` in `examples/fm-receiver/src/main.rs:106-113`; it is a
    named block here because every analog receiver needs it)."""

    def __init__(self, gain: float = 1.0):
        super().__init__()
        self.gain = float(gain)
        self.input = self.add_stream_input("in", np.complex64, min_items=2)
        self.output = self.add_stream_output("out", np.float32)
        self._last = np.complex64(1.0)

    async def work(self, io, mio, meta):
        inp = self.input.slice()
        out = self.output.slice()
        n = min(len(inp), len(out))
        if n > 0:
            prev = np.concatenate(([self._last], inp[:n - 1]))
            out[:n] = self.gain * np.angle(inp[:n] * np.conj(prev))
            self._last = inp[n - 1]
            self.input.consume(n)
            self.output.produce(n)
        if self.input.finished() and n == len(inp):
            io.finished = True
        elif n > 0 and n < len(inp):
            io.call_again = True


class Agc(Kernel):
    """Automatic gain control: exponential power tracking toward a reference level,
    with ``max_gain``/locking via message ports (reference `blocks/agc.rs`)."""

    def __init__(self, dtype=np.complex64, reference: float = 1.0,
                 adjustment_rate: float = 1e-3, max_gain: float = 65536.0,
                 mode: str = "sample"):
        """``mode``: "sample" = per-sample feedback exactly as the reference;
        "block" = vectorized block-floating gain (64-sample control granularity,
        ~50× faster on long streams — the CPU twin of ``ops.agc_stage``)."""
        super().__init__()
        self.reference = float(reference)
        self.rate = float(adjustment_rate)
        self.max_gain = float(max_gain)
        self.gain = 1.0
        self.locked = False
        assert mode in ("sample", "block")
        self.mode = mode
        self.block = 64
        self.input = self.add_stream_input("in", dtype)
        self.output = self.add_stream_output("out", dtype)

    @message_handler(name="gain_lock")
    async def gain_lock_handler(self, io, mio, meta, p: Pmt) -> Pmt:
        try:
            self.locked = bool(p.to_bool() if p.kind.name == "BOOL" else p.to_int())
        except Exception:
            return Pmt.invalid_value()
        return Pmt.ok()

    @message_handler(name="reference_power")
    async def reference_handler(self, io, mio, meta, p: Pmt) -> Pmt:
        try:
            self.reference = p.to_float()
        except Exception:
            return Pmt.invalid_value()
        return Pmt.ok()

    async def work(self, io, mio, meta):
        inp = self.input.slice()
        out = self.output.slice()
        n = min(len(inp), len(out))
        if self.mode == "block" and n >= self.block:
            n -= n % self.block
        if n > 0:
            x = inp[:n]
            if self.locked:
                out[:n] = self.gain * x
            elif self.mode == "block" and n >= self.block:
                mags = np.abs(x).reshape(-1, self.block).mean(axis=1)
                gains = np.empty(len(mags), dtype=np.float64)
                g = self.gain
                r, rate, mg = self.reference, self.rate * self.block, self.max_gain
                for i, m in enumerate(mags):     # short loop: one step per block
                    gains[i] = g
                    g = min(max(g + rate * (r - m * g), 0.0), mg)
                self.gain = g
                out[:n] = (np.repeat(gains, self.block) * x).astype(out.dtype,
                                                                    copy=False)
            else:
                mag = np.abs(x)
                gains = np.empty(n, dtype=np.float64)
                g = self.gain
                r, rate, mg = self.reference, self.rate, self.max_gain
                for i in range(n):          # sequential feedback loop
                    gains[i] = g
                    err = r - mag[i] * g
                    g = min(max(g + rate * err, 0.0), mg)
                self.gain = g
                out[:n] = (gains * x).astype(out.dtype, copy=False)
            self.input.consume(n)
            self.output.produce(n)
        if self.input.finished() and n == len(inp):
            io.finished = True
        elif n > 0 and n < len(inp):
            io.call_again = True
