"""Vector / null sources and sinks — the test & bench workhorses.

Reference: ``VectorSource``/``VectorSink`` (used throughout ``tests/``), ``NullSource``/
``NullSink`` and ``CopyRand`` (the ``perf/`` harness blocks, ``perf/fir/fir.rs:49-72``).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..runtime.kernel import Kernel

__all__ = ["VectorSource", "VectorSink", "NullSource", "NullSink", "CopyRand"]


class VectorSource(Kernel):
    """Emit a fixed vector (optionally repeated), then EOS."""

    def __init__(self, items, dtype=None, repeat: int = 1):
        super().__init__()
        self.items = np.asarray(items, dtype=dtype)
        self.repeat = repeat
        self._pos = 0
        self._round = 0
        self.output = self.add_stream_output("out", self.items.dtype)

    async def work(self, io, mio, meta):
        out = self.output.slice()
        n = len(out)
        produced = 0
        while produced < n:
            if self._round >= self.repeat:
                break
            take = min(n - produced, len(self.items) - self._pos)
            out[produced:produced + take] = self.items[self._pos:self._pos + take]
            produced += take
            self._pos += take
            if self._pos == len(self.items):
                self._pos = 0
                self._round += 1
        if produced:
            self.output.produce(produced)
        if self._round >= self.repeat:
            io.finished = True
        elif produced > 0:
            io.call_again = True  # progress made; more space may exist past the wrap


class VectorSink(Kernel):
    """Collect everything; final state readable after ``run`` (`tests/flowgraph.rs:63-70`)."""

    def __init__(self, dtype):
        super().__init__()
        self.input = self.add_stream_input("in", dtype)
        self._chunks: List[np.ndarray] = []

    async def work(self, io, mio, meta):
        inp = self.input.slice()
        if len(inp):
            self._chunks.append(inp.copy())
            self.input.consume(len(inp))
        if self.input.finished():
            io.finished = True

    def items(self) -> np.ndarray:
        if not self._chunks:
            return np.zeros(0, dtype=self.input.dtype)
        return np.concatenate(self._chunks)


class NullSource(Kernel):
    """Zeros forever (`blocks/null_source`)."""

    def __init__(self, dtype):
        super().__init__()
        self.output = self.add_stream_output("out", dtype)

    async def work(self, io, mio, meta):
        n = self.output.space()
        if n:
            # buffer is zero-initialized; producing without writing is the fast path
            self.output.produce(n)
            io.call_again = True
        # n == 0: park until a reader consumes (its consume() notifies this block)


class NullSink(Kernel):
    """Count-and-drop (`blocks/null_sink`); with ``count`` it finishes after n items."""

    def __init__(self, dtype, count: Optional[int] = None):
        super().__init__()
        self.input = self.add_stream_input("in", dtype)
        self.count = count
        self.n_received = 0

    async def work(self, io, mio, meta):
        n = self.input.available()
        if n:
            self.input.consume(n)
            self.n_received += n
        if self.count is not None and self.n_received >= self.count:
            io.finished = True
        elif self.input.finished() and self.input.available() == 0:
            io.finished = True


class CopyRand(Kernel):
    """Copy with randomized chunk sizes (`perf/perf/src/copy_rand.rs`) — stresses the
    wake/backpressure protocol with irregular work windows."""

    def __init__(self, dtype, max_copy: int = 512, seed: int = 1):
        super().__init__()
        self.input = self.add_stream_input("in", dtype)
        self.output = self.add_stream_output("out", dtype)
        self.max_copy = max_copy
        self._seed = seed              # native fastchain driver re-seeds its own rng
        self._rng = np.random.default_rng(seed)

    async def work(self, io, mio, meta):
        from ..runtime.tag import filter_tags
        inp = self.input.slice()
        out = self.output.slice()
        n = min(len(inp), len(out))
        if n > 0:
            n = min(n, 1 + int(self._rng.integers(self.max_copy)))
            out[:n] = inp[:n]
            for t in filter_tags(self.input.tags(), n):
                self.output.add_tag(t.index, t.tag)
            self.input.consume(n)
            self.output.produce(n)
        if self.input.finished() and n == len(inp):
            io.finished = True
        elif n > 0 and n < len(inp):
            io.call_again = True
