"""Audio blocks: WAV file source/sink and a soundcard sink (gated).

Reference: ``src/blocks/audio/`` (cpal ``AudioSink``/``AudioSource``, hound wav file
source/sink). WAV handling uses the stdlib ``wave`` module; the soundcard path is gated on
``sounddevice`` availability (not present in CI images) and degrades to a null sink with a
warning — the hardware-without-hardware pattern of SURVEY §4.
"""

from __future__ import annotations

import wave
from typing import Optional

import numpy as np

from ..log import logger
from ..runtime.kernel import Kernel

__all__ = ["WavSource", "WavSink", "AudioSink"]

log = logger("blocks.audio")


class WavSource(Kernel):
    """Stream float32 samples from a WAV file (`audio/wav file source`)."""

    def __init__(self, path: str, repeat: bool = False):
        super().__init__()
        self.path = path
        self.repeat = repeat
        self._w: Optional[wave.Wave_read] = None
        self.sample_rate = 0
        self.n_channels = 1
        self.output = self.add_stream_output("out", np.float32)

    async def init(self, mio, meta):
        self._w = wave.open(self.path, "rb")
        self.sample_rate = self._w.getframerate()
        self.n_channels = self._w.getnchannels()
        if self._w.getsampwidth() != 2:
            raise RuntimeError("WavSource supports 16-bit PCM only")

    async def deinit(self, mio, meta):
        if self._w:
            self._w.close()

    async def work(self, io, mio, meta):
        out = self.output.slice()
        want = len(out) // self.n_channels
        if want == 0:
            return
        raw = self._w.readframes(min(want, 1 << 15))
        if not raw:
            if self.repeat:
                self._w.rewind()
                io.call_again = True
                return
            io.finished = True
            return
        pcm = np.frombuffer(raw, dtype=np.int16).astype(np.float32) / 32768.0
        out[:len(pcm)] = pcm
        self.output.produce(len(pcm))
        io.call_again = True


class WavSink(Kernel):
    """Write float32 samples to a 16-bit PCM WAV file (`audio/wav_sink`)."""

    def __init__(self, path: str, sample_rate: int, n_channels: int = 1):
        super().__init__()
        self.path = path
        self.sample_rate = int(sample_rate)
        self.n_channels = n_channels
        self._w: Optional[wave.Wave_write] = None
        self.input = self.add_stream_input("in", np.float32)
        self.n_written = 0

    async def init(self, mio, meta):
        self._w = wave.open(self.path, "wb")
        self._w.setnchannels(self.n_channels)
        self._w.setsampwidth(2)
        self._w.setframerate(self.sample_rate)

    async def deinit(self, mio, meta):
        if self._w:
            self._w.close()

    async def work(self, io, mio, meta):
        inp = self.input.slice()
        if len(inp):
            pcm = np.clip(inp * 32767.0, -32768, 32767).astype(np.int16)
            self._w.writeframes(pcm.tobytes())
            self.n_written += len(inp)
            self.input.consume(len(inp))
        if self.input.finished():
            io.finished = True


class AudioSource(Kernel):
    """Soundcard capture (cpal `AudioSource` role).

    Without an audio backend this **raises at init** (an SDR app capturing silence is a
    trap, not a fallback) unless constructed with ``allow_null=True``, which emits
    silence at real-time pace (CI / headless use)."""

    BLOCKING = True

    def __init__(self, sample_rate: int, n_channels: int = 1, allow_null: bool = False):
        super().__init__()
        self.sample_rate = int(sample_rate)
        self.n_channels = n_channels
        self.allow_null = allow_null
        self._stream = None
        self.output = self.add_stream_output("out", np.float32)

    async def init(self, mio, meta):
        try:
            import sounddevice as sd
            self._stream = sd.InputStream(
                samplerate=self.sample_rate, channels=self.n_channels, dtype="float32")
            self._stream.start()
        except Exception as e:
            if not self.allow_null:
                raise RuntimeError(
                    f"AudioSource: no audio backend ({e!r}); pass allow_null=True "
                    f"to emit silence instead") from e
            log.warning("no audio backend (%r): AudioSource emits silence", e)
            self._stream = None

    async def deinit(self, mio, meta):
        if self._stream is not None:
            self._stream.stop()
            self._stream.close()

    async def work(self, io, mio, meta):
        import asyncio
        out = self.output.slice()
        want = (len(out) // self.n_channels)
        if want == 0:
            return
        if self._stream is not None:
            frames, _ = self._stream.read(min(want, 4096))
            data = frames.reshape(-1)
        else:
            # silence at roughly real-time pace
            n = min(want, self.sample_rate // 20)
            data = np.zeros(n * self.n_channels, np.float32)
            io.block_on(asyncio.sleep(n / self.sample_rate))
        out[:len(data)] = data
        self.output.produce(len(data))
        if self._stream is not None:
            io.call_again = True


class AudioSink(Kernel):
    """Soundcard playback (cpal `AudioSink` role).

    Without an audio backend this **raises at init** (an FM receiver that runs and plays
    nothing is a trap) unless constructed with ``allow_null=True``, which drops samples
    with a warning (CI / headless use)."""

    BLOCKING = True

    def __init__(self, sample_rate: int, n_channels: int = 1, allow_null: bool = False):
        super().__init__()
        self.sample_rate = int(sample_rate)
        self.n_channels = n_channels
        self.allow_null = allow_null
        self._stream = None
        # short queue: at 48 kHz a 16 KiB float buffer is already 85 ms of audio —
        # real-time playback wants the low-latency profile by default
        self.input = self.add_stream_input("in", np.float32,
                                           preferred_buffer_size=16384)

    async def init(self, mio, meta):
        try:
            import sounddevice as sd
            self._stream = sd.OutputStream(
                samplerate=self.sample_rate, channels=self.n_channels, dtype="float32")
            self._stream.start()
        except Exception as e:
            if not self.allow_null:
                raise RuntimeError(
                    f"AudioSink: no audio backend ({e!r}); pass allow_null=True "
                    f"to drop samples instead") from e
            log.warning("no audio backend (%r): AudioSink drops samples", e)
            self._stream = None

    async def deinit(self, mio, meta):
        if self._stream is not None:
            self._stream.stop()
            self._stream.close()

    async def work(self, io, mio, meta):
        inp = self.input.slice()
        if len(inp):
            if self._stream is not None:
                frames = inp[:len(inp) - len(inp) % self.n_channels]
                self._stream.write(frames.reshape(-1, self.n_channels).copy())
            self.input.consume(len(inp))
        if self.input.finished():
            io.finished = True
