"""Audio blocks: WAV file source/sink and a soundcard sink (gated).

Reference: ``src/blocks/audio/`` (cpal ``AudioSink``/``AudioSource``, hound wav file
source/sink). WAV handling uses the stdlib ``wave`` module; the soundcard path is gated on
``sounddevice`` availability (not present in CI images) and degrades to a null sink with a
warning — the hardware-without-hardware pattern of SURVEY §4.

Device plugability: :func:`set_audio_backend` swaps the device layer (cpal's
host-API abstraction role). :class:`FakeAudioBackend` is the in-memory device —
deterministic capture/playback so the REAL ``work()`` stream loops run in CI
instead of being skipped for lack of hardware (round-4 verdict item 7: the
device path previously had zero coverage without a soundcard).
"""

from __future__ import annotations

import wave
from typing import Callable, List, Optional

import numpy as np

from ..log import logger
from ..runtime.kernel import Kernel

__all__ = ["WavSource", "WavSink", "AudioSource", "AudioSink",
           "FakeAudioBackend", "set_audio_backend"]

log = logger("blocks.audio")

_backend = None          # None → probe sounddevice at stream-open time


def set_audio_backend(backend) -> None:
    """Install a device backend (``None`` restores the sounddevice probe).

    A backend exposes ``open(kind, samplerate, channels) -> stream`` where
    ``kind`` is ``"input"``/``"output"`` and the stream duck-types the
    sounddevice API used here: ``start()``, ``stop()``, ``close()``,
    ``read(n) -> (frames[n, ch], overflowed)`` (input) and
    ``write(frames[n, ch])`` (output)."""
    global _backend
    _backend = backend


def _open_stream(kind: str, samplerate: int, channels: int):
    if _backend is not None:
        return _backend.open(kind, samplerate, channels)
    import sounddevice as sd
    cls = sd.InputStream if kind == "input" else sd.OutputStream
    return cls(samplerate=samplerate, channels=channels, dtype="float32")


class FakeAudioBackend:
    """Deterministic in-memory audio device (CI twin of a soundcard).

    - capture: ``capture_fn(n, channels) -> float32 [n, channels]`` supplies
      input frames (``None`` → silence); return an empty array for "no more".
    - playback: every written chunk is appended to :attr:`played`.
    """

    def __init__(self, capture_fn: Optional[Callable] = None):
        self.capture_fn = capture_fn
        self.played: List[np.ndarray] = []
        self.opened: List[str] = []

    def open(self, kind: str, samplerate: int, channels: int):
        self.opened.append(kind)
        return _FakeStream(self, kind, channels)

    def played_samples(self) -> np.ndarray:
        return (np.concatenate([p.reshape(-1) for p in self.played])
                if self.played else np.zeros(0, np.float32))


class _FakeStream:
    def __init__(self, backend: FakeAudioBackend, kind: str, channels: int):
        self._b = backend
        self._kind = kind
        self._ch = channels
        self.started = False

    def start(self):
        self.started = True

    def stop(self):
        self.started = False

    def close(self):
        pass

    def read(self, n: int):
        fn = self._b.capture_fn
        frames = (np.zeros((n, self._ch), np.float32) if fn is None
                  else np.asarray(fn(n, self._ch), np.float32))
        return frames, False

    def write(self, frames: np.ndarray):
        self._b.played.append(np.array(frames, np.float32, copy=True))


class WavSource(Kernel):
    """Stream float32 samples from a WAV file (`audio/wav file source`)."""

    def __init__(self, path: str, repeat: bool = False):
        super().__init__()
        self.path = path
        self.repeat = repeat
        self._w: Optional[wave.Wave_read] = None
        self.sample_rate = 0
        self.n_channels = 1
        self.output = self.add_stream_output("out", np.float32)

    async def init(self, mio, meta):
        self._w = wave.open(self.path, "rb")
        self.sample_rate = self._w.getframerate()
        self.n_channels = self._w.getnchannels()
        if self._w.getsampwidth() != 2:
            raise RuntimeError("WavSource supports 16-bit PCM only")

    async def deinit(self, mio, meta):
        if self._w:
            self._w.close()

    async def work(self, io, mio, meta):
        out = self.output.slice()
        want = len(out) // self.n_channels
        if want == 0:
            return
        raw = self._w.readframes(min(want, 1 << 15))
        if not raw:
            if self.repeat:
                self._w.rewind()
                io.call_again = True
                return
            io.finished = True
            return
        pcm = np.frombuffer(raw, dtype=np.int16).astype(np.float32) / 32768.0
        out[:len(pcm)] = pcm
        self.output.produce(len(pcm))
        io.call_again = True


class WavSink(Kernel):
    """Write float32 samples to a 16-bit PCM WAV file (`audio/wav_sink`)."""

    def __init__(self, path: str, sample_rate: int, n_channels: int = 1):
        super().__init__()
        self.path = path
        self.sample_rate = int(sample_rate)
        self.n_channels = n_channels
        self._w: Optional[wave.Wave_write] = None
        self.input = self.add_stream_input("in", np.float32)
        self.n_written = 0

    async def init(self, mio, meta):
        self._w = wave.open(self.path, "wb")
        self._w.setnchannels(self.n_channels)
        self._w.setsampwidth(2)
        self._w.setframerate(self.sample_rate)

    async def deinit(self, mio, meta):
        if self._w:
            self._w.close()

    async def work(self, io, mio, meta):
        inp = self.input.slice()
        if len(inp):
            pcm = np.clip(inp * 32767.0, -32768, 32767).astype(np.int16)
            self._w.writeframes(pcm.tobytes())
            self.n_written += len(inp)
            self.input.consume(len(inp))
        if self.input.finished():
            io.finished = True


class AudioSource(Kernel):
    """Soundcard capture (cpal `AudioSource` role).

    Without an audio backend this **raises at init** (an SDR app capturing silence is a
    trap, not a fallback) unless constructed with ``allow_null=True``, which emits
    silence at real-time pace (CI / headless use)."""

    BLOCKING = True

    def __init__(self, sample_rate: int, n_channels: int = 1, allow_null: bool = False):
        super().__init__()
        self.sample_rate = int(sample_rate)
        self.n_channels = n_channels
        self.allow_null = allow_null
        self._stream = None
        self.output = self.add_stream_output("out", np.float32)

    async def init(self, mio, meta):
        try:
            self._stream = _open_stream("input", self.sample_rate,
                                        self.n_channels)
            self._stream.start()
        except Exception as e:
            if not self.allow_null:
                raise RuntimeError(
                    f"AudioSource: no audio backend ({e!r}); pass allow_null=True "
                    f"to emit silence instead") from e
            log.warning("no audio backend (%r): AudioSource emits silence", e)
            self._stream = None

    async def deinit(self, mio, meta):
        if self._stream is not None:
            self._stream.stop()
            self._stream.close()

    async def work(self, io, mio, meta):
        import asyncio
        out = self.output.slice()
        want = (len(out) // self.n_channels)
        if want == 0:
            return
        if self._stream is not None:
            frames, _ = self._stream.read(min(want, 4096))
            data = frames.reshape(-1)
            if len(data) == 0:
                # a real device blocks in read(); only a backend signalling
                # end-of-capture (FakeAudioBackend capture_fn exhausted)
                # returns empty — finish like a drained file source
                io.finished = True
                return
        else:
            # silence at roughly real-time pace
            n = min(want, self.sample_rate // 20)
            data = np.zeros(n * self.n_channels, np.float32)
            io.block_on(asyncio.sleep(n / self.sample_rate))
        out[:len(data)] = data
        self.output.produce(len(data))
        if self._stream is not None:
            io.call_again = True


class AudioSink(Kernel):
    """Soundcard playback (cpal `AudioSink` role).

    Without an audio backend this **raises at init** (an FM receiver that runs and plays
    nothing is a trap) unless constructed with ``allow_null=True``, which drops samples
    with a warning (CI / headless use)."""

    BLOCKING = True

    def __init__(self, sample_rate: int, n_channels: int = 1, allow_null: bool = False):
        super().__init__()
        self.sample_rate = int(sample_rate)
        self.n_channels = n_channels
        self.allow_null = allow_null
        self._stream = None
        # sub-frame remainder carried across work() calls: consuming a
        # wrap-capped or odd-length chunk is safe because channel identity is
        # absolute stream position mod n_channels — the dangling sample(s)
        # wait here for their partners instead of being dropped (review)
        self._pend = np.zeros(0, np.float32)
        # short queue: at 48 kHz a 16 KiB float buffer is already 85 ms of audio —
        # real-time playback wants the low-latency profile by default
        self.input = self.add_stream_input("in", np.float32,
                                           preferred_buffer_size=16384)

    async def init(self, mio, meta):
        try:
            self._stream = _open_stream("output", self.sample_rate,
                                        self.n_channels)
            self._stream.start()
        except Exception as e:
            if not self.allow_null:
                raise RuntimeError(
                    f"AudioSink: no audio backend ({e!r}); pass allow_null=True "
                    f"to drop samples instead") from e
            log.warning("no audio backend (%r): AudioSink drops samples", e)
            self._stream = None

    async def deinit(self, mio, meta):
        if self._stream is not None:
            self._stream.stop()
            self._stream.close()

    async def work(self, io, mio, meta):
        inp = self.input.slice()
        if len(inp):
            if self._stream is not None:
                buf = np.concatenate([self._pend, inp]) if len(self._pend) \
                    else np.asarray(inp)
                k = len(buf) - len(buf) % self.n_channels
                if k:
                    self._stream.write(
                        buf[:k].reshape(-1, self.n_channels).copy())
                self._pend = buf[k:].copy()
            self.input.consume(len(inp))
        if self.input.finished():
            if self.input.available():
                # the readable slice was wrap-capped below what is buffered —
                # keep draining (we consumed above, so this always progresses)
                io.call_again = True
            else:
                io.finished = True       # a sub-frame _pend tail is dropped
