"""Stream utility blocks.

Reference: ``src/blocks/{copy,head,throttle,moving_avg,tag_debug,delay,stream_duplicator,
stream_deinterleaver,selector}.rs``.
"""

from __future__ import annotations

import asyncio
import time
from typing import List, Optional

import numpy as np

from ..log import logger
from ..runtime.kernel import Kernel, message_handler
from ..runtime.tag import filter_tags
from ..types import Pmt

__all__ = ["Copy", "Head", "Throttle", "MovingAvg", "TagDebug", "Delay",
           "StreamDuplicator", "StreamDeinterleaver", "Selector"]

log = logger("blocks.stream")


class Copy(Kernel):
    """Pass-through (`copy.rs`)."""

    def __init__(self, dtype):
        super().__init__()
        self.input = self.add_stream_input("in", dtype)
        self.output = self.add_stream_output("out", dtype)

    async def work(self, io, mio, meta):
        inp = self.input.slice()
        out = self.output.slice()
        n = min(len(inp), len(out))
        if n > 0:
            out[:n] = inp[:n]
            for t in filter_tags(self.input.tags(), n):
                self.output.add_tag(t.index, t.tag)
            self.input.consume(n)
            self.output.produce(n)
        if self.input.finished() and n == len(inp):
            io.finished = True
        elif n > 0 and n < len(inp):
            io.call_again = True


class Head(Kernel):
    """Pass n items then finish (`head.rs`)."""

    def __init__(self, dtype, n: int):
        super().__init__()
        self.input = self.add_stream_input("in", dtype)
        self.output = self.add_stream_output("out", dtype)
        self.remaining = int(n)

    async def work(self, io, mio, meta):
        inp = self.input.slice()
        out = self.output.slice()
        n = min(len(inp), len(out), self.remaining)
        if n > 0:
            out[:n] = inp[:n]
            self.input.consume(n)
            self.output.produce(n)
            self.remaining -= n
        if self.remaining == 0 or (self.input.finished() and n == len(inp)):
            io.finished = True
        elif n > 0:
            io.call_again = True


class Throttle(Kernel):
    """Rate-limit by wall clock (`throttle.rs:92-94` — re-arms via ``io.block_on`` timer)."""

    def __init__(self, dtype, rate: float):
        super().__init__()
        self.input = self.add_stream_input("in", dtype)
        self.output = self.add_stream_output("out", dtype)
        self.rate = float(rate)
        self._t0: Optional[float] = None
        self._sent = 0

    @message_handler
    async def rate_handler(self, io, mio, meta, p: Pmt) -> Pmt:
        try:
            self.rate = p.to_float()
            self._t0 = None
        except Exception:
            return Pmt.invalid_value()
        return Pmt.ok()

    async def work(self, io, mio, meta):
        now = time.monotonic()
        if self._t0 is None:
            self._t0 = now
            self._sent = 0
        budget = int((now - self._t0) * self.rate) - self._sent
        inp = self.input.slice()
        out = self.output.slice()
        n = min(len(inp), len(out), max(budget, 0))
        if n > 0:
            out[:n] = inp[:n]
            self.input.consume(n)
            self.output.produce(n)
            self._sent += n
        if self.input.finished() and len(inp) == n:
            io.finished = True
            return
        if len(inp) > n and len(self.output.slice()) > 0:
            # starved by the rate limit, not by data: park on a timer
            io.block_on(asyncio.sleep(0.1))


class MovingAvg(Kernel):
    """Width-N sliding sum/average over interleaved frames (`moving_avg.rs`).

    Averages ``width`` consecutive frames of length ``frame_len`` (e.g. FFT rows) with
    exponential decay, emitting one averaged frame every ``width`` inputs.
    """

    def __init__(self, frame_len: int, width: int = 3, decay: float = 0.1, dtype=np.float32):
        super().__init__()
        self.input = self.add_stream_input("in", dtype, min_items=frame_len)
        self.output = self.add_stream_output("out", dtype, min_items=frame_len)
        self.frame_len = frame_len
        self.width = width
        self.decay = decay
        self._acc = np.zeros(frame_len, dtype=np.float64)
        self._count = 0

    async def work(self, io, mio, meta):
        inp = self.input.slice()
        out = self.output.slice()
        progressed = True
        while progressed:
            progressed = False
            if len(inp) >= self.frame_len:
                frame = inp[:self.frame_len]
                self._acc = self._acc * (1.0 - self.decay) + frame * self.decay
                self._count += 1
                self.input.consume(self.frame_len)
                inp = self.input.slice()
                if self._count >= self.width and len(out) >= self.frame_len:
                    out[:self.frame_len] = self._acc
                    self.output.produce(self.frame_len)
                    out = self.output.slice()
                    self._count = 0
                progressed = True
        if self.input.finished() and len(inp) < self.frame_len:
            io.finished = True


class TagDebug(Kernel):
    """Log tags passing by (`tag_debug.rs`)."""

    def __init__(self, dtype, name: str = "tag_debug"):
        super().__init__()
        self.input = self.add_stream_input("in", dtype)
        self.output = self.add_stream_output("out", dtype)
        self.name = name
        self.seen: List = []

    async def work(self, io, mio, meta):
        inp = self.input.slice()
        out = self.output.slice()
        n = min(len(inp), len(out))
        if n > 0:
            for t in filter_tags(self.input.tags(), n):
                log.info("[%s] tag @%d: %r", self.name, t.index, t.tag)
                self.seen.append(t)
                self.output.add_tag(t.index, t.tag)
            out[:n] = inp[:n]
            self.input.consume(n)
            self.output.produce(n)
        if self.input.finished() and n == len(inp):
            io.finished = True
        elif n > 0 and n < len(inp):
            io.call_again = True


class Delay(Kernel):
    """Delay the stream by n items, zero-padding the front (`delay.rs` Pad/Copy state
    machine); negative n skips items. Runtime-adjustable via the ``new_value`` handler."""

    def __init__(self, dtype, n: int):
        super().__init__()
        self.input = self.add_stream_input("in", dtype)
        self.output = self.add_stream_output("out", dtype)
        self._pad = max(n, 0)
        self._skip = max(-n, 0)

    @message_handler(name="new_value")
    async def new_value(self, io, mio, meta, p: Pmt) -> Pmt:
        try:
            n = p.to_int()
        except Exception:
            return Pmt.invalid_value()
        if n >= 0:
            self._pad += n
        else:
            self._skip += -n
        return Pmt.ok()

    async def work(self, io, mio, meta):
        out = self.output.slice()
        if self._pad and len(out):
            k = min(self._pad, len(out))
            out[:k] = 0
            self.output.produce(k)
            self._pad -= k
            out = self.output.slice()
        inp = self.input.slice()
        if self._skip and len(inp):
            k = min(self._skip, len(inp))
            self.input.consume(k)
            self._skip -= k
            inp = self.input.slice()
        n = min(len(inp), len(out))
        if n > 0:
            out[:n] = inp[:n]
            self.input.consume(n)
            self.output.produce(n)
        if self.input.finished() and n == len(inp) and self._pad == 0:
            io.finished = True
        elif n > 0 and n < len(inp):
            io.call_again = True


class StreamDuplicator(Kernel):
    """1→N duplicate (`stream_duplicator.rs`)."""

    def __init__(self, dtype, n_outputs: int = 2):
        super().__init__()
        self.input = self.add_stream_input("in", dtype)
        self.outputs = [self.add_stream_output(f"out{i}", dtype) for i in range(n_outputs)]

    async def work(self, io, mio, meta):
        inp = self.input.slice()
        n = min([len(inp)] + [o.space() for o in self.outputs])
        if n > 0:
            for o in self.outputs:
                o.slice()[:n] = inp[:n]
                o.produce(n)
            self.input.consume(n)
        if self.input.finished() and n == len(inp):
            io.finished = True
        elif n > 0 and n < len(inp):
            io.call_again = True


class StreamDeinterleaver(Kernel):
    """Round-robin deinterleave to N outputs (`stream_deinterleaver.rs`)."""

    def __init__(self, dtype, n_outputs: int = 2):
        super().__init__()
        self.n = n_outputs
        self.input = self.add_stream_input("in", dtype, min_items=n_outputs)
        self.outputs = [self.add_stream_output(f"out{i}", dtype) for i in range(n_outputs)]

    async def work(self, io, mio, meta):
        inp = self.input.slice()
        k = min([len(inp) // self.n] + [o.space() for o in self.outputs])
        if k > 0:
            frame = inp[:k * self.n].reshape(k, self.n)
            for i, o in enumerate(self.outputs):
                o.slice()[:k] = frame[:, i]
                o.produce(k)
            self.input.consume(k * self.n)
        if self.input.finished() and len(inp) - k * self.n < self.n:
            io.finished = True
        elif k > 0:
            io.call_again = True


class Selector(Kernel):
    """N×M switch (`selector.rs:10-107`): route input ``input_index`` → output
    ``output_index``; both switchable via message handlers; non-selected inputs follow the
    drop policy ("drop_all" | "same_rate" | "no_drop")."""

    def __init__(self, dtype, n_inputs: int, n_outputs: int, drop_policy: str = "drop_all"):
        super().__init__()
        self.inputs = [self.add_stream_input(f"in{i}", dtype) for i in range(n_inputs)]
        self.outputs = [self.add_stream_output(f"out{i}", dtype) for i in range(n_outputs)]
        self.input_index = 0
        self.output_index = 0
        assert drop_policy in ("drop_all", "same_rate", "no_drop")
        self.drop_policy = drop_policy

    @message_handler(name="input_index")
    async def input_index_handler(self, io, mio, meta, p: Pmt) -> Pmt:
        try:
            self.input_index = p.to_int() % len(self.inputs)
        except Exception:
            return Pmt.invalid_value()
        return Pmt.usize(self.input_index)

    @message_handler(name="output_index")
    async def output_index_handler(self, io, mio, meta, p: Pmt) -> Pmt:
        try:
            self.output_index = p.to_int() % len(self.outputs)
        except Exception:
            return Pmt.invalid_value()
        return Pmt.usize(self.output_index)

    async def work(self, io, mio, meta):
        sel_in = self.inputs[self.input_index]
        sel_out = self.outputs[self.output_index]
        inp = sel_in.slice()
        out = sel_out.slice()
        n = min(len(inp), len(out))
        if n > 0:
            out[:n] = inp[:n]
            sel_in.consume(n)
            sel_out.produce(n)
        if self.drop_policy == "drop_all":
            for i, p in enumerate(self.inputs):
                if i != self.input_index:
                    p.consume(p.available())
        elif self.drop_policy == "same_rate":
            for i, p in enumerate(self.inputs):
                if i != self.input_index:
                    p.consume(min(n, p.available()))
        if sel_in.finished() and n == len(inp):
            io.finished = True
        elif n > 0 and n < len(inp):
            io.call_again = True
