"""Message-plane blocks.

Reference: ``src/blocks/{message_annotator,message_apply,message_burst,message_copy,
message_pipe,message_sink,message_source}.rs``.
"""

from __future__ import annotations

import asyncio
from typing import Callable, List, Optional

from ..runtime.kernel import Kernel, message_handler
from ..types import Pmt

__all__ = ["MessageAnnotator", "MessageApply", "MessageBurst", "MessageCopy",
           "MessagePipe", "MessageSink", "MessageSource"]


class MessageCopy(Kernel):
    """Forward messages unchanged (`message_copy.rs`)."""

    def __init__(self):
        super().__init__()
        self.add_message_output("out")

    @message_handler(name="in")
    def in_handler(self, io, mio, meta, p: Pmt) -> Pmt:
        # sync handler: the hot message-plane path skips the per-message
        # coroutine allocation (call_handler supports both forms)
        if p.is_finished():
            io.finished = True
            return Pmt.ok()
        mio.post("out", p)
        return Pmt.ok()


class MessageAnnotator(Kernel):
    """Wrap each message in a map with extra fields (`message_annotator.rs`)."""

    def __init__(self, annotations: dict, key: str = "data"):
        super().__init__()
        self.annotations = annotations
        self.key = key
        self.add_message_output("out")

    @message_handler(name="in")
    def in_handler(self, io, mio, meta, p: Pmt) -> Pmt:
        # sync handler: direct-dispatch eligible (no awaits in the body)
        if p.is_finished():
            io.finished = True
            return Pmt.ok()
        d = dict(self.annotations)
        d[self.key] = p
        mio.post("out", Pmt.map(d))
        return Pmt.ok()


class MessageApply(Kernel):
    """Map messages through a function; None drops (`message_apply.rs`)."""

    def __init__(self, f: Callable[[Pmt], Optional[Pmt]]):
        super().__init__()
        self.f = f
        self.add_message_output("out")

    @message_handler(name="in")
    async def in_handler(self, io, mio, meta, p: Pmt) -> Pmt:
        if p.is_finished():
            io.finished = True
            return Pmt.ok()
        r = self.f(p)
        if r is not None:
            mio.post("out", r if isinstance(r, Pmt) else Pmt.from_py(r))
        return Pmt.ok()


class MessageBurst(Kernel):
    """Emit a burst of n copies of a message, then finish (`message_burst.rs`)."""

    def __init__(self, message: Pmt, n: int):
        super().__init__()
        self.message = message if isinstance(message, Pmt) else Pmt.from_py(message)
        self.n = int(n)
        self.add_message_output("out")

    async def work(self, io, mio, meta):
        for i in range(self.n):
            # backpressured: a large burst parks here instead of growing the
            # consumer's inbox without bound
            await mio.post_async("out", self.message)
            if (i & 0xFFF) == 0xFFF:
                # the direct-dispatch path never suspends on its own; yield
                # periodically so ctrl-port/supervisor traffic stays live
                # during a long burst
                await asyncio.sleep(0)
        io.finished = True


class MessageSink(Kernel):
    """Collect received messages (`message_sink.rs`)."""

    def __init__(self):
        super().__init__()
        self.received: List[Pmt] = []

    @message_handler(name="in")
    def in_handler(self, io, mio, meta, p: Pmt) -> Pmt:
        # sync handler: stays on the direct-dispatch fast path end to end
        if p.is_finished():
            io.finished = True
            return Pmt.ok()
        self.received.append(p)
        return Pmt.ok()


class MessagePipe(Kernel):
    """Forward messages into an asyncio queue for external consumption (`message_pipe.rs`)."""

    def __init__(self, queue: Optional[asyncio.Queue] = None):
        super().__init__()
        self.queue = queue or asyncio.Queue()

    @message_handler(name="in")
    async def in_handler(self, io, mio, meta, p: Pmt) -> Pmt:
        if p.is_finished():
            io.finished = True
            return Pmt.ok()
        await self.queue.put(p)
        return Pmt.ok()


class MessageSource(Kernel):
    """Emit a message periodically (`message_source.rs:120`): every ``interval`` seconds,
    optionally a limited count."""

    def __init__(self, message: Pmt, interval: float, count: Optional[int] = None):
        super().__init__()
        self.message = message if isinstance(message, Pmt) else Pmt.from_py(message)
        self.interval = float(interval)
        self.remaining = count
        self.add_message_output("out")

    async def work(self, io, mio, meta):
        if self.remaining is not None:
            if self.remaining <= 0:
                io.finished = True
                return
            self.remaining -= 1
        mio.post("out", self.message)
        io.block_on(asyncio.sleep(self.interval))
