"""Stream I/O blocks: file, TCP, UDP, in-process channels.

Reference: ``src/blocks/{file_source,file_sink,tcp_source,tcp_sink,udp_source,blob_to_udp,
channel_source,channel_sink}.rs``. Network blocks use asyncio transports directly — the
runtime is an asyncio actor system, so the reference's async-std sockets map 1:1.
"""

from __future__ import annotations

import asyncio
from typing import Optional

import numpy as np

from ..log import logger
from ..runtime.kernel import Kernel, message_handler
from ..types import Pmt

__all__ = ["FileSource", "FileSink", "TcpSource", "TcpSink", "UdpSource", "BlobToUdp",
           "ChannelSource", "ChannelSink"]

log = logger("blocks.io")


class FileSource(Kernel):
    """Stream items from a file (`file_source.rs`), optional repeat."""

    def __init__(self, path: str, dtype, repeat: bool = False, chunk_items: int = 1 << 16):
        super().__init__()
        self.path = path
        self.repeat = repeat
        self.chunk = chunk_items
        self._f = None
        self.output = self.add_stream_output("out", dtype)

    async def init(self, mio, meta):
        self._f = open(self.path, "rb")

    async def deinit(self, mio, meta):
        if self._f:
            self._f.close()

    async def work(self, io, mio, meta):
        out = self.output.slice()
        n = len(out)
        if n == 0:
            return
        itemsize = self.output.dtype.itemsize
        data = self._f.read(min(n, self.chunk) * itemsize)
        if not data:
            if self.repeat:
                self._f.seek(0)
                io.call_again = True
                return
            io.finished = True
            return
        k = len(data) // itemsize
        out[:k] = np.frombuffer(data[:k * itemsize], dtype=self.output.dtype)
        self.output.produce(k)
        io.call_again = True


class FileSink(Kernel):
    """Write stream items to a file (`file_sink.rs`)."""

    def __init__(self, path: str, dtype):
        super().__init__()
        self.path = path
        self._f = None
        self.input = self.add_stream_input("in", dtype)
        self.n_written = 0

    async def init(self, mio, meta):
        self._f = open(self.path, "wb")

    async def deinit(self, mio, meta):
        if self._f:
            self._f.flush()
            self._f.close()

    async def work(self, io, mio, meta):
        inp = self.input.slice()
        if len(inp):
            self._f.write(inp.tobytes())
            self.n_written += len(inp)
            self.input.consume(len(inp))
        if self.input.finished():
            io.finished = True


class TcpSource(Kernel):
    """Read a byte/item stream from a TCP connection (`tcp_source.rs`). Connects as a
    client, or accepts one connection when ``listen=True``."""

    def __init__(self, host: str, port: int, dtype=np.uint8, listen: bool = False):
        super().__init__()
        self.host, self.port, self.listen = host, port, listen
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer = None
        self._server = None
        self._tail = b""
        self.output = self.add_stream_output("out", dtype)

    async def init(self, mio, meta):
        # bind in init, but accept lazily in work: blocking the init barrier on a peer
        # that connects only after launch would deadlock the whole flowgraph
        if self.listen:
            self._accept_fut = asyncio.get_running_loop().create_future()

            async def on_conn(r, w):
                if not self._accept_fut.done():
                    self._accept_fut.set_result((r, w))

            self._server = await asyncio.start_server(on_conn, self.host, self.port)
        else:
            self._reader, self._writer = await asyncio.open_connection(self.host, self.port)

    async def deinit(self, mio, meta):
        if self._writer:
            self._writer.close()
        if self._server:
            self._server.close()

    async def work(self, io, mio, meta):
        if self._reader is None:
            self._reader, self._writer = await self._accept_fut
        out = self.output.slice()
        if len(out) == 0:
            return
        itemsize = self.output.dtype.itemsize
        data = await self._reader.read(len(out) * itemsize - len(self._tail))
        if not data and self._reader.at_eof():
            io.finished = True
            return
        buf = self._tail + data
        k = len(buf) // itemsize
        if k:
            out[:k] = np.frombuffer(buf[:k * itemsize], dtype=self.output.dtype)
            self.output.produce(k)
        self._tail = buf[k * itemsize:]
        io.call_again = True


class TcpSink(Kernel):
    """Write the stream to a TCP connection (`tcp_sink.rs`)."""

    def __init__(self, host: str, port: int, dtype=np.uint8, listen: bool = False):
        super().__init__()
        self.host, self.port, self.listen = host, port, listen
        self._writer: Optional[asyncio.StreamWriter] = None
        self._server = None
        self.input = self.add_stream_input("in", dtype)

    async def init(self, mio, meta):
        if self.listen:
            self._accept_fut = asyncio.get_running_loop().create_future()

            async def on_conn(r, w):
                if not self._accept_fut.done():
                    self._accept_fut.set_result((r, w))

            self._server = await asyncio.start_server(on_conn, self.host, self.port)
        else:
            _, self._writer = await asyncio.open_connection(self.host, self.port)

    async def deinit(self, mio, meta):
        if self._writer:
            try:
                await self._writer.drain()
                self._writer.close()
            except Exception:
                pass
        if self._server:
            self._server.close()

    async def work(self, io, mio, meta):
        if self._writer is None:
            _, self._writer = await self._accept_fut
        inp = self.input.slice()
        if len(inp):
            self._writer.write(inp.tobytes())
            await self._writer.drain()
            self.input.consume(len(inp))
        if self.input.finished():
            io.finished = True


class _UdpProto(asyncio.DatagramProtocol):
    def __init__(self, queue: asyncio.Queue, event: asyncio.Event):
        self.queue = queue
        self.event = event

    def datagram_received(self, data, addr):
        try:
            self.queue.put_nowait(data)
            self.event.set()
        except asyncio.QueueFull:
            pass  # drop on overrun, like a real radio


class UdpSource(Kernel):
    """Receive UDP datagrams as a sample stream (`udp_source.rs`)."""

    def __init__(self, bind: str, port: int, dtype=np.uint8, queue_size: int = 256):
        super().__init__()
        self.bind, self.port = bind, port
        self._queue: asyncio.Queue = None
        self._event: asyncio.Event = None
        self._transport = None
        self._tail = b""
        self._qsize = queue_size
        self.output = self.add_stream_output("out", dtype)

    async def init(self, mio, meta):
        self._queue = asyncio.Queue(self._qsize)
        self._event = asyncio.Event()
        loop = asyncio.get_running_loop()
        self._transport, _ = await loop.create_datagram_endpoint(
            lambda: _UdpProto(self._queue, self._event),
            local_addr=(self.bind, self.port))

    async def deinit(self, mio, meta):
        if self._transport:
            self._transport.close()

    async def work(self, io, mio, meta):
        # never await the socket inside work (it would starve Terminate handling);
        # drain what's there and park on the arrival event via block_on
        out = self.output.slice()
        if len(out) == 0:
            return
        self._event.clear()
        produced = 0
        buf = self._tail
        while not self._queue.empty():
            buf += self._queue.get_nowait()
        itemsize = self.output.dtype.itemsize
        k = min(len(buf) // itemsize, len(out))
        if k:
            out[:k] = np.frombuffer(buf[:k * itemsize], dtype=self.output.dtype)
            self.output.produce(k)
        self._tail = buf[k * itemsize:]
        if not self._queue.empty():
            io.call_again = True
        else:
            io.block_on(self._event.wait())


class BlobToUdp(Kernel):
    """Send each Blob message as a UDP datagram (`blob_to_udp.rs`)."""

    def __init__(self, host: str, port: int):
        super().__init__()
        self.host, self.port = host, port
        self._transport = None

    async def init(self, mio, meta):
        loop = asyncio.get_running_loop()
        self._transport, _ = await loop.create_datagram_endpoint(
            lambda: asyncio.DatagramProtocol(), remote_addr=(self.host, self.port))

    async def deinit(self, mio, meta):
        if self._transport:
            self._transport.close()

    @message_handler(name="in")
    async def in_handler(self, io, mio, meta, p: Pmt) -> Pmt:
        if p.is_finished():
            io.finished = True
            return Pmt.ok()
        try:
            self._transport.sendto(p.to_blob())
        except Exception:
            return Pmt.invalid_value()
        return Pmt.ok()


class ChannelSource(Kernel):
    """Feed samples pushed from outside (an asyncio queue) into the flowgraph
    (`channel_source.rs`). Push ``None`` for EOS."""

    def __init__(self, dtype, queue: Optional[asyncio.Queue] = None):
        super().__init__()
        self.queue = queue or asyncio.Queue()
        self._carry: Optional[np.ndarray] = None
        self.output = self.add_stream_output("out", dtype)

    async def work(self, io, mio, meta):
        out = self.output.slice()
        if len(out) == 0:
            return
        if self._carry is None:
            item = await self.queue.get()
            if item is None:
                io.finished = True
                return
            self._carry = np.asarray(item, dtype=self.output.dtype)
        k = min(len(out), len(self._carry))
        out[:k] = self._carry[:k]
        self.output.produce(k)
        self._carry = self._carry[k:] if k < len(self._carry) else None
        io.call_again = True


class ChannelSink(Kernel):
    """Push received chunks into an asyncio queue (`channel_sink.rs`)."""

    def __init__(self, dtype, queue: Optional[asyncio.Queue] = None):
        super().__init__()
        self.queue = queue or asyncio.Queue()
        self.input = self.add_stream_input("in", dtype)

    async def work(self, io, mio, meta):
        inp = self.input.slice()
        if len(inp):
            await self.queue.put(inp.copy())
            self.input.consume(len(inp))
        if self.input.finished():
            await self.queue.put(None)
            io.finished = True
