"""Polyphase filterbank blocks: channelizer, synthesizer, arbitrary resampler.

Reference: ``src/blocks/pfb/{channelizer,synthesizer,arb_resampler}.rs`` (derived from
liquid-dsp there). Re-designed vectorized: the channelizer is the textbook critically-sampled
polyphase analysis bank — commutated branch filters + IFFT across branches — expressed as
batched ``lfilter`` + batched FFT, which is also exactly the form that fuses into a single
XLA program on the TPU path.

Channel ``c`` carries the band centered at ``c/N`` of the input sample rate (FFT bin order);
each output runs at ``fs/N`` (critically sampled).

This module is the HOST actor form. The fused device-plane form is
``ops/stages.channelizer_stage`` — ``impl="matmul"`` (branch-MAC einsum +
batched IFFT) or ``impl="pallas"`` (the fused ``pallas_pfb`` kernel: both
passes in one kernel, the inter-pass branch bank never touching HBM; the
``"auto"`` default picks it on the TPU backend) — see docs/tpu_notes.md
"Interior precision".
"""

from __future__ import annotations

from typing import Optional

import numpy as np
from scipy.signal import lfilter

from ..dsp import firdes
from ..runtime.kernel import Kernel

__all__ = ["PfbChannelizer", "PfbSynthesizer", "PfbArbResampler", "pfb_default_taps"]


def pfb_default_taps(n_channels: int, taps_per_branch: int = 12, atten_db: float = 70.0):
    """Prototype lowpass for an N-channel bank (liquid's kaiser default)."""
    n = n_channels * taps_per_branch
    from ..dsp.windows import kaiser
    from ..dsp.firdes import kaiser_order
    _, beta = kaiser_order(atten_db, 0.1 / n_channels)
    return firdes.lowpass(0.5 / n_channels, n, kaiser(n, beta)) * n_channels


class PfbChannelizer(Kernel):
    """1 → N channel analysis bank (`pfb/channelizer.rs:1-140`), critically sampled."""

    def __init__(self, n_channels: int, taps=None):
        super().__init__()
        assert n_channels >= 2
        self.n = int(n_channels)
        taps = np.asarray(taps if taps is not None else pfb_default_taps(self.n),
                          dtype=np.float32)
        # branch p holds taps[p::N]; pad so all branches have equal length
        k = -(-len(taps) // self.n)
        padded = np.zeros(k * self.n, dtype=np.float64)
        padded[:len(taps)] = taps
        self.branch_taps = padded.reshape(k, self.n).T      # [N, K]
        self._zi = np.zeros((self.n, k - 1), dtype=np.complex128) if k > 1 else None
        self.input = self.add_stream_input("in", np.complex64, min_items=self.n)
        self.outputs = [self.add_stream_output(f"out{i}", np.complex64)
                        for i in range(self.n)]

    async def work(self, io, mio, meta):
        inp = self.input.slice()
        space = min(o.space() for o in self.outputs)
        t = min(len(inp) // self.n, space)    # output samples per channel
        if t > 0:
            blocks = inp[:t * self.n].reshape(t, self.n)
            u = blocks[:, ::-1].T                       # [N, t] commutator (reversed)
            if self._zi is not None:
                v = np.empty((self.n, t), dtype=np.complex128)
                for p in range(self.n):                 # batched short filters
                    v[p], self._zi[p] = lfilter(self.branch_taps[p], 1.0, u[p],
                                                zi=self._zi[p])
            else:
                v = self.branch_taps[:, :1] * u
            y = np.fft.ifft(v, axis=0) * self.n          # [N, t]
            for c, o in enumerate(self.outputs):
                o.slice()[:t] = y[c].astype(np.complex64)
                o.produce(t)
            self.input.consume(t * self.n)
        if self.input.finished() and len(inp) - t * self.n < self.n:
            io.finished = True
        elif t > 0:
            io.call_again = True


class PfbSynthesizer(Kernel):
    """N → 1 synthesis bank (`pfb/synthesizer.rs`): FFT across channels + commutated
    branch filters, critically sampled."""

    def __init__(self, n_channels: int, taps=None):
        super().__init__()
        self.n = int(n_channels)
        taps = np.asarray(taps if taps is not None else pfb_default_taps(self.n),
                          dtype=np.float32)
        k = -(-len(taps) // self.n)
        padded = np.zeros(k * self.n, dtype=np.float64)
        padded[:len(taps)] = taps
        self.branch_taps = padded.reshape(k, self.n).T
        self._zi = np.zeros((self.n, k - 1), dtype=np.complex128) if k > 1 else None
        self.inputs = [self.add_stream_input(f"in{i}", np.complex64)
                       for i in range(self.n)]
        self.output = self.add_stream_output("out", np.complex64, min_items=self.n)

    async def work(self, io, mio, meta):
        t = min(min(p.available() for p in self.inputs),
                self.output.space() // self.n)
        if t > 0:
            x = np.stack([p.slice()[:t] for p in self.inputs])   # [N, t]
            v = np.fft.fft(x, axis=0)                            # [N, t]
            if self._zi is not None:
                w = np.empty((self.n, t), dtype=np.complex128)
                for p in range(self.n):
                    w[p], self._zi[p] = lfilter(self.branch_taps[p], 1.0, v[p],
                                                zi=self._zi[p])
            else:
                w = self.branch_taps[:, :1] * v
            out = self.output.slice()
            out[:t * self.n] = w.T.reshape(-1).astype(np.complex64)
            for p in self.inputs:
                p.consume(t)
            self.output.produce(t * self.n)
        if any(p.finished() and p.available() == 0 for p in self.inputs):
            io.finished = True
        elif t > 0:
            io.call_again = True


class PfbArbResampler(Kernel):
    """Arbitrary-rate polyphase resampler (`pfb/arb_resampler.rs`): an M-branch bank
    stepped fractionally, with linear interpolation between adjacent branches."""

    def __init__(self, rate: float, taps=None, n_filters: int = 32, dtype=np.complex64):
        super().__init__()
        assert rate > 0
        self.rate = float(rate)
        self.M = int(n_filters)
        taps = np.asarray(taps if taps is not None else
                          firdes.lowpass(min(0.5, 0.5 * min(1.0, rate)) / self.M * 0.8,
                                         8 * self.M) * self.M,
                          dtype=np.float64)
        k = -(-len(taps) // self.M)
        padded = np.zeros(k * self.M, dtype=taps.dtype)
        padded[:len(taps)] = taps
        self.poly = padded.reshape(k, self.M).T       # [M, K]
        self.K = k
        self._hist: Optional[np.ndarray] = None
        self._m = 0                                    # absolute output index
        self._consumed = 0
        self.input = self.add_stream_input("in", dtype, min_items=self.K)
        self.output = self.add_stream_output("out", dtype)

    async def work(self, io, mio, meta):
        inp = self.input.slice()
        out = self.output.slice()
        # bound inputs so outputs fit: n_out ≈ n_in * rate
        n_in = min(len(inp), max(0, int(len(out) / self.rate) - 2))
        if n_in > 0:
            y = self._process(inp[:n_in])
            assert len(y) <= len(out)
            out[:len(y)] = y
            self.input.consume(n_in)
            self.output.produce(len(y))
        if self.input.finished() and n_in == len(inp):
            io.finished = True
        elif n_in > 0 and n_in < len(inp):
            io.call_again = True

    def _process(self, x: np.ndarray) -> np.ndarray:
        if self._hist is None:
            self._hist = np.zeros(self.K - 1, dtype=x.dtype)
            self._consumed = -(self.K - 1)
        buf = np.concatenate([self._hist, x])
        total = self._consumed + len(buf)
        # outputs m with floor(m/rate) <= total - 2 (need n_m+ for interp)
        m_hi = int(np.floor((total - 1) * self.rate))
        ms = np.arange(self._m, max(self._m, m_hi))
        if len(ms):
            pos = ms / self.rate
            n_m = np.floor(pos).astype(np.int64)
            frac = (pos - n_m) * self.M
            p_m = np.floor(frac).astype(np.int64)
            alpha = (frac - p_m)[:, None]
            idx = (n_m - self._consumed)[:, None] - np.arange(self.K)[None, :]
            windows = np.where(idx >= 0, buf[np.clip(idx, 0, None)], 0)
            y0 = np.einsum("mk,mk->m", windows, self.poly[p_m])
            p1 = (p_m + 1) % self.M
            shift = (p_m + 1) // self.M                # branch wrap advances one sample
            idx1 = (n_m + shift - self._consumed)[:, None] - np.arange(self.K)[None, :]
            in_range = (idx1 >= 0) & (idx1 < len(buf))
            w1 = np.where(in_range, buf[np.clip(idx1, 0, len(buf) - 1)], 0)
            y1 = np.einsum("mk,mk->m", w1, self.poly[p1])
            y = ((1 - alpha[:, 0]) * y0 + alpha[:, 0] * y1).astype(x.dtype, copy=False)
            self._m = ms[-1] + 1
        else:
            y = np.zeros(0, dtype=x.dtype)
        keep = min(self.K - 1 + 1, len(buf))
        self._hist = buf[len(buf) - keep:]
        self._consumed = total - keep
        return y
