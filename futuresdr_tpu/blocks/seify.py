"""Hardware source/sink blocks over the HAL driver registry.

Reference: ``src/blocks/seify/{source,sink,builder,config}.rs``: ``#[blocking]`` blocks with
``freq``/``gain``/``sample_rate``/``cmd`` message ports (`seify/source.rs:53-56`), built via a
fluent ``Builder``. Multi-channel RX maps to multiple output ports.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..hw import Device
from ..log import logger
from ..runtime.kernel import Kernel, message_handler
from ..types import Pmt

__all__ = ["SeifySource", "SeifySink", "SeifyBuilder"]

log = logger("blocks.seify")


def _apply_cmd(driver, p: Pmt, channel: int = 0):
    """Apply a config map: {"freq": .., "gain": .., "sample_rate": ..} (seify Config)."""
    m = p.to_map()
    for k, v in m.items():
        val = v.to_float()
        if k in ("freq", "frequency"):
            driver.set_frequency(val, channel)
        elif k == "gain":
            driver.set_gain(val, channel)
        elif k in ("sample_rate", "rate"):
            driver.set_sample_rate(val, channel)
        else:
            log.warning("unknown cmd key %r", k)


class SeifySource(Kernel):
    """RX streamer (`seify/source.rs`): blocking reads on a dedicated thread."""

    BLOCKING = True

    def __init__(self, args: str = "driver=dummy", n_channels: int = 1,
                 frequency: Optional[float] = None, gain: Optional[float] = None,
                 sample_rate: Optional[float] = None):
        super().__init__()
        self.device = Device(args)
        d = self.device.driver
        if sample_rate is not None:
            d.set_sample_rate(sample_rate)
        if frequency is not None:
            d.set_frequency(frequency)
        if gain is not None:
            d.set_gain(gain)
        self.n_channels = n_channels
        self.outputs = [self.add_stream_output(f"out{i}" if n_channels > 1 else "out",
                                               np.complex64)
                        for i in range(n_channels)]

    @message_handler(name="freq")
    async def freq_handler(self, io, mio, meta, p: Pmt) -> Pmt:
        try:
            self.device.driver.set_frequency(p.to_float())
        except Exception:
            return Pmt.invalid_value()
        return Pmt.ok()

    @message_handler(name="gain")
    async def gain_handler(self, io, mio, meta, p: Pmt) -> Pmt:
        try:
            self.device.driver.set_gain(p.to_float())
        except Exception:
            return Pmt.invalid_value()
        return Pmt.ok()

    @message_handler(name="sample_rate")
    async def sample_rate_handler(self, io, mio, meta, p: Pmt) -> Pmt:
        try:
            self.device.driver.set_sample_rate(p.to_float())
        except Exception:
            return Pmt.invalid_value()
        return Pmt.ok()

    @message_handler(name="cmd")
    async def cmd_handler(self, io, mio, meta, p: Pmt) -> Pmt:
        try:
            _apply_cmd(self.device.driver, p)
        except Exception:
            return Pmt.invalid_value()
        return Pmt.ok()

    async def init(self, mio, meta):
        self.device.driver.activate_rx(tuple(range(self.n_channels)))

    async def deinit(self, mio, meta):
        self.device.driver.deactivate()

    async def work(self, io, mio, meta):
        out = self.outputs[0].slice()
        n = min((len(o.slice()) for o in self.outputs), default=0)
        if n == 0:
            return
        data = self.device.driver.read(n)   # blocking; we're on a dedicated thread
        if data is None:                    # driver EOS (e.g. rtl_tcp server gone)
            io.finished = True
            return
        k = len(data)
        if k:
            if self.n_channels == 1:
                out[:k] = data
                self.outputs[0].produce(k)
            else:
                for o in self.outputs:
                    o.slice()[:k] = data
                    o.produce(k)
        io.call_again = True


class SeifySink(Kernel):
    """TX streamer (`seify/sink.rs`)."""

    BLOCKING = True

    def __init__(self, args: str = "driver=dummy",
                 frequency: Optional[float] = None, gain: Optional[float] = None,
                 sample_rate: Optional[float] = None):
        super().__init__()
        self.device = Device(args)
        d = self.device.driver
        if sample_rate is not None:
            d.set_sample_rate(sample_rate)
        if frequency is not None:
            d.set_frequency(frequency)
        if gain is not None:
            d.set_gain(gain)
        self.input = self.add_stream_input("in", np.complex64)

    @message_handler(name="freq")
    async def freq_handler(self, io, mio, meta, p: Pmt) -> Pmt:
        try:
            self.device.driver.set_frequency(p.to_float())
        except Exception:
            return Pmt.invalid_value()
        return Pmt.ok()

    @message_handler(name="cmd")
    async def cmd_handler(self, io, mio, meta, p: Pmt) -> Pmt:
        try:
            _apply_cmd(self.device.driver, p)
        except Exception:
            return Pmt.invalid_value()
        return Pmt.ok()

    async def init(self, mio, meta):
        self.device.driver.activate_tx()

    async def deinit(self, mio, meta):
        self.device.driver.deactivate()

    async def work(self, io, mio, meta):
        inp = self.input.slice()
        if len(inp):
            written = self.device.driver.write(inp)
            self.input.consume(written)
        if self.input.finished() and self.input.available() == 0:
            io.finished = True


class SeifyBuilder:
    """Fluent builder (`seify/builder.rs`)."""

    def __init__(self, args: str = "driver=dummy"):
        self._args = args
        self._freq = None
        self._gain = None
        self._rate = None
        self._channels = 1

    def args(self, a: str) -> "SeifyBuilder":
        self._args = a
        return self

    def frequency(self, f: float) -> "SeifyBuilder":
        self._freq = f
        return self

    def gain(self, g: float) -> "SeifyBuilder":
        self._gain = g
        return self

    def sample_rate(self, r: float) -> "SeifyBuilder":
        self._rate = r
        return self

    def channels(self, n: int) -> "SeifyBuilder":
        self._channels = n
        return self

    def build_source(self) -> SeifySource:
        return SeifySource(self._args, self._channels, self._freq, self._gain, self._rate)

    def build_sink(self) -> SeifySink:
        return SeifySink(self._args, self._freq, self._gain, self._rate)
