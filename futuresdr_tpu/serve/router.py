"""Pressure-routed admissions across a fleet (docs/observability.md "The
fleet plane", docs/serving.md "Lifecycle").

:class:`AdmissionRouter` closes the fleet observability loop: given a
:class:`~futuresdr_tpu.telemetry.fleet.FleetView`, route each REST
admission (``POST /api/fleet/serve/{app}/session/`` on any control port)
to the least-pressure READY host and fail over on 503/overload honoring
``Retry-After``. The routing score is **lexicographic**, worst signal
first::

    (shed-ladder level, credit pressure, e2e p99 seconds)

— a host one shed rung up loses to any host a rung down no matter its
pressure; among same-rung hosts the lower ``TenantCreditController``
pressure wins; p99 breaks pressure ties. Switching is **hysteretic**: the
previous pick per app keeps the traffic unless a candidate beats it by
more than ``fleet_hysteresis`` on the deciding component (shed-rung
differences always switch — rungs are already hysteretic at the source,
serve/overload.py), so near-tied hosts don't flap the router at poll
cadence.

Every decision journals under the ``fleet`` category with the scores
considered — ``perf/fleet_smoke.py`` asserts the journal shows routing
shifting to the survivors after a host dies. The module is jax-free and
HTTP-injectable (``post=``) so the scoring and failover logic unit-test
without sockets.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import Callable, Dict, List, Optional, Tuple

from ..log import logger
from ..telemetry import journal as _journal
from ..telemetry import prom

__all__ = ["AdmissionRouter", "NoReadyHost", "score"]

log = logger("serve.router")

ROUTES = prom.counter(
    "fsdr_fleet_route_total",
    "fleet admissions routed by app, target host and outcome",
    ("app", "host", "outcome"))
ROUTE_SECONDS = prom.histogram(
    "fsdr_fleet_route_seconds",
    "end-to-end fleet admission routing latency (pick + remote admit, "
    "failover hops included)", ("app",))


class NoReadyHost(RuntimeError):
    """No fleet host could take the admission (none ready, or every ready
    host 503'd). ``retry_after`` carries the smallest backoff any refusing
    host asked for — the front door's own 503 honors it upward."""

    def __init__(self, msg: str, retry_after: int = 1):
        super().__init__(msg)
        self.retry_after = max(1, int(retry_after))


def score(summary: dict, app: Optional[str] = None
          ) -> Optional[Tuple[float, float, float]]:
    """The routing score of one host summary — ``None`` when the host (or
    the named app on it) is not ready, which removes it from the candidate
    set entirely. Lower is better, compared lexicographically."""
    if not summary or not summary.get("ready"):
        return None
    apps = summary.get("apps") or {}
    if app is not None and app in apps:
        a = apps[app]
        if not a.get("ready"):
            return None
        rung = float(a.get("shed_level", 0))
        pressure = float(a.get("pressure", 0.0))
    else:
        rung = float(summary.get("shed_level", 0))
        pressure = float(summary.get("pressure", 0.0))
    p99 = (summary.get("e2e") or {}).get("p99_s") or 0.0
    return (rung, pressure, float(p99))


def _better(cand: Tuple[float, float, float],
            cur: Tuple[float, float, float], h: float) -> bool:
    """Hysteretic "worth switching": the candidate must beat the CURRENT
    pick by more than the band ``h`` on the component that decides —
    except the shed rung, where any strict improvement switches (the
    ladder is already hysteretic at the source)."""
    if cand[0] != cur[0]:
        return cand[0] < cur[0]
    if abs(cand[1] - cur[1]) > h:
        return cand[1] < cur[1]
    # pressure within the band: p99 decides, same relative band
    if cur[2] > 0 and abs(cand[2] - cur[2]) > h * cur[2]:
        return cand[2] < cur[2]
    return False


def _http_post(url: str, body: dict, timeout: float
               ) -> Tuple[int, Dict[str, str], bytes]:
    data = json.dumps(body).encode()
    req = urllib.request.Request(
        url, data=data, headers={"Content-Type": "application/json"},
        method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, dict(r.headers), r.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers or {}), e.read()


class AdmissionRouter:
    """Route admissions over a FleetView's ready hosts.

    ``post`` is injectable (``post(url, body, timeout) -> (status,
    headers, body_bytes)``); ``hysteresis`` defaults to the
    ``fleet_hysteresis`` config knob.
    """

    def __init__(self, view, hysteresis: Optional[float] = None,
                 timeout: float = 5.0,
                 post: Optional[Callable] = None):
        if hysteresis is None:
            from ..config import config
            hysteresis = float(config().get("fleet_hysteresis", 0.1))
        self.view = view
        self.hysteresis = float(hysteresis)
        self.timeout = float(timeout)
        self._post = post or _http_post
        self._last: Dict[str, str] = {}    # app -> host of the previous pick

    # -- picking -------------------------------------------------------------
    def candidates(self, app: str) -> Dict[str, Tuple[float, float, float]]:
        """Ready hosts and their scores for ``app`` (down/stale/unready
        hosts are filtered out by :func:`score` returning None)."""
        out: Dict[str, Tuple[float, float, float]] = {}
        for peer, h in self.view.ready_hosts().items():
            s = score(h.get("summary") or {}, app)
            if s is not None:
                out[peer] = s
        return out

    def pick(self, app: str, exclude: Tuple[str, ...] = ()
             ) -> Tuple[str, Dict[str, Tuple[float, float, float]]]:
        """The host the next admission for ``app`` should land on, plus
        every score considered (journaled with the decision). Sticky under
        hysteresis: the previous pick keeps the traffic unless a candidate
        beats it outside the band. Raises :class:`NoReadyHost` when the
        candidate set is empty."""
        cands = {p: s for p, s in self.candidates(app).items()
                 if p not in exclude}
        if not cands:
            raise NoReadyHost(f"{app}: no ready fleet host "
                              f"(excluded: {list(exclude) or None})")
        cur = self._last.get(app)
        if cur not in cands:
            # no sticky pick: plain lexicographic best (address breaks
            # exact ties deterministically)
            cur = min(sorted(cands), key=lambda p: cands[p])
        for peer in sorted(cands):
            if peer != cur and _better(cands[peer], cands[cur],
                                       self.hysteresis):
                cur = peer
        self._last[app] = cur
        return cur, cands

    # -- admission -----------------------------------------------------------
    def admit(self, app: str, tenant: str = "default",
              sid: Optional[str] = None, body: Optional[dict] = None
              ) -> dict:
        """Route one admission: pick, POST to the target's own
        ``/api/serve/{app}/session/``, fail over to the next-best host on
        503/overload (honoring the refusing host's ``Retry-After`` as the
        floor of the error we ultimately raise). Returns the admitting
        host's 201 body plus routing metadata; raises
        :class:`NoReadyHost` when every candidate refused."""
        t0 = time.monotonic()
        payload = dict(body or {})
        payload.setdefault("tenant", tenant)
        if sid is not None:
            payload.setdefault("sid", sid)
        tried: List[str] = []
        retry_after = 1
        while True:
            try:
                host, scores = self.pick(app, exclude=tuple(tried))
            except NoReadyHost as e:
                ROUTES.inc(app=app, host="-", outcome="no-host")
                _journal.emit("fleet", "route-failed", app=app,
                              tenant=tenant, tried=tried,
                              retry_after=retry_after)
                e.retry_after = max(e.retry_after, retry_after)
                raise
            try:
                status, headers, raw = self._post(
                    f"http://{host}/api/serve/{app}/session/", payload,
                    self.timeout)
            except Exception as err:       # noqa: BLE001 — a dead host mid-
                status, headers, raw = 599, {}, repr(err).encode()  # admit
            if status == 201:              # is a failover, not an error
                out = json.loads(raw)
                dur = time.monotonic() - t0
                ROUTES.inc(app=app, host=host, outcome="ok")
                ROUTE_SECONDS.observe(dur, app=app)
                _journal.emit("fleet", "route", app=app, host=host,
                              tenant=tenant, sid=out.get("sid"),
                              scores={p: list(s) for p, s
                                      in sorted(scores.items())},
                              failovers=len(tried),
                              dur_ms=round(dur * 1e3, 3))
                return {"host": host, "session": out,
                        "failovers": len(tried)}
            tried.append(host)
            self._last.pop(app, None)      # the sticky pick refused: re-pick
            try:
                retry_after = max(retry_after,
                                  int(headers.get("Retry-After", 1)))
            except (TypeError, ValueError):
                pass
            ROUTES.inc(app=app, host=host, outcome=f"http-{status}")
            _journal.emit("fleet", "route-failover", app=app, host=host,
                          tenant=tenant, status=status,
                          retry_after=retry_after)
