"""Slot-table session bookkeeping for the serving front-end.

A :class:`SlotTable` is the RAGGED-admission surface of docs/serving.md: a
fixed-capacity slot axis (the leading vmap axis of the compiled serving
program) whose lanes are claimed and released by sessions at runtime.
Sessions join, leave, stall and come back WITHOUT recompiling anything —
occupancy changes only flip entries of the active-lanes mask the engine
threads into every dispatch, and a lane's per-session carry slice is
swapped by functional index update, never by reshaping the batch.

The table is pure host bookkeeping (which session owns which lane, who is
admissible, which lanes are free); the device-side carry pages live in
:class:`~futuresdr_tpu.serve.engine.ServeEngine`, which owns the page pool
the slots index into.

Paged carries: alongside lane ownership the table maintains the
session→page binding of docs/serving.md "Paged session carries". A PAGE is
one lane-sized row of the engine's device-resident carry pool; the mapping
``page_of_lane`` is threaded into every dispatch as a program input, so the
compiled program gathers each lane's carry page, steps it, and scatters it
back — joins/leaves/evicts are edits to this host-side map, never a
restack of device memory. The map is kept a PERMUTATION of ``[0, capacity)``
at all times (admission SWAPS page entries between the claimed lane and
wherever its page was parked): the in-program scatter therefore never sees
duplicate indices, whose resolution order XLA does not define — the
permutation invariant is what makes the paged step deterministic.
"""

from __future__ import annotations

import itertools
import time
from collections import deque
from typing import Deque, Dict, List, Optional

__all__ = ["Session", "SlotTable", "ServeFull", "ServeDraining",
           "ServeOverload"]

#: session lifecycle states (docs/serving.md "Session lifecycle"):
#:   active   — owns a slot, dispatches whenever it has a pending frame
#:   evicted  — carry snapshotted to host, slot released; re-admissible
#:   retired  — faulted; its slot was masked off and released, outputs stop
#:   closed   — explicitly ended by the client; terminal
STATES = ("active", "evicted", "retired", "closed")

_sid_counter = itertools.count(1)


class ServeFull(RuntimeError):
    """Admission refused: every slot bucket is at capacity."""


class ServeDraining(ServeFull):
    """Admission refused: the engine is draining (graceful shutdown —
    rolling-restart lifecycle, docs/robustness.md "Serving-plane recovery").
    The REST plane maps it to 503 + ``Retry-After`` like :class:`ServeFull`;
    an orchestrator should route new sessions to another replica."""


class ServeOverload(ServeFull):
    """Admission refused by the overload-shedding ladder (rung 1): the
    engine is over its queue-pressure watermark or missing its latency SLO,
    so NEW admissions shed first while resident sessions keep their lanes
    bit-exact (serve/overload.py, billed on
    ``fsdr_serve_shed_total{reason="admission"}``)."""


class Session:
    """One tenant stream multiplexed through the serving program.

    Host-side queues only — ``pending`` holds ``(frame, t_submit_ns)``
    entries awaiting a dispatch lane, ``out`` the decoded per-frame results
    (per-sink tuples for fan-out/DAG pipelines). The device-side state is
    the session's carry LANE inside the engine's stacked carries while
    active, or the ``carry_leaves`` host snapshot while evicted.
    """

    __slots__ = ("sid", "tenant", "state", "slot", "page", "pending", "out",
                 "frames_in", "frames_out", "stall_steps", "created_ns",
                 "carry_leaves", "carry_treedef", "error", "last_latency_s")

    def __init__(self, tenant: str, sid: Optional[str] = None):
        self.sid = str(sid) if sid else f"s{next(_sid_counter)}"
        self.tenant = str(tenant)
        self.state = "active"
        self.slot: Optional[int] = None
        self.page: Optional[int] = None   # carry-pool page while active
        self.pending: Deque[tuple] = deque()
        self.out: Deque = deque()
        self.frames_in = 0
        self.frames_out = 0
        self.stall_steps = 0          # consecutive dispatches with no input
        self.created_ns = time.time_ns()
        self.carry_leaves: Optional[list] = None   # host snapshot (evicted)
        self.carry_treedef = None
        self.error: Optional[str] = None
        self.last_latency_s: Optional[float] = None

    def view(self) -> dict:
        """The per-session metrics/doctor view served by the REST plane."""
        return {
            "sid": self.sid,
            "tenant": self.tenant,
            "state": self.state,
            "slot": self.slot,
            "page": self.page,
            "frames_in": self.frames_in,
            "frames_out": self.frames_out,
            "queued": len(self.pending),
            "undelivered": len(self.out),
            "stall_steps": self.stall_steps,
            "evicted_carry": self.carry_leaves is not None,
            "error": self.error,
            "last_latency_ms": (round(self.last_latency_s * 1e3, 3)
                                if self.last_latency_s is not None else None),
        }

    def __repr__(self):
        return (f"Session({self.sid}, tenant={self.tenant}, "
                f"state={self.state}, slot={self.slot})")


class SlotTable:
    """Lane ownership over a growable slot axis.

    ``capacity`` only ever GROWS (to the next configured bucket — the engine
    compiles one program per resident bucket and restacks the carries); a
    session leaving frees its lane for the next admit, it never shrinks the
    axis. ``slots[i]`` is the owning session or None.
    """

    def __init__(self, capacity: int):
        self.capacity = int(capacity)
        self.slots: List[Optional[Session]] = [None] * self.capacity
        self._free: List[int] = list(range(self.capacity - 1, -1, -1))
        self.sessions: Dict[str, Session] = {}
        # session→page binding (module docstring): page_of_lane is the
        # permutation the engine threads into every dispatch; lane_of_page
        # is its inverse, kept in lockstep so admission can find where a
        # free page is parked in O(1)
        self.page_of_lane: List[int] = list(range(self.capacity))
        self.lane_of_page: List[int] = list(range(self.capacity))
        self._free_pages: List[int] = list(range(self.capacity - 1, -1, -1))

    # -- occupancy ------------------------------------------------------------
    @property
    def active(self) -> int:
        return self.capacity - len(self._free)

    def free_slots(self) -> int:
        return len(self._free)

    def get(self, sid: str) -> Optional[Session]:
        return self.sessions.get(str(sid))

    def occupants(self) -> List[Session]:
        """Sessions holding a lane, in slot order (the dispatch walk)."""
        return [s for s in self.slots if s is not None]

    # -- admission / release ---------------------------------------------------
    def _bind_page(self, slot: int) -> int:
        """Claim the lowest free page for ``slot``, SWAPPING map entries so
        ``page_of_lane`` stays a permutation: the claimed page is free, so
        the lane it is currently parked at is itself free — that lane takes
        over whatever page ``slot`` was parked with. (Release never swaps;
        a freed page stays parked at its lane until re-claimed.)"""
        page = self._free_pages.pop()
        lane2 = self.lane_of_page[page]
        if lane2 != slot:
            page2 = self.page_of_lane[slot]
            self.page_of_lane[slot], self.page_of_lane[lane2] = page, page2
            self.lane_of_page[page], self.lane_of_page[page2] = slot, lane2
        return page

    def admit(self, session: Session) -> int:
        """Claim a free lane for ``session`` (lowest index first — keeps the
        active prefix dense, which is what the autotuned buckets assume)
        and bind it the lowest free carry page. Raises :class:`ServeFull`
        when no lane is free; the ENGINE decides whether to grow to the
        next bucket first."""
        if not self._free:
            raise ServeFull(f"slot table at capacity ({self.capacity})")
        slot = self._free.pop()
        session.slot = slot
        session.page = self._bind_page(slot)
        session.state = "active"
        self.slots[slot] = session
        self.sessions[session.sid] = session
        return slot

    def release_slot(self, session: Session) -> Optional[int]:
        """Give the session's lane and page back (eviction/retire/close).
        The session stays in the registry — ``forget`` drops it entirely."""
        slot = session.slot
        if slot is None:
            return None
        self.slots[slot] = None
        self._free.append(slot)
        self._free.sort(reverse=True)     # lowest-index-first reuse
        session.slot = None
        if session.page is not None:
            self._free_pages.append(session.page)
            self._free_pages.sort(reverse=True)
            session.page = None
        return slot

    def forget(self, session: Session) -> None:
        self.release_slot(session)
        self.sessions.pop(session.sid, None)

    def grow(self, new_capacity: int) -> None:
        new_capacity = int(new_capacity)
        assert new_capacity > self.capacity, (new_capacity, self.capacity)
        extra = range(self.capacity, new_capacity)
        self.slots.extend([None] * (new_capacity - self.capacity))
        self._free = sorted(self._free + list(extra), reverse=True)
        # new pages park at the new lanes (identity tail keeps the
        # permutation invariant); existing bindings are untouched
        self.page_of_lane.extend(extra)
        self.lane_of_page.extend(extra)
        self._free_pages = sorted(self._free_pages + list(extra),
                                  reverse=True)
        self.capacity = new_capacity

    def tenants(self) -> Dict[str, int]:
        """``{tenant: live session count}`` over the registry (closed and
        retired sessions drop out once forgotten)."""
        out: Dict[str, int] = {}
        for s in self.sessions.values():
            out[s.tenant] = out.get(s.tenant, 0) + 1
        return out
