"""REST session plane for the serving front-end.

Extends the control port (``runtime/ctrl_port.py``) with the multi-tenant
session API of docs/serving.md — the routes are merged into every control
port automatically (plus available as ``routes()`` for a bespoke server):

  GET    /api/serve/                         → registered serving apps
  GET    /api/serve/{app}/                   → engine view (slots, buckets,
                                               per-tenant credit/latency)
  POST   /api/serve/{app}/session/           → admit  {"tenant": "...",
                                               "sid": optional}
  GET    /api/serve/{app}/session/{sid}/     → per-session metrics/doctor view
  POST   /api/serve/{app}/session/{sid}/evict/   → evict carry to host
  POST   /api/serve/{app}/session/{sid}/readmit/ → restore it bit-identically
  POST   /api/serve/{app}/session/{sid}/ctrl/    → lane-addressed retune
                                               {"stage": ..., "params": {...}}
  DELETE /api/serve/{app}/session/{sid}/     → leave
  POST   /api/serve/{app}/drain/             → graceful drain (refuse
                                               admissions, finish in-flight,
                                               persist all lanes)

plus the orchestrator lifecycle endpoints the control port mounts at the
server root (docs/serving.md "Lifecycle"):

  GET /healthz  → liveness (the process answers)
  GET /readyz   → readiness: every registered serving app compiled, not
                  draining, and the profile plane reports no serving-program storm
                  (503 + Retry-After otherwise)

Error responses are structured JSON (``{"error": ..., "app": ...}``), and
every 503 (ServeFull / draining / overload shed) carries a ``Retry-After``
header derived from the engine's measured step rate.

Engines register under an app name via :func:`register_app` (usually at
construction by the app's serving loop); the registry is process-global,
matching the control port's own process-global planes (/metrics, doctor).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from ..log import logger
from .slots import ServeFull

__all__ = ["register_app", "unregister_app", "get_app", "apps", "routes",
           "readiness", "healthz", "readyz", "readyz_retry_after"]

log = logger("serve.api")

# app name -> ServeEngine; the module deliberately depends only on the
# jax-free bookkeeping side (slots) so a host-only control port can merge
# these routes without importing the compute plane
_apps: Dict[str, "object"] = {}
_lock = threading.Lock()


def register_app(engine, name: Optional[str] = None) -> str:
    """Register a :class:`~futuresdr_tpu.serve.engine.ServeEngine` under an
    app name (default: its own ``app``). With config
    ``serve_drain_on_sigterm`` set, the first registration also installs
    the SIGTERM graceful-drain hook (rolling-restart lifecycle)."""
    name = str(name or engine.app)
    with _lock:
        _apps[name] = engine
    try:
        from ..config import config
        if config().get("serve_drain_on_sigterm", False):
            from .engine import install_sigterm_drain
            install_sigterm_drain()
    except Exception as e:                 # noqa: BLE001 — lifecycle sugar
        log.warning("sigterm drain hook unavailable: %r", e)
    return name


def unregister_app(name: str) -> None:
    with _lock:
        _apps.pop(str(name), None)


def get_app(name: str):
    with _lock:
        return _apps.get(str(name))


def apps() -> Dict[str, "object"]:
    with _lock:
        return dict(_apps)


# -- aiohttp handlers ---------------------------------------------------------

async def _call(fn, *args, **kw):
    """Run a blocking engine call off the event loop: surgery methods
    (evict/readmit/retune) contend on the engine's STEP lock, which a
    stepper holds across an entire dispatch — including a newly-resident
    capacity's jit compile (seconds on a real backend). Calling them inline
    would freeze every other control-port route (/metrics scrapes, doctor,
    flowgraph APIs) for that long. (Read-only views only take the narrow
    state lock, but they ride the executor too — uniformity is cheaper
    than auditing each handler's lock discipline.)"""
    import asyncio
    import functools
    return await asyncio.get_running_loop().run_in_executor(
        None, functools.partial(fn, *args, **kw))


def _json_error(app: Optional[str], message: str, status: int,
                retry_after: Optional[int] = None):
    """Structured JSON error body (``{"error": ..., "app": ...}``) with the
    ``Retry-After`` header on backpressure statuses — a client or load
    balancer reads WHEN to come back instead of hammering a 503."""
    from aiohttp import web
    headers = {"Retry-After": str(int(retry_after))} \
        if retry_after is not None else None
    return web.json_response({"error": message, "app": app},
                             status=status, headers=headers)


def _serve_full(eng, name: str, e: BaseException):
    """503 for ServeFull/ServeDraining/ServeOverload, Retry-After derived
    from the engine's measured step rate."""
    try:
        after = int(eng.retry_after_s())
    except Exception:                      # noqa: BLE001 — header is advisory
        after = 1
    return _json_error(name, str(e), 503, retry_after=after)


def _engine_or_404(request):
    from aiohttp import web
    name = request.match_info["app"]
    eng = get_app(name)
    if eng is None:
        raise web.HTTPNotFound(
            text='{"error": "serving app not found", "app": "%s"}' % name,
            content_type="application/json")
    return eng


async def _list_apps(request):
    from aiohttp import web
    return web.json_response(
        {name: {"sessions": len(eng.table.sessions),
                "active": eng.table.active,
                "capacity": eng.capacity,
                "draining": bool(getattr(eng, "draining", False))}
         for name, eng in sorted(apps().items())})


async def _describe_app(request):
    from aiohttp import web
    return web.json_response(await _call(_engine_or_404(request).describe))


async def _create_session(request):
    from aiohttp import web
    eng = _engine_or_404(request)
    name = request.match_info["app"]
    body = {}
    if request.can_read_body:
        try:
            body = await request.json()
        except Exception:                  # noqa: BLE001 — bad JSON → 400
            return _json_error(name, "bad json body", 400)
    tenant = str(body.get("tenant", "default"))
    try:
        s = await _call(eng.admit, tenant=tenant, sid=body.get("sid"))
    except ServeFull as e:
        return _serve_full(eng, name, e)
    except ValueError as e:
        return _json_error(name, str(e), 409)
    return web.json_response(s.view(), status=201)


async def _session_view(request):
    from aiohttp import web
    eng = _engine_or_404(request)
    try:
        return web.json_response(
            await _call(eng.session_view, request.match_info["sid"]))
    except KeyError:
        return _json_error(request.match_info["app"], "session not found",
                           404)


async def _session_evict(request):
    from aiohttp import web
    eng = _engine_or_404(request)
    name = request.match_info["app"]
    try:
        s = await _call(eng.evict, request.match_info["sid"])
    except KeyError:
        return _json_error(name, "session not found", 404)
    except ValueError as e:
        return _json_error(name, str(e), 409)
    return web.json_response(s.view())


async def _session_readmit(request):
    from aiohttp import web
    eng = _engine_or_404(request)
    name = request.match_info["app"]
    try:
        s = await _call(eng.readmit, request.match_info["sid"])
    except KeyError:
        return _json_error(name, "session not found", 404)
    except ServeFull as e:
        return _serve_full(eng, name, e)
    except ValueError as e:
        return _json_error(name, str(e), 409)
    return web.json_response(s.view())


async def _session_ctrl(request):
    """``POST /api/serve/{app}/session/{sid}/ctrl/``: lane-addressed
    retune — apply an ``update_stage`` hook to ONE session's carry page at
    the lane's next quiescent boundary, siblings untouched. Body
    ``{"stage": <name|index>, "params": {...}}``; a bad stage address or a
    stage without an update hook is a 409 on this app's contract (the
    session exists — the REQUEST is wrong)."""
    from aiohttp import web
    eng = _engine_or_404(request)
    name = request.match_info["app"]
    try:
        body = await request.json()
        stage = body["stage"]
        params = body.get("params") or {}
        if not isinstance(params, dict):
            raise TypeError("params must be an object")
    except (ValueError, KeyError, TypeError):
        return _json_error(name, "bad json body: expected "
                           '{"stage": ..., "params": {...}}', 400)
    try:
        s = await _call(eng.retune, request.match_info["sid"], stage,
                        **params)
    except KeyError:
        return _json_error(name, "session not found", 404)
    except (ValueError, TypeError) as e:
        return _json_error(name, str(e), 409)
    return web.json_response(s.view())


async def _session_delete(request):
    from aiohttp import web
    eng = _engine_or_404(request)
    try:
        await _call(eng.close, request.match_info["sid"])
    except KeyError:
        return _json_error(request.match_info["app"], "session not found",
                           404)
    return web.json_response({"ok": True})


async def _drain_app(request):
    """``POST /api/serve/{app}/drain/``: graceful drain — refuse new
    admissions (503 + Retry-After), finish in-flight megabatch groups,
    persist every live lane, report drained. Runs off the event loop (the
    pump steps the engine); body ``{"pump": false}`` only MARKS draining
    for apps with their own pump thread, ``{"timeout": s}`` bounds the
    pump."""
    from aiohttp import web
    eng = _engine_or_404(request)
    name = request.match_info["app"]
    body = {}
    if request.can_read_body:
        try:
            body = await request.json()
        except Exception:                  # noqa: BLE001
            body = {}
    try:
        report = await _call(eng.drain,
                             pump=bool(body.get("pump", True)),
                             timeout=float(body.get("timeout", 30.0)))
    except Exception as e:                 # noqa: BLE001 — drain must report
        return _json_error(name, f"drain failed: {e!r}", 500)
    return web.json_response(report)


# -- orchestrator lifecycle (healthz/readyz) ----------------------------------

def readiness() -> Tuple[bool, dict]:
    """Process readiness for ``GET /readyz``: every registered serving app
    ready (current bucket compiled, not draining) AND no live SERVING-
    program compile storm on the profile plane. Detail names the unready app/reason so an
    operator reads WHY a pod is out of rotation."""
    detail: Dict[str, dict] = {}
    ready = True
    for name, eng in sorted(apps().items()):
        try:
            h = eng.health()
        except Exception as e:             # noqa: BLE001 — an engine that
            h = {"ready": False, "error": repr(e)}     # cannot answer is
        detail[name] = h                               # not ready
        ready = ready and bool(h.get("ready"))
    storms = None
    try:
        from ..telemetry import profile
        # SERVING-program storms only ("serve:<app>" labels): the plane is
        # process-global and flowgraph instance names collide across runs
        # by design, so an unrelated kernel's recompile churn must not pull
        # this pod out of rotation — a churning slot-bucket ladder must
        storms = [s for s in profile.plane().storm_report()
                  if str(s.get("program", "")).startswith("serve:")] or None
    except Exception:                      # noqa: BLE001 — profile plane
        pass                               # absence must not fail readiness
    if storms:
        ready = False
    return ready, {"apps": detail, "compile_storms": storms}


async def healthz(request):
    """Liveness: the process (and its control-port event loop) answers."""
    from aiohttp import web
    return web.json_response({"ok": True})


def readyz_retry_after() -> int:
    """The Retry-After default of an unready 503: the largest registered
    engine's measured ``retry_after_s()`` (lock-free), clamped to [1, 30]
    like the engines' own estimate — a fleet poller or load balancer backs
    off by how long this pod actually needs, not a hardcoded second."""
    after = 1
    for _name, eng in apps().items():
        try:
            after = max(after, int(eng.retry_after_s()))
        except Exception:                  # noqa: BLE001 — advisory header
            pass
    return int(min(30, max(1, after)))


async def readyz(request):
    """Readiness for rolling restarts: 200 only when every serving app is
    compiled + not draining with no serving-program compile storm;
    503 (+ clamped Retry-After) otherwise so an orchestrator holds
    traffic."""
    from aiohttp import web
    ready, detail = readiness()
    if ready:
        return web.json_response({"ready": True, **detail})
    return web.json_response(
        {"ready": False, **detail}, status=503,
        headers={"Retry-After": str(readyz_retry_after())})


def routes() -> List[Tuple[str, str, object]]:
    """The session-plane route table, in control-port ``extra_routes``
    form (method, path, handler)."""
    return [
        ("GET", "/api/serve/", _list_apps),
        ("GET", "/api/serve/{app}/", _describe_app),
        ("POST", "/api/serve/{app}/session/", _create_session),
        ("GET", "/api/serve/{app}/session/{sid}/", _session_view),
        ("POST", "/api/serve/{app}/session/{sid}/evict/", _session_evict),
        ("POST", "/api/serve/{app}/session/{sid}/readmit/", _session_readmit),
        ("POST", "/api/serve/{app}/session/{sid}/ctrl/", _session_ctrl),
        ("DELETE", "/api/serve/{app}/session/{sid}/", _session_delete),
        ("POST", "/api/serve/{app}/drain/", _drain_app),
        ("GET", "/healthz", healthz),
        ("GET", "/readyz", readyz),
    ]
