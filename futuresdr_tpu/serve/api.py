"""REST session plane for the serving front-end.

Extends the control port (``runtime/ctrl_port.py``) with the multi-tenant
session API of docs/serving.md — the routes are merged into every control
port automatically (plus available as ``routes()`` for a bespoke server):

  GET    /api/serve/                         → registered serving apps
  GET    /api/serve/{app}/                   → engine view (slots, buckets,
                                               per-tenant credit/latency)
  POST   /api/serve/{app}/session/           → admit  {"tenant": "...",
                                               "sid": optional}
  GET    /api/serve/{app}/session/{sid}/     → per-session metrics/doctor view
  POST   /api/serve/{app}/session/{sid}/evict/   → evict carry to host
  POST   /api/serve/{app}/session/{sid}/readmit/ → restore it bit-identically
  DELETE /api/serve/{app}/session/{sid}/     → leave

Engines register under an app name via :func:`register_app` (usually at
construction by the app's serving loop); the registry is process-global,
matching the control port's own process-global planes (/metrics, doctor).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from ..log import logger
from .slots import ServeFull

__all__ = ["register_app", "unregister_app", "get_app", "apps", "routes"]

log = logger("serve.api")

# app name -> ServeEngine; the module deliberately depends only on the
# jax-free bookkeeping side (slots) so a host-only control port can merge
# these routes without importing the compute plane
_apps: Dict[str, "object"] = {}
_lock = threading.Lock()


def register_app(engine, name: Optional[str] = None) -> str:
    """Register a :class:`~futuresdr_tpu.serve.engine.ServeEngine` under an
    app name (default: its own ``app``)."""
    name = str(name or engine.app)
    with _lock:
        _apps[name] = engine
    return name


def unregister_app(name: str) -> None:
    with _lock:
        _apps.pop(str(name), None)


def get_app(name: str):
    with _lock:
        return _apps.get(str(name))


def apps() -> Dict[str, "object"]:
    with _lock:
        return dict(_apps)


# -- aiohttp handlers ---------------------------------------------------------

async def _call(fn, *args, **kw):
    """Run a blocking engine call off the event loop: engine methods contend
    on the engine lock, which ``step()`` holds across an entire dispatch —
    including a newly-resident bucket's jit compile (seconds on a real
    backend). Calling them inline would freeze every other control-port
    route (/metrics scrapes, doctor, flowgraph APIs) for that long."""
    import asyncio
    import functools
    return await asyncio.get_running_loop().run_in_executor(
        None, functools.partial(fn, *args, **kw))


def _engine_or_404(request):
    from aiohttp import web
    eng = get_app(request.match_info["app"])
    if eng is None:
        raise web.HTTPNotFound(
            text='{"error": "serving app not found"}',
            content_type="application/json")
    return eng


async def _list_apps(request):
    from aiohttp import web
    return web.json_response(
        {name: {"sessions": len(eng.table.sessions),
                "active": eng.table.active,
                "capacity": eng.capacity}
         for name, eng in sorted(apps().items())})


async def _describe_app(request):
    from aiohttp import web
    return web.json_response(await _call(_engine_or_404(request).describe))


async def _create_session(request):
    from aiohttp import web
    eng = _engine_or_404(request)
    body = {}
    if request.can_read_body:
        try:
            body = await request.json()
        except Exception:                  # noqa: BLE001 — bad JSON → 400
            return web.json_response({"error": "bad json body"}, status=400)
    tenant = str(body.get("tenant", "default"))
    try:
        s = await _call(eng.admit, tenant=tenant, sid=body.get("sid"))
    except ServeFull as e:
        return web.json_response({"error": str(e)}, status=503)
    except ValueError as e:
        return web.json_response({"error": str(e)}, status=409)
    return web.json_response(s.view(), status=201)


async def _session_view(request):
    from aiohttp import web
    eng = _engine_or_404(request)
    try:
        return web.json_response(
            await _call(eng.session_view, request.match_info["sid"]))
    except KeyError:
        return web.json_response({"error": "session not found"}, status=404)


async def _session_evict(request):
    from aiohttp import web
    eng = _engine_or_404(request)
    try:
        s = await _call(eng.evict, request.match_info["sid"])
    except KeyError:
        return web.json_response({"error": "session not found"}, status=404)
    except ValueError as e:
        return web.json_response({"error": str(e)}, status=409)
    return web.json_response(s.view())


async def _session_readmit(request):
    from aiohttp import web
    eng = _engine_or_404(request)
    try:
        s = await _call(eng.readmit, request.match_info["sid"])
    except KeyError:
        return web.json_response({"error": "session not found"}, status=404)
    except ServeFull as e:
        return web.json_response({"error": str(e)}, status=503)
    except ValueError as e:
        return web.json_response({"error": str(e)}, status=409)
    return web.json_response(s.view())


async def _session_delete(request):
    from aiohttp import web
    eng = _engine_or_404(request)
    try:
        await _call(eng.close, request.match_info["sid"])
    except KeyError:
        return web.json_response({"error": "session not found"}, status=404)
    return web.json_response({"ok": True})


def routes() -> List[Tuple[str, str, object]]:
    """The session-plane route table, in control-port ``extra_routes``
    form (method, path, handler)."""
    return [
        ("GET", "/api/serve/", _list_apps),
        ("GET", "/api/serve/{app}/", _describe_app),
        ("POST", "/api/serve/{app}/session/", _create_session),
        ("GET", "/api/serve/{app}/session/{sid}/", _session_view),
        ("POST", "/api/serve/{app}/session/{sid}/evict/", _session_evict),
        ("POST", "/api/serve/{app}/session/{sid}/readmit/", _session_readmit),
        ("DELETE", "/api/serve/{app}/session/{sid}/", _session_delete),
    ]
