"""Durable per-session carry snapshots for the serving plane.

The :class:`SessionStore` extends the ``checkpoint_dir`` disk contract
(``utils/snapshot.py`` — atomic rename + CRC, signature-keyed filenames) to
per-slot serving carries: one file per session, keyed by session id plus
the app's pipeline-signature hash, so

* a restarted (VIRGIN) :class:`~futuresdr_tpu.serve.engine.ServeEngine`
  incarnation re-admits every persisted session **bit-identically** through
  the ``carry_matches``-validated readmit path;
* a DIFFERENT pipeline under a reused app name never reads the other's
  snapshots (the signature-hash separation pinned for ``checkpoint_dir``
  holds here too);
* a corrupted or mismatched file is skipped **per session** — one torn
  write never blocks the other sessions' recovery;
* a cleanly closed session purges its file (complete state — a later
  incarnation must not resurrect it).

Writes ride the process-wide single-worker persistence executor
(:func:`~futuresdr_tpu.utils.snapshot.persist_executor`) and COALESCE
through a per-session latest box, so a persistence cadence faster than the
disk skips intermediate snapshots instead of backlogging — ``step()`` never
stalls on a write. Metadata (tenant, frame cursors) rides next to the
leaves so a resumed session knows exactly how many frames its restored
carry has consumed.
"""

from __future__ import annotations

import glob
import os
import threading
from typing import Any, Dict, List, Optional

import numpy as np

from ..log import logger
from ..utils import snapshot as _snapshot

__all__ = ["SessionStore"]

log = logger("serve.persist")


class SessionStore:
    """Disk store of per-session carry snapshots for ONE serving app."""

    def __init__(self, directory: str, app: str, pipeline):
        self._dir = os.path.expanduser(str(directory))
        self.app = str(app)
        self._safe_app = _snapshot.sanitize_name(self.app)
        #: pipeline-signature hash (stage names + in dtype, keyed by app):
        #: load_all only globs THIS signature, so a pipeline change under a
        #: reused app name orphans the old files instead of restoring them
        self.signature = _snapshot.snapshot_signature(pipeline, self.app)
        self._lock = threading.Lock()
        self._box: Dict[str, tuple] = {}   # sid -> (fetch, meta) newest wins
        self._queued = False

    # -- paths -----------------------------------------------------------------
    def path(self, sid: str) -> str:
        # sanitized name for readability PLUS a hash of the RAW sid:
        # sanitization is lossy ("t:1" and "t_1" both render "t_1"), and
        # sids are caller-supplied over REST — two live sessions must never
        # share a snapshot file last-writer-wins
        import hashlib
        safe = _snapshot.sanitize_name(sid)
        h = hashlib.sha1(str(sid).encode()).hexdigest()[:8]
        return os.path.join(
            self._dir,
            f"{self._safe_app}--{safe}.{h}-{self.signature}.sess.npz")

    def _glob(self) -> List[str]:
        return sorted(glob.glob(os.path.join(
            self._dir, f"{self._safe_app}--*-{self.signature}.sess.npz")))

    # -- writes (coalesced, off the step thread) -------------------------------
    def save(self, sid: str, fetch, meta: Dict[str, Any],
             sync: bool = False) -> None:
        """Queue one session snapshot. ``fetch`` is a zero-arg thunk yielding
        the host leaf list (materialized in the writer thread — the engine's
        stacked carries are never donated, so a captured reference stays
        readable); ``meta`` must carry ``sid``/``tenant``/``frames_out``.
        ``sync=True`` WAITS for the write to land — still via the ONE-worker
        executor: a second writer thread would share the pid-keyed tmp file
        with a queued background write of the same session and tear it
        (exactly the hazard the single-writer pool exists to prevent), and
        the box keeps newest-wins ordering either way."""
        with self._lock:
            self._box[sid] = (fetch, meta)
            queued = self._queued
            self._queued = True
        if not queued:
            _snapshot.persist_executor().submit(self._drain_box)
        if sync:
            self.flush()

    def _drain_box(self) -> None:
        while True:
            with self._lock:
                if not self._box:
                    self._queued = False
                    return
                sid, (fetch, meta) = self._box.popitem()
            self._write(sid, fetch, meta)

    def _write(self, sid: str, fetch, meta: Dict[str, Any]) -> None:
        try:
            leaves = [np.asarray(l) for l in fetch()]
        except Exception as e:                         # noqa: BLE001 — a lost
            log.warning("%s: session %s snapshot fetch failed (%r) — "
                        "skipped", self.app, sid, e)   # write never raises
            return
        seq = int(meta.get("frames_out", 0))
        if not _snapshot.write_snapshot(self.path(sid), seq, leaves, meta):
            log.warning("%s: session %s snapshot persist failed",
                        self.app, sid)

    def purge(self, sid: str) -> None:
        """Remove a session's snapshot (clean close / retire). Queued after
        any pending write of the same session, so a close during a persist
        cadence can never leave a resurrected file behind."""
        with self._lock:
            self._box.pop(sid, None)
        path = self.path(sid)

        def unlink():
            try:
                os.unlink(path)
            except OSError:
                pass

        _snapshot.persist_executor().submit(unlink)

    def flush(self) -> None:
        """Barrier: every snapshot queued before this call is on disk after
        it (the one-worker executor is FIFO)."""
        _snapshot.persist_executor().submit(lambda: None).result()

    # -- restore ---------------------------------------------------------------
    def load_all(self) -> List[dict]:
        """Every readable persisted session of this app+signature:
        ``{"sid", "tenant", "frames_in", "frames_out", "leaves", "path"}``.
        Corrupted/unreadable files are skipped per-session (logged by the
        snapshot reader); files whose metadata is absent fall back to the
        filename-derived sid with a default tenant."""
        out: List[dict] = []
        for path in self._glob():
            got = _snapshot.read_snapshot(path)
            if got is None:
                continue
            seq, leaves, meta = got
            meta = meta or {}
            sid = str(meta.get("sid") or "")
            if not sid:
                # filename fallback (metadata is CRC-protected and always
                # written by the engine, so this is belt-and-braces): strip
                # the signature and the trailing ".<8-hex raw-sid hash>"
                stem = os.path.basename(path).split("--", 1)[-1] \
                    .rsplit("-", 1)[0]
                head, _, tail = stem.rpartition(".")
                sid = head if head and len(tail) == 8 else stem
            out.append({
                "sid": sid,
                "tenant": str(meta.get("tenant", "default")),
                "frames_in": int(meta.get("frames_in", seq)),
                "frames_out": int(meta.get("frames_out", seq)),
                "leaves": leaves,
                "path": path,
            })
        return out
