"""futuresdr_tpu.serve — multi-tenant flowgraph serving (docs/serving.md).

Batch thousands of concurrent sessions of the SAME fused receiver program
into one dispatch per frame: a slot-table session manager with ragged
admission (:mod:`.slots`), the vmapped serving engine (:mod:`.engine`),
per-tenant fair credits (:mod:`.credits`) and the REST session plane
(:mod:`.api` — merged into every control port).
"""

from .credits import TenantCreditController
from .overload import ShedLadder
from .slots import (ServeDraining, ServeFull, ServeOverload, Session,
                    SlotTable)
from .api import apps, get_app, register_app, routes, unregister_app
from .router import AdmissionRouter, NoReadyHost

__all__ = ["ServeEngine", "ServeFull", "ServeDraining", "ServeOverload",
           "Session", "SlotTable", "SessionStore", "ShedLadder",
           "TenantCreditController", "build_slot_program", "default_buckets",
           "install_sigterm_drain", "drain_all_apps",
           "register_app", "unregister_app", "get_app", "apps", "routes",
           "AdmissionRouter", "NoReadyHost"]

#: engine symbols resolve lazily: the control port merges the REST session
#: plane into every server, and the HOST-only runtime must not pay the jax
#: import the engine's compute plane needs just for that
_LAZY_ENGINE = {"ServeEngine", "build_slot_program", "default_buckets",
                "install_sigterm_drain", "drain_all_apps", "SessionStore"}


def __getattr__(name):
    if name in _LAZY_ENGINE:
        if name == "SessionStore":
            from .persist import SessionStore as val
        else:
            from . import engine
            val = getattr(engine, name)
        globals()[name] = val
        return val
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
