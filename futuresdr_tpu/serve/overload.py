"""SLO-aware overload control for the serving plane: the shedding ladder.

An overloaded serving engine must degrade **in a documented order** instead
of falling over (docs/robustness.md "Serving-plane recovery"). The
:class:`ShedLadder` is a small hysteretic state machine the engine ticks
once per busy step with two signals:

* **queue pressure** — submitted-but-undispatched frames over the shared
  credit budget (``TenantCreditController.pressure``), against the
  ``serve_shed_hi``/``serve_shed_lo`` watermarks;
* **latency SLO** — the rolling p99 of submit→result latency against the
  ``serve_slo_ms`` deadline budget (0 = pressure-only).

Rungs, in escalation order (the engine acts on transitions):

| rung | name | action | resident numerics |
|---|---|---|---|
| 0 | ``ok`` | — | — |
| 1 | ``admission`` | NEW admissions refused (``ServeOverload`` → 503 + ``Retry-After``) | bit-exact |
| 2 | ``evict`` | most-stalled sessions evicted to host/disk, freeing lanes | bit-exact (evict/readmit is the bit-identical leaf contract) |
| 3 | ``brownout`` | optional lever (config ``serve_brownout``): drop megabatch K to 1, or retune interior precision to bf16 | documented loss (K-rounding / SNR-bounded) — **off by default** |

Escalation needs ``trip`` CONSECUTIVE unhealthy observations per rung;
recovery needs ``clear`` consecutive healthy observations per rung and
unwinds ONE rung at a time — the ladder never jumps from brownout straight
to open admission, so flapping load cannot oscillate the engine between
quality modes.
"""

from __future__ import annotations

from typing import Optional

__all__ = ["ShedLadder", "RUNGS", "LATENCY_RUNG"]

#: rung names, index == level
RUNGS = ("ok", "admission", "evict", "brownout")

#: the rung from which the engine prefers per-frame LATENCY over
#: throughput levers: at/above it the overlapped step collapses its
#: in-flight window to depth 1 (each extra in-flight group is a whole
#: group-time of queueing delay — the same trade as the "k" brownout
#: lever, taken one rung earlier because pipelining depth, unlike K, is
#: bit-exact to unwind)
LATENCY_RUNG = 2


class ShedLadder:
    """Hysteretic overload ladder; see the module docstring for semantics."""

    def __init__(self, hi: float = 0.85, lo: float = 0.50,
                 trip: int = 3, clear: int = 8, max_level: int = 3):
        self.hi = float(hi)
        self.lo = float(lo)
        self.trip = max(1, int(trip))
        self.clear = max(1, int(clear))
        self.max_level = max(0, min(int(max_level), len(RUNGS) - 1))
        self.level = 0
        self.escalations = 0              # lifetime rung-up transitions
        self._bad = 0
        self._good = 0

    @classmethod
    def from_config(cls, max_level: int = 3) -> "ShedLadder":
        from ..config import config
        c = config()
        return cls(hi=float(c.get("serve_shed_hi", 0.85)),
                   lo=float(c.get("serve_shed_lo", 0.50)),
                   trip=int(c.get("serve_shed_trip", 3)),
                   clear=int(c.get("serve_shed_clear", 8)),
                   max_level=max_level)

    @property
    def rung(self) -> str:
        return RUNGS[self.level]

    def observe(self, pressure: float, p99_ms: Optional[float],
                slo_ms: float) -> int:
        """One observation; returns the (possibly new) level.

        Unhealthy = pressure at/over the high watermark OR (with an SLO
        set) the rolling p99 over the deadline budget. Healthy = pressure
        at/under the LOW watermark AND the p99 back inside the SLO — the
        band between the watermarks holds the current rung (hysteresis).
        """
        slo_miss = bool(slo_ms) and p99_ms is not None and p99_ms > slo_ms
        over = pressure >= self.hi or slo_miss
        under = pressure <= self.lo and not slo_miss
        if over:
            self._good = 0
            self._bad += 1
            if self._bad >= self.trip and self.level < self.max_level:
                self.level += 1
                self.escalations += 1
                self._bad = 0
        elif under:
            self._bad = 0
            if self.level:
                self._good += 1
                if self._good >= self.clear:
                    self.level -= 1       # one rung at a time — in order
                    self._good = 0
        else:
            # between the watermarks: hold the rung, reset both streaks
            self._bad = 0
            self._good = 0
        return self.level

    def reset(self) -> None:
        self.level = 0
        self._bad = 0
        self._good = 0

    def view(self) -> dict:
        return {"level": self.level, "rung": self.rung,
                "hi": self.hi, "lo": self.lo,
                "trip": self.trip, "clear": self.clear,
                "escalations": self.escalations}
