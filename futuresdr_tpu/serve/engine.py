"""ServeEngine: batch N concurrent sessions of ONE receiver DAG into one
dispatch per frame.

The production serving plane of docs/serving.md. Every fused
``Pipeline``/``FanoutPipeline``/``DagPipeline`` program computes exactly one
session per dispatch on the actor path — at SDR frame rates that leaves the
chip almost entirely idle (MFU 5.6% on the resident chain, ROADMAP). This
engine multiplexes N concurrent sessions running the SAME program through a
single per-frame dispatch by compiling the pipeline ONCE per slot bucket
with a leading session axis:

* ``jax.vmap`` over the inputs AND the flat composed carry — the carry
  layout per lane stays exactly the linear contract, so ``update_stage``
  addressing and the checkpoint ``snapshot_carry``/``restore_carry``
  surface keep working per slot;
* RAGGED admission in the style of Ragged Paged Attention
  (arXiv:2604.15464): a fixed-capacity slot axis with padded inactive
  lanes masked by an ``active`` lanes vector threaded as a program input —
  sessions join, leave and stall mid-flight by flipping mask lanes, with
  ZERO recompiles of resident buckets (``self.compiles`` is the pin);
* PAGED carry storage (docs/serving.md "Paged session carries"): per-lane
  carries live in a fixed-size page pool indexed by the session→page
  permutation the :class:`~futuresdr_tpu.serve.slots.SlotTable` maintains;
  the compiled program gathers each lane's page, substitutes the fresh
  template on ``fresh``-flagged lanes, steps, and scatters back — so a
  join lands at its own frame cursor MID-megabatch as a page-map edit, a
  leave parks the page, and eviction reads one page, never a restack;
* an OVERLAPPED step: the dispatch group launched at step t rides async
  ``start_device_transfer`` H2D and ``start_host_transfer`` D2H finishes,
  governed by the streamed path's
  :class:`~futuresdr_tpu.tpu.kernel_block.CreditController`, so
  H2D(t+1) ∥ compute(t) ∥ D2H(t−1) holds for serving exactly as for the
  streamed kernel — committed carries advance ONLY after a group's D2H
  lands (a failed drain re-queues every uncommitted group's frames:
  PR 10's rollback contract, now over a window);
* autotuned bucket sizes (``tpu/autotune.autotune_serve``): occupancy
  crossing the current bucket grows the PAGE POOL to the next bucket's
  capacity and compiles THAT capacity once;
* per-session carry slots riding the checkpoint machinery: ``evict`` lands
  a session's carry lane on the host via ``snapshot_carry``'s leaf
  contract, ``readmit`` restores it bit-identically (validated by
  ``carry_matches`` against the fresh-carry template, exactly like the
  kernel recovery path);
* per-tenant fairness over the shared admission budget
  (:class:`~futuresdr_tpu.serve.credits.TenantCreditController` — the
  multi-tenant generalization of the streamed path's CreditController);
* per-session fault isolation (the ``isolate_group``-per-session
  semantics): a work/dispatch fault addressed at one session retires ONLY
  that slot — siblings keep their lanes and their bit-exact outputs.

Masking semantics: inactive lanes still ride through the vmapped program
(their input rows are zeros), but their computed carries are DISCARDED by a
``where(active, new, old)`` merge inside the jitted program — a stalled
lane's filter history and oscillator phase are bit-frozen until its next
real frame, and an active lane's carry is exactly what the standalone
program would have produced (the N=1 ≡ bare-pipeline bit-equality
contract, test-pinned).
"""

from __future__ import annotations

import math
import os
import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Sequence

import numpy as np

from ..log import logger
from ..ops import xfer
from ..runtime import faults as _faults
from ..telemetry import fleet as _fleet
from ..telemetry import journal as _journal
from ..telemetry import lineage as _lineage
from ..telemetry import profile as _profile
from ..telemetry import prom as _prom
from ..telemetry.doctor import E2E_LATENCY as _E2E_LATENCY
from ..telemetry.spans import recorder as _trace_recorder
from .credits import TenantCreditController
from .overload import LATENCY_RUNG as _LATENCY_RUNG
from .overload import ShedLadder
from .persist import SessionStore
from .slots import (ServeDraining, ServeFull, ServeOverload, Session,
                    SlotTable)

__all__ = ["ServeEngine", "ServeFull", "ServeDraining", "ServeOverload",
           "default_buckets", "install_sigterm_drain"]

log = logger("serve.engine")
_trace = _trace_recorder()

# per-tenant Prometheus families (docs/serving.md "Observability"): every
# family carries {app, tenant} so one scrape separates tenants; label
# ordering in the exposition is stable (telemetry/prom.py sorts samples)
_SESSIONS = _prom.gauge(
    "fsdr_serve_sessions", "live serving sessions per state",
    ("app", "tenant", "state"))
_FRAMES = _prom.counter(
    "fsdr_serve_frames_total", "frames dispatched through the serving plane",
    ("app", "tenant"))
_DISPATCHES = _prom.counter(
    "fsdr_serve_dispatches_total",
    "batched serving dispatches (one per step with >= 1 active lane)",
    ("app",))
_RETIRED = _prom.counter(
    "fsdr_serve_retired_total",
    "sessions retired by a per-session fault (slot-isolated)",
    ("app", "tenant"))
_EVICTIONS = _prom.counter(
    "fsdr_serve_evictions_total",
    "session carries evicted to the host", ("app", "tenant"))
_REJECTS = _prom.counter(
    "fsdr_serve_rejects_total",
    "frame submissions refused by the per-tenant credit guard",
    ("app", "tenant"))
_LATENCY = _prom.histogram(
    "fsdr_serve_latency_seconds",
    "submit -> decoded-result latency per frame", ("app", "tenant"))
_SHED = _prom.counter(
    "fsdr_serve_shed_total",
    "overload/drain shedding actions by the serving engine "
    "(reason: admission | evict | brownout | drain)",
    ("app", "tenant", "reason"))
_SHED_LEVEL = _prom.gauge(
    "fsdr_serve_shed_level",
    "current shedding-ladder rung (0 ok, 1 admission, 2 evict, 3 brownout)",
    ("app",))
_RESUMED = _prom.counter(
    "fsdr_serve_resumed_total",
    "sessions re-admitted from durable snapshots by a fresh incarnation",
    ("app", "tenant"))


def default_buckets() -> tuple:
    """The slot-bucket ladder when neither the caller nor the autotune cache
    provides one: config ``serve_buckets`` ("1,2,4,…"), else powers of two
    to 64."""
    from ..config import config
    spec = str(config().get("serve_buckets", "") or "").strip()
    if spec:
        try:
            out = sorted({int(x) for x in spec.replace(";", ",").split(",")
                          if x.strip()})
            if out and all(b > 0 for b in out):
                return tuple(out)
        except ValueError:
            log.warning("bad serve_buckets spec %r — using the default "
                        "ladder", spec)
    return (1, 2, 4, 8, 16, 32, 64)


def build_slot_program(pipeline, capacity: int, k: int = 1):
    """Compile the pipeline's PAGED slot-batched serving step for one
    page-pool capacity:

        step(pages, page_map, fresh, x, active) -> (pages', outs)

    with every page-pool leaf carrying a leading ``[capacity]`` page axis.
    ``page_map`` is the lane→page PERMUTATION of ``[0, capacity)`` the
    :class:`~futuresdr_tpu.serve.slots.SlotTable` maintains, threaded as a
    program INPUT: the step gathers each lane's carry page
    (``leaf[page_map]``), steps the lanes, and scatters the merged carries
    back (``leaf.at[page_map].set(...)``) — churn edits the map on the
    host, never the program. The permutation invariant is load-bearing:
    a duplicate scatter index would make the result order-undefined.
    ``fresh`` is a ``[capacity]`` bool vector flagging lanes admitted since
    the last dispatch: their gathered page (stale bits of whoever parked
    there last) is replaced by the pipeline's init-carry template INSIDE
    the program, so admission writes nothing to the device — a joining
    session starts at its own frame cursor mid-megabatch.

    ``k == 1`` (the default): ``x`` is ``[capacity, frame]``, ``active`` a
    ``[capacity]`` bool vector, outs ``[capacity, out]`` per sink.

    ``k > 1`` is the MEGABATCH serving form: ``x`` is ``[capacity, k,
    frame]``, ``active`` a ``[capacity, k]`` PER-FRAME mask, and a
    ``lax.scan`` chains the k frames through every lane in one program call
    (amortizing per-dispatch host cost exactly like ``TpuKernel``'s
    ``frames_per_dispatch``) — the mask is RAGGED per lane, so sessions
    with fewer than k queued frames ride the same dispatch with their tail
    masked and their carries frozen from their last real frame on (frames
    pack at the front of the k axis; a masked row can never corrupt a
    later real frame's carry). The page gather/scatter happens ONCE around
    the whole scan, not per frame.

    Inactive lanes keep their OLD carry (bit-frozen stall semantics) —
    except fresh lanes, which scatter the TEMPLATE back so their page is
    initialized by their first ride whether or not they had a frame.
    Output rows of inactive lane-frames are never delivered, so their
    value is irrelevant. No donation: eviction and lane surgery do
    functional page reads/updates on the live pool between dispatches, and
    the overlapped step keeps the committed pool alive while speculative
    groups are in flight — donation would invalidate exactly those
    buffers. Shared with ``tpu/autotune.autotune_serve`` so the measured
    program is exactly the served one."""
    import jax
    import jax.numpy as jnp

    inner = pipeline.fn()
    multi = bool(getattr(pipeline, "n_branches", 0))
    template = pipeline.init_carry()

    def gather(pages, page_map, fresh):
        def pick(P, t):
            c = P[page_map]
            m = fresh.reshape((fresh.shape[0],) + (1,) * (c.ndim - 1))
            return jnp.where(m, jnp.asarray(t)[None], c)

        return jax.tree_util.tree_map(pick, pages, template)

    def scatter(pages, page_map, carries):
        return jax.tree_util.tree_map(
            lambda P, c: P.at[page_map].set(c), pages, carries)

    def masked_lane_step(carries, x, active):
        new_c, y = jax.vmap(inner)(carries, x)

        def sel(n, o):
            m = active.reshape((active.shape[0],) + (1,) * (n.ndim - 1))
            return jnp.where(m, n, o)

        return jax.tree_util.tree_map(sel, new_c, carries), y

    if int(k) <= 1:
        def step(pages, page_map, fresh, x, active):
            carries = gather(pages, page_map, fresh)
            new_c, y = masked_lane_step(carries, x, active)
            return scatter(pages, page_map, new_c), (y if multi else (y,))
    else:
        def step(pages, page_map, fresh, x, active):
            carries = gather(pages, page_map, fresh)

            def body(c, xa):
                xk, ak = xa
                return masked_lane_step(c, xk, ak)

            carries, ys = jax.lax.scan(
                body, carries,
                (jnp.moveaxis(x, 1, 0), jnp.moveaxis(active, 1, 0)))
            # ys: [k, capacity, out] per sink -> [capacity, k, out]
            if multi:
                outs = tuple(jnp.moveaxis(yj, 0, 1) for yj in ys)
            else:
                outs = (jnp.moveaxis(ys, 0, 1),)
            return scatter(pages, page_map, carries), outs

    return jax.jit(step, donate_argnums=())


class _DispatchGroup:
    """One launched-but-uncommitted serving dispatch (the overlapped step's
    unit of flight): the host-side batch bookkeeping assembled at step t,
    the speculative output pages the program produced, and the pending D2H
    finishes. Committed oldest-first; a failed drain rolls the whole chain
    back (every younger group derived its pages from this one's output)."""

    __slots__ = ("capacity", "k", "lanes", "n_frames", "batch", "active",
                 "fresh", "page_map", "fresh_lanes", "step_tids", "t_step",
                 "new_pages", "fins", "wire")

    def __init__(self, capacity: int, k: int, lanes: list, batch, active,
                 fresh, page_map, fresh_lanes: frozenset, step_tids: list,
                 t_step: int):
        self.capacity = capacity
        self.k = k
        self.lanes = lanes            # (session, lane, popped, tids) tuples
        self.n_frames = sum(len(p) for _s, _l, p, _t in lanes)
        self.batch = batch
        self.active = active
        self.fresh = fresh
        self.page_map = page_map
        self.fresh_lanes = fresh_lanes
        self.step_tids = step_tids
        self.t_step = t_step
        self.new_pages = None         # set by launch
        self.fins = None              # pending D2H finishes, one per sink
        self.wire = None              # H2D (service, deadline) wire window


class ServeEngine:
    """Multi-tenant serving front-end over one compiled receiver program.

    Host-driven: a serving loop (``perf/serve_ab.py``, an app's pump thread)
    calls :meth:`step` once per frame time; the REST session plane
    (``serve/api.py``) and any thread may ``admit``/``submit``/``evict``/
    ``close`` concurrently — one engine lock serializes table mutations
    against the dispatch walk.
    """

    def __init__(self, pipeline, frame_size: Optional[int] = None,
                 app: str = "serve", inst=None,
                 buckets: Optional[Sequence[int]] = None,
                 queue_frames: Optional[int] = None,
                 frames_per_dispatch: int = 1,
                 persist_dir: Optional[str] = None,
                 persist_every: Optional[int] = None,
                 slo_ms: Optional[float] = None,
                 shard_devices: Optional[int] = None,
                 inflight: Optional[int] = None):
        from ..config import config
        from ..tpu.instance import instance
        self.pipeline = pipeline
        self._base_pipeline = pipeline     # pre-brownout program identity
        self.app = str(app)
        # per-lane e2e latency for the serving plane: the SAME
        # fsdr_e2e_latency_seconds family the streamed sinks observe, one
        # source child per app — so the doctor's e2e quantiles and the
        # lineage exemplars cover serving and streaming uniformly
        self._e2e_hist = _E2E_LATENCY.labels(source=f"serve:{self.app}")
        self.inst = inst or instance()
        self.k_batch = max(1, int(frames_per_dispatch))
        m = pipeline.frame_multiple
        fs = frame_size or config().tpu_frame_size
        self.frame_size = max(m, (fs // m) * m)
        self.n_sinks = int(getattr(pipeline, "n_branches", 0)) or 1
        self._multi = bool(getattr(pipeline, "n_branches", 0))
        if buckets is None:
            buckets = self._cached_buckets()
        self.buckets = tuple(sorted({int(b) for b in buckets})) \
            if buckets else default_buckets()
        # -- slot-axis sharding (docs/parallel.md "Mesh-sharded device
        # plane", docs/serving.md): a bucket's session lanes spread across
        # the chip mesh — the stacked carries, batch and mask shard on the
        # SLOT axis (one contiguous lane block per device), so a D-chip
        # mesh serves D x the lanes per dispatch with the same program.
        # Off (the default, serve_shard_devices=0 / D=1) is byte-for-byte
        # the single-device engine. Refusals are loud (make_mesh contract:
        # more devices than exist never truncates silently); a bucket whose
        # capacity does not divide by D stays UNSHARDED — evict/readmit and
        # lane surgery address (device, lane) through slot_device()
        sd = int(shard_devices if shard_devices is not None
                 else config().get("serve_shard_devices", 0) or 0)
        self._shard_d = max(1, sd)
        self._slot_sharding = None
        if self._shard_d > 1:
            from jax.sharding import NamedSharding, PartitionSpec as P
            from ..shard.data import shard_mesh
            from ..shard.plan import AXIS
            self._shard_mesh = shard_mesh(self._shard_d)   # loud refusal
            self._slot_sharding = NamedSharding(self._shard_mesh, P(AXIS))
            self._replicated_sharding = NamedSharding(self._shard_mesh, P())
        #: compiled serving programs keyed (capacity, k, pipeline tag) — the
        #: session-churn contract is that this map only ever GAINS entries
        #: (join/leave/stall/evict inside resident buckets never recompiles;
        #: the k/tag axes exist for the brownout lever, which is a DOCUMENTED
        #: program change, never churn)
        self._programs: Dict[tuple, object] = {}
        self.compiles = 0                 # program builds (the recompile pin)
        start_cap = self.buckets[0]
        if buckets is None:
            # the autotune cache's paged-bucket axis (serve_pages): a
            # measured page-pool capacity pre-provisions the pool so the
            # first churn wave never climbs the ladder compile-by-compile
            start_cap = self._cached_pages() or start_cap
        self.table = SlotTable(start_cap)
        self._fresh = None                # fresh single-lane carry template
        #: committed page pool: one lane-sized carry page per slot of the
        #: current capacity, indexed by the SlotTable's lane→page
        #: permutation. Advances ONLY when a dispatch group's D2H lands.
        self._pages = self._stacked_fresh(self.table.capacity)
        #: speculative head of the page-pool chain: the newest launched
        #: group's output pages — the next group's input. Equal to
        #: ``_pages`` whenever nothing is in flight.
        self._head_pages = self._pages
        #: lanes admitted since their first dispatch: the program replaces
        #: their gathered page with the fresh template (see
        #: build_slot_program) — the bits clear when a group launches and
        #: are restored by rollback
        self._fresh_lanes: set = set()
        per_slot = int(queue_frames
                       if queue_frames is not None
                       else config().get("serve_queue_frames", 2))
        self._queue_frames = max(1, per_slot)
        self.credits = TenantCreditController(
            self._queue_frames * self.table.capacity)
        # overlapped step (docs/serving.md "The overlapped step"): up to
        # ``serve_inflight`` dispatch groups ride concurrently — launched
        # (H2D + program call + D2H started) but uncommitted. Depth 1 is
        # byte-for-byte the synchronous engine. The budget is governed by
        # the streamed path's CreditController: with a modeled wire it
        # probes one extra group when the up-link idles between launches
        # and rolls back probes that don't pay (kernel_block.py).
        from ..tpu.kernel_block import CreditController
        depth = max(1, int(inflight if inflight is not None
                           else config().get("serve_inflight", 1)))
        self._flight = CreditController(depth, adaptive=depth > 1)
        self._inflight: Deque = deque()   # launched, uncommitted groups
        #: step/quiesce lock — ALWAYS acquired before ``_lock``. Held by
        #: steppers across launch+drain (so the in-flight chain has one
        #: owner) and by page-touching surgery (evict/readmit/retune/
        #: growth/brownout), which must drain the chain first. The state
        #: lock ``_lock`` below is held only for table/queue mutation —
        #: never across a compile, transfer wait, or program call — so
        #: /metrics, health() and describe() answer mid-step.
        self._step_lock = threading.RLock()
        self._lock = threading.RLock()
        self._ticking = False             # _overload_tick re-entry guard
        # bounded retired-session retention: a faulted client rarely comes
        # back to DELETE its session, so retired views (and their
        # undelivered output) would otherwise accumulate forever in a
        # long-running process — keep the newest N, forget the oldest
        self._retired_keep = max(0, int(config().get("serve_retired_keep",
                                                     64)))
        self._retired: List[str] = []
        self.steps = 0                    # step() calls (incl. idle)
        self.dispatches = 0               # steps that launched the program
        self.frames = 0                   # session-frames dispatched
        self._gauge_cache: Dict[tuple, object] = {}
        # profile plane (telemetry/profile.py): capacities whose first
        # dispatch (the real jit compile — build_slot_program only wraps)
        # has been billed as reason="serve_bucket", and the live-roofline
        # entry whose unit is ONE SESSION-FRAME (lane) — the registered
        # cost is the single-lane program's cost_analysis(), so vmapped
        # bucket MFU attributes per lane regardless of the resident bucket
        pipe, fs = self.pipeline, self.frame_size

        def _lane_cost():
            from ..utils.roofline import program_cost
            return program_cost(pipe, fs)

        self._warmed: set = set()
        from ..utils.roofline import dominant_dtype
        self._prof = _profile.register(f"serve:{self.app}",
                                       cost_thunk=_lane_cost,
                                       dtype=dominant_dtype(pipe.stages))
        # -- crash safety + lifecycle + overload control (this PR) ---------
        # durable session state (docs/robustness.md "Serving-plane
        # recovery"): per-slot carry snapshots under serve_persist_dir,
        # background cadence serve_persist_every (0 = off and free — one
        # falsy check per step)
        d = persist_dir if persist_dir is not None \
            else config().get("serve_persist_dir", "")
        d = str(d or "")
        self._store = SessionStore(d, self.app, pipeline) if d else None
        self._persist_every = max(0, int(
            persist_every if persist_every is not None
            else config().get("serve_persist_every", 0)))
        self._steps_since_persist = 0
        # graceful lifecycle: draining refuses admissions, finishes
        # in-flight groups, persists all lanes; drained is terminal-ish
        # (a new incarnation, not this one, serves the next wave)
        self._draining = False
        self._drained = False
        # SLO-aware overload shedding (serve/overload.py): queue-pressure
        # watermarks + latency deadline budget drive the hysteretic ladder
        self._slo_ms = float(slo_ms if slo_ms is not None
                             else config().get("serve_slo_ms", 0.0))
        self._ladder = ShedLadder.from_config()
        self._brownout = str(config().get("serve_brownout", "off") or "off")
        bp = str(config().get("serve_brownout_precision", "bf16") or "bf16")
        # unknown modes fall back to bf16 — a typo'd config must not turn
        # the overload lever into a no-op at the worst possible moment
        self._brownout_prec = bp if bp in ("bf16", "int8") else "bf16"
        self._brownout_active = False
        self._low_pipe = None              # lazily-planned lowered brownout form
        self._pipe_tag = "base"            # program-cache axis for brownout
        self._base_dt = None               # base-pipeline leaf dtypes (lazy)
        self._lat_recent: Deque[float] = deque(maxlen=128)   # seconds
        self._step_stamps: Deque[float] = deque(maxlen=32)   # busy-step times
        self.restored_sessions = 0         # persisted sessions re-admitted
        self.shed_evictions = 0            # ladder rung-2 evictions
        # doctor coverage: the engine registers with the process-global
        # watchdog (weakref — test churn must not leak attachments) so a
        # wedged step()/drain trips a flight record naming the stuck app
        self._doctor_token = None
        try:
            from ..telemetry import doctor as _doctor
            self._doctor_token = _doctor.doctor().attach_serve(self)
        except Exception as e:             # noqa: BLE001 — observability only
            log.warning("%s: doctor attach failed: %r", self.app, e)
        if self._store is not None:
            self._restore_persisted()

    # -- carry plumbing --------------------------------------------------------
    def _fresh_carry(self):
        if self._fresh is None:
            self._fresh = self.pipeline.init_carry()
        return self._fresh

    def _shard_ok(self, capacity: int) -> bool:
        """Does this bucket shard over the mesh? Needs the slot-axis mesh
        armed AND an even lane split (one contiguous block per device)."""
        return (self._slot_sharding is not None
                and capacity % self._shard_d == 0)

    def slot_device(self, slot: int) -> tuple:
        """The ``(device_index, lane)`` pair a slot addresses under the
        slot-axis sharding (``(0, slot)`` unsharded): slots shard in
        contiguous blocks, so device ``slot // (capacity // D)`` owns lane
        ``slot % (capacity // D)`` of its shard. Evict/readmit and lane
        surgery stay slot-addressed — this is the observability mapping
        (session views, doctor)."""
        if not self._shard_ok(self.table.capacity):
            return (0, int(slot))
        per = self.table.capacity // self._shard_d
        return (int(slot) // per, int(slot) % per)

    def _stacked_fresh(self, capacity: int):
        import jax
        import jax.numpy as jnp
        fresh = self._fresh_carry()
        stacked = jax.tree_util.tree_map(
            lambda l: jnp.stack([jnp.asarray(l)] * capacity), fresh)
        if self._shard_ok(capacity):
            stacked = jax.device_put(stacked, self._slot_sharding)
        else:
            # COMMIT the pool to the instance device: the program's output
            # pages (the pool after the first commit) are committed arrays,
            # and jit keys on sharding — an uncommitted seed pool would buy
            # a second silent compile of the same capacity on step 2
            stacked = jax.device_put(stacked, self.inst.device)
        return stacked

    def _set_page(self, page: int, value_tree) -> None:
        """Write one carry page of the COMMITTED pool (readmit, restore,
        retune). Only legal at a quiescent boundary — the caller holds the
        step lock with nothing in flight, so the speculative head is
        re-synced here and the next launch derives from the write."""
        import jax
        assert not self._inflight, "page write with groups in flight"
        if self._shard_ok(self.table.capacity):
            # page values arrive committed to ONE device (restore_carry,
            # fresh-carry leaves) — replicate them over the mesh so the
            # scatter into the slot-sharded pool sees one device set
            value_tree = jax.device_put(value_tree,
                                        self._replicated_sharding)
        self._pages = jax.tree_util.tree_map(
            lambda L, v: L.at[page].set(v), self._pages, value_tree)
        self._head_pages = self._pages

    def _page_leaves(self, page: int) -> tuple:
        """One carry page as host leaves ``(leaves, treedef)`` — the same
        leaf contract as ``Pipeline.snapshot_carry`` materialized, so
        ``carry_matches``/``restore_carry`` validate and rebuild it."""
        import jax
        leaves, _ = jax.tree_util.tree_flatten(self._pages)
        treedef = jax.tree_util.tree_flatten(self._fresh_carry())[1]
        return [xfer.to_host(l[page]) for l in leaves], treedef

    def _fresh_host_leaves(self) -> tuple:
        """The fresh-template carry as host leaves: what a still-fresh
        lane's page WILL hold after its first ride — its page bits are
        stale until then, so evict/persist of a fresh lane snapshot the
        template, not the page."""
        import jax
        leaves, treedef = jax.tree_util.tree_flatten(self._fresh_carry())
        return [np.asarray(l) for l in leaves], treedef

    def _session_leaves(self, s: Session) -> tuple:
        if s.slot is not None and s.slot in self._fresh_lanes:
            return self._fresh_host_leaves()
        return self._page_leaves(s.page)

    @property
    def _k_eff(self) -> int:
        """The megabatch K this step runs at: 1 under an active "k"-lever
        brownout (latency over throughput), else the configured K."""
        if self._brownout_active and self._brownout == "k":
            return 1
        return self.k_batch

    def _program(self, capacity: int, k: Optional[int] = None):
        k = self.k_batch if k is None else int(k)
        key = (capacity, k, self._pipe_tag)
        prog = self._programs.get(key)
        if prog is None:
            prog = build_slot_program(self.pipeline, capacity, k)
            self._programs[key] = prog
            self.compiles += 1
            log.info("%s: compiled serving program for slot bucket %d "
                     "(k=%d, %s; resident buckets: %s)", self.app, capacity,
                     k, self._pipe_tag, self.resident_buckets())
        return prog

    def resident_buckets(self) -> List[int]:
        return sorted({cap for cap, _k, _t in self._programs})

    def _cached_buckets(self) -> Optional[tuple]:
        try:
            from ..tpu.autotune import cached_serve_buckets
            got = cached_serve_buckets(self.pipeline, self.pipeline.in_dtype,
                                       self.inst.platform)
            return tuple(got) if got else None
        except Exception:                  # noqa: BLE001 — ladder seed only
            return None

    def _cached_pages(self) -> Optional[int]:
        """The autotune cache's measured page-pool capacity (the
        paged-bucket axis ``serve_pages``), honored only when it names a
        rung of this engine's ladder — a stale cache from a different
        ladder must not invent an uncompilable capacity."""
        try:
            from ..tpu.autotune import cached_serve_pages
            got = cached_serve_pages(self.pipeline, self.pipeline.in_dtype,
                                     self.inst.platform)
            return int(got) if got and int(got) in self.buckets else None
        except Exception:                  # noqa: BLE001 — pool seed only
            return None

    # -- occupancy / bucket growth ---------------------------------------------
    @property
    def capacity(self) -> int:
        return self.table.capacity

    def _grow_to_fit(self) -> None:
        """Called at a QUIESCENT boundary (step lock held, nothing in
        flight, state lock held) with no free slot: grow the page pool to
        the next bucket — append fresh tail pages, extend the table's
        page permutation, re-size the shared credit budget. Resident
        capacities keep their compiled programs untouched; only the new
        capacity compiles, once, on its first dispatch."""
        import jax
        import jax.numpy as jnp
        cur = self.table.capacity
        bigger = [b for b in self.buckets if b > cur]
        if not bigger:
            raise ServeFull(
                f"{self.app}: at the largest slot bucket ({cur}); "
                f"admission refused")
        cap = bigger[0]
        fresh = self._fresh_carry()
        extra = cap - cur
        self._pages = jax.tree_util.tree_map(
            lambda L, f: jnp.concatenate(
                [L, jnp.stack([jnp.asarray(f)] * extra)]),
            self._pages, fresh)
        if self._shard_ok(cap):
            # re-shard the grown pool: the concatenate above computed on
            # whatever sharding the old bucket had (a non-dividing small
            # bucket may have been unsharded) — the new bucket's lanes
            # split one contiguous block per device
            self._pages = jax.device_put(self._pages,
                                         self._slot_sharding)
        self._head_pages = self._pages
        self.table.grow(cap)
        self.credits.set_total(self._queue_frames * cap)
        log.info("%s: page pool grew %d -> %d (active %d)", self.app, cur,
                 cap, self.table.active)

    # -- session lifecycle -----------------------------------------------------
    def _refuse_admission(self, tenant: str) -> None:
        """Lifecycle/overload admission gate (called with the lock held):
        draining and the shedding ladder's first rung both refuse NEW
        admissions — 503 + ``Retry-After`` on the REST plane, billed on
        ``fsdr_serve_shed_total{reason}``."""
        if self._draining:
            _SHED.inc(app=self.app, tenant=tenant, reason="drain")
            _journal.emit("serve", "refuse", app=self.app, tenant=tenant,
                          reason="drain")
            raise ServeDraining(
                f"{self.app}: draining — admission refused")
        if self._ladder.level >= 1:
            _SHED.inc(app=self.app, tenant=tenant, reason="admission")
            _journal.emit("serve", "refuse", app=self.app, tenant=tenant,
                          reason="overload", rung=self._ladder.rung)
            raise ServeOverload(
                f"{self.app}: overloaded (shed rung "
                f"{self._ladder.rung}) — admission refused")

    def admit(self, tenant: str = "default",
              sid: Optional[str] = None) -> Session:
        """Join: claim a lane and bind it a carry page, with a FRESH
        per-session carry. The fast path is a pure host-side page-map edit
        — the fresh template is substituted INSIDE the next dispatch, so a
        join never touches device memory, never waits for in-flight
        groups, and lands at its own frame cursor mid-megabatch. Only pool
        GROWTH (no free page) quiesces the in-flight window. Raises
        :class:`ServeFull` past the largest bucket, :class:`ServeDraining`
        while draining, and :class:`ServeOverload` while the shedding
        ladder is engaged."""
        while True:
            with self._lock:
                self._refuse_admission(tenant)
                if self.table.get(sid) is not None:
                    raise ValueError(f"session id {sid!r} already exists")
                if self.table.free_slots():
                    s = Session(tenant, sid)
                    slot = self.table.admit(s)
                    self._fresh_lanes.add(slot)
                    self.credits.register(s.tenant)
                    _journal.emit("serve", "page-admit", app=self.app,
                                  session=s.sid, tenant=s.tenant, slot=slot,
                                  page=s.page)
                    self._refresh_gauges()
                    return s
            # no free page: growth is page-touching surgery — drain the
            # in-flight window under the step lock, grow the pool once,
            # and retry the map-edit fast path (another admitter may have
            # won the race, which is fine: the re-check sees its free page)
            with self._step_lock:
                self._drain_inflight(0)
                with self._lock:
                    if not self.table.free_slots():
                        self._grow_to_fit()

    def readmit(self, sid: str) -> Session:
        """Re-admit an evicted session: restore its host carry snapshot into
        a page BIT-IDENTICALLY (validated against the fresh-carry template —
        a snapshot that no longer matches the pipeline contract is
        refused). A page write, so the in-flight window drains first."""
        with self._step_lock:
            self._drain_inflight(0)
            with self._lock:
                self._refuse_admission(self._session(sid).tenant)
                s = self._session(sid)
                if s.state != "evicted" or s.carry_leaves is None:
                    raise ValueError(f"session {sid!r} is not evicted "
                                     f"(state={s.state})")
                if not self.pipeline.carry_matches(
                        s.carry_leaves, s.carry_treedef, self._fresh_carry()):
                    raise ValueError(f"session {sid!r}: evicted carry fails "
                                     f"the pipeline contract check")
                if not self.table.free_slots():
                    self._grow_to_fit()
                slot = self.table.admit(s)
                self._set_page(s.page, self.pipeline.restore_carry(
                    s.carry_leaves, s.carry_treedef, self.inst.device))
                s.carry_leaves = None
                s.carry_treedef = None
                s.stall_steps = 0
                _journal.emit("serve", "readmit", app=self.app, session=s.sid,
                              tenant=s.tenant, slot=slot, page=s.page)
                self._refresh_gauges()
                return s

    def evict(self, sid: str) -> Session:
        """Stall handling: snapshot the session's carry page to the host and
        free the lane for a busier session; queued input stays queued. The
        snapshot rides the same leaf contract as the kernel checkpoint
        machinery, so :meth:`readmit` restores it bit-identically. A page
        read, so the in-flight window drains first (a still-fresh lane —
        admitted but never dispatched — snapshots the template instead of
        its stale page bits)."""
        with self._step_lock:
            self._drain_inflight(0)
            return self._evict_quiesced(sid)

    def _evict_quiesced(self, sid: str) -> Session:
        with self._lock:
            s = self._session(sid)
            if s.state != "active":
                raise ValueError(f"session {sid!r} not active "
                                 f"(state={s.state})")
            leaves, treedef = self._session_leaves(s)
            s.carry_leaves = leaves
            s.carry_treedef = treedef
            self._fresh_lanes.discard(s.slot)
            self.table.release_slot(s)
            s.state = "evicted"
            if self._store is not None:
                # evict-to-disk: the host snapshot is already materialized,
                # so the durable copy is a pure background write — a crash
                # between evict and readmit loses nothing
                self._persist_session(s)
            _EVICTIONS.inc(app=self.app, tenant=s.tenant)
            _journal.emit("serve", "evict", app=self.app, session=s.sid,
                          tenant=s.tenant, stall_steps=s.stall_steps)
            self._refresh_gauges()
            return s

    def close(self, sid: str) -> None:
        """Leave: release the lane and forget the session. The lane's stale
        carry is inert (masked) until the next admit overwrites it."""
        with self._lock:
            s = self._session(sid)
            self.credits.release(s.tenant, len(s.pending))
            s.pending.clear()
            if s.slot is not None:
                self._fresh_lanes.discard(s.slot)
            self.table.forget(s)
            s.state = "closed"
            if self._store is not None:
                # clean close: the session's state is complete — purge its
                # durable snapshot so a later incarnation starts it fresh
                self._store.purge(s.sid)
            if not self._tenant_live(s.tenant):
                self.credits.unregister(s.tenant)
            _journal.emit("serve", "close", app=self.app, session=s.sid,
                          tenant=s.tenant)
            self._refresh_gauges()

    def _tenant_live(self, tenant: str) -> bool:
        """Does the tenant still have a session that can submit (active or
        re-admissible)? Retired/closed sessions stay in the registry for
        their views, but they must not keep the tenant's fair share
        reserved in the credit controller."""
        return any(o.tenant == tenant and o.state in ("active", "evicted")
                   for o in self.table.sessions.values())

    def _retire(self, s: Session, err: BaseException) -> None:
        """Per-session fault isolation (the isolate_group-of-one semantics):
        the faulted session's slot is masked off and released — the batch,
        and every sibling's carry and output, is untouched."""
        self.credits.release(s.tenant, len(s.pending))
        s.pending.clear()
        if s.slot is not None:
            self._fresh_lanes.discard(s.slot)
        self.table.release_slot(s)
        s.state = "retired"
        s.error = repr(err)
        if self._store is not None:
            # a faulted session must not resurrect into a fresh incarnation
            self._store.purge(s.sid)
        if not self._tenant_live(s.tenant):
            self.credits.unregister(s.tenant)
        self._retired.append(s.sid)
        while len(self._retired) > self._retired_keep:
            old = self.table.get(self._retired.pop(0))
            if old is not None and old.state == "retired":
                self.table.forget(old)
        _RETIRED.inc(app=self.app, tenant=s.tenant)
        _journal.emit("serve", "retire", app=self.app, session=s.sid,
                      tenant=s.tenant, error=repr(err))
        log.warning("%s: session %s (tenant %s) retired by %r — siblings "
                    "unaffected", self.app, s.sid, s.tenant, err)
        self._refresh_gauges()

    def _session(self, sid: str) -> Session:
        s = self.table.get(sid)
        if s is None:
            raise KeyError(f"no session {sid!r}")
        return s

    # -- the data plane --------------------------------------------------------
    def submit(self, sid: str, frame: np.ndarray) -> bool:
        """Queue one input frame for ``sid``. Returns False (backpressure)
        when the tenant's fair credit share is exhausted — a stalled tenant
        cannot starve siblings of queue budget (docs/serving.md)."""
        with self._lock:
            s = self._session(sid)
            if s.state in ("retired", "closed"):
                raise ValueError(f"session {sid!r} is {s.state}")
            frame = np.asarray(frame)
            if frame.shape != (self.frame_size,):
                raise ValueError(
                    f"frame shape {frame.shape} != ({self.frame_size},)")
            if not self.credits.try_acquire(s.tenant):
                _REJECTS.inc(app=self.app, tenant=s.tenant)
                return False
            s.pending.append((np.ascontiguousarray(
                frame, dtype=self.pipeline.in_dtype), time.perf_counter_ns()))
            s.frames_in += 1
            return True

    def results(self, sid: str) -> list:
        """Drain the session's decoded results (oldest first)."""
        with self._lock:
            s = self._session(sid)
            out, s.out = list(s.out), type(s.out)()
            return out

    def step(self) -> int:
        """One frame-time dispatch: every active lane with pending frames
        rides ONE vmapped program call — one H2D of the stacked batch, one
        dispatch, one D2H per sink, regardless of the active session count.
        ``frames_per_dispatch > 1`` additionally megabatches up to k queued
        frames PER LANE through the in-program scan, ragged per lane (a
        session with fewer queued frames masks its tail; a JOINING session
        rides with whatever frames it has — the fresh-page substitution
        lands it at its own cursor mid-megabatch).

        OVERLAPPED (docs/serving.md "The overlapped step"): the group
        launched here is committed only once its D2H lands; with
        ``serve_inflight > 1`` up to that many groups ride concurrently,
        so H2D(t+1) ∥ compute(t) ∥ D2H(t−1). The state lock is held only
        for batch assembly and commit bookkeeping — never across the
        compile, the transfers, or the program call — so /metrics,
        ``health()`` and ``describe()`` answer mid-step.

        Returns the number of session-frames LAUNCHED this step. An idle
        step (no lane has pending input) first commits everything still in
        flight, then returns 0 — so a pump loop's
        ``while eng.step(): pass`` still means "fully drained"."""
        # fleet hot-path hook (telemetry/fleet.py): refresh this host's own
        # fleet gauges at poll cadence. ONE falsy check when the fleet
        # plane is disabled — the guard is INLINE (a module-global read, no
        # call frame) so the disabled cost matches the park guard's; it is
        # the sixth per-call hook class the telemetry overhead gate bills
        # (tests/test_telemetry.py). Outside the engine locks by design:
        # the refresh reads only lock-free surfaces
        if _fleet._tick_state is not None:
            _fleet.tick()
        with self._step_lock:
            g = self._assemble()
            if g is None:
                self._drain_inflight(0)
                with self._lock:
                    if self._ladder.level:
                        # traffic stopped while the ladder was engaged: idle
                        # steps count as healthy observations so admissions
                        # reopen. idle=True: the latency window is FROZEN
                        # with the pre-idle samples, so the SLO term must
                        # not read a stale p99 as a live miss and ratchet
                        # the ladder up on an empty engine
                        self._overload_tick(idle=True)
                return 0
            try:
                self._launch(g)
            except Exception:
                # launch-failure rollback: a transfer/compile/dispatch error
                # must not silently drop the popped frames — re-queue them
                # at the front of their queues (original order), re-take
                # their credits, restore the fresh bits. The head never
                # advanced (launch's last effect), so older in-flight
                # groups stay valid and the caller's retry re-dispatches
                # the exact same frames
                self._rollback([g], reset_head=False)
                raise
            self._inflight.append(g)
            self._flight.note_dispatch(g.wire, len(self._inflight))
            n = g.n_frames
            self._drain_inflight(self._depth_limit() - 1)
            return n

    def _depth_limit(self) -> int:
        """The in-flight group budget this step: the flight controller's
        live credits, collapsed to 1 while the shed ladder is at or above
        the latency rung — an overloaded engine prefers per-frame latency
        over pipelining, the same trade as the ``"k"`` brownout lever."""
        if self._ladder.level >= _LATENCY_RUNG:
            return 1
        return max(1, int(self._flight.credits))

    def _assemble(self) -> Optional[_DispatchGroup]:
        """Build this step's dispatch group under the state lock: pop up to
        K pending frames per occupied lane into the stacked batch, snapshot
        the lane→page permutation and the fresh-lane vector, and CLEAR the
        fresh bits — the launch materializes those lanes' template pages
        (rollback restores the bits). Returns None on an idle step."""
        with self._lock:
            C = self.table.capacity
            K = self._k_eff
            fplan = _faults.plan()
            lanes: List[tuple] = []   # (session, lane, popped, tids)
            # serving-plane spans (docs/serving.md "Observability"): the
            # batch assembly is the serving path's encode lane; the H2D/D2H
            # lanes are emitted by the async transfer finishes themselves
            # (ops/xfer.py), so the doctor's interval-union lanes show the
            # REAL wire concurrency of the overlapped step
            t_step = _trace.now() if _trace.enabled else 0
            t_enc = t_step
            # idle frame-time ticks (no lane has pending input — the common
            # case for a pump loop ticking at frame rate) must cost nothing:
            # the batch/mask arrays allocate lazily on the first busy lane
            batch = None
            active = None
            step_tids: List[int] = []     # lineage-sampled frames this step
            for s in self.table.occupants():
                if not s.pending:
                    s.stall_steps += 1
                    continue
                if batch is None:
                    shape = (C, self.frame_size) if K == 1 \
                        else (C, K, self.frame_size)
                    batch = np.zeros(shape, dtype=self.pipeline.in_dtype)
                    active = np.zeros((C,) if K == 1 else (C, K), dtype=bool)
                if fplan.armed():
                    # per-session fault sites (runtime/faults.py): address a
                    # work/dispatch injector at ONE session id and only that
                    # slot retires — the tenant-isolation chaos scenario
                    try:
                        fplan.maybe("work", s.sid)
                        fplan.maybe("dispatch", s.sid)
                    except _faults.InjectedFault as e:
                        self._retire(s, e)
                        continue
                popped = []
                tids = []
                for j in range(min(K, len(s.pending))):
                    entry = s.pending.popleft()
                    frame, t_sub = entry
                    self.credits.release(s.tenant)
                    if K == 1:
                        batch[s.slot] = frame
                        active[s.slot] = True
                    else:
                        batch[s.slot, j] = frame
                        active[s.slot, j] = True
                    popped.append(entry)
                    # frame lineage (telemetry/lineage.py): 1-in-stride
                    # sampled frames get a trace id here; unsampled frames
                    # carry tid 0 and every stamp site below skips them
                    tid = _lineage.tracer().sample()
                    if tid:
                        _lineage.tracer().stamp(tid, "ingest", t_sub)
                        step_tids.append(tid)
                    tids.append(tid)
                s.stall_steps = 0
                lanes.append((s, s.slot, popped, tids))
            self.steps += 1
            if not lanes:
                return None
            if t_enc:
                _trace.complete("tpu", "encode", t_enc,
                                args={"sessions": len(lanes),
                                      "capacity": C})
            if step_tids:
                lin = _lineage.tracer()
                for tid in step_tids:
                    lin.stamp(tid, "encode")
            # the fresh vector covers EVERY fresh lane, busy or not: its
            # first ride writes the template to its page either way, so
            # the page is real from this group on
            fresh = np.zeros((C,), dtype=bool)
            for lane in self._fresh_lanes:
                if lane < C:
                    fresh[lane] = True
            g = _DispatchGroup(
                C, K, lanes, batch, active, fresh,
                np.asarray(self.table.page_of_lane, dtype=np.int32),
                frozenset(self._fresh_lanes), step_tids, t_step)
            self._fresh_lanes.clear()
            return g

    def _launch(self, g: _DispatchGroup) -> None:
        """Launch one assembled group OUTSIDE the state lock (step lock
        held): program lookup/compile, async H2D starts, the paged program
        call against the speculative head, async D2H starts. Advancing the
        head is the LAST effect — a failure anywhere above leaves the
        chain exactly as it was for the rollback path."""
        C, K = g.capacity, g.k
        prog = self._program(C, K)
        fx = self._start_h2d(g.batch, shard=True)
        fa = self._start_h2d(g.active, shard=True)
        fm = self._start_h2d(g.page_map, shard=False)
        ff = self._start_h2d(g.fresh, shard=False)
        x, act = fx(), fa()
        pmap, fresh = fm(), ff()
        g.wire = getattr(fx, "_wire", None)
        if g.step_tids:
            lin = _lineage.tracer()
            for tid in g.step_tids:
                lin.stamp(tid, "H2D")
        t0 = _trace.now() if _trace.enabled else 0
        key = (C, K, self._pipe_tag)
        if key in self._warmed:
            new_pages, outs = prog(self._head_pages, pmap, fresh, x, act)
        else:
            # a capacity's FIRST dispatch pays its jit compile: bill it
            # (fsdr_compiles_total{reason="serve_bucket"}) and mark the
            # window active so a slow compile reads as "compiling" to the
            # doctor, never as a stalled serving loop
            with _profile.compiling(f"serve:{self.app}", "serve_bucket",
                                    f"cap={C},k={K},"
                                    f"frame={self.frame_size},"
                                    f"pipe={self._pipe_tag}"):
                new_pages, outs = prog(self._head_pages, pmap, fresh, x, act)
            self._warmed.add(key)
        if t0:
            _trace.complete("tpu", "compute", t0,
                            args={"capacity": C,
                                  "active_lanes": len(g.lanes)})
        if g.step_tids:
            lin = _lineage.tracer()
            for tid in g.step_tids:
                lin.stamp(tid, "dispatch")
        g.fins = [xfer.start_host_transfer(o) for o in outs]
        g.new_pages = new_pages
        self._head_pages = new_pages

    def _start_h2d(self, arr: np.ndarray, shard: bool):
        """Start one async H2D for a group launch; returns a finish thunk.
        Unsharded buckets ride ``xfer.start_device_transfer``, whose finish
        models/measures the wire window (the ``_wire`` attribute feeding
        the flight controller) and emits the H2D trace span — the serving
        overlap evidence. Slot-sharded buckets place synchronously
        (``device_put`` owns the mesh layout)."""
        if self._shard_ok(self.table.capacity):
            import jax
            v = jax.device_put(arr, self._slot_sharding if shard
                               else self._replicated_sharding)
            return lambda: v
        return xfer.start_device_transfer(arr, self.inst.device)

    def _drain_inflight(self, keep: int) -> None:
        """Commit in-flight groups oldest-first until at most ``keep``
        remain (step lock held; the state lock is NOT held across the D2H
        wait). ``keep=0`` is the quiescent barrier page-touching surgery
        uses. A failed wait rolls back EVERY uncommitted group — each
        younger group derived its pages from the failed one's output, so
        none of them can commit."""
        keep = max(0, int(keep))
        while len(self._inflight) > keep:
            if keep:
                self._flight.note_limited()
            g = self._inflight[0]
            try:
                host = [np.asarray(f()) for f in g.fins]
            except Exception:
                doomed = list(self._inflight)
                self._inflight.clear()
                self._rollback(doomed, reset_head=True)
                raise
            self._inflight.popleft()
            self._commit(g, host)

    def _rollback(self, groups: list, reset_head: bool) -> None:
        """Re-queue every frame of the given UNCOMMITTED groups at the
        front of their sessions' queues (youngest group first, preserving
        order), re-take their credits and restore their fresh-lane bits —
        the retry re-dispatches the exact same frames. ``reset_head``: a
        drain failure abandons the whole speculative chain, so the head
        re-syncs to the committed pool; a LAUNCH failure never advanced
        the head, which must stay at the older in-flight groups' output."""
        with self._lock:
            for g in reversed(groups):
                for s, _lane, popped, _tids in g.lanes:
                    if s.state not in ("active", "evicted"):
                        continue          # closed/retired meanwhile: its
                    s.pending.extendleft(reversed(popped))   # credits were
                    self.credits.reacquire(s.tenant, len(popped))  # released
                self._fresh_lanes |= g.fresh_lanes
            if reset_head:
                self._head_pages = self._pages

    def _commit(self, g: _DispatchGroup, host: list) -> None:
        """Land one finished group (its D2H already waited out): the
        committed pool advances to its output pages, results fan back per
        session, latency/lineage/persist/overload bookkeeping runs — all
        under the state lock. A session that left while its group was in
        flight (closed/retired, or its lane re-bound) is skipped: there is
        nobody to deliver to."""
        end = time.perf_counter_ns()
        t_dec = _trace.now() if _trace.enabled else 0
        K = g.k
        with self._lock:
            self._pages = g.new_pages
            self.dispatches += 1
            dispatched = 0
            for s, lane, popped, tids in g.lanes:
                deliver = s.state == "active" and s.slot == lane
                if not deliver:
                    continue
                for j, (_, t_sub) in enumerate(popped):
                    rows = [h[lane] if K == 1 else h[lane, j] for h in host]
                    res = tuple(np.asarray(r) for r in rows) \
                        if self._multi else np.asarray(rows[0])
                    s.out.append(res)
                    s.frames_out += 1
                    lat = (end - t_sub) * 1e-9
                    s.last_latency_s = lat
                    self._lat_recent.append(lat)
                    _LATENCY.observe(lat, app=self.app, tenant=s.tenant)
                    # satellite of PR-4's stamp audit: each serving lane
                    # observes its OWN frame's submit->fan-back latency on
                    # the shared e2e family (the streamed sinks' histogram)
                    self._e2e_hist.observe(lat)
                    tid = tids[j]
                    if tid:
                        lin = _lineage.tracer()
                        lin.stamp(tid, "emit", end)
                        lin.finish(tid, source=f"serve:{self.app}",
                                   session=s.sid, tenant=s.tenant)
                        self._e2e_hist.exemplar(lat, tid)
                    _FRAMES.inc(app=self.app, tenant=s.tenant)
                    dispatched += 1
            self.frames += dispatched
            _DISPATCHES.inc(app=self.app)
            self._step_stamps.append(time.monotonic())
            if self._persist_every and self._store is not None:
                self._steps_since_persist += 1
                if self._steps_since_persist >= self._persist_every:
                    self._steps_since_persist = 0
                    self._persist_all()
            self._overload_tick()
            # live-roofline unit for serving: one SESSION-FRAME (the
            # registered cost is the single-lane program's); the commit
            # stamps its own group time
            self._prof.dispatch(dispatched, t=time.monotonic())
        if t_dec:
            _trace.complete("tpu", "decode", t_dec,
                            args={"frames": dispatched})
        if g.t_step:
            _trace.complete("serve", "serve_step", g.t_step,
                            args={"sessions": len(g.lanes),
                                  "active_lanes": len(g.lanes),
                                  "frames": dispatched,
                                  "capacity": g.capacity})

    # -- lane-addressed retunes ------------------------------------------------
    def retune(self, sid: str, stage, **params) -> Session:
        """Per-session mid-stream surgery: apply ``update_stage`` to ONE
        session's carry page at its next quiescent boundary (the in-flight
        window drains first), journaled as ``serve/lane-retune`` — one
        tenant retunes its receiver without touching a sibling's bits.
        ``stage`` addresses by name or index, ``params`` are the stage's
        ``update`` hook kwargs (the flat-carry contract of
        ``ops/stages.py``). Raises KeyError for an unknown session,
        ValueError for a non-active session or a refused update."""
        import jax
        with self._step_lock:
            self._drain_inflight(0)
            with self._lock:
                s = self._session(sid)
                if s.state != "active":
                    raise ValueError(f"session {sid!r} not active "
                                     f"(state={s.state})")
                page = s.page
                if s.slot in self._fresh_lanes:
                    # never dispatched: its page holds stale bits — retune
                    # the template it WILL start from, and materialize it
                    lane_carry = self._fresh_carry()
                else:
                    lane_carry = jax.tree_util.tree_map(
                        lambda P: P[page], self._pages)
                try:
                    new_carry = self.pipeline.update_stage(lane_carry, stage,
                                                           **params)
                except KeyError as e:
                    # a bad STAGE address is a client error on this app's
                    # contract (409), not a missing resource (404 is the
                    # session lookup's) — re-raise in the ValueError family
                    raise ValueError(f"retune of {sid!r}: {e}") from e
                self._set_page(page, new_carry)
                self._fresh_lanes.discard(s.slot)
                _journal.emit("serve", "lane-retune", app=self.app,
                              session=s.sid, tenant=s.tenant, slot=s.slot,
                              page=page, stage=str(stage),
                              params=sorted(params))
                log.info("%s: lane retune of %s (slot %d, page %d): "
                         "stage=%r params=%s", self.app, s.sid, s.slot,
                         page, stage, sorted(params))
                return s

    # -- durable session state (docs/robustness.md "Serving-plane recovery") --
    def _base_leaf_dtypes(self) -> list:
        """The BASE pipeline's flat carry leaf dtypes — the dtype contract
        every durable snapshot is written in, whatever the live program
        runs at (a brownout-lowered bf16 carry persisted as-is would fail
        ``carry_matches`` in the next incarnation and lose the session)."""
        if self._base_dt is None:
            import jax
            leaves = jax.tree_util.tree_flatten(
                self._base_pipeline.init_carry())[0]
            self._base_dt = [np.dtype(getattr(l, "dtype", "float32"))
                             for l in leaves]
        return self._base_dt

    def _persist_session(self, s: Session, sync: bool = False) -> None:
        """Queue one session's durable snapshot (state lock held). Active
        lanes capture their PAGE of the committed pool and fetch its host
        leaves off the step thread; evicted sessions already hold host
        leaves. Leaves are written in the BASE pipeline's dtypes (upcast
        when the precision brownout is live), so a kill -9 at any rung
        restores into a fresh base-pipeline incarnation."""
        import jax
        meta = {"sid": s.sid, "tenant": s.tenant,
                "frames_in": s.frames_in, "frames_out": s.frames_out}
        dts = self._base_leaf_dtypes()
        # a lane whose FIRST dispatch is still riding an in-flight group is
        # fresh too: assembly moved it out of ``_fresh_lanes`` (the program
        # does the template substitution in-flight) but the committed pool's
        # page still holds whatever a dead predecessor parked there — the
        # meta says frames_out=0, so the snapshot must say "start fresh"
        fresh_lane = s.slot is not None and (
            s.slot in self._fresh_lanes or
            any(s.slot in g.fresh_lanes for g in self._inflight))
        if s.state == "active" and fresh_lane:
            # admitted but never dispatched: its page holds stale bits —
            # the durable snapshot is the fresh template it will start from
            snap = self._fresh_host_leaves()[0]

            def fetch(_snap=snap, _dts=dts):
                raw = [np.asarray(a) for a in _snap]
                if len(raw) == len(_dts):
                    raw = [a if a.dtype == dt else a.astype(dt)
                           for a, dt in zip(raw, _dts)]
                return raw
        elif s.state == "active" and s.slot is not None:
            # page-granular capture: a reference to the COMMITTED pool's
            # leaves + this session's page index — the serving program
            # never donates, so the writer thread reads stable device
            # arrays even while later commits replace ``self._pages``
            leaves = jax.tree_util.tree_flatten(self._pages)[0]
            page = s.page

            def fetch(_leaves=leaves, _page=page, _dts=dts):
                raw = [np.asarray(xfer.to_host(l[_page])) for l in _leaves]
                if len(raw) == len(_dts):
                    raw = [a if a.dtype == dt else a.astype(dt)
                           for a, dt in zip(raw, _dts)]
                return raw
        elif s.state == "evicted" and s.carry_leaves is not None:
            snap = list(s.carry_leaves)

            def fetch(_snap=snap, _dts=dts):
                raw = [np.asarray(a) for a in _snap]
                if len(raw) == len(_dts):
                    raw = [a if a.dtype == dt else a.astype(dt)
                           for a, dt in zip(raw, _dts)]
                return raw
        else:
            return
        self._store.save(s.sid, fetch, meta, sync=sync)

    def _persist_all(self, sync: bool = False) -> int:
        """Snapshot every live (active/evicted) session (lock held).
        ``sync`` enqueues everything first and rides ONE flush barrier —
        every write still lands on the single-writer executor (two writer
        threads would tear the shared pid-keyed tmp file)."""
        n = 0
        for s in self.table.sessions.values():
            if s.state in ("active", "evicted"):
                self._persist_session(s)
                n += 1
        if sync and n and self._store is not None:
            self._store.flush()
        return n

    def flush_persist(self) -> None:
        """Barrier on the persistence executor: every snapshot queued before
        this call is durable after it (tests + pre-restart hooks)."""
        if self._store is not None:
            self._store.flush()

    def _restore_persisted(self) -> None:
        """Virgin-incarnation restore: re-admit every persisted session of
        this app+pipeline-signature bit-identically (the ``carry_matches``-
        validated readmit path). Corrupted files were already skipped by the
        store's reader; a snapshot failing the carry contract (pipeline
        changed under the same app name — the signature hash makes this
        near-impossible, but the check is cheap) is skipped per-session.
        Sessions beyond the largest bucket are left on disk (logged) — a
        smaller replacement deployment refuses gracefully instead of
        refusing to boot."""
        import jax
        records = self._store.load_all()
        if not records:
            return
        with self._lock:
            fresh = self._fresh_carry()
            treedef = jax.tree_util.tree_flatten(fresh)[1]
            skipped = 0
            for r in records:
                if self.table.get(r["sid"]) is not None:
                    continue
                if not self.pipeline.carry_matches(r["leaves"], treedef,
                                                   fresh):
                    log.warning("%s: persisted session %s fails the carry "
                                "contract — skipped", self.app, r["sid"])
                    skipped += 1
                    continue
                if not self.table.free_slots():
                    try:
                        self._grow_to_fit()
                    except ServeFull:
                        log.warning("%s: %d persisted session(s) exceed the "
                                    "largest slot bucket — left on disk",
                                    self.app,
                                    len(records) - self.restored_sessions
                                    - skipped)
                        break
                s = Session(r["tenant"], r["sid"])
                self.table.admit(s)
                self._set_page(s.page, self.pipeline.restore_carry(
                    r["leaves"], treedef, self.inst.device))
                s.frames_in = r["frames_in"]
                s.frames_out = r["frames_out"]
                self.credits.register(s.tenant)
                self.restored_sessions += 1
                _RESUMED.inc(app=self.app, tenant=s.tenant)
            self._refresh_gauges()
        if self.restored_sessions:
            _journal.emit("serve", "restore", app=self.app,
                          sessions=self.restored_sessions, skipped=skipped)
            log.info("%s: re-admitted %d persisted session(s) after a "
                     "process restart (%d skipped)", self.app,
                     self.restored_sessions, skipped)
            # warm the current bucket NOW: a restored pod must turn ready
            # (readyz 200) without waiting for traffic — restored sessions
            # have no pending frames, so no busy step would ever compile
            # the program and the pod would sit NotReady forever
            try:
                with self._lock:
                    self._warm_current_bucket()
            except Exception as e:         # noqa: BLE001 — a failed warmup
                log.warning("%s: restore warmup failed: %r", self.app, e)

    def _warm_current_bucket(self) -> None:
        """Compile + warm the current capacity's program with an ALL-MASKED
        no-op dispatch (lock held): every lane inactive and nothing fresh,
        so the in-program merge + permutation scatter keeps the restored
        pages bit-identical (the returned pool is discarded anyway) — the
        dispatch exists only to pay the jit compile before the orchestrator
        routes traffic. Billed ``serve_bucket`` like any first dispatch."""
        import jax
        C, K = self.table.capacity, self._k_eff
        key = (C, K, self._pipe_tag)
        if key in self._warmed:
            return
        prog = self._program(C, K)
        shape = (C, self.frame_size) if K == 1 else (C, K, self.frame_size)
        batch = np.zeros(shape, dtype=self.pipeline.in_dtype)
        active = np.zeros((C,) if K == 1 else (C, K), dtype=bool)
        pmap = np.asarray(self.table.page_of_lane, dtype=np.int32)
        no_fresh = np.zeros((C,), dtype=bool)
        with _profile.compiling(f"serve:{self.app}", "serve_bucket",
                                f"cap={C},k={K},frame={self.frame_size},"
                                f"pipe={self._pipe_tag},warm=restore"):
            # _start_h2d, not bare to_device: a slot-sharded bucket's
            # pages are committed to the mesh, and a single-device batch
            # would make the warm dispatch raise (and the first real step
            # pay a second, unbilled compile)
            _new_p, outs = prog(self._pages,
                                self._start_h2d(pmap, shard=False)(),
                                self._start_h2d(no_fresh, shard=False)(),
                                self._start_h2d(batch, shard=True)(),
                                self._start_h2d(active, shard=True)())
            jax.block_until_ready(outs)
        self._warmed.add(key)

    # -- graceful lifecycle ----------------------------------------------------
    def drain(self, pump: bool = True, timeout: float = 30.0,
              persist: bool = True) -> dict:
        """Graceful shutdown for rolling restarts: refuse new admissions
        (:class:`ServeDraining` → 503 + ``Retry-After``), finish the
        in-flight megabatch groups and every queued frame (``pump=True``
        steps the engine here; an app with its own pump thread passes
        ``pump=False`` and keeps stepping), persist all live lanes, and
        report drained. Idempotent — a second call re-reports."""
        with self._lock:
            self._draining = True
        _journal.emit("serve", "drain", app=self.app,
                      timeout_s=float(timeout), persist=bool(persist))
        pumped = 0
        deadline = (time.monotonic() + float(timeout)) if timeout else None
        if pump:
            while True:
                if deadline is not None and time.monotonic() > deadline:
                    log.warning("%s: drain timed out with frames still "
                                "queued", self.app)
                    break
                got = self.step()
                pumped += got
                if not got:
                    # no lane dispatched anything: every ACTIVE queue is
                    # empty. Frames may remain on evicted sessions' queues
                    # — those cannot dispatch without a readmit, which
                    # draining refuses, so there is nothing left to finish
                    # (the report's pending_frames counts them honestly)
                    break
        persisted = 0
        if persist and self._store is not None:
            # step lock first: the final persist must read the COMMITTED
            # pool with nothing speculative in flight, and a brownout
            # release is page-dtype surgery
            with self._step_lock:
                self._drain_inflight(0)
                with self._lock:
                    if self._brownout_active:
                        # release the brownout before the final persist: the
                        # snapshots must land in the base dtype contract (the
                        # per-write upcast covers a kill -9; a graceful drain
                        # hands the NEXT incarnation full-precision carries)
                        self._set_brownout(False)
                    persisted = self._persist_all(sync=True)
        with self._lock:
            leftover = sum(len(s.pending) for s in self.table.sessions.values())
            self._drained = True
            report = {
                "app": self.app,
                "draining": True,
                "drained": True,
                "frames_drained": pumped,
                "pending_frames": leftover,
                "sessions_persisted": persisted,
                "sessions": len(self.table.sessions),
            }
        _journal.emit("serve", "drained", app=self.app,
                      frames_drained=pumped, sessions_persisted=persisted,
                      pending_frames=report["pending_frames"])
        log.info("%s: drained — %d frame(s) finished, %d session(s) "
                 "persisted, %d frame(s) left queued", self.app, pumped,
                 persisted, report["pending_frames"])
        return report

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def drained(self) -> bool:
        return self._drained

    def retry_after_s(self) -> int:
        """``Retry-After`` seconds for a 503 (ServeFull/draining/overload),
        derived from the measured step rate: roughly how long until one
        queue-depth's worth of frames drains. Clamped to [1, 30].

        LOCK-FREE by design: the REST error path calls this on the aiohttp
        event loop, and step() holds the engine lock across an entire
        dispatch — including a new bucket's multi-second jit compile.
        Taking the lock here would freeze every control-port route (incl.
        /healthz) for that long. ``list(deque)`` under the GIL is safe
        against a concurrent append; ``_queue_frames`` is immutable."""
        stamps = list(self._step_stamps)
        qf = self._queue_frames
        if len(stamps) >= 2 and stamps[-1] > stamps[0]:
            rate = (len(stamps) - 1) / (stamps[-1] - stamps[0])
            est = qf / max(rate, 1e-3)
        else:
            est = 1.0
        return int(min(30, max(1, math.ceil(est))))

    def health(self) -> dict:
        """Liveness/readiness view for ``/healthz``/``/readyz``
        (docs/serving.md "Lifecycle"): ready = the CURRENT bucket's program
        has dispatched (compiled) — or nothing is admitted yet — and the
        engine is not draining. The readiness endpoint additionally refuses
        while the profile plane reports a serving-program compile storm.

        LOCK-FREE like :meth:`retry_after_s`: readyz runs on the aiohttp
        event loop, and while the overlapped step keeps the STATE lock
        narrow, a stepper can still be inside a capacity's first jit
        compile — exactly when an orchestrator probes hardest. Plain
        attribute/set reads under the GIL give an at-most-one-step-stale
        answer, which is all a probe needs; blocking here would freeze
        /healthz too and get a healthy pod killed mid-compile."""
        key = (self.table.capacity, self._k_eff, self._pipe_tag)
        active = self.table.active
        compiled = active == 0 or key in self._warmed
        return {"ready": bool(compiled and not self._draining),
                "compiled": bool(compiled),
                "draining": self._draining,
                "drained": self._drained,
                "shed_level": self._ladder.level,
                "shed_rung": self._ladder.rung,
                "active": active,
                "capacity": self.table.capacity}

    def watch_sample(self) -> Optional[dict]:
        """Cheap progress probe for the doctor's serve watchdog. Returns
        None when the engine lock is busy — a step() in flight IS progress
        (or a compile, which the doctor's ``compiling`` verdict explains),
        so the watchdog must not strike on it."""
        if not self._lock.acquire(timeout=0.05):
            return None
        try:
            stuck = sorted((s for s in self.table.occupants() if s.pending),
                           key=lambda s: -len(s.pending))
            return {"app": self.app,
                    "frames": self.frames,
                    "pending": sum(len(s.pending) for s in
                                   self.table.occupants()),
                    "draining": self._draining,
                    "capacity": self.table.capacity,
                    "active": self.table.active,
                    "shed_level": self._ladder.level,
                    "stuck_sessions": [s.sid for s in stuck[:4]]}
        finally:
            self._lock.release()

    def shutdown(self) -> None:
        """Detach from the doctor and stop persisting. Does NOT drain —
        call :meth:`drain` first for a graceful handoff."""
        if self._doctor_token is not None:
            try:
                from ..telemetry import doctor as _doctor
                _doctor.doctor().detach_serve(self._doctor_token)
            except Exception:                          # noqa: BLE001
                pass
            self._doctor_token = None

    # -- SLO-aware overload control (serve/overload.py) ------------------------
    def _overload_tick(self, idle: bool = False) -> None:
        """One shedding-ladder observation (lock held, busy steps + engaged
        idle steps): queue pressure vs the watermarks, rolling p99 vs the
        ``serve_slo_ms`` deadline budget. Escalations act on the transition
        — rung 2 evicts the most-stalled sessions, rung 3 engages the
        optional brownout lever; recovery unwinds one rung at a time.
        ``idle`` ticks skip the SLO term: the latency window holds only
        pre-idle samples, and a frozen p99 must read as "no current miss",
        not as a live violation that keeps escalating an empty engine.

        Re-entrant commits are guarded: a rung-2 shed evicts, eviction
        drains the in-flight window, and each nested commit would tick the
        ladder again mid-action — the ``_ticking`` flag makes the nested
        calls no-ops (the ladder loses one observation, not its
        hysteresis)."""
        if self._ticking:
            return
        p99_ms = None
        if self._slo_ms and self._lat_recent and not idle:
            p99_ms = float(np.quantile(
                np.asarray(self._lat_recent), 0.99)) * 1e3
        prev = self._ladder.level
        lvl = self._ladder.observe(self.credits.pressure(), p99_ms,
                                   self._slo_ms)
        if lvl == prev:
            return
        _SHED_LEVEL.set(float(lvl), app=self.app)
        # the shed-rung TRANSITION is the journal event (the gauge holds the
        # current level; the journal tells the story in seq order)
        _journal.emit("serve", "shed-rung", app=self.app,
                      level=lvl, prev=prev, rung=self._ladder.rung,
                      pressure=round(self.credits.pressure(), 4),
                      p99_ms=round(p99_ms, 3) if p99_ms is not None
                      else None)
        self._ticking = True
        try:
            if lvl > prev:
                log.warning("%s: overload ladder escalated to rung %d (%s) "
                            "— pressure %.2f, p99 %s ms (SLO %s)", self.app,
                            lvl, self._ladder.rung, self.credits.pressure(),
                            f"{p99_ms:.1f}" if p99_ms is not None else "-",
                            self._slo_ms or "-")
                if lvl >= 2:
                    self._shed_stalled()
                if lvl >= 3 and self._brownout != "off":
                    self._set_brownout(True)
            else:
                log.info("%s: overload ladder recovered to rung %d (%s)",
                         self.app, lvl, self._ladder.rung)
                if lvl < 3 and self._brownout_active:
                    self._set_brownout(False)
        finally:
            self._ticking = False

    def _shed_stalled(self) -> None:
        """Rung 2: evict the most-stalled sessions (no queued input, most
        consecutive inputless steps first) to host/disk — frees their lanes
        without touching a single resident bit (the evict/readmit leaf
        contract is bit-identical). At most a quarter of the active lanes
        per escalation, so one rung transition cannot empty the table."""
        cands = sorted((s for s in self.table.occupants()
                        if s.stall_steps >= 1 and not s.pending),
                       key=lambda s: -s.stall_steps)
        for s in cands[:max(1, self.table.active // 4)]:
            try:
                self.evict(s.sid)
            except (KeyError, ValueError) as e:
                log.warning("%s: shed-evict of %s failed: %r", self.app,
                            s.sid, e)
                continue
            self.shed_evictions += 1
            _SHED.inc(app=self.app, tenant=s.tenant, reason="evict")
            log.warning("%s: shed-evicted stalled session %s (tenant %s, "
                        "%d stalled steps)", self.app, s.sid, s.tenant,
                        s.stall_steps)

    def _set_brownout(self, on: bool) -> None:
        """Rung 3 (config ``serve_brownout``, default off): trade quality
        for headroom on resident buckets — ``"k"`` drops megabatch K to 1
        (per-dispatch latency over throughput; K>1 vs K=1 round differently
        by repo contract), ``"precision"`` retunes the interior to the
        configured ``serve_brownout_precision`` mode (bf16 default, or the
        deeper int8 rung) via ``ops/precision.py`` (SNR-bounded loss for
        the duration). Both
        compile their program form once (billed ``serve_bucket``) and keep
        the base programs cached — recovery never recompiles."""
        if on == self._brownout_active:
            return
        if self._brownout == "precision":
            # page-dtype surgery: every in-flight group was launched with
            # the OLD program form and must commit before the pool converts
            # (callers hold the step lock — the overload tick runs on the
            # step thread, drain takes it explicitly)
            self._drain_inflight(0)
            if not self._apply_precision_brownout(on):
                return
        self._brownout_active = on
        _journal.emit("serve", "brownout", app=self.app,
                      engaged=bool(on), lever=self._brownout)
        if on:
            _SHED.inc(app=self.app, tenant="-", reason="brownout")
        log.warning("%s: brownout lever (%s) %s", self.app, self._brownout,
                    "ENGAGED" if on else "released")

    def _apply_precision_brownout(self, on: bool) -> bool:
        """Swap the served pipeline between the base and the lowered form
        (``serve_brownout_precision``: bf16, or the deeper int8 rung),
        converting the stacked carries leaf-by-leaf (narrowing casts;
        widening upcasts the live values — the brownout's documented,
        bounded quality loss for its duration; int8 stages carry FLOAT
        weights and quantize in-trace, so their leaves convert as plain
        dtype casts like any other). Returns False (logged, no state
        change) when nothing lowers or the carry trees refuse."""
        import jax
        prev_pipe = self.pipeline
        if on:
            if self._low_pipe is None:
                try:
                    from ..ops import precision as _precision_mod
                    low, plan = _precision_mod.plan_interior_precision(
                        self._base_pipeline, mode=self._brownout_prec)
                except Exception as e:                 # noqa: BLE001
                    log.warning("%s: precision brownout plan failed (%r) — "
                                "lever disabled", self.app, e)
                    return False
                if low is self._base_pipeline:
                    log.warning("%s: precision brownout lowers nothing — "
                                "lever disabled", self.app)
                    return False
                self._low_pipe = low
            target, tag = self._low_pipe, self._brownout_prec
        else:
            target, tag = self._base_pipeline, "base"
        if target is self.pipeline:
            self._pipe_tag = tag
            return True
        old_leaves, old_def = jax.tree_util.tree_flatten(self._pages)
        self.pipeline = target
        self._fresh = None
        stacked = self._stacked_fresh(self.table.capacity)
        t_leaves, t_def = jax.tree_util.tree_flatten(stacked)
        if old_def != t_def or any(
                np.shape(a) != np.shape(b)
                for a, b in zip(old_leaves, t_leaves)):
            log.warning("%s: precision brownout carry trees mismatch — "
                        "lever disabled", self.app)
            self.pipeline = prev_pipe
            self._fresh = None
            return False
        conv = [a if getattr(a, "dtype", None) == getattr(b, "dtype", None)
                else a.astype(b.dtype)
                for a, b in zip(old_leaves, t_leaves)]
        self._pages = jax.tree_util.tree_unflatten(t_def, conv)
        self._head_pages = self._pages    # quiesced: re-root the chain
        # evicted sessions hold HOST snapshots in the old dtypes: convert
        # them too, or their readmit would fail the carry_matches dtype
        # check against the new template until a process restart
        lane = jax.tree_util.tree_flatten(self.pipeline.init_carry())[0]
        lane_dts = [np.dtype(getattr(l, "dtype", "float32")) for l in lane]
        for s in self.table.sessions.values():
            if s.state == "evicted" and s.carry_leaves is not None and \
                    len(s.carry_leaves) == len(lane_dts):
                s.carry_leaves = [
                    np.asarray(a) if np.asarray(a).dtype == dt
                    else np.asarray(a).astype(dt)
                    for a, dt in zip(s.carry_leaves, lane_dts)]
        self._pipe_tag = tag
        return True

    # -- observability ---------------------------------------------------------
    def _refresh_gauges(self) -> None:
        counts: Dict[tuple, int] = {}
        for s in self.table.sessions.values():
            counts[(s.tenant, s.state)] = counts.get((s.tenant, s.state), 0) + 1
        for key in set(self._gauge_cache) | set(counts):
            tenant, state = key
            _SESSIONS.set(float(counts.get(key, 0)), app=self.app,
                          tenant=tenant, state=state)
            self._gauge_cache[key] = True

    def tenant_latency_ms(self, tenant: str, q: float = 0.99) -> Optional[float]:
        v = _LATENCY.labels(app=self.app, tenant=tenant).quantile(q)
        return None if v is None else v * 1e3

    def describe(self) -> dict:
        """The app-level view served by ``GET /api/serve/{app}/``."""
        with self._lock:
            tenants = self.table.tenants()
            return {
                "app": self.app,
                "frame_size": self.frame_size,
                "frames_per_dispatch": self.k_batch,
                "buckets": list(self.buckets),
                "capacity": self.table.capacity,
                "resident_buckets": self.resident_buckets(),
                "compiles": self.compiles,
                "active": self.table.active,
                # paged carries + the overlapped step (this PR): the page
                # pool is the capacity; free/fresh counts and the in-flight
                # window tell an operator how churned and how pipelined the
                # engine currently is
                "pages": {"free": self.table.free_slots(),
                          "fresh_lanes": len(self._fresh_lanes)},
                "overlap": {"depth": int(self._flight.credits),
                            "in_flight": len(self._inflight)},
                "sessions": len(self.table.sessions),
                "steps": self.steps,
                "dispatches": self.dispatches,
                "frames": self.frames,
                "credit_total": self.credits.total,
                "credit_fair_share": self.credits.fair_share(),
                "draining": self._draining,
                "drained": self._drained,
                # slot-axis sharding (docs/parallel.md): the mesh width and
                # whether the CURRENT bucket's lanes spread over it
                "shard": ({"devices": self._shard_d,
                           "sharded": self._shard_ok(self.table.capacity),
                           "lanes_per_device":
                               (self.table.capacity // self._shard_d
                                if self._shard_ok(self.table.capacity)
                                else self.table.capacity)}
                          if self._shard_d > 1 else None),
                "shed": {**self._ladder.view(),
                         "slo_ms": self._slo_ms or None,
                         "brownout": self._brownout,
                         "brownout_active": self._brownout_active,
                         "evictions": self.shed_evictions,
                         "pressure": round(self.credits.pressure(), 4),
                         "tenant_pressure": self.credits.tenant_pressure()},
                "persist": ({"dir": self._store._dir,
                             "every": self._persist_every,
                             "restored_sessions": self.restored_sessions}
                            if self._store is not None else None),
                "tenants": {
                    t: {"sessions": n,
                        "credits_used": self.credits.used(t),
                        "p99_ms": self.tenant_latency_ms(t)}
                    for t, n in sorted(tenants.items())},
            }

    def session_view(self, sid: str) -> dict:
        with self._lock:
            v = self._session(sid).view()
            if self._shard_d > 1 and v.get("slot") is not None:
                # the (device, lane) pair this session's slot addresses
                # under the slot-axis sharding — evict/readmit stay
                # slot-addressed, this is the mesh-side identity
                dev, lane = self.slot_device(v["slot"])
                v["device"], v["device_lane"] = dev, lane
        t = v["tenant"]
        v["tenant_p50_ms"] = self.tenant_latency_ms(t, 0.5)
        v["tenant_p99_ms"] = self.tenant_latency_ms(t, 0.99)
        return v


# ---------------------------------------------------------------------------
# SIGTERM drain hook (rolling restarts)
# ---------------------------------------------------------------------------

_sigterm_installed = False
_sigterm_lock = threading.Lock()


def drain_all_apps(timeout: float = 30.0) -> Dict[str, dict]:
    """Drain every registered serving app (refuse admissions, finish
    in-flight groups, persist all lanes). The SIGTERM hook's body; callable
    directly from an app's own shutdown path."""
    from . import api as _api
    out: Dict[str, dict] = {}
    for name, eng in _api.apps().items():
        try:
            out[name] = eng.drain(timeout=timeout)
        except Exception as e:                         # noqa: BLE001 — one
            out[name] = {"app": name, "error": repr(e)}    # bad app must not
            log.error("drain of %s failed: %r", name, e)   # block the rest
    return out


def install_sigterm_drain(timeout: float = 30.0) -> bool:
    """Install a SIGTERM handler that gracefully drains every registered
    serving app (docs/robustness.md "Serving-plane recovery"): the
    orchestrator's rolling-restart contract is SIGTERM → readyz goes
    unready (draining) → in-flight groups finish → all lanes persist →
    process exit. The drain runs on a background thread (a signal handler
    must not take engine locks); the previous handler is chained after the
    drain completes. Idempotent; returns False when not on the main thread
    (signals uninstallable) — auto-installed by ``register_app`` when
    config ``serve_drain_on_sigterm`` is set."""
    global _sigterm_installed
    import signal
    with _sigterm_lock:
        if _sigterm_installed:
            return True
        try:
            prev = signal.getsignal(signal.SIGTERM)

            def on_term(signum, frame):
                def run():
                    drain_all_apps(timeout=timeout)
                    if callable(prev):
                        try:
                            prev(signum, frame)
                        except Exception:              # noqa: BLE001
                            pass
                    elif prev == signal.SIG_DFL:
                        # restore + re-raise so the process still dies the
                        # default way once the drain landed
                        try:
                            signal.signal(signal.SIGTERM, signal.SIG_DFL)
                            os.kill(os.getpid(), signal.SIGTERM)
                        except Exception:              # noqa: BLE001
                            pass

                threading.Thread(target=run, name="fsdr-serve-drain",
                                 daemon=True).start()

            signal.signal(signal.SIGTERM, on_term)
        except ValueError:
            # not the main thread: the caller owns its signal story
            return False
        _sigterm_installed = True
        return True
