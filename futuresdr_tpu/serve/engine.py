"""ServeEngine: batch N concurrent sessions of ONE receiver DAG into one
dispatch per frame.

The production serving plane of docs/serving.md. Every fused
``Pipeline``/``FanoutPipeline``/``DagPipeline`` program computes exactly one
session per dispatch on the actor path — at SDR frame rates that leaves the
chip almost entirely idle (MFU 5.6% on the resident chain, ROADMAP). This
engine multiplexes N concurrent sessions running the SAME program through a
single per-frame dispatch by compiling the pipeline ONCE per slot bucket
with a leading session axis:

* ``jax.vmap`` over the inputs AND the flat composed carry — the carry
  layout per lane stays exactly the linear contract, so ``update_stage``
  addressing and the checkpoint ``snapshot_carry``/``restore_carry``
  surface keep working per slot;
* RAGGED admission in the style of Ragged Paged Attention
  (arXiv:2604.15464): a fixed-capacity slot axis with padded inactive
  lanes masked by an ``active`` lanes vector threaded as a program input —
  sessions join, leave and stall mid-flight by flipping mask lanes and
  functionally updating carry slices, with ZERO recompiles of resident
  buckets (``self.compiles`` is the pin);
* autotuned bucket sizes (``tpu/autotune.autotune_serve``): occupancy
  crossing the current bucket restacks the carries into the next bucket's
  capacity and compiles THAT bucket once;
* per-session carry slots riding the checkpoint machinery: ``evict`` lands
  a session's carry lane on the host via ``snapshot_carry``'s leaf
  contract, ``readmit`` restores it bit-identically (validated by
  ``carry_matches`` against the fresh-carry template, exactly like the
  kernel recovery path);
* per-tenant fairness over the shared admission budget
  (:class:`~futuresdr_tpu.serve.credits.TenantCreditController` — the
  multi-tenant generalization of the streamed path's CreditController);
* per-session fault isolation (the ``isolate_group``-per-session
  semantics): a work/dispatch fault addressed at one session retires ONLY
  that slot — siblings keep their lanes and their bit-exact outputs.

Masking semantics: inactive lanes still ride through the vmapped program
(their input rows are zeros), but their computed carries are DISCARDED by a
``where(active, new, old)`` merge inside the jitted program — a stalled
lane's filter history and oscillator phase are bit-frozen until its next
real frame, and an active lane's carry is exactly what the standalone
program would have produced (the N=1 ≡ bare-pipeline bit-equality
contract, test-pinned).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..log import logger
from ..ops import xfer
from ..runtime import faults as _faults
from ..telemetry import profile as _profile
from ..telemetry import prom as _prom
from ..telemetry.spans import recorder as _trace_recorder
from .credits import TenantCreditController
from .slots import ServeFull, Session, SlotTable

__all__ = ["ServeEngine", "ServeFull", "default_buckets"]

log = logger("serve.engine")
_trace = _trace_recorder()

# per-tenant Prometheus families (docs/serving.md "Observability"): every
# family carries {app, tenant} so one scrape separates tenants; label
# ordering in the exposition is stable (telemetry/prom.py sorts samples)
_SESSIONS = _prom.gauge(
    "fsdr_serve_sessions", "live serving sessions per state",
    ("app", "tenant", "state"))
_FRAMES = _prom.counter(
    "fsdr_serve_frames_total", "frames dispatched through the serving plane",
    ("app", "tenant"))
_DISPATCHES = _prom.counter(
    "fsdr_serve_dispatches_total",
    "batched serving dispatches (one per step with >= 1 active lane)",
    ("app",))
_RETIRED = _prom.counter(
    "fsdr_serve_retired_total",
    "sessions retired by a per-session fault (slot-isolated)",
    ("app", "tenant"))
_EVICTIONS = _prom.counter(
    "fsdr_serve_evictions_total",
    "session carries evicted to the host", ("app", "tenant"))
_REJECTS = _prom.counter(
    "fsdr_serve_rejects_total",
    "frame submissions refused by the per-tenant credit guard",
    ("app", "tenant"))
_LATENCY = _prom.histogram(
    "fsdr_serve_latency_seconds",
    "submit -> decoded-result latency per frame", ("app", "tenant"))


def default_buckets() -> tuple:
    """The slot-bucket ladder when neither the caller nor the autotune cache
    provides one: config ``serve_buckets`` ("1,2,4,…"), else powers of two
    to 64."""
    from ..config import config
    spec = str(config().get("serve_buckets", "") or "").strip()
    if spec:
        try:
            out = sorted({int(x) for x in spec.replace(";", ",").split(",")
                          if x.strip()})
            if out and all(b > 0 for b in out):
                return tuple(out)
        except ValueError:
            log.warning("bad serve_buckets spec %r — using the default "
                        "ladder", spec)
    return (1, 2, 4, 8, 16, 32, 64)


def build_slot_program(pipeline, capacity: int, k: int = 1):
    """Compile the pipeline's slot-batched serving step for one bucket:

        step(carries, x, active) -> (carries', outs)

    with every carry leaf carrying a leading ``[capacity]`` axis. ``k == 1``
    (the default): ``x`` is ``[capacity, frame]``, ``active`` a
    ``[capacity]`` bool vector, outs ``[capacity, out]`` per sink.

    ``k > 1`` is the MEGABATCH serving form: ``x`` is ``[capacity, k,
    frame]``, ``active`` a ``[capacity, k]`` PER-FRAME mask, and a
    ``lax.scan`` chains the k frames through every lane in one program call
    (amortizing per-dispatch host cost exactly like ``TpuKernel``'s
    ``frames_per_dispatch``) — the mask is RAGGED per lane, so sessions
    with fewer than k queued frames ride the same dispatch with their tail
    masked and their carries frozen from their last real frame on (frames
    pack at the front of the k axis; a masked row can never corrupt a
    later real frame's carry).

    Inactive lanes keep their OLD carry (bit-frozen stall semantics);
    output rows of inactive lane-frames are never delivered, so their
    value is irrelevant. No donation: admission/eviction do functional
    lane reads/updates on the live stacked carries between dispatches —
    donation would invalidate exactly the buffers those touch. Shared
    with ``tpu/autotune.autotune_serve`` so the measured program is
    exactly the served one."""
    import jax
    import jax.numpy as jnp

    inner = pipeline.fn()
    multi = bool(getattr(pipeline, "n_branches", 0))

    def masked_lane_step(carries, x, active):
        new_c, y = jax.vmap(inner)(carries, x)

        def sel(n, o):
            m = active.reshape((active.shape[0],) + (1,) * (n.ndim - 1))
            return jnp.where(m, n, o)

        return jax.tree_util.tree_map(sel, new_c, carries), y

    if int(k) <= 1:
        def step(carries, x, active):
            new_c, y = masked_lane_step(carries, x, active)
            return new_c, (y if multi else (y,))
    else:
        def step(carries, x, active):
            def body(c, xa):
                xk, ak = xa
                return masked_lane_step(c, xk, ak)

            carries, ys = jax.lax.scan(
                body, carries,
                (jnp.moveaxis(x, 1, 0), jnp.moveaxis(active, 1, 0)))
            # ys: [k, capacity, out] per sink -> [capacity, k, out]
            if multi:
                outs = tuple(jnp.moveaxis(yj, 0, 1) for yj in ys)
            else:
                outs = (jnp.moveaxis(ys, 0, 1),)
            return carries, outs

    return jax.jit(step, donate_argnums=())


class ServeEngine:
    """Multi-tenant serving front-end over one compiled receiver program.

    Host-driven: a serving loop (``perf/serve_ab.py``, an app's pump thread)
    calls :meth:`step` once per frame time; the REST session plane
    (``serve/api.py``) and any thread may ``admit``/``submit``/``evict``/
    ``close`` concurrently — one engine lock serializes table mutations
    against the dispatch walk.
    """

    def __init__(self, pipeline, frame_size: Optional[int] = None,
                 app: str = "serve", inst=None,
                 buckets: Optional[Sequence[int]] = None,
                 queue_frames: Optional[int] = None,
                 frames_per_dispatch: int = 1):
        from ..config import config
        from ..tpu.instance import instance
        self.pipeline = pipeline
        self.app = str(app)
        self.inst = inst or instance()
        self.k_batch = max(1, int(frames_per_dispatch))
        m = pipeline.frame_multiple
        fs = frame_size or config().tpu_frame_size
        self.frame_size = max(m, (fs // m) * m)
        self.n_sinks = int(getattr(pipeline, "n_branches", 0)) or 1
        self._multi = bool(getattr(pipeline, "n_branches", 0))
        if buckets is None:
            buckets = self._cached_buckets()
        self.buckets = tuple(sorted({int(b) for b in buckets})) \
            if buckets else default_buckets()
        #: compiled serving programs per resident bucket capacity — the
        #: session-churn contract is that this map only ever GAINS entries
        #: (join/leave/stall/evict inside resident buckets never recompiles)
        self._programs: Dict[int, object] = {}
        self.compiles = 0                 # program builds (the recompile pin)
        self.table = SlotTable(self.buckets[0])
        self._fresh = None                # fresh single-lane carry template
        self._carries = self._stacked_fresh(self.table.capacity)
        per_slot = int(queue_frames
                       if queue_frames is not None
                       else config().get("serve_queue_frames", 2))
        self._queue_frames = max(1, per_slot)
        self.credits = TenantCreditController(
            self._queue_frames * self.table.capacity)
        self._lock = threading.RLock()
        # bounded retired-session retention: a faulted client rarely comes
        # back to DELETE its session, so retired views (and their
        # undelivered output) would otherwise accumulate forever in a
        # long-running process — keep the newest N, forget the oldest
        self._retired_keep = max(0, int(config().get("serve_retired_keep",
                                                     64)))
        self._retired: List[str] = []
        self.steps = 0                    # step() calls (incl. idle)
        self.dispatches = 0               # steps that launched the program
        self.frames = 0                   # session-frames dispatched
        self._gauge_cache: Dict[tuple, object] = {}
        # profile plane (telemetry/profile.py): capacities whose first
        # dispatch (the real jit compile — build_slot_program only wraps)
        # has been billed as reason="serve_bucket", and the live-roofline
        # entry whose unit is ONE SESSION-FRAME (lane) — the registered
        # cost is the single-lane program's cost_analysis(), so vmapped
        # bucket MFU attributes per lane regardless of the resident bucket
        pipe, fs = self.pipeline, self.frame_size

        def _lane_cost():
            from ..utils.roofline import program_cost
            return program_cost(pipe, fs)

        self._warmed: set = set()
        from ..utils.roofline import dominant_dtype
        self._prof = _profile.register(f"serve:{self.app}",
                                       cost_thunk=_lane_cost,
                                       dtype=dominant_dtype(pipe.stages))

    # -- carry plumbing --------------------------------------------------------
    def _fresh_carry(self):
        if self._fresh is None:
            self._fresh = self.pipeline.init_carry()
        return self._fresh

    def _stacked_fresh(self, capacity: int):
        import jax
        import jax.numpy as jnp
        fresh = self._fresh_carry()
        return jax.tree_util.tree_map(
            lambda l: jnp.stack([jnp.asarray(l)] * capacity), fresh)

    def _set_lane(self, slot: int, value_tree) -> None:
        import jax
        self._carries = jax.tree_util.tree_map(
            lambda L, v: L.at[slot].set(v), self._carries, value_tree)

    def _lane_leaves(self, slot: int) -> tuple:
        """One lane's carry as host leaves ``(leaves, treedef)`` — the same
        leaf contract as ``Pipeline.snapshot_carry`` materialized, so
        ``carry_matches``/``restore_carry`` validate and rebuild it."""
        import jax
        leaves, _ = jax.tree_util.tree_flatten(self._carries)
        treedef = jax.tree_util.tree_flatten(self._fresh_carry())[1]
        return [xfer.to_host(l[slot]) for l in leaves], treedef

    def _program(self, capacity: int):
        prog = self._programs.get(capacity)
        if prog is None:
            prog = build_slot_program(self.pipeline, capacity, self.k_batch)
            self._programs[capacity] = prog
            self.compiles += 1
            log.info("%s: compiled serving program for slot bucket %d "
                     "(k=%d, resident buckets: %s)", self.app, capacity,
                     self.k_batch, sorted(self._programs))
        return prog

    def _cached_buckets(self) -> Optional[tuple]:
        try:
            from ..tpu.autotune import cached_serve_buckets
            got = cached_serve_buckets(self.pipeline, self.pipeline.in_dtype,
                                       self.inst.platform)
            return tuple(got) if got else None
        except Exception:                  # noqa: BLE001 — ladder seed only
            return None

    # -- occupancy / bucket growth ---------------------------------------------
    @property
    def capacity(self) -> int:
        return self.table.capacity

    def _grow_to_fit(self) -> None:
        """Called with the lock held and no free slot: move to the next
        bucket — restack the carries with fresh tail lanes, grow the table,
        re-size the shared credit budget. Resident buckets keep their
        compiled programs untouched."""
        import jax
        import jax.numpy as jnp
        cur = self.table.capacity
        bigger = [b for b in self.buckets if b > cur]
        if not bigger:
            raise ServeFull(
                f"{self.app}: at the largest slot bucket ({cur}); "
                f"admission refused")
        cap = bigger[0]
        fresh = self._fresh_carry()
        extra = cap - cur
        self._carries = jax.tree_util.tree_map(
            lambda L, f: jnp.concatenate(
                [L, jnp.stack([jnp.asarray(f)] * extra)]),
            self._carries, fresh)
        self.table.grow(cap)
        self.credits.set_total(self._queue_frames * cap)
        log.info("%s: slot bucket grew %d -> %d (active %d)", self.app, cur,
                 cap, self.table.active)

    # -- session lifecycle -----------------------------------------------------
    def admit(self, tenant: str = "default",
              sid: Optional[str] = None) -> Session:
        """Join: claim a lane (growing to the next bucket when full), with a
        FRESH per-session carry. Raises :class:`ServeFull` past the largest
        bucket."""
        with self._lock:
            if self.table.get(sid) is not None:
                raise ValueError(f"session id {sid!r} already exists")
            s = Session(tenant, sid)
            if not self.table.free_slots():
                self._grow_to_fit()
            slot = self.table.admit(s)
            self._set_lane(slot, self._fresh_carry())
            self.credits.register(s.tenant)
            self._refresh_gauges()
            return s

    def readmit(self, sid: str) -> Session:
        """Re-admit an evicted session: restore its host carry snapshot into
        a lane BIT-IDENTICALLY (validated against the fresh-carry template —
        a snapshot that no longer matches the pipeline contract is
        refused)."""
        with self._lock:
            s = self._session(sid)
            if s.state != "evicted" or s.carry_leaves is None:
                raise ValueError(f"session {sid!r} is not evicted "
                                 f"(state={s.state})")
            if not self.pipeline.carry_matches(
                    s.carry_leaves, s.carry_treedef, self._fresh_carry()):
                raise ValueError(f"session {sid!r}: evicted carry fails the "
                                 f"pipeline contract check")
            if not self.table.free_slots():
                self._grow_to_fit()
            slot = self.table.admit(s)
            self._set_lane(slot, self.pipeline.restore_carry(
                s.carry_leaves, s.carry_treedef, self.inst.device))
            s.carry_leaves = None
            s.carry_treedef = None
            s.stall_steps = 0
            self._refresh_gauges()
            return s

    def evict(self, sid: str) -> Session:
        """Stall handling: snapshot the session's carry lane to the host and
        free the lane for a busier session; queued input stays queued. The
        snapshot rides the same leaf contract as the kernel checkpoint
        machinery, so :meth:`readmit` restores it bit-identically."""
        with self._lock:
            s = self._session(sid)
            if s.state != "active":
                raise ValueError(f"session {sid!r} not active "
                                 f"(state={s.state})")
            leaves, treedef = self._lane_leaves(s.slot)
            s.carry_leaves = leaves
            s.carry_treedef = treedef
            self.table.release_slot(s)
            s.state = "evicted"
            _EVICTIONS.inc(app=self.app, tenant=s.tenant)
            self._refresh_gauges()
            return s

    def close(self, sid: str) -> None:
        """Leave: release the lane and forget the session. The lane's stale
        carry is inert (masked) until the next admit overwrites it."""
        with self._lock:
            s = self._session(sid)
            self.credits.release(s.tenant, len(s.pending))
            s.pending.clear()
            self.table.forget(s)
            s.state = "closed"
            if not self._tenant_live(s.tenant):
                self.credits.unregister(s.tenant)
            self._refresh_gauges()

    def _tenant_live(self, tenant: str) -> bool:
        """Does the tenant still have a session that can submit (active or
        re-admissible)? Retired/closed sessions stay in the registry for
        their views, but they must not keep the tenant's fair share
        reserved in the credit controller."""
        return any(o.tenant == tenant and o.state in ("active", "evicted")
                   for o in self.table.sessions.values())

    def _retire(self, s: Session, err: BaseException) -> None:
        """Per-session fault isolation (the isolate_group-of-one semantics):
        the faulted session's slot is masked off and released — the batch,
        and every sibling's carry and output, is untouched."""
        self.credits.release(s.tenant, len(s.pending))
        s.pending.clear()
        self.table.release_slot(s)
        s.state = "retired"
        s.error = repr(err)
        if not self._tenant_live(s.tenant):
            self.credits.unregister(s.tenant)
        self._retired.append(s.sid)
        while len(self._retired) > self._retired_keep:
            old = self.table.get(self._retired.pop(0))
            if old is not None and old.state == "retired":
                self.table.forget(old)
        _RETIRED.inc(app=self.app, tenant=s.tenant)
        log.warning("%s: session %s (tenant %s) retired by %r — siblings "
                    "unaffected", self.app, s.sid, s.tenant, err)
        self._refresh_gauges()

    def _session(self, sid: str) -> Session:
        s = self.table.get(sid)
        if s is None:
            raise KeyError(f"no session {sid!r}")
        return s

    # -- the data plane --------------------------------------------------------
    def submit(self, sid: str, frame: np.ndarray) -> bool:
        """Queue one input frame for ``sid``. Returns False (backpressure)
        when the tenant's fair credit share is exhausted — a stalled tenant
        cannot starve siblings of queue budget (docs/serving.md)."""
        with self._lock:
            s = self._session(sid)
            if s.state in ("retired", "closed"):
                raise ValueError(f"session {sid!r} is {s.state}")
            frame = np.asarray(frame)
            if frame.shape != (self.frame_size,):
                raise ValueError(
                    f"frame shape {frame.shape} != ({self.frame_size},)")
            if not self.credits.try_acquire(s.tenant):
                _REJECTS.inc(app=self.app, tenant=s.tenant)
                return False
            s.pending.append((np.ascontiguousarray(
                frame, dtype=self.pipeline.in_dtype), time.perf_counter_ns()))
            s.frames_in += 1
            return True

    def results(self, sid: str) -> list:
        """Drain the session's decoded results (oldest first)."""
        with self._lock:
            s = self._session(sid)
            out, s.out = list(s.out), type(s.out)()
            return out

    def step(self) -> int:
        """One frame-time dispatch: every active lane with pending frames
        rides ONE vmapped program call — one H2D of the stacked batch, one
        dispatch, one D2H per sink, regardless of the active session count.
        ``frames_per_dispatch > 1`` additionally megabatches up to k queued
        frames PER LANE through the in-program scan, ragged per lane (a
        session with fewer queued frames masks its tail — joins/leaves land
        cleanly at megabatch boundaries because the mask, not the program
        shape, carries the raggedness). Returns the number of
        session-frames dispatched (0 = idle step)."""
        with self._lock:
            C = self.table.capacity
            K = self.k_batch
            fplan = _faults.plan()
            lanes: List[tuple] = []       # (session, popped pending entries)
            # serving-plane spans (docs/serving.md "Observability"): the
            # batch assembly is the serving path's encode lane, the program
            # call its compute lane, the host fetch + per-session fan-back
            # its D2H/decode lanes — so the doctor's interval-union lanes,
            # host_codec_overlap_frac and the trace export cover the
            # serving plane exactly like the streamed path
            t_step = _trace.now() if _trace.enabled else 0
            t_enc = t_step
            # idle frame-time ticks (no lane has pending input — the common
            # case for a pump loop ticking at frame rate) must cost nothing:
            # the batch/mask arrays allocate lazily on the first busy lane
            batch = None
            active = None
            for s in self.table.occupants():
                if not s.pending:
                    s.stall_steps += 1
                    continue
                if batch is None:
                    shape = (C, self.frame_size) if K == 1 \
                        else (C, K, self.frame_size)
                    batch = np.zeros(shape, dtype=self.pipeline.in_dtype)
                    active = np.zeros((C,) if K == 1 else (C, K), dtype=bool)
                if fplan.armed():
                    # per-session fault sites (runtime/faults.py): address a
                    # work/dispatch injector at ONE session id and only that
                    # slot retires — the tenant-isolation chaos scenario
                    try:
                        fplan.maybe("work", s.sid)
                        fplan.maybe("dispatch", s.sid)
                    except _faults.InjectedFault as e:
                        self._retire(s, e)
                        continue
                popped = []
                for j in range(min(K, len(s.pending))):
                    entry = s.pending.popleft()
                    frame, _ = entry
                    self.credits.release(s.tenant)
                    if K == 1:
                        batch[s.slot] = frame
                        active[s.slot] = True
                    else:
                        batch[s.slot, j] = frame
                        active[s.slot, j] = True
                    popped.append(entry)
                s.stall_steps = 0
                lanes.append((s, popped))
            self.steps += 1
            if not lanes:
                return 0
            if t_enc:
                _trace.complete("tpu", "encode", t_enc,
                                args={"sessions": len(lanes),
                                      "capacity": C})
            try:
                prog = self._program(C)
                t0 = _trace.now() if _trace.enabled else 0
                x = xfer.to_device(batch, self.inst.device)
                act = xfer.to_device(active, self.inst.device)
                if t0:
                    _trace.complete("tpu", "H2D", t0,
                                    args={"bytes": batch.nbytes})
                t0 = _trace.now() if _trace.enabled else 0
                if C in self._warmed:
                    new_carries, outs = prog(self._carries, x, act)
                else:
                    # a bucket's FIRST dispatch pays its jit compile: bill
                    # it (fsdr_compiles_total{reason="serve_bucket"}) and
                    # mark the window active so a slow bucket compile reads
                    # as "compiling" to the doctor, never as a stalled
                    # serving loop
                    with _profile.compiling(f"serve:{self.app}",
                                            "serve_bucket",
                                            f"cap={C},k={K},"
                                            f"frame={self.frame_size}"):
                        new_carries, outs = prog(self._carries, x, act)
                    self._warmed.add(C)
                if t0:
                    _trace.complete("tpu", "compute", t0,
                                    args={"capacity": C,
                                          "active_lanes": len(lanes)})
                t0 = _trace.now() if _trace.enabled else 0
                host = [xfer.to_host(o) for o in outs]  # one D2H per sink
                if t0:
                    _trace.complete("tpu", "D2H", t0,
                                    args={"sinks": len(host)})
            except Exception:
                # dispatch-failure rollback: a real transfer/compile/dispatch
                # error must not silently drop the popped frames for every
                # session in the batch — re-queue them at the front of their
                # queues (original order), re-take their credits and leave
                # the carries untouched so the caller's retry re-dispatches
                # the exact same frames
                for s, popped in lanes:
                    s.pending.extendleft(reversed(popped))
                    self.credits.reacquire(s.tenant, len(popped))
                raise
            self._carries = new_carries
            self.dispatches += 1
            end = time.perf_counter_ns()
            t_dec = _trace.now() if _trace.enabled else 0
            dispatched = 0
            for s, popped in lanes:
                for j, (_, t_sub) in enumerate(popped):
                    if K == 1:
                        rows = [h[s.slot] for h in host]
                    else:
                        rows = [h[s.slot, j] for h in host]
                    res = tuple(np.asarray(r) for r in rows) \
                        if self._multi else np.asarray(rows[0])
                    s.out.append(res)
                    s.frames_out += 1
                    lat = (end - t_sub) * 1e-9
                    s.last_latency_s = lat
                    _LATENCY.observe(lat, app=self.app, tenant=s.tenant)
                    _FRAMES.inc(app=self.app, tenant=s.tenant)
                    dispatched += 1
            self.frames += dispatched
            _DISPATCHES.inc(app=self.app)
            # live-roofline unit for serving: one SESSION-FRAME (the
            # registered cost is the single-lane program's); the step
            # stamps its own group time
            self._prof.dispatch(dispatched, t=time.monotonic())
            if t_dec:
                _trace.complete("tpu", "decode", t_dec,
                                args={"frames": dispatched})
            if t_step:
                _trace.complete("serve", "serve_step", t_step,
                                args={"sessions": len(lanes),
                                      "active_lanes": len(lanes),
                                      "frames": dispatched,
                                      "capacity": C})
            return dispatched

    # -- observability ---------------------------------------------------------
    def _refresh_gauges(self) -> None:
        counts: Dict[tuple, int] = {}
        for s in self.table.sessions.values():
            counts[(s.tenant, s.state)] = counts.get((s.tenant, s.state), 0) + 1
        for key in set(self._gauge_cache) | set(counts):
            tenant, state = key
            _SESSIONS.set(float(counts.get(key, 0)), app=self.app,
                          tenant=tenant, state=state)
            self._gauge_cache[key] = True

    def tenant_latency_ms(self, tenant: str, q: float = 0.99) -> Optional[float]:
        v = _LATENCY.labels(app=self.app, tenant=tenant).quantile(q)
        return None if v is None else v * 1e3

    def describe(self) -> dict:
        """The app-level view served by ``GET /api/serve/{app}/``."""
        with self._lock:
            tenants = self.table.tenants()
            return {
                "app": self.app,
                "frame_size": self.frame_size,
                "frames_per_dispatch": self.k_batch,
                "buckets": list(self.buckets),
                "capacity": self.table.capacity,
                "resident_buckets": sorted(self._programs),
                "compiles": self.compiles,
                "active": self.table.active,
                "sessions": len(self.table.sessions),
                "steps": self.steps,
                "dispatches": self.dispatches,
                "frames": self.frames,
                "credit_total": self.credits.total,
                "credit_fair_share": self.credits.fair_share(),
                "tenants": {
                    t: {"sessions": n,
                        "credits_used": self.credits.used(t),
                        "p99_ms": self.tenant_latency_ms(t)}
                    for t, n in sorted(tenants.items())},
            }

    def session_view(self, sid: str) -> dict:
        with self._lock:
            v = self._session(sid).view()
        t = v["tenant"]
        v["tenant_p50_ms"] = self.tenant_latency_ms(t, 0.5)
        v["tenant_p99_ms"] = self.tenant_latency_ms(t, 0.99)
        return v
