"""Per-tenant fair credit budgeting for the serving front-end.

This generalizes the single-stream :class:`~futuresdr_tpu.tpu.kernel_block.
CreditController` (the adaptive in-flight budget of the streamed drain loop)
to the MULTI-tenant admission plane: the serving engine holds ONE shared
frame-credit budget (how many submitted-but-undispatched frames the whole
slot table may queue), and this controller divides it fairly between
tenants. The invariant it enforces is the starvation guard of
docs/serving.md:

    a stalled tenant — one whose sessions stopped consuming their queued
    frames — can never hold so much of the shared budget that a sibling
    tenant is denied its fair share.

Mechanically: every tenant is guaranteed ``fair = max(1, total //
n_tenants)`` credits at all times. A tenant may borrow PAST its fair share
(a lone busy tenant should be able to use the whole chip), but only out of
headroom that is not reserved for the other tenants' unexhausted guarantees
— so when a sibling shows up, its ``fair`` credits are by construction
still grantable, no matter how wedged the borrower is. All O(tenants) per
acquire, lock-cheap (admission rate, not sample rate).
"""

from __future__ import annotations

import threading
from typing import Dict

__all__ = ["TenantCreditController"]


class TenantCreditController:
    """Fair division of a shared frame-credit ``total`` between tenants.

    ``register``/``unregister`` track tenant membership (the engine calls
    them on the first admit / last close of a tenant's sessions);
    ``try_acquire`` grants one credit to a tenant or refuses (the engine
    surfaces refusal as submit backpressure, billed per tenant on
    ``fsdr_serve_rejects_total``); ``release`` returns one.
    """

    def __init__(self, total: int):
        self._total = max(1, int(total))
        self._used: Dict[str, int] = {}
        self._lock = threading.Lock()

    # -- membership -----------------------------------------------------------
    def register(self, tenant: str) -> None:
        with self._lock:
            self._used.setdefault(tenant, 0)

    def unregister(self, tenant: str) -> None:
        """Drop a tenant from the fair-share divisor. Outstanding credits (a
        closed session's still-queued frames) die with the registration."""
        with self._lock:
            self._used.pop(tenant, None)

    def set_total(self, total: int) -> None:
        """Re-size the shared budget (the engine grows it with the slot
        table). Shrinking below current usage only throttles NEW acquires —
        outstanding credits drain normally."""
        with self._lock:
            self._total = max(1, int(total))

    # -- introspection --------------------------------------------------------
    @property
    def total(self) -> int:
        return self._total

    def fair_share(self) -> int:
        with self._lock:
            return self._fair()

    def _fair(self) -> int:
        return max(1, self._total // max(1, len(self._used)))

    def used(self, tenant: str) -> int:
        with self._lock:
            return self._used.get(tenant, 0)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._used)

    def pressure(self) -> float:
        """Aggregate queue pressure in [0, 1]: outstanding credits over the
        shared budget — the shedding ladder's primary signal
        (serve/overload.py)."""
        with self._lock:
            return min(1.0, sum(self._used.values()) / float(self._total))

    def tenant_pressure(self) -> Dict[str, float]:
        """Per-tenant queue-depth watermark view: each tenant's outstanding
        credits over its fair share (>1 = borrowing past the guarantee).
        Served on the engine's describe() so an operator sees WHICH tenant
        is driving the ladder."""
        with self._lock:
            fair = float(self._fair())
            return {t: round(u / fair, 4) for t, u in self._used.items()}

    # -- the credit operations ------------------------------------------------
    def try_acquire(self, tenant: str) -> bool:
        """Grant one credit to ``tenant`` or refuse.

        Grant when the tenant is under its fair share, OR when the remaining
        headroom exceeds what the OTHER tenants' guarantees still reserve —
        borrowing never eats into a sibling's unexhausted fair share, which
        is exactly the stalled-tenant starvation guard."""
        with self._lock:
            self._used.setdefault(tenant, 0)
            fair = self._fair()
            mine = self._used[tenant]
            if mine < fair:
                self._used[tenant] = mine + 1
                return True
            reserved = sum(max(0, fair - u) for t, u in self._used.items()
                           if t != tenant)
            if sum(self._used.values()) + reserved < self._total:
                self._used[tenant] = mine + 1
                return True
            return False

    def release(self, tenant: str, n: int = 1) -> None:
        with self._lock:
            if tenant in self._used:
                self._used[tenant] = max(0, self._used[tenant] - int(n))

    def reacquire(self, tenant: str, n: int = 1) -> None:
        """Unconditionally re-take ``n`` credits released in error — the
        engine's dispatch-failure rollback re-queues popped frames and their
        credits with it. Bypasses the fairness check: the frames it covers
        already passed admission once."""
        with self._lock:
            self._used[tenant] = self._used.get(tenant, 0) + int(n)
