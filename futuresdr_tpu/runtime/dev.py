"""Prelude for custom-block authors (reference: ``src/runtime/dev.rs`` ``dev::prelude``).

``from futuresdr_tpu.runtime.dev import *`` brings in everything a block implementation
needs: the kernel base, ports, WorkIo, tags, Pmt, and the message-handler decorator.
"""

from ..types import Pmt, PmtKind, PortId
from .buffer import BufferReader, BufferWriter, StreamInput, StreamOutput
from .buffer.circuit import Circuit, InplaceInput, InplaceOutput
from .kernel import BlockMeta, Kernel, message_handler
from .message_output import MessageOutputs
from .tag import ItemTag, Tag
from .work_io import WorkIo

__all__ = [
    "Pmt", "PmtKind", "PortId",
    "BufferReader", "BufferWriter", "StreamInput", "StreamOutput",
    "Circuit", "InplaceInput", "InplaceOutput",
    "BlockMeta", "Kernel", "message_handler",
    "MessageOutputs", "ItemTag", "Tag", "WorkIo",
]
