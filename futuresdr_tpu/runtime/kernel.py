"""The user-facing block trait: ``Kernel`` with async ``init``/``work``/``deinit``.

Re-design of the reference's ``Kernel`` trait (``src/runtime/kernel.rs:54-90``) plus the port
plumbing its ``#[derive(Block)]`` macro generates (``crates/macros/src/lib.rs:419-1121``).
Instead of derive macros, ports are declared in ``__init__`` via ``add_stream_input/_output``
(stored as ordered attributes, accessible as ``self.input``…), and message handlers are either
registered with ``add_message_input`` or marked with the :func:`message_handler` decorator
(the ``#[message_inputs(...)]`` attribute equivalent).
"""

from __future__ import annotations

import inspect
import types
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from ..types import Pmt, PortId
from .buffer import StreamInput, StreamOutput
from .message_output import MessageOutputs
from .work_io import WorkIo

__all__ = ["Kernel", "BlockMeta", "message_handler"]


@dataclass
class BlockMeta:
    """Block metadata (`BlockMeta` in the reference macros)."""

    type_name: str = ""
    instance_name: str = ""
    blocking: bool = False
    id: int = -1


def message_handler(fn=None, *, name: Optional[str] = None):
    """Mark a method as a message-input handler.

    Handler signature: ``def h(self, io: WorkIo, mio: MessageOutputs, meta: BlockMeta,
    pmt: Pmt) -> Pmt`` — plain OR ``async def`` (both are dispatched by
    ``call_handler``; prefer plain for hot paths, it skips the per-message
    coroutine allocation — only go async to ``await`` something). The handler
    gets the live WorkIo so it can set ``finished`` / ``call_again``
    (reference: handlers take ``&mut WorkIo``, ``tests/flowgraph.rs:30-39``).
    """

    def mark(f):
        f._message_handler_name = name or f.__name__
        return f

    return mark(fn) if fn is not None else mark


class Kernel:
    """Base class for all blocks.

    Subclasses declare ports in ``__init__`` and implement any of ``init``, ``work``, ``deinit``.
    A kernel with no ``work`` override is a pure message block (`#[null_kernel]` equivalent).
    """

    #: class-level `#[blocking]` marker: run this block's event loop on a dedicated thread
    BLOCKING: bool = False

    def __init__(self, type_name: Optional[str] = None):
        self._stream_inputs: List[StreamInput] = []
        self._stream_outputs: List[StreamOutput] = []
        self._message_handlers: Dict[str, Callable] = {}
        self._handler_names = None       # index->name cache (call_handler)
        self._mio = MessageOutputs([])
        self.meta = BlockMeta(
            type_name=type_name or type(self).__name__,
            blocking=type(self).BLOCKING,
        )
        # Collect decorator-marked handlers (class scan replaces the derive macro).
        for attr_name, member in inspect.getmembers(type(self), inspect.isfunction):
            hname = getattr(member, "_message_handler_name", None)
            if hname:
                self._message_handlers[hname] = getattr(self, attr_name)
        # direct-dispatch eligibility (message_output.py fast path): a kernel
        # with the BASE no-op work() has no work coroutine a synchronously
        # delivered handler could interleave with, so its SYNC handlers may be
        # invoked in the sender's stack frame instead of through the inbox
        self._direct_ok = type(self).work is Kernel.work

    def _sync_handler(self, name: str) -> Optional[Callable]:
        """The named handler if it is a plain (non-coroutine) function."""
        fn = self._message_handlers.get(name)
        if fn is not None and not inspect.iscoroutinefunction(fn):
            return fn
        return None

    # -- port declaration ------------------------------------------------------
    def add_stream_input(self, name: str, dtype, min_items: int = 1,
                         preferred_buffer_size=None) -> StreamInput:
        port = StreamInput(name, dtype, min_items, preferred_buffer_size)
        self._stream_inputs.append(port)
        return port

    def add_stream_output(self, name: str, dtype, min_items: int = 1,
                          min_buffer_size: int = 0, buffer=None,
                          preferred_buffer_size=None) -> StreamOutput:
        port = StreamOutput(name, dtype, min_items, min_buffer_size, buffer,
                            preferred_buffer_size)
        self._stream_outputs.append(port)
        return port

    def add_inplace_input(self, name: str, dtype=None):
        """Circuit (in-place) input port (`buffer/circuit.rs`; see buffer/circuit.py)."""
        from .buffer.circuit import InplaceInput
        port = InplaceInput(name, dtype)
        self._stream_inputs.append(port)
        return port

    def add_inplace_output(self, name: str, dtype=None):
        from .buffer.circuit import InplaceOutput
        port = InplaceOutput(name, dtype)
        self._stream_outputs.append(port)
        return port

    def add_message_input(self, name: str, handler: Callable) -> None:
        self._message_handlers[name] = handler
        self._handler_names = None

    def add_message_output(self, name: str) -> None:
        self._mio.add_port(name)

    # -- port lookup (KernelInterface equivalent, `kernel_interface.rs:23-64`) -
    @property
    def stream_inputs(self) -> List[StreamInput]:
        return self._stream_inputs

    @property
    def stream_outputs(self) -> List[StreamOutput]:
        return self._stream_outputs

    @property
    def mio(self) -> MessageOutputs:
        return self._mio

    def stream_input(self, id) -> StreamInput:
        return self._port(self._stream_inputs, id, "input")

    def stream_output(self, id) -> StreamOutput:
        return self._port(self._stream_outputs, id, "output")

    @staticmethod
    def _port(ports, id, kind):
        if isinstance(id, PortId):
            id = id.id
        if isinstance(id, int):
            try:
                return ports[id]
            except IndexError:
                raise KeyError(f"no stream {kind} #{id}") from None
        for p in ports:
            if p.name == id:
                return p
        raise KeyError(f"no stream {kind} named {id!r} (have {[p.name for p in ports]})")

    def message_input_names(self) -> List[str]:
        return list(self._message_handlers)

    async def call_handler(self, io: WorkIo, meta: BlockMeta, port: PortId, pmt: Pmt) -> Pmt:
        """Dispatch a message to the named handler (`macros/lib.rs:1092-1114`).

        Handlers may be async OR plain functions — sync handlers skip the
        per-message coroutine allocation (the message-plane hot path)."""
        pid = port.id if isinstance(port, PortId) else port
        if isinstance(pid, int):
            names = self._handler_names
            if names is None:
                names = self._handler_names = tuple(self._message_handlers)
            try:
                pid = names[pid]
            except IndexError:
                return Pmt.invalid_value()
        handler = self._message_handlers.get(pid)
        if handler is None:
            return Pmt.invalid_value()
        result = handler(io, self._mio, meta, pmt)
        if type(result) is types.CoroutineType or inspect.isawaitable(result):
            result = await result
        return result if isinstance(result, Pmt) else Pmt.from_py(result)

    # -- validation (stream_ports_validate equivalent) -------------------------
    def validate_ports(self) -> None:
        for p in self._stream_inputs:
            if p.reader is None:
                raise RuntimeError(
                    f"{self.meta.instance_name or self.meta.type_name}: input {p.name!r} not connected")

    # -- lifecycle -------------------------------------------------------------
    async def init(self, mio: MessageOutputs, meta: BlockMeta) -> None:
        pass

    async def work(self, io: WorkIo, mio: MessageOutputs, meta: BlockMeta) -> None:
        pass

    async def deinit(self, mio: MessageOutputs, meta: BlockMeta) -> None:
        pass

    # -- connect DSL sugar: `fg.connect(a >> b >> c)` --------------------------
    # (the reference's `connect!(fg, a > b > c)`; Python chains `>` comparisons,
    #  so the stream-chain operator here is `>>`)
    def __rshift__(self, other):
        from .flowgraph import Chain
        return Chain([self]) >> other

    def __repr__(self):
        nm = self.meta.instance_name or self.meta.type_name
        return f"<{nm}>"
