"""WrappedKernel: the per-block actor task containing the block event loop.

Re-design of ``src/runtime/wrapped_kernel.rs:27-309`` (``run_impl``): the loop drains the inbox
(Call/Callback/StreamInputDone/Terminate), runs orderly shutdown when finished, parks on the
coalescing notifier (or a ``WorkIo.block_on`` awaitable) when no work is requested, and otherwise
calls ``kernel.work``.
"""

from __future__ import annotations

import asyncio
import time
from typing import Optional

from ..log import logger
from ..telemetry.doctor import WORK_DURATION as _WORK_DURATION
from ..telemetry.spans import recorder as _trace_recorder
from ..types import Pmt
from .inbox import (BlockInbox, Call, Callback, Initialize, StreamInputDone,
                    StreamOutputDone, Terminate)
from .kernel import Kernel
from .work_io import WorkIo

_trace = _trace_recorder()

__all__ = ["WrappedKernel"]

log = logger("runtime.block")


class WrappedKernel:
    """Kernel + meta + inbox: the erased ``dyn Block`` of this framework (`block.rs:20-66`)."""

    def __init__(self, kernel: Kernel, block_id: int):
        self.kernel = kernel
        self.inbox = BlockInbox()
        kernel.meta.id = block_id
        if not kernel.meta.instance_name:
            kernel.meta.instance_name = f"{kernel.meta.type_name}_{block_id}"
        # observability counters (SURVEY §5: block-level metrics are ad hoc in the
        # reference; here every block reports them via describe/REST)
        self.work_calls = 0
        self.work_time_s = 0.0
        self.messages_handled = 0
        # bound histogram child, resolved ONCE (labels() takes the family
        # lock); the per-work-call observe_sampled (1-in-8 systematic) is
        # billed by the ≤3% telemetry overhead gate alongside the span guard
        self._work_hist = _WORK_DURATION.labels(
            block=kernel.meta.instance_name)
        # direct message dispatch state (message_output.py fast path): the
        # event loop publishes its WorkIo, owning loop and liveness so a
        # same-loop sender can invoke a sync handler in its own stack frame
        self.io = WorkIo()
        self.loop = None
        self.live = False
        self._in_direct = False

    def metrics(self) -> dict:
        k = self.kernel
        # extra_metrics FIRST: hooks may refresh the base counters (the native
        # fast-chain's live bridge does) — reading them afterwards keeps one-shot
        # snapshots current; the update() below still lets hooks override keys
        extra = getattr(k, "extra_metrics", None)
        extra_out = {}
        if callable(extra):
            try:
                extra_out = extra() or {}
            except Exception:
                pass
        m = {
            "work_calls": self.work_calls,
            "work_time_s": round(self.work_time_s, 6),
            "messages_handled": self.messages_handled,
            "items_in": {p.name: getattr(p, "items_consumed", 0)
                         for p in k.stream_inputs},
            "items_out": {p.name: getattr(p, "items_produced", 0)
                          for p in k.stream_outputs},
            # buffer plane (telemetry): input ring occupancy sampled at scrape
            # time, plus park classifications counted by the event loop below
            # (inplace frame-plane ports duck-type only part of the stream
            # surface — getattr-guard everything)
            "buffer_fill": {p.name: round(f, 4) for p in k.stream_inputs
                            if (f := getattr(p, "fill", lambda: None)())
                            is not None},
            "stalls": {p.name: getattr(p, "stalls", 0)
                       for p in k.stream_outputs},
            "starved": {p.name: getattr(p, "starved", 0)
                        for p in k.stream_inputs},
        }
        m.update(extra_out)
        return m

    def _note_park(self) -> tuple:
        """Classify a park (backpressure vs starvation) into the port counters;
        returns the (stalled, starved) port-name lists for the park span."""
        k = self.kernel
        stalled, starved = [], []
        for p in k.stream_outputs:
            space = getattr(p, "space", None)   # inplace ports have no ring
            if space is not None and p.connected and space() < p.min_items:
                p.stalls += 1
                stalled.append(p.name)
        for p in k.stream_inputs:
            avail = getattr(p, "available", None)
            if avail is not None and p.connected and not p.finished() \
                    and avail() < p.min_items:
                p.starved += 1
                starved.append(p.name)
        return stalled, starved

    @property
    def id(self) -> int:
        return self.kernel.meta.id

    @property
    def instance_name(self) -> str:
        return self.kernel.meta.instance_name

    @property
    def is_blocking(self) -> bool:
        return self.kernel.meta.blocking

    def description(self):
        from ..types import BlockDescription
        k = self.kernel
        return BlockDescription(
            id=self.id,
            type_name=k.meta.type_name,
            instance_name=k.meta.instance_name,
            stream_inputs=[p.name for p in k.stream_inputs],
            stream_outputs=[p.name for p in k.stream_outputs],
            message_inputs=k.message_input_names(),
            message_outputs=k.mio.names,
            blocking=k.meta.blocking,
        )

    async def run(self, fg_inbox) -> None:
        """The block task body (`wrapped_kernel.rs:60-232`). ``fg_inbox`` is the supervisor's
        queue receiving Initialized/BlockDone/BlockError (see runtime.py)."""
        from .runtime import BlockDoneMsg, BlockErrorMsg, InitializedMsg

        kernel = self.kernel
        meta = kernel.meta
        io = self.io
        block_on_task: Optional[asyncio.Task] = None

        # ---- init barrier (`wrapped_kernel.rs:84-99`) ------------------------
        try:
            kernel.validate_ports()
            while True:
                msg = self.inbox.try_recv()
                if isinstance(msg, Initialize):
                    break
                if isinstance(msg, Terminate):
                    fg_inbox.send(BlockDoneMsg(self.id, self))
                    return
                if isinstance(msg, Callback):
                    # cannot service handlers before init; never leave a caller hanging
                    msg.reply.set(Pmt.invalid_value())
                if msg is None:
                    await self.inbox.wait()
                    self.inbox.take_pending()
            await kernel.init(kernel.mio, meta)
            fg_inbox.send(InitializedMsg(self.id, ok=True))
        except Exception as e:  # init failure → BlockError (`runtime.rs:501-505`)
            log.error("block %s failed in init: %r", self.instance_name, e)
            fg_inbox.send(BlockErrorMsg(self.id, e))
            return

        # ---- event loop (`wrapped_kernel.rs:106-229`) ------------------------
        error: Optional[Exception] = None
        self.loop = asyncio.get_running_loop()
        self.live = True                    # direct dispatch may target us now
        try:
            while True:
                io.call_again |= self.inbox.take_pending()
                while True:
                    msg = self.inbox.try_recv()
                    if msg is None:
                        break
                    if isinstance(msg, Call):
                        try:
                            await kernel.call_handler(io, meta, msg.port, msg.data)
                        except Exception as e:
                            log.error("block %s handler error: %r", self.instance_name, e)
                        self.messages_handled += 1
                        io.call_again = True
                    elif isinstance(msg, Callback):
                        try:
                            result = await kernel.call_handler(io, meta, msg.port, msg.data)
                        except Exception as e:
                            log.error("block %s handler error: %r", self.instance_name, e)
                            result = Pmt.invalid_value()
                        msg.reply.set(result)
                        self.messages_handled += 1
                        io.call_again = True
                    elif isinstance(msg, StreamInputDone):
                        kernel.stream_inputs[msg.port_index].set_finished()
                        io.call_again = True
                    elif isinstance(msg, StreamOutputDone):
                        # downstream reader detached → finish (`wrapped_kernel.rs:136-138`)
                        io.finished = True
                    elif isinstance(msg, Terminate):
                        io.finished = True

                if io.finished:
                    break

                if not io.call_again:
                    if block_on_task is None:
                        aw = io.take_block_on()
                        if aw is not None:
                            block_on_task = asyncio.ensure_future(aw)
                    if block_on_task is not None:
                        # select(block_on_future, inbox.notified()) — `wrapped_kernel.rs:207-222`
                        inbox_t = asyncio.ensure_future(self.inbox.wait())
                        done, _ = await asyncio.wait(
                            {block_on_task, inbox_t}, return_when=asyncio.FIRST_COMPLETED)
                        if block_on_task in done:
                            block_on_task = None
                            io.call_again = True
                        if inbox_t not in done:
                            inbox_t.cancel()
                    else:
                        # park: classify into backpressure/starvation counters
                        # (parks are off the hot path — the loop only lands
                        # here when there is NO work to run)
                        stalled, starved = self._note_park()
                        t_park = time.perf_counter_ns()
                        await self.inbox.wait()
                        if _trace.enabled:
                            _trace.complete(
                                "park", self.instance_name, t_park,
                                args={"stalled": stalled, "starved": starved})
                    continue

                io.reset()
                t0 = time.perf_counter_ns()
                await kernel.work(io, kernel.mio, meta)
                end = time.perf_counter_ns()
                self.work_time_s += (end - t0) * 1e-9
                self.work_calls += 1
                self._work_hist.observe_sampled((end - t0) * 1e-9)
                if _trace.enabled:
                    _trace.complete("block", self.instance_name, t0, end_ns=end)
        except Exception as e:
            log.error("block %s failed in work: %r", self.instance_name, e)
            error = e
        finally:
            self.live = False               # direct dispatch falls back to inbox
            if block_on_task is not None:
                block_on_task.cancel()
            leftover = io.take_block_on()
            if leftover is not None and hasattr(leftover, "close"):
                leftover.close()      # un-started coroutine: close to avoid the warning

        # ---- orderly shutdown (`wrapped_kernel.rs:188-205`) ------------------
        try:
            for p in kernel.stream_outputs:
                p.notify_finished()
            for p in kernel.stream_inputs:
                p.notify_finished()
            kernel.mio.notify_finished()
            await kernel.deinit(kernel.mio, meta)
        except Exception as e:
            log.error("block %s failed in deinit: %r", self.instance_name, e)
            error = error or e

        if error is not None:
            fg_inbox.send(BlockErrorMsg(self.id, error))
        else:
            fg_inbox.send(BlockDoneMsg(self.id, self))
