"""WrappedKernel: the per-block actor task containing the block event loop.

Re-design of ``src/runtime/wrapped_kernel.rs:27-309`` (``run_impl``): the loop drains the inbox
(Call/Callback/StreamInputDone/Terminate), runs orderly shutdown when finished, parks on the
coalescing notifier (or a ``WorkIo.block_on`` awaitable) when no work is requested, and otherwise
calls ``kernel.work``.

Failure policy (docs/robustness.md): each block resolves a :class:`BlockPolicy`
— its kernel's own ``policy`` attribute, else the ``block_policy`` config
default. ``fail_fast`` keeps the reference behavior (one error terminates the
flowgraph). ``restart`` re-initializes the block in place — capped exponential
backoff, ``kernel.deinit``+``kernel.init`` (fresh carry for device kernels),
``fsdr_block_restarts_total{block}`` billed, a ``BlockRestartMsg`` informing
the supervisor — without tearing down the rest of the graph; a restart forfeits
nothing when the fault fired before ``work()`` consumed input (the
``work:<block>`` injection site guarantees exactly that). ``isolate`` is
decided by the SUPERVISOR (runtime.py): this loop's error path already
EOSes the block's ports, so an isolated block retires gracefully while
independent branches finish.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass
from typing import Optional

from ..log import logger
from ..telemetry import prom as _prom
from ..telemetry.doctor import WORK_DURATION as _WORK_DURATION
from ..telemetry.spans import recorder as _trace_recorder
from ..types import Pmt
from . import faults as _faults
from .inbox import (BlockInbox, Call, Callback, Initialize, StreamInputDone,
                    StreamOutputDone, Terminate)
from .kernel import Kernel
from .work_io import WorkIo

_trace = _trace_recorder()

__all__ = ["WrappedKernel", "BlockPolicy", "policy_allows_fusion",
           "fusion_degraded", "isolate_groups_from_config"]

log = logger("runtime.block")

_RESTARTS = _prom.counter(
    "fsdr_block_restarts_total",
    "block restarts under the restart failure policy", ("block",))

_POLICIES = ("fail_fast", "restart", "isolate")


@dataclass(frozen=True)
class BlockPolicy:
    """Per-block failure policy (set ``kernel.policy = BlockPolicy(...)``).

    * ``fail_fast`` — any error terminates the whole flowgraph (default, the
      reference's behavior).
    * ``restart`` — re-initialize the block in place up to ``max_restarts``
      times with capped exponential backoff (``backoff * 2**(attempt-1)``,
      ≤ ``backoff_cap``); the budget covers init AND work failures. Exhausted
      budget escalates to fail_fast. A kernel exposing a ``recover()``
      coroutine (the TPU kernels' carry checkpoint/replay,
      ``tpu/kernel_block.py``) is offered bit-correct in-place recovery
      first; the forfeiting deinit+init path is the fallback.
    * ``isolate`` — retire the failed block (its ports EOS, downstream drains,
      upstream detaches) and let independent branches finish; the run still
      raises a structured :class:`~.runtime.FlowgraphError` at the end.
      ``isolate_group="name"`` widens the blast radius from one block to a
      named SUBGRAPH: any member's failure retires every block carrying the
      same group (group-wide port EOS in topological order — no survivor
      waits on a half-dead branch) while unrelated branches finish. The
      config-side assignment is ``block_isolate_groups =
      "block_name=group;…"`` for blocks with no own policy.

    Blocks carrying an ``isolate``/``isolate_group`` policy refuse
    fastchain/devchain fusion (retiring ONE member of a fused program is not
    sound). ``restart`` members refuse the native fastchain but are accepted
    by device-graph fusion: the fused kernel restarts from its composed-carry
    checkpoint (``policy_allows_fusion(restartable=True)``).
    """

    on_error: str = "fail_fast"
    max_restarts: int = 3
    backoff: float = 0.05
    backoff_cap: float = 2.0
    isolate_group: Optional[str] = None

    def __post_init__(self):
        if self.on_error not in _POLICIES:
            raise ValueError(
                f"on_error must be one of {_POLICIES}, got {self.on_error!r}")
        if self.isolate_group is not None:
            if self.on_error == "fail_fast":
                # isolate_group IS an isolate policy; spelling only the group
                # is the ergonomic form (BlockPolicy(isolate_group="rx"))
                object.__setattr__(self, "on_error", "isolate")
            elif self.on_error != "isolate":
                raise ValueError(
                    "isolate_group requires on_error='isolate' "
                    f"(got {self.on_error!r})")

    @staticmethod
    def from_config() -> "BlockPolicy":
        """The process-default policy (``block_policy`` / ``block_max_restarts``
        / ``block_backoff`` config knobs). A typo'd ``block_policy`` value
        falls back to fail_fast with an error log — it must NOT raise: this
        resolves lazily inside the block error paths, where an exception
        would kill the actor coroutine without a BlockErrorMsg and wedge the
        supervisor forever."""
        from ..config import config
        c = config()
        on_error = str(c.get("block_policy", "fail_fast"))
        if on_error not in _POLICIES:
            log.error("invalid block_policy config %r (want one of %s): "
                      "using fail_fast", on_error, _POLICIES)
            on_error = "fail_fast"
        return BlockPolicy(on_error=on_error,
                           max_restarts=int(c.get("block_max_restarts", 3)),
                           backoff=float(c.get("block_backoff", 0.05)))


def isolate_groups_from_config() -> dict:
    """Parse the ``block_isolate_groups`` config spec
    (``"block_name=group;other=group2"``; a TOML table works too) into
    ``{instance_name: group}``. Malformed entries are logged and skipped —
    this resolves inside block error paths (same no-raise contract as
    :meth:`BlockPolicy.from_config`)."""
    from ..config import config
    spec = config().get("block_isolate_groups", "")
    if isinstance(spec, dict):
        return {str(k): str(v) for k, v in spec.items()}
    out = {}
    for raw in str(spec or "").replace(",", ";").split(";"):
        raw = raw.strip()
        if not raw:
            continue
        name, sep, group = raw.partition("=")
        if not sep or not name.strip() or not group.strip():
            log.error("bad block_isolate_groups entry %r "
                      "(want name=group)", raw)
            continue
        out[name.strip()] = group.strip()
    return out


def policy_allows_fusion(kernel, restartable: bool = False) -> bool:
    """Per-member fusion gate shared by the fastchain/devchain finders.
    ``restartable=False`` (the native fastchain): any non-fail_fast policy
    stays on the actor path. ``restartable=True`` (device-graph fusion):
    ``restart`` members fuse too — the fused TpuKernel checkpoints its
    composed carry and the devchain drive loop restarts it in place
    (``tpu/kernel_block.py`` recover contract) — while ``isolate``/
    ``isolate_group`` members still refuse (retiring one member of a fused
    program is not sound)."""
    pol = getattr(kernel, "policy", None)
    if pol is None:
        # a config-side isolate-group assignment is an isolate policy too
        name = getattr(getattr(kernel, "meta", None), "instance_name", None)
        if name and name in isolate_groups_from_config():
            return False
    on_error = getattr(pol, "on_error", "fail_fast") if pol is not None \
        else "fail_fast"
    return on_error == "fail_fast" or (restartable and on_error == "restart")


def fusion_degraded(fault_sites=("work",), allow_restart: bool = False) -> bool:
    """Process-global fusion degrade shared by the fastchain/devchain
    finders: a non-fail_fast ``block_policy`` config default (``restart``
    exempted when the caller recovers fused kernels — ``allow_restart``), or
    an armed fault campaign on any of ``fault_sites``, keeps every block on
    the per-hop actor path (the fused substitutes bypass per-block
    supervision and injection points)."""
    from ..config import config
    pol = str(config().get("block_policy", "fail_fast"))
    if pol != "fail_fast" and not (allow_restart and pol == "restart"):
        return True
    p = _faults.plan()
    return any(p.has_site(s) for s in fault_sites)


class WrappedKernel:
    """Kernel + meta + inbox: the erased ``dyn Block`` of this framework (`block.rs:20-66`)."""

    def __init__(self, kernel: Kernel, block_id: int):
        self.kernel = kernel
        self.inbox = BlockInbox()
        kernel.meta.id = block_id
        if not kernel.meta.instance_name:
            kernel.meta.instance_name = f"{kernel.meta.type_name}_{block_id}"
        # observability counters (SURVEY §5: block-level metrics are ad hoc in the
        # reference; here every block reports them via describe/REST)
        self.work_calls = 0
        self.work_time_s = 0.0
        self.messages_handled = 0
        # failure-policy state: resolved lazily (config may not be final at
        # construction); restarts counts BOTH init and work restart attempts
        self.restarts = 0
        self._policy: Optional[BlockPolicy] = None
        self._restart_ctr = None
        # bound histogram child, resolved ONCE (labels() takes the family
        # lock); the per-work-call observe_sampled (1-in-8 systematic) is
        # billed by the ≤3% telemetry overhead gate alongside the span guard
        self._work_hist = _WORK_DURATION.labels(
            block=kernel.meta.instance_name)
        # direct message dispatch state (message_output.py fast path): the
        # event loop publishes its WorkIo, owning loop and liveness so a
        # same-loop sender can invoke a sync handler in its own stack frame
        self.io = WorkIo()
        self.loop = None
        self.live = False
        self._in_direct = False

    @property
    def policy(self) -> BlockPolicy:
        """The block's failure policy: the kernel's own ``policy`` attribute
        when it is a :class:`BlockPolicy`, else the config default — with the
        ``block_isolate_groups`` config assignment applied to blocks that
        carry no own policy (resolved once per WrappedKernel)."""
        p = self._policy
        if p is None:
            p = getattr(self.kernel, "policy", None)
            if not isinstance(p, BlockPolicy):
                p = BlockPolicy.from_config()
                group = isolate_groups_from_config().get(self.instance_name)
                if group:
                    p = BlockPolicy(on_error="isolate", isolate_group=group,
                                    max_restarts=p.max_restarts,
                                    backoff=p.backoff)
            self._policy = p
        return p

    def metrics(self) -> dict:
        k = self.kernel
        # extra_metrics FIRST: hooks may refresh the base counters (the native
        # fast-chain's live bridge does) — reading them afterwards keeps one-shot
        # snapshots current; the update() below still lets hooks override keys
        extra = getattr(k, "extra_metrics", None)
        extra_out = {}
        if callable(extra):
            try:
                extra_out = extra() or {}
            except Exception:
                pass
        m = {
            "work_calls": self.work_calls,
            "work_time_s": round(self.work_time_s, 6),
            "messages_handled": self.messages_handled,
            "restarts": self.restarts,
            "items_in": {p.name: getattr(p, "items_consumed", 0)
                         for p in k.stream_inputs},
            "items_out": {p.name: getattr(p, "items_produced", 0)
                          for p in k.stream_outputs},
            # buffer plane (telemetry): input ring occupancy sampled at scrape
            # time, plus park classifications counted by the event loop below
            # (inplace frame-plane ports duck-type only part of the stream
            # surface — getattr-guard everything)
            "buffer_fill": {p.name: round(f, 4) for p in k.stream_inputs
                            if (f := getattr(p, "fill", lambda: None)())
                            is not None},
            "stalls": {p.name: getattr(p, "stalls", 0)
                       for p in k.stream_outputs},
            "starved": {p.name: getattr(p, "starved", 0)
                        for p in k.stream_inputs},
        }
        m.update(extra_out)
        return m

    def _note_park(self) -> tuple:
        """Classify a park (backpressure vs starvation) into the port counters;
        returns the (stalled, starved) port-name lists for the park span."""
        k = self.kernel
        stalled, starved = [], []
        for p in k.stream_outputs:
            space = getattr(p, "space", None)   # inplace ports have no ring
            if space is not None and p.connected and space() < p.min_items:
                p.stalls += 1
                stalled.append(p.name)
        for p in k.stream_inputs:
            avail = getattr(p, "available", None)
            if avail is not None and p.connected and not p.finished() \
                    and avail() < p.min_items:
                p.starved += 1
                starved.append(p.name)
        return stalled, starved

    # -- restart machinery (BlockPolicy on_error="restart") --------------------
    async def _note_restart(self, err: Exception, fg_inbox, phase: str) -> None:
        """Bill one restart attempt (counter + supervisor notification) and
        sleep out the capped exponential backoff."""
        from .runtime import BlockRestartMsg
        pol = self.policy
        self.restarts += 1
        if self._restart_ctr is None:
            self._restart_ctr = _RESTARTS.labels(block=self.instance_name)
        self._restart_ctr.inc()
        log.warning("block %s failed in %s (%r): restart %d/%d",
                    self.instance_name, phase, err, self.restarts,
                    pol.max_restarts)
        _trace.instant("runtime", "block_restart",
                       args={"block": self.instance_name, "phase": phase,
                             "attempt": self.restarts})
        fg_inbox.send(BlockRestartMsg(self.id, self.restarts, err, phase))
        delay = min(pol.backoff * (2 ** (self.restarts - 1)), pol.backoff_cap)
        if delay > 0:
            await asyncio.sleep(delay)

    async def _reinit_for_restart(self, err: Exception,
                                  fg_inbox) -> Optional[Exception]:
        """Restart the kernel in place after a work-loop error: backoff, then
        — when the kernel exposes a ``recover()`` coroutine (the TPU kernels'
        carry checkpoint/replay, ``tpu/kernel_block.py``) — bit-correct
        in-place recovery first; else (or when recovery declines/fails)
        deinit (best-effort, before EVERY attempt — init need not be
        idempotent) + init — a fresh carry/compiled state for device kernels,
        which FORFEITS in-flight dispatch state (billed on
        ``fsdr_frames_forfeited_total``). Returns None on success, or the
        TERMINAL exception when re-init keeps failing past the restart
        budget (the caller reports that one — the operator needs the failure
        that actually ended the block, not the work error the restarts were
        trying to recover from)."""
        kernel = self.kernel
        await self._note_restart(err, fg_inbox, phase="work")
        recover = getattr(kernel, "recover", None)
        while callable(recover):
            try:
                if not await recover(err):
                    break                # declined (no usable checkpoint)
                log.info("block %s recovered in place from its carry "
                         "checkpoint (replay)", self.instance_name)
                return None
            except Exception as e:                     # noqa: BLE001
                # a fault DURING recovery (e.g. a fatal transfer failure
                # while re-staging the replay window) consumes another
                # restart attempt and retries — recover() is idempotent, the
                # replay log is intact, and forfeiting here would throw away
                # a recovery the next attempt could still complete
                if self.restarts >= self.policy.max_restarts:
                    log.warning("block %s checkpoint recovery failed on the "
                                "final restart (%r): falling back to a "
                                "fresh re-init", self.instance_name, e)
                    break
                await self._note_restart(e, fg_inbox, phase="work")
                err = e
        while True:
            try:
                await kernel.deinit(kernel.mio, kernel.meta)
            except Exception as e:                     # noqa: BLE001 — the old
                log.debug("deinit of failed block %s raised: %r",  # incarnation
                          self.instance_name, e)                   # best-effort
            try:
                await kernel.init(kernel.mio, kernel.meta)
                return None
            except Exception as e2:                    # noqa: BLE001
                if self.restarts >= self.policy.max_restarts:
                    log.error("block %s re-init failed on final restart: %r",
                              self.instance_name, e2)
                    return e2
                await self._note_restart(e2, fg_inbox, phase="init")

    def _notify_ports_finished(self) -> None:
        """EOS every port (downstream drains, upstream detaches). Used by the
        orderly-shutdown path AND the init-failure path — an isolated block
        that never came up must still release its neighbours."""
        kernel = self.kernel
        for p in kernel.stream_outputs:
            p.notify_finished()
        for p in kernel.stream_inputs:
            p.notify_finished()
        kernel.mio.notify_finished()

    @property
    def id(self) -> int:
        return self.kernel.meta.id

    @property
    def instance_name(self) -> str:
        return self.kernel.meta.instance_name

    @property
    def is_blocking(self) -> bool:
        return self.kernel.meta.blocking

    def description(self):
        from ..types import BlockDescription
        k = self.kernel
        return BlockDescription(
            id=self.id,
            type_name=k.meta.type_name,
            instance_name=k.meta.instance_name,
            stream_inputs=[p.name for p in k.stream_inputs],
            stream_outputs=[p.name for p in k.stream_outputs],
            message_inputs=k.message_input_names(),
            message_outputs=k.mio.names,
            blocking=k.meta.blocking,
            policy=self.policy.on_error,
            restarts=self.restarts,
            isolate_group=self.policy.isolate_group,
        )

    async def run(self, fg_inbox) -> None:
        """The block task body (`wrapped_kernel.rs:60-232`). ``fg_inbox`` is the supervisor's
        queue receiving Initialized/BlockDone/BlockError (see runtime.py)."""
        from .runtime import BlockDoneMsg, BlockErrorMsg, InitializedMsg

        kernel = self.kernel
        meta = kernel.meta
        io = self.io
        block_on_task: Optional[asyncio.Task] = None

        # ---- init barrier (`wrapped_kernel.rs:84-99`) ------------------------
        try:
            kernel.validate_ports()
            while True:
                msg = self.inbox.try_recv()
                if isinstance(msg, Initialize):
                    break
                if isinstance(msg, Terminate):
                    fg_inbox.send(BlockDoneMsg(self.id, self))
                    return
                if isinstance(msg, Callback):
                    # cannot service handlers before init; never leave a caller hanging
                    msg.reply.set(Pmt.invalid_value())
                if msg is None:
                    await self.inbox.wait()
                    self.inbox.take_pending()
            while True:
                try:
                    await kernel.init(kernel.mio, meta)
                    break
                except Exception as e:
                    # restart policy covers init too: retry with backoff out
                    # of the same budget (fresh deploys against flaky links
                    # fail here first)
                    pol = self.policy
                    if pol.on_error != "restart" or \
                            self.restarts >= pol.max_restarts:
                        raise
                    try:
                        # release whatever the failed attempt allocated —
                        # init need not be idempotent (same contract as
                        # _reinit_for_restart's deinit-then-init)
                        await kernel.deinit(kernel.mio, meta)
                    except Exception as e2:            # noqa: BLE001
                        log.debug("deinit after failed init raised: %r", e2)
                    await self._note_restart(e, fg_inbox, phase="init")
            fg_inbox.send(InitializedMsg(self.id, ok=True))
        except Exception as e:  # init failure → BlockError (`runtime.rs:501-505`)
            log.error("block %s failed in init: %r", self.instance_name, e)
            try:
                # EOS the ports even though the block never came up: under an
                # `isolate` policy the supervisor keeps the graph running, so
                # neighbours must not wait on a dead block (fail_fast's
                # terminate cascade makes this a harmless no-op)
                self._notify_ports_finished()
            except Exception as e2:                    # noqa: BLE001
                log.debug("port EOS after init failure raised: %r", e2)
            fg_inbox.send(BlockErrorMsg(self.id, e))
            return

        # ---- event loop (`wrapped_kernel.rs:106-229`) ------------------------
        error: Optional[Exception] = None
        self.loop = asyncio.get_running_loop()
        self.live = True                    # direct dispatch may target us now
        # fault injection (runtime/faults.py): resolve the work:<block> site
        # ONCE — the armed-check is one attribute read, the unarmed path costs
        # a None compare per work call (inside the ≤3% telemetry budget)
        fplan = _faults.plan()
        work_fault = fplan.resolve("work", self.instance_name) \
            if fplan.armed() else None
        try:
            # restart wrapper: a work-loop error under an on_error="restart"
            # policy re-initializes the kernel in place and re-enters the
            # event loop instead of retiring the block (see BlockPolicy)
            while True:
                try:
                    # ---- one incarnation of the event loop -----------------
                    while True:
                        io.call_again |= self.inbox.take_pending()
                        while True:
                            msg = self.inbox.try_recv()
                            if msg is None:
                                break
                            if isinstance(msg, Call):
                                try:
                                    await kernel.call_handler(io, meta, msg.port, msg.data)
                                except Exception as e:
                                    log.error("block %s handler error: %r", self.instance_name, e)
                                self.messages_handled += 1
                                io.call_again = True
                            elif isinstance(msg, Callback):
                                try:
                                    result = await kernel.call_handler(io, meta, msg.port, msg.data)
                                except Exception as e:
                                    log.error("block %s handler error: %r", self.instance_name, e)
                                    result = Pmt.invalid_value()
                                msg.reply.set(result)
                                self.messages_handled += 1
                                io.call_again = True
                            elif isinstance(msg, StreamInputDone):
                                kernel.stream_inputs[msg.port_index].set_finished()
                                io.call_again = True
                            elif isinstance(msg, StreamOutputDone):
                                # downstream reader detached → finish (`wrapped_kernel.rs:136-138`)
                                io.finished = True
                            elif isinstance(msg, Terminate):
                                io.finished = True

                        if io.finished:
                            break

                        if not io.call_again:
                            if block_on_task is None:
                                aw = io.take_block_on()
                                if aw is not None:
                                    block_on_task = asyncio.ensure_future(aw)
                            if block_on_task is not None:
                                # select(block_on_future, inbox.notified()) — `wrapped_kernel.rs:207-222`
                                inbox_t = asyncio.ensure_future(self.inbox.wait())
                                done, _ = await asyncio.wait(
                                    {block_on_task, inbox_t}, return_when=asyncio.FIRST_COMPLETED)
                                if block_on_task in done:
                                    block_on_task = None
                                    io.call_again = True
                                if inbox_t not in done:
                                    inbox_t.cancel()
                            else:
                                # park: classify into backpressure/starvation counters
                                # (parks are off the hot path — the loop only lands
                                # here when there is NO work to run)
                                stalled, starved = self._note_park()
                                t_park = time.perf_counter_ns()
                                await self.inbox.wait()
                                if _trace.enabled:
                                    _trace.complete(
                                        "park", self.instance_name, t_park,
                                        args={"stalled": stalled, "starved": starved})
                            continue

                        io.reset()
                        if work_fault is not None:
                            # before work() touches any port: a restart after
                            # this fault loses no consumed input
                            work_fault.check()
                        t0 = time.perf_counter_ns()
                        await kernel.work(io, kernel.mio, meta)
                        end = time.perf_counter_ns()
                        self.work_time_s += (end - t0) * 1e-9
                        self.work_calls += 1
                        self._work_hist.observe_sampled((end - t0) * 1e-9)
                        if _trace.enabled:
                            _trace.complete("block", self.instance_name, t0, end_ns=end)
                except Exception as e:
                    pol = self.policy
                    if pol.on_error == "restart" and \
                            self.restarts < pol.max_restarts:
                        if block_on_task is not None:
                            block_on_task.cancel()
                            block_on_task = None
                        leftover = io.take_block_on()
                        if leftover is not None and hasattr(leftover, "close"):
                            leftover.close()
                        terminal = await self._reinit_for_restart(e, fg_inbox)
                        if terminal is None:
                            io.reset()
                            io.finished = False
                            io.call_again = True    # re-examine ports now
                            continue
                        e = terminal    # report what actually ended the block
                    log.error("block %s failed: %r", self.instance_name, e)
                    error = e
                break
        finally:
            self.live = False               # direct dispatch falls back to inbox
            if block_on_task is not None:
                block_on_task.cancel()
            leftover = io.take_block_on()
            if leftover is not None and hasattr(leftover, "close"):
                leftover.close()      # un-started coroutine: close to avoid the warning

        # ---- orderly shutdown (`wrapped_kernel.rs:188-205`) ------------------
        try:
            self._notify_ports_finished()
            await kernel.deinit(kernel.mio, meta)
        except Exception as e:
            log.error("block %s failed in deinit: %r", self.instance_name, e)
            error = error or e

        if error is not None:
            fg_inbox.send(BlockErrorMsg(self.id, error))
        else:
            fg_inbox.send(BlockDoneMsg(self.id, self))
