"""Portable CPU ring buffer: single writer, N broadcast readers, wrap-capped slices.

This is the pure-Python fallback backend (the role the reference's ``slab`` buffer plays on
wasm, ``buffer/slab.rs``); the default CPU backend is the C++ double-mapped circular buffer in
:mod:`.circular` which exposes fully contiguous views (as the reference's ``vmcircbuffer``,
``buffer/circular.rs``). Readable/writable slices here are capped at the wrap boundary, which is
correct but can shorten work windows near the wrap.

Wake protocol (`circular.rs:23-35,241-248,371-387`): ``produce`` notifies every reader's block,
``consume`` notifies the writer's block; EOS travels through block inboxes as
StreamInputDone/StreamOutputDone.
"""

from __future__ import annotations

import threading
from typing import List, Sequence

import numpy as np

from ..inbox import BlockInbox, StreamInputDone, StreamOutputDone
from ..tag import ItemTag
from . import BufferReader, BufferWriter

__all__ = ["RingWriter", "RingReader"]


class _ReaderState:
    __slots__ = ("pos", "tags", "inbox", "port_index", "detached")

    def __init__(self, pos: int, inbox: BlockInbox, port_index: int):
        self.pos = pos              # absolute read position (monotonic item counter)
        self.tags: List[ItemTag] = []   # absolute indices
        self.inbox = inbox
        self.port_index = port_index
        self.detached = False       # reader finished; ignore for space accounting


class RingWriter(BufferWriter):
    def __init__(self, dtype, capacity: int, writer_inbox: BlockInbox,
                 writer_port_index: int = 0):
        self.dtype = np.dtype(dtype)
        self.capacity = int(capacity)
        self._data = np.zeros(self.capacity, dtype=self.dtype)
        self._wpos = 0              # absolute write position
        self._readers: List[_ReaderState] = []
        self._lock = threading.Lock()
        self._inbox = writer_inbox
        self._port_index = writer_port_index
        self._finished = False

    # -- connect ---------------------------------------------------------------
    def add_reader(self, reader_inbox: BlockInbox, port_index: int,
                   min_items: int = 1) -> "RingReader":
        with self._lock:
            st = _ReaderState(self._wpos, reader_inbox, port_index)
            self._readers.append(st)
        return RingReader(self, st)

    # -- writer side -----------------------------------------------------------
    def _space(self) -> int:
        live = [r.pos for r in self._readers if not r.detached]
        if not live:
            return self.capacity
        return self.capacity - (self._wpos - min(live))

    def slice(self) -> np.ndarray:
        with self._lock:
            space = self._space()
            off = self._wpos % self.capacity
            n = min(space, self.capacity - off)
            return self._data[off:off + n]

    def produce(self, n: int, tags: Sequence[ItemTag] = ()) -> None:
        if n == 0:
            return
        with self._lock:
            base = self._wpos
            self._wpos += n
            for r in self._readers:
                if not r.detached and tags:
                    r.tags.extend(ItemTag(base + t.index, t.tag) for t in tags)
            readers = [r.inbox for r in self._readers if not r.detached]
        for ib in readers:
            ib.notify()

    def notify_finished(self) -> None:
        """EOS downstream: StreamInputDone into every reader block inbox (`circular.rs:213-222`)."""
        with self._lock:
            if self._finished:
                return
            self._finished = True
            readers = [(r.inbox, r.port_index) for r in self._readers if not r.detached]
        for ib, pidx in readers:
            ib.send(StreamInputDone(pidx))

    # -- reader callbacks ------------------------------------------------------
    def _reader_slice(self, st: _ReaderState) -> np.ndarray:
        with self._lock:
            avail = self._wpos - st.pos
            off = st.pos % self.capacity
            n = min(avail, self.capacity - off)
            return self._data[off:off + n]

    def _reader_tags(self, st: _ReaderState) -> List[ItemTag]:
        with self._lock:
            return [ItemTag(t.index - st.pos, t.tag) for t in st.tags if t.index >= st.pos]

    def _reader_consume(self, st: _ReaderState, n: int) -> None:
        if n == 0:
            return
        with self._lock:
            assert n <= self._wpos - st.pos, "consumed more than available"
            st.pos += n
            st.tags = [t for t in st.tags if t.index >= st.pos]
        self._inbox.notify()  # space freed → wake writer block

    def _reader_finished(self, st: _ReaderState) -> None:
        """EOS upstream: detach reader, StreamOutputDone to writer (`circular.rs:332-342`)."""
        with self._lock:
            if st.detached:
                return
            st.detached = True
            st.tags.clear()
        self._inbox.send(StreamOutputDone(self._port_index))


class RingReader(BufferReader):
    def __init__(self, writer: RingWriter, state: _ReaderState):
        self._writer = writer
        self._state = state
        self.port_index = state.port_index

    def slice(self) -> np.ndarray:
        return self._writer._reader_slice(self._state)

    def tags(self) -> List[ItemTag]:
        return self._writer._reader_tags(self._state)

    def consume(self, n: int) -> None:
        self._writer._reader_consume(self._state, n)

    def notify_finished(self) -> None:
        self._writer._reader_finished(self._state)
