"""Default CPU buffer: C++ double-mapped circular buffer with lock-free SPSC indices.

Re-design of the reference's default buffer (``src/runtime/buffer/circular.rs`` over the
``vmcircbuffer`` crate): a memfd-backed region mapped twice back-to-back so every read/write
window is contiguous regardless of the wrap position — work windows are never split, unlike the
portable :mod:`.ring` fallback. Index arithmetic (produce/consume/space) lives in C++ atomics
(``native/ringbuf.cpp``), so the data-plane accounting is lock-free exactly as in the reference.

Falls back transparently: :func:`available` reports whether the native library loaded; the
flowgraph default buffer is set accordingly at import time (see ``runtime/__init__``).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Sequence

import numpy as np

from ...log import logger
from ..inbox import BlockInbox, StreamInputDone, StreamOutputDone
from ..tag import ItemTag
from . import BufferReader, BufferWriter

__all__ = ["CircularWriter", "CircularReader", "available", "load_native"]

log = logger("buffer.circular")

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), os.pardir, "native")
_NATIVE_DIR = os.path.normpath(_NATIVE_DIR)

_lib = None


def load_native() -> Optional[ctypes.CDLL]:
    """Load (building if necessary) the native library; returns None when unavailable."""
    global _lib
    if _lib is not None:
        return _lib
    so = os.path.join(_NATIVE_DIR, "libfsdr_native.so")
    # always run make: incremental no-op when up to date, and a pre-existing .so
    # from before a new source file (e.g. mm.cpp) was added gets its symbols
    try:
        subprocess.run(["make", "-C", _NATIVE_DIR], check=True,
                       capture_output=True, timeout=120)
    except Exception as e:
        if not os.path.exists(so):
            log.warning("native build failed (%r); using portable ring buffer", e)
            return None
        log.warning("native rebuild failed (%r); using existing %s", e, so)
    try:
        lib = ctypes.CDLL(so)
    except OSError as e:
        log.warning("native load failed (%r); using portable ring buffer", e)
        return None
    lib.fsdr_dbuf_create.restype = ctypes.c_void_p
    lib.fsdr_dbuf_create.argtypes = [ctypes.c_size_t]
    lib.fsdr_dbuf_destroy.argtypes = [ctypes.c_void_p]
    lib.fsdr_dbuf_ptr.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.fsdr_dbuf_ptr.argtypes = [ctypes.c_void_p]
    lib.fsdr_dbuf_size.restype = ctypes.c_size_t
    lib.fsdr_dbuf_size.argtypes = [ctypes.c_void_p]
    lib.fsdr_ring_create.restype = ctypes.c_void_p
    lib.fsdr_ring_create.argtypes = [ctypes.c_uint64]
    lib.fsdr_ring_destroy.argtypes = [ctypes.c_void_p]
    lib.fsdr_ring_add_reader.restype = ctypes.c_int
    lib.fsdr_ring_add_reader.argtypes = [ctypes.c_void_p]
    lib.fsdr_ring_remove_reader.argtypes = [ctypes.c_void_p, ctypes.c_int]
    for f in ("fsdr_ring_wpos", "fsdr_ring_space"):
        getattr(lib, f).restype = ctypes.c_uint64
        getattr(lib, f).argtypes = [ctypes.c_void_p]
    for f in ("fsdr_ring_rpos", "fsdr_ring_available"):
        getattr(lib, f).restype = ctypes.c_uint64
        getattr(lib, f).argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.fsdr_ring_produce.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.fsdr_ring_consume.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_uint64]
    _lib = lib
    return _lib


def available() -> bool:
    return load_native() is not None


def probe_native(symbol: str, restype, argtypes) -> Optional[ctypes.CDLL]:
    """Shared native-kernel probe: honors the ``FSDR_NO_NATIVE=1`` escape hatch
    (forces every portable fallback — rule out the C++ toolchain when debugging
    or benchmarking the pure-Python/XLA paths), loads the library, checks the
    symbol, binds its signature, and returns the CDLL (or None). Every native
    kernel (MM clock recovery, Viterbi, …) routes through here so the fallback
    convention cannot silently diverge per call site."""
    if os.environ.get("FSDR_NO_NATIVE"):
        return None
    lib = load_native()
    if lib is None or not hasattr(lib, symbol):
        return None
    fn = getattr(lib, symbol)
    fn.restype = restype
    fn.argtypes = argtypes
    return lib


class CircularWriter(BufferWriter):
    """1 writer → N broadcast readers over a double-mapped region."""

    def __init__(self, dtype, capacity: int, writer_inbox: BlockInbox,
                 writer_port_index: int = 0):
        lib = load_native()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self.dtype = np.dtype(dtype)
        want_bytes = int(capacity) * self.dtype.itemsize
        self._dbuf = lib.fsdr_dbuf_create(want_bytes)
        if not self._dbuf:
            raise MemoryError("fsdr_dbuf_create failed")
        size_bytes = lib.fsdr_dbuf_size(self._dbuf)
        self.capacity = size_bytes // self.dtype.itemsize
        ptr = lib.fsdr_dbuf_ptr(self._dbuf)
        # View over BOTH mappings: 2×capacity items, [i] and [i+capacity] alias.
        raw = np.ctypeslib.as_array(ptr, shape=(2 * size_bytes,))[:2 * size_bytes]
        n_items = (2 * size_bytes) // self.dtype.itemsize
        self._data = raw.view(self.dtype)[:n_items]
        self._ring = lib.fsdr_ring_create(self.capacity)
        self._readers: List["CircularReader"] = []
        self._inbox = writer_inbox
        self._port_index = writer_port_index
        self._finished = False
        # tag lists are per-reader, python-side (control plane, low rate)
        self._tag_lock = threading.Lock()

    def __del__(self):
        try:
            if getattr(self, "_ring", None):
                self._lib.fsdr_ring_destroy(self._ring)
                self._ring = None
            if getattr(self, "_dbuf", None):
                self._lib.fsdr_dbuf_destroy(self._dbuf)
                self._dbuf = None
        except Exception:
            pass

    # -- connect ---------------------------------------------------------------
    def add_reader(self, reader_inbox: BlockInbox, port_index: int,
                   min_items: int = 1) -> "CircularReader":
        idx = self._lib.fsdr_ring_add_reader(self._ring)
        if idx < 0:
            raise RuntimeError(
                "too many readers on one circular buffer (native cap: 16, "
                "FSDR_MAX_READERS in native/ringbuf.cpp). For wider broadcast "
                "fan-out use the portable ring buffer (buffer='ring', unbounded "
                "readers) on this edge.")
        r = CircularReader(self, idx, reader_inbox, port_index)
        self._readers.append(r)
        return r

    # -- writer side -----------------------------------------------------------
    def slice(self) -> np.ndarray:
        space = self._lib.fsdr_ring_space(self._ring)
        off = self._lib.fsdr_ring_wpos(self._ring) % self.capacity
        return self._data[off:off + space]   # contiguous thanks to double mapping

    def space_available(self) -> int:
        return int(self._lib.fsdr_ring_space(self._ring))

    def produce(self, n: int, tags: Sequence[ItemTag] = ()) -> None:
        if n == 0:
            return
        if tags:
            base = self._lib.fsdr_ring_wpos(self._ring)
            with self._tag_lock:
                for r in self._readers:
                    if not r._detached:
                        r._tags.extend(ItemTag(base + t.index, t.tag) for t in tags)
        self._lib.fsdr_ring_produce(self._ring, n)
        for r in self._readers:
            if not r._detached:
                r._inbox.notify()

    def notify_finished(self) -> None:
        if self._finished:
            return
        self._finished = True
        for r in self._readers:
            if not r._detached:
                r._inbox.send(StreamInputDone(r.port_index))


class CircularReader(BufferReader):
    def __init__(self, writer: CircularWriter, ring_idx: int,
                 inbox: BlockInbox, port_index: int):
        self._w = writer
        self._idx = ring_idx
        self._inbox = inbox
        self.port_index = port_index
        self._tags: List[ItemTag] = []
        self._detached = False

    def slice(self) -> np.ndarray:
        w = self._w
        avail = w._lib.fsdr_ring_available(w._ring, self._idx)
        off = w._lib.fsdr_ring_rpos(w._ring, self._idx) % w.capacity
        return w._data[off:off + avail]

    def items_available(self) -> int:
        return int(self._w._lib.fsdr_ring_available(self._w._ring, self._idx))

    def tags(self) -> List[ItemTag]:
        w = self._w
        pos = w._lib.fsdr_ring_rpos(w._ring, self._idx)
        with w._tag_lock:
            return [ItemTag(t.index - pos, t.tag) for t in self._tags if t.index >= pos]

    def consume(self, n: int) -> None:
        if n == 0:
            return
        w = self._w
        w._lib.fsdr_ring_consume(w._ring, self._idx, n)
        if self._tags:
            pos = w._lib.fsdr_ring_rpos(w._ring, self._idx)
            with w._tag_lock:
                self._tags = [t for t in self._tags if t.index >= pos]
        w._inbox.notify()   # space freed → wake writer block

    def notify_finished(self) -> None:
        if self._detached:
            return
        self._detached = True
        self._w._lib.fsdr_ring_remove_reader(self._w._ring, self._idx)
        self._w._inbox.send(StreamOutputDone(self._w._port_index))
