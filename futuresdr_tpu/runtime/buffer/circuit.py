"""Circuit (in-place) buffers: owned frames circulating through a pipeline.

Re-design of ``src/runtime/buffer/circuit.rs`` (reference): the source pops an EMPTY
frame, fills it, pushes it FULL to the next block; intermediate blocks mutate in place and
forward; the final block returns the frame to the source — closing the circuit
(``Flowgraph::close_circuit``, ``flowgraph.rs:433-491``). Zero copies end to end.

On the TPU path the same idea appears as donated device buffers (`TpuKernel` donates its
carry); this CPU version serves pipelines of mutating host blocks.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Deque, Optional, Sequence, Tuple

import numpy as np

from ..inbox import BlockInbox, StreamInputDone

__all__ = ["Circuit", "InplaceOutput", "InplaceInput"]


class Circuit:
    """The frame pool + the chain of stage queues."""

    def __init__(self, n_buffers: int, items_per_buffer: int, dtype):
        self.dtype = np.dtype(dtype)
        self.items = items_per_buffer
        self._lock = threading.Lock()
        self._empty: Deque[np.ndarray] = deque(
            np.zeros(items_per_buffer, self.dtype) for _ in range(n_buffers))
        self._source_inbox: Optional[BlockInbox] = None

    # -- source side -----------------------------------------------------------
    def attach_source(self, inbox: BlockInbox):
        self._source_inbox = inbox

    def get_empty(self) -> Optional[np.ndarray]:
        with self._lock:
            return self._empty.popleft() if self._empty else None

    def put_empty(self, buf: np.ndarray) -> None:
        """Return a frame to the pool (the closing edge of the circuit)."""
        with self._lock:
            self._empty.append(buf)
        if self._source_inbox is not None:
            self._source_inbox.notify()


class InplaceOutput:
    """Output port pushing full frames to the connected input(s) (`InplaceWriter`).

    Duck-types enough of :class:`..StreamOutput` to live in a kernel's port list.

    An inplace output wired to SEVERAL edges BROADCASTS: every consumer's
    queue receives every frame (the same 1-writer→N-reader semantics a stream
    output port group has, ``buffer/circular.py``). Device-plane frames are
    immutable jax arrays, so sharing the frame object across consumers is
    safe — this is the per-hop fallback topology the device-graph fan-out
    fusion pass (``runtime/devchain.py``) collapses into one multi-output
    dispatch. Backpressure is the SLOWEST consumer's: ``queue_depth`` reports
    the deepest queue, so a producer's in-flight gate parks until every
    branch caught up. NOTE for CPU circuit pipelines of MUTATING blocks: a
    broadcast consumer mutating the shared frame would be visible to its
    siblings — mutating circuits must stay single-reader (unchanged)."""

    def __init__(self, name: str, dtype=None):
        self.name = name
        self.dtype = np.dtype(dtype) if dtype is not None else None
        self.min_items = 1
        self.stalls = 0             # telemetry parity with StreamOutput (the
        #                             park classifier skips queue ports)
        self._peers: list = []
        self._finished = False

    @property
    def connected(self) -> bool:
        return bool(self._peers)

    def connect(self, peer: "InplaceInput"):
        # idempotent: re-running the same Flowgraph re-materializes its
        # edges, and appending the same consumer twice would push every
        # frame twice into its queue (and trip the broadcast guard below
        # for a single-reader circuit)
        if not any(p is peer for p in self._peers):
            self._peers.append(peer)

    def put_full(self, buf: np.ndarray, n_items: int, tags: Sequence = ()) -> None:
        """Push a full frame (+ frame-relative stream tags riding alongside it —
        the TPU plane's item-indexed metadata transport, SURVEY §7). With
        several peers every queue receives the frame (broadcast)."""
        if len(self._peers) > 1 and isinstance(buf, np.ndarray) \
                and buf.flags.writeable:
            # broadcast shares ONE frame object; the CPU circuit plane's
            # mutating consumers (and its put_empty pool return) would alias
            # it across branches — only immutable device-plane frames (jax
            # arrays) may broadcast. Raise HERE, where the frame kind is
            # known, rather than corrupt silently (class docstring).
            raise RuntimeError(
                f"inplace output {self.name!r} broadcasts to "
                f"{len(self._peers)} consumers, but the frame is a writable "
                f"host array — mutable circuit frames must stay "
                f"single-reader (device-plane jax frames may broadcast)")
        for p in self._peers:
            p.push(buf, n_items, tags)

    def queue_depth(self) -> int:
        """Frames waiting at the slowest consumer (backpressure signal)."""
        return max((len(p) for p in self._peers), default=0)

    def notify_finished(self) -> None:
        if self._peers and not self._finished:
            self._finished = True
            for p in self._peers:
                p.mark_finished()


class InplaceInput:
    """Input port receiving full frames (`InplaceReader`).

    Duck-types :class:`..StreamInput`'s event-loop surface (``set_finished``,
    ``notify_finished``, ``reader``) so the block event loop and validation treat it
    like any other input port.
    """

    def __init__(self, name: str, dtype=None):
        self.name = name
        self.dtype = np.dtype(dtype) if dtype is not None else None
        self.min_items = 1
        self.starved = 0            # telemetry parity with StreamInput
        self._q: Deque[Tuple[np.ndarray, int, tuple]] = deque()
        self._lock = threading.Lock()
        self._inbox: Optional[BlockInbox] = None
        self._port_index = 0
        self._finished = False

    # -- StreamInput duck-typing ----------------------------------------------
    @property
    def reader(self):
        return self._inbox          # non-None once bound ⇒ "connected"

    def set_finished(self) -> None:
        self._finished = True

    def finished(self) -> bool:
        return self._finished

    def notify_finished(self) -> None:
        pass                        # no upstream space accounting for circuits

    @property
    def connected(self) -> bool:
        return self._inbox is not None

    # -- circuit API -----------------------------------------------------------
    def bind(self, inbox: BlockInbox, port_index: int):
        self._inbox = inbox
        self._port_index = port_index

    def bind_producer(self, inbox: BlockInbox):
        """Wake the producing block when frames are taken (backpressure release)."""
        self._producer_inbox = inbox

    def push(self, buf: np.ndarray, n_items: int, tags: Sequence = ()) -> None:
        with self._lock:
            self._q.append((buf, n_items, tuple(tags)))
        if self._inbox is not None:
            self._inbox.notify()

    def get_full(self) -> Optional[Tuple[np.ndarray, int, tuple]]:
        with self._lock:
            item = self._q.popleft() if self._q else None
        if item is not None and getattr(self, "_producer_inbox", None) is not None:
            self._producer_inbox.notify()
        return item

    def __len__(self):
        return len(self._q)

    def mark_finished(self) -> None:
        if self._inbox is not None:
            self._inbox.send(StreamInputDone(self._port_index))
