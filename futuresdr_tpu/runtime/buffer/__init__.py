"""Buffer layer — the stream data plane between blocks.

Re-design of ``src/runtime/buffer/`` (reference, 5.3k LoC): writers/readers move sample items
through lock-free-ish shared memory with broadcast (1 writer → N readers), size negotiation at
connect time, tag transport with index rebasing, and EOS propagation through block inboxes
(``buffer/mod.rs:361-507``, ``buffer/circular.rs``).

Layering:
  * :class:`BufferWriter` / :class:`BufferReader` — backend interface (ring, slab, circuit, tpu).
  * :class:`StreamOutput` / :class:`StreamInput` — the port facades blocks declare as attributes
    (the reference's ``#[input]``/``#[output]`` struct fields, ``macros/src/lib.rs:494-1082``).
  * Buffer choice is per-connection, defaulting to the double-mapped circular buffer
    (the reference's ``DefaultCpuReader/Writer`` aliases, ``buffer/mod.rs:564-575``).
"""

from __future__ import annotations

import math
from abc import ABC, abstractmethod
from typing import List, Optional, Sequence, Type

import numpy as np

from ...config import config
from ..tag import ItemTag, Tag

__all__ = [
    "BufferReader",
    "BufferWriter",
    "StreamInput",
    "StreamOutput",
    "negotiate_capacity",
]


class BufferReader(ABC):
    """Reader endpoint of one connection (`buffer/mod.rs:361-384,445-477`)."""

    #: index of the input port on the consuming block (for StreamInputDone routing)
    port_index: int = 0

    @abstractmethod
    def slice(self) -> np.ndarray:
        """Readable view of available items (zero-copy where the backend allows)."""

    @abstractmethod
    def tags(self) -> List[ItemTag]:
        """Tags in the currently readable window, indices relative to the read position."""

    @abstractmethod
    def consume(self, n: int) -> None:
        """Advance the read position; wakes the upstream writer block."""

    @abstractmethod
    def notify_finished(self) -> None:
        """Reader's block finished: tell the upstream writer (`circular.rs:332-342`)."""

    def items_available(self) -> int:
        return len(self.slice())

    def capacity_items(self) -> Optional[int]:
        """Total ring capacity if the backend knows it (None otherwise)."""
        return getattr(getattr(self, "_w", None), "capacity", None) \
            or getattr(getattr(self, "_writer", None), "capacity", None)


class BufferWriter(ABC):
    """Writer endpoint owning the storage; broadcasts to N readers (`buffer/mod.rs:391-420`)."""

    @abstractmethod
    def add_reader(self, reader_inbox, port_index: int, min_items: int = 1) -> BufferReader:
        """Connect one more reader (`BufferWriter::connect`)."""

    @abstractmethod
    def slice(self) -> np.ndarray:
        """Writable view of free space."""

    @abstractmethod
    def produce(self, n: int, tags: Sequence[ItemTag] = ()) -> None:
        """Commit n written items (+ tags indexed relative to the write window); wakes readers."""

    @abstractmethod
    def notify_finished(self) -> None:
        """Writer's block finished: send StreamInputDone to every reader (`circular.rs:213-222`)."""

    def space_available(self) -> int:
        return len(self.slice())


def negotiate_capacity(itemsize: int, min_items_constraints: Sequence[int],
                       min_buffer_sizes: Sequence[int],
                       override_bytes: Optional[int] = None) -> int:
    """Connect-time size negotiation (`buffer/circular.rs:154-189`).

    Capacity in items = max(byte budget, explicit byte minimums, 2× the largest
    ``min_items`` constraint so a full work window always fits), rounded up to a
    power of two. The byte budget is ``override_bytes`` (a per-edge latency/
    throughput override) when given, else the config default.
    """
    if override_bytes is not None and override_bytes <= 0:
        raise ValueError(f"buffer_size override must be positive, got {override_bytes}")
    budget = override_bytes if override_bytes is not None else config().buffer_size
    items = max(1, budget // itemsize)
    for b in min_buffer_sizes:
        if b:
            items = max(items, math.ceil(b / itemsize))
    for m in min_items_constraints:
        if m:
            items = max(items, 2 * m)
    return 1 << (items - 1).bit_length()


class StreamOutput:
    """Output port facade declared by a block (`#[output]` field equivalent)."""

    def __init__(self, name: str, dtype, min_items: int = 1,
                 min_buffer_size: int = 0, buffer: Optional[Type] = None,
                 preferred_buffer_size: Optional[int] = None):
        self.name = name
        self.dtype = np.dtype(dtype) if dtype is not None else None
        self.min_items = min_items
        self.min_buffer_size = min_buffer_size
        self.preferred_buffer_size = preferred_buffer_size
        self.buffer = buffer          # backend class override for this port
        self.writer: Optional[BufferWriter] = None
        self._pending_tags: List[ItemTag] = []
        self.items_produced = 0       # observability counter (SURVEY §5 metrics)
        self.stalls = 0               # parks while this output ring was full
        #                               (counted by the block event loop)

    # -- work()-time API -------------------------------------------------------
    def slice(self) -> np.ndarray:
        return self.writer.slice()

    def space(self) -> int:
        return self.writer.space_available()

    def add_tag(self, index: int, tag: Tag) -> None:
        """Attach ``tag`` to item ``index`` of the next ``produce`` window."""
        self._pending_tags.append(ItemTag(index, tag))

    def produce(self, n: int) -> None:
        tags, self._pending_tags = self._pending_tags, []
        self.items_produced += n
        self.writer.produce(n, tags)

    def notify_finished(self) -> None:
        if self.writer is not None:
            self.writer.notify_finished()

    @property
    def connected(self) -> bool:
        return self.writer is not None


class StreamInput:
    """Input port facade declared by a block (`#[input]` field equivalent)."""

    def __init__(self, name: str, dtype, min_items: int = 1,
                 preferred_buffer_size: Optional[int] = None):
        self.name = name
        self.dtype = np.dtype(dtype) if dtype is not None else None
        self.min_items = min_items
        # latency hint: a port that feeds a real-time sink (audio, feedback loop)
        # prefers a short queue; honored at negotiation unless the edge overrides
        self.preferred_buffer_size = preferred_buffer_size
        self.reader: Optional[BufferReader] = None
        self._finished = False        # StreamInputDone received (upstream writer done)
        self.items_consumed = 0       # observability counter (SURVEY §5 metrics)
        self.starved = 0              # parks while this input was below min_items

    # -- work()-time API -------------------------------------------------------
    def slice(self) -> np.ndarray:
        return self.reader.slice()

    def available(self) -> int:
        return self.reader.items_available()

    def tags(self, n: Optional[int] = None) -> List[ItemTag]:
        ts = self.reader.tags()
        return ts if n is None else [t for t in ts if t.index < n]

    def consume(self, n: int) -> None:
        self.items_consumed += n
        self.reader.consume(n)

    def fill(self) -> Optional[float]:
        """Ring occupancy in [0, 1] (None when the backend hides its capacity) —
        the buffer-occupancy gauge sampled by ``WrappedKernel.metrics``."""
        if self.reader is None:
            return None
        cap = self.reader.capacity_items()
        if not cap:
            return None
        return min(1.0, self.reader.items_available() / cap)

    def finished(self) -> bool:
        """Upstream signalled EOS; buffered data may remain (`apply.rs:122-124` pattern)."""
        return self._finished

    def set_finished(self) -> None:
        self._finished = True

    def notify_finished(self) -> None:
        if self.reader is not None:
            self.reader.notify_finished()

    @property
    def connected(self) -> bool:
        return self.reader is not None
