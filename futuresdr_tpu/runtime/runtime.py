"""Runtime: launches flowgraphs and runs the per-flowgraph supervisor.

Re-design of ``src/runtime/runtime.rs`` (reference): ``run_flowgraph`` (``runtime.rs:363-597``)
is the supervisor coroutine — init barrier, message routing, error→terminate cascade, joins block
tasks, restores blocks into the flowgraph so final state stays readable. ``FlowgraphHandle``
(``src/runtime/flowgraph_handle.rs:21-171``) is the clonable control handle used by apps, the
REST control port, and tests.
"""

from __future__ import annotations

import asyncio
import threading
import time
from dataclasses import dataclass
from typing import Any, Awaitable, Dict, List, Optional, Union

from ..config import config
from ..log import logger
from ..telemetry.spans import recorder as _trace_recorder
from ..types import FlowgraphDescription, Pmt
from .block import WrappedKernel
from .flowgraph import Flowgraph
from .inbox import BlockInbox, Call, Callback, Initialize, ReplySlot, Terminate
from .kernel import Kernel
from .scheduler import AsyncScheduler, Scheduler

__all__ = [
    "Runtime",
    "FlowgraphHandle",
    "RunningFlowgraph",
    "RuntimeHandle",
    "InitializedMsg",
    "BlockDoneMsg",
    "BlockErrorMsg",
    "BlockRestartMsg",
    "CancelMsg",
    "FlowgraphError",
    "FlowgraphCancelled",
]

log = logger("runtime")
_trace = _trace_recorder()


# ---- FlowgraphMessage equivalents (`src/runtime/mod.rs` FlowgraphMessage) ----
class FlowgraphMessage:
    __slots__ = ()


@dataclass(frozen=True)
class InitializedMsg(FlowgraphMessage):
    block_id: int
    ok: bool


@dataclass(frozen=True)
class BlockDoneMsg(FlowgraphMessage):
    block_id: int
    block: WrappedKernel


@dataclass(frozen=True)
class BlockErrorMsg(FlowgraphMessage):
    block_id: int
    error: Exception


@dataclass(frozen=True)
class BlockRestartMsg(FlowgraphMessage):
    """A block restarted itself under its ``restart`` policy (informational —
    the supervisor records the decision; the block handles the re-init)."""
    block_id: int
    attempt: int
    error: Exception
    phase: str                       # "init" | "work"


@dataclass(frozen=True)
class CancelMsg(FlowgraphMessage):
    """Cancel the run WITH an error: terminate cascade + a
    :class:`FlowgraphCancelled` in the final error set — unlike TerminateMsg,
    which is a *successful* early stop. Sent by the run-deadline path
    (``Runtime.run(timeout=)``) and the doctor's ``doctor_action: cancel``
    escalation; ``flight_record`` is the dump path when one was written."""
    reason: str
    flight_record: Optional[str] = None


@dataclass(frozen=True)
class BlockCallMsg(FlowgraphMessage):
    block_id: int
    port: Any
    data: Pmt


@dataclass(frozen=True)
class BlockCallbackMsg(FlowgraphMessage):
    block_id: int
    port: Any
    data: Pmt
    reply: ReplySlot


@dataclass(frozen=True)
class DescribeMsg(FlowgraphMessage):
    reply: ReplySlot


@dataclass(frozen=True)
class MetricsMsg(FlowgraphMessage):
    reply: ReplySlot


@dataclass(frozen=True)
class TerminateMsg(FlowgraphMessage):
    pass


class FlowgraphError(RuntimeError):
    """A block errored (or the run was cancelled) and the flowgraph ended
    (`tests/fail.rs` behavior), carrying the structured failure story:

    * ``errors`` — every collected exception (multi-block failures are
      aggregated, not dropped; concurrent errors each appear once);
    * ``blocks`` — the faulted block's instance name per error (None for
      non-block errors such as a cancel);
    * ``policy_decisions`` — the per-block policy actions the supervisor took
      (restart attempts, isolations, restart-exhausted escalations, cancels);
    * ``flight_record`` — path of the doctor's flight-record dump when one was
      written for this failure (None otherwise).
    """

    def __init__(self, message: str, *, errors=(), blocks=(),
                 policy_decisions=(), flight_record: Optional[str] = None):
        super().__init__(message)
        self.errors: List[Exception] = list(errors)
        self.blocks: List[Optional[str]] = list(blocks)
        self.policy_decisions: List[dict] = list(policy_decisions)
        self.flight_record = flight_record


class FlowgraphCancelled(RuntimeError):
    """The error recorded when a run is cancelled by deadline or doctor."""


def _make_flowgraph_error(errors, blocks, decisions,
                          flight_record=None) -> FlowgraphError:
    """Aggregate the collected block errors into ONE structured error.
    Single-error message stays ``str(error)`` (the historical contract tests
    match on); multi-error messages carry the count and every block."""
    pairs = list(zip(blocks, errors))
    if len(errors) == 1:
        msg = str(errors[0])
    else:
        msg = f"{len(errors)} blocks failed: " + "; ".join(
            f"{b or '<runtime>'}: {e!r}" for b, e in pairs)
    return FlowgraphError(msg, errors=errors, blocks=blocks,
                          policy_decisions=decisions,
                          flight_record=flight_record)


async def run_flowgraph_supervisor(fg: Flowgraph, scheduler: Scheduler,
                                   fg_inbox: BlockInbox,
                                   initialized: ReplySlot) -> Flowgraph:
    """The per-flowgraph supervisor (`runtime.rs:363-597`)."""
    from ..telemetry.doctor import doctor as _doctor
    from .devchain import (find_device_chains, run_devchain_task,
                           shed_devchain_bridge)
    from .fastchain import (find_native_chains, run_chain_task,
                            shed_metrics_bridge)
    t_sup = _trace.now()
    chain_kernels = find_native_chains(fg)
    dev_chains = find_device_chains(fg)
    blocks = fg.take_blocks()
    by_id: Dict[int, WrappedKernel] = {b.id: b for b in blocks}
    # native fast-chain substitution (see fastchain.py): whole pipes of trivial
    # stream blocks run in one C++ thread instead of per-block actor tasks; the
    # chain task speaks the same supervisor protocol for every member.
    # Device-graph fusion (see devchain.py) does the same for device-plane
    # runs: one fused TpuKernel dispatch per frame instead of one per hop.
    wk = {id(b.kernel): b for b in blocks}
    # flowgraph-doctor attachment (telemetry/doctor.py): the stall watchdog
    # samples these blocks' progress counters and classifies wedges over the
    # resolved stream edges; the finally below keeps completed flowgraphs out
    # of the watch list, and an unexpected supervisor exit flight-records the
    # terminal state before propagating
    _doc = _doctor()

    def _doctor_cancel(diag: dict, path: Optional[str]) -> None:
        # doctor_action=cancel escalation: called from the watchdog thread
        # AFTER the flight record landed — the send is thread-safe, and the
        # supervisor converts it into a FlowgraphError carrying the record
        fg_inbox.send(CancelMsg(
            f"doctor watchdog: {diag.get('state')} — {diag.get('detail')}",
            path))

    _doc_token = _doc.attach(blocks, [
        (wk[id(e.src)], e.src_port, wk[id(e.dst)], e.dst_port)
        for e in fg.stream_edges if id(e.src) in wk and id(e.dst) in wk],
        cancel=_doctor_cancel)
    # failure bookkeeping (read by the except clause below — defined before
    # the try so an early supervisor error still reports sane state)
    errors: List[Exception] = []
    err_blocks: List[Optional[str]] = []       # instance name per error
    decisions: List[dict] = []                 # policy actions taken
    flight_paths: List[str] = []               # CancelMsg-attached dumps
    # isolate groups (docs/robustness.md): blocks sharing an isolate_group
    # retire TOGETHER — one member's failure EOSes the whole named subgraph
    # in topological order while unrelated branches finish
    groups: Dict[str, List[WrappedKernel]] = {}
    for b in blocks:
        g = b.policy.isolate_group
        if g:
            groups.setdefault(g, []).append(b)
    if groups:
        ranks = _topo_ranks(fg, wk)
        for g in groups:
            groups[g].sort(key=lambda b: ranks.get(id(b), 0))
    retired_groups: set = set()

    def retire_group(group: str, origin: str, err) -> None:
        """Retire every member of ``group`` after ``origin``'s failure:
        record the GROUP verdict (one decision naming every member — the
        flight record and `GET /api/fg/{fg}/` surface it), then EOS the
        surviving members' ports source→sink and terminate them so no
        survivor waits on a half-dead branch. Idempotent per group."""
        if group in retired_groups:
            return
        retired_groups.add(group)
        members_g = groups.get(group, [])
        decisions.append({"block": origin, "action": "isolate_group",
                          "group": group,
                          "members": [m.instance_name for m in members_g],
                          "error": repr(err)})
        log.error("block %s failed (%r): isolate group %r retires %s; "
                  "flowgraph continues", origin, err, group,
                  [m.instance_name for m in members_g])
        _trace.instant("runtime", "group_isolated",
                       args={"group": group, "origin": origin,
                             "members": [m.instance_name
                                         for m in members_g]})
        for m in members_g:
            if m.instance_name == origin:
                continue                 # its own error path EOSed already
            m.inbox.send(Terminate())
            try:
                # EOS NOW, in topo order, from here: waiting for each
                # member's own orderly shutdown would release the ports in
                # scheduler order instead (notify_finished is idempotent —
                # the member's shutdown repeats it harmlessly)
                m._notify_ports_finished()
            except Exception as e2:                    # noqa: BLE001
                log.debug("group EOS of %s raised: %r", m.instance_name, e2)
    try:
        fused: set = set()
        chain_tasks = []
        for ch in chain_kernels:
            members = [wk[id(k)] for k in ch]
            fused.update(id(b) for b in members)
            chain_tasks.append((members, getattr(ch, "in_ring", None)))
        dev_tasks = []
        for ch in dev_chains:
            members = [wk[id(k)] for k in ch]
            fused.update(id(b) for b in members)
            dev_tasks.append((members, ch))
        actor_blocks = [b for b in blocks if id(b) not in fused]
        for b in actor_blocks:
            # a kernel that fused in a PREVIOUS flowgraph but runs the actor
            # path now sheds its stale metrics bridge (each pass owns its
            # convention)
            shed_metrics_bridge(b.kernel)
            shed_devchain_bridge(b.kernel)
        handles = scheduler.run_flowgraph_blocks(actor_blocks, fg_inbox)
        for members, inr in chain_tasks:
            handles.append(scheduler.spawn(
                run_chain_task(members, fg_inbox, scheduler, in_ring=inr)))
        for members, ch in dev_tasks:
            handles.append(scheduler.spawn(
                run_devchain_task(members, ch, fg_inbox, scheduler)))

        # ---- init barrier (`runtime.rs:380-415`) ----------------------------
        t_barrier = _trace.now()
        for b in blocks:
            b.inbox.send(Initialize())
        waiting = len(blocks)
        active = len(blocks)
        finished: List[WrappedKernel] = []
        failed: List[WrappedKernel] = []       # errored blocks — restored too,
        #   so post-mortem metrics/ports stay readable (chaos invariant)
        queued: List[FlowgraphMessage] = []
        fatal_init: Optional[Exception] = None
        abandoned = False      # a cancel arrived while a block sat inside
        #   init(): that block can never be joined — the supervisor abandons
        #   the barrier (and the joins) instead of hanging with it
        while waiting > 0:
            msg = await fg_inbox.recv()
            if isinstance(msg, InitializedMsg):
                waiting -= 1
            elif isinstance(msg, BlockErrorMsg):
                waiting -= 1
                active -= 1
                errors.append(msg.error)
                blk = by_id.get(msg.block_id)
                name = blk.instance_name if blk else str(msg.block_id)
                err_blocks.append(name)
                if blk is not None:
                    failed.append(blk)
                if blk is not None and blk.policy.on_error == "isolate":
                    # the block EOSed its ports before reporting (block.py
                    # init-failure path) — the rest of the graph runs on
                    if blk.policy.isolate_group:
                        retire_group(blk.policy.isolate_group, name,
                                     msg.error)
                    else:
                        decisions.append({"block": name, "action": "isolate",
                                          "phase": "init",
                                          "error": repr(msg.error)})
                        log.error("block %s failed in init (%r): isolated by "
                                  "policy, flowgraph continues", name,
                                  msg.error)
                else:
                    fatal_init = fatal_init or msg.error
            elif isinstance(msg, BlockDoneMsg):
                waiting -= 1
                active -= 1
                finished.append(msg.block)
            elif isinstance(msg, BlockRestartMsg):
                _record_restart(decisions, by_id, msg)
            elif isinstance(msg, CancelMsg):
                # doctor_action=cancel / run-deadline cancel while the
                # barrier waits: the wedged init will never report, so
                # waiting it out would hang the very path that promises not
                # to — record the cancel and abandon the barrier
                errors.append(FlowgraphCancelled(msg.reason))
                err_blocks.append(None)
                decisions.append({"block": None, "action": "cancel",
                                  "reason": msg.reason})
                if msg.flight_record:
                    flight_paths.append(msg.flight_record)
                fatal_init = fatal_init or errors[-1]
                abandoned = True
                log.error("flowgraph cancelled during the init barrier "
                          "(%s): abandoning blocks still inside init()",
                          msg.reason)
                break
            else:
                queued.append(msg)  # early control messages; replay after barrier

        terminated = False
        if fatal_init is not None:
            for b in blocks:
                b.inbox.send(Terminate())
            terminated = True

        _trace.complete("runtime", "init_barrier", t_barrier,
                        args={"blocks": len(blocks), "errors": len(errors)})

        # ---- start signal (`runtime.rs:418-429`) ----------------------------
        for b in blocks:
            b.inbox.notify()
        initialized.set(fatal_init)

        # ---- main loop (`runtime.rs:440-571`) -------------------------------
        def handle(msg: FlowgraphMessage):
            nonlocal active, terminated
            if isinstance(msg, BlockCallMsg):
                blk = by_id.get(msg.block_id)
                if blk is not None:
                    blk.inbox.send(Call(msg.port, msg.data))
            elif isinstance(msg, BlockCallbackMsg):
                blk = by_id.get(msg.block_id)
                if blk is None:
                    msg.reply.set(Pmt.invalid_value())
                else:
                    blk.inbox.send(Callback(msg.port, msg.data, msg.reply))
            elif isinstance(msg, DescribeMsg):
                msg.reply.set(_describe(fg, blocks, decisions))
            elif isinstance(msg, MetricsMsg):
                msg.reply.set({b.instance_name: b.metrics() for b in blocks})
            elif isinstance(msg, TerminateMsg):
                if not terminated:
                    _trace.instant("runtime", "terminate_cascade",
                                   args={"reason": "requested"})
                    for b in blocks:
                        b.inbox.send(Terminate())
                    terminated = True
            elif isinstance(msg, CancelMsg):
                # deadline / doctor escalation: a terminate cascade that ALSO
                # records an error, so the run raises instead of "succeeding"
                errors.append(FlowgraphCancelled(msg.reason))
                err_blocks.append(None)
                decisions.append({"block": None, "action": "cancel",
                                  "reason": msg.reason})
                if msg.flight_record:
                    flight_paths.append(msg.flight_record)
                if not terminated:
                    log.error("flowgraph cancelled: %s", msg.reason)
                    _trace.instant("runtime", "terminate_cascade",
                                   args={"reason": "cancel"})
                    for b in blocks:
                        b.inbox.send(Terminate())
                    terminated = True
            elif isinstance(msg, BlockRestartMsg):
                _record_restart(decisions, by_id, msg)
            elif isinstance(msg, BlockDoneMsg):
                active -= 1
                finished.append(msg.block)
            elif isinstance(msg, BlockErrorMsg):
                active -= 1
                errors.append(msg.error)
                blk = by_id.get(msg.block_id)
                name = blk.instance_name if blk else str(msg.block_id)
                err_blocks.append(name)
                if blk is not None:
                    failed.append(blk)
                action = blk.policy.on_error if blk is not None else "fail_fast"
                if action == "isolate" and not terminated:
                    # the block's own error path already EOSed its ports —
                    # downstream drains, upstream detaches, independent
                    # branches keep running; the error still surfaces in the
                    # final structured FlowgraphError
                    if blk is not None and blk.policy.isolate_group:
                        # group verdict: the whole named subgraph retires
                        retire_group(blk.policy.isolate_group, name,
                                     msg.error)
                    else:
                        decisions.append({"block": name, "action": "isolate",
                                          "error": repr(msg.error)})
                        log.error("block %s errored (%r): isolated by "
                                  "policy, flowgraph continues", name,
                                  msg.error)
                        _trace.instant("runtime", "block_isolated",
                                       args={"block": msg.block_id})
                elif not terminated:
                    decisions.append(
                        {"block": name,
                         "action": ("restarts_exhausted"
                                    if action == "restart" else "fail_fast"),
                         "error": repr(msg.error)})
                    log.error("block %d errored (%r): terminating flowgraph",
                              msg.block_id, msg.error)
                    _trace.instant("runtime", "terminate_cascade",
                                   args={"reason": "block_error",
                                         "block": msg.block_id})
                    for b in blocks:
                        b.inbox.send(Terminate())
                    terminated = True

        for msg in queued:
            handle(msg)
        while active > 0 and not abandoned:
            handle(await fg_inbox.recv())

        # ---- join + restore (`runtime.rs:589-596`) --------------------------
        if not abandoned:
            for h in handles:
                try:
                    await h
                except Exception as e:
                    log.error("block task raised: %r", e)
        # abandoned: the block wedged inside init() cannot be joined; the
        # healthy blocks got Terminate and wind down in the background
        # against the closed inbox below (their late sends return False)
        # refuse new control sends, then answer anything still queued: a call
        # into a finished flowgraph returns InvalidValue instead of hanging
        # the caller
        fg_inbox.close()
        while True:
            msg = fg_inbox.try_recv()
            if msg is None:
                break
            if isinstance(msg, BlockCallbackMsg):
                msg.reply.set(Pmt.invalid_value())
            elif isinstance(msg, DescribeMsg):
                msg.reply.set(_describe(fg, blocks, decisions))
            elif isinstance(msg, MetricsMsg):
                # a metrics() racing flowgraph completion landed here after the
                # main loop exited — answer with the FINAL per-block snapshot
                # instead of silently dropping the reply (the caller would
                # await forever; `FlowgraphHandle.metrics` only short-circuits
                # to {} when the send itself fails)
                msg.reply.set({b.instance_name: b.metrics() for b in blocks})
        # post-run describe (REST `GET /api/fg/{fg}/`, fg.describe())
        # keeps the final policy story: the same decision dicts a
        # FlowgraphError would carry, surfaced for RECOVERED runs too
        fg._policy_decisions = list(decisions)
        fg.restore_blocks(finished + failed)
        _trace.complete("runtime", "flowgraph", t_sup,
                        args={"blocks": len(blocks), "errors": len(errors)})
        if errors:
            raise _make_flowgraph_error(
                errors, err_blocks, decisions,
                flight_record=flight_paths[0] if flight_paths else None,
            ) from errors[0]
        return fg
    except BaseException as e:
        # unhandled supervisor exit (incl. the FlowgraphError raise above):
        # flight-record the terminal state BEFORE detaching — watchdog-enabled
        # processes get a black box for post-mortem, others skip silently.
        # The record's `supervisor` section surfaces the aggregated error
        # count and policy decisions (multi-block failures are not dropped).
        paths = _doc.on_supervisor_error(
            e, extra={"block_errors": len(errors),
                      "blocks": [b for b in err_blocks if b],
                      "policy_decisions": list(decisions)})
        if isinstance(e, FlowgraphError) and e.flight_record is None and paths:
            e.flight_record = paths[0]
        raise
    finally:
        _doc.detach(_doc_token)


def _topo_ranks(fg: Flowgraph, wk: Dict[int, WrappedKernel]) -> Dict[int, int]:
    """Topological rank per WrappedKernel id over the data-plane edges
    (stream + inplace), sources first; ties keep block order, cycles fall
    back to block order. Isolate-group retirement EOSes members in this
    order so the cascade always releases upstream-to-downstream — no
    survivor waits on a half-dead branch (``runtime/block.py`` isolate
    contract, widened to subgraphs)."""
    edges = []
    for e in list(fg.stream_edges) + list(getattr(fg, "inplace_edges", [])):
        if id(e.src) in wk and id(e.dst) in wk:
            edges.append((id(wk[id(e.src)]), id(wk[id(e.dst)])))
    indeg: Dict[int, int] = {id(b): 0 for b in wk.values()}
    out: Dict[int, list] = {}
    for s, d in edges:
        indeg[d] = indeg.get(d, 0) + 1
        out.setdefault(s, []).append(d)
    order = [k for k, v in indeg.items() if v == 0]
    seen = set(order)
    i = 0
    while i < len(order):
        for d in out.get(order[i], ()):
            indeg[d] -= 1
            if indeg[d] == 0 and d not in seen:
                order.append(d)
                seen.add(d)
        i += 1
    ranks = {k: r for r, k in enumerate(order)}
    nxt = len(order)
    for k in indeg:                      # cycle remnants: stable tail
        if k not in ranks:
            ranks[k] = nxt
            nxt += 1
    return ranks


def _record_restart(decisions: List[dict], by_id, msg: "BlockRestartMsg"):
    blk = by_id.get(msg.block_id)
    name = blk.instance_name if blk else str(msg.block_id)
    decisions.append({"block": name, "action": "restart",
                      "attempt": msg.attempt, "phase": msg.phase,
                      "error": repr(msg.error)})


def _describe(fg: Flowgraph, blocks: List[WrappedKernel],
              decisions=()) -> FlowgraphDescription:
    desc = FlowgraphDescription(id=0, blocks=[b.description() for b in blocks])
    desc.stream_edges = [
        (fg.block_id(e.src), e.src_port, fg.block_id(e.dst), e.dst_port)
        for e in fg.stream_edges
    ]
    desc.message_edges = [
        (fg.block_id(e.src), e.src_port, fg.block_id(e.dst), e.dst_port)
        for e in fg.message_edges
    ]
    desc.policy_decisions = list(decisions)
    return desc


class FlowgraphHandle:
    """Clonable control handle (`flowgraph_handle.rs:21-171`).

    Async methods must run on the scheduler loop; the ``*_sync`` variants bridge from plain
    threads (the reference's ``block_on``).
    """

    def __init__(self, fg: Flowgraph, fg_inbox: BlockInbox, scheduler: Scheduler):
        self._fg = fg
        self._inbox = fg_inbox
        self._scheduler = scheduler

    def _bid(self, block: Union[Kernel, int]) -> int:
        return block if isinstance(block, int) else self._fg.block_id(block)

    # -- async API -------------------------------------------------------------
    async def post(self, block: Union[Kernel, int], port, data: Pmt = None) -> None:
        """Fire-and-forget handler invocation (`flowgraph_handle.rs:64-83`)."""
        data = Pmt.from_py(data) if not isinstance(data, Pmt) else data
        self._inbox.send(BlockCallMsg(self._bid(block), port, data))

    async def call(self, block: Union[Kernel, int], port, data: Pmt = None) -> Pmt:
        """Invoke a handler and await its Pmt result (`flowgraph_handle.rs:85-104`)."""
        data = Pmt.from_py(data) if not isinstance(data, Pmt) else data
        reply = ReplySlot()
        if not self._inbox.send(BlockCallbackMsg(self._bid(block), port, data, reply)):
            return Pmt.invalid_value()   # flowgraph already completed
        return await reply.get()

    async def describe(self) -> FlowgraphDescription:
        reply = ReplySlot()
        if not self._inbox.send(DescribeMsg(reply)):
            return self._fg.describe()   # flowgraph completed; describe statically
        return await reply.get()

    async def metrics(self) -> dict:
        """Per-block runtime metrics (work calls/time, items in/out, messages)."""
        reply = ReplySlot()
        if not self._inbox.send(MetricsMsg(reply)):
            return {}
        return await reply.get()

    def metrics_sync(self) -> dict:
        return self._scheduler.run_coro_sync(self.metrics())

    async def terminate(self) -> None:
        self._inbox.send(TerminateMsg())

    async def cancel(self, reason: str = "requested",
                     flight_record: Optional[str] = None) -> None:
        """Terminate WITH an error: the run raises a FlowgraphError carrying
        ``reason`` (and ``flight_record``) instead of completing cleanly."""
        self._inbox.send(CancelMsg(reason, flight_record))

    def cancel_sync(self, reason: str = "requested",
                    flight_record: Optional[str] = None) -> None:
        self._inbox.send(CancelMsg(reason, flight_record))

    # -- sync bridges ----------------------------------------------------------
    def post_sync(self, block, port, data: Pmt = None) -> None:
        data = Pmt.from_py(data) if not isinstance(data, Pmt) else data
        self._inbox.send(BlockCallMsg(self._bid(block), port, data))

    def call_sync(self, block, port, data: Pmt = None) -> Pmt:
        return self._scheduler.run_coro_sync(self.call(block, port, data))

    def describe_sync(self) -> FlowgraphDescription:
        return self._scheduler.run_coro_sync(self.describe())

    def terminate_sync(self) -> None:
        self._inbox.send(TerminateMsg())


class RunningFlowgraph:
    """Handle + completion future (`src/runtime/running_flowgraph.rs:19-98`)."""

    def __init__(self, handle: FlowgraphHandle, task: Awaitable, scheduler: Scheduler):
        self.handle = handle
        self._task = task
        self._scheduler = scheduler

    @staticmethod
    def _resolve_timeout(timeout: Optional[float]) -> Optional[float]:
        """Explicit argument wins; else the ``run_timeout`` config knob
        (0 = no deadline)."""
        if timeout is not None:
            return float(timeout) or None
        from ..config import config
        return float(config().get("run_timeout", 0.0)) or None

    async def wait(self, timeout: Optional[float] = None) -> Flowgraph:
        """Await completion; returns the flowgraph with final block state.

        ``timeout`` (or the ``run_timeout`` config knob) bounds the wait: on
        expiry the run is flight-recorded, cancelled (EOS drain + join, the
        graceful path), and raises a structured FlowgraphError instead of
        hanging the caller — a wedged pytest gets a diagnosis, not a kill.

        Loop-safe: the join task lives on the SCHEDULER loop (start_async
        delegates launches there), so awaiting from any other loop bridges via
        ``run_coroutine_threadsafe`` — awaiting a foreign-loop task directly is
        a RuntimeError in asyncio."""
        timeout = self._resolve_timeout(timeout)
        if asyncio.get_running_loop() is not self._scheduler.loop:
            fut = asyncio.run_coroutine_threadsafe(self._wait_impl(timeout),
                                                   self._scheduler.loop)
            return await asyncio.wrap_future(fut)
        return await self._wait_impl(timeout)

    def wait_sync(self, timeout: Optional[float] = None) -> Flowgraph:
        return self._scheduler.run_coro_sync(
            self._wait_impl(self._resolve_timeout(timeout)))

    async def _wait_impl(self, timeout: Optional[float]) -> Flowgraph:
        if timeout is None:
            return await self._task
        try:
            return await asyncio.wait_for(asyncio.shield(self._task), timeout)
        except asyncio.TimeoutError:
            pass
        # deadline blown: record the black box FIRST (live state), then
        # cancel — the supervisor converts the CancelMsg into a structured
        # FlowgraphError carrying the record path
        from ..config import config
        from ..telemetry.doctor import doctor as _doctor
        d = _doctor()
        paths = d.dump(d.flight_record(f"run_timeout:{timeout}s"))
        path = paths[0] if paths else None
        log.error("flowgraph exceeded its %.3fs run deadline: cancelling "
                  "(flight record: %s)", timeout, path or "in memory")
        self.handle.cancel_sync(f"run deadline exceeded ({timeout}s)", path)
        # grace=0 means "give up right after the cancel", never "wait forever"
        grace = max(0.0, float(config().get("run_timeout_grace", 5.0)))
        try:
            if grace > 0:
                return await asyncio.wait_for(asyncio.shield(self._task),
                                              grace)
            raise asyncio.TimeoutError
        except asyncio.TimeoutError:
            # a block is wedged INSIDE work() and cannot see Terminate — give
            # the caller its thread back with the story attached; the block
            # thread is abandoned (the flight record has its stack)
            raise FlowgraphError(
                f"flowgraph did not terminate within {grace}s of the "
                f"deadline cancel (run deadline {timeout}s) — a block is "
                "wedged inside work(); see the flight record",
                errors=[FlowgraphCancelled("run deadline exceeded")],
                blocks=[None],
                policy_decisions=[{"block": None, "action": "cancel",
                                   "reason": "run deadline exceeded"}],
                flight_record=path) from None

    async def _wrap(self):
        return await self._task

    async def stop(self) -> Flowgraph:
        await self.handle.terminate()
        return await self.wait()

    def stop_sync(self) -> Flowgraph:
        self.handle.terminate_sync()
        return self.wait_sync()


class RuntimeHandle:
    """Registry of running flowgraphs for the control plane (`runtime.rs:311-349`)."""

    def __init__(self, scheduler: Scheduler):
        self.scheduler = scheduler
        self._flowgraphs: Dict[int, FlowgraphHandle] = {}
        self._next_id = 0
        self._lock = threading.Lock()

    def register(self, handle: FlowgraphHandle) -> int:
        with self._lock:
            fg_id = self._next_id
            self._next_id += 1
            self._flowgraphs[fg_id] = handle
            return fg_id

    def unregister(self, fg_id: int) -> None:
        with self._lock:
            self._flowgraphs.pop(fg_id, None)

    def get_flowgraph(self, fg_id: int) -> Optional[FlowgraphHandle]:
        with self._lock:
            return self._flowgraphs.get(fg_id)

    def flowgraph_ids(self) -> List[int]:
        with self._lock:
            return list(self._flowgraphs)


class Runtime:
    """Owns the scheduler and (optionally) the REST control port (`runtime.rs:55-207`)."""

    def __init__(self, scheduler: Optional[Scheduler] = None, extra_routes=None):
        """``extra_routes``: ``[(method, path, async_handler), …]`` mounted on the
        control-port aiohttp app beside the ``/api/fg/`` families — the
        ``Runtime::with_custom_routes`` extension point
        (`examples/custom-routes/src/main.rs:33-42`); see
        ``examples/custom_routes.py``. Ignored when the control port is disabled."""
        if scheduler is None:
            if config().default_scheduler == "threaded":
                from .scheduler import ThreadedScheduler
                scheduler = ThreadedScheduler()
            else:
                scheduler = AsyncScheduler()
        self.scheduler = scheduler
        self.handle = RuntimeHandle(self.scheduler)
        if config().doctor:
            # FUTURESDR_TPU_DOCTOR=1: the stall watchdog runs for the life of
            # the process (enable() is idempotent across Runtime constructions)
            from ..telemetry.doctor import enable as _doctor_enable
            _doctor_enable()
        self._ctrl_port = None
        if config().ctrlport_enable:
            from .ctrl_port import ControlPort
            self._ctrl_port = ControlPort(self.handle, extra_routes=extra_routes)
            self._ctrl_port.start()

    # -- async API -------------------------------------------------------------
    async def start_async(self, fg: Flowgraph) -> RunningFlowgraph:
        """Launch; resolves once all blocks passed the init barrier (`runtime.rs:169-191`).

        Callable from ANY event loop: when invoked off the scheduler loop (e.g.
        inside a control-port handler — ``examples/custom_routes.py``, reference
        `examples/custom-routes/src/main.rs:65-76`), the launch is delegated to
        the scheduler loop so the supervisor and block tasks land where
        ``run_flowgraph_blocks`` and every sync bridge expect them."""
        self.scheduler.start()
        if asyncio.get_running_loop() is not self.scheduler.loop:
            fut = asyncio.run_coroutine_threadsafe(
                self._start_on_scheduler(fg), self.scheduler.loop)
            return await asyncio.wrap_future(fut)
        return await self._start_on_scheduler(fg)

    async def _start_on_scheduler(self, fg: Flowgraph) -> RunningFlowgraph:
        fg_inbox = BlockInbox()
        initialized = ReplySlot()
        loop = asyncio.get_running_loop()
        task = loop.create_task(
            run_flowgraph_supervisor(fg, self.scheduler, fg_inbox, initialized))
        handle = FlowgraphHandle(fg, fg_inbox, self.scheduler)
        fg_id = self.handle.register(handle)
        try:
            err = await initialized.get()
        except asyncio.CancelledError:
            # launch abandoned (run_async's init deadline): a LATE-completing
            # barrier must terminate instead of running detached — the
            # CancelMsg queues during the barrier and replays right after it.
            # A sweeper owns the join: it retrieves the supervisor's
            # (expected) FlowgraphError and unregisters the handle.
            fg_inbox.send(CancelMsg(
                "launch abandoned: run deadline exceeded in init"))

            async def _sweep():
                try:
                    await task
                except BaseException:          # noqa: BLE001 — expected
                    pass                       # cancel-induced FlowgraphError
                finally:
                    self.handle.unregister(fg_id)

            loop.create_task(_sweep())
            raise
        join = loop.create_task(_unregister_on_done(task, self.handle, fg_id))
        running = RunningFlowgraph(handle, join, self.scheduler)
        if err is not None:
            # propagate init failure after blocks drained (`tests/fail.rs:66-104`)
            try:
                await running.wait()
            finally:
                self.handle.unregister(fg_id)
            raise FlowgraphError(str(err)) from err
        return running

    async def run_async(self, fg: Flowgraph,
                        timeout: Optional[float] = None) -> Flowgraph:
        timeout = RunningFlowgraph._resolve_timeout(timeout)
        if timeout is None:
            running = await self.start_async(fg)
            return await running.wait(timeout=None)
        # the deadline is a TOTAL budget: it bounds the launch too — a
        # kernel.init wedged on a dead link must not hang run() any more
        # than a wedged work() may. A launch that blows the deadline is
        # flight-recorded and abandoned (blocks stuck inside init cannot
        # see Terminate; the record's thread stacks carry the post-mortem).
        t0 = time.monotonic()
        try:
            running = await asyncio.wait_for(self.start_async(fg), timeout)
        except asyncio.TimeoutError:
            from ..telemetry.doctor import doctor as _doctor
            d = _doctor()
            paths = d.dump(d.flight_record(f"run_timeout:init:{timeout}s"))
            path = paths[0] if paths else None
            log.error("flowgraph launch exceeded the %.3fs run deadline "
                      "inside the init barrier (flight record: %s)",
                      timeout, path or "in memory")
            raise FlowgraphError(
                f"flowgraph did not pass the init barrier within the "
                f"{timeout}s run deadline — a block is wedged inside "
                "init(); see the flight record",
                errors=[FlowgraphCancelled("run deadline exceeded in init")],
                blocks=[None],
                policy_decisions=[{"block": None, "action": "cancel",
                                   "reason": "run deadline exceeded in init"}],
                flight_record=path) from None
        remaining = max(0.05, timeout - (time.monotonic() - t0))
        return await running.wait(timeout=remaining)

    # -- sync API --------------------------------------------------------------
    def run(self, fg: Flowgraph, timeout: Optional[float] = None) -> Flowgraph:
        """Run to completion (`runtime.rs:204-207`). ``timeout`` (or the
        ``run_timeout`` config knob) is the graceful run deadline: flight
        record + cancel + FlowgraphError instead of a hang."""
        return self.scheduler.run_coro_sync(self.run_async(fg, timeout=timeout))

    def start(self, fg: Flowgraph) -> RunningFlowgraph:
        return self.scheduler.run_coro_sync(self.start_async(fg))

    def shutdown(self) -> None:
        if self._ctrl_port is not None:
            self._ctrl_port.stop()
        self.scheduler.shutdown()


async def _unregister_on_done(task, rt_handle: RuntimeHandle, fg_id: int):
    try:
        return await task
    finally:
        rt_handle.unregister(fg_id)
