"""Block inboxes: bounded MPSC control queue + coalescing data-notification.

Re-design of the reference's actor plumbing (``src/runtime/block_inbox.rs:28-191``,
``src/runtime/mod.rs:178-214``): every block has
  * an **inbox** for control messages (`BlockMessage`: Initialize/Call/Callback/
    StreamInputDone/StreamOutputDone/Terminate), and
  * a **notifier** — a coalescing wake-only flag used by the data plane (buffer produce/consume)
    so per-item wakeups carry no payload and collapse into one.

Unlike the Rust original (kanal channel + atomic waker), this implementation is loop-agnostic and
thread-safe: blocks may run on different event loops (multi-loop scheduler, blocking blocks on
dedicated threads), so waking uses ``call_soon_threadsafe`` when crossing loops.
"""

from __future__ import annotations

import asyncio
import threading
from collections import deque
from dataclasses import dataclass
from typing import Any, Optional

from ..types import Pmt, PortId

__all__ = [
    "BlockMessage",
    "Initialize",
    "Call",
    "Callback",
    "StreamInputDone",
    "StreamOutputDone",
    "Terminate",
    "BlockInbox",
]


class BlockMessage:
    """Base class of control messages (`src/runtime/mod.rs:178-214`)."""

    __slots__ = ()


@dataclass(frozen=True)
class Initialize(BlockMessage):
    pass


@dataclass(frozen=True)
class Call(BlockMessage):
    port: PortId
    data: Pmt


@dataclass(frozen=True)
class Callback(BlockMessage):
    port: PortId
    data: Pmt
    reply: "ReplySlot"


@dataclass(frozen=True)
class StreamInputDone(BlockMessage):
    port_index: int


@dataclass(frozen=True)
class StreamOutputDone(BlockMessage):
    port_index: int


@dataclass(frozen=True)
class Terminate(BlockMessage):
    pass


class ReplySlot:
    """A oneshot reply channel usable across event loops (reference: futures oneshot)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._value: Any = None
        self._set = False
        self._waiter: Optional[tuple] = None  # (loop, asyncio.Event)

    def set(self, value: Any) -> None:
        with self._lock:
            if self._set:
                return
            self._value = value
            self._set = True
            waiter = self._waiter
        if waiter is not None:
            loop, ev = waiter
            try:
                running = asyncio.get_running_loop()
            except RuntimeError:
                running = None
            if loop is running:
                ev.set()
            else:
                loop.call_soon_threadsafe(ev.set)

    async def get(self) -> Any:
        with self._lock:
            if self._set:
                return self._value
            loop = asyncio.get_running_loop()
            ev = asyncio.Event()
            self._waiter = (loop, ev)
        await ev.wait()
        return self._value


class BlockInbox:
    """Inbox + coalescing notifier for one block.

    ``send``/``try_send`` enqueue a control message and wake the block.  ``notify`` only sets the
    coalesced pending flag and wakes (`block_inbox.rs:48-65`).  The block's event loop drains with
    ``take_pending``/``try_recv`` and parks on ``wait`` (`Notified` future equivalent).
    """

    def __init__(self, capacity: Optional[int] = None):
        if capacity is None:
            from ..config import config
            capacity = config().queue_size
        self.capacity = capacity
        self._q: deque = deque()
        self._lock = threading.Lock()
        self._pending = False          # coalesced data notification
        self._waiter: Optional[tuple] = None  # (loop, asyncio.Event)
        self._space_waiters: list = []        # producers parked in send_async
        self.closed = False

    # -- producer side --------------------------------------------------------
    def send(self, msg: BlockMessage) -> bool:
        """Enqueue a control message and wake the block (`block_inbox.rs:120-136`).
        Returns False if the inbox is closed (receiver gone).

        UNBOUNDED: reserved for runtime control traffic (Initialize/Terminate/
        Stream*Done) that must never be dropped. High-rate message-plane producers
        use :meth:`send_async` (backpressure) or :meth:`try_send` (bounded drop)."""
        with self._lock:
            if self.closed:
                return False
            self._q.append(msg)
            waiter = self._take_waiter_locked()
        self._wake(waiter)
        return True

    def try_send(self, msg: BlockMessage) -> bool:
        """Bounded enqueue: returns False (drops) when the inbox is full or closed —
        the reference's `try_send` on its bounded kanal channel."""
        with self._lock:
            if self.closed or (self.capacity > 0 and len(self._q) >= self.capacity):
                return False
            self._q.append(msg)
            waiter = self._take_waiter_locked()
        self._wake(waiter)
        return True

    async def send_async(self, msg: BlockMessage) -> bool:
        """Bounded enqueue with backpressure: awaits until space frees (the
        reference's `send().await`). Returns False if the inbox closed."""
        while True:
            with self._lock:
                if self.closed:
                    return False
                if self.capacity <= 0 or len(self._q) < self.capacity:
                    self._q.append(msg)
                    waiter = self._take_waiter_locked()
                    break
                loop = asyncio.get_running_loop()
                ev = asyncio.Event()
                self._space_waiters.append((loop, ev))
            await ev.wait()
        self._wake(waiter)
        return True

    def notify(self) -> None:
        """Coalescing data-plane wake: no payload, collapses repeats (`block_inbox.rs:48-52`)."""
        with self._lock:
            if self.closed:
                return
            self._pending = True
            waiter = self._take_waiter_locked()
        self._wake(waiter)

    def _take_waiter_locked(self):
        w, self._waiter = self._waiter, None
        return w

    @staticmethod
    def _wake(waiter):
        if waiter is None:
            return
        loop, ev = waiter
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if loop is running:
            ev.set()
        else:
            try:
                loop.call_soon_threadsafe(ev.set)
            except RuntimeError:
                pass  # target loop already closed (teardown race)

    # -- consumer side (the block's event loop) --------------------------------
    def take_pending(self) -> bool:
        """Consume the coalesced notification flag (`block_inbox.rs:104-111`)."""
        with self._lock:
            p, self._pending = self._pending, False
            return p

    def try_recv(self) -> Optional[BlockMessage]:
        with self._lock:
            m = self._q.popleft() if self._q else None
            sw: list = []
            if m is not None and self._space_waiters and \
                    (self.capacity <= 0 or len(self._q) < self.capacity):
                sw, self._space_waiters = self._space_waiters, []
        for w in sw:
            self._wake(w)
        return m

    def __len__(self) -> int:
        return len(self._q)

    async def wait(self) -> None:
        """Park until a message arrives or a notification is pending."""
        with self._lock:
            if self._pending or self._q:
                return
            loop = asyncio.get_running_loop()
            ev = asyncio.Event()
            self._waiter = (loop, ev)
        await ev.wait()

    async def recv(self) -> BlockMessage:
        """Blocking receive (used by the flowgraph supervisor's main loop)."""
        while True:
            m = self.try_recv()
            if m is not None:
                return m
            await self.wait()
            self.take_pending()

    def close(self) -> None:
        """Refuse new sends; already-queued messages stay drainable via try_recv."""
        with self._lock:
            self.closed = True
            sw, self._space_waiters = self._space_waiters, []
        for w in sw:                   # unpark producers so send_async sees closed
            self._wake(w)
