"""Mocker: run a single block without runtime or scheduler.

Re-design of ``src/runtime/mocker.rs:33-393``: vec-backed mock reader/writer ports, direct
handler invocation, a ``run()`` that loops ``work()`` until ``!call_again``, and capture of
posted messages. This is the unit-test and micro-bench harness (``tests/mocker.rs``,
``benches/apply.rs``) — and on TPU it doubles as the golden-test harness for numeric parity
against NumPy/SciPy references (SURVEY §4).
"""

from __future__ import annotations

import asyncio
from typing import List, Sequence, Tuple

import numpy as np

from ..types import Pmt
from .buffer import BufferReader, BufferWriter
from .inbox import BlockInbox, Call
from .kernel import Kernel
from .tag import ItemTag
from .work_io import WorkIo

__all__ = ["Mocker"]


class _MockReader(BufferReader):
    """Vec-backed reader (`mocker.rs:195-289`)."""

    def __init__(self, data: np.ndarray, tags: Sequence[ItemTag] = ()):
        self._data = data
        self._pos = 0
        self._tags: List[ItemTag] = list(tags)

    def slice(self) -> np.ndarray:
        return self._data[self._pos:]

    def tags(self) -> List[ItemTag]:
        return [ItemTag(t.index - self._pos, t.tag) for t in self._tags
                if t.index >= self._pos]

    def consume(self, n: int) -> None:
        self._pos += n

    def notify_finished(self) -> None:
        pass


class _MockWriter(BufferWriter):
    """Vec-backed writer capturing produced items + tags (`mocker.rs:291-393`)."""

    def __init__(self, dtype, capacity: int):
        self._data = np.zeros(capacity, dtype=dtype)
        self._pos = 0
        self.tags: List[ItemTag] = []

    def add_reader(self, reader_inbox, port_index, min_items=1):
        raise NotImplementedError("mock writer has no readers")

    def slice(self) -> np.ndarray:
        return self._data[self._pos:]

    def produce(self, n: int, tags: Sequence[ItemTag] = ()) -> None:
        self.tags.extend(ItemTag(self._pos + t.index, t.tag) for t in tags)
        self._pos += n

    def notify_finished(self) -> None:
        pass

    def produced(self) -> np.ndarray:
        return self._data[:self._pos]


class _CaptureInbox(BlockInbox):
    """Message sink capturing `mio.post` fan-out."""

    def __init__(self, record: List[Tuple[str, Pmt]], port: str):
        super().__init__(capacity=1 << 30)
        self._record = record
        self._port = port

    def send(self, msg) -> None:
        if isinstance(msg, Call):
            self._record.append((self._port, msg.data))


class Mocker:
    """Test harness for one block (`mocker.rs:33-191`).

    Usage::

        m = Mocker(block)
        m.input("in", np.arange(128, dtype=np.float32))
        m.init_output("out", 128)
        m.init(); m.run(); m.deinit()
        out = m.output("out")
    """

    def __init__(self, kernel: Kernel):
        self.kernel = kernel
        self.io = WorkIo()
        self.messages: List[Tuple[str, Pmt]] = []
        for name in kernel.mio.names:
            kernel.mio.connect(name, _CaptureInbox(self.messages, name), "capture")

    # -- port setup ------------------------------------------------------------
    def input(self, port, data: np.ndarray, tags: Sequence[ItemTag] = ()) -> None:
        p = self.kernel.stream_input(port)
        arr = np.ascontiguousarray(data, dtype=p.dtype)
        p.reader = _MockReader(arr, tags)

    def input_finished(self, port) -> None:
        self.kernel.stream_input(port).set_finished()

    def init_output(self, port, capacity_items: int) -> None:
        p = self.kernel.stream_output(port)
        p.writer = _MockWriter(p.dtype, capacity_items)

    def output(self, port) -> np.ndarray:
        return self.kernel.stream_output(port).writer.produced()

    def output_tags(self, port) -> List[ItemTag]:
        return list(self.kernel.stream_output(port).writer.tags)

    # -- lifecycle -------------------------------------------------------------
    def init(self) -> None:
        asyncio.run(self.kernel.init(self.kernel.mio, self.kernel.meta))

    def deinit(self) -> None:
        asyncio.run(self.kernel.deinit(self.kernel.mio, self.kernel.meta))

    def run(self, max_iters: int = 1_000_000) -> None:
        """Loop ``work()`` until it stops requesting ``call_again`` (`mocker.rs:117-160`)."""

        async def go():
            self.io.call_again = True
            iters = 0
            while self.io.call_again and not self.io.finished:
                self.io.reset()
                await self.kernel.work(self.io, self.kernel.mio, self.kernel.meta)
                iters += 1
                if iters >= max_iters:
                    raise RuntimeError("Mocker.run exceeded max_iters")

        asyncio.run(go())

    def post(self, handler, data: Pmt = None) -> Pmt:
        """Invoke a message handler directly (`mocker.rs:96-115`)."""
        data = Pmt.from_py(data) if not isinstance(data, Pmt) else data

        async def go():
            return await self.kernel.call_handler(self.io, self.kernel.meta, handler, data)

        return asyncio.run(go())

    @property
    def finished(self) -> bool:
        return self.io.finished
