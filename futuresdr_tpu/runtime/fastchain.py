"""Native fast-chain substitution: run whole pipes of trivial stream blocks in C++.

Reference role: ``src/runtime/scheduler/flow.rs:265-442`` — the reference's
FlowScheduler exists because per-work-call executor overhead dominates when
blocks forward tiny chunks (its ``perf/null_rand`` regime). Python's asyncio
actor loop costs ~10 µs per ``work()`` call there; no amount of scheduling
fixes that floor. This module takes the reference's answer one step further on
the runtime side: a maximal LINEAR chain whose members are all native-capable
(NullSource/Head/Copy/CopyRand/NullSink), with no message ports, taps,
broadcasts, or inplace edges, is lifted out of the actor plane entirely and
executed by ``native/fastchain.cpp`` — one C++ thread round-robining the whole
pipe over plain ring buffers (one pinned flow.rs worker that owns every block
of the pipe).

The substitution is transparent to the supervisor protocol: the chain task
answers the init barrier for each member, watches for Terminate (the native
loop honors a stop flag), and reports per-member BlockDone with item counters
filled in, so describe/metrics/REST see the same flowgraph. Opt out with
``FSDR_NO_NATIVE=1`` (everything native) or ``FSDR_NO_FASTCHAIN=1`` (just this).
"""

from __future__ import annotations

import asyncio
import ctypes
import os
from typing import List, Optional, Sequence

from ..log import logger
from .inbox import Callback, Initialize, Terminate

__all__ = ["find_native_chains", "run_chain_task", "fastchain_available"]

log = logger("runtime.fastchain")

# stage kinds — keep in sync with native/fastchain.cpp
(FC_NULL_SOURCE, FC_HEAD, FC_COPY, FC_COPY_RAND, FC_NULL_SINK,
 FC_VEC_SOURCE, FC_VEC_SINK) = range(7)


class _FcStage(ctypes.Structure):
    _fields_ = [("kind", ctypes.c_int32), ("_pad", ctypes.c_int32),
                ("p0", ctypes.c_int64), ("p1", ctypes.c_int64),
                ("data", ctypes.c_void_p)]


_lib = None


def _load() -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is not None:
        return _lib
    if os.environ.get("FSDR_NO_FASTCHAIN"):
        return None
    from .buffer.circular import probe_native
    lib = probe_native(
        "fsdr_fastchain_run", ctypes.c_int64,
        [ctypes.POINTER(_FcStage), ctypes.c_int32, ctypes.c_int64,
         ctypes.c_int64, ctypes.POINTER(ctypes.c_int32),
         ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64)])
    _lib = lib
    return lib


def fastchain_available() -> bool:
    return _load() is not None


def _native_stage(kernel) -> Optional[tuple]:
    """(kind, p0, p1, data|None) for natively runnable kernels; None otherwise.

    Central registry rather than per-class methods: the chain driver owns the
    exact semantics it re-implements, so a behavioral change to one of these
    blocks must be mirrored HERE or the kernel dropped from the registry."""
    import numpy as np

    from ..blocks.stream import Copy, Head
    from ..blocks.vector import CopyRand, NullSink, NullSource, VectorSink, \
        VectorSource

    if type(kernel) is NullSource:
        return (FC_NULL_SOURCE, 0, 0, None)
    if type(kernel) is Head:
        return (FC_HEAD, int(kernel.remaining), 0, None)
    if type(kernel) is Copy:
        return (FC_COPY, 0, 0, None)
    if type(kernel) is CopyRand:
        if int(kernel.max_copy) < 1:
            return None                # let the actor path raise its ValueError
        return (FC_COPY_RAND, int(kernel.max_copy), int(kernel._seed), None)
    if type(kernel) is NullSink:
        return (FC_NULL_SINK,
                -1 if kernel.count is None else int(kernel.count), 0, None)
    if type(kernel) is VectorSource:
        period = len(kernel.items)
        if period == 0 or int(kernel.repeat) < 0 or kernel._pos or kernel._round:
            return None                # degenerate/pre-consumed: actor path
        if period * int(kernel.repeat) >= 2 ** 62:
            return None                # int64 budget overflow: actor path
        # data materialized ONCE in run_chain_task — this predicate runs
        # several times per launch and must not copy the vector
        return (FC_VEC_SOURCE, period * int(kernel.repeat), period, None)
    if type(kernel) is VectorSink:
        if kernel._chunks:
            return None                # already holds data: actor path
        return (FC_VEC_SINK, -1, 0, None)   # capacity bound resolved per chain
    return None


def _chain_bound(chain) -> Optional[int]:
    """Exact item count a chain's sink receives (None = unbounded): the min of
    every finite source/Head budget along the pipe (Copy/CopyRand are
    count-preserving)."""
    bound = None
    for k in chain:
        spec = _native_stage(k)
        if spec is None:
            return None
        kind, p0 = spec[0], spec[1]
        if kind in (FC_VEC_SOURCE, FC_HEAD):
            bound = p0 if bound is None else min(bound, p0)
        elif kind == FC_NULL_SINK and p0 >= 0:
            bound = p0 if bound is None else min(bound, p0)
    return bound


def find_native_chains(fg) -> List[List[object]]:
    """Maximal source→sink linear chains of native-capable kernels in ``fg``.

    A member must: be native-capable, touch no message or inplace edges, have
    every stream port wired exactly once (no taps/broadcasts), and the chain
    must start at a no-input source and end at a no-output sink — so no tags
    can enter the chain and no Python block shares its buffers."""
    # env checked per call (not just at lib load) so perf probes can A/B the
    # Python actor path vs the native chain inside one process
    if os.environ.get("FSDR_NO_FASTCHAIN") or not fastchain_available():
        return []
    msg_touched = {id(e.src) for e in fg.message_edges} | \
                  {id(e.dst) for e in fg.message_edges}
    inp_touched = {id(e.src) for e in fg.inplace_edges} | \
                  {id(e.dst) for e in fg.inplace_edges}
    out_edges: dict = {}
    in_deg: dict = {}
    for e in fg.stream_edges:
        out_edges.setdefault(id(e.src), []).append(e)
        in_deg[id(e.dst)] = in_deg.get(id(e.dst), 0) + 1

    def eligible(k) -> bool:
        return (_native_stage(k) is not None
                and id(k) not in msg_touched and id(k) not in inp_touched
                and len(k.stream_inputs) <= 1 and len(k.stream_outputs) <= 1
                and len(out_edges.get(id(k), [])) == len(k.stream_outputs)
                and in_deg.get(id(k), 0) == len(k.stream_inputs))

    chains = []
    for k in (b.kernel for b in fg._blocks if b is not None):
        if not (eligible(k) and not k.stream_inputs and k.stream_outputs):
            continue                                   # chain heads: sources
        chain = [k]
        cur = k
        while True:
            outs = out_edges.get(id(cur), [])
            if len(outs) != 1:
                break
            nxt = outs[0].dst
            if not eligible(nxt):
                break
            chain.append(nxt)
            if not nxt.stream_outputs:
                break                                  # reached a sink
            cur = nxt
        if len(chain) < 2 or chain[-1].stream_outputs:
            continue
        from ..blocks.vector import VectorSink
        if type(chain[-1]) is VectorSink and _chain_bound(chain) is None:
            continue                   # unbounded into a collecting sink
        dtypes = {p.dtype for k in chain
                  for p in list(k.stream_inputs) + list(k.stream_outputs)
                  if p.dtype is not None}
        if len(dtypes) != 1:
            # heterogeneous OR fully-untyped chain: the sink buffer and the C
            # item_size must agree on ONE dtype, or the driver would write
            # item_size-wide items into a differently-sized buffer
            continue
        chains.append(chain)
    return chains


async def run_chain_task(members: Sequence, fg_inbox, scheduler,
                         ring_items: int = 1 << 16) -> None:
    """Impersonate ``members`` (WrappedKernels) at the supervisor protocol level
    while the native driver runs the chain: answer the init barrier per member,
    watch for Terminate, then report per-member BlockDone with counters."""
    from .runtime import BlockDoneMsg, BlockErrorMsg, InitializedMsg
    from ..types import Pmt

    def _finish_all():
        for b in members:
            fg_inbox.send(BlockDoneMsg(b.id, b))

    async def _next_msg(inbox):
        """Next inbox message, parking on the coalescing notifier. Returns None
        on a bare notify (the supervisor's start signal is a notify with no
        message)."""
        msg = inbox.try_recv()
        if msg is not None:
            return msg
        await inbox.wait()
        inbox.take_pending()
        return inbox.try_recv()

    # ---- init barrier for every member --------------------------------------
    for b in members:
        while True:
            msg = await _next_msg(b.inbox)
            if isinstance(msg, Initialize):
                break
            if isinstance(msg, Terminate):
                _finish_all()
                return
            if isinstance(msg, Callback):
                msg.reply.set(Pmt.invalid_value())
        fg_inbox.send(InitializedMsg(b.id, ok=True))

    # ---- start signal ---------------------------------------------------------
    # Do NOT run (or send BlockDone) before the supervisor releases the barrier:
    # each block must emit exactly one of Initialized/BlockError/BlockDone
    # before the start notify, or a fast chain's BlockDones double-decrement the
    # barrier counter and init failures elsewhere stop propagating from start()
    # (`runtime.rs:380-429` contract; actor blocks park the same way).
    while True:
        msg = await _next_msg(members[0].inbox)
        if isinstance(msg, Terminate):
            _finish_all()
            return
        if isinstance(msg, Callback):
            msg.reply.set(Pmt.invalid_value())
        if msg is None:
            break                       # bare notify = the start signal

    import numpy as np

    def _build_stages():
        """Everything that can raise (allocation, int64 bounds) — called inside
        the guarded region below so a failure becomes BlockError, not a
        silently dead task and a hung supervisor."""
        lib = _load()
        n = len(members)
        # the ONE chain dtype (find_native_chains guarantees exactly one
        # non-None dtype across the chain's ports): sizes both the C item
        # width and the sink buffer — deriving them separately corrupted
        # memory when the sink port was untyped
        chain_dt = next(p.dtype for b in members
                        for p in list(b.kernel.stream_inputs)
                        + list(b.kernel.stream_outputs) if p.dtype is not None)
        stages = (_FcStage * n)()
        keepalive = []                 # numpy buffers the C side points into
        sink_buf = None
        bound = _chain_bound([b.kernel for b in members])
        for i, b in enumerate(members):
            kind, p0, p1, data = _native_stage(b.kernel)
            if kind == FC_VEC_SOURCE:
                data = np.ascontiguousarray(b.kernel.items)
            elif kind == FC_VEC_SINK:
                sink_buf = np.empty(int(bound), dtype=chain_dt)
                data, p0 = sink_buf, int(bound)
            ptr = None
            if data is not None:
                keepalive.append(data)
                ptr = data.ctypes.data_as(ctypes.c_void_p)
            stages[i] = _FcStage(kind, 0, p0, p1, ptr)
        return lib, stages, keepalive, sink_buf, int(chain_dt.itemsize)

    try:
        lib, stages, keepalive, sink_buf, item_size = _build_stages()
    except Exception as e:                              # noqa: BLE001
        log.error("fastchain stage build failed (%r)", e)
        fg_inbox.send(BlockErrorMsg(members[0].id, e))
        for b in members[1:]:
            fg_inbox.send(BlockDoneMsg(b.id, b))
        return
    n = len(members)
    per_stage = (ctypes.c_int64 * n)()
    per_calls = (ctypes.c_int64 * n)()
    stop = ctypes.c_int32(0)

    # live metrics bridge: the native driver updates the shared counter arrays
    # DURING the run, so /metrics/ and handle.metrics() observe a fused chain
    # in flight exactly like actor-run blocks (work_calls = chunks moved)
    def _bridge(i, b):
        k = b.kernel
        base_extra = getattr(k, "extra_metrics", None)

        def refresh():
            b.work_calls = int(per_calls[i])
            moved = int(per_stage[i])
            for p in k.stream_outputs:
                p.items_produced = moved
            for p in k.stream_inputs:
                p.items_consumed = moved
            if hasattr(k, "n_received") and k.stream_inputs:
                k.n_received = moved               # NullSink contract
        k.extra_metrics = lambda: (refresh() or dict(
            (base_extra() if callable(base_extra) else {}), fused_native=True))
        return refresh

    refreshers = [_bridge(i, b) for i, b in enumerate(members)]

    # Inbox watchers, one per member: Terminate (broadcast to every member)
    # sets the native stop flag; Callbacks to ANY fused member are answered
    # with invalid_value instead of hanging the caller (fused blocks have no
    # handlers — the same answer an actor block gives for an unknown port).
    async def watch(b):
        while True:
            msg = await _next_msg(b.inbox)
            if isinstance(msg, Terminate):
                stop.value = 1
                return
            if isinstance(msg, Callback):
                msg.reply.set(Pmt.invalid_value())

    watchers = [asyncio.ensure_future(watch(b)) for b in members]

    def _cancel_watchers():
        for w in watchers:
            w.cancel()

    try:
        rc = await scheduler.spawn_blocking(
            lambda: lib.fsdr_fastchain_run(stages, n, item_size, ring_items,
                                           ctypes.byref(stop), per_stage,
                                           per_calls))
    except Exception as e:                              # noqa: BLE001
        _cancel_watchers()
        log.error("fastchain failed (%r)", e)
        fg_inbox.send(BlockErrorMsg(members[0].id, e))
        for b in members[1:]:
            fg_inbox.send(BlockDoneMsg(b.id, b))
        return
    _cancel_watchers()
    if rc < 0:
        e = RuntimeError(f"fastchain returned {rc} (malformed chain)")
        fg_inbox.send(BlockErrorMsg(members[0].id, e))
        for b in members[1:]:
            fg_inbox.send(BlockDoneMsg(b.id, b))
        return

    # ---- final counter sync (the live bridge stays installed) ----------------
    for r in refreshers:
        r()
    if sink_buf is not None:
        members[-1].kernel._chunks = [sink_buf[:int(per_stage[n - 1])]]
    del keepalive
    _finish_all()
